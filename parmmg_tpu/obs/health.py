"""Run-health observatory: termination verdicts, churn detection,
drain curves and the live run state behind `PMMGTPU_STATUS_PORT`.

ParMmg judges an adaptation by the unit-mesh goal — the fraction of
edges whose metric length lands in [1/sqrt2, sqrt2] (`PMMG_prilen`,
reference `src/quality_pmmg.c:591`) — yet "why did the run stop?" is
normally answered by reading stdout. This module turns the driver
history (the HIST_COLS per-sweep records, now carrying
`n_len_unit`/`n_len_edges` and the derived `in_band` fraction) into:

- :func:`assess` — a typed per-run termination verdict
  (``converged | stalled | oscillating | budget_exhausted``) folding
  operator-acceptance decay, the frontier drain curve, the in-band
  trajectory and a split<->collapse churn detector (same-region thrash:
  sweep k's splits undone by sweep k+1's collapses and vice versa);
- :func:`emit_run_health` — the `health:*` tracer events the drivers
  flush at run end, from which :func:`health_summary` /
  :func:`render_health` (CLI ``tools/obs_report.py --health``)
  reconstruct the post-mortem: verdict, world edge-length histogram
  and drain curve;
- :func:`run_state` — the process-local live snapshot (phase /
  iteration / in-band / heartbeat age / drain ETA) that
  `service.status.run_status_text` serves over HTTP while the run is
  still going (`PMMGTPU_STATUS_PORT` contract).

Everything here is host-side dict arithmetic over already-materialized
history records — no device work, no extra syncs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from . import trace as trace_mod

__all__ = [
    "VERDICTS", "assess", "churn_scores", "drain_curve",
    "format_history_rows", "render_length_doc", "emit_run_health",
    "health_summary", "render_health", "run_state", "note_sweep",
    "history_in_band", "in_band_slope", "GOVERN_WINDOW",
]

VERDICTS = ("converged", "stalled", "oscillating", "budget_exhausted")

# churn detector tuning: a consecutive sweep pair where at least
# CHURN_MIN_FRACTION of the combined split+collapse work mutually
# cancels (sweep k's splits matched by sweep k+1's collapses and vice
# versa) counts as thrash; CHURN_PAIRS such pairs among the last
# CHURN_WINDOW make the run "oscillating"
CHURN_MIN_FRACTION = 0.35
CHURN_WINDOW = 4
CHURN_PAIRS = 2

# acceptance decay: ops at budget end below this fraction of the
# window start count as "still converging" (budget_exhausted), not
# stalled
DECAY_RATIO = 0.7

# history rows shipped in the health:history tracer event are capped so
# a 10k-sweep run cannot bloat the JSONL; the drop is recorded
HISTORY_EVENT_CAP = 512

# rolling-window width (sweep records) for IN-RUN verdicts: the live
# governor and the killed-run re-assessment both judge the same last-N
# slice, so post-mortem and in-run verdicts can't disagree on
# identical history rows
GOVERN_WINDOW = 8


def sweep_records(history: Sequence[dict]) -> List[dict]:
    """The operator-sweep records of a driver history — `failure`
    entries (rollbacks) carry no counters and are skipped."""
    return [r for r in history if "nsplit" in r]


def history_in_band(history: Sequence[dict]) -> Optional[float]:
    """Last known unit-band fraction of a run history (None when no
    sweep measured one — e.g. a pre-health checkpoint resumed)."""
    for r in reversed(sweep_records(history)):
        if "in_band" in r:
            return float(r["in_band"])
    return None


def _ops(rec: dict) -> int:
    return int(rec.get("nsplit", 0)) + int(rec.get("ncollapse", 0)) \
        + int(rec.get("nswap", 0))


def _active_fraction(rec: dict) -> float:
    if "active_fraction" in rec:
        return float(rec["active_fraction"])
    return rec.get("n_active", 0) / max(rec.get("n_unique", 1), 1)


def churn_scores(recs: Sequence[dict]) -> List[float]:
    """Per consecutive same-iteration sweep pair: the fraction of the
    pair's combined split+collapse work that mutually cancels —
    min(split_k, collapse_{k+1}) + min(collapse_k, split_{k+1}) over
    the pair's total ops. 1.0 = pure thrash, 0.0 = disjoint work."""
    out: List[float] = []
    for a, b in zip(recs, recs[1:]):
        if a.get("iter") != b.get("iter"):
            continue
        cancel = (
            min(int(a.get("nsplit", 0)), int(b.get("ncollapse", 0)))
            + min(int(a.get("ncollapse", 0)), int(b.get("nsplit", 0)))
        )
        out.append(2.0 * cancel / max(_ops(a) + _ops(b), 1))
    return out


def in_band_slope(history: Sequence[dict],
                  window: Optional[int] = None) -> Optional[float]:
    """Per-sweep slope of the unit-band fraction over the last
    `window` band-carrying sweep records (endpoint difference /
    span). None when fewer than two sweeps measured a band — callers
    treat that as "no improvement evidence", not as flat."""
    recs = sweep_records(history)
    if window is not None:
        recs = recs[-window:]
    bands = [float(r["in_band"]) for r in recs if "in_band" in r]
    if len(bands) < 2:
        return None
    return (bands[-1] - bands[0]) / (len(bands) - 1)


def drain_curve(recs: Sequence[dict]) -> dict:
    """Frontier drain telemetry: the active-fraction series and a
    linear-extrapolation ETA (sweeps until the active set reaches zero
    at the recent drain rate; None when not draining)."""
    series = [round(_active_fraction(r), 4) for r in recs]
    eta = None
    if len(series) >= 2:
        k = min(len(series), 4)
        rate = (series[-k] - series[-1]) / (k - 1)
        if rate > 1e-6 and series[-1] > 0:
            eta = round(series[-1] / rate, 1)
        elif series[-1] == 0:
            eta = 0.0
    return dict(series=series, eta_sweeps=eta)


def assess(
    history: Sequence[dict],
    converge_frac: float = 0.005,
    max_sweeps: Optional[int] = None,
    status: Optional[int] = None,
    window: Optional[int] = None,
) -> dict:
    """Fold a driver history into the typed termination verdict.

    Rules, in priority order over the final iteration's sweeps:

    1. ``converged`` — the last sweep met the driver's own stopping
       rule (ops <= converge_frac * ne, not capped) or the frontier
       fully drained;
    2. ``oscillating`` — sustained split<->collapse churn
       (>= CHURN_PAIRS of the last CHURN_WINDOW pairs above
       CHURN_MIN_FRACTION) with non-negligible ops;
    3. ``budget_exhausted`` — the sweep budget ran out while
       acceptance was still clearly decaying (>= 3 sweeps of evidence,
       last ops <= DECAY_RATIO * window start);
    4. ``stalled`` — everything else: ops neither converged nor
       decaying (includes the forced max_sweeps=1 case, where one
       sweep gives no decay evidence).

    With `window` set, only the last `window` sweep records are
    judged — the ROLLING form shared by the live run governor and
    the killed-run re-assessment (GOVERN_WINDOW), so an in-run stop
    and the post-mortem can never disagree on identical rows.
    """
    recs = sweep_records(history)
    failures = len(history) - len(recs)
    if window is not None:
        recs = recs[-window:]
    if not recs:
        return dict(
            verdict="stalled", reason="no operator sweeps recorded",
            sweeps=0, iterations=0, failures=failures,
            in_band_first=None, in_band_last=None,
            churn=dict(scores=[], sustained=False),
            drain=dict(series=[], eta_sweeps=None),
            status=status, window=window,
        )

    last = recs[-1]
    last_it = last.get("iter", 0)
    tail = [r for r in recs if r.get("iter", 0) == last_it]
    ops_tail = [_ops(r) for r in tail]
    drain = drain_curve(recs)
    bands = [float(r["in_band"]) for r in recs if "in_band" in r]

    converged = (
        not last.get("capped")
        and _ops(last) <= converge_frac * max(int(last.get("ne", 0)), 1)
    ) or (last.get("n_active", None) == 0 and last.get("skipped"))

    scores = churn_scores(recs)
    wscores = scores[-CHURN_WINDOW:]
    hot = sum(1 for s in wscores if s >= CHURN_MIN_FRACTION)
    sustained = (
        hot >= CHURN_PAIRS
        and _ops(last) > converge_frac * max(int(last.get("ne", 0)), 1)
    )

    decaying = (
        len(ops_tail) >= 3
        and ops_tail[-1] < ops_tail[0]
        and ops_tail[-1] <= DECAY_RATIO * max(ops_tail[0], 1)
    )
    budget_hit = max_sweeps is None or len(tail) >= max_sweeps

    if converged:
        verdict, reason = "converged", (
            f"last sweep ops {_ops(last)} <= "
            f"{converge_frac:g} * ne {int(last.get('ne', 0))}"
            if not last.get("skipped")
            else "frontier drained (converged sweep skipped)"
        )
    elif sustained:
        verdict, reason = "oscillating", (
            f"{hot}/{len(wscores)} recent sweep pairs above "
            f"{CHURN_MIN_FRACTION:.0%} split<->collapse churn "
            f"(max {max(wscores):.0%})"
        )
    elif decaying and budget_hit:
        verdict, reason = "budget_exhausted", (
            f"ops still decaying ({ops_tail[0]} -> {ops_tail[-1]}) "
            f"when the sweep budget ran out"
        )
    else:
        verdict, reason = "stalled", (
            f"ops flat at {_ops(last)} (neither converged nor "
            f"decaying) after {len(tail)} sweep(s)"
        )

    return dict(
        verdict=verdict, reason=reason,
        sweeps=len(recs),
        iterations=len({r.get("iter", 0) for r in recs}),
        failures=failures,
        ops_first=_ops(recs[0]), ops_last=_ops(last),
        in_band_first=bands[0] if bands else None,
        in_band_last=bands[-1] if bands else None,
        churn=dict(
            scores=[round(s, 4) for s in wscores],
            max_score=round(max(scores), 4) if scores else 0.0,
            sustained=sustained,
        ),
        drain=drain,
        status=int(status) if status is not None else None,
        window=window,
    )


# -- formatting -----------------------------------------------------------

def format_history_rows(history: Sequence[dict]) -> str:
    """One line per sweep record — the single sweep-history formatter
    (tools/sweep_hist.py renders through this; `--health` renders the
    reconstructed rows through it too)."""
    lines = []
    for r in sweep_records(history):
        band = f" band={float(r['in_band']):7.2%}" if "in_band" in r \
            else ""
        act = f" act={_active_fraction(r):4.0%}" \
            if "n_active" in r or "active_fraction" in r else ""
        flags = " CAP" if r.get("capped") else ""
        flags += " skip" if r.get("skipped") else ""
        lines.append(
            f"it{r.get('iter', 0)} sw{r.get('sweep', 0):2d}: "
            f"split={int(r.get('nsplit', 0)):6d} "
            f"coll={int(r.get('ncollapse', 0)):6d} "
            f"swap={int(r.get('nswap', 0)):6d} "
            f"moved={int(r.get('nmoved', 0)):6d} "
            f"ne={int(r.get('ne', 0)):8d}{act}{band}{flags}"
        )
    return "\n".join(lines)


def render_length_doc(doc: dict) -> str:
    """Render a `quality.length_stats_doc` payload — the post-mortem
    twin of `quality.format_length_stats` (which needs device arrays)."""
    def fin(v, fmt="12.4f"):
        return format(float(v), fmt) if v is not None else "   --   "

    ne = max(int(doc.get("nedge", 0)), 1)
    edges = doc.get("edges", [])
    counts = doc.get("counts", [])
    lines = [
        f"  -- UNIT EDGE LENGTHS  {int(doc.get('nedge', 0))} edges",
        f"     AVERAGE LENGTH {fin(doc.get('lavg'))}",
        f"     SMALLEST EDGE  {fin(doc.get('lmin'))}",
        f"     LARGEST  EDGE  {fin(doc.get('lmax'))}",
        f"     unit [1/sqrt2, sqrt2]: {int(doc.get('n_unit', 0))} "
        f"({100.0 * int(doc.get('n_unit', 0)) / ne:.2f} %)",
    ]
    for k in range(len(edges) - 1):
        c = counts[k + 1] if k + 1 < len(counts) else 0
        lines.append(
            f"     {edges[k]:6.2f} < L < {edges[k + 1]:6.2f}  "
            f"{c:10d}  {100.0 * c / ne:6.2f} %"
        )
    if edges:
        c_over = counts[len(edges)] if len(edges) < len(counts) else 0
        lines.append(
            f"     {edges[-1]:6.2f} < L          {c_over:10d}  "
            f"{100.0 * c_over / ne:6.2f} %"
        )
    return "\n".join(lines)


# -- tracer emission + post-mortem reconstruction -------------------------

_HEALTH_ROW_COLS = (
    "iter", "sweep", "nsplit", "ncollapse", "nswap", "nmoved", "ne",
    "n_unique", "n_active", "in_band", "capped", "skipped",
)


def _compact_rows(recs: Sequence[dict]) -> List[list]:
    return [[r.get(k) for k in _HEALTH_ROW_COLS] for r in recs]


def emit_run_health(
    history: Sequence[dict],
    length_doc: Optional[dict] = None,
    verdict: Optional[dict] = None,
    driver: str = "centralized",
    tracer=None,
) -> None:
    """Flush the run's health section as `health:*` tracer events (the
    durable JSONL is what `--health` reconstructs from). World-level
    payloads are emitted from rank 0 only — the history records are
    already world sums on the distributed paths, so every rank would
    write identical copies."""
    tr = tracer or trace_mod.get_tracer()
    if not tr.enabled or getattr(tr, "rank", 0) != 0:
        return
    recs = sweep_records(history)
    rows = _compact_rows(recs)
    dropped = max(len(rows) - HISTORY_EVENT_CAP, 0)
    if dropped:
        rows = rows[-HISTORY_EVENT_CAP:]
    tr.event(
        "health:history", driver=driver, cols=list(_HEALTH_ROW_COLS),
        rows=rows, dropped=dropped,
    )
    if length_doc is not None:
        tr.event("health:length_histogram", driver=driver, **length_doc)
    if verdict is not None:
        tr.event("health:verdict", driver=driver, **verdict)


def _last_event(recs: Sequence[dict], name: str) -> Optional[dict]:
    for r in reversed(recs):
        if r.get("type") == "event" and r.get("name") == name:
            return r.get("args", {})
    return None


def health_summary(dirpath: str) -> dict:
    """Reconstruct the run-health section from a trace directory's
    per-rank JSONL timelines. A run killed before its exit emit leaves
    no `health:verdict` — the summary then re-assesses from whatever
    `health:history` rows survived (possibly none)."""
    from . import report as report_mod  # deferred: report imports health

    tls = report_mod.rank_timelines(dirpath)
    ranks = sorted(tls)
    merged: List[dict] = [r for rank in ranks for r in tls[rank]]
    hist_ev = _last_event(merged, "health:history")
    history: List[dict] = []
    if hist_ev:
        cols = hist_ev.get("cols", list(_HEALTH_ROW_COLS))
        for row in hist_ev.get("rows", []):
            rec = {k: v for k, v in zip(cols, row) if v is not None}
            history.append(rec)
    verdict = _last_event(merged, "health:verdict")
    if verdict is None and history:
        # killed-run re-assessment judges the SAME rolling window as
        # the live governor — a post-mortem must not call a run
        # "converged" (full-history view) where the in-run control
        # loop would have called the same rows "oscillating"
        verdict = assess(history, window=GOVERN_WINDOW)
        verdict["reassessed"] = True
    length = _last_event(merged, "health:length_histogram")
    return dict(
        dir=dirpath, ranks=ranks, history=history,
        dropped=hist_ev.get("dropped", 0) if hist_ev else 0,
        verdict=verdict, length=length,
        drain=drain_curve(sweep_records(history)),
        in_band=history_in_band(history),
    )


def render_health(dirpath: str) -> str:
    """The ``--health`` report: verdict, unit edge-length histogram,
    drain curve and the per-sweep history table."""
    s = health_summary(dirpath)
    lines = [f"== run health ({s['dir']}) =="]
    lines.append(f"ranks traced: {s['ranks'] or 'none'}")
    v = s["verdict"]
    if v:
        lines.append(
            f"verdict: {v.get('verdict', '?')}"
            + (" (reassessed post-mortem)" if v.get("reassessed") else "")
        )
        lines.append(f"  reason: {v.get('reason', '')}")
        lines.append(
            f"  sweeps {v.get('sweeps', 0)} over "
            f"{v.get('iterations', 0)} iteration(s), "
            f"failures {v.get('failures', 0)}"
        )
        if v.get("in_band_last") is not None:
            first = v.get("in_band_first")
            lines.append(
                "  in-band trajectory: "
                + (f"{first:.2%} -> " if first is not None else "")
                + f"{v['in_band_last']:.2%}"
            )
        ch = v.get("churn", {})
        if ch:
            lines.append(
                f"  churn: max {ch.get('max_score', 0.0):.0%}, "
                f"sustained={bool(ch.get('sustained'))}"
            )
    else:
        lines.append("verdict: unknown (no health events in trace)")
    d = s["drain"]
    if d["series"]:
        lines.append("-- drain curve (active fraction per sweep) --")
        lines.append(
            "  " + " ".join(f"{x:.2f}" for x in d["series"][-16:])
        )
        eta = d["eta_sweeps"]
        lines.append(
            f"  eta: ~{eta:g} sweep(s) to empty frontier"
            if eta is not None else "  eta: not draining"
        )
    if s["length"]:
        lines.append(render_length_doc(s["length"]))
    if s["history"]:
        lines.append("-- sweep history --")
        if s["dropped"]:
            lines.append(f"  ({s['dropped']} earlier sweep(s) dropped "
                         "from the trace event)")
        lines.append(format_history_rows(s["history"]))
    return "\n".join(lines)


# -- live run state (PMMGTPU_STATUS_PORT backing store) -------------------

class RunState:
    """Process-local snapshot of the running adaptation for the live
    status endpoint: phase, iteration, sweep, in-band fraction, drain
    ETA and the monotonic heartbeat stamp every update refreshes. All
    writes are a dict-merge under one lock — always-on like the
    metrics registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._doc: Dict[str, object] = {}
        self._fracs: List[float] = []

    def update(self, **kw) -> None:
        # monotonic, not wall clock: the heartbeat AGE is what the
        # endpoint serves, and it must survive wall-clock steps
        with self._lock:
            self._doc.update(
                {k: v for k, v in kw.items() if v is not None}
            )
            self._doc["heartbeat_ts"] = time.monotonic()

    def note_sweep(self, rec: dict) -> None:
        af = _active_fraction(rec)
        with self._lock:
            self._fracs.append(af)
            del self._fracs[:-8]
            fr = list(self._fracs)
        d = drain_curve([dict(active_fraction=x) for x in fr])
        self.update(
            sweep=rec.get("sweep"), in_band=rec.get("in_band"),
            active_fraction=round(af, 4),
            drain_eta_sweeps=d["eta_sweeps"],
        )

    def snapshot(self) -> dict:
        with self._lock:
            d = dict(self._doc)
        ts = d.pop("heartbeat_ts", None)
        d["heartbeat_age_s"] = (
            round(time.monotonic() - ts, 3) if ts is not None else None
        )
        return d

    def reset(self) -> None:
        with self._lock:
            self._doc.clear()
            self._fracs.clear()


_RUN_STATE = RunState()


def run_state() -> RunState:
    """The process-global live run state (the drivers write it at phase
    / iteration / sweep boundaries; `service.status` serves it)."""
    return _RUN_STATE


def note_sweep(rec: dict) -> None:
    """Hook called by `obs.metrics.record_sweep` for every sweep record
    on every driver path — keeps the live endpoint current without
    separate instrumentation sites."""
    _RUN_STATE.note_sweep(rec)
