"""Unified observability layer: span tracer + metrics registry + report.

One subsystem serving both drivers, the failsafe/checkpoint stack and
the bench ladder (the `mytime`/`printim`/`PMMG_VERB_*` role of the
reference, extended to attribute time inside jitted/SPMD regions):

- `obs.trace`  — hierarchical spans (run → iteration → phase → op)
  exported as Chrome-trace-event JSON (Perfetto-loadable) + a durable
  JSONL event log, with `jax.profiler` alignment and an opt-in device
  capture window (``PMMGTPU_TRACE=dir[,profile]``). Disabled (the
  default) it compiles down to no-op singletons.
- `obs.metrics` — typed counters/gauges/histograms, per-rank under
  `jax.distributed`, with a rank merge so one JSON describes the world.
- `obs.costs` — XLA cost/roofline attribution per jitted phase
  (flops, bytes accessed, bound=compute|memory vs a per-platform peak
  table) + HBM watermark gauges at phase boundaries.
- `obs.history` — the PERF_DB record envelope (`schema`/`run_id`/
  `git_sha`/`timestamp`/`platform`/`rung`), the historical-bench
  backfill importer, and the noise-aware regression gate behind
  `tools/perf_gate.py`.
- `obs.report` — the post-mortem renderer behind `tools/obs_report.py`.
- `obs.dist` — the cross-rank performance observatory (round 11):
  clock-aligned merged timelines, collective straggler/transfer
  decomposition, load-imbalance accounting and per-iteration
  critical-path extraction behind ``obs_report --dist``.
"""

from . import costs, dist, history, metrics, report, trace  # noqa: F401
from .metrics import MetricsRegistry, merge_rank_docs, registry  # noqa: F401
from .trace import (  # noqa: F401
    NullTracer,
    Tracer,
    emit_event,
    get_tracer,
    install,
    traced,
)
