"""parmmg_tpu: TPU-native parallel tetrahedral mesh adaptation.

A from-scratch JAX/XLA/Pallas framework with the capabilities of ParMmg
(distributed anisotropic remeshing by iterative remesh-and-repartition; see
SURVEY.md): flat sharded mesh arrays, batched remeshing kernels, SFC
repartitioning, and collective-based interface exchange in place of MPI.
"""

__version__ = "0.1.0"

from .core.mesh import Mesh  # noqa: F401
from .core import tags  # noqa: F401
