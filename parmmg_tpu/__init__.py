"""parmmg_tpu: TPU-native parallel tetrahedral mesh adaptation.

A from-scratch JAX/XLA/Pallas framework with the capabilities of ParMmg
(distributed anisotropic remeshing by iterative remesh-and-repartition; see
SURVEY.md): flat sharded mesh arrays, batched remeshing kernels, SFC
repartitioning, and collective-based interface exchange in place of MPI.
"""

__version__ = "0.3.0"
# version metadata surface of the reference's configure-time header
# (`src/pmmgversion.h.in:31-39`: RELEASE/MAJOR/MINOR/PATCH/DATE macros)
VERSION_MAJOR, VERSION_MINOR, VERSION_PATCH = (
    int(x) for x in __version__.split(".")
)
RELEASE_DATE = "2026-07-31"
COPYRIGHT = "TPU-native rebuild; reference ParMmg (c) Bx INP/INRIA"


def version_eq(major: int, minor: int) -> bool:
    """`PMMG_VERSION_EQ` role (reference `src/pmmgversion.h.in:40`)."""
    return VERSION_MAJOR == major and VERSION_MINOR == minor


def version_ge(major: int, minor: int) -> bool:
    """`PMMG_VERSION_GE` role."""
    return (VERSION_MAJOR, VERSION_MINOR) >= (major, minor)


# multi-host runs (the mpirun -np analog): the coordination service
# must come up before anything touches the XLA backend, and the heavy
# imports below do — so the env-contract hook runs first
import os as _os  # noqa: E402

if _os.environ.get("PMMGTPU_COORDINATOR"):
    from .parallel import multihost as _multihost  # noqa: E402

    _multihost.init_from_env()

# jax version graft: this tree (and its tests) target the public
# `jax.shard_map` API; on jax builds that still ship it as
# `jax.experimental.shard_map` only, alias it so one source works on
# both — without this every shard_map code path dies with
# AttributeError on the older runtime
import jax as _jax  # noqa: E402

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map  # noqa: E402

    _jax.shard_map = _shard_map

from .core.mesh import Mesh  # noqa: E402,F401
from .core import tags  # noqa: E402,F401
