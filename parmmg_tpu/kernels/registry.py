"""Named-kernel registry and backend dispatch for the Pallas subsystem.

Every hand-fused Pallas kernel registers here as a pair
``{pallas_impl, lax_reference}`` under a stable name; op-layer call
sites go through :func:`dispatch` and stay backend-agnostic. Selection
is a process-wide *mode*:

- ``auto`` (default): Pallas on TPU, the lax reference elsewhere — the
  fused kernels exist for the TPU memory hierarchy; on CPU the XLA
  fusion of the reference chain is the fast path.
- ``off``: lax reference everywhere (the A/B baseline: bit-identical
  to the pre-kernel code paths, which the references *are*).
- ``on``: Pallas everywhere — ``interpret=True`` execution on
  non-TPU backends, so tier-1 / check.sh exercise the kernel bodies
  on every run (tools/kernel_smoke.py, tests/test_m18_kernels.py).
- ``<csv>``: comma-separated allowlist of kernel names that run as
  Pallas (interpret off-TPU); everything else takes the reference.

Mode sources, strongest first: an explicit :func:`set_mode` (the
``AdaptOptions.kernels`` plumbing in both drivers) > the
``PMMGTPU_KERNELS`` environment variable > ``auto``.

The dispatch decision is read at *trace time* (the call sites live in
module-level jitted sweeps), so an effective-mode change must
invalidate warmed traces: :func:`set_mode` calls ``jax.clear_caches()``
when the effective mode actually changes. Mode flips are A/B events
(bench, smoke), not hot-path events, so the recompile is the honest
price of the switch.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Callable, Dict, Optional

__all__ = [
    "Kernel", "register", "get", "names", "resolve_mode", "set_mode",
    "use_mode", "enabled", "interpret", "dispatch",
]

_ENV = "PMMGTPU_KERNELS"


@dataclasses.dataclass(frozen=True)
class Kernel:
    """One registered kernel: the fused Pallas implementation, its lax
    reference (the exact pre-kernel computation — `off` mode routes
    here, which is what makes the A/B bit-identical), and an analytic
    I/O cost model for the roofline after-picture (the Pallas kernel's
    bytes-moved contract is exactly its operand/result footprint)."""

    name: str
    pallas_impl: Callable
    lax_reference: Callable
    doc: str = ""
    # est_cost(*args) -> dict(flops=..., bytes_accessed=...) for the
    # fused kernel's I/O contract (tables counted once, index streams
    # and outputs once) — fed to pl.CostEstimate and profile_ops
    est_cost: Optional[Callable] = None


_REGISTRY: Dict[str, Kernel] = {}
# explicit mode override ([None] = fall through to the environment);
# a one-element list so jitted closures never capture a stale binding
_MODE = [None]
_LOCK = threading.Lock()


def register(name: str, pallas_impl: Callable, lax_reference: Callable,
             doc: str = "", est_cost: Optional[Callable] = None) -> Kernel:
    """Register (or re-register, e.g. on module reload) a kernel pair."""
    k = Kernel(name, pallas_impl, lax_reference, doc, est_cost)
    with _LOCK:
        _REGISTRY[name] = k
    return k


def get(name: str) -> Kernel:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown kernel {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def names() -> tuple:
    return tuple(sorted(_REGISTRY))


def _normalize(mode: Optional[str]) -> str:
    if mode is None or mode == "":
        return "auto"
    m = str(mode).strip().lower()
    if m in ("auto",):
        return "auto"
    if m in ("off", "0", "none", "false"):
        return "off"
    if m in ("on", "1", "all", "force", "true"):
        return "on"
    return m  # csv allowlist, kept verbatim (lowercased)


def resolve_mode() -> str:
    """The effective mode: explicit override > PMMGTPU_KERNELS > auto."""
    m = _MODE[0]
    if m is None:
        m = os.environ.get(_ENV)
    return _normalize(m)


def set_mode(mode: Optional[str]) -> str:
    """Set the process kernel mode (None = defer to the environment
    again). When the *effective* mode changes, warmed jit traces are
    dropped (`jax.clear_caches`) — the dispatch decision is baked in at
    trace time, so a stale trace would silently keep the old backend.
    Returns the previous override value (for use_mode restore)."""
    with _LOCK:
        prev = _MODE[0]
        before = resolve_mode()
        _MODE[0] = mode
        after = resolve_mode()
    if before != after:
        import jax

        jax.clear_caches()
    return prev


@contextlib.contextmanager
def use_mode(mode: Optional[str]):
    """Scoped mode override (tests, smoke A/Bs)."""
    prev = set_mode(mode)
    try:
        yield
    finally:
        set_mode(prev)


def enabled(name: str) -> bool:
    """Does `name` dispatch to its Pallas implementation right now?
    Read at trace time by the jitted call sites (see set_mode)."""
    mode = resolve_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    if mode == "auto":
        import jax

        return jax.default_backend() == "tpu"
    allow = {s.strip() for s in mode.split(",") if s.strip()}
    return name in allow


def interpret() -> bool:
    """Pallas execution mode for the current backend: compiled Mosaic
    on TPU, `interpret=True` elsewhere (the CPU path tier-1 and the
    kernel smoke exercise)."""
    import jax

    return jax.default_backend() != "tpu"


def dispatch(name: str, *args, **kwargs):
    """The single backend-agnostic entry point: route to the Pallas
    implementation when the mode admits `name`, else to the lax
    reference. Both implementations share one calling convention per
    kernel (documented at the registration site)."""
    k = get(name)
    impl = k.pallas_impl if enabled(name) else k.lax_reference
    return impl(*args, **kwargs)
