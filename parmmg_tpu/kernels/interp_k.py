"""Barycentric locate + metric interpolation Pallas kernel
(`interp_bary`) for `ops/interp.py`.

The interpolation pull after a walk-locate runs three chained
memory-bound passes per query point: gather the containing tet's
corner rows, evaluate + clamp the barycentric coordinates, then gather
the corner metrics and interpolate (harmonic-in-1/h for iso). The
fused kernel keeps the vertex and metric tables VMEM-resident and
emits (clamped barycentric weights, interpolated metric) in one pass
over the packed query stream.

Calling convention (both impls):

    interp_bary(vert [P,3], met [P,C], vids [Q,4] i32, pts [Q,3])
        -> (bary [Q,4], met_q [Q,C])

The barycentric expression is exactly `ops.locate.tet_barycoords` +
`clamp_bary`, and the metric rule exactly `core.metric.interp_metric`,
so recomputing them here agrees bit-for-bit with the walk's own
output. The anisotropic (C == 6) metric rule is log-Euclidean — an
eigendecomposition per point, outside what a TPU Pallas body can
express — so the Pallas wrapper routes aniso calls to the lax
reference (documented tolerance story: there is none to justify;
aniso simply stays on the reference path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import metric as metric_mod
from ..ops import locate as locate_mod
from . import registry
from .quality_k import BLK, pad_rows, stream_spec, table_spec


def _interp_bary_ref(vert, met, vids, pts):
    lam = locate_mod.tet_barycoords(vert[vids], pts)
    bary = locate_mod.clamp_bary(lam)
    return bary, metric_mod.interp_metric(met[vids], bary)


def interp_bary_kernel(vert_ref, met_ref, vids_ref, pts_ref,
                       bary_ref, met_out_ref):
    verts = vert_ref[...]
    mets = met_ref[...]
    vids = vids_ref[...]
    pts = pts_ref[...]
    lam = locate_mod.tet_barycoords(verts[vids], pts)
    bary = locate_mod.clamp_bary(lam)
    bary_ref[...] = bary
    met_out_ref[...] = metric_mod.interp_metric(mets[vids], bary)


def _interp_bary_pallas(vert, met, vids, pts):
    import jax.experimental.pallas as pl

    if met.shape[-1] != 1:
        # log-Euclidean aniso interpolation needs an eigh per point —
        # not expressible in a TPU Pallas body; stay on the reference
        return _interp_bary_ref(vert, met, vids, pts)
    q = vids.shape[0]
    vidsp = pad_rows(vids.astype(jnp.int32), BLK)
    ptsp = pad_rows(pts, BLK)
    npad = vidsp.shape[0]
    bary, met_q = pl.pallas_call(
        interp_bary_kernel,
        grid=(npad // BLK,),
        in_specs=[
            table_spec(vert.shape),
            table_spec(met.shape),
            stream_spec(4),
            stream_spec(3),
        ],
        out_specs=(stream_spec(4), stream_spec(met.shape[1])),
        out_shape=(
            jax.ShapeDtypeStruct((npad, 4), vert.dtype),
            jax.ShapeDtypeStruct((npad, met.shape[1]), met.dtype),
        ),
        interpret=registry.interpret(),
    )(vert, met, vidsp, ptsp)
    return bary[:q], met_q[:q]


def _interp_bary_cost(vert, met, vids, pts):
    q = vids.shape[0]
    itemsize = jnp.dtype(vert.dtype).itemsize
    table_b = (vert.size + met.size) * itemsize
    stream_b = vids.size * 4 + (pts.size + q * 4 + q * met.shape[1]) * itemsize
    return dict(flops=float(140 * q),
                bytes_accessed=float(table_b + stream_b))


registry.register(
    "interp_bary", _interp_bary_pallas, _interp_bary_ref,
    doc="fused barycentric coordinates (clamped) + metric "
        "interpolation at located points (ops/interp.py pull phase; "
        "aniso metrics route to the lax reference — log-Euclidean "
        "needs eigh)",
    est_cost=_interp_bary_cost,
)
