"""Cavity-evaluation Pallas kernels for collapse and split.

`collapse_cavity` is the PERF_NOTES round-9 740 ms target: inside the
collapse MIS loop every evaluation round re-streams the vertex/metric
tables to score the tentative (retargeted) one-ring — quality of the
would-be cavity, its new volumes, and the positivity gate that feeds
the per-winner ball minimum. The fused kernel gathers each candidate
tet's corners once from the VMEM-resident tables and emits the gated
quality directly (`q_new` where `vol_new` clears the scale-relative
floor, else -inf), exactly the value the ball min-scatter consumes.

`split_midpoint` fuses split's curvature-corrected-midpoint validity:
gather the corners of every incident tet, substitute the offset
midpoint into both child configurations (one-hot select — the batched
equivalent of the `.at[rows, l].set` pair), and compare both child
volumes against the positivity floor of the parent volume, in one
pass.

Calling conventions (both impls each):

    collapse_cavity(vert [P,3], met [P,C], new_tet [N,4] i32,
                    vol_floor [N]) -> gated quality [N]
    split_midpoint(vert [P,3], tet [N,4] i32, newp [N,3],
                   li [N] i32, lj [N] i32) -> ok [N] bool

Both lax references are the pre-kernel expression DAGs verbatim, so
`off` mode is bit-identical to the historical code path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import registry
from .quality_k import BLK, pad_rows, quality_vol_math, stream_spec, table_spec


# ---------------------------------------------------------------------------
# collapse cavity
# ---------------------------------------------------------------------------


def _collapse_cavity_ref(vert, met, new_tet, vol_floor):
    from ..ops import common

    q_new = common.quality_of(vert, met, new_tet)
    vol_new = common.vol_of(vert, new_tet)
    return jnp.where(vol_new > vol_floor, q_new, -jnp.inf)


def collapse_cavity_kernel(vert_ref, met_ref, tet_ref, floor_ref, out_ref):
    verts = vert_ref[...]
    mets = met_ref[...]
    idx = tet_ref[...]
    q, vol = quality_vol_math(verts[idx], mets[idx])
    gate = jnp.where(vol > floor_ref[..., 0], q, -jnp.inf)
    out_ref[...] = gate[:, None]


def _collapse_cavity_pallas(vert, met, new_tet, vol_floor):
    import jax.experimental.pallas as pl

    n = new_tet.shape[0]
    tetp = pad_rows(new_tet.astype(jnp.int32), BLK)
    floorp = pad_rows(vol_floor[:, None], BLK)
    npad = tetp.shape[0]
    out = pl.pallas_call(
        collapse_cavity_kernel,
        grid=(npad // BLK,),
        in_specs=[
            table_spec(vert.shape),
            table_spec(met.shape),
            stream_spec(4),
            stream_spec(1),
        ],
        out_specs=stream_spec(1),
        out_shape=jax.ShapeDtypeStruct((npad, 1), vert.dtype),
        interpret=registry.interpret(),
    )(vert, met, tetp, floorp)
    return out[:n, 0]


def _collapse_cavity_cost(vert, met, new_tet, vol_floor):
    n = new_tet.shape[0]
    itemsize = jnp.dtype(vert.dtype).itemsize
    table_b = (vert.size + met.size) * itemsize
    stream_b = new_tet.size * 4 + 2 * n * itemsize
    per_row = 170 if met.shape[1] == 1 else 430
    return dict(flops=float(per_row * n),
                bytes_accessed=float(table_b + stream_b))


registry.register(
    "collapse_cavity", _collapse_cavity_pallas, _collapse_cavity_ref,
    doc="collapse MIS evaluation: gated cavity quality of the "
        "retargeted one-ring in one VMEM-resident pass (the round-9 "
        "740 ms fusion target)",
    est_cost=_collapse_cavity_cost,
)


# ---------------------------------------------------------------------------
# split midpoint validity
# ---------------------------------------------------------------------------


def _tet_vol(cc):
    d1 = cc[:, 1] - cc[:, 0]
    d2 = cc[:, 2] - cc[:, 0]
    d3 = cc[:, 3] - cc[:, 0]
    return jnp.einsum("ti,ti->t", jnp.cross(d1, d2), d3) / 6.0


def _split_midpoint_ref(vert, tet, newp, li, lj):
    from ..ops import common

    c = vert[tet]                                   # [N,4,3]
    rows = jnp.arange(tet.shape[0], dtype=jnp.int32)
    cA = c.at[rows, lj].set(newp)
    cB = c.at[rows, li].set(newp)
    vol_p = jnp.abs(_tet_vol(c))
    floor = common.POS_VOL_FRAC * vol_p
    return (_tet_vol(cA) > floor) & (_tet_vol(cB) > floor)


def split_midpoint_kernel(vert_ref, tet_ref, newp_ref, li_ref, lj_ref,
                          ok_ref):
    from ..ops.common import POS_VOL_FRAC

    verts = vert_ref[...]
    idx = tet_ref[...]
    newp = newp_ref[...]
    li = li_ref[..., 0]
    lj = lj_ref[..., 0]
    c = verts[idx]                                  # [B,4,3]
    slot = jax.lax.broadcasted_iota(jnp.int32, (c.shape[0], 4), 1)
    selA = (slot == lj[:, None])[..., None]
    selB = (slot == li[:, None])[..., None]
    cA = jnp.where(selA, newp[:, None, :], c)
    cB = jnp.where(selB, newp[:, None, :], c)
    floor = POS_VOL_FRAC * jnp.abs(_tet_vol(c))
    ok = (_tet_vol(cA) > floor) & (_tet_vol(cB) > floor)
    ok_ref[...] = ok.astype(jnp.int32)[:, None]


def _split_midpoint_pallas(vert, tet, newp, li, lj):
    import jax.experimental.pallas as pl

    n = tet.shape[0]
    tetp = pad_rows(tet.astype(jnp.int32), BLK)
    newpp = pad_rows(newp, BLK)
    lip = pad_rows(li.astype(jnp.int32)[:, None], BLK)
    ljp = pad_rows(lj.astype(jnp.int32)[:, None], BLK)
    npad = tetp.shape[0]
    ok = pl.pallas_call(
        split_midpoint_kernel,
        grid=(npad // BLK,),
        in_specs=[
            table_spec(vert.shape),
            stream_spec(4),
            stream_spec(3),
            stream_spec(1),
            stream_spec(1),
        ],
        out_specs=stream_spec(1),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        interpret=registry.interpret(),
    )(vert, tetp, newpp, lip, ljp)
    return ok[:n, 0] != 0


def _split_midpoint_cost(vert, tet, newp, li, lj):
    n = tet.shape[0]
    itemsize = jnp.dtype(vert.dtype).itemsize
    table_b = vert.size * itemsize
    stream_b = tet.size * 4 + newp.size * itemsize + 2 * n * 4 + n * 4
    return dict(flops=float(130 * n),
                bytes_accessed=float(table_b + stream_b))


registry.register(
    "split_midpoint", _split_midpoint_pallas, _split_midpoint_ref,
    doc="split curvature-corrected midpoint validity: both child "
        "volumes of every incident tet vs the parent positivity floor "
        "in one fused pass",
    est_cost=_split_midpoint_cost,
)
