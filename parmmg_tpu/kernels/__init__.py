"""Hand-fused Pallas TPU kernels for the memory-bound sweep hot paths.

PERF_NOTES round 9 measured every sweep op memory-bound at 0.24–0.55
flop/byte and 0.8–2.5 % of the bandwidth roof: the lax versions lower
to unfused gather→compute→scatter chains that re-stream the
vertex/tet tables from HBM many times per op. This package hand-fuses
the worst offenders as Pallas kernels over int32 index streams and
flat f32 arrays, each paired with its exact lax reference behind the
:mod:`registry` dispatch so every call site stays backend-agnostic:

- ``collapse_cavity`` — tet quality + cavity evaluation for collapse
  (the round-9 740 ms / 0.81 %-of-roof target);
- ``quality_vol`` — fused per-tet quality + volume (swap 3-2/2-3,
  collapse hoists, smoothing, quality histograms);
- ``split_midpoint`` — split's curvature-corrected midpoint validity;
- ``interp_bary`` — barycentric locate + metric interpolation for
  `ops/interp.py`.

Selection: ``AdaptOptions.kernels`` / ``PMMGTPU_KERNELS`` =
``auto | off | on | <csv-allowlist>`` (auto = Pallas on TPU, lax
elsewhere; non-TPU backends run Pallas in ``interpret=True`` mode so
tier-1 and check.sh exercise the kernel bodies — see
tools/kernel_smoke.py). ``off`` routes every call to the lax
reference, which *is* the pre-kernel code path: bit-identical A/B.
"""

from .registry import (  # noqa: F401
    Kernel, dispatch, enabled, get, interpret, names, register,
    resolve_mode, set_mode, use_mode,
)

# importing the kernel modules registers them
from . import cavity_k, interp_k, quality_k  # noqa: F401, E402


def quality_vol(vert, met, tet):
    """(q [N], vol [N]) of packed tet rows — fused quality + volume."""
    return dispatch("quality_vol", vert, met, tet)


def collapse_cavity(vert, met, new_tet, vol_floor):
    """Gated cavity quality of the retargeted one-ring: q_new where
    vol_new clears `vol_floor`, else -inf (the ball-min operand)."""
    return dispatch("collapse_cavity", vert, met, new_tet, vol_floor)


def split_midpoint(vert, tet, newp, li, lj):
    """[N] bool — both children of the midpoint substitution keep the
    positivity floor of the parent volume."""
    return dispatch("split_midpoint", vert, tet, newp, li, lj)


def interp_bary(vert, met, vids, pts):
    """(clamped bary [Q,4], interpolated metric [Q,C]) at located
    points."""
    return dispatch("interp_bary", vert, met, vids, pts)
