"""Fused tet quality + volume Pallas kernel (`quality_vol`).

The lax chain the sweep ops ran before this subsystem —
`common.quality_of(vert, met, tet)` followed by `common.vol_of(vert,
tet)` — lowers to two gathers of the corner rows plus a string of
HBM-materialized intermediates (`e` [T,6,3], `l2` [T,6], the sym6
tensor mean), which is why PERF_NOTES round 9 measures every consumer
memory-bound at 0.24–0.55 flop/byte. The fused kernel keeps the
vertex/metric tables VMEM-resident, gathers the 4 corner rows of each
packed tet row once, and produces (quality, signed volume) in one
pass: its bytes-moved contract is exactly tables + index stream +
two output columns.

Shared calling convention (both impls, enforced by the m18
equivalence tests):

    quality_vol(vert [P,3], met [P,C], tet [N,4] int32) -> (q [N], vol [N])

with C == 1 (iso size) or 6 (sym6 tensor), dtype following `vert`.
The arithmetic is the *same expression DAG* as the reference
(`ops.common.quality_of` / `vol_of`), so `PMMGTPU_KERNELS=off` and the
interpret-mode Pallas path agree bit-for-bit on CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import metric as metric_mod
from ..core.mesh import EDGE_VERTS
from ..ops.quality import ALPHA
from . import registry

# rows per grid step: one VMEM-sized tile of the packed candidate
# stream (the tables ride along whole — the VMEM-residency premise).
# 1024 rows keeps the interpret-mode grid short on the CPU fixtures
# while staying far under the VMEM budget next to a ~1M-row table.
BLK = 1024

# the 6 tet edges as STATIC python pairs: a Pallas body cannot close
# over array constants, and the static unroll selects the same corner
# rows the reference's EDGE_VERTS gather does (bit-identical values)
_EV_PAIRS = tuple((int(a), int(b)) for a, b in np.asarray(EDGE_VERTS))


def quality_vol_math(c: jax.Array, m4: jax.Array):
    """(q, vol) from gathered corners c [B,4,3] and corner metrics
    m4 [B,4,C] — the exact `quality_of`/`vol_of` expression DAG,
    shared by the Pallas kernel body and usable on any backend."""
    d1, d2, d3 = c[:, 1] - c[:, 0], c[:, 2] - c[:, 0], c[:, 3] - c[:, 0]
    vol = jnp.einsum("ti,ti->t", jnp.cross(d1, d2), d3) / 6.0
    e = jnp.stack([c[:, b] - c[:, a] for a, b in _EV_PAIRS], axis=1)
    if m4.shape[-1] == 6:
        mt = jnp.mean(m4, axis=1)
        M = metric_mod.sym6_to_mat(mt)
        l2 = jnp.einsum("tei,tij,tej->te", e, M, e)
        volm = vol * jnp.sqrt(jnp.maximum(metric_mod.metric_det(mt), 0.0))
    else:
        h = jnp.mean(m4[..., 0], axis=1)
        l2 = jnp.sum(e * e, axis=-1) / jnp.maximum(h[:, None] ** 2, 1e-30)
        volm = vol / jnp.maximum(h ** 3, 1e-30)
    rap = jnp.sum(l2, axis=-1)
    q = ALPHA * volm / jnp.maximum(rap, 1e-30) ** 1.5
    return jnp.where(jnp.isfinite(q), q, 0.0), vol


def _quality_vol_ref(vert, met, tet):
    """Lax reference: the pre-kernel chain, verbatim (off-mode =
    bit-identical to the code the call sites ran before)."""
    from ..ops import common

    return common.quality_of(vert, met, tet), common.vol_of(vert, tet)


def quality_vol_kernel(vert_ref, met_ref, tet_ref, q_ref, vol_ref):
    """Pallas body: VMEM-resident tables, one corner gather, fused
    quality+volume. f32/i32 on the compiled TPU path (PML011)."""
    verts = vert_ref[...]
    mets = met_ref[...]
    idx = tet_ref[...]
    q, vol = quality_vol_math(verts[idx], mets[idx])
    q_ref[...] = q[:, None]
    vol_ref[...] = vol[:, None]


def pad_rows(a: jax.Array, blk: int) -> jax.Array:
    """Pad the leading dim up to a multiple of `blk` (zero rows — the
    padded outputs are sliced off by the wrapper)."""
    n = a.shape[0]
    npad = -(-max(n, 1) // blk) * blk
    if npad == n:
        return a
    pad = [(0, npad - n)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


def table_spec(shape):
    """BlockSpec for a whole-array (VMEM-resident) table input."""
    import jax.experimental.pallas as pl

    return pl.BlockSpec(shape, lambda i: (0,) * len(shape))


def stream_spec(cols: int):
    """BlockSpec for one BLK-row tile of a packed per-candidate
    stream (index columns or per-row scalars)."""
    import jax.experimental.pallas as pl

    return pl.BlockSpec((BLK, cols), lambda i: (i, 0))


def _quality_vol_pallas(vert, met, tet):
    import jax.experimental.pallas as pl

    n = tet.shape[0]
    tetp = pad_rows(tet.astype(jnp.int32), BLK)
    npad = tetp.shape[0]
    q, vol = pl.pallas_call(
        quality_vol_kernel,
        grid=(npad // BLK,),
        in_specs=[
            table_spec(vert.shape),
            table_spec(met.shape),
            stream_spec(4),
        ],
        out_specs=(stream_spec(1), stream_spec(1)),
        out_shape=(
            jax.ShapeDtypeStruct((npad, 1), vert.dtype),
            jax.ShapeDtypeStruct((npad, 1), vert.dtype),
        ),
        interpret=registry.interpret(),
    )(vert, met, tetp)
    return q[:n, 0], vol[:n, 0]


def _quality_vol_cost(vert, met, tet):
    n = tet.shape[0]
    itemsize = jnp.dtype(vert.dtype).itemsize
    table_b = vert.size * itemsize + met.size * itemsize
    stream_b = tet.size * 4 + 2 * n * itemsize
    # ~40 flops for the volume triple product, ~6*(4..25) for the edge
    # lengths, plus the mean/pow tail — order-of-magnitude anchor
    per_row = 160 if met.shape[1] == 1 else 420
    return dict(flops=float(per_row * n),
                bytes_accessed=float(table_b + stream_b))


registry.register(
    "quality_vol", _quality_vol_pallas, _quality_vol_ref,
    doc="fused per-tet quality + signed volume over a packed int32 "
        "tet stream (collapse/swap/smooth/quality call sites)",
    est_cost=_quality_vol_cost,
)
