"""Byte-buffer bridge for the C ABI (`native/parmmg_capi.c`).

The reference exposes its full setter/getter surface to C/Fortran
callers (`src/API_functions_pmmg.c`, `src/API_functionsf_pmmg.c`); here
the same staged-arrays workflow — set vertices/tets/trias/metric from
raw buffers, adapt, read results back — crosses the FFI as contiguous
bytes and is reshaped onto `api.ParMesh` on this side. Entity indices
cross the ABI 1-BASED like the reference API (Fortran heritage); the
conversion to the internal 0-based arrays happens here.
"""

from __future__ import annotations

import numpy as np

from .api import Param, ParMesh


def make_parmesh(nparts: int) -> ParMesh:
    return ParMesh(nparts=max(1, int(nparts)))


def set_vertices(pm: ParMesh, coords: bytes, refs: bytes | None, n: int):
    c = np.frombuffer(coords, np.float64).reshape(n, 3)
    r = np.frombuffer(refs, np.int32) if refs else None
    return int(pm.set_vertices(c, r))


def set_tetrahedra(pm: ParMesh, tets: bytes, refs: bytes | None, n: int):
    t = np.frombuffer(tets, np.int32).reshape(n, 4) - 1  # 1-based ABI
    r = np.frombuffer(refs, np.int32) if refs else None
    return int(pm.set_tetrahedra(t, r))


def set_triangles(pm: ParMesh, trias: bytes, refs: bytes | None, n: int):
    t = np.frombuffer(trias, np.int32).reshape(n, 3) - 1
    r = np.frombuffer(refs, np.int32) if refs else None
    return int(pm.set_triangles(t, r))


def set_metric(pm: ParMesh, met: bytes, n: int, ncomp: int):
    m = np.frombuffer(met, np.float64).reshape(n, ncomp)
    return int(pm.set_metric_sols(m))


def set_iparameter(pm: ParMesh, param: int, value: int):
    return int(pm.set_iparameter(Param(param), value))


def set_dparameter(pm: ParMesh, param: int, value: float):
    return int(pm.set_dparameter(Param(param), value))


def run(pm: ParMesh) -> int:
    return int(pm.parmmglib_centralized())


def get_mesh_size(pm: ParMesh):
    d = pm._result_mesh().to_numpy()
    return len(d["verts"]), len(d["tets"]), len(d["trias"])


def get_vertices(pm: ParMesh):
    d = pm._result_mesh().to_numpy()
    return (
        np.ascontiguousarray(d["verts"], np.float64).tobytes(),
        np.ascontiguousarray(d["vrefs"], np.int32).tobytes(),
    )


def get_tetrahedra(pm: ParMesh):
    d = pm._result_mesh().to_numpy()
    return (
        np.ascontiguousarray(d["tets"] + 1, np.int32).tobytes(),
        np.ascontiguousarray(d["trefs"], np.int32).tobytes(),
    )


def get_triangles(pm: ParMesh):
    d = pm._result_mesh().to_numpy()
    return (
        np.ascontiguousarray(d["trias"] + 1, np.int32).tobytes(),
        np.ascontiguousarray(d["trrefs"], np.int32).tobytes(),
    )


def get_metric(pm: ParMesh):
    d = pm._result_mesh().to_numpy()
    return np.ascontiguousarray(d["met"], np.float64).tobytes()
