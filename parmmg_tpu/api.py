"""Public API: parameter enum + ParMesh setter/getter surface.

TPU-native counterpart of the reference's public API layer
(`PMMG_Init_parMesh` / `PMMG_Set_*` / `PMMG_Get_*` /
`PMMG_Set_iparameter` / `PMMG_Set_dparameter`, reference
`src/API_functions_pmmg.c:36,531,735` and the `PMMG_Param` enum at
`src/libparmmg.h:54-90`). The reference stages everything into MMG5
structs before running; here the setters stage 0-based numpy arrays and
`parmmglib_centralized()` / `parmmglib_distributed()` build the device
`Mesh`, run the adaptation drivers, and leave results readable through
the getters.

Entity indices are 0-based throughout (the Fortran-facing 1-based
convention of the C API is a language accident, not a capability).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Tuple

import numpy as np

from .core import tags
from .core.mesh import Mesh
from .core.tags import APIDistrib, ReturnStatus
from .models.adapt import AdaptOptions
from .models.distributed import DistOptions


class Param(enum.IntEnum):
    """`PMMG_Param` equivalents (reference `src/libparmmg.h:54-90`)."""

    # integer parameters
    IPARAM_verbose = 0
    IPARAM_mem = 1
    IPARAM_debug = 2
    IPARAM_angle = 3          # enable angle detection (1) or not (0)
    IPARAM_iso = 4            # level-set discretization mode
    IPARAM_opnbdy = 5
    IPARAM_optim = 6
    IPARAM_optimLES = 7
    IPARAM_nofem = 8
    IPARAM_noinsert = 9
    IPARAM_noswap = 10
    IPARAM_nomove = 11
    IPARAM_nosurf = 12
    IPARAM_anisosize = 13
    IPARAM_octree = 14
    IPARAM_meshSize = 15      # remesher target mesh size
    IPARAM_nobalancing = 16
    IPARAM_metisRatio = 17
    IPARAM_ifcLayers = 18
    IPARAM_groupsRatio = 19
    IPARAM_APImode = 20
    IPARAM_globalNum = 21
    IPARAM_niter = 22
    IPARAM_distributedOutput = 23
    IPARAM_nparts = 24        # TPU addition: shard count (devices)
    # lagrangian motion (reference PMMG_IPARAM_lag, src/libparmmg.h:63):
    # present so API-compatible callers get the reference's clean
    # rejection (src/libparmmg.c:69-73) instead of an attribute error
    IPARAM_lag = 25
    # double parameters
    DPARAM_angleDetection = 32
    DPARAM_hmin = 33
    DPARAM_hmax = 34
    DPARAM_hsiz = 35
    DPARAM_hausd = 36
    DPARAM_hgrad = 37
    DPARAM_hgradreq = 38
    DPARAM_ls = 39
    # TPU addition: closed-loop balance band (measured work max/mean
    # above which the balancer forces a re-cut; <= 0 disables)
    DPARAM_balanceBand = 40


_SOL_SIZES = {"scalar": 1, "vector": 3, "tensor": 6}


@dataclasses.dataclass
class _Staging:
    """Host-side entity staging (the MMG5_Mesh-filling role of
    `MMG3D_Set_vertex` etc. that the reference's setters delegate to)."""

    verts: Optional[np.ndarray] = None
    vrefs: Optional[np.ndarray] = None
    tets: Optional[np.ndarray] = None
    trefs: Optional[np.ndarray] = None
    trias: Optional[np.ndarray] = None
    trrefs: Optional[np.ndarray] = None
    edges: Optional[np.ndarray] = None
    edrefs: Optional[np.ndarray] = None
    corners: List[int] = dataclasses.field(default_factory=list)
    req_verts: List[int] = dataclasses.field(default_factory=list)
    req_trias: List[int] = dataclasses.field(default_factory=list)
    req_edges: List[int] = dataclasses.field(default_factory=list)
    ridges: List[int] = dataclasses.field(default_factory=list)
    met: Optional[np.ndarray] = None
    ls: Optional[np.ndarray] = None
    disp: Optional[np.ndarray] = None
    fields: List[np.ndarray] = dataclasses.field(default_factory=list)


class ParMesh:
    """The `PMMG_ParMesh` role: staged mesh + parameters + results.

    Typical centralized flow (mirrors
    `libexamples/adaptation_example0/sequential_IO/manual_IO/main.c`):

        pm = ParMesh()
        pm.set_mesh_size(np=..., ne=..., nt=...)
        pm.set_vertices(coords, refs)
        pm.set_tetrahedra(tets, refs)
        pm.set_metric_sols(h)
        pm.set_dparameter(Param.DPARAM_hsiz, 0.05)
        assert pm.parmmglib_centralized() == ReturnStatus.SUCCESS
        verts, tets = pm.get_vertices()[0], pm.get_tetrahedra()[0]
    """

    def __init__(self, nparts: int = 1):
        self.stage = _Staging()
        self.opts = DistOptions(nparts=nparts)
        self.iparam: Dict[Param, int] = {}
        self.dparam: Dict[Param, float] = {}
        self.api_mode = APIDistrib.UNSET
        # distributed-API interface staging: rank -> list of
        # (color, local_ids, global_ids)
        self._node_comms: List[Tuple[int, np.ndarray, np.ndarray]] = []
        self._face_comms: List[Tuple[int, np.ndarray, np.ndarray]] = []
        self.mesh: Optional[Mesh] = None      # result (centralized view)
        self.stacked: Optional[Mesh] = None   # result (distributed view)
        self.comm = None                      # ShardComm of the result
        self.info: dict = {}
        self.status = ReturnStatus.SUCCESS

    # --- sizes ------------------------------------------------------------
    def set_mesh_size(self, np_: int = 0, ne: int = 0, nt: int = 0,
                      na: int = 0):
        """`PMMG_Set_meshSize`: pre-declare entity counts (np vertices,
        ne tetra, nt triangles, na edges). Allocation is implicit here;
        kept for call-site parity and early validation."""
        self._declared = (np_, ne, nt, na)
        return ReturnStatus.SUCCESS

    def get_mesh_size(self):
        m = self._result_mesh()
        return (int(m.npoin), int(m.ntet), int(m.ntria), int(m.nedge))

    # --- entity setters (bulk and by-index, PMMG_Set_vertex/vertices) -----
    def set_vertices(self, coords, refs=None):
        coords = np.asarray(coords, np.float64).reshape(-1, 3)
        self.stage.verts = coords
        self.stage.vrefs = (
            np.zeros(len(coords), np.int32) if refs is None
            else np.asarray(refs, np.int32)
        )
        return ReturnStatus.SUCCESS

    def set_vertex(self, c0, c1, c2, ref: int, pos: int):
        if self.stage.verts is None:
            n = self._declared[0]
            self.stage.verts = np.zeros((n, 3), np.float64)
            self.stage.vrefs = np.zeros(n, np.int32)
        self.stage.verts[pos] = (c0, c1, c2)
        self.stage.vrefs[pos] = ref
        return ReturnStatus.SUCCESS

    def set_tetrahedra(self, tets, refs=None):
        tets = np.asarray(tets, np.int32).reshape(-1, 4)
        self.stage.tets = tets
        self.stage.trefs = (
            np.zeros(len(tets), np.int32) if refs is None
            else np.asarray(refs, np.int32)
        )
        return ReturnStatus.SUCCESS

    def set_tetrahedron(self, v0, v1, v2, v3, ref: int, pos: int):
        if self.stage.tets is None:
            n = self._declared[1]
            self.stage.tets = np.zeros((n, 4), np.int32)
            self.stage.trefs = np.zeros(n, np.int32)
        self.stage.tets[pos] = (v0, v1, v2, v3)
        self.stage.trefs[pos] = ref
        return ReturnStatus.SUCCESS

    def set_triangles(self, trias, refs=None):
        trias = np.asarray(trias, np.int32).reshape(-1, 3)
        self.stage.trias = trias
        self.stage.trrefs = (
            np.zeros(len(trias), np.int32) if refs is None
            else np.asarray(refs, np.int32)
        )
        return ReturnStatus.SUCCESS

    def set_triangle(self, v0, v1, v2, ref: int, pos: int):
        if self.stage.trias is None:
            n = self._declared[2]
            self.stage.trias = np.zeros((n, 3), np.int32)
            self.stage.trrefs = np.zeros(n, np.int32)
        self.stage.trias[pos] = (v0, v1, v2)
        self.stage.trrefs[pos] = ref
        return ReturnStatus.SUCCESS

    def set_edges(self, edges, refs=None):
        edges = np.asarray(edges, np.int32).reshape(-1, 2)
        self.stage.edges = edges
        self.stage.edrefs = (
            np.zeros(len(edges), np.int32) if refs is None
            else np.asarray(refs, np.int32)
        )
        return ReturnStatus.SUCCESS

    def set_corner(self, pos: int):
        self.stage.corners.append(pos)
        return ReturnStatus.SUCCESS

    def set_required_vertex(self, pos: int):
        self.stage.req_verts.append(pos)
        return ReturnStatus.SUCCESS

    def set_required_triangle(self, pos: int):
        self.stage.req_trias.append(pos)
        return ReturnStatus.SUCCESS

    def set_required_edge(self, pos: int):
        self.stage.req_edges.append(pos)
        return ReturnStatus.SUCCESS

    def set_ridge(self, pos: int):
        self.stage.ridges.append(pos)
        return ReturnStatus.SUCCESS

    # --- solutions --------------------------------------------------------
    def set_met_size(self, typ: str, np_: int):
        ncomp = _SOL_SIZES[typ]
        self.stage.met = np.ones((np_, ncomp), np.float64)
        return ReturnStatus.SUCCESS

    def set_metric_sols(self, values):
        values = np.asarray(values, np.float64)
        if values.ndim == 1:
            values = values[:, None]
        if values.shape[1] not in (1, 6):
            raise ValueError("metric must be scalar or symmetric tensor")
        self.stage.met = values
        return ReturnStatus.SUCCESS

    def set_scalar_met(self, value: float, pos: int):
        self.stage.met[pos, 0] = value
        return ReturnStatus.SUCCESS

    def set_tensor_met(self, six, pos: int):
        self.stage.met[pos, :] = six
        return ReturnStatus.SUCCESS

    def set_level_set(self, values):
        self.stage.ls = np.asarray(values, np.float64).reshape(-1, 1)
        return ReturnStatus.SUCCESS

    def set_displacement(self, values):
        self.stage.disp = np.asarray(values, np.float64).reshape(-1, 3)
        return ReturnStatus.SUCCESS

    def set_field(self, values):
        v = np.asarray(values, np.float64)
        self.stage.fields.append(v.reshape(len(v), -1))
        return ReturnStatus.SUCCESS

    # --- parameters (PMMG_Set_iparameter / _dparameter) -------------------
    def set_iparameter(self, param: Param, value: int):
        param = Param(param)
        o = self.opts
        if param == Param.IPARAM_verbose:
            o.verbose = int(value)
        elif param == Param.IPARAM_niter:
            o.niter = int(value)
        elif param == Param.IPARAM_noinsert:
            o.noinsert = bool(value)
        elif param == Param.IPARAM_noswap:
            o.noswap = bool(value)
        elif param == Param.IPARAM_nomove:
            o.nomove = bool(value)
        elif param == Param.IPARAM_nosurf:
            o.nosurf = bool(value)
        elif param == Param.IPARAM_optim:
            o.optim = bool(value) or o.optim_les
        elif param == Param.IPARAM_optimLES:
            o.optim_les = bool(value)
            # optim is implied by optimLES but must unlatch when it is
            # cleared (unless IPARAM_optim was set on its own)
            o.optim = o.optim_les or bool(
                self.iparam.get(Param.IPARAM_optim, 0)
            )
        elif param == Param.IPARAM_nofem:
            o.nofem = bool(value)
        elif param == Param.IPARAM_anisosize:
            o.aniso = bool(value)
        elif param == Param.IPARAM_angle:
            if not value:
                o.angle = None
            elif o.angle is None:
                # re-enable detection: restore the last DPARAM value or
                # the 45-degree default (reference PMMG_Set_iparameter
                # toggle semantics)
                from .ops.analysis import ANG_DEFAULT

                last = self.dparam.get(Param.DPARAM_angleDetection)
                o.angle = ANG_DEFAULT if last is None else last
        elif param == Param.IPARAM_nobalancing:
            o.nobalancing = bool(value)
        elif param == Param.IPARAM_ifcLayers:
            o.ifc_layers = int(value)
        elif param == Param.IPARAM_groupsRatio:
            o.grps_ratio = float(value)
        elif param == Param.IPARAM_nparts:
            o.nparts = int(value)
        elif param == Param.IPARAM_APImode:
            self.api_mode = APIDistrib(value)
        elif param == Param.IPARAM_mem:
            # -m: memory budget in MB per shard (zaldy_pmmg.c role)
            o.mem_budget_mb = float(value) if value > 0 else None
        elif param == Param.IPARAM_opnbdy:
            o.opnbdy = bool(value)
        elif param == Param.IPARAM_lag:
            # the reference rejects lagrangian motion up-front
            # (src/libparmmg.c:69-73); same diagnostic here
            if value >= 0:
                raise ValueError(
                    "lagrangian motion (IPARAM_lag) is not implemented"
                )
        elif param == Param.IPARAM_debug:
            # debug mode arms the communicator invariant checks each
            # iteration (the reference's assert-rich debug builds,
            # chkcomm asserts at phase boundaries, src/libparmmg.c:326)
            o.check_comm = bool(value)
        elif param == Param.IPARAM_meshSize:
            # remesher target size: in the shard=device design the
            # closest knob is the pre-split growth floor per shard
            # (PMMG_REMESHER_TARGET_MESH_SIZE role, src/parmmg.h:209)
            if value > 0:
                o.min_shard_elts = int(value)
        elif param in (Param.IPARAM_octree, Param.IPARAM_metisRatio):
            # genuinely obviated: no PROctree in the batched kernels, no
            # Metis graph in the SFC partitioner — warn instead of
            # silently accepting
            import warnings

            warnings.warn(
                f"{param.name} has no effect in the TPU runtime "
                "(obviated: batched kernels use no octree; partitioning "
                "is SFC-based, not Metis)", stacklevel=2,
            )
        elif param == Param.IPARAM_globalNum:
            # numbering is always available lazily via
            # get_vertex_glonum / get_triangle_glonum /
            # get_node_communicator_owners; the flag is call parity
            # only (remembered below for get_iparameter)
            pass
        else:
            # accepted for call-site parity; remembered for
            # get_iparameter
            pass
        self.iparam[param] = int(value)
        return ReturnStatus.SUCCESS

    def get_iparameter(self, param: Param) -> int:
        return self.iparam.get(Param(param), 0)

    def set_dparameter(self, param: Param, value: float):
        param = Param(param)
        o = self.opts
        if param == Param.DPARAM_hmin:
            o.hmin = float(value)
        elif param == Param.DPARAM_hmax:
            o.hmax = float(value)
        elif param == Param.DPARAM_hsiz:
            o.hsiz = float(value)
        elif param == Param.DPARAM_hausd:
            o.hausd = float(value)
        elif param == Param.DPARAM_hgrad:
            o.hgrad = None if value <= 0 else float(value)
        elif param == Param.DPARAM_hgradreq:
            o.hgradreq = None if value <= 0 else float(value)
        elif param == Param.DPARAM_angleDetection:
            o.angle = float(value)
        elif param == Param.DPARAM_balanceBand:
            # <= 0 disables the closed-loop balancer (resolve_balance_band
            # treats non-positive bands as off)
            o.balance_band = float(value)
        self.dparam[param] = float(value)
        return ReturnStatus.SUCCESS

    def get_dparameter(self, param: Param) -> float:
        return self.dparam.get(Param(param), 0.0)

    # --- checkpoint / elastic-resume plumbing -----------------------------
    def set_checkpoint(self, dirpath: Optional[str] = None, *,
                       store=None, every: int = 1, keep: int = 2,
                       async_staging: bool = False):
        """Arm durable checkpoint/resume for the next `parmmglib_*`
        run (the failsafe layer's `checkpoint_dir`/`checkpoint_store`
        options; no `PMMG_Param` analog exists — the reference restarts
        from its per-rank mesh files, RR-9307 §restart). `dirpath`
        selects the POSIX `LocalFSStore`; `store` a
        `io.ckpt_store.CheckpointStore` instance or spec string
        (``mem://bucket``, ``file:///path``) with GCS-style object
        semantics. `async_staging` stages the device→host snapshot to
        a background writer so the adapt loop only blocks on the
        previous epoch's commit. A compatible checkpoint found at entry
        RESUMES the run — including elastically across world sizes
        (see README "Failure handling & checkpointing")."""
        o = self.opts
        o.checkpoint_dir = dirpath
        o.checkpoint_store = store
        o.checkpoint_every = int(every)
        o.checkpoint_keep = int(keep)
        o.checkpoint_async = bool(async_staging)
        return ReturnStatus.SUCCESS

    # --- distributed-API communicator setters -----------------------------
    def set_number_of_node_communicators(self, n: int):
        self._node_comms = [None] * n
        self.api_mode = APIDistrib.NODES
        return ReturnStatus.SUCCESS

    def set_number_of_face_communicators(self, n: int):
        self._face_comms = [None] * n
        self.api_mode = APIDistrib.FACES
        return ReturnStatus.SUCCESS

    def set_ith_node_communicator_size(self, i: int, color: int, size: int):
        self._node_comms[i] = (
            color, np.zeros(size, np.int64), np.zeros(size, np.int64)
        )
        return ReturnStatus.SUCCESS

    def set_ith_node_communicator_nodes(self, i: int, local_ids,
                                        global_ids=None):
        color, loc, glob = self._node_comms[i]
        loc[:] = np.asarray(local_ids)
        if global_ids is not None:
            glob[:] = np.asarray(global_ids)
        return ReturnStatus.SUCCESS

    def set_ith_face_communicator_size(self, i: int, color: int, size: int):
        self._face_comms[i] = (
            color, np.zeros(size, np.int64), np.zeros(size, np.int64)
        )
        return ReturnStatus.SUCCESS

    def set_ith_face_communicator_faces(self, i: int, local_ids,
                                        global_ids=None):
        color, loc, glob = self._face_comms[i]
        loc[:] = np.asarray(local_ids)
        if global_ids is not None:
            glob[:] = np.asarray(global_ids)
        return ReturnStatus.SUCCESS

    def get_ith_node_communicator_nodes(self, i: int):
        return self._node_comms[i]

    # --- build + run ------------------------------------------------------
    def _build_mesh(self) -> Mesh:
        s = self.stage
        if s.verts is None or s.tets is None:
            raise ValueError("vertices and tetrahedra must be set")
        npo = len(s.verts)
        vtags = np.zeros(npo, np.int32)
        vtags[np.asarray(s.corners, int)] |= tags.CORNER | tags.REQUIRED
        vtags[np.asarray(s.req_verts, int)] |= tags.REQUIRED
        trtags = None
        if s.trias is not None:
            trtags = np.zeros(len(s.trias), np.int32)
            trtags[np.asarray(s.req_trias, int)] |= tags.REQUIRED
        edtags = None
        if s.edges is not None:
            edtags = np.zeros(len(s.edges), np.int32)
            edtags[np.asarray(s.req_edges, int)] |= tags.REQUIRED
            edtags[np.asarray(s.ridges, int)] |= tags.RIDGE
        fields = None
        ncomp: Tuple[int, ...] = ()
        if s.fields:
            fields = np.concatenate(s.fields, axis=1)
            ncomp = tuple(f.shape[1] for f in s.fields)
        return Mesh.from_numpy(
            s.verts, s.tets, vrefs=s.vrefs, trefs=s.trefs,
            trias=s.trias, trrefs=s.trrefs,
            edges=s.edges, edrefs=s.edrefs,
            vtags=vtags, trtags=trtags, edtags=edtags,
            met=s.met, ls=s.ls, disp=s.disp,
            fields=fields, field_ncomp=ncomp,
        )

    def load_mesh(self, path: str, metpath: str | None = None):
        """`PMMG_loadMesh_centralized` equivalent."""
        from .io import medit

        m = medit.load_mesh(path, metpath)
        self.mesh = m
        self._loaded = m
        return ReturnStatus.SUCCESS

    def parmmglib_centralized(self) -> ReturnStatus:
        """`PMMG_parmmglib_centralized` (reference
        `src/libparmmg.c:1444`): adapt the staged/loaded mesh; results
        readable via getters / saveable via save_mesh."""
        from .models.adapt import adapt
        from .models.distributed import adapt_distributed, merge_adapted

        mesh = getattr(self, "_loaded", None)
        if mesh is None:
            mesh = self._build_mesh()
        try:
            if self.opts.nparts <= 1:
                aopts = AdaptOptions(**{
                    f.name: getattr(self.opts, f.name)
                    for f in dataclasses.fields(AdaptOptions)
                })
                self.mesh, self.info = adapt(mesh, aopts)
            else:
                self.stacked, self.comm, self.info = adapt_distributed(
                    mesh, self.opts
                )
                self.mesh = merge_adapted(self.stacked, self.comm)
            self.status = ReturnStatus(
                self.info.get("status", ReturnStatus.SUCCESS)
            )
        except Exception as e:  # graded failure: keep last valid mesh
            from . import failsafe

            self.info = dict(error=str(e), error_type=type(e).__name__)
            self.status = failsafe.classify(e, self.mesh is not None)
        return self.status

    def parmmglib_distributed(self) -> ReturnStatus:
        """`PMMG_parmmglib_distributed` (reference `src/libparmmg.c:1519`):
        adapt a mesh given per-shard with interface communicators."""
        from .models.distributed import adapt_stacked_input

        if self.stacked is None:
            raise ValueError(
                "distributed input requires a stacked mesh (use "
                "io.medit distributed load or stage shards)"
            )
        try:
            self.stacked, self.comm, self.info = adapt_stacked_input(
                self.stacked, self.comm, self.opts
            )
            self.status = ReturnStatus(
                self.info.get("status", ReturnStatus.SUCCESS)
            )
        except Exception as e:
            self.info = dict(error=str(e), error_type=type(e).__name__)
            self.status = ReturnStatus.STRONGFAILURE
        return self.status

    # --- getters ----------------------------------------------------------
    def _result_mesh(self) -> Mesh:
        if self.mesh is None:
            raise ValueError("no result mesh; run parmmglib_* first")
        return self.mesh

    def get_vertices(self):
        d = self._result_mesh().to_numpy()
        return d["verts"], d["vrefs"]

    def get_tetrahedra(self):
        d = self._result_mesh().to_numpy()
        return d["tets"], d["trefs"]

    def get_triangles(self):
        d = self._result_mesh().to_numpy()
        return d["trias"], d["trrefs"]

    def get_edges(self):
        d = self._result_mesh().to_numpy()
        return d["edges"], d["edrefs"]

    def get_metric_sols(self):
        return self._result_mesh().to_numpy()["met"]

    def get_vertex_glonum(self):
        """Global vertex numbering of the result
        (`PMMG_Compute_verticesGloNum` role, reference
        `src/libparmmg.c:923`). Distributed result: list of per-shard
        [np] arrays (interface vertices share one id); centralized:
        one contiguous 0..np-1 array (a single-shard run never assigns
        vglob, whose column would read -1)."""
        if self.stacked is not None:
            vglob = np.asarray(self.stacked.vglob)
            vmask = np.asarray(self.stacked.vmask)
            return [vglob[s][vmask[s]] for s in range(vglob.shape[0])]
        d = self._result_mesh().to_numpy()
        return np.arange(len(d["verts"]), dtype=np.int64)

    def get_triangle_glonum(self):
        """Global triangle numbering of the distributed result
        (`PMMG_Compute_trianglesGloNum` role, reference
        `src/libparmmg.c:464`): list of per-shard [nt] arrays over the
        live trias; synthetic interface trias read -1, replicated
        boundary trias share one id."""
        if self.stacked is None:
            d = self._result_mesh().to_numpy()
            return np.arange(len(d["trias"]), dtype=np.int64)
        from .parallel.distribute import assign_triangle_gids

        gids = assign_triangle_gids(self.stacked)
        trmask = np.asarray(self.stacked.trmask)
        return [gids[s][trmask[s]] for s in range(gids.shape[0])]

    def get_node_communicator_owners(self):
        """Per shard: (owner_rank [np], global_id [np], nunique, ntot)
        over that shard's interface vertices — the
        `PMMG_Get_NodeCommunicator_owners` role (reference
        `src/libparmmg.h:2499`). The owner is the lowest shard sharing
        the vertex; nunique counts each interface vertex once globally,
        ntot counts replicas."""
        if self.comm is None:
            raise ValueError("no distributed result; run with nparts > 1")
        l2g = np.asarray(self.comm.l2g)
        owner = np.asarray(self.comm.owner)
        D = l2g.shape[0]
        live = l2g >= 0
        # interface = gid held by MORE THAN ONE shard (l2g covers every
        # live vertex, so multiplicity separates interior from shared)
        gmax = int(l2g.max(initial=0)) + 1
        mult = np.zeros(gmax, np.int64)
        owner_rank = np.full(gmax, 2**30, np.int64)
        for s in range(D):
            g = l2g[s][live[s]]
            np.add.at(mult, g, 1)
            np.minimum.at(owner_rank, g, s)
        ifc = live & (mult[np.maximum(l2g, 0)] > 1)
        ntot = int(ifc.sum())
        nunique = int(owner[ifc].sum())
        return [
            (owner_rank[l2g[s][ifc[s]]], l2g[s][ifc[s]], nunique, ntot)
            for s in range(D)
        ]

    def save_mesh(self, path: str):
        from .io import medit

        medit.save_mesh(self._result_mesh(), path)
        return ReturnStatus.SUCCESS

    def save_met(self, path: str):
        from .io import medit

        medit.save_met(self._result_mesh(), path)
        return ReturnStatus.SUCCESS


def adapt_file(inmesh: str, insol: str, outmesh: str, hsiz: float,
               niter: int, nparts: int) -> int:
    """File-driven one-call adaptation — the target of the C-ABI shim
    (`native/parmmg_capi.c`, the Fortran-surface role of the reference's
    `API_functionsf_pmmg.c`): load -> adapt (centralized or distributed)
    -> save, returning the graded ReturnStatus as an int. `insol` may be
    "" (implied -optim metric); `hsiz` <= 0 means "use the sol metric"."""
    from .io import medit
    from .models.adapt import adapt as _adapt

    try:
        mesh = medit.load_mesh(inmesh, insol or None)
        hs = hsiz if hsiz > 0 else None
        if nparts > 1:
            from .models.distributed import adapt_distributed, merge_adapted

            st, comm, info = adapt_distributed(
                mesh, DistOptions(hsiz=hs, niter=niter, nparts=nparts)
            )
            out = merge_adapted(st, comm)
            status = int(info["status"])
        else:
            out, _info = _adapt(mesh, AdaptOptions(hsiz=hs, niter=niter))
            status = int(_info.get("status", ReturnStatus.SUCCESS))
        medit.save_mesh(out, outmesh)
        return status
    except Exception:
        import traceback

        traceback.print_exc()
        return int(ReturnStatus.STRONGFAILURE)
