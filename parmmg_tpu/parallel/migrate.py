"""Device-side interface displacement and shard-to-shard tet migration.

Re-design of the reference's between-iteration load balancing
(`PMMG_loadBalancing`, `src/loadbalancing_pmmg.c:44`) without the host
merge+re-split of the global mesh:

 - `displace_colors` — the advancing-front interface displacement
   (`PMMG_part_moveInterfaces`, `src/moveinterfaces_pmmg.c:1306`) as
   per-shard front propagation: local face-adjacency advance plus
   cross-shard agreement through the node-communicator tables (the
   reference's `PMMG_mark_interfacePoints`/`PMMG_mark_boulevolp` rounds
   exchange interface-point colors the same way). Pure device code over
   the stacked [D, ...] arrays; under `shard_map` the halo step is one
   `all_to_all` over ICI.
 - `migrate` — the group-transfer role (`PMMG_transfer_all_grps`,
   `src/distributegrps_pmmg.c:1843`; pack at `src/mpipack_pmmg.c:1116`):
   outgoing tets (with their vertex payloads, real-surface trias and
   feature edges, all addressed by GLOBAL vertex ids) are packed into
   fixed-capacity per-destination slots, exchanged with one transpose —
   `jax.lax.all_to_all` under `shard_map`, an axis swap on stacked
   arrays — and integrated on the receiving shard by sort-merge gid
   matching. No byte packing, no MPI datatypes, no tags.
 - `retag_interfaces` — re-derives the interface discipline afterwards:
   PARBDY vertex tags from global gid multiplicity, synthetic NOSURF
   interface trias from cross-shard open-face matching (the
   `PMMG_updateTag`/`PMMG_parbdySet` roles, `src/tag_pmmg.c:267,460`).
   Host-side but CONNECTIVITY-ONLY and O(interface + shared): no
   geometry, metrics or fields ever leave the device — this replaces
   the former merge of the whole mesh onto the host.
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import adjacency, tags
from ..core.mesh import FACE_VERTS, Mesh
from ..failsafe import CapacityError
from ..ops import common
from ..utils.retry import jit_retry
from .distribute import ShardComm, rebuild_comm


# ---------------------------------------------------------------------------
# stacked halo combine (vmap-mode equivalent of parallel.comm.halo_max)
# ---------------------------------------------------------------------------

def stacked_halo_max(vals: jax.Array, comm: ShardComm) -> jax.Array:
    """[D,P] values -> [D,P] with each interface vertex holding the MAX
    over its copies on all shards. On stacked arrays the exchange is a
    pure gather; under shard_map the same access pattern is
    `parallel.comm.halo_max` (one all_to_all)."""
    ci = comm.comm_idx                      # [D(s), D(r), I]
    safe = jnp.maximum(ci, 0)
    d = ci.shape[0]
    # recv[s, r, k] = vals[r, ci[r, s, k]]
    src_rows = jnp.broadcast_to(
        jnp.arange(d, dtype=jnp.int32)[None, :, None], safe.shape
    )
    recv = vals[src_rows, jnp.swapaxes(safe, 0, 1)]
    neutral = (
        jnp.iinfo(vals.dtype).min
        if jnp.issubdtype(vals.dtype, jnp.integer) else -jnp.inf
    )
    recv = jnp.where(jnp.swapaxes(ci, 0, 1) >= 0, recv, neutral)

    def per_shard(v, ci_s, r_s):
        tgt = jnp.where(ci_s >= 0, ci_s, v.shape[0]).reshape(-1)
        return v.at[tgt].max(r_s.reshape(-1), mode="drop")

    return jax.vmap(per_shard)(vals, ci, recv)


# ---------------------------------------------------------------------------
# closed-loop balance policy (host, telemetry-driven)
# ---------------------------------------------------------------------------

# conservative default band: fire only past 1.5x max/mean measured work
# (the reference's PMMG_GRPS_RATIO=2.0 governs ELEMENT counts at group
# granularity; live demand is spikier, so the band sits below the
# grps_ratio escape hatch but far enough from 1.0 not to thrash)
BALANCE_BAND_DEFAULT = 1.5

# PERF_DB to derive the band from when neither the option nor the env
# band is set (the same file the perf gate and SLO admission read)
BALANCE_DB_ENV = "PMMGTPU_PERF_DB"

# history-derived bands are clamped here: never tighter than 1.2 (a
# band hugging 1.0 thrashes on noise) and never looser than the
# GRPS_RATIO-adjacent default's reasoning allows
_BAND_CLAMP = (1.2, 2.0)

# (db path, platform) -> derived band or None; resolve_balance_band is
# called once per iteration, the db only changes between runs
_BAND_CACHE: dict = {}


def _band_from_history() -> Optional[float]:
    """Data-derived work-imbalance band: the rolling-median measured
    ``imbalance`` of the PERF_DB's ``dist-*`` rungs (the same
    :func:`obs.history.quote` API SLO admission uses), held 25% above
    the steady state so the loop fires on drift, not on the imbalance
    the runs historically settle at. None when no PERF_DB is named
    (``PMMGTPU_PERF_DB``) or its dist records carry no imbalance —
    callers fall back to :data:`BALANCE_BAND_DEFAULT`."""
    path = os.environ.get(BALANCE_DB_ENV, "")
    if not path or not os.path.exists(path):
        return None
    try:
        platform = jax.default_backend()
    except Exception:  # backend probe must never break balancing
        platform = "cpu"
    key = (path, platform)
    if key in _BAND_CACHE:
        return _BAND_CACHE[key]
    band: Optional[float] = None
    try:
        from ..obs import history as history_mod
        db = history_mod.load_db(path)
        vals = []
        for rung in sorted({str(r.get("rung", "")) for r in db
                            if str(r.get("rung", "")).startswith("dist-")}):
            q = history_mod.quote(db, platform, rung)
            # quote keys by metric; the imbalance median rides each
            # metric's doc when the rung's records measured it
            for doc in q.values():
                if doc.get("imbalance"):
                    vals.append(float(doc["imbalance"]))
        if vals:
            vals.sort()
            steady = vals[len(vals) // 2]
            if steady > 0:
                band = min(max(1.25 * steady, _BAND_CLAMP[0]),
                           _BAND_CLAMP[1])
    except Exception:  # an unreadable db is a fallback, not a crash
        band = None
    _BAND_CACHE[key] = band
    return band


def resolve_balance_band(opts) -> Optional[float]:
    """Effective work-imbalance band: `opts.balance_band` when set,
    else the PMMGTPU_BALANCE_BAND env contract, else the PERF_DB
    history quote (:func:`_band_from_history`, armed by naming a db in
    ``PMMGTPU_PERF_DB``), else the conservative default. A band <= 0
    (the `-nobalance`-style A/B escape hatch for the policy alone)
    disables the closed loop — interface displacement and the
    GRPS_RATIO guard are untouched either way."""
    band = getattr(opts, "balance_band", None)
    if band is None:
        env = os.environ.get("PMMGTPU_BALANCE_BAND")
        if env:
            band = float(env)
        else:
            band = _band_from_history()
            if band is None:
                band = BALANCE_BAND_DEFAULT
    band = float(band)
    return band if band > 0 else None


def measured_shard_work(history: List[dict], it: int) -> Optional[list]:
    """Per-shard MEASURED work of iteration `it`: sum over the
    iteration's sweep records of active_fraction x live tets per shard
    (`shard_active[i] * shard_ne[i]` — the candidates each shard
    actually offered its operators, not element counts alone). Falls
    back to the last record's raw `shard_ne` when every sweep was
    drained (work 0 everywhere still means the ELEMENT skew is what
    the next iteration will pay to hold in memory/compile). None when
    the iteration left no distributed records."""
    rows = [
        r for r in history
        if r.get("iter") == it and "shard_ne" in r and "failure" not in r
    ]
    if not rows:
        return None
    d = len(rows[-1]["shard_ne"])
    work = [0.0] * d
    for r in rows:
        act = r.get("shard_active") or [1.0] * d
        for i, (a, ne) in enumerate(zip(act, r["shard_ne"])):
            work[i] += float(a) * float(ne)
    if max(work) <= 0.0:
        work = [float(x) for x in rows[-1]["shard_ne"]]
    return work


class BalancePolicy:
    """Band-with-hysteresis controller over the measured work imbalance
    (the closed loop on PR 14's `work/imbalance` telemetry).

    Evaluated once per iteration at the `_one_iteration` balancing
    boundary. Semantics (the unit-test matrix in
    tests/test_m24_balance.py):

      - imbalance < `low_water` re-arms the controller (strikes reset);
      - `low_water` <= imbalance <= `band` holds (hysteresis: a reading
        inside the dead band neither fires nor re-arms, so one noisy
        sample cannot oscillate the trigger);
      - imbalance > `band` fires — unless the last firing was fewer
        than `min_interval` iterations ago (migration itself perturbs
        the next reading; the throttle keeps the loop from chasing its
        own wake). The FIRST firing is ``displace`` (credit the
        standing interface displacement as the corrective action and
        let it work); a repeat firing escalates to ``recut`` — the
        GRPS_RATIO-style full SFC re-cut escape hatch — because a skew
        displacement could not cure within the band needs a fresh cut.

    Host-deterministic by construction: decisions read only the
    replicated history records, so every process computes the same
    action (no collective, no divergence surface)."""

    def __init__(self, band: float, low_water: Optional[float] = None,
                 min_interval: int = 2):
        self.band = float(band)
        # default re-arm threshold: halfway between even and the band
        self.low_water = (
            float(low_water) if low_water is not None
            else 1.0 + 0.5 * (self.band - 1.0)
        )
        self.min_interval = int(min_interval)
        self._last_fire: Optional[int] = None
        self._strikes = 0

    def evaluate(self, history: List[dict], it: int) -> dict:
        """Decision for iteration `it`: dict(imbalance, work, action,
        reason) with action in (None, "displace", "recut")."""
        work = measured_shard_work(history, it)
        if work is None:
            return dict(imbalance=None, work=None, action=None,
                        reason="no-telemetry")
        imb = round(max(work) / max(sum(work) / len(work), 1e-9), 4)
        out = dict(imbalance=imb, work=work, action=None, reason="")
        if imb < self.low_water:
            self._strikes = 0
            out["reason"] = "in-band"
            return out
        if imb <= self.band:
            out["reason"] = "hysteresis-hold"
            return out
        if (
            self._last_fire is not None
            and it - self._last_fire < self.min_interval
        ):
            out["reason"] = "throttled"
            return out
        self._strikes += 1
        self._last_fire = it
        if self._strikes >= 2:
            self._strikes = 0
            out.update(action="recut", reason="band-exceeded-again")
        else:
            out.update(action="displace", reason="band-exceeded")
        return out


# ---------------------------------------------------------------------------
# interface displacement (device)
# ---------------------------------------------------------------------------

def _color_prio(nparts: int, round_id: int) -> jax.Array:
    """Fixed deterministic priority permutation of the colors.

    The driver keeps it CONSTANT across iterations (round_id=0) so
    fronts move monotonically: the reference's bigger-group-wins rule
    (`PMMG_get_ifcDirection`, `src/moveinterfaces_pmmg.c:74-98`)
    oscillates at shard granularity because counts stay noise-level
    equal, re-freezing the same band; the reference tolerates that by
    re-splitting groups with Metis, machinery replaced here by the
    driver's GRPS_RATIO re-cut guard."""
    pr = (
        (np.arange(nparts, dtype=np.int64) * 40503 + round_id * 25173)
        * 2654435761
    ) % (1 << 16)
    return jnp.asarray(pr, jnp.int32)


# parmmg-lint: disable=PML005 -- returns colors only; the caller keeps the stacked mesh
@partial(jax.jit, static_argnames=("nparts", "round_id", "layers",
                                   "min_elts"))
def displace_colors(
    stacked: Mesh,
    comm: ShardComm,
    nparts: int,
    round_id: int = 0,
    layers: int = 2,
    min_elts: int = 8,
) -> jax.Array:
    """[D,T] int32 destination color per tet (own shard id where kept).

    Per layer: every tet face-adjacent — locally via `adja`, across
    shards via an open face whose corners agree through the node-table
    halo — to a higher-priority color adopts it, with the `min_elts`
    starvation floor enforced on GLOBAL color counts (psum'd across
    shards).
    """
    if nparts > 256:
        raise ValueError(
            "displace_colors packs (prio, color) in radix 256; "
            f"nparts={nparts} needs a wider encoding"
        )
    d = stacked.vert.shape[0]
    tcap = stacked.tet.shape[1]
    pcap = stacked.vert.shape[1]
    prio = _color_prio(nparts, round_id)
    tmask = stacked.tmask
    color0 = jnp.where(
        tmask, jnp.arange(d, dtype=jnp.int32)[:, None], -1
    )
    floor_c = jnp.int32(min_elts)

    nb = stacked.adja >> 2
    valid_nb = (stacked.adja >= 0) & tmask[:, :, None]
    par_v = (stacked.vtag & tags.PARBDY) != 0

    def body(_, color):
        # encode (prio, color) so one max carries both
        enc_t = jnp.where(
            color >= 0, prio[jnp.maximum(color, 0)] * 256 + color, -1
        )
        # local face-adjacency best
        nb_enc = jnp.where(
            valid_nb,
            jax.vmap(lambda e, n: e[n])(enc_t, jnp.maximum(nb, 0)),
            -1,
        )
        best_local = jnp.max(nb_enc, axis=2)            # [D,T]
        # cross-shard: interface vertices carry the max enc of their
        # incident tets, agreed through the halo
        venc = jnp.full((d, pcap), -1, jnp.int32)

        def scatter_venc(ve, tet_s, enc_s, tm_s):
            idx = jnp.where(tm_s[:, None], tet_s, pcap)
            return ve.at[idx.reshape(-1)].max(
                jnp.repeat(enc_s, 4), mode="drop"
            )

        venc = jax.vmap(scatter_venc)(venc, stacked.tet, enc_t, tmask)
        venc = jnp.where(par_v, venc, -1)
        venc = stacked_halo_max(venc, comm)
        venc = jnp.where(par_v, venc, -1)
        # cross-shard advance is FACE-based like the reference front: a
        # tet adopts a neighbor-shard color only through one of its OPEN
        # faces whose three corners agree on the same higher color (the
        # vertex-ball hop would also flip diagonal tets and advance ~2x
        # the per-layer front)
        best_ifc = jnp.full(enc_t.shape, -1, jnp.int32)
        fv4 = jnp.asarray(FACE_VERTS)                      # [4,3]
        for f in range(4):
            fverts = stacked.tet[:, :, fv4[f]]             # [D,T,3]
            ve = jax.vmap(lambda vv, t: vv[t])(venc, fverts)
            open_f = (stacked.adja[:, :, f] < 0) & tmask
            all_pos = jnp.all(ve >= 0, axis=2)
            col = jnp.where(ve >= 0, ve % 256, -1)
            same_col = (
                (col[..., 0] == col[..., 1])
                & (col[..., 1] == col[..., 2])
            )
            fenc = jnp.min(ve, axis=2)
            ok = open_f & all_pos & same_col
            best_ifc = jnp.maximum(
                best_ifc, jnp.where(ok, fenc, -1)
            )
        best = jnp.maximum(best_local, best_ifc)
        own_enc = jnp.where(
            color >= 0, prio[jnp.maximum(color, 0)] * 256 + color, -1
        )
        bestcol = best % 256
        flip = tmask & (best > own_enc) & (best >= 0)
        # starvation floor on GLOBAL counts (the reference's nemin,
        # src/moveinterfaces_pmmg.c:1343)
        safe_c = jnp.where(tmask, jnp.maximum(color, 0), 0)
        counts = jnp.zeros((d, nparts), jnp.int32)
        counts = jax.vmap(
            lambda c, sc, tm: c.at[sc].add(
                tm.astype(jnp.int32), mode="drop")
        )(counts, safe_c, tmask)
        g_counts = jnp.sum(counts, axis=0)              # psum role
        losses = jnp.zeros((d, nparts), jnp.int32)
        losses = jax.vmap(
            lambda c, sc, fl: c.at[sc].add(fl.astype(jnp.int32),
                                           mode="drop")
        )(losses, safe_c, flip)
        g_losses = jnp.sum(losses, axis=0)
        starved = (g_counts - g_losses) < floor_c
        flip = flip & ~starved[safe_c]
        return jnp.where(flip, bestcol, color)

    return jax.lax.fori_loop(0, layers, body, color0)


def fix_contiguity(
    stacked: Mesh, color: jax.Array, nparts: int, rounds: int = 2
):
    """Reattach stranded color components after front displacement — the
    `PMMG_fix_contiguity` / `PMMG_check_reachability` role (reference
    `src/moveinterfaces_pmmg.c:475-700`): the advancing front can pinch
    off an island destined for a shard it no longer touches; left alone
    the island stays frozen interface forever (its faces never become
    interior). Connected components of the same-color tet graph
    (within-shard face adjacency + cross-shard gid-matched open faces)
    are labeled by pointer-jumping min-label propagation; each color
    keeps its heaviest component and every other component is reassigned
    to its majority adjacent color. Host-side but connectivity-only
    (int arrays) and fully vectorized, like `retag_interfaces`.

    Takes/returns the [D,T] color array of `displace_colors`.
    """
    col = np.asarray(jax.device_get(color)).copy()
    adja = np.asarray(jax.device_get(stacked.adja))
    tmask = np.asarray(jax.device_get(stacked.tmask))
    tet = np.asarray(jax.device_get(stacked.tet))
    vglob = np.asarray(jax.device_get(stacked.vglob))
    S, TC = col.shape
    N = S * TC
    live = tmask.reshape(-1)
    colf = col.reshape(-1)

    # --- adjacency pairs: within-shard faces --------------------------
    nb = adja >> 2
    valid = (adja >= 0) & tmask[:, :, None]
    t_id = np.broadcast_to(np.arange(TC)[None, :, None], nb.shape)
    base = (np.arange(S) * TC)[:, None, None]
    base = np.broadcast_to(base, nb.shape)
    a_in = (base + t_id)[valid]
    b_in = (base + np.where(valid, nb, 0))[valid]
    once = a_in < b_in
    pairs_a = [a_in[once]]
    pairs_b = [b_in[once]]

    # --- cross-shard: open faces matched by sorted gid triples --------
    open_f = (adja < 0) & tmask[:, :, None]
    s_i, t_i, f_i = np.nonzero(open_f)
    if len(s_i):
        fv = np.asarray(FACE_VERTS)
        corners = tet[s_i, t_i][np.arange(len(t_i))[:, None], fv[f_i]]
        g3 = np.sort(vglob[s_i[:, None], corners], axis=1).astype(np.int64)
        node = s_i.astype(np.int64) * TC + t_i
        order = np.lexsort((g3[:, 2], g3[:, 1], g3[:, 0]))
        g3s, nodes = g3[order], node[order]
        samekey = np.all(g3s[1:] == g3s[:-1], axis=1)
        # matched interface faces come in pairs; gid>=0 guards unset ids
        ok = samekey & np.all(g3s[1:] >= 0, axis=1)
        pairs_a.append(nodes[:-1][ok])
        pairs_b.append(nodes[1:][ok])
    A = np.concatenate(pairs_a)
    B = np.concatenate(pairs_b)

    for _ in range(rounds):
        same = (colf[A] == colf[B]) & (colf[A] >= 0)
        a, b = A[same], B[same]

        # min-label propagation with pointer jumping (converges in
        # O(log N) rounds on mesh-like graphs)
        lab = np.arange(N, dtype=np.int64)
        for _ in range(64):
            l2 = lab.copy()
            np.minimum.at(l2, a, lab[b])
            np.minimum.at(l2, b, lab[a])
            l2 = np.minimum(l2, l2[l2])
            l2 = np.minimum(l2, l2[l2])
            if (l2 == lab).all():
                break
            lab = l2
        while True:
            l2 = lab[lab]
            if (l2 == lab).all():
                break
            lab = l2

        sel = live & (colf >= 0)
        roots, inv, cnts = np.unique(
            lab[sel], return_inverse=True, return_counts=True
        )
        if not len(roots):
            break
        root_col = np.zeros(len(roots), np.int64)
        root_col[inv] = colf[sel]  # every member shares the color
        # heaviest component per color survives
        byc = np.lexsort((cnts, root_col))
        last = np.concatenate(
            [root_col[byc][1:] != root_col[byc][:-1], [True]]
        )
        main_roots = roots[byc[last]]
        stranded_root = np.ones(len(roots), bool)
        stranded_root[np.searchsorted(roots, main_roots)] = False
        if not stranded_root.any():
            break

        # majority adjacent color per stranded component, over the
        # color-crossing adjacency edges
        diff = (colf[A] != colf[B]) & (colf[A] >= 0) & (colf[B] >= 0)
        ca = np.concatenate([A[diff], B[diff]])
        cb = np.concatenate([B[diff], A[diff]])
        ra = lab[ca]
        ri = np.searchsorted(roots, ra)
        inb = (ri < len(roots)) & (roots[np.minimum(ri, len(roots) - 1)]
                                   == ra)
        strand_e = inb & stranded_root[np.minimum(ri, len(roots) - 1)]
        if not strand_e.any():
            break
        er, ec = ra[strand_e], colf[cb[strand_e]]
        key = er * np.int64(nparts) + ec
        uk, kcnt = np.unique(key, return_counts=True)
        kr = uk // nparts
        byr = np.lexsort((kcnt, kr))
        lastr = np.concatenate([kr[byr][1:] != kr[byr][:-1], [True]])
        win_root, win_col = kr[byr[lastr]], (uk % nparts)[byr[lastr]]
        dest = np.full(N, -1, np.int64)
        dest[win_root] = win_col
        node_sel = sel & (dest[lab] >= 0)
        # only stranded members move (main components are not in dest)
        colf[node_sel] = dest[lab[node_sel]]

    return jnp.asarray(colf.reshape(S, TC).astype(np.int32))


# ---------------------------------------------------------------------------
# migration (pack -> exchange -> integrate), device
# ---------------------------------------------------------------------------

def migration_counts(stacked: Mesh, color: jax.Array, nparts: int):
    """[D,D] int32 outgoing tet counts (host uses the max to pick the
    static slot capacity)."""
    d = stacked.vert.shape[0]
    sid = jnp.arange(d, dtype=jnp.int32)[:, None]
    out = stacked.tmask & (color >= 0) & (color != sid)
    safe = jnp.where(out, color, 0)
    cnt = jnp.zeros((d, nparts), jnp.int32)
    return jax.vmap(
        lambda c, sc, o: c.at[sc].add(o.astype(jnp.int32), mode="drop")
    )(cnt, safe, out)


# parmmg-lint: disable=PML005 -- caller still reads `stacked` when integrating the received buffers
@partial(jax.jit, static_argnames=("slot_cap", "tria_cap", "edge_cap"))
def _pack(stacked: Mesh, color: jax.Array, slot_cap: int,
          tria_cap: int, edge_cap: int):
    """Build per-destination slot buffers. Returns dict of [D,D,cap,W]
    arrays (int payloads) + float payloads [D,D,cap,4,Wf]."""
    d = stacked.vert.shape[0]
    tcap = stacked.tet.shape[1]
    fcap = stacked.tria.shape[1]
    ecap = stacked.edge.shape[1]
    sid = jnp.arange(d, dtype=jnp.int32)[:, None]
    out_t = stacked.tmask & (color >= 0) & (color != sid)   # [D,T]

    def pack_shard(m: Mesh, out_s, color_s):
        # --- tets ---------------------------------------------------------
        gids4 = m.vglob[m.tet]                              # [T,4]
        ti = jnp.concatenate(
            [
                gids4,
                m.tref[:, None],
                m.vtag[m.tet],
                m.vref[m.tet],
            ],
            axis=1,
        ).astype(jnp.int32)                  # [T,13]
        fpay = jnp.concatenate(
            [m.vert, m.met, m.ls, m.disp, m.fields], axis=1
        )                                                    # [P,Wf]
        tf = fpay[m.tet]                                     # [T,4,Wf]
        buf_ti = jnp.full((d, slot_cap, 13), -1, jnp.int32)
        buf_tf = jnp.zeros((d, slot_cap, 4, tf.shape[-1]), m.vert.dtype)
        n_t = jnp.zeros(d, jnp.int32)
        # rank within destination: cumsum over tets of (out & color==dest)
        # one pass per destination (D is small and static)
        for dst in range(d):
            sel = out_s & (color_s == dst)
            n_t = n_t.at[dst].set(jnp.sum(sel, dtype=jnp.int32))
            rank = jnp.cumsum(sel.astype(jnp.int32)) - 1
            tgt = common.unique_oob(sel, rank, slot_cap)
            buf_ti = buf_ti.at[dst].set(
                common.scatter_rows(buf_ti[dst], tgt, ti, unique=True)
            )
            buf_tf = buf_tf.at[dst].set(
                buf_tf[dst].at[tgt].set(tf, mode="drop",
                                        unique_indices=True)
            )
        # --- real trias owned by moving tets ------------------------------
        # owner tets by face match; pure synthetic interface trias are
        # dropped globally and re-derived by retag_interfaces
        fverts = m.tet[:, jnp.asarray(FACE_VERTS)].reshape(-1, 3)
        fkeys = jnp.sort(fverts, axis=1)
        fkeys = jnp.where(
            jnp.repeat(m.tmask, 4)[:, None], fkeys, -1
        )
        syn = tags.pure_interface_tria(m.trtag)
        real_tr = m.trmask & ~syn
        trkeys = jnp.sort(jnp.where(real_tr[:, None], m.tria, -1), axis=1)
        fid1, fid2, cnt = common.match_rows2(fkeys, trkeys,
                                             bound=m.pcap)
        own1 = jnp.maximum(fid1, 0) // 4
        own2 = jnp.maximum(fid2, 0) // 4
        tria_int = jnp.concatenate(
            [
                m.vglob[m.tria],
                m.trref[:, None],
                # strip only the interface-position bits; NOSURF stays
                # with the REQUIRED it marks as split-added, so merge
                # can still strip the pair (reference MG_NOSURF role)
                (m.trtag & ~(tags.PARBDY | tags.PARBDYBDY))[:, None],
            ],
            axis=1,
        ).astype(jnp.int32)                  # [F,5]
        buf_fi = jnp.full((d, tria_cap, 5), -1, jnp.int32)
        n_f = jnp.zeros(d, jnp.int32)
        for dst in range(d):
            d1 = (cnt >= 1) & out_s[own1] & (color_s[own1] == dst)
            d2 = (cnt >= 2) & out_s[own2] & (color_s[own2] == dst)
            sel = real_tr & (d1 | d2)
            n_f = n_f.at[dst].set(jnp.sum(sel, dtype=jnp.int32))
            rank = jnp.cumsum(sel.astype(jnp.int32)) - 1
            tgt = common.unique_oob(sel, rank, tria_cap)
            buf_fi = buf_fi.at[dst].set(
                common.scatter_rows(buf_fi[dst], tgt, tria_int,
                                    unique=True)
            )
        # tria stays locally iff some owner stays. Pure synthetic
        # interface trias are dropped HERE, not in retag: keeping them
        # through compact() would keep their vertices alive in the
        # departed shard, and every such stale replica reads as a shared
        # gid — freezing the genuine copy on the receiving side too.
        # retag_interfaces recreates exactly the ones still needed.
        keep1 = (cnt >= 1) & ~out_s[own1]
        keep2 = (cnt >= 2) & ~out_s[own2]
        tria_keep = m.trmask & ~syn & (
            keep1 | keep2 | (cnt == 0)
        )
        # --- feature edges ------------------------------------------------
        ed_int = jnp.concatenate(
            [m.vglob[m.edge], m.edref[:, None], m.edtag[:, None]], axis=1
        ).astype(jnp.int32)                  # [E,4]
        buf_ei = jnp.full((d, edge_cap, 4), -1, jnp.int32)
        n_e = jnp.zeros(d, jnp.int32)
        pcap = m.pcap
        for dst in range(d):
            vd = jnp.zeros(pcap, bool)
            selt = out_s & (color_s == dst)
            idx = jnp.where(selt[:, None], m.tet, pcap)
            vd = vd.at[idx.reshape(-1)].set(True, mode="drop")
            sel = m.edmask & vd[m.edge[:, 0]] & vd[m.edge[:, 1]]
            n_e = n_e.at[dst].set(jnp.sum(sel, dtype=jnp.int32))
            rank = jnp.cumsum(sel.astype(jnp.int32)) - 1
            tgt = common.unique_oob(sel, rank, edge_cap)
            buf_ei = buf_ei.at[dst].set(
                common.scatter_rows(buf_ei[dst], tgt, ed_int,
                                    unique=True)
            )
        # edges stay only where both endpoints still belong to a STAYING
        # tet — otherwise the departed region's feature web would remain
        # as frozen orphans (its REQUIRED/ridge endpoints survive
        # compact(), then read as spuriously shared gids)
        stay_v = jnp.zeros(pcap, bool)
        sidx = jnp.where((m.tmask & ~out_s)[:, None], m.tet, pcap)
        stay_v = stay_v.at[sidx.reshape(-1)].set(True, mode="drop")
        edge_keep = (
            m.edmask & stay_v[m.edge[:, 0]] & stay_v[m.edge[:, 1]]
        )
        return (buf_ti, buf_tf, buf_fi, buf_ei, tria_keep, edge_keep,
                jnp.stack([n_t, n_f, n_e]))

    return jax.vmap(pack_shard)(stacked, out_t, color), out_t


def _exchange(buf: jax.Array) -> jax.Array:
    """Stacked-mode exchange: [D_src, D_dst, ...] -> [D_dst, D_src, ...].
    Under shard_map the identical data motion is
    `jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0)`."""
    return jnp.swapaxes(buf, 0, 1)


# parmmg-lint: disable=PML005 -- deliberate (see NB below): capacity-miss fallback reuses the arrays
@jax.jit
def _integrate(stacked: Mesh, out_t, rti, rtf, rfi, rei, tria_keep,
               edge_keep):
    # NB: deliberately NOT donating `stacked` — on a capacity-estimate
    # miss the caller falls back to the host re-cut with the same arrays
    """Receive-side merge: dedup vertices by gid, append new entities,
    drop outgoing ones. All sort-merge device code, vmapped over shards."""

    def per_shard(m: Mesh, out_s, ti, tf, fi, ei, tr_keep, ed_keep):
        pcap, tcap, fcap, ecap = m.pcap, m.tcap, m.fcap, m.ecap
        ti = ti.reshape(-1, ti.shape[-1])                   # [K,13]
        tf = tf.reshape(-1, 4, tf.shape[-1])                # [K,4,Wf]
        fi = fi.reshape(-1, fi.shape[-1])                   # [Kf,5]
        ei = ei.reshape(-1, ei.shape[-1])                   # [Ke,4]
        k = ti.shape[0]
        t_valid = ti[:, 0] >= 0

        # ---- vertices: dedup corners by gid, match against local -------
        cg = jnp.where(t_valid[:, None], ti[:, :4], -1).reshape(-1)  # [4K]
        ckey = jnp.where(cg >= 0, cg, jnp.int32(2**30))
        order = jnp.argsort(ckey).astype(jnp.int32)
        sg = ckey[order]
        newg = jnp.concatenate([jnp.ones(1, bool), sg[1:] != sg[:-1]])
        live_s = sg < jnp.int32(2**30)
        uid = jnp.cumsum(newg.astype(jnp.int32)) - 1        # group id
        rep_sorted = newg & live_s
        # match unique incoming gids against local live gids
        lkeys = jnp.where(m.vmask, m.vglob, -1)[:, None]
        q = jnp.where(rep_sorted, sg, -1)[:, None]
        loc = common.match_rows(lkeys, q)                   # [4K] or -1
        isnew_rep = rep_sorted & (loc < 0)
        nrank = jnp.cumsum(isnew_rep.astype(jnp.int32)) - 1
        # int32-pinned live counts: npoin/ntet/... reduce to int64
        # under x64 and would widen every slot scatter below
        np0 = jnp.asarray(m.npoin, jnp.int32)
        slot_rep = jnp.where(isnew_rep, np0 + nrank, loc)   # [4K] sorted
        # per-group slot, then back to original corner order
        gslot = jnp.full(4 * k, -1, jnp.int32).at[
            jnp.where(rep_sorted, uid, 4 * k)
        ].max(slot_rep, mode="drop")
        slot_sorted = gslot[uid]
        corner_slot = jnp.full(4 * k, -1, jnp.int32).at[order].set(
            slot_sorted, unique_indices=True
        )                                                   # [4K]
        # write payloads of NEW vertices (one writer: the representative)
        wnew = jnp.zeros(4 * k, bool).at[order].set(
            isnew_rep, unique_indices=True
        )
        tgt_v = common.unique_oob(wnew, corner_slot, pcap)
        vtag_in = ti[:, 5:9].reshape(-1)
        vref_in = ti[:, 9:13].reshape(-1)
        gid_in = ti[:, :4].reshape(-1)
        fpay = tf.reshape(-1, tf.shape[-1])                 # [4K,Wf]
        mcomp = m.met.shape[1]
        lc = m.ls.shape[1]
        dc = m.disp.shape[1]
        vert = common.scatter_rows(m.vert, tgt_v, fpay[:, :3], unique=True)
        met = common.scatter_rows(m.met, tgt_v, fpay[:, 3:3 + mcomp],
                                  unique=True)
        ls = common.scatter_rows(m.ls, tgt_v, fpay[:, 3 + mcomp:3 + mcomp + lc],
                                 unique=True)
        disp = common.scatter_rows(
            m.disp, tgt_v, fpay[:, 3 + mcomp + lc:3 + mcomp + lc + dc],
            unique=True,
        )
        fields = common.scatter_rows(
            m.fields, tgt_v, fpay[:, 3 + mcomp + lc + dc:], unique=True
        )
        kwu = dict(mode="drop", unique_indices=True)
        vtag = m.vtag.at[tgt_v].set(vtag_in, **kwu)
        vref = m.vref.at[tgt_v].set(vref_in, **kwu)
        vglob = m.vglob.at[tgt_v].set(gid_in, **kwu)
        vmask = m.vmask.at[tgt_v].set(True, **kwu)

        # ---- tets ------------------------------------------------------
        cs4 = corner_slot.reshape(k, 4)
        ne0 = jnp.asarray(m.ntet, jnp.int32)
        trank = jnp.cumsum(t_valid.astype(jnp.int32)) - 1
        tgt_t = common.unique_oob(t_valid, ne0 + trank, tcap)
        tet = common.scatter_rows(m.tet, tgt_t, cs4, unique=True)
        tref = m.tref.at[tgt_t].set(ti[:, 4], **kwu)
        tmask = (m.tmask & ~out_s).at[tgt_t].set(t_valid, **kwu)

        # ---- trias: dedup against local by gid triple ------------------
        f_valid = fi[:, 0] >= 0
        # local keys in gid space (kept real trias only)
        ltr = jnp.sort(
            jnp.where(tr_keep[:, None], m.vglob[m.tria], -1), axis=1
        )
        qtr = jnp.sort(jnp.where(f_valid[:, None], fi[:, :3], -1), axis=1)
        dup_loc = common.sorted_membership(ltr, qtr)
        # dedup among incoming (first occurrence wins)
        ord_f = jnp.lexsort((qtr[:, 2], qtr[:, 1], qtr[:, 0])).astype(
            jnp.int32
        )
        sq = qtr[ord_f]
        firstf = jnp.concatenate(
            [jnp.ones(1, bool), jnp.any(sq[1:] != sq[:-1], axis=1)]
        ) & (sq[:, 0] >= 0)
        f_first = jnp.zeros(fi.shape[0], bool).at[ord_f].set(
            firstf, unique_indices=True
        )
        f_add = f_valid & f_first & ~dup_loc
        # map gids -> local slots (all corners were sent with some tet)
        fslot = common.match_rows(
            jnp.where(vmask, vglob, -1)[:, None],
            jnp.where(f_add[:, None], fi[:, :3], -1).reshape(-1, 1),
        ).reshape(-1, 3)
        f_add = f_add & jnp.all(fslot >= 0, axis=1)
        # kept trias stay in place (mask only); appends go after the
        # pre-migration live prefix — compact() later repacks
        frank = jnp.cumsum(f_add.astype(jnp.int32)) - 1
        free0 = jnp.asarray(m.ntria, jnp.int32)  # append after live prefix
        tgt_f = common.unique_oob(f_add, free0 + frank, fcap)
        tria = common.scatter_rows(m.tria, tgt_f, fslot, unique=True)
        trref = m.trref.at[tgt_f].set(fi[:, 3], **kwu)
        trtag = m.trtag.at[tgt_f].set(fi[:, 4], **kwu)
        trmask = tr_keep.at[tgt_f].set(f_add, **kwu)

        # ---- feature edges: dedup by gid pair --------------------------
        e_valid = ei[:, 0] >= 0
        led = jnp.sort(
            jnp.where(ed_keep[:, None], m.vglob[m.edge], -1), axis=1
        )
        qed = jnp.sort(jnp.where(e_valid[:, None], ei[:, :2], -1), axis=1)
        dup_le = common.sorted_membership(led, qed)
        ord_e = jnp.lexsort((qed[:, 1], qed[:, 0])).astype(jnp.int32)
        se = qed[ord_e]
        firste = jnp.concatenate(
            [jnp.ones(1, bool), jnp.any(se[1:] != se[:-1], axis=1)]
        ) & (se[:, 0] >= 0)
        e_first = jnp.zeros(ei.shape[0], bool).at[ord_e].set(
            firste, unique_indices=True
        )
        e_add = e_valid & e_first & ~dup_le
        eslot = common.match_rows(
            jnp.where(vmask, vglob, -1)[:, None],
            jnp.where(e_add[:, None], ei[:, :2], -1).reshape(-1, 1),
        ).reshape(-1, 2)
        e_add = e_add & jnp.all(eslot >= 0, axis=1)
        erank = jnp.cumsum(e_add.astype(jnp.int32)) - 1
        tgt_e = common.unique_oob(
            e_add, jnp.asarray(m.nedge, jnp.int32) + erank, ecap
        )
        edge = common.scatter_rows(m.edge, tgt_e, eslot, unique=True)
        edref = m.edref.at[tgt_e].set(ei[:, 2], **kwu)
        edtag = m.edtag.at[tgt_e].set(ei[:, 3], **kwu)
        edmask = ed_keep.at[tgt_e].set(e_add, **kwu)

        # capacity overflow flags: appended entities beyond the caps are
        # DROPPED by the scatters above, so the caller must be told
        overflow = jnp.stack([
            np0 + jnp.sum(wnew.astype(jnp.int32)) - pcap,
            ne0 + jnp.sum(t_valid.astype(jnp.int32)) - tcap,
            free0 + jnp.sum(f_add.astype(jnp.int32)) - fcap,
            jnp.asarray(m.nedge, jnp.int32)
            + jnp.sum(e_add.astype(jnp.int32)) - ecap,
        ])
        return m.replace(
            vert=vert, met=met, ls=ls, disp=disp, fields=fields,
            vtag=vtag, vref=vref, vglob=vglob, vmask=vmask,
            tet=tet, tref=tref, tmask=tmask,
            tria=tria, trref=trref, trtag=trtag, trmask=trmask,
            edge=edge, edref=edref, edtag=edtag, edmask=edmask,
        ), overflow

    return jax.vmap(per_shard)(stacked, out_t, rti, rtf, rfi, rei,
                               tria_keep, edge_keep)


def migrate(stacked: Mesh, color: jax.Array, nparts: int,
            slot_cap: int) -> Mesh:
    """Move tets to their `color` shard via the fixed-slot exchange.
    `slot_cap` must be >= max outgoing count per (src,dst) pair — the
    host picks it from `migration_counts`. Capacities must have headroom
    for the incoming entities (host responsibility, like every other
    growth decision)."""
    tria_cap = slot_cap + 8
    edge_cap = max(slot_cap // 2, 64)
    # cost doc for the exchange's pack program (the bandwidth-dominant
    # leg of the migration — the integrate side is a vmapped scatter of
    # the same payload), under the migrate_exchange device-span name
    from ..obs import costs as obs_costs
    from ..obs import trace as obs_trace

    obs_costs.capture(
        "migrate_exchange", _pack, (stacked, color),
        dict(slot_cap=slot_cap, tria_cap=tria_cap, edge_cap=edge_cap),
    )
    tr = obs_trace.get_tracer()
    with tr.span("migrate:pack", slot_cap=slot_cap):
        (bti, btf, bfi, bei, tria_keep, edge_keep, pack_n), out_t = \
            jit_retry(_pack, stacked, color, slot_cap, tria_cap,
                      edge_cap)
    # pack-side overflow check: a slot cap that undershoots would DROP
    # outgoing entities (their source copies are already released), so
    # verify the true per-destination counts before anything is applied.
    # The typed CapacityError carries the counts/caps the grow-and-retry
    # loop in the distributed driver needs to size the retry exactly.
    pn = np.asarray(jax.device_get(pack_n))      # [D, 3(kind), D(dst)]
    caps = np.asarray([slot_cap, tria_cap, edge_cap])
    if (pn > caps[None, :, None]).any():
        raise CapacityError(
            "migration slot capacities too small (per-source max "
            f"[tets,trias,edges]: {pn.max(axis=(0, 2)).tolist()} vs caps "
            f"{caps.tolist()}) — raise slot_cap",
            counts=pn, caps=caps,
        )
    # the transfer leg proper: the (src,dst)-slot buffers swap owners
    # here — obs.dist reads this sub-span (inside the world-matched
    # migrate_exchange device-span) as the TRUE transfer time, vs the
    # straggler lag it measures from the enclosing span's entries
    with tr.span("migrate:xchg"):
        rti, rtf, rfi, rei = (
            _exchange(bti), _exchange(btf), _exchange(bfi),
            _exchange(bei)
        )
    with tr.span("migrate:integrate"):
        out, overflow = jit_retry(_integrate, stacked, out_t, rti, rtf,
                                  rfi, rei, tria_keep, edge_keep)
    over = np.asarray(jax.device_get(overflow))
    if (over > 0).any():
        raise CapacityError(
            "migration overflowed shard capacities "
            f"(excess per shard [verts,tets,trias,edges]: {over.tolist()})"
            " — grow the stacked mesh before migrating",
            overflow=over,
        )
    return out


# ---------------------------------------------------------------------------
# interface re-tagging (host, connectivity-only)
# ---------------------------------------------------------------------------

_IFC_TAG = tags.PARBDY | tags.REQUIRED | tags.NOSURF | tags.BDY


# parmmg-lint: disable=PML005 -- the host merges results back into the SAME stacked mesh
@partial(jax.jit, static_argnames=("fcapq",))
def _retag_device_core(stacked: Mesh, fcapq: int):
    """Device-resident interface retagging (the PMMG_updateTag role,
    reference `src/tag_pmmg.c:267`, plus the interface-face derivation
    of `PMMG_setdhd`-style exchanges, `src/analys_pmmg.c:2001`):

      1. PARBDY vertex bits from GLOBAL gid multiplicity — one
         scatter-add histogram over the gid space, no host bincount;
      2. each shard's open faces (compacted to `fcapq` rows) keyed by
         sorted gid triples, their cross-shard multiplicity from ONE
         lexsort + segmented count over all shards' rows — the
         device sort-merge replacing the host np.unique;
      3. per-shard (vmapped) synthetic-tria bookkeeping: stale drop,
         interface-bit refresh, missing-tria append into free slots;
      4. PARBDYBDY vertex bits.

    Returns the updated arrays plus per-shard diagnostics
    (n_open, n_missing, n_free) — the host only checks the three
    scalars-per-shard for capacity overflow (and retries with a larger
    `fcapq` or raises), so nothing mesh-sized crosses to the host:
    the round-4 verdict's ask (device-resident exchanges, host touches
    O(interface) reductions only)."""
    D, PC = stacked.vglob.shape
    TC = stacked.tet.shape[1]
    FC = stacked.tria.shape[1]
    vglob = stacked.vglob.astype(jnp.int32)
    vmask = stacked.vmask
    tmask = stacked.tmask
    G = D * PC  # exclusive gid bound (gids index live global vertices)

    adja = jax.vmap(adjacency.build_adjacency)(stacked).adja

    # --- 1. PARBDY from gid multiplicity ------------------------------
    gidx = jnp.where(vmask, vglob, G)
    mult = jnp.zeros(G, jnp.int32).at[gidx.reshape(-1)].add(
        1, mode="drop"
    )
    shared = vmask & (mult[jnp.clip(vglob, 0, G - 1)] > 1)
    vtag = jnp.where(
        shared, stacked.vtag | tags.PARBDY,
        stacked.vtag & ~(tags.PARBDY | tags.PARBDYBDY),
    )

    # --- 2. open faces -> cross-shard interface faces -----------------
    fv = jnp.asarray(FACE_VERTS)
    corners = stacked.tet[:, :, fv]                      # [D,TC,4,3]
    vg = jax.vmap(lambda g, c: g[c])(vglob, corners)
    g3 = jnp.sort(vg, axis=-1).reshape(D, 4 * TC, 3)
    openf = ((adja < 0) & tmask[:, :, None]).reshape(D, 4 * TC)
    n_open = jnp.sum(openf, axis=1)
    # compact to fcapq rows, preserving enumeration order (stable sort)
    pick = jax.vmap(
        lambda o: jnp.argsort(
            jnp.where(o, jnp.arange(4 * TC, dtype=jnp.int32), 4 * TC)
        )
    )(openf)[:, :fcapq].astype(jnp.int32)
    pvalid = jnp.take_along_axis(openf, pick, axis=1)
    prow = jax.vmap(lambda r, p: r[p])(g3, pick)         # [D,fcapq,3]
    prow = jnp.where(pvalid[..., None], prow, -1)

    allr = prow.reshape(D * fcapq, 3)
    invalid = jnp.any(allr < 0, axis=1)
    order, newgrp = common._row_order_groups(allr, invalid, None)
    cnt_sorted = common.seg_broadcast(
        (~invalid[order]).astype(jnp.int32), newgrp, jnp.add, 0
    )
    cnt = jnp.zeros(D * fcapq, jnp.int32).at[order].set(
        cnt_sorted, unique_indices=True
    )
    is_ifc = ((~invalid) & (cnt > 1)).reshape(D, fcapq)

    # within-shard duplicate face rows (pathological pinch): only the
    # first copy may materialize a synthetic tria (np.unique role)
    def shard_first(rows):
        idx = common.match_rows(rows, rows)
        return idx == jnp.arange(fcapq, dtype=jnp.int32)

    first = jax.vmap(shard_first)(prow) & is_ifc

    # --- 3. per-shard synthetic-tria bookkeeping (vmapped) ------------
    def shard_tria(vglob_s, vmask_s, tria_s, trtag_s, trref_s, trmask_s,
                   prow_s, ifc_s, first_s):
        t_rows = jnp.where(
            trmask_s[:, None], jnp.sort(vglob_s[tria_s], axis=1), -1
        )
        keys = jnp.where(ifc_s[:, None], prow_s, -1)
        member = common.sorted_membership(keys, t_rows)
        syn = tags.pure_interface_tria(trtag_s) & trmask_s
        trmask2 = trmask_s & ~(syn & ~member)            # stale drop
        real = trmask2 & ~syn
        at_ifc = real & member
        tt = jnp.where(
            at_ifc,
            trtag_s | (tags.PARBDY | tags.PARBDYBDY | tags.BDY),
            trtag_s,
        )
        fresh_noreq = at_ifc & ((trtag_s & tags.REQUIRED) == 0)
        tt = jnp.where(
            fresh_noreq, tt | (tags.REQUIRED | tags.NOSURF), tt
        )
        clear = real & ~member & ((trtag_s & tags.PARBDYBDY) != 0)
        tt = jnp.where(
            clear, tt & ~(tags.PARBDY | tags.PARBDYBDY), tt
        )
        syn_req = clear & ((tt & tags.NOSURF) != 0)
        tt = jnp.where(
            syn_req, tt & ~(tags.REQUIRED | tags.NOSURF), tt
        )
        # missing: first-copy interface faces with no live tria
        live_rows = jnp.where(trmask2[:, None], t_rows, -1)
        have = common.sorted_membership(
            live_rows, jnp.where(first_s[:, None], prow_s, -1)
        )
        missing = first_s & ~have
        # gid -> local slot via the shard's sorted gid table
        order_v = jnp.argsort(
            jnp.where(vmask_s, vglob_s, G)
        ).astype(jnp.int32)
        sg = jnp.where(vmask_s, vglob_s, G)[order_v]
        pos = jnp.clip(
            jnp.searchsorted(sg, jnp.clip(prow_s, 0, None).reshape(-1)),
            0, PC - 1,
        )
        slot = order_v[pos].reshape(fcapq, 3)
        free = ~trmask2
        free_list = jnp.argsort(
            jnp.where(free, jnp.arange(FC, dtype=jnp.int32), FC)
        ).astype(jnp.int32)
        rank = jnp.cumsum(missing.astype(jnp.int32)) - 1
        tgt = common.unique_oob(
            missing, free_list[jnp.clip(rank, 0, FC - 1)], FC
        )
        tria2 = common.scatter_rows(
            tria_s, tgt, slot.astype(tria_s.dtype), unique=True
        )
        tt = tt.at[tgt].set(
            jnp.asarray(_IFC_TAG, tt.dtype), mode="drop",
            unique_indices=True,
        )
        trref2 = trref_s.at[tgt].set(
            jnp.asarray(0, trref_s.dtype), mode="drop", unique_indices=True
        )
        trmask3 = trmask2.at[tgt].set(
            True, mode="drop", unique_indices=True
        )
        return (tria2, tt, trref2, trmask3,
                jnp.sum(missing.astype(jnp.int32)),
                jnp.sum(free.astype(jnp.int32)))

    tria2, trtag2, trref2, trmask2, n_missing, n_free = jax.vmap(
        shard_tria
    )(vglob, vmask, stacked.tria, stacked.trtag, stacked.trref,
      stacked.trmask, prow, is_ifc, first)

    # --- 4. PARBDYBDY vertex bits -------------------------------------
    both = ((vtag & tags.PARBDY) != 0) & ((vtag & tags.BDY) != 0)
    vtag = jnp.where(both, vtag | tags.PARBDYBDY, vtag)

    return (vtag, tria2, trref2, trtag2, trmask2,
            n_open, n_missing, n_free)


def retag_interfaces(stacked: Mesh, icap=None) -> Tuple[Mesh, ShardComm]:
    """Recompute the parallel-interface discipline after migration —
    device-resident (`_retag_device_core`); the host reads only the
    per-shard overflow scalars. PARMMG_HOST_RETAG=1 selects the
    original host-numpy path (kept as the equivalence reference)."""
    import os

    if os.environ.get("PARMMG_HOST_RETAG"):
        return _retag_interfaces_host(stacked, icap)
    TC = stacked.tet.shape[1]
    fcapq = min(4 * TC, max(2048, TC))  # 4*TC = exact upper bound
    for _ in range(2):
        (vtag, tria, trref, trtag, trmask,
         n_open, n_missing, n_free) = jit_retry(
            _retag_device_core, stacked, fcapq
        )
        mx = int(jax.device_get(jnp.max(n_open)))
        if mx <= fcapq:
            break
        fcapq = 4 * TC  # every tet face open
    over = np.asarray(jax.device_get(n_missing > n_free))
    if over.any():
        raise CapacityError(
            "tria capacity too small for interface trias "
            f"(shards {np.nonzero(over)[0].tolist()})",
            overflow=np.stack([
                np.zeros_like(np.asarray(n_missing)),
                np.zeros_like(np.asarray(n_missing)),
                np.asarray(jax.device_get(n_missing - n_free)),
                np.zeros_like(np.asarray(n_missing)),
            ], axis=1),
        )
    stacked = stacked.replace(
        vtag=vtag, tria=tria, trref=trref, trtag=trtag, trmask=trmask,
    )
    return stacked, rebuild_comm(stacked, icap)


def _retag_interfaces_host(stacked: Mesh, icap=None) -> Tuple[Mesh, ShardComm]:
    """Recompute the parallel-interface discipline after migration:
    PARBDY/PARBDYBDY vertex tags from global gid multiplicity, synthetic
    NOSURF trias from cross-shard open-face matching, then the node
    tables. Host numpy over CONNECTIVITY ARRAYS ONLY (gids, faces, tags
    — ints); geometry stays on device."""
    d = stacked.vert.shape[0]
    vglob = np.asarray(stacked.vglob)
    vmask = np.asarray(stacked.vmask)
    vtag = np.asarray(stacked.vtag).copy()
    tet = np.asarray(stacked.tet)
    tmask = np.asarray(stacked.tmask)
    adja = np.asarray(jax.device_get(
        jax.vmap(adjacency.build_adjacency)(stacked).adja
    ))
    tria = np.asarray(stacked.tria)
    trmask = np.asarray(stacked.trmask).copy()
    trtag = np.asarray(stacked.trtag).copy()
    trref = np.asarray(stacked.trref).copy()

    # --- PARBDY from gid multiplicity ---------------------------------
    all_g = [vglob[s][vmask[s]] for s in range(d)]
    cat = np.concatenate(all_g) if len(all_g) else np.zeros(0, np.int64)
    if len(cat):
        mult = np.bincount(cat.astype(np.int64),
                           minlength=int(cat.max()) + 1)
    else:
        mult = np.zeros(1, np.int64)
    for s in range(d):
        live = vmask[s]
        shared = np.zeros(vglob.shape[1], bool)
        shared[live] = mult[vglob[s][live]] > 1
        vtag[s] = np.where(
            shared, vtag[s] | tags.PARBDY,
            vtag[s] & ~(tags.PARBDY | tags.PARBDYBDY),
        )

    # --- open faces per shard -> cross-shard interface faces ----------
    from ..utils.rows import row_member

    fv = np.asarray(FACE_VERTS)
    face_rows = []
    for s in range(d):
        open_f = (adja[s] < 0) & tmask[s][:, None]
        t_ids, f_ids = np.nonzero(open_f)
        if len(t_ids):
            corners = tet[s][t_ids[:, None], fv[f_ids]]        # [K,3]
            g3 = np.sort(vglob[s][corners], axis=1)
        else:
            g3 = np.zeros((0, 3), np.int64)
        face_rows.append(g3)
    allr = np.concatenate(face_rows)
    _, inv, cnts = np.unique(
        allr, axis=0, return_inverse=True, return_counts=True
    )
    is_ifc = cnts[inv] > 1                     # face present in 2 shards

    # --- synthetic trias: drop stale, refresh bits, add missing -------
    new_syn = []
    off = 0
    for s in range(d):
        g3 = face_rows[s]
        k = len(g3)
        ifc_rows = g3[is_ifc[off:off + k]]
        off += k
        syn_mask = tags.pure_interface_tria(trtag[s]) & trmask[s]
        syn_slots = np.nonzero(syn_mask)[0]
        # stale synthetic trias: no longer an interface face
        if len(syn_slots):
            syn_rows = np.sort(vglob[s][tria[s][syn_slots]], axis=1)
            still = row_member(syn_rows, ifc_rows)
            trmask[s][syn_slots[~still]] = False
        # real trias: set/clear interface bits by membership
        real_slots = np.nonzero(trmask[s] & ~syn_mask)[0]
        if len(real_slots):
            real_rows = np.sort(vglob[s][tria[s][real_slots]], axis=1)
            at_ifc = row_member(real_rows, ifc_rows)
            trtag[s][real_slots[at_ifc]] |= (
                tags.PARBDY | tags.PARBDYBDY | tags.BDY
            )
            # freeze real interface trias that are not yet required —
            # with NOSURF marking the REQUIRED as split-added so merge
            # strips it; USER-required trias keep their plain REQUIRED
            fresh = real_slots[at_ifc]
            noreq = (trtag[s][fresh] & tags.REQUIRED) == 0
            trtag[s][fresh[noreq]] |= tags.REQUIRED | tags.NOSURF
            was_par = (trtag[s][real_slots] & tags.PARBDYBDY) != 0
            clear = real_slots[~at_ifc & was_par]
            trtag[s][clear] &= ~(tags.PARBDY | tags.PARBDYBDY)
            # ...and unfreeze them: the REQUIRED that NOSURF marks as
            # split-added must go with the interface, or the band behind
            # a displaced front never adapts
            syn_req = clear[(trtag[s][clear] & tags.NOSURF) != 0]
            trtag[s][syn_req] &= ~(tags.REQUIRED | tags.NOSURF)
        # missing synthetic trias: interface faces with no tria at all
        live_now = np.nonzero(trmask[s])[0]
        have_rows = (
            np.sort(vglob[s][tria[s][live_now]], axis=1)
            if len(live_now) else np.zeros((0, 3), np.int64)
        )
        missing = ifc_rows[~row_member(ifc_rows, have_rows)]
        missing = np.unique(missing, axis=0)
        # gid -> local slot lookup
        live_v = np.nonzero(vmask[s])[0]
        lut = np.full(int(vglob[s][live_v].max(initial=0)) + 2, -1,
                      np.int64)
        lut[vglob[s][live_v]] = live_v
        new_syn.append(lut[missing] if len(missing)
                       else np.zeros((0, 3), np.int64))

    # append synthetic trias (host write into the stacked arrays)
    tria_new = np.asarray(stacked.tria).copy()
    IFC_TAG = tags.PARBDY | tags.REQUIRED | tags.NOSURF | tags.BDY
    for s in range(d):
        need = len(new_syn[s])
        if need == 0:
            continue
        free = np.nonzero(~trmask[s])[0]
        if need > len(free):
            raise CapacityError(
                f"tria capacity too small for {need} interface trias"
            )
        sel = free[:need]
        tria_new[s][sel] = np.asarray(new_syn[s])
        trref[s][sel] = 0
        trtag[s][sel] = IFC_TAG
        trmask[s][sel] = True

    # PARBDYBDY vertex bits
    for s in range(d):
        both = ((vtag[s] & tags.PARBDY) != 0) & ((vtag[s] & tags.BDY) != 0)
        vtag[s] = np.where(both, vtag[s] | tags.PARBDYBDY, vtag[s])

    stacked = stacked.replace(
        vtag=jnp.asarray(vtag),
        tria=jnp.asarray(tria_new),
        trref=jnp.asarray(trref),
        trtag=jnp.asarray(trtag),
        trmask=jnp.asarray(trmask),
    )
    return stacked, rebuild_comm(stacked, icap)


# ---------------------------------------------------------------------------
# frontier remap through the exchange — round 8
#
# The active-set carry of the distributed sweeps (models/distributed)
# must survive the repartition: a cell that crosses a shard boundary
# has to arrive ACTIVE on its new owner, and the interface bands the
# displacement unfreezes are exactly the regions with pending work
# (ParMmg's interface-displacement loop makes them the next
# iteration's working set). Vertex identity across the exchange is the
# persistent global id (`Mesh.vglob`, remapped through every compact),
# so the remap is gid-set membership: encode the active set as gid
# keys BEFORE the exchange, decode per shard AFTER it — one sort-merge
# over [D*PC] rows, immune to capacity growth, slot permutation and
# ownership changes in between.
# ---------------------------------------------------------------------------


# parmmg-lint: disable=PML005 -- pure query (leaving-cell vertex mask); the caller keeps migrating the mesh
@jax.jit
def migrating_vertices(stacked: Mesh, color: jax.Array) -> jax.Array:
    """[D, PC] bool: vertices of tets about to leave their shard (their
    whole 1-ring context changes owner, so they re-enter the frontier
    on arrival)."""
    d, pc = stacked.vmask.shape
    own = jnp.arange(d, dtype=color.dtype)[:, None]
    leaving = stacked.tmask & (color >= 0) & (color != own)

    def per_shard(tet_s, lv_s):
        idx = jnp.where(lv_s[:, None], tet_s, pc)
        return jnp.zeros(pc, bool).at[idx.reshape(-1)].set(
            True, mode="drop"
        )

    return jax.vmap(per_shard)(stacked.tet, leaving)


# parmmg-lint: disable=PML005 -- pure query (gid encode); the caller exchanges the mesh next
@jax.jit
def frontier_gid_keys(stacked: Mesh, sel: jax.Array) -> jax.Array:
    """[D*PC, 1] int32 gid rows of the selected live vertices (-1 rows
    never match). Requires `assign_global_ids` to have run."""
    g = jnp.where(sel & stacked.vmask, stacked.vglob, -1)
    return g.reshape(-1, 1).astype(jnp.int32)


# parmmg-lint: disable=PML005 -- pure query (gid decode) on the post-exchange mesh the caller keeps
@jax.jit
def frontier_from_gid_keys(stacked: Mesh, keys: jax.Array) -> jax.Array:
    """[D, PC] bool: live vertices whose gid appears among `keys` — the
    post-exchange decode of `frontier_gid_keys` (exact: gid membership
    is ownership-independent, so a migrated cell's vertices land active
    on the receiving shard)."""
    q = jnp.where(
        stacked.vmask, stacked.vglob, -1
    ).reshape(-1, 1).astype(jnp.int32)
    hit = common.sorted_membership(keys, q)
    return hit.reshape(stacked.vmask.shape) & stacked.vmask
