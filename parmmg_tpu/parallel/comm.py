"""Device-side halo exchange over the static node-communicator tables.

Replaces the reference's entire L3 exchange pattern — scatter values into
the internal communicator, copy per-neighbor slices, `MPI_Sendrecv`, gather
back (e.g. reference `src/libparmmg.c:743-790`) — with one
`jax.lax.all_to_all` plus masked gather/scatter over `ShardComm.comm_idx`.
All functions here run INSIDE `shard_map` over the shard axis: `vals` is
one shard's [P,...] array, `comm_idx` that shard's [D,I] slice.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def status_allgather(
    vec: jax.Array, axis_name: str = "shards"
) -> jax.Array:
    """Replicated [D, n] table of every shard's status vector.

    One psum of a one-hot row scatter: each shard contributes its [n]
    vector at its own row index, and the sum is identical (replicated)
    on every shard — the role of the reference's per-phase
    `MPI_Allgather` of the `ier` agreement, used by the device-resident
    phase validator (`failsafe.stacked_status`) so only this tiny table
    ever crosses to host."""
    d = jax.lax.psum(1, axis_name)  # static axis size
    row = jax.lax.axis_index(axis_name)
    full = jnp.zeros((d,) + vec.shape, vec.dtype).at[row].set(vec)
    return jax.lax.psum(full, axis_name)


def halo_exchange(
    vals: jax.Array, comm_idx: jax.Array, axis_name: str = "shards"
) -> jax.Array:
    """Raw neighbor exchange: returns [D, I, ...] where row r holds the
    values shard r gathered at its side of the shared-vertex list (same k
    ordering both sides). Padded slots return the row's slot-0 value and
    must be masked by the caller via comm_idx >= 0."""
    safe = jnp.maximum(comm_idx, 0)  # [D,I]
    send = vals[safe]  # [D,I,...]
    return jax.lax.all_to_all(
        send, axis_name, split_axis=0, concat_axis=0, tiled=True
    )


def _scatter_combine(
    vals: jax.Array,
    comm_idx: jax.Array,
    recv: jax.Array,
    combine: str,
    neutral,
) -> jax.Array:
    p = vals.shape[0]
    valid = comm_idx >= 0
    tgt = jnp.where(valid, comm_idx, p).reshape(-1)  # OOB drop for pads
    r = jnp.where(
        valid.reshape(valid.shape + (1,) * (recv.ndim - 2)),
        recv,
        jnp.asarray(neutral, recv.dtype),
    ).reshape((-1,) + recv.shape[2:])
    upd = getattr(vals.at[tgt], combine)
    return upd(r, mode="drop")


def halo_sum(vals, comm_idx, axis_name: str = "shards"):
    """Each interface vertex accumulates the SUM of its copies' values
    across all shards holding it (every copy converges to the same total,
    like the reference's node-comm Allreduce pattern)."""
    recv = halo_exchange(vals, comm_idx, axis_name)
    return _scatter_combine(vals, comm_idx, recv, "add", 0)


def halo_min(vals, comm_idx, axis_name: str = "shards"):
    recv = halo_exchange(vals, comm_idx, axis_name)
    big = jnp.iinfo(vals.dtype).max if jnp.issubdtype(
        vals.dtype, jnp.integer
    ) else jnp.inf
    return _scatter_combine(vals, comm_idx, recv, "min", big)


def halo_max(vals, comm_idx, axis_name: str = "shards"):
    recv = halo_exchange(vals, comm_idx, axis_name)
    small = jnp.iinfo(vals.dtype).min if jnp.issubdtype(
        vals.dtype, jnp.integer
    ) else -jnp.inf
    return _scatter_combine(vals, comm_idx, recv, "max", small)


def halo_or(vals, comm_idx, axis_name: str = "shards"):
    """Bitwise (int) / boolean OR across copies — tag agreement across
    shards (reference's tag-consistency exchanges in `src/tag_pmmg.c`).
    There is no native scatter-or, so integer neighbor rows fold
    sequentially (D is the small device count; within one row each target
    slot appears at most once, so gather-modify-scatter is exact)."""
    recv = halo_exchange(vals, comm_idx, axis_name)
    if vals.dtype == jnp.bool_:
        return _scatter_combine(vals, comm_idx, recv, "max", False)
    p = vals.shape[0]
    out = vals
    for d in range(comm_idx.shape[0]):
        idx = comm_idx[d]
        valid = idx >= 0
        tgt = jnp.where(valid, idx, p)
        r = jnp.where(valid, recv[d], 0)
        cur = out.at[tgt].get(mode="fill", fill_value=0)
        out = out.at[tgt].set(cur | r, mode="drop")
    return out
