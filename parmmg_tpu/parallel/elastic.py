"""Elastic world supervisor: notice→shrink, capacity-restored→grow.

The reference's remesh–repartition loop assumes one MPI world for the
life of the run (`PMMG_Init_parMesh(PMMG_ARG_MPIComm, ...)`); on
preemptible TPU pools that assumption is what forces an operator into
the loop — before this module, a maintenance notice ended in the
checkpoint-backed exit-86 family and a human restarting the job with a
new layout. This module makes world-size changes an INTERNAL recovery
action, the way `models.distributed._elastic_recut` already made shard-
count changes an internal array transformation:

- a **preemption notice** on rank r (any `parallel.multihost` notice
  source) turns into a world-agreed SHRINK: the noticed rank publishes
  a departure record into the checkpoint store, every rank agrees at
  the same iteration boundary (one psum vote,
  `multihost.agree_flags` — the ``MPI_Allreduce(ier)`` role), the
  world force-commits its checkpoint, the departing rank exits through
  the preemption path (86) and the survivors exit with the typed
  :data:`~parmmg_tpu.failsafe.REFORM_EXIT_CODE`; the fleet supervisor
  (`tools/fleet.py`) relaunches the survivors as a world of N−1, which
  resumes from the committed epoch (re-cutting the shards through
  `_elastic_recut` when the device pool changed);
- a **capacity-restored signal** (`multihost.capacity_restored`:
  programmatic request / callback probe / ``PMMGTPU_CAPACITY_FILE`` —
  the exact mirror of the notice sources) on a world running below its
  target size turns into the symmetric GROW: a grow record, the same
  vote, the same commit, all ranks exit 90 and the fleet relaunches at
  N+1 with a fresh member.

Coordination is **store-backed**, not ack-based: the membership
manifest (`elastic_manifest_e<k>.json`, one per reformation epoch) and
the per-rank reform/ack records live in the same durable
`CheckpointStore` as the checkpoints themselves, so a reformation
survives the dying rank never acking — the survivors and the fleet
read the store, they do not wait on the departing process. Records:

- ``elastic_manifest_e<k>.json`` — ``{epoch, world, members,
  target_world, reason, ts}``; published (commit-token put) by the
  fleet before launching epoch k;
- ``elastic_reform_e<k>_r<r>.json`` — rank r's reform request in epoch
  k (``kind`` = ``shrink`` | ``grow``, ``ts``); per-rank names, so
  concurrent requesters never conflict;
- ``elastic_ack_e<k>_r<r>.json`` — rank r's exit ack (best-effort;
  used only to measure downtime, never waited on).

Every transition is observable: the deciding epoch emits a
``world_reform`` event, and the FIRST boundary of the new epoch emits
``world_shrink`` / ``world_grow`` with ``old``/``new`` world sizes and
``downtime_s`` (wall time from the previous epoch's last ack — or its
manifest — to the new epoch's coordinator coming up), rendered by
``tools/obs_report.py --chaos`` as the world-size timeline.

A world that cannot reform — a shrink below
``PMMGTPU_ELASTIC_MIN_WORLD`` (default 1; raise it when a lone
survivor's device pool could not hold ``min_shard_elts`` per shard) —
refuses loudly with the typed :class:`UnreformableWorldError` instead
of limping into an unservable layout.

Env contract (set per epoch by `tools/fleet.py`)::

  PMMGTPU_ELASTIC            arm the coordinator (requires a checkpoint
                             store — without one there is nothing to
                             shrink/grow FROM)
  PMMGTPU_ELASTIC_EPOCH      this launch's reformation epoch (default:
                             newest manifest in the store, else 0)
  PMMGTPU_ELASTIC_TARGET     target world size grows aim for (default:
                             the current world size)
  PMMGTPU_ELASTIC_MIN_WORLD  smallest world a shrink may leave
                             (default 1)
  PMMGTPU_CAPACITY_FILE      capacity-restored marker file (see
                             `multihost.capacity_restored`)
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional

from ..failsafe import (
    AdaptError,
    PreemptionError,
    WorldReformError,
)
from ..io.ckpt_store import CheckpointIOError, CheckpointStore
from ..obs import metrics as obs_metrics, trace as obs_trace
from . import multihost

MANIFEST_FMT = "elastic_manifest_e{:05d}.json"
REFORM_FMT = "elastic_reform_e{:05d}_r{}.json"
ACK_FMT = "elastic_ack_e{:05d}_r{}.json"
ELASTIC_FORMAT = 1


class UnreformableWorldError(AdaptError):
    """A reformation was agreed but the resulting world would be
    unservable (shrink below the configured minimum — e.g. a lone
    survivor whose device pool cannot hold ``min_shard_elts`` per
    shard). Refuse loudly: the checkpoint stands, the operatorless
    answer is "wait for capacity", not "limp on a broken layout"."""


# ---------------------------------------------------------------------------
# store-backed records
# ---------------------------------------------------------------------------


def publish_manifest(store: CheckpointStore, epoch: int, world: int,
                     members: List[int], target_world: int,
                     reason: str = "", ts: Optional[float] = None) -> dict:
    """Publish epoch ``epoch``'s membership manifest (the fleet calls
    this before every launch; exactly-one-writer via the store's
    commit-token put)."""
    doc = dict(
        format=ELASTIC_FORMAT, epoch=int(epoch), world=int(world),
        members=[int(m) for m in members],
        target_world=int(target_world), reason=reason,
        ts=float(ts if ts is not None else time.time()),
    )
    store.publish_json(MANIFEST_FMT.format(int(epoch)), doc)
    return doc


def read_manifest(store: CheckpointStore, epoch: int) -> Optional[dict]:
    try:
        return store.get_json(MANIFEST_FMT.format(int(epoch)))
    except (FileNotFoundError, CheckpointIOError):
        return None


def latest_epoch(store: CheckpointStore) -> Optional[int]:
    """Newest manifest epoch in the store, or None."""
    epochs = []
    for name in store.list():
        if name.startswith("elastic_manifest_e") and name.endswith(".json"):
            digits = name[len("elastic_manifest_e"):-len(".json")]
            if digits.isdigit():
                epochs.append(int(digits))
    return max(epochs) if epochs else None


def reform_records(store: CheckpointStore, epoch: int) -> List[dict]:
    """Every rank's reform request for ``epoch`` (corrupt or torn
    records are skipped — a broken request must not wedge the vote)."""
    prefix = f"elastic_reform_e{int(epoch):05d}_"
    recs = []
    for name in store.list():
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            recs.append(store.get_json(name))
        except (FileNotFoundError, CheckpointIOError):
            continue
    return recs


def write_exit_ack(store: CheckpointStore, epoch: int, rank: int,
                   role: str, kind: str) -> None:
    """Best-effort exit ack (downtime bookkeeping only — the protocol
    never waits on it, so a failure here is swallowed: the manifest ts
    is the fallback clock)."""
    try:
        store.put_json(
            ACK_FMT.format(int(epoch), int(rank)),
            dict(format=ELASTIC_FORMAT, epoch=int(epoch),
                 rank=int(rank), role=role, kind=kind, ts=time.time()),
        )
    except Exception:
        pass


def last_ack_ts(store: CheckpointStore, epoch: int) -> Optional[float]:
    prefix = f"elastic_ack_e{int(epoch):05d}_"
    best = None
    for name in store.list():
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            ts = float(store.get_json(name).get("ts", 0.0))
        except (FileNotFoundError, CheckpointIOError, TypeError,
                ValueError):
            continue
        best = ts if best is None else max(best, ts)
    return best


# ---------------------------------------------------------------------------
# the coordinator the failsafe harness holds
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReformDecision:
    """One world-agreed reformation: every rank of the epoch holds an
    identical copy of this after the vote."""

    kind: str                 # "shrink" | "grow"
    epoch: int
    old_world: int
    new_world: int
    departing: tuple          # ranks leaving (shrink), () for grow
    requested_ts: float       # wall clock of the earliest request

    def mine(self, rank: int) -> bool:
        return rank in self.departing


class ElasticCoordinator:
    """Per-run elastic state: polled by the failsafe harness at every
    iteration boundary of the distributed driver. Holds no collective
    state beyond the one-psum vote — everything durable lives in the
    checkpoint store."""

    def __init__(self, store: CheckpointStore, *, epoch: int, rank: int,
                 world: int, target_world: int, min_world: int = 1):
        self.store = store
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.world = int(world)
        self.target_world = max(int(target_world), 1)
        self.min_world = max(int(min_world), 1)
        self._published = False
        self._decision: Optional[ReformDecision] = None

    # -- transition observability ---------------------------------------
    def note_transition(self) -> Optional[str]:
        """Emit ``world_shrink`` / ``world_grow`` (old/new world size,
        ``downtime_s``) when this epoch's world differs from the
        previous epoch's — called once at coordinator construction, the
        first code of the resumed world that can see both manifests.
        Idempotent per (process, epoch)."""
        if self.epoch <= 0 or self.epoch in _NOTED_EPOCHS:
            return None
        cur = read_manifest(self.store, self.epoch)
        prev = read_manifest(self.store, self.epoch - 1)
        if not cur or not prev:
            return None
        _NOTED_EPOCHS.add(self.epoch)
        old, new = int(prev.get("world", 0)), int(cur.get("world", 0))
        if not old or not new or old == new:
            return None
        end_ts = last_ack_ts(self.store, self.epoch - 1)
        if end_ts is None:
            end_ts = float(prev.get("ts", 0.0)) or None
        downtime = (
            max(0.0, time.time() - end_ts) if end_ts is not None else -1.0
        )
        name = "world_shrink" if new < old else "world_grow"
        obs_trace.emit_event(
            name, old=old, new=new, epoch=self.epoch,
            downtime_s=round(downtime, 3),
            reason=str(cur.get("reason", "")),
        )
        obs_metrics.registry().counter(f"elastic/{name}").inc()
        return name

    # -- the boundary poll ------------------------------------------------
    def _publish_reform(self, kind: str, reason: str,
                        timeout: Optional[float] = None) -> None:
        # the publish happens BEFORE the vote collective: peers may
        # already be waiting in agree_flags, so a wedged store must
        # become a typed PeerLostError within the watchdog window, not
        # an open-ended stall that strands the whole world (PML015)
        multihost.run_with_watchdog(
            lambda: self.store.put_json(
                REFORM_FMT.format(self.epoch, self.rank),
                dict(format=ELASTIC_FORMAT, epoch=self.epoch,
                     rank=self.rank, kind=kind, reason=reason,
                     ts=time.time()),
            ),
            f"elastic-publish:{kind}", timeout,
        )

    def poll(self, it: int,
             timeout: Optional[float] = None) -> Optional[ReformDecision]:
        """One iteration-boundary reform vote. EVERY rank of the epoch
        must call this at the SAME boundary (it contains a collective):
        a rank with a standing preemption notice publishes its
        departure, a rank seeing restored capacity below the target
        world publishes a grow request, and one psum agreement makes
        the decision identical everywhere — the ranks that saw nothing
        locally learn the details from the store AFTER the vote, so
        the steady-state cost is one tiny collective and zero store
        reads. Returns None (keep adapting) or the agreed decision;
        raises :class:`UnreformableWorldError` when the agreed shrink
        would leave fewer than ``min_world`` ranks."""
        if self._decision is not None:
            return self._decision
        flag = 0
        if multihost.preemption_notice():
            if not self._published:
                self._publish_reform(
                    "shrink",
                    f"preemption notice on rank {self.rank} at it {it}",
                    timeout=timeout,
                )
                self._published = True
            flag = 1
        elif self.world < self.target_world \
                and multihost.capacity_restored():
            if not self._published:
                self._publish_reform(
                    "grow",
                    f"capacity restored, world {self.world} below "
                    f"target {self.target_world} (it {it})",
                    timeout=timeout,
                )
                self._published = True
            flag = 1
        agreed = multihost.agree_flags(
            flag, tag=f"elastic-vote:{it}", timeout=timeout
        )
        if not agreed:
            return None
        recs = reform_records(self.store, self.epoch)
        if not recs:
            # a voter whose record publish failed: consistent on every
            # rank (same store read), so everyone keeps adapting and
            # the requester re-publishes at the next boundary
            return None
        departing = tuple(sorted({
            int(r["rank"]) for r in recs if r.get("kind") == "shrink"
        }))
        requested_ts = min(float(r.get("ts", time.time())) for r in recs)
        if departing:
            kind = "shrink"
            new_world = self.world - len(departing)
        else:
            # batch grow: go straight to the target world in ONE
            # reformation. Each reformation costs a full barrier +
            # checkpoint + repartition, so growing 1 -> N as N-1
            # single-step reforms pays that price N-1 times for the
            # same final world; the capacity probe that triggered the
            # vote already said the whole target stands, and a member
            # that fails to come up is just the next shrink vote.
            kind = "grow"
            new_world = self.target_world
        decision = ReformDecision(
            kind=kind, epoch=self.epoch, old_world=self.world,
            new_world=new_world, departing=departing,
            requested_ts=requested_ts,
        )
        obs_trace.emit_event(
            "world_reform", kind=kind, epoch=self.epoch, it=int(it),
            old=self.world, new=new_world,
            departing=list(departing),
        )
        obs_metrics.registry().counter("elastic/reforms").inc()
        if kind == "shrink" and new_world < self.min_world:
            raise UnreformableWorldError(
                f"agreed shrink at epoch {self.epoch} would leave "
                f"{new_world} rank(s), below the configured minimum "
                f"world of {self.min_world} (ranks {list(departing)} "
                "departing): the world cannot reform — the checkpoint "
                "stands; restart when capacity returns"
            )
        self._decision = decision
        return decision

    # -- exit -------------------------------------------------------------
    def ack_exit(self, decision: ReformDecision) -> None:
        """Durable exit ack AFTER the reform checkpoint committed —
        the downtime clock's start. Best-effort by design."""
        role = "departing" if decision.mine(self.rank) else "survivor"
        write_exit_ack(self.store, self.epoch, self.rank, role,
                       decision.kind)

    def error_for(self, decision: ReformDecision) -> BaseException:
        """The typed error each rank leaves the driver with: the
        departing rank exits through the preemption family (86 — it IS
        being preempted), survivors through the reform code (90 — the
        fleet relaunches them at the new world size)."""
        if decision.mine(self.rank):
            return PreemptionError(
                f"elastic departure: preemption notice honored at "
                f"epoch {decision.epoch} — checkpoint committed, world "
                f"reforming {decision.old_world}→{decision.new_world} "
                "without this rank"
            )
        return WorldReformError(
            kind=decision.kind, epoch=decision.epoch,
            old_world=decision.old_world, new_world=decision.new_world,
        )


_NOTED_EPOCHS: set = set()


def coordinator_from_env(store) -> Optional[ElasticCoordinator]:
    """The coordinator for this process per the PMMGTPU_ELASTIC_* env
    contract (module docstring), or None when elasticity is not armed
    or no store exists to coordinate through. Emits the world
    transition event for a freshly reformed epoch."""
    if not os.environ.get("PMMGTPU_ELASTIC") or store is None:
        return None
    import jax

    rank = int(jax.process_index())
    world = int(jax.process_count())
    epoch_env = os.environ.get("PMMGTPU_ELASTIC_EPOCH")
    if epoch_env is not None and epoch_env != "":
        epoch = int(epoch_env)
    else:
        epoch = latest_epoch(store) or 0
    target = int(os.environ.get("PMMGTPU_ELASTIC_TARGET", world) or world)
    minw = int(os.environ.get("PMMGTPU_ELASTIC_MIN_WORLD", "1") or 1)
    coord = ElasticCoordinator(
        store, epoch=epoch, rank=rank, world=world,
        target_world=max(target, world), min_world=minw,
    )
    coord.note_transition()
    return coord
