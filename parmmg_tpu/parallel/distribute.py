"""Initial mesh distribution: split a centralized mesh into device shards.

Host-side counterpart of the reference's centralized scatter
(`src/distributemesh_pmmg.c`: `PMMG_distribute_mesh:1109` — bcast, metis
partition, `PMMG_mark_localMesh:506`, `PMMG_permuteMesh:445`,
`PMMG_create_communicators:739`). Here the mesh lives in host numpy once
(I/O side), is cut by a partition array, and becomes a stacked device
pytree of per-shard Meshes (leading axis = shard) plus a static
communicator index table.

Communicator model (reference `src/libparmmgtypes.h:249-307` re-expressed):
the internal/external communicator pair becomes ONE static gather table
`comm_idx[s, r, k]` = local vertex slot, in shard s, of the k-th vertex
shared between shards s and r (ordered by global id, so slot k on both
sides names the same physical vertex; -1 pads). Halo exchange is then a
pure `all_to_all` + masked scatter (`parallel/comm.py`) — no tags, no
pack/unpack, no MPI datatypes.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import adjacency, tags
from ..core.mesh import FACE_VERTS, Mesh
from ..utils.retry import jit_retry


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardComm:
    """Static node-communicator tables for a D-shard mesh."""

    comm_idx: jax.Array   # [D, D, I] local vertex slot of k-th shared
    #                       vertex with the other shard, -1 pad
    counts: jax.Array     # [D, D] int32 number of shared vertices per pair
    l2g: jax.Array        # [D, PC] int32 global vertex id per local slot
    #                       (-1 on dead slots)
    owner: jax.Array      # [D, PC] bool: this shard owns the vertex (the
    #                       lowest-id shard sharing it) — dedup for
    #                       reductions, reference PMMG_count_nodes_par role

    @property
    def nshard(self) -> int:
        return self.comm_idx.shape[0]

    @property
    def icap(self) -> int:
        return self.comm_idx.shape[2]


def split_mesh(
    mesh: Mesh,
    part: np.ndarray,
    nparts: int,
    headroom: float = 1.5,
    assume_adjacency: bool = False,
    build_shard_adjacency: bool = True,
) -> Tuple[Mesh, ShardComm]:
    """Split a host/device Mesh into `nparts` shards per tet partition.

    Returns (stacked Mesh with leading shard axis, ShardComm). Vertices on
    inter-shard interfaces are tagged PARBDY in every shard that holds
    them (freeze discipline, reference `src/tag_pmmg.c:267`); boundary
    trias follow the shard of their adjacent tet; feature edges replicate
    into every shard containing both endpoints. Pass
    `assume_adjacency=True` when `mesh.adja` is already fresh to skip the
    full-mesh rebuild (it is the dominant host cost of resharding), and
    `build_shard_adjacency=False` when the caller rebuilds per-shard
    adjacency itself (the distributed driver does, for the interp
    snapshot).
    """
    if not assume_adjacency:
        mesh = adjacency.build_adjacency(mesh)
    part = np.asarray(part)
    tmask = np.asarray(mesh.tmask)
    adja = np.asarray(mesh.adja)
    tet = np.asarray(mesh.tet)
    vert = np.asarray(mesh.vert)
    vref_g = np.asarray(mesh.vref)
    vtag_g = np.asarray(mesh.vtag)
    tref_g = np.asarray(mesh.tref)
    met_g = np.asarray(mesh.met)
    ls_g = np.asarray(mesh.ls)
    disp_g = np.asarray(mesh.disp)
    fields_g = np.asarray(mesh.fields)
    tria = np.asarray(mesh.tria)
    trmask = np.asarray(mesh.trmask)
    trref_g = np.asarray(mesh.trref)
    trtag_g = np.asarray(mesh.trtag)
    edge = np.asarray(mesh.edge)
    edmask = np.asarray(mesh.edmask)
    edref_g = np.asarray(mesh.edref)
    edtag_g = np.asarray(mesh.edtag)

    live_t = np.nonzero(tmask)[0]
    if (part[live_t] < 0).any() or (part[live_t] >= nparts).any():
        raise ValueError("partition must assign every valid tet to a shard")

    # --- interface vertices: shards-per-vertex incidence (vectorized) ------
    npcap = vert.shape[0]
    pairs = np.unique(
        np.stack(
            [tet[live_t].ravel(), np.repeat(part[live_t], 4)], axis=1
        ),
        axis=0,
    )
    v_nshards = np.bincount(pairs[:, 0], minlength=npcap)

    # --- tria -> owning tet shard (boundary faces have a unique tet) -------
    fv = tet[:, np.asarray(FACE_VERTS)].reshape(-1, 3)
    fkey = np.sort(fv, axis=1)
    ftet = np.repeat(np.arange(tet.shape[0]), 4)
    fvalid = np.repeat(tmask, 4)

    tria_live = np.nonzero(trmask)[0]
    tkey = np.sort(tria[tria_live], axis=1)

    # row-wise unique matching (no bit packing: immune to vertex counts
    # beyond any fixed field width)
    vsel = np.nonzero(fvalid)[0]
    fk = fkey[vsel]
    allrows = np.concatenate([fk, tkey]) if len(tkey) else fk
    _, inv = np.unique(allrows, axis=0, return_inverse=True)
    fid, qid = inv[: len(fk)], inv[len(fk):]
    nrows = inv.max() + 1 if len(inv) else 1
    face_tet = np.full(nrows, -1, np.int64)
    face_tet[fid] = ftet[vsel]
    # inverse map: face-row -> tria slot (reused below for interface faces)
    face_tria = np.full(nrows, -1, np.int64)
    face_tria[qid] = tria_live
    tria_shard = np.full(tria.shape[0], -1)
    if len(tkey):
        hit = face_tet[qid] >= 0
        if not hit.all():
            bad = tria_live[~hit][:5]
            raise ValueError(f"boundary trias {bad} match no valid tet face")
        tria_shard[tria_live] = part[face_tet[qid]]

    # --- interface faces become PARBDY triangles in each side shard --------
    # (the reference materializes parallel faces as MG_PARBDY boundary
    # triangles per group so the remesher treats them as frozen surface;
    # src/tag_pmmg.c:267 discipline)
    nb = adja // 4
    ifc_mask = (adja >= 0) & tmask[:, None]
    ifc_mask &= part[np.maximum(nb, 0)] != part[:, None]
    ifc_t, ifc_f = np.nonzero(ifc_mask)
    ifc_verts = tet[ifc_t[:, None], np.asarray(FACE_VERTS)[ifc_f]]  # [K,3]
    ifc_shard = part[ifc_t]
    IFC_TAG = tags.PARBDY | tags.REQUIRED | tags.NOSURF | tags.BDY

    # an input boundary tria can lie on an interior face that becomes an
    # inter-shard interface (opnbdy meshes): reuse that tria's ref/tags on
    # BOTH sides (PARBDYBDY discipline, reference src/tag_pmmg.c:646)
    # instead of duplicating a synthetic NOSURF tria next to it. NOSURF
    # also marks the REQUIRED bit as split-added (the reference's
    # MG_NOSURF convention) so merge can strip it without touching
    # user-required trias.
    ifc_ref = np.zeros(len(ifc_verts), np.int64)
    ifc_tag = np.full(len(ifc_verts), IFC_TAG, np.int64)
    if len(tkey) and len(ifc_verts):
        # interface faces are tet faces already matched above: look their
        # tria up through the first pass's row ids instead of re-sorting
        pos = np.searchsorted(vsel, ifc_t * 4 + ifc_f)
        hit = face_tria[fid[pos]]
        m = hit >= 0
        ifc_ref[m] = trref_g[hit[m]]
        # keep the ORIGINAL tria winding on both replicas (tet-face order
        # differs per side and would flip the surface normal for one of
        # them; merge dedup would then keep an arbitrary orientation)
        ifc_verts[m] = tria[hit[m]]
        # NOSURF marks the REQUIRED bit as split-added — only when the
        # user did NOT already require the tria (else merge would strip a
        # genuine user constraint)
        user_req = (trtag_g[hit[m]] & tags.REQUIRED) != 0
        ifc_tag[m] = (
            trtag_g[hit[m]]
            | (tags.PARBDY | tags.PARBDYBDY | tags.REQUIRED | tags.BDY)
            | np.where(user_req, 0, tags.NOSURF)
        )
        tria_shard[hit[m]] = -1  # replicated via the interface list instead

    # --- per-shard extraction ---------------------------------------------
    shard_data = []
    for s in range(nparts):
        t_ids = live_t[part[live_t] == s]
        gids = np.unique(tet[t_ids])  # sorted: local order = gid order
        ltet = np.searchsorted(gids, tet[t_ids])
        f_ids = np.nonzero(tria_shard == s)[0]
        sel_ifc = ifc_shard == s
        own_ifc = ifc_verts[sel_ifc]
        ltria = np.concatenate(
            [
                np.searchsorted(gids, tria[f_ids]).reshape(-1, 3),
                np.searchsorted(gids, own_ifc).reshape(-1, 3),
            ]
        )
        ltrref = np.concatenate([trref_g[f_ids], ifc_ref[sel_ifc]])
        ltrtag = np.concatenate([trtag_g[f_ids], ifc_tag[sel_ifc]])
        e_live = np.nonzero(edmask)[0]
        in_s = np.isin(edge[e_live], gids).all(axis=1)
        e_keep = e_live[in_s]
        ledge = (
            np.searchsorted(gids, edge[e_keep])
            if len(e_keep)
            else np.zeros((0, 2), np.int64)
        )
        # PARBDY: vertices seen by more than one shard
        lvtag = vtag_g[gids].copy()
        par = v_nshards[gids] > 1
        lvtag[par] |= tags.PARBDY
        lvtag[par & ((lvtag & tags.BDY) != 0)] |= tags.PARBDYBDY
        shard_data.append(
            dict(
                gids=gids,
                verts=vert[gids],
                vrefs=vref_g[gids],
                vtags=lvtag,
                tets=ltet,
                trefs=tref_g[t_ids],
                trias=ltria,
                trrefs=ltrref,
                trtags=ltrtag,
                edges=ledge,
                edrefs=edref_g[e_keep],
                edtags=edtag_g[e_keep],
                met=met_g[gids],
                ls=ls_g[gids] if ls_g.shape[1] else None,
                disp=disp_g[gids] if disp_g.shape[1] else None,
                fields=fields_g[gids] if fields_g.shape[1] else None,
            )
        )

    # --- uniform capacities ------------------------------------------------
    def cap(n):
        return max(8, int(np.ceil(n * headroom)))

    pcap = cap(max(len(d["gids"]) for d in shard_data))
    tcap = cap(max(len(d["tets"]) for d in shard_data))
    fcap = cap(max(max(len(d["trias"]), 1) for d in shard_data))
    ecap = cap(max(max(len(d["edges"]), 1) for d in shard_data))

    meshes = [
        Mesh.from_numpy(
            d["verts"],
            d["tets"],
            vrefs=d["vrefs"],
            vtags=d["vtags"],
            trefs=d["trefs"],
            trias=d["trias"],
            trrefs=d["trrefs"],
            trtags=d["trtags"],
            edges=d["edges"],
            edrefs=d["edrefs"],
            edtags=d["edtags"],
            met=d["met"] if mesh.met_set else None,
            ls=d["ls"],
            disp=d["disp"],
            fields=d["fields"],
            field_ncomp=mesh.field_ncomp,
            vglob=d["gids"],
            pcap=pcap,
            tcap=tcap,
            fcap=fcap,
            ecap=ecap,
            dtype=mesh.dtype,
        )
        for d in shard_data
    ]
    if build_shard_adjacency:
        meshes = [adjacency.build_adjacency(m) for m in meshes]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *meshes
    )
    # communicator tables from the seeded vglob + PARBDY tags — the same
    # construction that re-derives them after every remesh (one code path,
    # reference PMMG_create_communicators at distributemesh_pmmg.c:739)
    return stacked, rebuild_comm(stacked)


def _pow2_at_least(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


_GID_INF = np.int32(2**31 - 1)


@partial(jax.jit, static_argnames=("kv", "icap"))
def _rebuild_comm_device(vglob, vmask, vtag, kv: int, icap: int):
    """Device core of `rebuild_comm`: per-pair sorted-gid intersections
    into fixed [D,D,icap] tables. `kv` bounds the per-shard interface
    list, `icap` the per-pair shared list (both static; the host wrapper
    sizes them and retries on overflow)."""
    D, PC = vglob.shape
    par = vmask & (vglob >= 0) & ((vtag & tags.PARBDY) != 0)
    key = jnp.where(par, vglob, _GID_INF)
    order = jnp.argsort(key, axis=1)[:, :kv].astype(jnp.int32)  # [D,kv]
    gids = jnp.take_along_axis(key, order, axis=1)              # sorted
    valid = gids < _GID_INF
    nv = jnp.sum(par.astype(jnp.int32), axis=1)                 # [D]

    # pairwise membership: for (s,r), is gids[s,k] present in gids[r]?
    def member(g_s, v_s, g_r):
        pos = jnp.searchsorted(g_r, g_s).astype(jnp.int32)
        pos = jnp.clip(pos, 0, kv - 1)
        return v_s & (g_r[pos] == g_s)

    hit = jax.vmap(  # [D,D,kv]: hit[s,r,k]
        lambda g_s, v_s: jax.vmap(lambda g_r: member(g_s, v_s, g_r))(gids),
        in_axes=(0, 0),
    )(gids, valid)
    # a shard never communicates with itself
    eye = jnp.eye(D, dtype=bool)
    hit = hit & ~eye[:, :, None]
    counts = jnp.sum(hit.astype(jnp.int32), axis=2)             # [D,D]

    # pack each pair's hits (already in ascending-gid order, so both
    # sides of a pair produce the same k-ordering) into icap slots
    rank = jnp.cumsum(hit.astype(jnp.int32), axis=2) - 1
    slots_b = jnp.broadcast_to(order[:, None, :], (D, D, kv))

    def pack(hit_row, rank_row, slot_row):
        tgt = jnp.where(hit_row & (rank_row < icap), rank_row, icap)
        return jnp.full(icap + 1, -1, jnp.int32).at[tgt].set(
            slot_row, mode="drop"
        )[:icap]

    comm_idx = jax.vmap(jax.vmap(pack))(hit, rank, slots_b)     # [D,D,icap]

    # owner = lowest shard holding the gid (PMMG_count_nodes_par role)
    lower = jnp.tril(jnp.ones((D, D), bool), k=-1)              # r < s
    held_lower = jnp.any(hit & lower[:, :, None], axis=1)       # [D,kv]
    own_list = valid & ~held_lower

    def scat_owner(base, slot_row, val_row, v_row):
        idx = jnp.where(v_row, slot_row, PC)
        return base.at[idx].set(val_row, mode="drop")

    owner = jax.vmap(scat_owner)(vmask, order, own_list, valid)
    l2g = jnp.where(vmask, vglob, -1)
    need = jnp.max(counts)
    kv_need = jnp.max(nv)
    return comm_idx, counts, l2g, owner, need, kv_need


def rebuild_comm(stacked: Mesh, icap: int | None = None) -> ShardComm:
    """(Re-)derive `ShardComm` node tables from `Mesh.vglob`.

    Used both for the initial split and after remeshing. The reference
    remaps its communicators after each Mmg call via a face-vertex hash
    (`src/libparmmg1.c:361`); here interface vertices are frozen and keep
    their global ids through `compact()`, so the shared list of each shard
    pair is the gid-intersection of PARBDY vertices — sorted by gid,
    giving identical k-ordering on both sides (the invariant
    `parallel/comm.py` halo exchange relies on). The intersection runs
    on device (`_rebuild_comm_device`); the host only sizes the static
    table capacities and checks for overflow (one scalar readback per
    rebuild instead of fetching the whole vertex table).
    """
    D, PC = stacked.vglob.shape
    par_counts = jnp.sum(
        (stacked.vmask & (stacked.vglob >= 0)
         & ((stacked.vtag & tags.PARBDY) != 0)).astype(jnp.int32),
        axis=1,
    )
    kv = _pow2_at_least(max(int(jnp.max(par_counts)), 1))
    kv = min(kv, PC)
    want_icap = icap
    while True:
        use_icap = want_icap if want_icap is not None else kv
        comm_idx, counts, l2g, owner, need, _ = jit_retry(
            _rebuild_comm_device,
            stacked.vglob, stacked.vmask, stacked.vtag, kv, use_icap,
        )
        need = int(need)
        if need <= use_icap:
            break
        if want_icap is not None:
            raise ValueError(f"icap {want_icap} < largest shared list {need}")
        want_icap = _pow2_at_least(need)
    if icap is None:
        # size the tables to the largest PAIR list, not the per-shard
        # total: kv over-pads every later halo exchange (a shard's
        # interface is split among all its neighbors)
        tight = _pow2_at_least(max(need, 1))
        if tight < use_icap:
            comm_idx, counts, l2g, owner, _, _ = jit_retry(
                _rebuild_comm_device,
                stacked.vglob, stacked.vmask, stacked.vtag, kv, tight,
            )
    return ShardComm(
        comm_idx=comm_idx, counts=counts, l2g=l2g, owner=owner
    )


@partial(jax.jit, donate_argnums=0)
def _assign_gids_device(stacked: Mesh) -> Mesh:
    vglob, vmask = stacked.vglob, stacked.vmask
    new = vmask & (vglob < 0)
    base = jnp.max(jnp.where(vmask & (vglob >= 0), vglob, -1)) + 1
    counts = jnp.sum(new.astype(jnp.int32), axis=1)
    offs = base + jnp.cumsum(counts) - counts        # exclusive scan
    rank = jnp.cumsum(new.astype(jnp.int32), axis=1) - 1
    newid = offs[:, None] + rank
    return stacked.replace(
        vglob=jnp.where(new, newid.astype(jnp.int32), vglob)
    )


def assign_global_ids(stacked: Mesh) -> Mesh:
    """Give remeshing-created vertices (vglob == -1) fresh contiguous
    global ids — on device.

    The reference numbers output vertices owner-first across ranks
    (`PMMG_Compute_verticesGloNum`, `src/libparmmg.c:923`) — here every
    new vertex is strictly interior to its shard (interfaces are frozen),
    so numbering is an exclusive scan of per-shard new-vertex counts on
    top of the current global max; no halo agreement is required.

    Not routed through `utils.retry.jit_retry`: the device fn donates
    its input buffers, so a second invocation after a transient failure
    could see already-deleted arrays — for donating entry points the
    retry lives at the iteration level (failsafe RetraceError recovery).
    """
    return _assign_gids_device(stacked)


def assign_triangle_gids(stacked: Mesh) -> np.ndarray:
    """[D,FC] int64 global triangle ids for true-surface trias; -1 on
    dead slots and on synthetic NOSURF interface trias.

    The triangle side of the distributed-output contract
    (`PMMG_Compute_trianglesGloNum`, reference `src/libparmmg.c:464`): a
    PARBDYBDY tria replicated on both sides of an interface gets ONE id
    (both replicas read the same number; the lowest shard is the owner),
    ids are contiguous from 0 in sorted vertex-gid-triple order. Host,
    connectivity-only, sort-merge — no per-entity Python."""
    tria = np.asarray(jax.device_get(stacked.tria))
    trmask = np.asarray(jax.device_get(stacked.trmask))
    trtag = np.asarray(jax.device_get(stacked.trtag))
    vglob = np.asarray(jax.device_get(stacked.vglob))
    D, FC = trmask.shape
    out = np.full((D, FC), -1, np.int64)
    real = trmask & ~tags.pure_interface_tria(trtag)
    s_i, f_i = np.nonzero(real)
    if not len(s_i):
        return out
    g3 = np.sort(vglob[s_i[:, None], tria[s_i, f_i]], axis=1).astype(np.int64)
    order = np.lexsort((g3[:, 2], g3[:, 1], g3[:, 0]))
    gs = g3[order]
    newkey = np.concatenate([[True], np.any(gs[1:] != gs[:-1], axis=1)])
    gid_sorted = np.cumsum(newkey) - 1
    out[s_i[order], f_i[order]] = gid_sorted
    return out


def stack_loaded_shards(
    raws,
    dtype=None,
    headroom: float = 1.5,
):
    """Per-rank loaded `io.medit.RawMesh` objects (with
    `ParallelCommunicator*` sections) → (stacked Mesh, ShardComm).

    The distributed-input preprocessing of the reference
    (`PMMG_preprocessMesh_distributed`, `src/libparmmg.c:206-314`):
    interface vertices get PARBDY tags and a shared global numbering,
    interface trias (face-comm mode) are tagged frozen, and the node
    tables are derived. Vertex identity across ranks comes from the
    stored global ids when present (node-comm mode,
    `PMMG_loadCommunicator`, `src/inout_pmmg.c:74`), else from exact
    coordinate matching (the `coorcell_pmmg.c` role) — per-rank files
    print coordinates identically on both sides, so exact match is
    well-defined.
    """
    D = len(raws)
    loc_ids: List[np.ndarray] = []
    gids: List[np.ndarray | None] = []
    ifc_trias: List[np.ndarray] = []
    for raw in raws:
        # face-comm tria lists restore the PARBDY|NOSURF tagging of the
        # synthetic interface trias regardless of which mode identifies
        # the vertices (a checkpoint written by save_mesh_distributed
        # carries BOTH: node comms for gids, face comms for trias)
        if raw.face_comms:
            tr = np.concatenate([np.asarray(c[1], np.int64)
                                 for c in raw.face_comms])
            ifc_trias.append(np.unique(tr))
        else:
            ifc_trias.append(np.zeros(0, np.int64))
        if raw.node_comms:
            loc = np.concatenate([np.asarray(c[1], np.int64)
                                  for c in raw.node_comms])
            gid = np.concatenate([np.asarray(c[2], np.int64)
                                  for c in raw.node_comms])
            loc, first = np.unique(loc, return_index=True)
            loc_ids.append(loc)
            gids.append(gid[first] if (gid >= 0).all() and len(gid) else None)
        elif raw.face_comms:
            loc_ids.append(np.unique(raw.trias[ifc_trias[-1]].reshape(-1)))
            gids.append(None)
        else:
            loc_ids.append(np.zeros(0, np.int64))
            gids.append(None)

    if any(g is None and len(l) for g, l in zip(gids, loc_ids)):
        # derive shared numbering by exact coordinate matching
        coords = np.concatenate(
            [raws[s].verts[loc_ids[s]] for s in range(D)], axis=0
        )
        uniq, inv = np.unique(coords, axis=0, return_inverse=True)
        off = 0
        gids = []
        for s in range(D):
            n = len(loc_ids[s])
            gids.append(inv[off:off + n].astype(np.int64))
            off += n

    # uniform capacities
    def cap(n):
        return max(8, int(np.ceil(n * headroom)))

    pc = cap(max(len(r.verts) for r in raws))
    tc = cap(max(len(r.tets) for r in raws))
    fc = cap(max(len(r.trias) for r in raws))
    ec = cap(max(max(len(r.edges), 8) for r in raws))

    from ..io.medit import raw_to_mesh

    shards = []
    for s, raw in enumerate(raws):
        m = raw_to_mesh(
            raw, pcap=pc, tcap=tc, fcap=fc, ecap=ec,
            **({} if dtype is None else dict(dtype=dtype)),
        )
        vtag = np.asarray(m.vtag).copy()
        vtag[loc_ids[s]] |= tags.PARBDY
        vglob = np.full(pc, -1, np.int32)
        vglob[loc_ids[s]] = gids[s]
        trtag = np.asarray(m.trtag).copy()
        if len(ifc_trias[s]):
            ifc = ifc_trias[s]
            trtag[ifc] |= (
                tags.PARBDY | tags.REQUIRED | tags.NOSURF | tags.BDY
            )
            # a face-comm tria ALSO listed in RequiredTriangles is a
            # real-surface interface replica (PARBDYBDY discipline): the
            # checkpoint writer keeps those in RequiredTriangles and drops
            # the pure synthetic ones (io.medit.save_mesh)
            bb = np.isin(ifc, raw.req_trias)
            trtag[ifc[bb]] |= tags.PARBDYBDY
            # user-required interface replicas carry no NOSURF and are
            # therefore NOT in the face-comm list (split_mesh withholds
            # NOSURF when user_req): restore their PARBDY|PARBDYBDY|BDY
            # bookkeeping from the interface vertex set
            vtx_par = np.zeros(len(raw.verts), bool)
            vtx_par[loc_ids[s]] = True
            tria_np = raw.trias
            if len(tria_np):
                in_ifc = np.zeros(len(tria_np), bool)
                in_ifc[ifc] = True
                ureq = np.zeros(len(tria_np), bool)
                ureq[raw.req_trias] = True
                rep = ureq & ~in_ifc & vtx_par[tria_np].all(axis=1)
                trtag[np.nonzero(rep)[0]] |= (
                    tags.PARBDY | tags.PARBDYBDY | tags.BDY
                )
        m = m.replace(
            vtag=jnp.asarray(vtag),
            vglob=jnp.asarray(vglob),
            trtag=jnp.asarray(trtag),
        )
        from ..core.adjacency import build_adjacency

        shards.append(build_adjacency(m))
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
    # PARBDYBDY: interface vertices that also lie on the true boundary
    from ..ops.analysis import mark_boundary

    marked = [mark_boundary(m) for m in unstack_mesh(stacked)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *marked)
    both = (
        ((stacked.vtag & tags.PARBDY) != 0)
        & ((stacked.vtag & tags.BDY) != 0)
    )
    stacked = stacked.replace(
        vtag=jnp.where(both, stacked.vtag | tags.PARBDYBDY, stacked.vtag)
    )
    return stacked, rebuild_comm(stacked)


def unstack_mesh(stacked: Mesh) -> List[Mesh]:
    """Stacked [D,...] Mesh -> list of per-shard host Meshes."""
    d = stacked.vert.shape[0]
    return [
        jax.tree_util.tree_map(lambda a: a[s], stacked) for s in range(d)
    ]


def merge_shards(stacked: Mesh, comm: ShardComm) -> Mesh:
    """Gather all shards into one centralized host Mesh, deduplicating
    interface vertices by global id (the reference's
    `PMMG_merge_parmesh:1571` / `PMMG_mergeParmesh_rcvParMeshes` matched
    shared nodes via int-comm indices; global ids make this a plain
    scatter)."""
    parts = unstack_mesh(stacked)
    l2g = np.asarray(comm.l2g)
    vmask_all = np.asarray(stacked.vmask)
    if (vmask_all & (l2g < 0)).any():
        raise ValueError(
            "merge: live vertices without global ids — run "
            "assign_global_ids after remeshing"
        )
    # the gid space may have gaps (collapsed-away original vertices):
    # compress to dense output ids via the sorted set of live gids
    live_gids = np.unique(l2g[vmask_all])
    nglob = len(live_gids)
    vert = np.zeros((nglob, 3), np.asarray(parts[0].vert).dtype)
    vref = np.zeros(nglob, np.int32)
    vtag = np.zeros(nglob, np.int32)
    met = np.zeros((nglob, parts[0].met.shape[1]), vert.dtype)
    ls = np.zeros((nglob, parts[0].ls.shape[1]), vert.dtype)
    disp = np.zeros((nglob, parts[0].disp.shape[1]), vert.dtype)
    fields = np.zeros((nglob, parts[0].fields.shape[1]), vert.dtype)
    seen = np.zeros(nglob, bool)
    all_tets, all_trefs, all_trias, all_trrefs, all_trtags = [], [], [], [], []
    all_edges, all_edrefs, all_edtags = [], [], []
    for s, m in enumerate(parts):
        vm = np.asarray(m.vmask)
        # dense output id per local slot (-1 on dead slots)
        g = np.full(l2g.shape[1], -1, np.int64)
        g[vm] = np.searchsorted(live_gids, l2g[s][vm])
        valid = vm
        gi = g[valid]
        vert[gi] = np.asarray(m.vert)[valid]
        vref[gi] = np.asarray(m.vref)[valid]
        # drop the interface bookkeeping bits when centralizing
        vtag[gi] = np.asarray(m.vtag)[valid] & ~(
            tags.PARBDY | tags.PARBDYBDY | tags.OLDPARBDY
        )
        met[gi] = np.asarray(m.met)[valid]
        if ls.shape[1]:
            ls[gi] = np.asarray(m.ls)[valid]
        if disp.shape[1]:
            disp[gi] = np.asarray(m.disp)[valid]
        if fields.shape[1]:
            fields[gi] = np.asarray(m.fields)[valid]
        seen[gi] = True
        tm = np.asarray(m.tmask)
        all_tets.append(g[np.asarray(m.tet)[tm]])
        all_trefs.append(np.asarray(m.tref)[tm])
        # drop synthetic interface trias (PARBDY+NOSURF, not PARBDYBDY):
        # they are interior faces of the centralized mesh, not real
        # boundary. PARBDYBDY trias are REAL boundary replicated on both
        # sides — kept (and deduplicated below).
        trtag_s = np.asarray(m.trtag)
        fm = np.asarray(m.trmask) & ~tags.pure_interface_tria(trtag_s)
        tt = trtag_s[fm] & ~(tags.PARBDY | tags.PARBDYBDY)
        # REQUIRED that came with NOSURF was split-added (reference
        # MG_NOSURF convention): strip both, keep user-required intact
        syn = (tt & tags.NOSURF) != 0
        tt = np.where(syn, tt & ~(tags.REQUIRED | tags.NOSURF), tt)
        all_trias.append(g[np.asarray(m.tria)[fm]])
        all_trrefs.append(np.asarray(m.trref)[fm])
        all_trtags.append(tt)
        em = np.asarray(m.edmask)
        all_edges.append(g[np.asarray(m.edge)[em]])
        all_edrefs.append(np.asarray(m.edref)[em])
        all_edtags.append(np.asarray(m.edtag)[em])
    if not seen.all():
        raise ValueError("merge: some global vertex ids were never filled")
    # dedup trias replicated into both side shards (PARBDYBDY discipline)
    trias_m = np.concatenate(all_trias)
    trrefs_m = np.concatenate(all_trrefs)
    trtags_m = np.concatenate(all_trtags)
    if len(trias_m):
        tk = np.sort(trias_m, axis=1)
        _, uniq = np.unique(tk, axis=0, return_index=True)
        trias_m, trrefs_m, trtags_m = (
            trias_m[uniq], trrefs_m[uniq], trtags_m[uniq]
        )
    edges = np.concatenate(all_edges) if all_edges else np.zeros((0, 2), int)
    # dedup replicated feature edges
    if len(edges):
        ek = np.sort(edges, axis=1)
        _, uniq = np.unique(ek, axis=0, return_index=True)
        edges = edges[uniq]
        edrefs = np.concatenate(all_edrefs)[uniq]
        edtags = np.concatenate(all_edtags)[uniq]
    else:
        edrefs = edtags = np.zeros(0, int)
    return Mesh.from_numpy(
        vert,
        np.concatenate(all_tets),
        vrefs=vref,
        vtags=vtag,
        trefs=np.concatenate(all_trefs),
        trias=trias_m,
        trrefs=trrefs_m,
        trtags=trtags_m,
        edges=edges,
        edrefs=edrefs,
        edtags=edtags,
        met=met if parts[0].met_set else None,
        ls=ls if ls.shape[1] else None,
        disp=disp if disp.shape[1] else None,
        fields=fields if fields.shape[1] else None,
        field_ncomp=parts[0].field_ncomp,
        dtype=parts[0].dtype,
    )
