"""Space-filling-curve mesh partitioning and locality renumbering.

TPU-native replacement for the reference's graph partitioning stack
(`src/metis_pmmg.c`: `PMMG_part_meshElts2metis:1271` builds a CSR tetra
adjacency graph and calls `METIS_PartGraphKway`; ParMetis variant at
`:1561`) and for the optional Scotch renumbering (`src/libparmmg1.c:468`):
tets are ordered by the Morton key of their barycenter and cut into
contiguous weighted ranges — one sort plus one prefix sum, fully
batched, no graph build. Balance weights play the role of the reference's
metric-aware vertex weights (`PMMG_computeWgt`, `src/metis_pmmg.c:280`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..core import sfc
from ..core.mesh import Mesh


def tet_morton_keys(mesh: Mesh) -> jax.Array:
    """[TC] int32 Morton key of each valid tet barycenter (dead slots get
    the max key so they sort last)."""
    bc = jnp.mean(mesh.vert[mesh.tet], axis=1)
    live = mesh.tmask
    lo = jnp.min(jnp.where(live[:, None], bc, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(live[:, None], bc, -jnp.inf), axis=0)
    keys = sfc.morton_keys(bc, lo, hi)
    return jnp.where(live, keys, jnp.int32(2**30))


@partial(jax.jit, static_argnames=("nparts",))
def sfc_partition(
    mesh: Mesh,
    nparts: int,
    weights: jax.Array | None = None,
) -> jax.Array:
    """[TC] int32 part id per tet (-1 for dead slots).

    Sorts tets along the Morton curve and cuts the weight prefix sum into
    `nparts` equal ranges — the SFC analog of METIS k-way with vertex
    weights. Contiguity along the curve gives compact (if not minimal-cut)
    interfaces.
    """
    keys = tet_morton_keys(mesh)
    w = jnp.where(
        mesh.tmask,
        jnp.ones(mesh.tcap, jnp.float32) if weights is None else weights,
        0.0,
    )
    order = jnp.argsort(keys).astype(jnp.int32)
    wsort = w[order]
    csum = jnp.cumsum(wsort)
    total = csum[-1]
    # part of sorted position i: how many cut points its mid-weight passes
    mid = csum - 0.5 * wsort
    part_sorted = jnp.clip(
        (mid * nparts / jnp.maximum(total, 1e-30)).astype(jnp.int32),
        0,
        nparts - 1,
    )
    part = jnp.zeros(mesh.tcap, jnp.int32).at[order].set(part_sorted)
    return jnp.where(mesh.tmask, part, -1)


def displace_partition(
    part: "np.ndarray",
    adja: "np.ndarray",
    tmask: "np.ndarray",
    nparts: int,
    round_id: int,
    layers: int = 2,
    min_elts: int = 8,
):
    """Advancing-front interface displacement (host-side, numpy).

    The partition-change role of the reference's
    `PMMG_part_moveInterfaces` (`src/moveinterfaces_pmmg.c:1306`): for
    `layers` rounds (reference default `PMMG_MVIFCS_NLAYERS=2`,
    `src/parmmg.h:227`), every tet face-adjacent to a higher-priority
    color adopts it, so winning colors grow a layer and every interface
    surface moves sideways — the band frozen during the previous remesh
    becomes interior. Priority is a FIXED deterministic permutation of
    the colors (seeded by `round_id`; the driver keeps it constant so
    fronts move monotonically — measured: the reference's
    bigger-group-wins rule (`PMMG_get_ifcDirection`,
    `src/moveinterfaces_pmmg.c:74-98`) oscillates at shard granularity
    because counts stay noise-level equal, re-freezing the same band;
    the reference tolerates that by re-splitting groups with Metis,
    machinery we replace with the driver's GRPS_RATIO re-cut guard). A
    color may not shrink below `min_elts` tets (the `nemin` floor,
    `src/moveinterfaces_pmmg.c:1343`).
    """
    import numpy as np

    part = np.asarray(part).copy()
    adja = np.asarray(adja)
    tmask = np.asarray(tmask)
    # fixed priority permutation (odd multiplier mod 2^16)
    prio = ((np.arange(nparts, dtype=np.int64) * 40503 + round_id * 25173)
            * 2654435761) % (1 << 16)
    nb = adja >> 2
    valid = (adja >= 0) & tmask[:, None]
    for _ in range(layers):
        nbcol = np.where(valid, part[np.maximum(nb, 0)], -1)
        nbprio = np.where(nbcol >= 0, prio[np.maximum(nbcol, 0)], -1)
        k = np.argmax(nbprio, axis=1)
        rows = np.arange(part.shape[0])
        bestprio = nbprio[rows, k]
        bestcol = nbcol[rows, k]
        own = np.where(tmask, part, 0)
        flip = tmask & (bestprio > prio[own]) & (bestcol >= 0)
        # don't let a color shrink below min_elts (empty-shard repair)
        counts = np.bincount(part[tmask], minlength=nparts)
        losses = np.bincount(
            part[flip], minlength=nparts
        )
        starved = (counts - losses) < min_elts
        flip &= ~starved[np.where(tmask, part, 0)]
        part = np.where(flip, bestcol, part)
    return part


def renumber_sfc(mesh: Mesh) -> Mesh:
    """Reorder valid tets along the Morton curve (cache-locality role of
    the reference's Scotch renumbering)."""
    keys = tet_morton_keys(mesh)
    order = jnp.argsort(keys).astype(jnp.int32)
    return mesh.replace(
        tet=mesh.tet[order],
        tref=mesh.tref[order],
        tmask=mesh.tmask[order],
        adja=jnp.full_like(mesh.adja, -1),  # stale after permutation
    )
