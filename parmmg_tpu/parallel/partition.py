"""Space-filling-curve mesh partitioning and locality renumbering.

TPU-native replacement for the reference's graph partitioning stack
(`src/metis_pmmg.c`: `PMMG_part_meshElts2metis:1271` builds a CSR tetra
adjacency graph and calls `METIS_PartGraphKway`; ParMetis variant at
`:1561`) and for the optional Scotch renumbering (`src/libparmmg1.c:468`):
tets are ordered by the Morton key of their barycenter and cut into
contiguous weighted ranges — one sort plus one prefix sum, fully
batched, no graph build. Balance weights play the role of the reference's
metric-aware vertex weights (`PMMG_computeWgt`, `src/metis_pmmg.c:280`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..core import sfc
from ..core.mesh import Mesh


def tet_morton_keys(mesh: Mesh) -> jax.Array:
    """[TC] int32 Morton key of each valid tet barycenter (dead slots get
    the max key so they sort last)."""
    bc = jnp.mean(mesh.vert[mesh.tet], axis=1)
    live = mesh.tmask
    lo = jnp.min(jnp.where(live[:, None], bc, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(live[:, None], bc, -jnp.inf), axis=0)
    keys = sfc.morton_keys(bc, lo, hi)
    return jnp.where(live, keys, jnp.int32(2**30))


def metric_weights(mesh: Mesh) -> jax.Array:
    """[TC] predicted output-element count per tet under the current
    metric — the balance weight proportional to the number of elements
    to be *created* (the `PMMG_computeWgt` role, reference
    `src/metis_pmmg.c:280`): vol(t)·sqrt(det M) is the integrand of
    `estimate_target_ntet`. Cutting on these weights keeps the partition
    balanced AFTER the splits a localized-refinement metric will cause,
    not just before. A floor keeps zero-density regions from collapsing
    onto one shard."""
    from ..core import metric as metric_mod
    from ..core.mesh import tet_volumes

    vol = jnp.abs(tet_volumes(mesh))
    dens = metric_mod.metric_det(mesh.met)
    dens_t = jnp.mean(jnp.sqrt(jnp.maximum(dens[mesh.tet], 0.0)), axis=1)
    w = (vol * dens_t).astype(jnp.float32)
    mean_w = jnp.sum(jnp.where(mesh.tmask, w, 0.0)) / jnp.maximum(
        jnp.sum(mesh.tmask.astype(jnp.float32)), 1.0
    )
    w = jnp.maximum(w, 1e-3 * jnp.maximum(mean_w, 1e-30))
    return jnp.where(mesh.tmask, w, 0.0)


# parmmg-lint: disable=PML005 -- returns partition labels; mesh reused by split/migration
@partial(jax.jit, static_argnames=("nparts",))
def sfc_partition(
    mesh: Mesh,
    nparts: int,
    weights: jax.Array | None = None,
) -> jax.Array:
    """[TC] int32 part id per tet (-1 for dead slots).

    Sorts tets along the Morton curve and cuts the weight prefix sum into
    `nparts` equal ranges — the SFC analog of METIS k-way with vertex
    weights. Contiguity along the curve gives compact (if not minimal-cut)
    interfaces.
    """
    keys = tet_morton_keys(mesh)
    w = jnp.where(
        mesh.tmask,
        jnp.ones(mesh.tcap, jnp.float32) if weights is None else weights,
        0.0,
    )
    order = jnp.argsort(keys).astype(jnp.int32)
    wsort = w[order]
    csum = jnp.cumsum(wsort)
    total = csum[-1]
    # part of sorted position i: how many cut points its mid-weight passes
    mid = csum - 0.5 * wsort
    part_sorted = jnp.clip(
        (mid * nparts / jnp.maximum(total, 1e-30)).astype(jnp.int32),
        0,
        nparts - 1,
    )
    part = jnp.zeros(mesh.tcap, jnp.int32).at[order].set(part_sorted)
    return jnp.where(mesh.tmask, part, -1)


# parmmg-lint: disable=PML005 -- returns partition labels; mesh reused by split/migration
@partial(jax.jit, static_argnames=("nparts", "nbuckets"))
def stacked_graph_colors(
    stacked: Mesh,
    nparts: int,
    nbuckets: int = 4096,
) -> jax.Array:
    """[D, TC] target-shard color per tet from a GLOBAL weighted SFC cut
    computed WITHOUT centralizing the mesh — the graph-balancing
    redistribution mode (reference `PMMG_REDISTRIBUTION_graph_balancing`,
    `src/libparmmgtypes.h:173-178`, dispatched at
    `src/distributegrps_pmmg.c:2055`; metis computes a fresh k-way cut of
    the group graph there, here the weighted Morton cut plays that role
    as everywhere else in this framework).

    Device-side reduction shape: per-shard bucket histograms of Morton
    keys (a [D, B] scatter-add), summed over the shard axis, prefix-
    summed, and cut into `nparts` equal weight ranges — every shard then
    reads its tets' target part from the shared [B] bucket→part table.
    Balance granularity is one bucket (~ntet/nbuckets tets); interfaces
    stay compact because buckets are contiguous Morton ranges. The
    result feeds the same fixed-slot `migrate` path as interface
    displacement — the mesh never touches the host."""
    D, TC = stacked.tet.shape[:2]
    live = stacked.tmask
    # global bbox over all shards (all keys must share one frame)
    bc = jax.vmap(lambda m: jnp.mean(m.vert[m.tet], axis=1))(stacked)
    lo = jnp.min(jnp.where(live[..., None], bc, jnp.inf), axis=(0, 1))
    hi = jnp.max(jnp.where(live[..., None], bc, -jnp.inf), axis=(0, 1))
    keys = jax.vmap(lambda b: sfc.morton_keys(b, lo, hi))(bc)  # [D,TC]
    # morton_keys yields 3*10-bit keys in [0, 2^30)
    bucket = jnp.clip(keys >> (30 - nbuckets.bit_length() + 1),
                      0, nbuckets - 1)
    w = jax.vmap(metric_weights)(stacked)
    hist = jnp.zeros((nbuckets,), jnp.float32)
    hist = hist.at[bucket.reshape(-1)].add(
        jnp.where(live, w, 0.0).reshape(-1)
    )
    csum = jnp.cumsum(hist)
    total = csum[-1]
    mid = csum - 0.5 * hist
    part_of_bucket = jnp.clip(
        (mid * nparts / jnp.maximum(total, 1e-30)).astype(jnp.int32),
        0, nparts - 1,
    )
    color = part_of_bucket[bucket]
    return jnp.where(live, color, -1)


def renumber_sfc(mesh: Mesh) -> Mesh:
    """Reorder valid tets along the Morton curve (cache-locality role of
    the reference's Scotch renumbering)."""
    keys = tet_morton_keys(mesh)
    order = jnp.argsort(keys).astype(jnp.int32)
    return mesh.replace(
        tet=mesh.tet[order],
        tref=mesh.tref[order],
        tmask=mesh.tmask[order],
        adja=jnp.full_like(mesh.adja, -1),  # stale after permutation
    )
