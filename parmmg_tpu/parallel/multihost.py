"""Multi-host (multi-process) runtime — the DCN axis of the scaling
story.

The reference scales across nodes with its MPI world (`mpirun -np N
parmmg`; every entry point takes the communicator, e.g.
`PMMG_Init_parMesh(PMMG_ARG_MPIComm, ...)` in `src/libparmmg.c`). The
tpu-native equivalent is JAX's multi-controller runtime: each host
process calls `jax.distributed.initialize`, after which `jax.devices()`
returns the GLOBAL device list and every `shard_map` collective in
`parallel/comm.py` / `parallel/migrate.py` transparently crosses the
process boundary (ICI within a slice, DCN between slices — XLA picks
the transport; no NCCL/MPI calls to port).

Single-process runs are unaffected: `init_from_env()` is a no-op unless
the coordination env vars are present, and `device_mesh()` already lays
shards over whatever `jax.devices()` returns — local chips or a
multi-host fleet.

Env contract (mirrors `mpirun`'s rank/world interface):
  PMMGTPU_COORDINATOR  host:port of process 0 (e.g. "10.0.0.1:9876")
  PMMGTPU_NUM_PROCS    world size
  PMMGTPU_PROC_ID      this process's rank, 0-based

On TPU pods with the standard runtime metadata (GCE/Cloud TPU), plain
`jax.distributed.initialize()` auto-discovers all three — set
PMMGTPU_COORDINATOR=auto to use that path.
"""

from __future__ import annotations

import os

import jax
import numpy as np

_INITIALIZED = False


def init_from_env() -> bool:
    """Initialize the multi-controller runtime from the env contract.

    Returns True when running multi-process (after initialization),
    False for plain single-process runs. Idempotent."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("PMMGTPU_COORDINATOR")
    if not coord:
        return False
    if coord == "auto":
        jax.distributed.initialize()
    else:
        nprocs = os.environ.get("PMMGTPU_NUM_PROCS")
        pid = os.environ.get("PMMGTPU_PROC_ID")
        if nprocs is None or pid is None:
            raise RuntimeError(
                "multi-host env contract incomplete: "
                f"PMMGTPU_COORDINATOR={coord!r} requires "
                "PMMGTPU_NUM_PROCS (world size) and PMMGTPU_PROC_ID "
                "(0-based rank) to be set as well"
            )
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nprocs),
            process_id=int(pid),
        )
    _INITIALIZED = True
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def put_sharded_global(tree, dmesh):
    """Place a host-resident stacked [D,...] pytree onto a device mesh
    that may span processes.

    Single-process `put_sharded` uses `jax.device_put`, which requires
    an addressable sharding; across processes each controller owns only
    its local shards, so every process passes the SAME full global
    array (host phases are replicated-deterministic here — see
    `models/distributed.py` module docstring) and the callback hands
    each addressable device its global slice. NOT
    `make_array_from_process_local_data`: that API interprets its
    argument as this process's LOCAL rows, so passing the full array
    silently double-counts shards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .shard import AXIS

    sh = NamedSharding(dmesh, P(AXIS))

    def put(a):
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])

    return jax.tree_util.tree_map(put, tree)


def gather_stacked(tree):
    """Fetch a (possibly cross-process) stacked pytree to host numpy on
    every process — the allgather that feeds the replicated host phases
    (retag/analysis exchanges). Within one process this is a plain
    device_get."""
    if not is_multiprocess():
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def fetch(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            # replicates the global value on every process
            return np.asarray(
                multihost_utils.process_allgather(a, tiled=True)
            )
        # host numpy / fully-addressable leaves are already whole;
        # process_allgather would CONCATENATE the per-process copies
        # (doubling dim 0) instead of replicating
        return np.asarray(jax.device_get(a))

    return jax.tree_util.tree_map(fetch, tree)
