"""Multi-host (multi-process) runtime — the DCN axis of the scaling
story.

The reference scales across nodes with its MPI world (`mpirun -np N
parmmg`; every entry point takes the communicator, e.g.
`PMMG_Init_parMesh(PMMG_ARG_MPIComm, ...)` in `src/libparmmg.c`). The
tpu-native equivalent is JAX's multi-controller runtime: each host
process calls `jax.distributed.initialize`, after which `jax.devices()`
returns the GLOBAL device list and every `shard_map` collective in
`parallel/comm.py` / `parallel/migrate.py` transparently crosses the
process boundary (ICI within a slice, DCN between slices — XLA picks
the transport; no NCCL/MPI calls to port).

Single-process runs are unaffected: `init_from_env()` is a no-op unless
the coordination env vars are present, and `device_mesh()` already lays
shards over whatever `jax.devices()` returns — local chips or a
multi-host fleet.

Env contract (mirrors `mpirun`'s rank/world interface):
  PMMGTPU_COORDINATOR  host:port of process 0 (e.g. "10.0.0.1:9876")
  PMMGTPU_NUM_PROCS    world size
  PMMGTPU_PROC_ID      this process's rank, 0-based

On TPU pods with the standard runtime metadata (GCE/Cloud TPU), plain
`jax.distributed.initialize()` auto-discovers all three — set
PMMGTPU_COORDINATOR=auto to use that path.

Failure surface: `barrier()` is the coordination point the sharded
checkpointer commits through (the role of the reference's
`MPI_Barrier` around its per-rank I/O), and `run_with_watchdog()`
bounds every such collective so a silently dead peer becomes a typed
`failsafe.PeerLostError` instead of an indefinite hang — the MPI
analog is a communicator error handler, which plain collectives on a
lost TCP peer never deliver."""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

import jax
import numpy as np

from ..lint import contracts as lint_contracts
from ..obs import metrics as obs_metrics, trace as obs_trace


class MultihostConfigError(RuntimeError):
    """The PMMGTPU_* multi-host env contract is malformed (non-integer
    or out-of-range rank/world). Raised BEFORE
    `jax.distributed.initialize`, which would otherwise block forever
    waiting for a world that can never assemble (a rank >= world size
    means some expected rank never dials in)."""


_INITIALIZED = False


def init_from_env() -> bool:
    """Initialize the multi-controller runtime from the env contract.

    Returns True when running multi-process (after initialization),
    False for plain single-process runs. Idempotent. A malformed
    rank/world raises :class:`MultihostConfigError` up front instead of
    letting the coordination handshake hang."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("PMMGTPU_COORDINATOR")
    if not coord:
        return False
    if coord == "auto":
        _arm_cpu_collectives()
        jax.distributed.initialize()
    else:
        nprocs = os.environ.get("PMMGTPU_NUM_PROCS")
        pid = os.environ.get("PMMGTPU_PROC_ID")
        if nprocs is None or pid is None:
            raise MultihostConfigError(
                "multi-host env contract incomplete: "
                f"PMMGTPU_COORDINATOR={coord!r} requires "
                "PMMGTPU_NUM_PROCS (world size) and PMMGTPU_PROC_ID "
                "(0-based rank) to be set as well"
            )
        try:
            world = int(nprocs)
            rank = int(pid)
        except ValueError as e:
            raise MultihostConfigError(
                f"PMMGTPU_NUM_PROCS={nprocs!r} / PMMGTPU_PROC_ID={pid!r} "
                "must be integers"
            ) from e
        if world <= 0:
            raise MultihostConfigError(
                f"PMMGTPU_NUM_PROCS={world} must be positive"
            )
        if not 0 <= rank < world:
            raise MultihostConfigError(
                f"PMMGTPU_PROC_ID={rank} out of range for "
                f"PMMGTPU_NUM_PROCS={world} (want 0 <= rank < world; "
                "jax.distributed.initialize would hang on this)"
            )
        _arm_cpu_collectives()
        _initialize_resilient(coord, world, rank)
    _INITIALIZED = True
    return True


def _arm_cpu_collectives() -> None:
    """A multi-process world that lands on the CPU backend (the
    2-process CI harness, host fallbacks) needs a cross-process
    collectives implementation: the default CPU client rejects every
    multiprocess computation outright ("Multiprocess computations
    aren't implemented on the CPU backend"). Must run before the
    backend exists — `init_from_env` is pre-backend by contract
    (package __init__ hook). Harmless for TPU/GPU runs (the flag only
    affects CPU client construction)."""
    try:
        from jax._src import xla_bridge as _xb

        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value == "none":
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
    except (ImportError, AttributeError):
        pass  # jax without the flag: nothing to arm


# set by the distributed client's missed-heartbeat callback: a peer
# stopped responding (or the coordination service reported a dead
# task). `barrier()` checks it so a peer loss surfaces as a typed
# PeerLostError at the next phase boundary instead of the default
# behavior — jaxlib's callback LOG(QFATAL)s the SURVIVING process,
# which would turn one preempted worker into a whole-job crash with no
# chance to run the checkpoint-backed exit path.
_PEER_LOSS = threading.Event()
_PEER_LOSS_STATUS: list = []


def peer_loss_detected() -> bool:
    return _PEER_LOSS.is_set()


def _on_peer_loss(status) -> None:
    # called from a runtime thread: only record; raising here would be
    # lost (and must not run Python teardown on a foreign thread). The
    # timeline event is the post-mortem's detection record — the
    # tracer's per-line flush makes it durable even if the survivor
    # dies moments later.
    _PEER_LOSS_STATUS.append(str(status))
    if not _PEER_LOSS.is_set():
        obs_trace.emit_event("peer_lost", status=str(status))
    _PEER_LOSS.set()


def simulate_peer_loss(reason: str = "") -> None:
    """Inject a coordination-service peer-death report into THIS
    process (the chaos harness's ``peer-lost`` fault kind): the next
    `barrier`/heartbeat raises the typed ``failsafe.PeerLostError``
    exactly as if the runtime's missed-heartbeat callback had fired —
    the survivor-side detection path, without needing a peer to
    actually die."""
    _on_peer_loss(reason or "injected peer loss")


def clear_peer_loss() -> None:
    """Reset the latched peer-loss report (tests only — in a real
    world a lost peer stays lost until checkpoint-backed restart)."""
    _PEER_LOSS.clear()
    _PEER_LOSS_STATUS.clear()


def _initialize_resilient(coord: str, world: int, rank: int) -> None:
    """`jax.distributed.initialize` with a survivable peer-loss path.

    Identical to the stock initialization (service on rank 0, client
    everywhere, preemption sync manager) except the client's
    ``missed_heartbeat_callback`` records the failure instead of the
    default LOG(QFATAL) process termination — the failsafe layer, not
    the runtime, decides how a survivor dies (checkpoint-backed
    PeerLostError exit). Falls back to the stock path on jax builds
    whose client factory lacks the callback parameter."""
    from jax._src import distributed as jdist

    try:
        from jax._src.lib import xla_extension as xe
    except ImportError:  # pragma: no cover - very old/new layouts
        xe = None
    state = jdist.global_state
    if state.client is not None:  # already initialized elsewhere
        return
    try:
        if xe is None:
            raise TypeError("no xla_extension")
        if rank == 0:
            bind = "[::]:" + coord.rsplit(":", 1)[1]
            state.service = xe.get_distributed_runtime_service(
                bind, world,
            )
        client = xe.get_distributed_runtime_client(
            coord, rank,
            init_timeout=300,
            missed_heartbeat_callback=_on_peer_loss,
            shutdown_on_destruction=True,
            use_compression=True,
        )
        client.connect()
        state.client = client
        state.process_id = rank
        state.num_processes = world
        state.coordinator_address = coord
        try:
            state.initialize_preemption_sync_manager()
        except Exception:
            pass  # optional (TPU preemption notices); not load-bearing
    except TypeError:
        # client factory without the callback kwarg: stock init (peer
        # loss then terminates the survivor — documented degradation)
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world,
            process_id=rank,
        )


def is_multiprocess() -> bool:
    return jax.process_count() > 1


# ---------------------------------------------------------------------------
# proactive preemption notices (pod-level maintenance events)
# ---------------------------------------------------------------------------
# SIGTERM is the LAST word a platform says before killing a worker; most
# platforms say an earlier, softer one — Cloud TPU/GCE publish a
# maintenance-event metadata entry, batch schedulers touch a drain file.
# The failsafe harness polls this between iterations and checkpoints out
# of cadence while the notice stands, so the eventual SIGTERM finds the
# state already durable. Three sources, any of which arms the notice:
#  - request_preemption_notice(): programmatic (the injected
#    ``preempt-notice`` fault kind, platform glue code);
#  - set_preemption_callback(cb): a zero-arg callable polled lazily
#    (e.g. a metadata-server probe) — returning truthy latches the
#    notice;
#  - PMMGTPU_PREEMPT_FILE: a path whose existence signals the event
#    (the drain-file convention; cheap enough to stat every iteration).

_PREEMPT_NOTICE = threading.Event()
_PREEMPT_NOTICE_REASON: list = []
_PREEMPT_CB = None
# True when the latch came from an EXPLICIT request (platform glue, the
# injected ``preempt-notice`` fault): those never un-happen on their
# own. A latch from a polled source (callback probe, drain file) is
# re-verified on every poll — a cancelled maintenance event (probe went
# quiet, drain file removed) must stop forcing out-of-cadence
# checkpoints instead of staying latched for the rest of the run.
_PREEMPT_STICKY: list = []


def request_preemption_notice(reason: str = "") -> None:
    """Latch a pending preemption notice (idempotent, sticky — only
    :func:`clear_preemption_notice` resets an explicit request)."""
    _latch_preempt_notice(reason, sticky=True)


def _latch_preempt_notice(reason: str, sticky: bool) -> None:
    if not _PREEMPT_NOTICE.is_set():
        obs_trace.emit_event("preempt_notice", reason=reason)
    if reason:
        _PREEMPT_NOTICE_REASON.append(reason)
    if sticky:
        _PREEMPT_STICKY.append(reason or "requested")
    _PREEMPT_NOTICE.set()


def clear_preemption_notice(reason: str = "") -> None:
    """Reset the latched notice (a cancelled maintenance event, tests).
    A standing notice leaves a ``preempt_notice_cleared`` record in the
    obs timeline — the post-mortem must show WHY a run armed, then
    stopped, forcing per-iteration commits."""
    if _PREEMPT_NOTICE.is_set():
        obs_trace.emit_event("preempt_notice_cleared", reason=reason)
    _PREEMPT_NOTICE.clear()
    _PREEMPT_NOTICE_REASON.clear()
    _PREEMPT_STICKY.clear()


def set_preemption_callback(cb) -> None:
    """Install (or with None, remove) the lazily-polled maintenance
    probe. The callback must be cheap and non-blocking — it runs on the
    driver thread between iterations."""
    global _PREEMPT_CB
    _PREEMPT_CB = cb


def preemption_notice() -> bool:
    """True while a preemption notice stands: an explicit request, a
    truthy callback probe, or the PMMGTPU_PREEMPT_FILE drain file.
    Polled-source latches are re-verified here — when the probe goes
    quiet AND the drain file is gone AND no explicit request stands,
    the latch auto-clears (with a ``preempt_notice_cleared`` event) so
    a cancelled maintenance event stops forcing out-of-cadence
    checkpoints."""
    live = False
    if _PREEMPT_CB is not None and _PREEMPT_CB():
        _latch_preempt_notice("preemption callback fired", sticky=False)
        live = True
    path = os.environ.get("PMMGTPU_PREEMPT_FILE")
    if not live and path and os.path.exists(path):
        _latch_preempt_notice(f"drain file {path} present", sticky=False)
        live = True
    if live:
        return True
    if _PREEMPT_NOTICE.is_set():
        if _PREEMPT_STICKY:
            return True
        clear_preemption_notice("polled source no longer reports the "
                                "maintenance event")
    return False


# ---------------------------------------------------------------------------
# capacity-restored signals (the grow half of elastic autoscaling)
# ---------------------------------------------------------------------------
# Symmetric to the preemption-notice sources above: a platform that can
# take capacity away can also give it back (a spot pool refilling, a
# maintenance window ending). Three sources, any of which arms the
# signal; `parallel.elastic` polls it between iterations and — when the
# current world runs below its target size — turns it into a
# world-grow reformation, the same checkpoint-backed transition a
# notice-driven shrink takes in the other direction.

_CAPACITY_SIGNAL = threading.Event()
_CAPACITY_REASON: list = []
_CAPACITY_CB = None
_CAPACITY_STICKY: list = []


def request_capacity_restored(reason: str = "") -> None:
    """Latch a capacity-restored signal (idempotent, sticky)."""
    _latch_capacity(reason, sticky=True)


def _latch_capacity(reason: str, sticky: bool) -> None:
    if not _CAPACITY_SIGNAL.is_set():
        obs_trace.emit_event("capacity_restored", reason=reason)
    if reason:
        _CAPACITY_REASON.append(reason)
    if sticky:
        _CAPACITY_STICKY.append(reason or "requested")
    _CAPACITY_SIGNAL.set()


def clear_capacity_signal(reason: str = "") -> None:
    """Reset the latched capacity signal (tests; capacity withdrawn
    again before the grow could happen)."""
    if _CAPACITY_SIGNAL.is_set():
        obs_trace.emit_event("capacity_signal_cleared", reason=reason)
    _CAPACITY_SIGNAL.clear()
    _CAPACITY_REASON.clear()
    _CAPACITY_STICKY.clear()


def set_capacity_callback(cb) -> None:
    """Install (or with None, remove) the lazily-polled capacity probe
    (e.g. a pool-inventory query). Cheap and non-blocking, like the
    preemption probe."""
    global _CAPACITY_CB
    _CAPACITY_CB = cb


def capacity_restored() -> bool:
    """True while a capacity-restored signal stands: explicit request,
    truthy callback probe, or the PMMGTPU_CAPACITY_FILE marker file.
    Polled-source latches auto-clear when every source goes quiet,
    mirroring :func:`preemption_notice`."""
    live = False
    if _CAPACITY_CB is not None and _CAPACITY_CB():
        _latch_capacity("capacity callback fired", sticky=False)
        live = True
    path = os.environ.get("PMMGTPU_CAPACITY_FILE")
    if not live and path and os.path.exists(path):
        _latch_capacity(f"capacity file {path} present", sticky=False)
        live = True
    if live:
        return True
    if _CAPACITY_SIGNAL.is_set():
        if _CAPACITY_STICKY:
            return True
        clear_capacity_signal("polled source no longer reports "
                              "restored capacity")
    return False


def run_with_watchdog(fn, tag: str = "collective",
                      timeout: float | None = None):
    """Run `fn` (a blocking collective) under a liveness watchdog.

    `timeout=None` runs `fn` inline (no thread, no overhead). With a
    timeout, `fn` runs in a daemon worker thread; if it has not
    completed within `timeout` seconds, a `failsafe.PeerLostError` is
    raised in the caller — converting the silent hang of a collective
    whose peer died (killed worker, preempted pod slice) into a typed,
    catchable failure. The stuck worker thread cannot be cancelled; the
    expected reaction to PeerLostError is checkpoint-backed process
    exit, which reaps it."""
    if timeout is None:
        return fn()
    import time

    box: dict = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as e:  # re-raised on the waiting side
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(
        target=_run, name=f"parmmg-watchdog:{tag}", daemon=True
    )
    t.start()
    deadline = time.monotonic() + timeout
    while True:
        if done.wait(min(1.0, max(deadline - time.monotonic(), 0.01))):
            break
        from ..failsafe import PeerLostError

        if _PEER_LOSS.is_set():
            # the runtime's heartbeat tracking confirmed the loss —
            # no point waiting out the rest of the window
            raise PeerLostError(
                f"collective '{tag}' abandoned: the coordination "
                "service reports a dead peer "
                f"({_PEER_LOSS_STATUS[-1] if _PEER_LOSS_STATUS else ''})"
            )
        if time.monotonic() >= deadline:
            raise PeerLostError(
                f"collective '{tag}' did not complete within "
                f"{timeout:.1f}s (world size {jax.process_count()}, "
                f"rank {jax.process_index()}) — a peer process is "
                "unreachable; restart and resume from the last "
                "checkpoint"
            )
    if "error" in box:
        raise box["error"]
    return box.get("value")


# per-name collective sequence numbers: collectives are dispatched in
# the same order by every process (the whole coordination layer depends
# on that), so the nth `coll:<name>` span on each rank is the SAME
# world instance — the matching key `obs.dist` uses to decompose a
# collective into straggler lag vs true transfer time across ranks
_COLL_SEQ: dict = {}
# accumulated seconds THIS rank spent blocked inside coordination
# collectives (always-on, like the comm/* counters); the per-rank
# `comm/wait_s` gauge survives the world merge as a per-rank map
_COLL_WAIT = [0.0]


@contextmanager
def _coll_span(name: str, tag: str):
    """Paired enter/exit attribution around one collective dispatch.

    Traced runs get a ``coll:<name>`` span carrying the per-name
    sequence number; untraced runs still pay two clock reads to keep
    the `comm/wait_s` gauge honest. Host-side coordination code — the
    clocks here never sit under a jitted region."""
    seq = _COLL_SEQ.get(name, 0)
    _COLL_SEQ[name] = seq + 1
    # collective-lockstep ledger (validate="full"): every dispatch
    # rolls into the per-rank schedule hash that
    # `lint.contracts.verify_ledger` world-compares at phase
    # boundaries — a single None-check when the ledger is not armed
    lint_contracts.record_collective(name, seq, tag)
    tr = obs_trace.get_tracer()
    t0 = time.perf_counter()
    try:
        if tr.enabled:
            with tr.span(f"coll:{name}", tag=tag, seq=seq):
                yield
        else:
            yield
    finally:
        _COLL_WAIT[0] += time.perf_counter() - t0
        obs_metrics.registry().gauge("comm/wait_s").set(_COLL_WAIT[0])


def _barrier_fn():
    """One compiled psum-of-ones over ALL global devices — the barrier
    collective. Built lazily and memoized on first use (rebuilding
    jit(shard_map) per barrier would retrace every call, parmmg-lint
    PML004). A psum via shard_map is the ONE collective path every
    backend this repo runs on supports (`multihost_utils`'
    pmap-based sync is rejected by the multi-process CPU runtime the
    2-process tests use)."""
    global _BARRIER
    if _BARRIER is not None:
        return _BARRIER
    import jax.numpy as jnp
    from jax.sharding import (
        Mesh as DeviceMesh, NamedSharding, PartitionSpec as P,
    )

    devs = jax.devices()
    dmesh = DeviceMesh(np.array(devs), ("procs",))
    sh = NamedSharding(dmesh, P("procs"))
    ones = np.ones(len(devs), np.int32)
    x = jax.make_array_from_callback(
        (len(devs),), sh, lambda idx: ones[idx]
    )

    def body(blk):
        return jax.lax.psum(jnp.sum(blk), "procs")

    # parmmg-lint: disable=PML004 -- built once, memoized in _BARRIER
    fn = jax.jit(jax.shard_map(
        body, mesh=dmesh, in_specs=(P("procs"),), out_specs=P()
    ))
    _BARRIER = (fn, x, len(devs))
    return _BARRIER


_BARRIER = None


def barrier(tag: str = "parmmg-barrier",
            timeout: float | None = None) -> None:
    """Coordination barrier across all processes (no-op single-process).

    A psum-of-ones over the global device mesh: the program cannot
    complete until every process has dispatched it, and its replicated
    result is fetched locally — so returning from here means every peer
    reached this point (the `MPI_Barrier` role around the reference's
    per-rank I/O). The sharded checkpointer brackets its two-phase
    commit with this (data barrier before the rank-0 manifest, commit
    barrier after), and the drivers use it as the phase-boundary
    heartbeat. `timeout` arms the :func:`run_with_watchdog` conversion
    of a lost peer into `failsafe.PeerLostError`; collective failures
    the coordination service surfaces on its own (peer disconnect RPC
    errors) are mapped to the same type."""
    if not is_multiprocess():
        return
    obs_metrics.registry().counter("comm/barriers").inc()
    from ..failsafe import PeerLostError

    if _PEER_LOSS.is_set():
        # the loss is already latched (runtime callback or an injected
        # report): dispatching the collective would just hang until
        # the watchdog window — and with no watchdog armed, forever
        raise PeerLostError(
            f"collective '{tag}' refused: a peer is already reported "
            "lost "
            f"({_PEER_LOSS_STATUS[-1] if _PEER_LOSS_STATUS else ''})"
        )

    def _sync():
        fn, x, ndev = _barrier_fn()
        got = int(jax.device_get(fn(x)))
        if got != ndev:
            raise RuntimeError(
                f"barrier psum returned {got}, want {ndev}"
            )

    try:
        with _coll_span("barrier", tag):
            run_with_watchdog(_sync, tag=tag, timeout=timeout)
    except PeerLostError:
        raise
    except Exception as e:
        # the coordination service noticed the dead peer before the
        # watchdog did (heartbeat/RPC errors surface as runtime
        # errors): same meaning, same typed failure
        raise PeerLostError(
            f"collective '{tag}' failed "
            f"(rank {jax.process_index()}): {e}"
        ) from e


def agree_flags(value: int, tag: str = "agree",
                timeout: float | None = None) -> int:
    """World-agreed bitwise-OR of one small non-negative int per
    process — the ``MPI_Allreduce(ier)`` role for control decisions
    that must be taken by EVERY process at the SAME boundary (the
    elastic reform vote: "someone is departing / a grow was
    requested"). Single-process this is the identity.

    Implemented as one psum over the global device mesh (each device
    carries its owner process's value, so the sum is
    ``sum_r value_r * local_device_count``; uniform local device
    counts make the per-process sum recoverable, and because callers
    pass disjoint bit flags the division yields their bitwise OR).
    Runs under the same peer-loss refusal + watchdog conversion as
    :func:`barrier` — a dead peer turns the vote into a typed
    `failsafe.PeerLostError` instead of a hang."""
    val = int(value)
    if not is_multiprocess():
        return val
    if val < 0:
        raise ValueError(f"agree_flags wants a non-negative int, got {val}")
    obs_metrics.registry().counter("comm/collectives").inc()
    from ..failsafe import PeerLostError

    if _PEER_LOSS.is_set():
        raise PeerLostError(
            f"collective '{tag}' refused: a peer is already reported "
            "lost "
            f"({_PEER_LOSS_STATUS[-1] if _PEER_LOSS_STATUS else ''})"
        )
    fn, sh, ndev = _agree_fn()
    nloc = jax.local_device_count()
    if ndev % jax.process_count() or nloc * jax.process_count() != ndev:
        raise RuntimeError(
            f"agree_flags needs uniform local device counts "
            f"({ndev} devices over {jax.process_count()} processes)"
        )

    def _cb(idx):
        sl = idx[0]
        lo = 0 if sl.start is None else sl.start
        hi = ndev if sl.stop is None else sl.stop
        return np.full((hi - lo,), val, np.int32)

    def _vote():
        x = jax.make_array_from_callback((ndev,), sh, _cb)
        return int(jax.device_get(fn(x)))

    try:
        with _coll_span("agree_flags", tag):
            total = run_with_watchdog(_vote, tag=tag, timeout=timeout)
    except PeerLostError:
        raise
    except Exception as e:
        raise PeerLostError(
            f"collective '{tag}' failed "
            f"(rank {jax.process_index()}): {e}"
        ) from e
    return total // nloc


_AGREE = None


def _agree_fn():
    """Memoized psum program + sharding for :func:`agree_flags`
    (rebuilding jit(shard_map) per vote would retrace every boundary,
    parmmg-lint PML004)."""
    global _AGREE
    if _AGREE is not None:
        return _AGREE
    import jax.numpy as jnp
    from jax.sharding import (
        Mesh as DeviceMesh, NamedSharding, PartitionSpec as P,
    )

    devs = jax.devices()
    dmesh = DeviceMesh(np.array(devs), ("procs",))
    sh = NamedSharding(dmesh, P("procs"))

    def body(blk):
        return jax.lax.psum(jnp.sum(blk), "procs")

    # parmmg-lint: disable=PML004 -- built once, memoized in _AGREE
    fn = jax.jit(jax.shard_map(
        body, mesh=dmesh, in_specs=(P("procs"),), out_specs=P()
    ))
    _AGREE = (fn, sh, len(devs))
    return _AGREE


_TSX = None


def _tsx_fn():
    """Memoized timestamp-allgather for :func:`estimate_clock_offset`:
    one psum over a ``[ndev, nprocs]`` float64 one-hot (each device
    carries its owner's timestamp at its owner's column), so every
    process reads back every rank's clock sample in one collective.
    float64 µs keeps sub-µs precision out to ~decades of uptime (the
    drivers run under jax_enable_x64; without it the estimate degrades
    to float32 and the reported err_us says so)."""
    global _TSX
    if _TSX is not None:
        return _TSX
    import jax.numpy as jnp
    from jax.sharding import (
        Mesh as DeviceMesh, NamedSharding, PartitionSpec as P,
    )

    devs = jax.devices()
    nproc = jax.process_count()
    dmesh = DeviceMesh(np.array(devs), ("procs",))
    sh = NamedSharding(dmesh, P("procs"))

    def body(blk):
        return jax.lax.psum(jnp.sum(blk, axis=0), "procs")

    # parmmg-lint: disable=PML004 -- built once, memoized in _TSX
    fn = jax.jit(jax.shard_map(
        body, mesh=dmesh, in_specs=(P("procs"),), out_specs=P()
    ))
    _TSX = (fn, sh, len(devs), nproc)
    return _TSX


def _exchange_timestamps(value_us: float,
                         timeout: float | None = None) -> np.ndarray:
    """All ranks' ``value_us`` samples (µs, local monotonic clocks),
    indexed by process rank — one watchdogged psum round."""
    fn, sh, ndev, nproc = _tsx_fn()
    nloc = jax.local_device_count()
    rank = jax.process_index()

    def _cb(idx):
        sl = idx[0]
        lo = 0 if sl.start is None else sl.start
        hi = ndev if sl.stop is None else sl.stop
        block = np.zeros((hi - lo, nproc), np.float64)
        block[:, rank] = value_us
        return block

    def _round():
        x = jax.make_array_from_callback((ndev, nproc), sh, _cb)
        return np.asarray(jax.device_get(fn(x)), np.float64) / nloc

    return run_with_watchdog(_round, tag="clock_sync", timeout=timeout)


def estimate_clock_offset(rounds: int = 5,
                          timeout: float | None = None):
    """Median-of-K offset (µs) from THIS rank's monotonic clock to
    rank 0's, plus a spread-based error bound: ``(offset_us, err_us)``.

    Protocol: K+1 timestamp-psum rounds. Every rank exits a psum at
    (nearly) the same instant — the collective cannot complete until
    every rank contributed — so round ``k`` exchanges each rank's
    EXIT timestamp of round ``k-1`` and each sample of the offset is
    ``exit_us[rank0] - exit_us[me]`` for one shared exit instant. The
    median over K rounds rejects stragglers (a rank descheduled across
    one exit); the error bound is the median absolute deviation. Rank 0
    measures exactly 0 by construction. Single-process: ``(0.0, 0.0)``
    without touching the device."""
    if not is_multiprocess():
        return 0.0, 0.0
    from ..failsafe import PeerLostError

    if _PEER_LOSS.is_set():
        raise PeerLostError(
            "clock_sync refused: a peer is already reported lost "
            f"({_PEER_LOSS_STATUS[-1] if _PEER_LOSS_STATUS else ''})"
        )
    obs_metrics.registry().counter("comm/collectives").inc()
    samples = []
    prev_exit = time.perf_counter_ns() / 1e3
    for _ in range(max(int(rounds), 1) + 1):
        vec = _exchange_timestamps(prev_exit, timeout=timeout)
        samples.append(float(vec[0]) - prev_exit)
        prev_exit = time.perf_counter_ns() / 1e3
    # the first exchange carried ENTRY timestamps (no shared exit
    # instant behind them yet) — drop it, keep the K exit-anchored ones
    offs = np.asarray(samples[1:], np.float64)
    off = float(np.median(offs))
    err = float(np.median(np.abs(offs - off)))
    return off, err


def sync_tracer_clock(tracer=None, rounds: int = 5,
                      timeout: float | None = None) -> float:
    """Estimate this rank's clock offset to rank 0 and persist it into
    the active tracer's JSONL clock header (`obs.dist` applies it when
    merging rank timelines onto one timebase). No-op when tracing is
    disabled; writes an exact-zero offset single-process — which still
    marks the segment as aligned, the contract resumed runs rely on.
    MUST be called at the same point on every process (it is a
    collective)."""
    tr = tracer if tracer is not None else obs_trace.get_tracer()
    if not tr.enabled:
        # keep the collective schedule identical whether or not a rank
        # traces: all current callers trace on every rank or none, but
        # a lopsided config must not desync the world
        if is_multiprocess():
            off, _err = estimate_clock_offset(rounds=rounds,
                                              timeout=timeout)
            return off
        return 0.0
    off, err = estimate_clock_offset(rounds=rounds, timeout=timeout)
    tr.set_clock_offset(off, err_us=err, rounds=int(rounds))
    return off


def put_sharded_global(tree, dmesh):
    """Place a host-resident stacked [D,...] pytree onto a device mesh
    that may span processes.

    Single-process `put_sharded` uses `jax.device_put`, which requires
    an addressable sharding; across processes each controller owns only
    its local shards, so every process passes the SAME full global
    array (host phases are replicated-deterministic here — see
    `models/distributed.py` module docstring) and the callback hands
    each addressable device its global slice. NOT
    `make_array_from_process_local_data`: that API interprets its
    argument as this process's LOCAL rows, so passing the full array
    silently double-counts shards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .shard import AXIS

    sh = NamedSharding(dmesh, P(AXIS))

    def put(a):
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])

    return jax.tree_util.tree_map(put, tree)


def put_sharded_local_rows(tree, dmesh):
    """Inverse orientation of `put_sharded_global`: build the globally
    sharded stacked [D,...] pytree from THIS process's shard rows only.

    Each leaf is an [n_owned, ...] stack of the rows this process
    computed, in ascending shard order (`shard.owned_shards`) — exactly
    the layout `jax.make_array_from_process_local_data` expects for a
    1-D `P(AXIS)` sharding, whose addressable shards it walks in the
    same device order. This is the assembly step of the shard-local
    unfused dispatch (models/distributed._remesh_phase_shardlocal):
    unlike `put_sharded_global`, no process ever materializes the other
    processes' rows. Single-process the mesh is fully addressable and
    the local rows ARE the global array."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .shard import AXIS

    if not is_multiprocess():
        return tree
    sh = NamedSharding(dmesh, P(AXIS))
    nshards = int(dmesh.devices.size)

    def put(a):
        a = np.asarray(a)
        gshape = (nshards,) + a.shape[1:]
        return jax.make_array_from_process_local_data(sh, a, gshape)

    return jax.tree_util.tree_map(put, tree)


# replicate-identity programs keyed by device assignment (jit caches
# per leaf structure/shapes underneath); a dict, not lru_cache, because
# device tuples are the key and there is realistically one entry
_REPLICATE_FNS: dict = {}


def _identity(tree):
    return tree


def _replicate_fn(device_assignment):
    fn = _REPLICATE_FNS.get(device_assignment)
    if fn is None:
        from jax.sharding import (
            Mesh as DeviceMesh, NamedSharding, PartitionSpec as P,
        )

        sh = NamedSharding(
            DeviceMesh(np.array(device_assignment), ("d",)), P()
        )
        # parmmg-lint: disable=PML004 -- memoized in _REPLICATE_FNS
        fn = jax.jit(_identity, out_shardings=sh)
        _REPLICATE_FNS[device_assignment] = fn
    return fn


def gather_stacked(tree, timeout: float | None = None):
    """Fetch a (possibly cross-process) stacked pytree to host numpy on
    every process — the allgather that feeds the replicated host phases
    (retag/analysis exchanges). Within one process this is a plain
    device_get.

    All non-addressable leaves ride ONE jitted replicate-identity
    program (out_shardings=replicated) instead of one collective per
    leaf: a ~20-leaf mesh pytree per sweep meant ~20 sequential
    collective dispatch/rendezvous rounds, which is both slower and —
    observed on the 2-process CPU runtime — a hang surface (two ranks
    wedged mid-sequence in `process_allgather`, one dispatching leaf k
    while the other waits on it; see the stall tripwire in
    tests/multihost_worker.py). `timeout` puts the whole gather
    (dispatch + wait) under `run_with_watchdog`, so a residual wedge
    becomes a typed `failsafe.PeerLostError` instead of an indefinite
    hang."""
    if not is_multiprocess():
        return jax.device_get(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [
        i for i, a in enumerate(leaves)
        if isinstance(a, jax.Array) and not a.is_fully_addressable
    ]
    if idx:
        obs_metrics.registry().counter("comm/collectives").inc()
        sub = [leaves[i] for i in idx]
        dev = sub[0].sharding._device_assignment

        def _gather():
            rep = _replicate_fn(dev)(sub)
            return [np.asarray(r.addressable_data(0)) for r in rep]

        with _coll_span("gather", "gather_stacked"):
            vals = run_with_watchdog(
                _gather, tag="gather_stacked", timeout=timeout
            )
        for i, v in zip(idx, vals):
            leaves[i] = v
    # host numpy / fully-addressable leaves are already whole on every
    # process (replicated host phases) — a plain device_get suffices
    out = [
        a if isinstance(a, np.ndarray) else np.asarray(jax.device_get(a))
        for a in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, out)
