"""Communicator invariant checking — the distributed-correctness tool.

TPU-native analog of the reference's `src/chkcomm_pmmg.c` (geometric
coincidence of matched entities: `PMMG_check_extNodeComm:815`): every
shard sends the coordinates of its side of each shared-vertex list; the
peer compares them against its own copies. Run as a debug assertion at
phase boundaries, exactly like the reference wraps these checks in
`assert()` (`src/libparmmg.c:326-329`).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.mesh import Mesh
from ..utils.retry import jit_retry
from .comm import halo_exchange
from .distribute import ShardComm
from .shard import AXIS, _squeeze


@lru_cache(maxsize=8)
def _node_comm_checker(dmesh):
    """Jitted node-communicator checker for one device mesh. Memoized:
    rebuilding jit(shard_map(...)) per call would retrace every call
    (parmmg-lint PML004)."""

    def body(blk: Mesh, comm_idx_blk, l2g_blk):
        mesh = _squeeze(blk)
        comm_idx = comm_idx_blk[0]  # [D, I]
        l2g = l2g_blk[0]
        valid = comm_idx >= 0
        # geometric coincidence: peer coords must equal local coords
        recv = halo_exchange(mesh.vert, comm_idx, AXIS)  # [D,I,3]
        local = mesh.vert[jnp.maximum(comm_idx, 0)]
        err = jnp.where(valid[..., None], jnp.abs(recv - local), 0.0)
        max_err = jax.lax.pmax(jnp.max(err), AXIS)
        # global-id coincidence both sides
        recv_g = halo_exchange(l2g, comm_idx, AXIS)
        local_g = l2g[jnp.maximum(comm_idx, 0)]
        gid_mismatch = jax.lax.psum(
            jnp.sum((jnp.where(valid, recv_g != local_g, False)).astype(jnp.int32)),
            AXIS,
        )
        # pairwise symmetry of list lengths: my count for peer d must
        # equal peer d's count for me
        my_counts = jnp.sum(valid.astype(jnp.int32), axis=1)  # [D]
        peer_counts = jax.lax.all_to_all(
            my_counts, AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        count_mismatch = jax.lax.psum(
            jnp.sum((my_counts != peer_counts).astype(jnp.int32)), AXIS
        )
        # referenced slots must be valid vertices
        bad_slot = jnp.sum(
            (valid & ~mesh.vmask[jnp.maximum(comm_idx, 0)]).astype(jnp.int32)
        )
        valid_mismatch = jax.lax.psum(bad_slot, AXIS)
        return max_err, gid_mismatch, count_mismatch, valid_mismatch

    return jax.jit(
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()),
        )
    )


def check_node_comm(
    stacked: Mesh, comm: ShardComm, dmesh
) -> dict:
    """Geometric + topological node-communicator invariants.

    Returns dict(max_coord_err, count_mismatch, valid_mismatch) as host
    scalars; all zero/small means the tables are coherent.
    """
    f = _node_comm_checker(dmesh)
    max_err, gid_mm, cnt_mm, val_mm = jit_retry(
        f, stacked, comm.comm_idx, comm.l2g
    )
    return dict(
        max_coord_err=float(max_err),
        gid_mismatch=int(gid_mm),
        count_mismatch=int(cnt_mm),
        valid_mismatch=int(val_mm),
    )


@lru_cache(maxsize=8)
def _face_edge_checker(dmesh):
    """Jitted face/edge-communicator checker for one device mesh,
    memoized like `_node_comm_checker` (parmmg-lint PML004)."""
    from ..core import tags
    from ..ops import common

    def spread(rows, vals, valid, newgrp, order):
        """Max per-group coordinate spread of `vals` over valid members
        (rows pre-sorted by `order`, groups from `newgrp`)."""
        n = rows.shape[0]
        gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
        sval = valid[order]
        sv = vals[order]
        hi = jnp.full((n, 3), -jnp.inf, sv.dtype).at[gid].max(
            jnp.where(sval[:, None], sv, -jnp.inf)
        )
        lo = jnp.full((n, 3), jnp.inf, sv.dtype).at[gid].min(
            jnp.where(sval[:, None], sv, jnp.inf)
        )
        d = jnp.where(jnp.isfinite(hi) & jnp.isfinite(lo), hi - lo, 0.0)
        return jnp.max(d), gid, sval

    def body(blk: Mesh, l2g_blk):
        mesh = _squeeze(blk)
        l2g = l2g_blk[0]
        # --- interface trias, keyed by sorted global ids ----------------
        pp = tags.pure_interface_tria(mesh.trtag) & mesh.trmask
        g3 = jnp.sort(l2g[mesh.tria], axis=1)
        g3 = jnp.where(pp[:, None], g3, -1)
        bc = jnp.mean(mesh.vert[mesh.tria], axis=1)
        G = jax.lax.all_gather(g3, AXIS).reshape(-1, 3)
        B = jax.lax.all_gather(bc, AXIS).reshape(-1, 3)
        V = jax.lax.all_gather(pp, AXIS).reshape(-1)
        order, newgrp = common._row_order_groups(G, ~V, None)
        face_err, gid, sval = spread(G, B, V, newgrp, order)
        n = G.shape[0]
        cnt = jnp.zeros(n, jnp.int32).at[gid].add(sval.astype(jnp.int32))
        face_bad = jnp.sum((sval & (cnt[gid] != 2)).astype(jnp.int32))

        # --- interface feature edges, keyed by sorted gid pairs ---------
        par_v = (mesh.vtag & tags.PARBDY) != 0
        e_ok = (
            mesh.edmask
            & par_v[jnp.clip(mesh.edge[:, 0], 0, mesh.pcap - 1)]
            & par_v[jnp.clip(mesh.edge[:, 1], 0, mesh.pcap - 1)]
        )
        g2 = jnp.sort(l2g[mesh.edge], axis=1)
        g2 = jnp.where(e_ok[:, None], g2, -1)
        mid = jnp.mean(mesh.vert[mesh.edge], axis=1)
        E = jax.lax.all_gather(g2, AXIS).reshape(-1, 2)
        M = jax.lax.all_gather(mid, AXIS).reshape(-1, 3)
        W = jax.lax.all_gather(e_ok, AXIS).reshape(-1)
        T = jax.lax.all_gather(mesh.edtag, AXIS).reshape(-1)
        order_e, newgrp_e = common._row_order_groups(E, ~W, None)
        edge_err, gid_e, sval_e = spread(E, M, W, newgrp_e, order_e)
        ne = E.shape[0]
        # geometric feature bits must agree across copies (RIDGE/REF;
        # parallel-discipline bits may legitimately differ per shard)
        st = T[order_e] & (tags.RIDGE | tags.REF)
        thi = jnp.zeros(ne, jnp.int32).at[gid_e].max(
            jnp.where(sval_e, st, 0)
        )
        tlo = jnp.full(ne, 2**30, jnp.int32).at[gid_e].min(
            jnp.where(sval_e, st, 2**30)
        )
        tag_mm = jnp.sum(
            (sval_e & (thi[gid_e] != jnp.where(
                tlo[gid_e] == 2**30, thi[gid_e], tlo[gid_e]
            ))).astype(jnp.int32)
        )
        # every shard computed the same global answer; pmax just folds
        return (
            jax.lax.pmax(face_err, AXIS),
            jax.lax.pmax(face_bad, AXIS),
            jax.lax.pmax(edge_err, AXIS),
            jax.lax.pmax(tag_mm, AXIS),
        )

    return jax.jit(
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()),
        )
    )


def check_face_edge_comm(stacked: Mesh, comm: ShardComm, dmesh) -> dict:
    """Geometric face/edge-communicator invariants — the
    `PMMG_check_extFaceComm` (barycenter agreement,
    reference `src/chkcomm_pmmg.c:1027`) and `PMMG_check_extEdgeComm`
    (midpoint agreement, `:605`) roles.

    Interface trias (PARBDY|NOSURF) and interface feature edges are
    replicated per shard and matched *by sorted global-vertex-id key*
    across the all-gathered set: every pure-interface tria must appear on
    exactly two shards, and every copy of a matched tria/edge must have
    the same barycenter/midpoint. Returns dict(face_count_bad,
    max_face_bc_err, max_edge_mid_err, edge_tag_mismatch).
    """
    face_err, face_bad, edge_err, tag_mm = jit_retry(
        _face_edge_checker(dmesh), stacked, comm.l2g
    )
    return dict(
        max_face_bc_err=float(face_err),
        face_count_bad=int(face_bad),
        max_edge_mid_err=float(edge_err),
        edge_tag_mismatch=int(tag_mm),
    )


def assert_comm_ok(stacked, comm, dmesh, tol: float = 1e-12):
    rep = check_node_comm(stacked, comm, dmesh)
    rep.update(check_face_edge_comm(stacked, comm, dmesh))
    ok = (
        rep["max_coord_err"] <= tol
        and rep["gid_mismatch"] == 0
        and rep["count_mismatch"] == 0
        and rep["valid_mismatch"] == 0
        and rep["max_face_bc_err"] <= tol
        and rep["face_count_bad"] == 0
        and rep["max_edge_mid_err"] <= tol
        and rep["edge_tag_mismatch"] == 0
    )
    if not ok:
        raise AssertionError(f"communicator check failed: {rep}")
    return rep
