"""Communicator invariant checking — the distributed-correctness tool.

TPU-native analog of the reference's `src/chkcomm_pmmg.c` (geometric
coincidence of matched entities: `PMMG_check_extNodeComm:815`): every
shard sends the coordinates of its side of each shared-vertex list; the
peer compares them against its own copies. Run as a debug assertion at
phase boundaries, exactly like the reference wraps these checks in
`assert()` (`src/libparmmg.c:326-329`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.mesh import Mesh
from .comm import halo_exchange
from .distribute import ShardComm
from .shard import AXIS, _squeeze


def check_node_comm(
    stacked: Mesh, comm: ShardComm, dmesh
) -> dict:
    """Geometric + topological node-communicator invariants.

    Returns dict(max_coord_err, count_mismatch, valid_mismatch) as host
    scalars; all zero/small means the tables are coherent.
    """

    def body(blk: Mesh, comm_idx_blk, l2g_blk):
        mesh = _squeeze(blk)
        comm_idx = comm_idx_blk[0]  # [D, I]
        l2g = l2g_blk[0]
        valid = comm_idx >= 0
        # geometric coincidence: peer coords must equal local coords
        recv = halo_exchange(mesh.vert, comm_idx, AXIS)  # [D,I,3]
        local = mesh.vert[jnp.maximum(comm_idx, 0)]
        err = jnp.where(valid[..., None], jnp.abs(recv - local), 0.0)
        max_err = jax.lax.pmax(jnp.max(err), AXIS)
        # global-id coincidence both sides
        recv_g = halo_exchange(l2g, comm_idx, AXIS)
        local_g = l2g[jnp.maximum(comm_idx, 0)]
        gid_mismatch = jax.lax.psum(
            jnp.sum((jnp.where(valid, recv_g != local_g, False)).astype(jnp.int32)),
            AXIS,
        )
        # pairwise symmetry of list lengths: my count for peer d must
        # equal peer d's count for me
        my_counts = jnp.sum(valid.astype(jnp.int32), axis=1)  # [D]
        peer_counts = jax.lax.all_to_all(
            my_counts, AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        count_mismatch = jax.lax.psum(
            jnp.sum((my_counts != peer_counts).astype(jnp.int32)), AXIS
        )
        # referenced slots must be valid vertices
        bad_slot = jnp.sum(
            (valid & ~mesh.vmask[jnp.maximum(comm_idx, 0)]).astype(jnp.int32)
        )
        valid_mismatch = jax.lax.psum(bad_slot, AXIS)
        return max_err, gid_mismatch, count_mismatch, valid_mismatch

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()),
        )
    )
    max_err, gid_mm, cnt_mm, val_mm = f(stacked, comm.comm_idx, comm.l2g)
    return dict(
        max_coord_err=float(max_err),
        gid_mismatch=int(gid_mm),
        count_mismatch=int(cnt_mm),
        valid_mismatch=int(val_mm),
    )


def assert_comm_ok(stacked, comm, dmesh, tol: float = 1e-12):
    rep = check_node_comm(stacked, comm, dmesh)
    ok = (
        rep["max_coord_err"] <= tol
        and rep["gid_mismatch"] == 0
        and rep["count_mismatch"] == 0
        and rep["valid_mismatch"] == 0
    )
    if not ok:
        raise AssertionError(f"communicator check failed: {rep}")
    return rep
