"""shard_map plumbing for the stacked per-shard mesh pytree.

The reference's `PMMG_Grp` array-of-groups per rank becomes one stacked
Mesh pytree with a leading shard axis, laid over a 1-D
`jax.sharding.Mesh` of TPU devices; per-shard kernels run under
`shard_map` and see a plain single-shard `Mesh` (SURVEY.md §7 "group =
shard"). Multi-host scaling rides the same code path: the device mesh
spans hosts and XLA routes the all_to_all over ICI/DCN.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh as DeviceMesh, NamedSharding, PartitionSpec as P

from ..core.mesh import Mesh
from .distribute import ShardComm

AXIS = "shards"


def device_mesh(n: int | None = None) -> DeviceMesh:
    devs = jax.devices()
    n = n or len(devs)
    return DeviceMesh(np.array(devs[:n]), (AXIS,))


def owned_shards(dmesh: DeviceMesh) -> tuple:
    """Shard indices THIS process owns under the 1-D device mesh
    (shard i <-> device i, owner = `device.process_index`) — ascending,
    which is also the order `NamedSharding.addressable_devices` walks
    them, so a [n_owned, ...] local-row stack in this order feeds
    `jax.make_array_from_process_local_data` directly (the shard-local
    sweep dispatch in models/distributed)."""
    pid = jax.process_index()
    return tuple(
        i for i, d in enumerate(dmesh.devices.ravel().tolist())
        if d.process_index == pid
    )


def put_sharded(tree, dmesh: DeviceMesh):
    """Place a stacked [D,...] pytree with its leading axis split over the
    device mesh."""
    sh = NamedSharding(dmesh, P(AXIS))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def _squeeze(tree):
    return jax.tree_util.tree_map(lambda a: a[0], tree)


def _unsqueeze(tree):
    return jax.tree_util.tree_map(lambda a: a[None], tree)


def shard_fn(fn: Callable, dmesh: DeviceMesh, out_stacked: bool = True):
    """Wrap `fn(mesh: Mesh, comm_idx [D,I]) -> pytree` so it runs per
    shard under shard_map over the stacked mesh. Scalar/unsharded outputs
    of `fn` must already be replicated (e.g. psum-reduced). For extra
    per-call arguments, close over them in `fn`."""

    def body(stacked_blk: Mesh, comm_idx_blk):
        mesh = _squeeze(stacked_blk)
        out = fn(mesh, comm_idx_blk[0])
        return _unsqueeze(out) if out_stacked else out

    spec = P(AXIS)
    # factory by contract: the CALLER owns the returned wrapper's
    # lifetime and is responsible for caching it across calls
    return jax.jit(  # parmmg-lint: disable=PML004
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(spec, spec),
            out_specs=spec if out_stacked else P(),
            # arbitrary shard bodies may reach pallas_call (kernel
            # subsystem dispatch) — no replication rule in this jax
            check_rep=False,
        )
    )


@lru_cache(maxsize=8)
def _sharded_hist_fn(dmesh: DeviceMesh):
    """Jitted per-device-mesh histogram reducer. Memoized: rebuilding
    jit(shard_map(...)) per call would retrace on every histogram
    (parmmg-lint PML004)."""
    from ..ops import quality

    def body(blk: Mesh):
        m = _squeeze(blk)
        h = quality.quality_histogram(m)
        return quality.reduce_histograms(h, AXIS)

    # check_rep=False: the histogram body reaches pallas_call when the
    # kernel subsystem dispatches Pallas (tet_quality -> quality_vol),
    # and this jax's shard_map has no replication rule for it; the
    # reduced outputs are psum/pmin-replicated by construction
    return jax.jit(
        jax.shard_map(
            body, mesh=dmesh, in_specs=(P(AXIS),), out_specs=P(),
            check_rep=False,
        )
    )


def sharded_quality_histogram(stacked: Mesh, dmesh: DeviceMesh):
    """Distributed quality histogram: per-shard histogram + cross-shard
    reduction (reference `PMMG_qualhisto`, `src/quality_pmmg.c:156` — the
    custom MPI_Op becomes `reduce_histograms`' pmin/psum)."""
    return _sharded_hist_fn(dmesh)(stacked)


@lru_cache(maxsize=8)
def _sharded_len_fn(dmesh: DeviceMesh, ecap: int):
    """Jitted per-device-mesh edge-length reducer — the `PMMG_prilen`
    world totals as a psum reduction. Memoized like `_sharded_hist_fn`
    (fresh jit(shard_map) per call retraces, parmmg-lint PML004);
    `ecap` is a static shape so it keys the cache too."""
    from ..ops import quality

    def body(blk: Mesh):
        m = _squeeze(blk)
        ls = quality.mesh_length_stats(m, ecap)
        return quality.reduce_length_stats(ls, AXIS)

    # check_rep=False for the same reason as the histogram body: the
    # outputs are psum/pmin-replicated by construction
    return jax.jit(
        jax.shard_map(
            body, mesh=dmesh, in_specs=(P(AXIS),), out_specs=P(),
            check_rep=False,
        )
    )


def sharded_length_stats(stacked: Mesh, dmesh: DeviceMesh):
    """Distributed edge-length histogram: per-shard unique-edge tables +
    metric lengths, world-merged like `sharded_quality_histogram`.
    Interface edges count once per owning shard (thin-band
    approximation, documented in `reduce_length_stats`)."""
    ecap = int(stacked.tet.shape[1] * 1.7) + 64
    return _sharded_len_fn(dmesh, ecap)(stacked)
