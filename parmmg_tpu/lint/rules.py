"""Rule catalog of the JAX-invariant linter.

Every rule has a stable ID (``PML0xx``), fires as a :class:`Finding`,
and can be silenced with ``# parmmg-lint: disable=PML0xx`` on the
offending line, the line above, or the function's ``def``/decorator
line (which scopes the suppression to the whole function), or
``# parmmg-lint: disable-file=PML0xx`` anywhere in the file.

Catalog (see README "Static analysis" for the prose version):

PML001 host-sync-call      explicit device→host syncs (``.item()``,
                           ``.tolist()``, ``jax.device_get``, ``np.*``
                           on traced data) inside jit-reachable code.
PML002 traced-bool         implicit ``bool()``/``int()``/``float()`` of
                           a traced value: ``if``/``assert``/``and``/
                           ``or``/``not`` or conversion calls on
                           tracers inside jit-reachable code.
PML003 traced-loop         Python ``for``/``while`` over traced values
                           (mesh entities) where ``lax`` control flow
                           is required.
PML004 inline-jit          ``jax.jit``/``partial(jax.jit,...)`` applied
                           inside a function body: a fresh cache per
                           call, i.e. unbounded retracing.
PML005 missing-donate      jitted function whose leading parameter is a
                           (large) Mesh pytree without
                           ``donate_argnums`` — doubles peak device
                           memory on the remesh hot path.
PML006 dtype-widening      ``jnp.float64``/``jnp.int64`` (or string
                           dtype spellings) in device code: int32
                           connectivity / declared-dtype geometry is
                           the contract.
PML007 dynamic-shape       boolean-mask indexing or calls that produce
                           data-dependent shapes (``jnp.nonzero``,
                           1-arg ``jnp.where``, ``jnp.unique`` without
                           ``size=``) inside jit-reachable code.
PML008 print-under-trace   ``print`` in jit-reachable code runs at
                           trace time only — use ``jax.debug.print``.
PML009 arange-no-dtype     ``jnp.arange`` without ``dtype=``: under
                           ``jax_enable_x64`` (the test harness) the
                           index array silently widens to int64.
PML010 host-clock-trace    ``time.time()``/``time.perf_counter()``/
                           ``time.monotonic()`` inside jit-reachable
                           code: a host clock under trace measures
                           TRACE time (once, at compile), not run
                           time — instrument with `obs.trace` spans
                           around the dispatch instead.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from .analyzer import (
    Finding, FuncInfo, ModuleInfo, Project, analyze_paths, is_tainted,
    local_taint, local_rank_taint, rank_origin, _dotted_root,
)

RULES: Dict[str, str] = {
    "PML001": "host-sync call inside jit-reachable code",
    "PML002": "implicit bool/int/float of a traced value",
    "PML003": "Python loop over traced values (use lax control flow)",
    "PML004": "jax.jit constructed inside a function body (retraces "
              "every call)",
    "PML005": "jitted Mesh-pytree function without donate_argnums",
    "PML006": "64-bit dtype widening in device code",
    "PML007": "data-dependent output shape inside jit-reachable code",
    "PML008": "print under trace (use jax.debug.print)",
    "PML009": "jnp.arange without explicit dtype (int64 under x64)",
    "PML010": "host clock inside jit-reachable code (measures trace "
              "time, not run time — use obs.trace spans)",
    "PML011": "Pallas kernel registration hygiene (paired lax "
              "reference + equivalence test; f32/i32-only kernel "
              "bodies, no host numpy)",
    "PML012": "collective call dominated by a rank-tainted branch "
              "(a subset of ranks issues it: the canonical SPMD "
              "deadlock)",
    "PML013": "nondeterministic iteration order (set iteration, "
              "unsorted listdir/glob) feeding traced code or "
              "collective payload construction",
    "PML014": "unseeded randomness or wall-clock flowing into retry "
              "jitter, cache keys or seeds (per-rank divergence)",
    "PML015": "blocking host I/O inside a collective window without "
              "a run_with_watchdog wrapper",
    "PML016": "typed raise between paired collectives (one rank "
              "raising while peers wait = silent hang)",
}

# -- the repo's collective surface (PML012/015/016) -----------------------
# classified by LEAF name: call targets like `fs.heartbeat` /
# `self.barrier` / `multihost.agree_flags` are method or module calls
# whose base cannot always be resolved statically, but the leaf names
# are reserved vocabulary across the codebase.
COLLECTIVE_HOST_LEAVES = frozenset({
    "barrier", "_barrier", "agree_flags", "gather_stacked",
    "estimate_clock_offset", "sync_tracer_clock",
    "_exchange_timestamps", "heartbeat", "elastic_poll",
    "verify_collectives", "put_sharded_global",
})
COLLECTIVE_TRACED_LEAVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter",
})
COLLECTIVE_LEAVES = COLLECTIVE_HOST_LEAVES | COLLECTIVE_TRACED_LEAVES

# checkpoint-store operations (the repo-wide durable-I/O surface) and
# direct file I/O: the blocking-host-I/O vocabulary of PML015
STORE_OP_LEAVES = frozenset({
    "put", "put_json", "publish", "publish_json", "get", "get_json",
    "delete", "list",
})
# directory listings whose order is filesystem-defined (PML013)
LISTING_FNS = frozenset({
    "os.listdir", "glob.glob", "glob.iglob", "os.scandir",
})
# wall-clock reads (PML014 sink analysis; superset lives in
# HOST_CLOCK_CALLS for the under-trace rule PML010)
WALL_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic",
    "time.monotonic_ns", "time.perf_counter", "time.perf_counter_ns",
})
# sanctioned seeded-RNG constructors (utils.retry's
# `random.Random(seed)` jitter pattern): exempt from PML014
SEEDED_RNG_CALLS = frozenset({
    "random.Random", "random.SystemRandom", "random.getstate",
    "random.setstate",
    "numpy.random.default_rng", "numpy.random.Generator",
    "numpy.random.RandomState", "numpy.random.SeedSequence",
})
_NONDET_SINK_RE = re.compile(r"seed|jitter|key|salt", re.IGNORECASE)

# host-clock reads that are meaningless under trace (PML010): they
# execute once at trace time and bake a constant into the program
HOST_CLOCK_CALLS = frozenset({
    "time.time", "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns", "time.process_time",
})

# names whose first parameter is the big mesh pytree (PML005)
MESH_PARAM_NAMES = frozenset({"mesh", "stacked", "m", "blk"})
MESH_ANNOTATIONS = frozenset({"Mesh"})

HOST_SYNC_METHODS = frozenset({"item", "tolist", "__array__"})
DYNAMIC_SHAPE_FNS = frozenset({
    "nonzero", "flatnonzero", "argwhere", "unique", "compress",
    "extract", "union1d", "intersect1d", "setdiff1d",
})


def _is_numpy(mi: ModuleInfo, node: ast.AST) -> bool:
    dotted = _dotted_root(mi, node)
    return dotted is not None and dotted.split(".")[0] == "numpy"


def _is_jnp(mi: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Return the function name when `node` is a jax.numpy attribute."""
    dotted = _dotted_root(mi, node)
    if dotted and dotted.startswith("jax.numpy."):
        return dotted[len("jax.numpy."):]
    return None


class _FuncChecker(ast.NodeVisitor):
    """Per-function rule pass. Reachability-gated rules consult
    `self.reachable`; syntax rules run everywhere."""

    def __init__(self, fi: FuncInfo, findings: List[Finding]):
        self.fi = fi
        self.mi = fi.module
        self.findings = findings
        self.reachable = fi.reachable
        self.taint = local_taint(fi) if fi.reachable else set()
        self.own_nested = {
            sub.node
            for sub in fi.module.funcs.values()
            if sub.parent is fi
        }
        # a memoized factory (@lru_cache/@cache) builds its jit wrapper
        # once per key — the sanctioned fix for PML004, not a violation
        self.memoized = False
        cur: Optional[FuncInfo] = fi
        while cur is not None:
            if any(_is_memoize_decorator(d) for d in
                   cur.node.decorator_list):
                self.memoized = True
                break
            cur = cur.parent

    # -- helpers -----------------------------------------------------------

    def emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            rule, self.mi.path, node.lineno, node.col_offset, msg,
            func=self.fi.key,
        ))

    def tainted(self, node: ast.AST) -> bool:
        return is_tainted(self.fi, node, self.taint)

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and (
                child in self.own_nested
            ):
                continue  # nested defs get their own checker
            self.visit(child)

    # -- statement rules ---------------------------------------------------

    def visit_If(self, node: ast.If) -> None:
        if self.reachable and self.tainted(node.test):
            self.emit(
                "PML002", node.test,
                "`if` on a traced value forces a host sync (or a "
                "TracerBoolConversionError under jit) — use jax.lax.cond "
                "or jnp.where",
            )
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if self.reachable and self.tainted(node.test):
            self.emit(
                "PML002", node.test,
                "conditional expression on a traced value — use "
                "jnp.where or jax.lax.cond",
            )
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self.reachable and self.tainted(node.test):
            self.emit(
                "PML002", node.test,
                "assert on a traced value — use "
                "parmmg_tpu.lint.contracts (jit-compatible checkers) or "
                "jax.debug.check",
            )
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self.reachable and self.tainted(node.test):
            self.emit(
                "PML003", node.test,
                "Python `while` on a traced condition — use "
                "jax.lax.while_loop",
            )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self.reachable and self.tainted(node.iter):
            self.emit(
                "PML003", node.iter,
                "Python `for` over traced values (mesh entities) — "
                "batch the body or use jax.lax.fori_loop/scan",
            )
        self.generic_visit(node)

    def visit_BoolOp(self, node: ast.BoolOp) -> None:
        if self.reachable and any(self.tainted(v) for v in node.values):
            self.emit(
                "PML002", node,
                "`and`/`or` on traced values short-circuits through "
                "bool() — use & / | (jnp.logical_and/or)",
            )
        self.generic_visit(node)

    def visit_UnaryOp(self, node: ast.UnaryOp) -> None:
        if (
            self.reachable
            and isinstance(node.op, ast.Not)
            and self.tainted(node.operand)
        ):
            self.emit(
                "PML002", node,
                "`not` on a traced value calls bool() — use ~ "
                "(jnp.logical_not)",
            )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self.reachable:
            idx = node.slice
            mask_like = (
                isinstance(idx, (ast.Compare, ast.BoolOp))
                or (
                    isinstance(idx, (ast.Name, ast.Attribute))
                    and _leaf_name(idx).endswith("mask")
                )
            )
            if mask_like and self.tainted(idx) and self.tainted(node.value):
                self.emit(
                    "PML007", node,
                    "boolean-mask indexing produces a data-dependent "
                    "shape under jit — use jnp.where(mask, ...) or "
                    "masked scatter/gather",
                )
        self.generic_visit(node)

    # -- call rules --------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        mi = self.mi

        # PML004: inline jit (anywhere inside a function body). A
        # decorator of a MODULE-LEVEL function evaluates once at import
        # and is not "inline"; a decorator of a nested def re-evaluates
        # per enclosing call and is.
        from .analyzer import _jit_decl_from_call

        is_toplevel_decorator = (
            self.fi.parent is None
            and any(node is d for d in self.fi.node.decorator_list)
        )
        if not is_toplevel_decorator and not self.memoized and (
            _jit_decl_from_call(node, mi) is not None
        ):
            self.emit(
                "PML004", node,
                "jax.jit constructed inside a function body creates a "
                "fresh compile cache every call (unbounded retracing) — "
                "hoist to module scope or memoize the wrapper",
            )

        if self.reachable:
            # PML001: explicit host syncs
            if isinstance(fn, ast.Attribute):
                if fn.attr in HOST_SYNC_METHODS and self.tainted(fn.value):
                    self.emit(
                        "PML001", node,
                        f".{fn.attr}() on a traced value blocks on the "
                        "device and fails under jit",
                    )
                dotted = _dotted_root(mi, fn)
                if dotted in ("jax.device_get",):
                    self.emit(
                        "PML001", node,
                        "jax.device_get inside jit-reachable code is a "
                        "host sync (and fails on tracers)",
                    )
                # PML010: host clocks under trace time the TRACE, not
                # the run (and a clock-derived value baked into the
                # program is a silent correctness bug)
                if dotted in HOST_CLOCK_CALLS:
                    self.emit(
                        "PML010", node,
                        f"{dotted}() in jit-reachable code runs once at "
                        "trace time — it measures compilation, not the "
                        "run; wrap the DISPATCH in a parmmg_tpu.obs."
                        "trace span (PMMGTPU_TRACE) instead",
                    )
                if _is_numpy(mi, fn) and any(
                    self.tainted(a) for a in node.args
                ):
                    self.emit(
                        "PML001", node,
                        "numpy call on traced data pulls the array to "
                        "the host — use jax.numpy",
                    )
                # PML007: dynamic-shape producers
                jname = _is_jnp(mi, fn)
                if jname in DYNAMIC_SHAPE_FNS and not any(
                    kw.arg == "size" for kw in node.keywords
                ):
                    self.emit(
                        "PML007", node,
                        f"jnp.{jname} without size= has a data-dependent "
                        "output shape and cannot be jitted",
                    )
                if jname == "where" and len(node.args) == 1:
                    self.emit(
                        "PML007", node,
                        "1-argument jnp.where has a data-dependent "
                        "output shape — pass size= via jnp.nonzero or "
                        "use the 3-argument form",
                    )
                # PML009: arange without dtype
                if jname == "arange" and not any(
                    kw.arg == "dtype" for kw in node.keywords
                ):
                    self.emit(
                        "PML009", node,
                        "jnp.arange without dtype= silently widens to "
                        "int64 under jax_enable_x64 — pin dtype=jnp.int32",
                    )
            elif isinstance(fn, ast.Name):
                if fn.id in ("bool", "int", "float") and node.args and (
                    self.tainted(node.args[0])
                ):
                    self.emit(
                        "PML002", node,
                        f"{fn.id}() on a traced value forces a host sync "
                        "(fails under jit) — keep it on device or hoist "
                        "out of the jit region",
                    )
                if fn.id == "print":
                    self.emit(
                        "PML008", node,
                        "print in jit-reachable code runs at trace time "
                        "only — use jax.debug.print",
                    )

        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # PML006: 64-bit dtypes in device code (syntax rule, any func)
        dotted = _dotted_root(self.mi, node)
        if dotted in ("jax.numpy.float64", "jax.numpy.int64"):
            self.emit(
                "PML006", node,
                f"{node.attr} widens device arrays — connectivity is "
                "int32 and geometry follows mesh.dtype",
            )
        self.generic_visit(node)


KERNEL_TEST_MODULE = "test_m18_kernels.py"


def _kernels_module(mi: ModuleInfo) -> bool:
    parts = mi.path.replace("\\", "/").split("/")
    return "kernels" in parts


def _kernel_test_source(mi: ModuleInfo) -> Optional[str]:
    """Source of tests/test_m18_kernels.py next to the package holding
    this kernels module (None when unreadable)."""
    import os

    parts = mi.path.replace("\\", "/").split("/")
    try:
        idx = parts.index("parmmg_tpu")
    except ValueError:
        return None
    root = os.path.join(*parts[:idx]) if idx else "."
    path = os.path.join(root, "tests", KERNEL_TEST_MODULE)
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None


def _check_kernels_module(mi: ModuleInfo, findings: List[Finding]) -> None:
    """PML011 — the Pallas kernel subsystem contract:

    1. every `register(...)` in a kernels module must pair a
       `pallas_impl` with a `lax_reference` (3 positional args or the
       explicit keywords);
    2. the registered kernel name must appear in
       tests/test_m18_kernels.py — no kernel lands without an
       equivalence test module covering it;
    3. kernel BODIES (functions named `*_kernel`) are what Mosaic
       compiles for TPU: f32/i32 only — f64 dtypes/constants and
       host-side `np.` calls are flagged.
    """
    test_src = None
    test_src_loaded = False
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        if _leaf_name(node.func) != "register":
            continue
        kwnames = {kw.arg for kw in node.keywords}
        has_pair = len(node.args) >= 3 or (
            {"pallas_impl", "lax_reference"} <= kwnames
        )
        if not has_pair:
            findings.append(Finding(
                "PML011", mi.path, node.lineno, node.col_offset,
                "kernel registration without a paired lax reference — "
                "every pallas_impl needs its exact lax counterpart "
                "(the off-mode / equivalence baseline)",
            ))
        name_node = node.args[0] if node.args else None
        if isinstance(name_node, ast.Constant) and isinstance(
                name_node.value, str):
            if not test_src_loaded:
                test_src = _kernel_test_source(mi)
                test_src_loaded = True
            if test_src is not None and name_node.value not in test_src:
                findings.append(Finding(
                    "PML011", mi.path, node.lineno, node.col_offset,
                    f"registered kernel {name_node.value!r} has no "
                    f"equivalence coverage in tests/{KERNEL_TEST_MODULE}",
                ))
    # kernel bodies: f32/i32 only, no host numpy
    for fi in mi.funcs.values():
        if not fi.node.name.endswith("_kernel"):
            continue
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Attribute):
                dotted = _dotted_root(mi, node) or ""
                if dotted.split(".")[0] == "numpy":
                    findings.append(Finding(
                        "PML011", mi.path, node.lineno, node.col_offset,
                        "host-side numpy inside a Pallas kernel body — "
                        "kernel bodies trace to Mosaic; use jnp",
                        func=fi.key,
                    ))
                if dotted in ("jax.numpy.float64", "jax.numpy.int64",
                              "numpy.float64", "numpy.int64"):
                    findings.append(Finding(
                        "PML011", mi.path, node.lineno, node.col_offset,
                        f"{node.attr} inside a Pallas kernel body — TPU "
                        "Pallas is f32/i32",
                        func=fi.key,
                    ))
            elif isinstance(node, ast.Constant) and node.value in (
                    "float64", "int64", "f8"):
                findings.append(Finding(
                    "PML011", mi.path, node.lineno, node.col_offset,
                    f"{node.value!r} dtype constant inside a Pallas "
                    "kernel body — TPU Pallas is f32/i32",
                    func=fi.key,
                ))


def _is_memoize_decorator(dec: ast.AST) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    return _leaf_name(target) in ("lru_cache", "cache", "memoize")


def _leaf_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _check_module_level(mi: ModuleInfo, findings: List[Finding]) -> None:
    """Syntax rules that also apply outside function bodies."""
    func_spans = [f.span() for f in mi.funcs.values()]

    def in_func(line: int) -> bool:
        return any(a <= line <= b for a, b in func_spans)

    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Attribute) and not in_func(node.lineno):
            dotted = _dotted_root(mi, node)
            if dotted in ("jax.numpy.float64", "jax.numpy.int64"):
                findings.append(Finding(
                    "PML006", mi.path, node.lineno, node.col_offset,
                    f"{node.attr} widens device arrays — connectivity "
                    "is int32 and geometry follows mesh.dtype",
                ))


def _check_donation(fi: FuncInfo, findings: List[Finding]) -> None:
    """PML005: jit declarations over Mesh-pytree functions must donate
    (or carry an explicit suppression explaining why they cannot)."""
    if not fi.jit_decls:
        return
    args = fi.node.args
    pos = args.posonlyargs + args.args
    if not pos:
        return
    first = pos[0]
    ann = ""
    if isinstance(first.annotation, ast.Name):
        ann = first.annotation.id
    elif isinstance(first.annotation, ast.Constant):
        ann = str(first.annotation.value)
    is_mesh = ann in MESH_ANNOTATIONS or (
        not ann and first.arg in MESH_PARAM_NAMES
    ) or first.arg in MESH_PARAM_NAMES
    if not is_mesh:
        return
    for decl in fi.jit_decls:
        if decl.inline:
            continue  # the inline-jit finding (PML004) already covers it
        if not decl.donates:
            findings.append(Finding(
                "PML005", fi.module.path, decl.line, 0,
                f"jitted `{fi.node.name}` takes the mesh pytree but "
                "declares no donate_argnums — the sweep-scale arrays "
                "are copied instead of reused (2x peak device memory)",
                func=fi.key,
            ))


def _own_nested(fi: FuncInfo) -> set:
    return {
        sub.node for sub in fi.module.funcs.values() if sub.parent is fi
    }


def _iter_sans_nested(root: ast.AST, skip: set):
    """Yield every descendant of `root` except nested-def subtrees
    (those get their own per-function pass)."""
    stack = [root]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and child in skip:
                continue
            yield child
            stack.append(child)


def _is_store_io(call: ast.Call) -> bool:
    """A blocking durable-I/O call: a CheckpointStore-protocol op on a
    `*store*` base, or a direct `open(...)`."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id == "open"
    if isinstance(fn, ast.Attribute) and fn.attr in STORE_OP_LEAVES:
        return "store" in _leaf_name(fn.value)
    return False


def _watchdogged_ids(root: ast.AST, skip: set) -> set:
    """ids of every node inside a run_with_watchdog(...) call's
    arguments — the sanctioned bounded-I/O pattern."""
    out: set = set()
    for c in _iter_sans_nested(root, skip):
        if not (isinstance(c, ast.Call)
                and _leaf_name(c.func) == "run_with_watchdog"):
            continue
        for a in list(c.args) + [kw.value for kw in c.keywords]:
            out.add(id(a))
            for n in _iter_sans_nested(a, set()):
                out.add(id(n))
    return out


def _fn_does_host_io(fi: FuncInfo) -> bool:
    """Whether a function's body performs store/file I/O directly
    (outside any run_with_watchdog call). Cached on the FuncInfo."""
    cached = getattr(fi, "_does_host_io", None)
    if cached is not None:
        return cached
    skip = _own_nested(fi)
    wd = _watchdogged_ids(fi.node, skip)
    out = False
    for node in _iter_sans_nested(fi.node, skip):
        if isinstance(node, ast.Call) and _is_store_io(node) and (
            id(node) not in wd
        ):
            out = True
            break
    fi._does_host_io = out  # type: ignore[attr-defined]
    return out


def _fn_has_host_collective(fi: FuncInfo) -> bool:
    cached = getattr(fi, "_has_host_coll", None)
    if cached is not None:
        return cached
    skip = _own_nested(fi)
    out = any(
        isinstance(n, ast.Call)
        and _leaf_name(n.func) in COLLECTIVE_HOST_LEAVES
        for n in _iter_sans_nested(fi.node, skip)
    )
    fi._has_host_coll = out  # type: ignore[attr-defined]
    return out


def _check_spmd(fi: FuncInfo, findings: List[Finding],
                project: Project) -> None:
    """PML012-016: the SPMD divergence pass over one function."""
    mi = fi.module
    skip = _own_nested(fi)
    rtaint = local_rank_taint(fi)

    def emit(rule, node, msg, chain=()):
        findings.append(Finding(
            rule, mi.path, node.lineno, node.col_offset, msg,
            func=fi.key, chain=list(chain),
        ))

    calls = [n for n in _iter_sans_nested(fi.node, skip)
             if isinstance(n, ast.Call)]
    host_colls = [c for c in calls
                  if _leaf_name(c.func) in COLLECTIVE_HOST_LEAVES]
    coll_bearing = bool(host_colls) or any(
        _leaf_name(c.func) in COLLECTIVE_TRACED_LEAVES for c in calls
    )

    # -- PML012: collective dominated by a rank-tainted branch ---------
    def fire_dominated(stmts, origin, guard_line):
        for st in stmts:
            if isinstance(st, ast.FunctionDef) and st in skip:
                continue
            for n in [st] + list(_iter_sans_nested(st, skip)):
                if isinstance(n, ast.Call) and (
                    _leaf_name(n.func) in COLLECTIVE_LEAVES
                ):
                    emit(
                        "PML012", n,
                        f"collective `{_leaf_name(n.func)}` is only "
                        "issued by a subset of ranks — the branch "
                        f"guarding it is rank-derived; every rank must "
                        "run the same collective schedule (agree the "
                        "predicate first: multihost.agree_flags)",
                        chain=[origin,
                               f"rank-tainted guard at line {guard_line}"],
                    )

    def branch_escapes(stmts) -> bool:
        for st in stmts:
            if isinstance(st, ast.FunctionDef) and st in skip:
                continue
            for n in [st] + list(_iter_sans_nested(st, skip)):
                if isinstance(n, (ast.Return, ast.Raise)):
                    return True
        return False

    def walk_stmts(stmts):
        dom = None  # (origin, guard line) after a rank-guarded escape
        for st in stmts:
            if isinstance(st, ast.FunctionDef) and st in skip:
                continue
            if dom is not None:
                fire_dominated([st], dom[0], dom[1])
                continue
            if isinstance(st, (ast.If, ast.While)):
                o = rank_origin(fi, st.test, rtaint)
                if o is not None and o[1]:
                    fire_dominated(st.body, o[0], st.lineno)
                    fire_dominated(getattr(st, "orelse", []) or [],
                                   o[0], st.lineno)
                    # `if rank != 0: return` fall-through: the ranks
                    # that escaped never reach the statements below
                    if isinstance(st, ast.If) and (
                        branch_escapes(st.body)
                        != branch_escapes(st.orelse)
                    ):
                        dom = (o[0], st.lineno)
                    continue
                walk_stmts(st.body)
                walk_stmts(getattr(st, "orelse", []) or [])
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.With,
                                 ast.AsyncWith)):
                walk_stmts(st.body)
                walk_stmts(getattr(st, "orelse", []) or [])
            elif isinstance(st, ast.Try):
                walk_stmts(st.body)
                for h in st.handlers:
                    walk_stmts(h.body)
                walk_stmts(st.orelse)
                walk_stmts(st.finalbody)

    walk_stmts(fi.node.body)

    # -- PML013: nondeterministic iteration order ----------------------
    sorted_wrapped = {
        id(a) for c in calls if _leaf_name(c.func) == "sorted"
        for a in c.args
    }
    for c in calls:
        dotted = _dotted_root(mi, c.func)
        if dotted in LISTING_FNS and id(c) not in sorted_wrapped:
            emit(
                "PML013", c,
                f"{dotted}() order is filesystem-defined and differs "
                "across ranks — wrap in sorted(...) before iterating",
            )
    if fi.reachable or coll_bearing:
        def is_set_expr(e) -> bool:
            return isinstance(e, (ast.Set, ast.SetComp)) or (
                isinstance(e, ast.Call)
                and isinstance(e.func, ast.Name)
                and e.func.id in ("set", "frozenset")
            )

        set_names = {
            t.id
            for n in _iter_sans_nested(fi.node, skip)
            if isinstance(n, ast.Assign) and is_set_expr(n.value)
            for t in n.targets if isinstance(t, ast.Name)
        }
        iters = [n.iter for n in _iter_sans_nested(fi.node, skip)
                 if isinstance(n, (ast.For, ast.AsyncFor))]
        for n in _iter_sans_nested(fi.node, skip):
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                iters.extend(g.iter for g in n.generators)
        for it in iters:
            is_set = is_set_expr(it) or (
                isinstance(it, ast.Name) and it.id in set_names
            )
            if is_set:
                emit(
                    "PML013", it,
                    "iteration over a set is PYTHONHASHSEED-ordered — "
                    "per-rank order divergence feeding traced code or "
                    "collective payloads; iterate sorted(...) instead",
                )

    # -- PML014: unseeded randomness / wall-clock into seeds -----------
    for c in calls:
        dotted = _dotted_root(mi, c.func)
        if dotted is None:
            continue
        if (dotted.startswith("random.")
                or dotted.startswith("numpy.random.")) and (
                dotted not in SEEDED_RNG_CALLS):
            emit(
                "PML014", c,
                f"{dotted}() draws from the process-global RNG — "
                "per-rank divergence in jitter/ordering; use the "
                "seeded pattern (random.Random(seed), see "
                "utils.retry)",
            )

    def has_clock(node) -> Optional[str]:
        nodes = [node] + list(_iter_sans_nested(node, skip))
        for n in nodes:
            if isinstance(n, ast.Call):
                d = _dotted_root(mi, n.func)
                if d in WALL_CLOCK_CALLS:
                    return d
        return None

    for n in _iter_sans_nested(fi.node, skip):
        if isinstance(n, ast.Assign):
            names = [t.id for t in n.targets if isinstance(t, ast.Name)]
            if any(_NONDET_SINK_RE.search(x) for x in names):
                clk = has_clock(n.value)
                if clk:
                    emit(
                        "PML014", n,
                        f"{clk}() flows into `{names[0]}` — a "
                        "wall-clock-derived seed/jitter/key differs "
                        "per rank; derive it from the schedule "
                        "(iteration, attempt index) instead",
                    )
        elif isinstance(n, ast.Call):
            for kw in n.keywords:
                if kw.arg and _NONDET_SINK_RE.search(kw.arg):
                    clk = has_clock(kw.value)
                    if clk:
                        emit(
                            "PML014", n,
                            f"{clk}() passed as `{kw.arg}=` — a "
                            "wall-clock seed/jitter/key diverges per "
                            "rank; derive it from the schedule "
                            "instead",
                        )

    # -- PML015/016: the paired-collective window ----------------------
    if not host_colls:
        return
    last_coll = max(c.lineno for c in host_colls)
    first_coll = min(c.lineno for c in host_colls)

    # I/O calls inside run_with_watchdog(...) arguments are the
    # sanctioned bounded pattern
    watchdogged = _watchdogged_ids(fi.node, skip)

    for c in calls:
        if id(c) in watchdogged or c.lineno > last_coll:
            continue
        if _is_store_io(c):
            emit(
                "PML015", c,
                "blocking host I/O before the window's last "
                "collective — a wedged store strands peers inside "
                f"the collective at line {last_coll}; wrap in "
                "multihost.run_with_watchdog (or bound it with the "
                "store's timeout envelope)",
            )
            continue
        leaf = _leaf_name(c.func)
        if leaf in COLLECTIVE_LEAVES or leaf == "run_with_watchdog":
            continue
        callee = project.resolve_callable(mi, fi, c.func)
        if (callee is not None and callee is not fi
                and _fn_does_host_io(callee)
                and not _fn_has_host_collective(callee)):
            emit(
                "PML015", c,
                f"`{leaf}` performs blocking host I/O and is called "
                "before the window's last collective (line "
                f"{last_coll}) — a wedge there strands peers; wrap "
                "the I/O in multihost.run_with_watchdog",
                chain=[f"{callee.key} does store/file I/O"],
            )

    if len(host_colls) >= 2 and first_coll < last_coll:
        for n in _iter_sans_nested(fi.node, skip):
            if not isinstance(n, ast.Raise) or n.exc is None:
                continue
            if not (first_coll < n.lineno < last_coll):
                continue
            exc = n.exc
            leaf = _leaf_name(exc.func if isinstance(exc, ast.Call)
                              else exc)
            if "PeerLost" in leaf or "Divergence" in leaf:
                continue  # the typed watchdog-conversion pattern
            emit(
                "PML016", n,
                f"`raise {leaf}` between paired collectives (lines "
                f"{first_coll}..{last_coll}): one rank raising while "
                "peers sit in the next collective is a silent hang — "
                "agree the error first (multihost.agree_flags) or "
                "raise the PeerLost/Divergence watchdog class",
            )


def _suppressed(mi: ModuleInfo, f: Finding) -> bool:
    if f.rule in mi.suppress_file or "all" in mi.suppress_file:
        return True

    def hit(line: int) -> bool:
        rules = mi.suppress_lines.get(line)
        return rules is not None and (f.rule in rules or "all" in rules)

    if hit(f.line) or hit(f.line - 1):
        return True
    # def-line (or decorator-line) scoping: suppressions on the header
    # of the enclosing function apply to its whole body
    for fi in mi.funcs.values():
        a, b = fi.span()
        if a <= f.line <= b:
            header_end = fi.node.body[0].lineno if fi.node.body else b
            # a - 1: a standalone comment line above the decorator
            for ln in range(a - 1, header_end + 1):
                if hit(ln):
                    return True
    return False


def run_lint(
    paths: List[str],
    root: Optional[str] = None,
    select: Optional[List[str]] = None,
    project: Optional[Project] = None,
) -> List[Finding]:
    """Lint `paths`; return unsuppressed findings sorted by location."""
    project = project or analyze_paths(paths, root=root)
    findings: List[Finding] = []
    for mi in project.modules.values():
        err = getattr(mi, "parse_error", None)
        if err:
            findings.append(Finding(
                "PML000", mi.path, 1, 0, f"could not parse: {err}"
            ))
            continue
        _check_module_level(mi, findings)
        if _kernels_module(mi):
            _check_kernels_module(mi, findings)
        seen_nodes = set()
        for fi in mi.funcs.values():
            if id(fi.node) in seen_nodes:
                continue  # alias entries (wrapper-name -> wrapped fn)
            seen_nodes.add(id(fi.node))
            _FuncChecker(fi, findings).visit(fi.node)
            _check_donation(fi, findings)
            _check_spmd(fi, findings, project)
    out = []
    for f in findings:
        if select and f.rule not in select:
            continue
        mi = project.modules.get(_module_of(project, f))
        if mi is not None and _suppressed(mi, f):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _module_of(project: Project, f: Finding) -> str:
    for name, mi in project.modules.items():
        if mi.path == f.path:
            return name
    return ""
