"""Command line for the JAX-invariant linter.

    python -m parmmg_tpu.lint <paths...> [--json [out.json]]
                              [--select PML001,...]
                              [--list-rules] [--root DIR]

``--json`` prints the machine-readable findings document (rule,
file:line, message, taint chain); when followed by a path ending in
``.json`` the document is ALSO written there — the artifact
tools/check.sh's lint stage archives and asserts on.

Exit codes: 0 clean, 1 findings, 2 usage error.  Pure stdlib — linting
never initializes jax or touches an accelerator.
"""

from __future__ import annotations

import json
import sys
from typing import List

from .analyzer import analyze_paths
from .rules import RULES, run_lint


def main(argv: List[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = False
    json_out = None
    select = None
    root = None
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--json":
            as_json = True
            # optional artifact path: only a ".json"-suffixed token is
            # consumed, so `--json parmmg_tpu tools` keeps meaning
            # "json to stdout over these paths"
            if i + 1 < len(argv) and argv[i + 1].endswith(".json"):
                i += 1
                json_out = argv[i]
        elif a == "--list-rules":
            for rid, desc in sorted(RULES.items()):
                print(f"{rid}  {desc}")
            return 0
        elif a == "--select":
            i += 1
            if i >= len(argv):
                print("--select needs a value", file=sys.stderr)
                return 2
            select = [r.strip() for r in argv[i].split(",") if r.strip()]
        elif a == "--root":
            i += 1
            if i >= len(argv):
                print("--root needs a value", file=sys.stderr)
                return 2
            root = argv[i]
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        elif a.startswith("-"):
            print(f"unknown flag {a}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2

    project = analyze_paths(paths, root=root)
    findings = run_lint(paths, root=root, select=select, project=project)
    if as_json:
        doc = json.dumps(
            dict(
                findings=[f.as_dict() for f in findings],
                count=len(findings),
                rules=RULES,
            ),
            indent=2,
        )
        print(doc)
        if json_out:
            with open(json_out, "w") as f:
                f.write(doc + "\n")
    else:
        for f in findings:
            print(f.format())
        n_jit = sum(1 for fi in project.funcs.values() if fi.jit_decls)
        n_reach = sum(
            1 for fi in project.funcs.values() if fi.reachable
        )
        print(
            f"parmmg-lint: {len(findings)} finding(s) in "
            f"{len(project.modules)} module(s) "
            f"({n_jit} jit entry points, {n_reach} jit-reachable "
            "functions)",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
