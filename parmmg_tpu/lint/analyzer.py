"""AST project model for the JAX-invariant linter.

Builds, with the stdlib only (no jax import — the linter must run in a
bare interpreter and never touch the accelerator tunnel):

- a module table for every ``.py`` file under the linted paths, with
  import-alias resolution (absolute and package-relative);
- the set of *jit entry points*: functions decorated ``@jax.jit`` /
  ``@partial(jax.jit, ...)``, module-level ``name = partial(jax.jit,
  ...)(fn)`` wrappings, and functions passed to an inline ``jax.jit(...)``
  call (unwrapping ``shard_map``/``vmap``/``partial`` shells);
- *jit reachability*: the call-graph closure of the entry-point bodies
  across project modules (nested defs of a reachable function count as
  reachable — they are the ``lax.cond``/``while_loop`` branch bodies);
- a *traced-value taint* approximation per reachable function: which
  names may hold tracers.  Seeds are the non-static parameters of the
  jit declarations; taint flows through assignments, ``jnp``/``lax``
  calls and project-function calls, and interprocedurally through call
  arguments to a fixpoint.  Attributes that are static under tracing
  (``.shape``, the Mesh capacity properties, ...) stop the flow.

The model is a conservative approximation: rules that need precision
read the taint sets, rules that key on syntax alone (dtype widening,
inline-jit) scan every function.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set, Tuple

# attribute reads that are static under tracing even on a traced base:
# array metadata, and the Mesh/ShardComm capacity- and flag-properties
# (parmmg_tpu.core.mesh / parallel.distribute), which read .shape only
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "sharding",
    "pcap", "tcap", "fcap", "ecap", "icap", "nshard",
    "aniso", "met_set", "field_ncomp",
})

# host-safe builtins: results are never tracers (and taint does not
# pass through them)
UNTAINTED_CALLS = frozenset({
    "len", "isinstance", "hasattr", "type", "id", "repr",
    "str", "print", "max", "min",
})

# metadata/introspection calls whose results are host values even when
# fed traced arguments (dtype queries, backend identity, ...)
HOST_META_CALLS = frozenset({
    "jax.numpy.finfo", "jax.numpy.iinfo", "jax.numpy.issubdtype",
    "jax.numpy.dtype", "jax.numpy.result_type", "jax.numpy.promote_types",
    "jax.numpy.ndim", "jax.numpy.shape",
    "jax.default_backend", "jax.devices", "jax.local_devices",
    "jax.device_count", "jax.local_device_count", "jax.process_index",
    "jax.process_count", "jax.eval_shape",
    "jax.dtypes.canonicalize_dtype", "jax.dtypes.issubdtype",
    "numpy.finfo", "numpy.iinfo", "numpy.dtype", "numpy.issubdtype",
    "numpy.result_type", "numpy.promote_types",
})

# rank-identity sources (the SPMD divergence pass): calls whose result
# names *this process* inside the world. per_rank=True sources differ
# across ranks (branching on them diverges the collective schedule);
# per_rank=False sources (world size) are world-uniform — tracked for
# taint chains, but a uniform predicate takes the same arm on every
# rank and is the sanctioned `is_multiprocess()` guard pattern.
RANK_SOURCE_CALLS = {
    "jax.process_index": True,
    "jax.process_count": False,
    "jax.distributed.initialize": False,
}

# per-rank environment keys (the launch contract of parallel.multihost)
RANK_ENV_KEYS = {"PMMGTPU_PROC_ID": True, "PMMGTPU_NUM_PROCS": False}

# attribute leaves that carry rank identity by convention (the elastic
# coordinator and launch configs store process_index under these names)
RANK_ATTR_NAMES = frozenset({"rank", "proc_id"})

_SUPPRESS_RE = re.compile(
    r"#\s*parmmg-lint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s+--.*)?$"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*parmmg-lint:\s*disable-file=([A-Za-z0-9_,\s]+?)(?:\s+--.*)?$"
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    func: str = ""
    # taint provenance (rank-taint rules): source -> ... -> sink steps
    chain: List[str] = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        fn = f" [{self.func}]" if self.func else ""
        tail = ""
        if self.chain:
            tail = "  {" + " -> ".join(self.chain) + "}"
        return f"{loc}: {self.rule}{fn}: {self.message}{tail}"


@dataclasses.dataclass
class JitDecl:
    """One jit compilation declaration (decorator, module-level partial
    wrap, or inline jax.jit(...) call) attached to a project function."""

    static_names: Set[str]
    donates: bool
    line: int
    inline: bool = False  # constructed inside a function body


@dataclasses.dataclass
class FuncInfo:
    module: "ModuleInfo"
    qualname: str
    node: ast.FunctionDef
    parent: Optional["FuncInfo"] = None
    jit_decls: List[JitDecl] = dataclasses.field(default_factory=list)
    reachable: bool = False
    tainted_params: Set[str] = dataclasses.field(default_factory=set)
    # whether the function may return traced values (computed in the
    # interprocedural fixpoint; monotone False -> True)
    returns_tainted: bool = False
    # rank-taint domain (SPMD divergence pass): param -> (origin
    # description, per_rank). Unlike tracer taint this runs over EVERY
    # function — host coordination code is exactly what it targets.
    rank_tainted_params: Dict[str, Tuple[str, bool]] = dataclasses.field(
        default_factory=dict
    )
    returns_rank_tainted: bool = False
    rank_return_origin: Tuple[str, bool] = ("", False)
    # resolved project callees: (callee FuncInfo, call node)
    calls: List[Tuple["FuncInfo", ast.Call]] = dataclasses.field(
        default_factory=list
    )

    @property
    def key(self) -> str:
        return f"{self.module.name}.{self.qualname}"

    @property
    def params(self) -> List[str]:
        a = self.node.args
        return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]

    def static_names(self) -> Set[str]:
        out: Set[str] = set()
        for d in self.jit_decls:
            out |= d.static_names
        return out

    def span(self) -> Tuple[int, int]:
        first = min(
            [self.node.lineno]
            + [d.lineno for d in self.node.decorator_list]
        )
        return first, self.node.end_lineno or self.node.lineno


@dataclasses.dataclass
class ModuleInfo:
    name: str
    path: str
    tree: ast.Module
    lines: List[str]
    # alias -> dotted module path ("jnp" -> "jax.numpy"); includes
    # project submodule aliases ("split" -> "parmmg_tpu.ops.split")
    mod_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    # symbol -> (module path, attr) for `from m import f`
    sym_imports: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )
    funcs: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    suppress_lines: Dict[int, Set[str]] = dataclasses.field(
        default_factory=dict
    )
    suppress_file: Set[str] = dataclasses.field(default_factory=set)


class Project:
    """All analyzed modules plus the resolved call graph and taint."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.funcs: Dict[str, FuncInfo] = {}

    # -- construction ------------------------------------------------------

    def add_module(self, mi: ModuleInfo) -> None:
        mi.project = self  # back-ref for taint-time call resolution
        self.modules[mi.name] = mi
        for fi in mi.funcs.values():
            self.funcs[fi.key] = fi

    def finalize(self) -> None:
        self._resolve_calls()
        self._mark_reachable()
        self._propagate_taint()
        self._propagate_rank_taint()

    # -- name resolution ---------------------------------------------------

    def resolve_callable(
        self, mi: ModuleInfo, scope: Optional[FuncInfo], node: ast.AST
    ) -> Optional[FuncInfo]:
        """Resolve a call-target expression to a project function."""
        if isinstance(node, ast.Name):
            # nested defs in the enclosing function chain
            cur = scope
            while cur is not None:
                cand = mi.funcs.get(f"{cur.qualname}.{node.id}")
                if cand is not None:
                    return cand
                cur = cur.parent
            if node.id in mi.funcs:
                return mi.funcs[node.id]
            if node.id in mi.sym_imports:
                mod, attr = mi.sym_imports[node.id]
                target = self.modules.get(mod)
                if target is not None:
                    return target.funcs.get(attr)
            return None
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            # self.method()/cls.method(): sibling methods of the scope's
            # enclosing class (qualnames are "Class.method[.nested]")
            if node.value.id in ("self", "cls") and scope is not None:
                parts = scope.qualname.split(".")
                for i in range(len(parts) - 1, 0, -1):
                    cand = mi.funcs.get(
                        ".".join(parts[:i]) + "." + node.attr
                    )
                    if cand is not None:
                        return cand
            mod = mi.mod_aliases.get(node.value.id)
            if mod is not None and mod in self.modules:
                return self.modules[mod].funcs.get(node.attr)
        return None

    def external_name(
        self, mi: ModuleInfo, node: ast.AST
    ) -> Optional[str]:
        """Dotted external name of an expression, e.g. ``jnp.where`` ->
        ``jax.numpy.where``; None when it isn't a plain module attr."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = mi.mod_aliases.get(cur.id)
        if root is None:
            sym = mi.sym_imports.get(cur.id)
            if sym is not None:
                root = f"{sym[0]}.{sym[1]}"
            else:
                return None
        return ".".join([root] + list(reversed(parts)))

    # -- call graph & reachability ----------------------------------------

    def _iter_call_targets(self, call: ast.Call):
        """Call-target expressions of a Call, following an IfExp func
        (the ``(_sweep_body if unfused else remesh_sweep)(...)`` idiom)."""
        fn = call.func
        if isinstance(fn, ast.IfExp):
            yield fn.body
            yield fn.orelse
        else:
            yield fn

    def _resolve_calls(self) -> None:
        for fi in self.funcs.values():
            mi = fi.module
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for tgt in self._iter_call_targets(node):
                    callee = self.resolve_callable(mi, fi, tgt)
                    if callee is not None and callee is not fi:
                        fi.calls.append((callee, node))

    def _mark_reachable(self) -> None:
        work = [f for f in self.funcs.values() if f.jit_decls]
        seen: Set[str] = set()
        while work:
            fi = work.pop()
            if fi.key in seen:
                continue
            seen.add(fi.key)
            fi.reachable = True
            # nested defs are the lax branch/loop bodies — reachable
            for sub in fi.module.funcs.values():
                if sub.parent is fi and sub.key not in seen:
                    work.append(sub)
            for callee, _ in fi.calls:
                if callee.key not in seen:
                    work.append(callee)

    # -- taint -------------------------------------------------------------

    def _seed_taint(self) -> None:
        for fi in self.funcs.values():
            if not fi.jit_decls:
                continue
            static = fi.static_names()
            for p in fi.params:
                if p not in static:
                    fi.tainted_params.add(p)

    def _propagate_taint(self) -> None:
        self._seed_taint()
        # fixpoint: local taint per function, then push through call
        # args and return values
        for _ in range(20):  # project call-graph depth is far below this
            changed = False
            for fi in self.funcs.values():
                if not fi.reachable:
                    continue
                taint = local_taint(fi)
                if not fi.returns_tainted and _returns_tainted(fi, taint):
                    fi.returns_tainted = True
                    changed = True
                for callee, call in fi.calls:
                    if not callee.reachable:
                        continue
                    static = callee.static_names()
                    for pname, expr in map_call_args(callee, call):
                        if pname in static:
                            continue
                        if pname not in callee.tainted_params and (
                            expr is not None
                            and is_tainted(fi, expr, taint)
                        ):
                            callee.tainted_params.add(pname)
                            changed = True
            if not changed:
                break

    def _propagate_rank_taint(self) -> None:
        """Interprocedural fixpoint of the rank-taint domain over ALL
        functions (reachability does not gate it: the divergence rules
        target host coordination code, not jitted bodies)."""
        for _ in range(20):
            changed = False
            for fi in self.funcs.values():
                rtaint = local_rank_taint(fi)
                ret = _returns_rank(fi, rtaint)
                if ret is not None and not fi.returns_rank_tainted:
                    fi.returns_rank_tainted = True
                    fi.rank_return_origin = ret
                    changed = True
                for callee, call in fi.calls:
                    for pname, expr in map_call_args(callee, call):
                        if expr is None:
                            continue
                        o = rank_origin(fi, expr, rtaint)
                        if o is None:
                            continue
                        prev = callee.rank_tainted_params.get(pname)
                        if prev is None or (o[1] and not prev[1]):
                            callee.rank_tainted_params[pname] = (
                                f"{o[0]} via {fi.key}:{call.lineno}",
                                o[1],
                            )
                            changed = True
            if not changed:
                break


def _returns_tainted(fi: FuncInfo, taint: Set[str]) -> bool:
    own_nested = {
        sub.node for sub in fi.module.funcs.values() if sub.parent is fi
    }

    def walk(node) -> bool:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and child in own_nested:
                continue
            if isinstance(child, ast.Return) and child.value is not None:
                if is_tainted(fi, child.value, taint):
                    return True
            if walk(child):
                return True
        return False

    return walk(fi.node)


def map_call_args(callee: FuncInfo, call: ast.Call):
    """Yield (param_name, arg_expr) pairs for a call of a project
    function (best effort: *args/**kwargs are skipped)."""
    params = callee.params
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            yield params[i], arg
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in params:
            yield kw.arg, kw.value


def is_tainted(fi: FuncInfo, node: ast.AST, taint: Set[str]) -> bool:
    """Whether an expression may hold a traced value, given the set of
    tainted local names."""
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return is_tainted(fi, node.value, taint)
    if isinstance(node, ast.Call):
        return call_result_tainted(fi, node, taint)
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return any(is_tainted(fi, e, taint) for e in node.elts)
    if isinstance(node, ast.Starred):
        return is_tainted(fi, node.value, taint)
    if isinstance(node, ast.Subscript):
        return is_tainted(fi, node.value, taint)
    if isinstance(node, ast.BinOp):
        return is_tainted(fi, node.left, taint) or is_tainted(
            fi, node.right, taint
        )
    if isinstance(node, ast.UnaryOp):
        return is_tainted(fi, node.operand, taint)
    if isinstance(node, ast.BoolOp):
        return any(is_tainted(fi, v, taint) for v in node.values)
    if isinstance(node, ast.Compare):
        # identity checks (`x is None`) never call bool() on a tracer
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return is_tainted(fi, node.left, taint) or any(
            is_tainted(fi, c, taint) for c in node.comparators
        )
    if isinstance(node, ast.IfExp):
        return is_tainted(fi, node.body, taint) or is_tainted(
            fi, node.orelse, taint
        )
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return is_tainted(fi, node.elt, taint)
    if isinstance(node, ast.Lambda):
        return False
    return False


def call_result_tainted(
    fi: FuncInfo, call: ast.Call, taint: Set[str]
) -> bool:
    mi = fi.module
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in ("range", "enumerate", "zip", "getattr", "tuple",
                     "list", "sorted", "reversed"):
            return any(is_tainted(fi, a, taint) for a in call.args)
        if fn.id in UNTAINTED_CALLS:
            return False
        if fn.id in ("int", "float", "bool"):
            # conversion forces a sync: the *result* is a host value
            return False
    dotted = _dotted_root(mi, fn)
    if dotted in HOST_META_CALLS:
        return False
    # method call on a tainted object (e.g. mesh.replace(...)) -> tainted
    if isinstance(fn, ast.Attribute) and is_tainted(fi, fn.value, taint):
        return True
    # project functions: use the computed return taint
    project = getattr(mi, "project", None)
    if project is not None:
        callee = project.resolve_callable(mi, fi, fn)
        if callee is not None:
            return callee.returns_tainted
    # jnp./lax./jax. calls build traced values inside a jit region
    # regardless of their args (jnp.zeros(...) is a tracer under trace)
    if dotted is not None:
        root = dotted.split(".", 1)[0]
        if root == "jax":
            return True
        if root in ("numpy",):
            # numpy on traced args syncs; the result is host data
            return False
    # unresolved calls (callables held in variables, methods on host
    # objects): conservative — assume traced
    return True


def _dotted_root(mi: ModuleInfo, node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    base = mi.mod_aliases.get(cur.id)
    if base is None:
        sym = mi.sym_imports.get(cur.id)
        if sym is None:
            return None
        base = f"{sym[0]}.{sym[1]}"
    return ".".join([base] + list(reversed(parts)))


def local_taint(fi: FuncInfo) -> Set[str]:
    """Fixpoint set of tainted local names in a reachable function."""
    taint: Set[str] = set(fi.tainted_params)

    own_nested = {
        sub.node for sub in fi.module.funcs.values() if sub.parent is fi
    }

    def visit_stmts(stmts):
        changed = False
        for st in stmts:
            changed |= visit(st)
        return changed

    def add(name: str) -> bool:
        if name not in taint:
            taint.add(name)
            return True
        return False

    def bind_target(tgt, tainted: bool) -> bool:
        if not tainted:
            return False
        changed = False
        if isinstance(tgt, ast.Name):
            changed |= add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                changed |= bind_target(e, True)
        elif isinstance(tgt, ast.Starred):
            changed |= bind_target(tgt.value, True)
        return changed

    def visit(node) -> bool:
        changed = False
        if isinstance(node, ast.FunctionDef) and node in own_nested:
            return False  # nested defs analyzed separately
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = node.value
            if value is not None:
                t = is_tainted(fi, value, taint)
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if isinstance(node, ast.AugAssign):
                    t = t or is_tainted(fi, node.target, taint)
                for tgt in targets:
                    changed |= bind_target(tgt, t)
            return changed
        if isinstance(node, ast.For):
            changed |= bind_target(
                node.target, is_tainted(fi, node.iter, taint)
            )
        if isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    changed |= bind_target(
                        item.optional_vars,
                        is_tainted(fi, item.context_expr, taint),
                    )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and child in own_nested:
                continue
            changed |= visit(child)
        return changed

    for _ in range(10):
        if not visit_stmts(fi.node.body):
            break
    return taint


# ---------------------------------------------------------------------------
# rank taint (SPMD divergence pass)
# ---------------------------------------------------------------------------

RankOrigin = Tuple[str, bool]  # (human-readable source, per_rank)


def _best(a: Optional[RankOrigin],
          b: Optional[RankOrigin]) -> Optional[RankOrigin]:
    """Merge two origins: a per-rank source dominates a world-uniform
    one (a predicate mixing both still diverges per rank)."""
    if a is None:
        return b
    if b is None:
        return a
    return a if a[1] or not b[1] else b


def rank_origin(
    fi: FuncInfo, node: ast.AST, rtaint: Dict[str, RankOrigin]
) -> Optional[RankOrigin]:
    """Origin of rank identity in an expression, or None.

    Semantics deliberately differ from tracer taint: Compare nodes DO
    propagate (``rank == 0`` is the canonical divergent predicate),
    STATIC_ATTRS do not stop the flow (these are host ints, not
    tracers), and unresolved calls are NOT conservatively tainted —
    rank identity enters only through the known sources."""
    if isinstance(node, ast.Name):
        return rtaint.get(node.id)
    if isinstance(node, ast.Attribute):
        if node.attr in RANK_ATTR_NAMES:
            return (f".{node.attr} attribute", True)
        return rank_origin(fi, node.value, rtaint)
    if isinstance(node, ast.Call):
        return call_rank_origin(fi, node, rtaint)
    if isinstance(node, ast.Subscript):
        dotted = _dotted_root(fi.module, node.value)
        if dotted == "os.environ" and isinstance(
            node.slice, ast.Constant
        ) and node.slice.value in RANK_ENV_KEYS:
            return (f"os.environ[{node.slice.value!r}]",
                    RANK_ENV_KEYS[node.slice.value])
        return _best(rank_origin(fi, node.value, rtaint),
                     rank_origin(fi, node.slice, rtaint))
    if isinstance(node, (ast.Constant, ast.Lambda)):
        return None
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        out = None
        for e in node.elts:
            out = _best(out, rank_origin(fi, e, rtaint))
        return out
    if isinstance(node, ast.Starred):
        return rank_origin(fi, node.value, rtaint)
    if isinstance(node, ast.BinOp):
        return _best(rank_origin(fi, node.left, rtaint),
                     rank_origin(fi, node.right, rtaint))
    if isinstance(node, ast.UnaryOp):
        return rank_origin(fi, node.operand, rtaint)
    if isinstance(node, ast.BoolOp):
        out = None
        for v in node.values:
            out = _best(out, rank_origin(fi, v, rtaint))
        return out
    if isinstance(node, ast.Compare):
        out = rank_origin(fi, node.left, rtaint)
        for c in node.comparators:
            out = _best(out, rank_origin(fi, c, rtaint))
        return out
    if isinstance(node, ast.IfExp):
        out = rank_origin(fi, node.test, rtaint)
        out = _best(out, rank_origin(fi, node.body, rtaint))
        return _best(out, rank_origin(fi, node.orelse, rtaint))
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return rank_origin(fi, node.elt, rtaint)
    if isinstance(node, ast.JoinedStr):
        out = None
        for v in node.values:
            out = _best(out, rank_origin(fi, v, rtaint))
        return out
    if isinstance(node, ast.FormattedValue):
        return rank_origin(fi, node.value, rtaint)
    return None


def call_rank_origin(
    fi: FuncInfo, call: ast.Call, rtaint: Dict[str, RankOrigin]
) -> Optional[RankOrigin]:
    mi = fi.module
    fn = call.func
    dotted = _dotted_root(mi, fn)
    if dotted in RANK_SOURCE_CALLS:
        return (f"{dotted}()", RANK_SOURCE_CALLS[dotted])
    if dotted in ("os.environ.get", "os.getenv") and call.args:
        key = call.args[0]
        if isinstance(key, ast.Constant) and key.value in RANK_ENV_KEYS:
            return (f"os.environ[{key.value!r}]",
                    RANK_ENV_KEYS[key.value])
    # project callee whose return is rank-derived
    project = getattr(mi, "project", None)
    if project is not None:
        callee = project.resolve_callable(mi, fi, fn)
        if callee is not None and callee.returns_rank_tainted:
            org = callee.rank_return_origin
            return (f"{org[0]} via {callee.key}()", org[1])
    # method on a rank-derived value (rank_str.strip(), ...)
    out = None
    if isinstance(fn, ast.Attribute):
        out = rank_origin(fi, fn.value, rtaint)
    # argument pass-through (int(env), min(rank, n), f(rank), ...) —
    # NOT conservative on unresolved calls: rank identity only enters
    # through the known sources
    for a in call.args:
        out = _best(out, rank_origin(fi, a, rtaint))
    for kw in call.keywords:
        out = _best(out, rank_origin(fi, kw.value, rtaint))
    return out


def local_rank_taint(fi: FuncInfo) -> Dict[str, RankOrigin]:
    """Fixpoint map of rank-derived local names -> origin."""
    rtaint: Dict[str, RankOrigin] = dict(fi.rank_tainted_params)

    own_nested = {
        sub.node for sub in fi.module.funcs.values() if sub.parent is fi
    }

    def bind(tgt, origin: Optional[RankOrigin]) -> bool:
        if origin is None:
            return False
        changed = False
        if isinstance(tgt, ast.Name):
            prev = rtaint.get(tgt.id)
            if prev is None or (origin[1] and not prev[1]):
                rtaint[tgt.id] = origin
                changed = True
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                changed |= bind(e, origin)
        elif isinstance(tgt, ast.Starred):
            changed |= bind(tgt.value, origin)
        return changed

    def visit(node) -> bool:
        changed = False
        if isinstance(node, ast.FunctionDef) and node in own_nested:
            return False
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                o = rank_origin(fi, node.value, rtaint)
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    changed |= bind(tgt, o)
            return changed
        if isinstance(node, ast.For):
            changed |= bind(
                node.target, rank_origin(fi, node.iter, rtaint)
            )
        if isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    changed |= bind(
                        item.optional_vars,
                        rank_origin(fi, item.context_expr, rtaint),
                    )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and child in own_nested:
                continue
            changed |= visit(child)
        return changed

    for _ in range(10):
        changed = False
        for st in fi.node.body:
            changed |= visit(st)
        if not changed:
            break
    return rtaint


def _returns_rank(
    fi: FuncInfo, rtaint: Dict[str, RankOrigin]
) -> Optional[RankOrigin]:
    own_nested = {
        sub.node for sub in fi.module.funcs.values() if sub.parent is fi
    }
    out: Optional[RankOrigin] = None

    def walk(node) -> None:
        nonlocal out
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef) and child in own_nested:
                continue
            if isinstance(child, ast.Return) and child.value is not None:
                out = _best(out, rank_origin(fi, child.value, rtaint))
            walk(child)

    walk(fi.node)
    return out


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def _module_name(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in rel.split(os.sep) if p not in (".",)]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _resolve_relative(mi_name: str, level: int, module: str) -> str:
    """Resolve `from ...module import x` against a module's dotted name."""
    base = mi_name.split(".")
    # a module's package is its name minus the leaf (modules here are
    # files, not packages, except __init__ which already dropped leaf)
    base = base[: len(base) - level] if level <= len(base) else []
    if module:
        base = base + module.split(".")
    return ".".join(base)


def _collect_imports(mi: ModuleInfo) -> None:
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                if al.asname:
                    mi.mod_aliases[al.asname] = al.name
                else:
                    root = al.name.split(".")[0]
                    mi.mod_aliases[root] = root
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level:
                mod = _resolve_relative(mi.name, node.level, mod)
            for al in node.names:
                name = al.asname or al.name
                mi.sym_imports[name] = (mod, al.name)
                # `from pkg import submodule` — record as module alias too
                mi.mod_aliases.setdefault(name, f"{mod}.{al.name}")


def _collect_suppressions(mi: ModuleInfo) -> None:
    for i, line in enumerate(mi.lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            mi.suppress_lines.setdefault(i, set()).update(rules)
        m = _SUPPRESS_FILE_RE.search(line)
        if m:
            mi.suppress_file.update(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )


def _jit_decl_from_call(call: ast.Call, mi: ModuleInfo) -> Optional[dict]:
    """If `call` is jax.jit(...) or partial(jax.jit, ...), return its
    static/donate config, else None."""

    def is_jit_ref(node) -> bool:
        if isinstance(node, ast.Name):
            sym = mi.sym_imports.get(node.id)
            return node.id == "jit" and sym is not None and sym[0] == "jax"
        dotted = _dotted_root(mi, node)
        return dotted == "jax.jit"

    cfg = None
    if is_jit_ref(call.func):
        cfg = dict(static=set(), donates=False, kws=call.keywords)
    elif (
        isinstance(call.func, ast.Name)
        and call.func.id == "partial"
        and call.args
        and is_jit_ref(call.args[0])
    ):
        cfg = dict(static=set(), donates=False, kws=call.keywords)
    if cfg is None:
        return None
    for kw in cfg.pop("kws"):
        if kw.arg in ("static_argnames", "static_argnums"):
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    cfg["static"].add(c.value)
        if kw.arg in ("donate_argnums", "donate_argnames"):
            cfg["donates"] = True
    return cfg


def _unwrap_to_func(node: ast.AST) -> Optional[ast.AST]:
    """Peel transform shells (shard_map/vmap/partial/closures) off a
    jit argument down to a function reference expression."""
    seen = 0
    while isinstance(node, ast.Call) and seen < 6:
        if not node.args:
            return None
        node = node.args[0]
        seen += 1
    if isinstance(node, (ast.Name, ast.Attribute)):
        return node
    return None


def _collect_funcs(mi: ModuleInfo) -> None:
    def walk_body(body, prefix: str, parent: Optional[FuncInfo]):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                fi = FuncInfo(mi, qual, node, parent=parent)
                mi.funcs[qual] = fi
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        cfg = _jit_decl_from_call(dec, mi)
                        if cfg:
                            fi.jit_decls.append(JitDecl(
                                cfg["static"], cfg["donates"], dec.lineno
                            ))
                    elif _dotted_root(mi, dec) == "jax.jit" or (
                        isinstance(dec, ast.Name)
                        and dec.id == "jit"
                        and mi.sym_imports.get("jit", ("",))[0] == "jax"
                    ):
                        fi.jit_decls.append(
                            JitDecl(set(), False, dec.lineno)
                        )
                walk_body(node.body, f"{qual}.", fi)
            elif isinstance(node, ast.ClassDef):
                walk_body(node.body, f"{prefix}{node.name}.", parent)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                for field in ("body", "orelse", "finalbody"):
                    sub_body = getattr(node, field, None)
                    if sub_body:
                        walk_body(sub_body, prefix, parent)
                for h in getattr(node, "handlers", []) or []:
                    walk_body(h.body, prefix, parent)

    walk_body(mi.tree.body, "", None)


def _attach_wrapped_jits(mi: ModuleInfo, project: Project) -> None:
    """Module-level `name = partial(jax.jit, ...)(fn)` wrappings and
    inline `jax.jit(shard_map(body, ...))` calls inside functions: mark
    the wrapped project function as a jit entry."""
    # module-level assignments
    for node in mi.tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        cfg = None
        if isinstance(call.func, ast.Call):
            cfg = _jit_decl_from_call(call.func, mi)  # partial(...)(fn)
        if cfg is None:
            cfg = _jit_decl_from_call(call, mi)  # jax.jit(fn, ...)
            wrapped = call.args[0] if cfg and call.args else None
        else:
            wrapped = call.args[0] if call.args else None
        if cfg is None or wrapped is None:
            continue
        ref = _unwrap_to_func(wrapped) or wrapped
        fi = project.resolve_callable(mi, None, ref)
        if fi is not None:
            fi.jit_decls.append(
                JitDecl(cfg["static"], cfg["donates"], node.lineno)
            )
            # alias: calls to the wrapper name hit the wrapped function
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mi.funcs.setdefault(tgt.id, fi)
    # inline jax.jit(...) inside function bodies
    for fi in list(mi.funcs.values()):
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            cfg = _jit_decl_from_call(node, mi)
            if cfg is None or not node.args:
                continue
            ref = _unwrap_to_func(node.args[0])
            if ref is None:
                continue
            wrapped = project.resolve_callable(mi, fi, ref)
            if wrapped is not None:
                wrapped.jit_decls.append(JitDecl(
                    cfg["static"], cfg["donates"], node.lineno,
                    inline=True,
                ))


def parse_module(path: str, root: str) -> Optional[ModuleInfo]:
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
    except (SyntaxError, UnicodeDecodeError, OSError) as exc:
        mi = ModuleInfo(
            _module_name(path, root), path, ast.Module(body=[],
                                                       type_ignores=[]),
            [],
        )
        mi.parse_error = str(exc)  # type: ignore[attr-defined]
        return mi
    mi = ModuleInfo(_module_name(path, root), path, tree,
                    src.splitlines())
    _collect_imports(mi)
    _collect_suppressions(mi)
    _collect_funcs(mi)
    return mi


def iter_python_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if not d.startswith(".") and d != "__pycache__"
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out


def analyze_paths(paths: List[str], root: Optional[str] = None) -> Project:
    """Parse every .py under `paths` and build the resolved project."""
    root = os.path.abspath(root or os.getcwd())
    project = Project()
    for path in iter_python_files(paths):
        mi = parse_module(path, root)
        if mi is not None:
            project.add_module(mi)
    for mi in project.modules.values():
        _attach_wrapped_jits(mi, project)
    # re-register aliased funcs added by _attach_wrapped_jits
    for mi in project.modules.values():
        for fi in mi.funcs.values():
            project.funcs.setdefault(fi.key, fi)
    project.finalize()
    return project
