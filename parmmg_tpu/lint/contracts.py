"""Runtime contracts for the flat-mesh invariants + a retrace counter.

The static analyzer (`parmmg_tpu.lint`) checks what the *source* cannot
do; this module checks what the *data* must satisfy — the runtime half
of the reference's assertion discipline (`assert()` around `chkcomm`,
`src/libparmmg.c:326-329`), restated for the flat SoA mesh:

- connectivity in range and pointing at live vertices;
- `adja` involution: ``adja[t, f] = 4*u + g  =>  adja[u, g] = 4*t + f``
  (the invariant `MMG3D_hashTetra` guarantees by construction);
- sentinel domains: ``adja``/``vglob`` are ``>= -1`` everywhere;
- owner-rank consistency of the node communicator (exactly one owning
  shard per shared global vertex — mirroring `parallel/chkcomm.py`'s
  geometric checks with a pure-topological one).

All report functions are CHEAP and JIT-COMPATIBLE: pure `jnp`, fixed
shapes, no host syncs — they can run inside a jitted phase and cost a
few reductions.  The `assert_*` wrappers sync once at the end and raise
:class:`MeshContractError` with the full report.

The second half is the retrace counter: a context manager that counts
jit cache misses (via jax's compile logging) per named phase, with
optional budgets — the guard against the warm-cache/compile-budget
failures documented in ADVICE.md.

The third half (sic) is the collective-lockstep ledger: the runtime
backstop for the static SPMD divergence rules (PML012–PML016). Every
host-coordination collective dispatch rolls (name, seq, tag) into a
per-rank hash; `verify_ledger` psum-compares the digests at phase
boundaries under ``validate="full"``, so a desynced collective
schedule — the failure the static rules can only flag in SOURCE —
becomes a typed :class:`~parmmg_tpu.failsafe.CollectiveDivergenceError`
at the next boundary instead of a watchdog timeout deep inside some
later collective.
"""

from __future__ import annotations

import hashlib
import logging
from contextlib import contextmanager
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


class MeshContractError(AssertionError):
    """A runtime mesh/communicator invariant does not hold."""

    def __init__(self, message: str, report: dict):
        super().__init__(f"{message}: {report}")
        self.report = report


class RetraceBudgetExceeded(RuntimeError):
    """A phase recompiled more programs than its budget allows."""


# the counter currently installed via `RetraceCounter.__enter__` (one at
# a time — nesting replaces and restores). `budget_exempt` uses it to
# route failure-recovery compiles out of the budgeted phases.
_ACTIVE: Optional["RetraceCounter"] = None


@contextmanager
def budget_exempt(label: str = "failure-recovery"):
    """Attribute compiles inside this block to a ``recovery:<label>``
    phase instead of the current one. The failsafe layer wraps its
    grow-and-retry / clear-caches-and-retry re-entries in this: a
    recovery retry legitimately recompiles (capacities changed shape, or
    the executable cache was cleared), and charging those compiles to
    the steady phase would trip its budget for doing the right thing.
    Recovery phases still appear in `RetraceCounter.counts`, so the
    recompiles stay visible in BENCH/scale JSON — they are exempt from
    budgets (unless a ``recovery:*`` budget is set explicitly), not
    hidden."""
    counter = _ACTIVE
    if counter is None:
        yield
        return
    with counter.phase(f"recovery:{label}"):
        yield


@contextmanager
def no_host_transfers():
    """Forbid IMPLICIT device->host transfers inside the block (JAX's
    transfer guard) — the runtime twin of the static PML001 host-sync
    rule. The device-resident validator contract (``validate="basic"``
    on the SPMD path must never gather mesh arrays to host,
    `failsafe.stacked_status`) is asserted by running it under this
    guard: any implicit transfer raises immediately, while the one
    EXPLICIT `jax.device_get` of the tiny status table remains
    allowed — exactly the distinction the contract draws."""
    with jax.transfer_guard_device_to_host("disallow"):
        yield


# ---------------------------------------------------------------------------
# mesh invariants (jit-compatible)
# ---------------------------------------------------------------------------


def _conn_bad(conn, mask, vmask, pcap):
    """Count of valid entities referencing out-of-range or dead
    vertices."""
    in_range = (conn >= 0) & (conn < pcap)
    live = vmask[jnp.clip(conn, 0, pcap - 1)]
    ok = jnp.all(in_range & live, axis=1)
    return jnp.sum((mask & ~ok).astype(jnp.int32))


def mesh_invariant_report(mesh) -> Dict[str, jax.Array]:
    """Flat-mesh invariant counters, all-zero iff the mesh is coherent.

    Pure jnp on fixed shapes — safe to call under jit / shard_map (wrap
    per shard) and cheap enough for per-phase assertions.
    """
    pc, tc = mesh.pcap, mesh.tcap
    rep = dict(
        tet_conn_bad=_conn_bad(mesh.tet, mesh.tmask, mesh.vmask, pc),
        tria_conn_bad=_conn_bad(mesh.tria, mesh.trmask, mesh.vmask, pc),
        edge_conn_bad=_conn_bad(mesh.edge, mesh.edmask, mesh.vmask, pc),
    )
    # sentinel domains: -1 is the only legal negative
    rep["adja_sentinel_bad"] = jnp.sum(
        ((mesh.adja < -1) | (mesh.adja >= 4 * tc)).astype(jnp.int32)
    )
    rep["vglob_sentinel_bad"] = jnp.sum(
        (mesh.vglob < -1).astype(jnp.int32)
    )
    # adjacency: valid faces must point at live tets, and the gluing
    # must be an involution
    adja = mesh.adja
    has = (adja >= 0) & mesh.tmask[:, None]
    nb = jnp.clip(adja >> 2, 0, tc - 1)
    nf = adja & 3
    nb_live = mesh.tmask[nb]
    rep["adja_dead_ref"] = jnp.sum((has & ~nb_live).astype(jnp.int32))
    back = adja[nb, nf]
    want = 4 * jnp.arange(tc, dtype=jnp.int32)[:, None] + jnp.arange(
        4, dtype=jnp.int32
    )[None, :]
    rep["adja_sym_bad"] = jnp.sum(
        (has & nb_live & (back != want)).astype(jnp.int32)
    )
    return rep


def mesh_static_report(mesh) -> Dict[str, bool]:
    """Host-side (trace-time) dtype/shape contract: int32 connectivity,
    bool masks. Violations here are construction bugs, not data bugs."""
    i32 = jnp.int32
    return dict(
        tet_int32=mesh.tet.dtype == i32,
        tria_int32=mesh.tria.dtype == i32,
        edge_int32=mesh.edge.dtype == i32,
        adja_int32=mesh.adja.dtype == i32,
        vglob_int32=mesh.vglob.dtype == i32,
        masks_bool=(
            mesh.vmask.dtype == jnp.bool_
            and mesh.tmask.dtype == jnp.bool_
            and mesh.trmask.dtype == jnp.bool_
            and mesh.edmask.dtype == jnp.bool_
        ),
    )


def assert_mesh_ok(mesh, check_adjacency: bool = True) -> dict:
    """Host wrapper: one device sync, raises MeshContractError with the
    full report on any violation. Returns the (host-int) report."""
    static = mesh_static_report(mesh)
    if not all(static.values()):
        raise MeshContractError("mesh dtype contract violated", static)
    rep = {k: int(v) for k, v in
           jax.device_get(mesh_invariant_report(mesh)).items()}
    skip = ("adja_sym_bad", "adja_dead_ref") if not check_adjacency else ()
    if any(v for k, v in rep.items() if k not in skip):
        raise MeshContractError("mesh invariants violated", rep)
    return rep


# ---------------------------------------------------------------------------
# communicator invariants (jit-compatible)
# ---------------------------------------------------------------------------


def comm_invariant_report(comm) -> Dict[str, jax.Array]:
    """Topological node-communicator invariants, mirroring the checks
    of `parallel/chkcomm.py` without the geometric halo exchange:

    - comm_idx slots in [-1, PC) and pointing at globally-numbered
      vertices;
    - per-pair counts table consistent with the index table;
    - OWNER-RANK CONSISTENCY: every shared global vertex has exactly
      one owning shard among its copies (the reference's
      `PMMG_count_nodes_par` dedup contract).
    """
    D, PC = comm.l2g.shape
    ci = comm.comm_idx
    rep = dict(
        idx_range_bad=jnp.sum(((ci < -1) | (ci >= PC)).astype(jnp.int32))
    )
    valid = ci >= 0
    safe = jnp.clip(ci, 0, PC - 1)
    gid_at = jax.vmap(lambda l, i: l[i])(comm.l2g, safe)  # [D, D, I]
    rep["idx_dead_ref"] = jnp.sum(
        (valid & (gid_at < 0)).astype(jnp.int32)
    )
    rep["counts_bad"] = jnp.sum(
        (comm.counts != jnp.sum(valid.astype(jnp.int32), axis=-1))
        .astype(jnp.int32)
    )
    # owner-rank consistency over the global id space
    gcap = D * PC
    live = comm.l2g >= 0
    gid = jnp.clip(comm.l2g, 0, gcap - 1).reshape(-1)
    rep["gid_range_bad"] = jnp.sum(
        (live & (comm.l2g >= gcap)).astype(jnp.int32)
    )
    own = jnp.zeros(gcap, jnp.int32).at[gid].add(
        (comm.owner & live).reshape(-1).astype(jnp.int32), mode="drop"
    )
    cpy = jnp.zeros(gcap, jnp.int32).at[gid].add(
        live.reshape(-1).astype(jnp.int32), mode="drop"
    )
    rep["owner_bad"] = jnp.sum(((cpy > 0) & (own != 1)).astype(jnp.int32))
    return rep


def assert_comm_ok(comm) -> dict:
    """Host wrapper for `comm_invariant_report` (topological half; the
    geometric half stays in `parallel.chkcomm.assert_comm_ok`)."""
    rep = {k: int(v) for k, v in
           jax.device_get(comm_invariant_report(comm)).items()}
    if any(rep.values()):
        raise MeshContractError("communicator invariants violated", rep)
    return rep


# ---------------------------------------------------------------------------
# retrace counter
# ---------------------------------------------------------------------------


# jax compiles op-by-op dispatch outside jit as tiny jits named after
# the primitive; they fire once per process and are not retraces of a
# user program — excluded from the counts by default
_DISPATCH_NOISE = frozenset({
    "convert_element_type", "broadcast_in_dim", "copy", "iota",
    "reshape", "squeeze", "transpose", "concatenate", "slice",
})

# the logger that emits "Compiling <name> ..." exactly once per jit
# cache miss (jax._src/interpreters/pxla.py), and its siblings that
# turn noisy under jax_log_compiles
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_NOISY_LOGGERS = (
    "jax._src.dispatch", "jax._src.compiler", "jax._src.compilation_cache",
)


class _CompileLogHandler(logging.Handler):
    def __init__(self, counter: "RetraceCounter"):
        super().__init__(level=logging.WARNING)
        self.counter = counter

    def emit(self, record: logging.LogRecord) -> None:
        msg = str(record.msg)
        if not msg.startswith("Compiling "):
            return
        name = str(record.args[0]) if record.args else "<unknown>"
        if name in _DISPATCH_NOISE:
            return
        self.counter._record(name)


class RetraceCounter:
    """Counts jit cache misses (XLA compilations) per named phase.

    Uses jax's compile logging (`jax_log_compiles`): every trace that
    reaches compilation logs one "Compiling <name> ..." record — exactly
    the event a warm cache must not produce.  Phases are entered either
    via the `phase(name, budget=)` context manager or sequentially via
    `enter_phase(name)` (the shape of `models.adapt`'s phase hook).

    >>> counter = RetraceCounter()
    >>> with counter, counter.phase("sweeps", budget=2):
    ...     run_sweeps()          # raises RetraceBudgetExceeded if >2
    >>> counter.counts
    {'sweeps': 1}
    """

    def __init__(self) -> None:
        self.counts: Dict[str, int] = {}
        self.names: Dict[str, list] = {}
        self._phase = "<outside>"
        self._handler: Optional[_CompileLogHandler] = None
        self._prev_flag = None

    def _record(self, name: str) -> None:
        self.counts[self._phase] = self.counts.get(self._phase, 0) + 1
        self.names.setdefault(self._phase, []).append(name)
        # the obs registry mirrors the per-phase miss counts so the
        # run report's retrace table needs no live counter handle
        # (compiles are rare — the lazy import costs nothing steady)
        from ..obs import metrics as obs_metrics

        obs_metrics.registry().counter(
            f"recompiles/{self._phase}"
        ).inc()

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def enter_phase(self, name: str) -> None:
        self._phase = name

    def __enter__(self) -> "RetraceCounter":
        global _ACTIVE
        self._prev_active = _ACTIVE
        _ACTIVE = self
        self._prev_flag = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        self._handler = _CompileLogHandler(self)
        src = logging.getLogger(_PXLA_LOGGER)
        src.addHandler(self._handler)
        # capture at the source and stop propagation: the counter, not
        # the console, consumes the "Compiling" records — and quiet the
        # sibling loggers jax_log_compiles turns on
        self._prev_prop = src.propagate
        src.propagate = False
        self._prev_levels = []
        for name in _NOISY_LOGGERS:
            lg = logging.getLogger(name)
            self._prev_levels.append((lg, lg.level))
            lg.setLevel(logging.ERROR)
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev_active
        src = logging.getLogger(_PXLA_LOGGER)
        src.removeHandler(self._handler)
        src.propagate = self._prev_prop
        for lg, level in self._prev_levels:
            lg.setLevel(level)
        self._handler = None
        jax.config.update("jax_log_compiles", self._prev_flag)

    @contextmanager
    def phase(self, name: str, budget: Optional[int] = None):
        prev = self._phase
        self._phase = name
        start = self.counts.get(name, 0)
        try:
            yield self
        finally:
            self._phase = prev
            n = self.counts.get(name, 0) - start
            if budget is not None and n > budget:
                raise RetraceBudgetExceeded(
                    f"phase '{name}' recompiled {n} programs "
                    f"(budget {budget}): {self.names.get(name, [])[-n:]}"
                )

    def check(self, budgets: Dict[str, int]) -> None:
        """Post-hoc budget check over accumulated per-phase counts."""
        for name, budget in budgets.items():
            n = self.counts.get(name, 0)
            if n > budget:
                raise RetraceBudgetExceeded(
                    f"phase '{name}' recompiled {n} programs "
                    f"(budget {budget}): {self.names.get(name, [])}"
                )


def run_adapt_with_budget(
    mesh,
    opts=None,
    budgets: Optional[Dict[str, int]] = None,
    counter: Optional[RetraceCounter] = None,
):
    """Run `models.adapt.adapt` under the retrace counter and enforce
    per-phase compile budgets (phase names are adapt's own markers:
    "analysis", "metric", "input histogram", "sweeps", "finalize").

    Returns (mesh, info) with info["recompiles"] = per-phase counts;
    raises RetraceBudgetExceeded when a budgeted phase overdraws.
    """
    from ..models.adapt import adapt

    counter = counter or RetraceCounter()
    with counter:
        counter.enter_phase("setup")
        out, info = adapt(mesh, opts, phase_hook=counter.enter_phase)
    counter.check(budgets or {})
    info["recompiles"] = dict(counter.counts)
    return out, info


# ---------------------------------------------------------------------------
# collective-lockstep ledger (runtime half of PML012–PML016)
# ---------------------------------------------------------------------------

# agree_flags psums int32: the sum-of-squares round needs
# world * (2^DIGEST_BITS - 1)^2 < 2^31, which 12 bits satisfies for
# worlds up to 128 processes — far beyond anything this repo runs
_DIGEST_BITS = 12


class CollectiveLedger:
    """Per-rank rolling hash of the host-collective dispatch schedule.

    The whole coordination layer (`parallel.multihost`) depends on every
    process dispatching the same collectives in the same order; the
    static rules PML012–PML016 reject the source patterns that break
    that, and this ledger is the runtime check of the same contract:
    each `_coll_span` rolls ``(name, seq, tag)`` into a sha256, and
    `verify_ledger` compares the truncated digests across the world.
    A rank that skipped (or injected) a collective carries a different
    digest, and EVERY rank sees the mismatch at the same boundary —
    the desync becomes a simultaneous typed error, not one rank hanging
    in a collective its peers never dispatch.
    """

    def __init__(self) -> None:
        self._hash = hashlib.sha256()
        self.count = 0
        self.last = ""

    def record(self, name: str, seq: int, sig: str = "") -> None:
        self._hash.update(f"{name}:{seq}:{sig}\n".encode())
        self.count += 1
        self.last = f"{name}#{seq}"

    @property
    def digest(self) -> int:
        """Truncated schedule digest, small enough that the world sum
        AND the world sum of squares both fit the int32 psum lane."""
        return int(self._hash.hexdigest()[:8], 16) & (
            (1 << _DIGEST_BITS) - 1
        )


# the ledger currently recording (one at a time, like the retrace
# counter's _ACTIVE): None keeps `record_collective` a single attribute
# load + comparison, so validate="basic"/"off" runs pay nothing
_LEDGER: Optional[CollectiveLedger] = None


def install_ledger() -> CollectiveLedger:
    """Arm collective-schedule recording (idempotent: an already
    installed ledger keeps accumulating — nested harnesses must share
    one schedule, a reset mid-run would desync the comparison)."""
    global _LEDGER
    if _LEDGER is None:
        _LEDGER = CollectiveLedger()
    return _LEDGER


def uninstall_ledger() -> None:
    global _LEDGER
    _LEDGER = None


def ledger() -> Optional[CollectiveLedger]:
    return _LEDGER


def record_collective(name: str, seq: int, sig: str = "") -> None:
    """Hook for `parallel.multihost._coll_span`: one None-check when no
    ledger is installed (the steady-state path)."""
    if _LEDGER is not None:
        _LEDGER.record(name, seq, sig)


def verify_ledger(it: int, phase: str = "iteration",
                  timeout: Optional[float] = None) -> None:
    """World-compare the collective schedule digests; raise the typed
    :class:`~parmmg_tpu.failsafe.CollectiveDivergenceError` on EVERY
    rank when they disagree.

    Two `agree_flags` rounds carry the digest sum and the digest
    sum-of-squares; by Cauchy–Schwarz ``world * sum(d^2) == sum(d)^2``
    iff all digests are equal, and both sums are psum-replicated, so
    every rank computes the SAME verdict — the whole world raises
    together instead of a subset raising while the rest wedge in the
    next collective. No-op single-process or with no ledger installed.
    """
    led = _LEDGER
    if led is None:
        return
    from ..parallel import multihost

    if not multihost.is_multiprocess():
        return
    mine = led.digest
    count = led.count
    world = jax.process_count()
    # the verification rounds are themselves collectives every rank
    # dispatches here, so they extend the ledger identically everywhere
    s1 = multihost.agree_flags(
        mine, tag=f"ledger:{phase}:{it}", timeout=timeout
    )
    s2 = multihost.agree_flags(
        mine * mine, tag=f"ledger2:{phase}:{it}", timeout=timeout
    )
    if world * s2 == s1 * s1:
        return
    from ..obs import trace as obs_trace

    obs_trace.emit_event(
        "collective_divergence", it=int(it), phase=phase,
        rank=int(jax.process_index()), digest=int(mine),
        count=int(count), last=led.last,
    )
    from .. import failsafe

    raise failsafe.CollectiveDivergenceError(
        f"collective schedule diverged at {phase} boundary (it {it}): "
        f"rank {jax.process_index()} digest {mine:#05x} after {count} "
        f"collectives (last {led.last!r}) disagrees with the world "
        f"(sum {s1}, sum-of-squares {s2}, world {world}) — a subset of "
        "ranks skipped or injected a collective; resume from the last "
        "committed checkpoint"
    )
