"""parmmg_tpu.lint — JAX-invariant static analyzer + runtime contracts.

The reference ParMmg guards its pointer kernels with pervasive runtime
assertions and communicator checks (`chkcomm_pmmg.c`); this package is
the analogous guard rail for the TPU port, whose correctness hinges on
*implicit* JAX invariants instead: fixed array shapes, `-1` sentinel
padding, int32 connectivity, and no host syncs or retraces inside the
jitted remesh-repartition loop.

Two halves:

- the AST static analyzer (`python -m parmmg_tpu.lint <paths>`), rule
  catalog in `rules.py`, engine in `analyzer.py`.  Pure-stdlib: linting
  never imports jax.
- the runtime contract layer (`contracts.py`): cheap jit-compatible
  mesh/communicator invariant checkers plus a retrace-counter harness.
  Imported lazily so the CLI stays light.

Suppression syntax (same line, the line above, or the `def`/decorator
line to scope a whole function)::

    x = np.asarray(t)  # parmmg-lint: disable=PML001  -- host fallback path

File-level, in the first comment block::

    # parmmg-lint: disable-file=PML009
"""

from .analyzer import Finding, Project, analyze_paths  # noqa: F401
from .rules import RULES, run_lint  # noqa: F401

__all__ = ["Finding", "Project", "analyze_paths", "RULES", "run_lint"]
