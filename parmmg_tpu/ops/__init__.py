from . import quality  # noqa: F401
