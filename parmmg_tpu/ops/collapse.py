"""Batched edge collapse: coarsen every metric-short edge in parallel.

Counterpart of the coarsening half of Mmg's kernel (`MMG5_mmg3d1_delone` via
reference `src/libparmmg1.c:739`). A candidate short edge (src→dst) removes
vertex src and retargets its ball onto dst. Independent-set selection uses
the union of tets touching either endpoint as the conflict arena, which
guarantees (a) each vertex joins at most one collapse per sweep and (b)
simultaneous application is safe. Validity = positive volumes + bounded
quality loss; topological safety (Mmg's link condition) is enforced by a
vectorized duplicate-tet detector on the tentative configuration.

Round-1 scope: interior vertices only — boundary/ridge collapses arrive
with the surface-analysis milestone (Hausdorff control), so the boundary
surface is preserved exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import metric as metric_mod
from ..core import tags
from ..core.mesh import Mesh
from . import common


class CollapseStats(NamedTuple):
    ncollapse: jax.Array
    ncand: jax.Array
    nrej_geom: jax.Array   # rejected by volume/quality
    nrej_topo: jax.Array   # rejected by duplicate-tet (link) check


@partial(jax.jit, static_argnames=("lshrt",), donate_argnums=0)
def collapse_short_edges(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
    lshrt: float = float(metric_mod.LSHRT),
):
    """One collapse sweep. Mesh must be compacted; adjacency left stale."""
    ecap = edges.shape[0]
    tcap, pcap = mesh.tcap, mesh.pcap
    tet, tmask = mesh.tet, mesh.tmask

    a, b = edges[:, 0], edges[:, 1]
    l = metric_mod.edge_length(
        mesh.vert[a], mesh.vert[b], mesh.met[a], mesh.met[b]
    )
    interior = mesh.vmask & (
        (mesh.vtag & (tags.UNCOLLAPSIBLE | tags.BDY | tags.OVERLAP)) == 0
    )
    ra, rb = interior[a], interior[b]
    cand = emask & (l < lshrt) & (ra | rb)
    src = jnp.where(ra, a, b)
    dst = jnp.where(ra, b, a)
    ncand = jnp.sum(cand.astype(jnp.int32))

    # --- arena selection: tets containing src or dst ----------------------
    def scatter_arena(vals):
        vb = jnp.full(pcap, -jnp.inf, vals.dtype)
        vb = vb.at[src].max(vals, mode="drop")
        vb = vb.at[dst].max(vals, mode="drop")
        tv = jnp.max(vb[tet], axis=1)
        return jnp.where(tmask, tv, -jnp.inf)

    def gather_arena(tv):
        ub = jnp.full(pcap, -jnp.inf, tv.dtype)
        idx = jnp.where(tmask[:, None], tet, pcap)
        ub = ub.at[idx.reshape(-1)].max(
            jnp.broadcast_to(tv[:, None], (tcap, 4)).reshape(-1), mode="drop"
        )
        return jnp.maximum(ub[src], ub[dst])

    # shorter edge = higher priority
    win = common.two_phase_winners(-l, cand, scatter_arena, gather_arena)

    # per-vertex winner map (each vertex touched by <= 1 winner)
    eidx = jnp.arange(ecap, dtype=jnp.int32)
    wv = jnp.full(pcap, -1, jnp.int32)
    wv = wv.at[jnp.where(win, src, pcap)].max(eidx, mode="drop")
    wv = wv.at[jnp.where(win, dst, pcap)].max(eidx, mode="drop")

    # per-tet winner and role
    wt4 = wv[tet]                                   # [TC,4]
    e_t = jnp.max(wt4, axis=1)                      # winner edge or -1
    has = (e_t >= 0) & tmask
    e_ts = jnp.maximum(e_t, 0)
    src_t, dst_t = src[e_ts], dst[e_ts]
    has_src = jnp.any(tet == src_t[:, None], axis=1) & has
    has_dst = jnp.any(tet == dst_t[:, None], axis=1) & has
    is_shell = has_src & has_dst
    is_ball = has_src & ~is_shell

    new_tet = jnp.where(
        (tet == src_t[:, None]) & is_ball[:, None], dst_t[:, None], tet
    )
    q_old = common.quality_of(mesh.vert, mesh.met, tet)
    q_new = common.quality_of(mesh.vert, mesh.met, new_tet)
    vol_new = common.vol_of(mesh.vert, new_tet)
    # scale-relative positivity (common.POS_VOL_FRAC of the tet's own
    # old volume)
    vol_old = common.vol_of(mesh.vert, tet)
    vol_floor = common.POS_VOL_FRAC * jnp.abs(vol_old)

    # --- geometric validity per winner ------------------------------------
    inf = jnp.inf
    ball_old = jnp.full(ecap, inf).at[jnp.where(is_ball, e_t, ecap)].min(
        q_old, mode="drop"
    )
    ball_new = jnp.full(ecap, inf).at[jnp.where(is_ball, e_t, ecap)].min(
        jnp.where(vol_new > vol_floor, q_new, -inf), mode="drop"
    )
    # accept if the new ball keeps ~a third of the old worst quality (the
    # class of criterion Mmg's colver uses) or is absolutely decent, with
    # a hard floor against degenerate configurations
    ok_geom = (ball_new >= 0.3 * ball_old) | (ball_new >= 0.3)
    ok_geom = ok_geom & (ball_new > 0.02) & jnp.isfinite(ball_new)
    accept = win & ok_geom
    nrej_geom = jnp.sum((win & ~ok_geom).astype(jnp.int32))

    # --- topological check: tentative apply + duplicate detection ---------
    app_t = is_ball & accept[e_ts]
    del_t = is_shell & accept[e_ts]
    tet_tent = jnp.where(app_t[:, None], new_tet, tet)
    valid_tent = tmask & ~del_t
    dup = common.duplicate_tets(tet_tent, valid_tent)
    bad_e = jnp.zeros(ecap, bool).at[jnp.where(dup & has, e_t, ecap)].max(
        True, mode="drop"
    )
    nrej_topo = jnp.sum((accept & bad_e).astype(jnp.int32))
    accept = accept & ~bad_e

    # --- final apply -------------------------------------------------------
    app_t = is_ball & accept[e_ts]
    del_t = is_shell & accept[e_ts]
    tet_out = jnp.where(app_t[:, None], new_tet, tet)
    tmask_out = tmask & ~del_t
    vmask_out = mesh.vmask.at[jnp.where(accept, src, pcap)].set(
        False, mode="drop"
    )
    ncollapse = jnp.sum(accept.astype(jnp.int32))

    out = mesh.replace(tet=tet_out, tmask=tmask_out, vmask=vmask_out)
    return out, CollapseStats(
        ncollapse=ncollapse, ncand=ncand, nrej_geom=nrej_geom,
        nrej_topo=nrej_topo,
    )
