"""Batched edge collapse: coarsen every metric-short edge in parallel.

Counterpart of the coarsening half of Mmg's kernel (`MMG5_mmg3d1_delone`
via reference `src/libparmmg1.c:739`), including the boundary discipline
of `MMG5_colver`/`chkcol_bdy`: a candidate short edge (src→dst) removes
vertex src and retargets its ball onto dst. Independent-set selection
uses the union of tets touching either endpoint as the conflict arena,
which guarantees (a) each vertex joins at most one collapse per sweep and
(b) simultaneous application is safe. Validity = positive volumes +
bounded quality loss; topological safety (Mmg's link condition) is
enforced by a vectorized duplicate-tet detector on the tentative
configuration.

Boundary discipline (batched re-design of `chkcol_bdy`):
 - vertex classes order collapsibility: free interior > regular surface >
   feature-line (ridge/ref) vertex; corners, required, non-manifold and
   parallel-interface vertices are never removed (`MG_CORNER`/`MG_REQ`/
   `MG_PARBDY` semantics, reference `src/tag_pmmg.c`).
 - a surface vertex may only slide along a *surface* edge, a feature
   vertex only along a *feature* edge — the collapse stays on the
   geometry it discretizes.
 - surface fidelity: every retargeted boundary tria must keep its
   orientation within the dihedral threshold (no folds, no new ridges)
   and the removed vertex must stay within `hausd` of the new surface
   (the Hausdorff control of Mmg's `-hausd`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import kernels
from ..core import metric as metric_mod
from ..core import tags
from ..core.mesh import Mesh
from . import common
from .analysis import surf_tria_mask

_FEAT_BITS = tags.RIDGE | tags.REF | tags.NOM
# vertices that can never be removed
_HARD = tags.REQUIRED | tags.CORNER | tags.PARBDY | tags.NOM | tags.OVERLAP
# normal-deviation bound for retargeted surface trias (cos 45deg — the
# angle-detection threshold: a collapse must not create a new ridge)
_COS_SURF = 0.70710678


class CollapseStats(NamedTuple):
    ncollapse: jax.Array
    ncand: jax.Array
    nrej_geom: jax.Array   # rejected by volume/quality
    nrej_topo: jax.Array   # rejected by duplicate-tet (link) check
    nrej_surf: jax.Array   # rejected by surface fidelity (fold/hausd)
    nsurf: jax.Array       # accepted collapses that moved the surface
    changed_v: jax.Array   # [PC] bool — vertices whose 1-ring changed


@partial(jax.jit, static_argnames=("lshrt", "nosurf"), donate_argnums=0)
def collapse_short_edges(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
    lshrt: float = float(metric_mod.LSHRT),
    hausd: float = 0.01,
    nosurf: bool = False,
    active: jax.Array | None = None,
):
    """One collapse sweep. Mesh must be compacted; adjacency left stale.

    With an `active` vertex mask (the one-ring closure of the previous
    sweep's changes — frontier mode, round 6), candidates are restricted
    to short edges near the frontier and the whole heavy phase (edge
    classes, selection loop, validity evaluation, apply) is skipped via
    `lax.cond` when no short active edge exists. `active=None`
    reproduces the full-table sweep exactly."""
    ecap = edges.shape[0]
    tcap, pcap, fcap = mesh.tcap, mesh.pcap, mesh.fcap
    tet, tmask = mesh.tet, mesh.tmask

    a, b = edges[:, 0], edges[:, 1]
    l = metric_mod.edge_length(
        mesh.vert[a], mesh.vert[b], mesh.met[a], mesh.met[b]
    )
    pre = emask & (l < lshrt)
    if active is not None:
        # frontier gate: an inactive short edge was offered to the MIS
        # last sweep with an identical ball and did not act
        pre = pre & (active[a] | active[b])

    # --- vertex classes ---------------------------------------------------
    vt = mesh.vtag
    hard = (vt & _HARD) != 0
    bdy_v = (vt & tags.BDY) != 0
    feat_v = (vt & _FEAT_BITS) != 0
    free_i = mesh.vmask & ~hard & ~bdy_v
    surf_v = mesh.vmask & ~hard & bdy_v & ~feat_v
    ridge_v = mesh.vmask & ~hard & bdy_v & feat_v
    score = (
        3 * free_i.astype(jnp.int32)
        + 2 * surf_v.astype(jnp.int32)
        + ridge_v.astype(jnp.int32)
    )
    if nosurf:
        score = jnp.where(free_i, 3, 0)

    # --- edge classes (inside the frontier skip: the surf/feat
    # memberships are sort-merge passes) -----------------------------------
    def _edge_classes(mesh):
        smask = surf_tria_mask(mesh)
        tri_keys = common.tria_edge_keys(mesh, smask)
        surf_e = common.sorted_membership(
            tri_keys, jnp.where(emask[:, None], edges, -1), bound=mesh.pcap
        )
        feat = common.feature_edge_index(mesh, edges, emask)
        feat_tag = jnp.where(feat >= 0, mesh.edtag[jnp.maximum(feat, 0)], 0)
        feat_e = (feat_tag & _FEAT_BITS) != 0
        return surf_e, feat_e

    sa, sb = score[a], score[b]
    src_is_a = sa >= sb
    src = jnp.where(src_is_a, a, b)
    dst = jnp.where(src_is_a, b, a)
    s_src = jnp.maximum(sa, sb)

    # --- arena selection: tets containing src or dst ----------------------
    def scatter_arena(vals):
        vb = jnp.full(pcap, -jnp.inf, vals.dtype)
        vb = vb.at[src].max(vals, mode="drop")
        vb = vb.at[dst].max(vals, mode="drop")
        tv = jnp.max(vb[tet], axis=1)
        return jnp.where(tmask, tv, -jnp.inf)

    def gather_arena(tv):
        ub = jnp.full(pcap, -jnp.inf, tv.dtype)
        idx = jnp.where(tmask[:, None], tet, pcap)
        ub = ub.at[idx.reshape(-1)].max(
            jnp.broadcast_to(tv[:, None], (tcap, 4)).reshape(-1), mode="drop"
        )
        return jnp.maximum(ub[src], ub[dst])

    def _heavy(mesh):
        surf_e, feat_e = _edge_classes(mesh)
        legal = (
            (s_src == 3)
            | ((s_src == 2) & surf_e)
            | ((s_src == 1) & feat_e)
        )
        cand = pre & legal
        ncand = jnp.sum(cand.astype(jnp.int32)).astype(jnp.int32)

        # win-independent quantities, hoisted out of the evaluation
        # (fused quality+volume: one pass over the tet stream instead
        # of two — kernels.quality_vol, Pallas on TPU)
        q_old, vol_old = kernels.quality_vol(mesh.vert, mesh.met, tet)
        # scale-relative positivity (common.POS_VOL_FRAC of the tet's own
        # old volume)
        vol_floor = common.POS_VOL_FRAC * jnp.abs(vol_old)

        def raw_normal(tri):
            p0, p1, p2 = mesh.vert[tri[:, 0]], mesh.vert[tri[:, 1]], mesh.vert[tri[:, 2]]
            return jnp.cross(p1 - p0, p2 - p0)

        r_old = raw_normal(mesh.tria)
        n_old = jnp.linalg.norm(r_old, axis=1)
        req_tria = (mesh.trtag & tags.REQUIRED) != 0
        eidx = jnp.arange(ecap, dtype=jnp.int32)

        def eval_winners(win):
            """Validity of a winner set with pairwise-disjoint arenas.

            Returns (accept, rej_geom, rej_surf, rej_topo [bool sets], aux
            intermediates for the apply step)."""
            # per-vertex winner map (each vertex touched by <= 1 winner)
            wv = jnp.full(pcap, -1, jnp.int32)
            wv = wv.at[jnp.where(win, src, pcap)].max(eidx, mode="drop")
            wv = wv.at[jnp.where(win, dst, pcap)].max(eidx, mode="drop")

            # per-tet winner and role
            wt4 = wv[tet]                                   # [TC,4]
            e_t = jnp.max(wt4, axis=1)                      # winner edge or -1
            has = (e_t >= 0) & tmask
            e_ts = jnp.maximum(e_t, 0)
            src_t, dst_t = src[e_ts], dst[e_ts]
            has_src = jnp.any(tet == src_t[:, None], axis=1) & has
            has_dst = jnp.any(tet == dst_t[:, None], axis=1) & has
            is_shell = has_src & has_dst
            is_ball = has_src & ~is_shell

            new_tet = jnp.where(
                (tet == src_t[:, None]) & is_ball[:, None], dst_t[:, None], tet
            )
            # fused cavity evaluation (the round-9 740 ms target): the
            # retargeted ring's quality, new volumes, and the
            # positivity gate in ONE VMEM-resident pass — the kernel
            # emits exactly the ball-min operand
            gate_new = kernels.collapse_cavity(
                mesh.vert, mesh.met, new_tet, vol_floor
            )

            # --- geometric validity per winner --------------------------------
            inf = jnp.inf
            ball_old = jnp.full(ecap, inf).at[jnp.where(is_ball, e_t, ecap)].min(
                q_old, mode="drop"
            )
            ball_new = jnp.full(ecap, inf).at[jnp.where(is_ball, e_t, ecap)].min(
                gate_new, mode="drop"
            )
            # accept if the new ball keeps ~a third of the old worst quality
            # (the class of criterion Mmg's colver uses) or is absolutely
            # decent, with a hard floor against degenerate configurations
            ok_geom = (ball_new >= 0.3 * ball_old) | (ball_new >= 0.3)
            ok_geom = ok_geom & (ball_new > 0.02) & jnp.isfinite(ball_new)
            rej_geom = win & ~ok_geom
            accept = win & ok_geom

            # --- surface fidelity for boundary collapses (chkcol_bdy role) ----
            # per-tria winner/role mirrors the tet logic
            wf3 = wv[mesh.tria]                              # [FC,3]
            e_f = jnp.max(wf3, axis=1)
            fhas = (e_f >= 0) & mesh.trmask
            e_fs = jnp.maximum(e_f, 0)
            src_f, dst_f = src[e_fs], dst[e_fs]
            f_has_src = jnp.any(mesh.tria == src_f[:, None], axis=1) & fhas
            f_has_dst = jnp.any(mesh.tria == dst_f[:, None], axis=1) & fhas
            f_shell = f_has_src & f_has_dst                  # deleted trias
            f_ball = f_has_src & ~f_shell                    # retargeted trias
            new_tria = jnp.where(
                (mesh.tria == src_f[:, None]) & f_ball[:, None],
                dst_f[:, None], mesh.tria,
            )

            r_new = raw_normal(new_tria)
            n_new = jnp.linalg.norm(r_new, axis=1)
            dotn = jnp.einsum("fi,fi->f", r_old, r_new) / jnp.maximum(
                n_old * n_new, 1e-30
            )
            # Hausdorff: removed vertex must stay within hausd of the plane
            # of every retargeted tria (point-to-plane, the batched stand-in
            # for Mmg's point-to-surface distance)
            unit_new = r_new / jnp.maximum(n_new, 1e-30)[:, None]
            dist = jnp.abs(
                jnp.einsum(
                    "fi,fi->f", unit_new,
                    mesh.vert[src_f] - mesh.vert[new_tria[:, 0]],
                )
            )
            degen = n_new < 1e-12 * jnp.maximum(n_old, 1e-30)
            # hausd may be a per-tria-reference table (parsop local
            # parameters): look up by the retargeted tria's reference
            hausd_f = (
                hausd[jnp.clip(mesh.trref, 0, hausd.shape[0] - 1)]
                if getattr(hausd, "ndim", 0)
                else hausd
            )
            tria_bad = f_ball & ((dotn < _COS_SURF) | (dist > hausd_f) | degen)
            # REQUIRED trias are immutable: any touched required tria kills it
            bad_surf = jnp.zeros(ecap, bool)
            bad_surf = bad_surf.at[
                jnp.where(tria_bad | (fhas & req_tria), e_f, ecap)
            ].max(True, mode="drop")
            rej_surf = accept & bad_surf
            accept = accept & ~bad_surf

            # --- topological check: tentative apply + duplicate detection -----
            app_t = is_ball & accept[e_ts]
            del_t = is_shell & accept[e_ts]
            tet_tent = jnp.where(app_t[:, None], new_tet, tet)
            valid_tent = tmask & ~del_t
            dup = common.duplicate_tets(tet_tent, valid_tent, bound=mesh.pcap)
            bad_e = jnp.zeros(ecap, bool).at[
                jnp.where(dup & has, e_t, ecap)
            ].max(True, mode="drop")
            rej_topo = accept & bad_e
            accept = accept & ~bad_e
            aux = (e_ts, is_ball, is_shell, new_tet, e_fs, f_ball, f_shell,
                   new_tria, wv)
            return accept, rej_geom, rej_surf, rej_topo, aux

        # Select → evaluate → commit, iterated. One round of the
        # 2-vertex-ball arena MIS is far too sparse for bulk coarsening (a
        # candidate must be the strict minimum of its whole 2-hop
        # neighborhood), so committed winners keep occupying their arenas
        # while further rounds pick among the remaining candidates.
        #
        # Each selection round is ONE arena max-propagation. Candidates
        # carry a per-sweep UNIQUE f32-exact integer rank (shorter edge =
        # higher rank, exact ties broken by a hashed index so uniform
        # meshes don't serialize on spatially-sorted edge ids), and
        # committed winners participate with +inf: a candidate whose arena
        # overlaps a committed winner sees +inf and can never win, which
        # implements arena claiming with no extra scatter/gather rounds
        # (the previous scheme spent 2 propagation rounds on the two-phase
        # priority+hash compare and a 3rd on explicit tet claiming — 3x the
        # HBM traffic for the same winner sets). Rejected winners are
        # excluded from the +inf set, so their arenas are released and stop
        # starving their neighborhoods (the serial kernel simply moves to
        # the next edge; this is the batched equivalent). Disjoint arenas
        # keep simultaneous application safe: each tet and each vertex
        # joins at most one winner.
        if ecap < (1 << 24):
            h24 = (
                jnp.arange(ecap, dtype=jnp.uint32) * jnp.uint32(2654435761)
            ) & jnp.uint32(0xFFFFFF)
            order = jnp.lexsort((h24, jnp.where(cand, l, jnp.inf)))
            rnk = (
                jnp.zeros(ecap, jnp.float32)
                .at[order]
                .set(jnp.arange(ecap, 0, -1, dtype=jnp.float32))
            )

            def select_round(w_acc, rej, sup):
                """One round: winners + newly-suppressed candidates.

                A candidate that sees +inf is permanently blocked by a
                committed winner; it must LEAVE the candidate pool (not
                merely lose), else its own rank keeps suppressing its
                neighborhood forever — candidates two hops from a winner
                would starve."""
                active = cand & ~w_acc & ~rej & ~sup
                pv = jnp.where(active, rnk, -jnp.inf)
                pv = jnp.where(w_acc, jnp.inf, pv)
                best = gather_arena(scatter_arena(pv))
                return active & (rnk >= best), active & jnp.isinf(best)
        else:
            # ranks stop being f32-exact beyond 2^24 edges: fall back to
            # the two-phase compare (priority then hashed index)
            def select_round(w_acc, rej, sup):
                active = cand & ~w_acc & ~rej & ~sup
                blocked = gather_arena(
                    scatter_arena(jnp.where(w_acc, 1.0, -jnp.inf))
                ) > 0.0
                w = common.two_phase_winners(
                    -l, active & ~blocked, scatter_arena, gather_arena
                )
                return w, active & blocked

        # initial carries derived from mesh data (not fresh constants) so
        # they inherit the device-varying type under shard_map — a literal
        # jnp.zeros carry is 'unvarying' and the loop body would change its
        # type on the first iteration
        zero_e = cand & False

        if common._split_scatter_cols():
            # TPU: each propagation round is fixed scatter/gather cost
            # whether or not it finds work, so the selection loops exit as
            # soon as a round adds no winners (the common case once the mesh
            # converges) and the validity evaluation is skipped when the
            # trial set did not change. On CPU the nested
            # while_loop/cond control flow costs more than it saves
            # (latency-bound small meshes measured -23%), so that backend
            # keeps the fixed fori_loop below.
            def sel_cond(carry):
                _, _, _, k, got = carry
                return (k < 5) & got

            def sel_body(carry):
                w_acc, rej, sup, k, _ = carry
                w, sup_add = select_round(w_acc, rej, sup)
                return (w_acc | w, rej, sup | sup_add, k + 1, jnp.any(w))

            def outer_cond(carry):
                _, _, _, _, k, got = carry
                return (k < 3) & got

            def outer_body(carry):
                win_acc, rej_g, rej_s, rej_t, k, _ = carry
                rej = rej_g | rej_s | rej_t
                # suppression resets each outer round: eval may reject
                # winners, releasing arenas the suppressed candidates need
                trial, _, _, _, _ = jax.lax.while_loop(
                    sel_cond, sel_body,
                    (win_acc, rej, zero_e, jnp.int32(0), jnp.any(cand)),
                )
                new_any = jnp.any(trial & ~win_acc)

                def do_eval(_):
                    acc, rg, rs, rt, _aux = eval_winners(trial)
                    return acc, rej_g | rg, rej_s | rs, rej_t | rt

                def skip_eval(_):
                    # selection added nothing: the carried set was already
                    # validated in the previous round
                    return win_acc, rej_g, rej_s, rej_t

                acc, rg_o, rs_o, rt_o = jax.lax.cond(
                    new_any, do_eval, skip_eval, None
                )
                return acc, rg_o, rs_o, rt_o, k + 1, new_any

            win_acc, rej_g, rej_s, rej_t, _, _ = jax.lax.while_loop(
                outer_cond, outer_body,
                (zero_e, zero_e, zero_e, zero_e, jnp.int32(0),
                 jnp.any(cand)),
            )
        else:
            def sel_body_f(_, carry):
                w_acc, rej, sup = carry
                w, sup_add = select_round(w_acc, rej, sup)
                return w_acc | w, rej, sup | sup_add

            def outer_body_f(_, carry):
                win_acc, rej_g, rej_s, rej_t = carry
                rej = rej_g | rej_s | rej_t
                trial, _, _ = jax.lax.fori_loop(
                    0, 5, sel_body_f, (win_acc, rej, zero_e)
                )
                acc, rg, rs, rt, _aux = eval_winners(trial)
                return acc, rej_g | rg, rej_s | rs, rej_t | rt

            win_acc, rej_g, rej_s, rej_t = jax.lax.fori_loop(
                0, 3, outer_body_f,
                (zero_e, zero_e, zero_e, zero_e),
            )
        # Cheap final pass: winners were fully validated inside the loop;
        # re-derive only the apply intermediates (scatter/compare, no
        # quality/surface re-evaluation) plus one duplicate guard on exactly
        # the applied configuration — removing rejected winners restores
        # their shell tets, which could in principle re-collide with a
        # survivor's retarget.
        win = win_acc
        wv = jnp.full(pcap, -1, jnp.int32)
        wv = wv.at[jnp.where(win, src, pcap)].max(eidx, mode="drop")
        wv = wv.at[jnp.where(win, dst, pcap)].max(eidx, mode="drop")
        wt4 = wv[tet]
        e_t = jnp.max(wt4, axis=1)
        has = (e_t >= 0) & tmask
        e_ts = jnp.maximum(e_t, 0)
        src_t, dst_t = src[e_ts], dst[e_ts]
        has_src = jnp.any(tet == src_t[:, None], axis=1) & has
        has_dst = jnp.any(tet == dst_t[:, None], axis=1) & has
        is_shell = has_src & has_dst
        is_ball = has_src & ~is_shell
        new_tet = jnp.where(
            (tet == src_t[:, None]) & is_ball[:, None], dst_t[:, None], tet
        )
        wf3 = wv[mesh.tria]
        e_f = jnp.max(wf3, axis=1)
        fhas = (e_f >= 0) & mesh.trmask
        e_fs = jnp.maximum(e_f, 0)
        src_f, dst_f = src[e_fs], dst[e_fs]
        f_has_src = jnp.any(mesh.tria == src_f[:, None], axis=1) & fhas
        f_has_dst = jnp.any(mesh.tria == dst_f[:, None], axis=1) & fhas
        f_shell = f_has_src & f_has_dst
        f_ball = f_has_src & ~f_shell
        new_tria = jnp.where(
            (mesh.tria == src_f[:, None]) & f_ball[:, None],
            dst_f[:, None], mesh.tria,
        )
        dup = common.duplicate_tets(
            jnp.where((is_ball & win[e_ts])[:, None], new_tet, tet),
            tmask & ~(is_shell & win[e_ts]),
            bound=mesh.pcap,
        )
        bad_e = jnp.zeros(ecap, bool).at[
            jnp.where(dup & has, e_t, ecap)
        ].max(True, mode="drop")
        accept = win & ~bad_e
        nrej_geom = jnp.sum(rej_g.astype(jnp.int32)).astype(jnp.int32)
        nrej_surf = jnp.sum(rej_s.astype(jnp.int32)).astype(jnp.int32)
        nrej_topo = jnp.sum((rej_t | bad_e).astype(jnp.int32)).astype(jnp.int32)

        # --- final apply -------------------------------------------------------
        app_t = is_ball & accept[e_ts]
        del_t = is_shell & accept[e_ts]
        tet_out = jnp.where(app_t[:, None], new_tet, tet)
        tmask_out = tmask & ~del_t
        vmask_out = mesh.vmask.at[jnp.where(accept, src, pcap)].set(
            False, mode="drop"
        )
        # trias: delete shells, retarget balls
        app_f = f_ball & accept[e_fs]
        del_f = f_shell & accept[e_fs]
        tria_out = jnp.where(app_f[:, None], new_tria, mesh.tria)
        trmask_out = mesh.trmask & ~del_f
        # feature edges: same discipline
        we2 = wv[mesh.edge]                              # [EC,2]
        e_e = jnp.max(we2, axis=1)
        ehas = (e_e >= 0) & mesh.edmask
        e_es = jnp.maximum(e_e, 0)
        src_e, dst_e = src[e_es], dst[e_es]
        g_has_src = jnp.any(mesh.edge == src_e[:, None], axis=1) & ehas
        g_has_dst = jnp.any(mesh.edge == dst_e[:, None], axis=1) & ehas
        g_shell = g_has_src & g_has_dst
        g_ball = g_has_src & ~g_shell
        new_edge = jnp.where(
            (mesh.edge == src_e[:, None]) & g_ball[:, None],
            dst_e[:, None], mesh.edge,
        )
        app_g = g_ball & accept[e_es]
        del_g = g_shell & accept[e_es]
        edge_out = jnp.where(app_g[:, None], new_edge, mesh.edge)
        edmask_out = mesh.edmask & ~del_g

        ncollapse = jnp.sum(accept.astype(jnp.int32)).astype(jnp.int32)
        nsurf = jnp.sum((accept & (s_src < 3)).astype(jnp.int32)).astype(jnp.int32)

        # frontier: every vertex of a retargeted or deleted tet (the
        # deleted shell rows still read their original vertices, so src
        # and the whole ring land in the mark)
        chg = jnp.zeros(pcap, bool).at[
            jnp.where((app_t | del_t)[:, None], new_tet, pcap).reshape(-1)
        ].set(True, mode="drop")
        chg = chg.at[jnp.where(accept, dst, pcap)].set(True, mode="drop")

        out = mesh.replace(
            tet=tet_out, tmask=tmask_out, vmask=vmask_out,
            tria=tria_out, trmask=trmask_out,
            edge=edge_out, edmask=edmask_out,
        )
        return (out, ncollapse, ncand, nrej_geom, nrej_topo, nrej_surf,
                nsurf, chg)

    def _skip(mesh):
        z = jnp.int32(0)
        return mesh, z, z, z, z, z, z, jnp.zeros(pcap, bool)

    if active is None:
        (out, ncollapse, ncand, nrej_geom, nrej_topo, nrej_surf, nsurf,
         chg) = _heavy(mesh)
    else:
        # converged regions: no short active edge anywhere means no
        # surf/feat sort-merge, no selection loop, no duplicate sorts
        (out, ncollapse, ncand, nrej_geom, nrej_topo, nrej_surf, nsurf,
         chg) = jax.lax.cond(jnp.any(pre), _heavy, _skip, mesh)
    return out, CollapseStats(
        ncollapse=ncollapse, ncand=ncand, nrej_geom=nrej_geom,
        nrej_topo=nrej_topo, nrej_surf=nrej_surf, nsurf=nsurf,
        changed_v=chg,
    )
