"""Batched vertex smoothing (relaxation toward neighbor centroid).

Counterpart of Mmg's vertex-move operators inside `MMG5_mmg3d1_delone`
(reference `src/libparmmg1.c:739`): `movintpt` for free interior vertices,
`movbdyregpt` for regular surface vertices (tangential motion only), and
`movbdyridpt` for feature-line vertices (motion along the feature).
Free interior vertices relax toward the centroid of their edge-neighbors
(Jacobi, under-relaxed); surface vertices relax toward the centroid of
their *surface* neighbors with the normal component of the displacement
removed (first-order geometry preservation); ridge vertices toward the
centroid of their *feature* neighbors. Validity is restored iteratively:
tets that would invert or degrade too much — and surface trias whose
normal would swing past the dihedral threshold (no folds, no new ridges)
— freeze all their vertices back to the original positions; the freeze
loop runs a fixed number of rounds (XLA-friendly) with a global revert as
the final safety net, so the sweep never worsens the worst element below
the bound.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import kernels
from ..core import tags
from ..core.mesh import Mesh
from . import common
from .analysis import surf_tria_mask, vertex_normals

_FEAT_BITS = tags.RIDGE | tags.REF | tags.NOM
_HARD = tags.REQUIRED | tags.CORNER | tags.PARBDY | tags.NOM | tags.OVERLAP
_COS_SURF = 0.70710678
# a vertex whose accepted displacement stays below this fraction of its
# local metric size did not meaningfully move: the move is SUPPRESSED
# (the vertex snaps back to its old position) and the vertex does not
# re-enter the next sweep's frontier. Without the snap, Laplacian
# relaxation never reaches a literal fixed point — a converged mesh
# keeps jittering ~80% of its vertices by ~0.5% of h per sweep
# (measured round 6) and the active set never drains. 0.5% of the local
# metric size is far below any length band that could flip a
# split/collapse verdict (those need ~41% changes) and below the
# quality jitter the histogram gates already tolerate — measured qmin
# on the tier-1 workloads is flat-to-better at this threshold (1e-2
# was too aggressive: it froze the slow cumulative drift that lifts
# small-mesh floors).
MOVE_TOL = 5e-3


class SmoothStats(NamedTuple):
    nmoved: jax.Array
    nfrozen: jax.Array     # movable vertices frozen by validity rounds
    changed_v: jax.Array   # [PC] bool — vertices that really moved


def _local_h(met):
    """[PC] local metric size: h for iso metrics, the mean-eigenvalue
    estimate 1/sqrt(tr(M)/3) for sym6 tensors."""
    if met.shape[1] == 1:
        return met[:, 0]
    tr = (met[:, 0] + met[:, 3] + met[:, 5]) / 3.0
    return jax.lax.rsqrt(jnp.maximum(tr, 1e-30))


@partial(
    jax.jit,
    static_argnames=("relax", "rounds", "qfactor", "nosurf"),
    donate_argnums=0,
)
def smooth_vertices(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    relax: float = 0.5,
    rounds: int = 4,
    qfactor: float = 0.5,
    nosurf: bool = False,
    active: jax.Array | None = None,
):
    """One smoothing sweep; returns (mesh, SmoothStats).

    With an `active` vertex mask (one-ring closure of the previous
    sweep's changes — frontier mode, round 6), only active vertices are
    relaxed: an inactive vertex's neighbor set and neighbor positions
    are unchanged since its last (accepted or sub-MOVE_TOL) step, so its
    next step is the same sub-threshold fixed-point iteration. The whole
    sweep — centroid accumulation, vertex normals, validity rounds — is
    skipped via `lax.cond` when no movable vertex is active.
    `active=None` smooths every movable vertex (legacy full sweep)."""
    pcap = mesh.pcap
    vert0 = mesh.vert
    dtype = vert0.dtype

    vt = mesh.vtag
    hard = (vt & _HARD) != 0
    bdy_v = (vt & tags.BDY) != 0
    feat_v = (vt & _FEAT_BITS) != 0
    free_i = mesh.vmask & ~hard & ~bdy_v
    surf_v = mesh.vmask & ~hard & bdy_v & ~feat_v
    ridge_v = mesh.vmask & ~hard & bdy_v & feat_v
    if nosurf:
        surf_v = jnp.zeros_like(surf_v)
        ridge_v = jnp.zeros_like(ridge_v)
    if active is not None:
        free_i = free_i & active
        surf_v = surf_v & active
        ridge_v = ridge_v & active
    movable = free_i | surf_v | ridge_v

    def _heavy(mesh):
        # --- edge classes -----------------------------------------------------
        a, b = edges[:, 0], edges[:, 1]
        smask = surf_tria_mask(mesh)
        tri_keys = common.tria_edge_keys(mesh, smask)
        surf_e = common.sorted_membership(
            tri_keys, jnp.where(emask[:, None], edges, -1), bound=mesh.pcap
        )
        feat = common.feature_edge_index(mesh, edges, emask)
        feat_tag = jnp.where(feat >= 0, mesh.edtag[jnp.maximum(feat, 0)], 0)
        feat_e = (feat_tag & _FEAT_BITS) != 0

        # ONE fused centroid pass: each vertex class wants the centroid over
        # a different edge subset (interior: all edges, surface: surface
        # edges, ridge: feature edges — the movintpt/movbdyregpt/movbdyridpt
        # neighbor disciplines). The classes partition the vertices, so the
        # edge weight can be chosen PER ENDPOINT and all three accumulations
        # share one scatter round — 1/3 the scatter dispatches of the former
        # three-pass version on the latency-bound TPU path (round 5).
        def end_w(vid):
            return (
                emask
                & (
                    free_i[vid]
                    | (surf_v[vid] & surf_e)
                    | (ridge_v[vid] & feat_e)
                )
            ).astype(dtype)

        wa = end_w(a)
        wb = end_w(b)
        acc = jnp.zeros((pcap, 3), dtype)
        acc = common.scatter_rows(acc, a, vert0[b] * wa[:, None], op="add")
        acc = common.scatter_rows(acc, b, vert0[a] * wb[:, None], op="add")
        cnt = jnp.zeros(pcap, dtype)
        cnt = cnt.at[a].add(wa, mode="drop")
        cnt = cnt.at[b].add(wb, mode="drop")
        cent = acc / jnp.maximum(cnt, 1.0)[:, None]

        d = cent - vert0
        # surface: tangential part of the surface-neighbor displacement
        # (movbdyregpt role — normal component removed against the vertex
        # normal so the vertex slides on the surface)
        # frontier mode reads normals only at the (active-gated) surface
        # vertices being relaxed — their rows are exact under `need`
        vn = vertex_normals(
            mesh, need=surf_v if active is not None else None
        )
        d_surf = d - jnp.sum(d * vn, axis=1, keepdims=True) * vn

        has_cnt = (cnt > 0)[:, None]
        disp = jnp.where((free_i | ridge_v)[:, None] & has_cnt, d, 0.0)
        disp = jnp.where(surf_v[:, None] & has_cnt, d_surf, disp)
        target = vert0 + relax * disp

        # fused quality+volume of the pre-move configuration
        q_old, vol0 = kernels.quality_vol(vert0, mesh.met, mesh.tet)
        # scale-relative inversion floor (common.POS_VOL_FRAC of the
        # pre-move volume)
        vol_floor = common.POS_VOL_FRAC * jnp.abs(vol0)

        # surface-fold guard: original tria normals to compare against
        tri = mesh.tria

        def tria_normals_at(pos):
            p0, p1, p2 = pos[tri[:, 0]], pos[tri[:, 1]], pos[tri[:, 2]]
            return jnp.cross(p1 - p0, p2 - p0)

        r_old = tria_normals_at(vert0)
        nr_old = jnp.linalg.norm(r_old, axis=1)

        def bad_entities(pos):
            q_new, vol = kernels.quality_vol(pos, mesh.met, mesh.tet)
            bad_t = mesh.tmask & ((vol <= vol_floor) | (q_new < qfactor * q_old))
            r_new = tria_normals_at(pos)
            nr_new = jnp.linalg.norm(r_new, axis=1)
            dotn = jnp.einsum("fi,fi->f", r_old, r_new) / jnp.maximum(
                nr_old * nr_new, 1e-30
            )
            bad_f = smask & (
                (dotn < _COS_SURF) | (nr_new < 1e-12 * jnp.maximum(nr_old, 1e-30))
            )
            return bad_t, bad_f

        def body(_, frozen):
            pos = jnp.where(frozen[:, None], vert0, target)
            bad_t, bad_f = bad_entities(pos)
            freeze_v = jnp.zeros(pcap, bool)
            idx = jnp.where(bad_t[:, None], mesh.tet, pcap)
            freeze_v = freeze_v.at[idx.reshape(-1)].set(True, mode="drop")
            idxf = jnp.where(bad_f[:, None], tri, pcap)
            freeze_v = freeze_v.at[idxf.reshape(-1)].set(True, mode="drop")
            return frozen | freeze_v

        if common._split_scatter_cols():
            # TPU: each freeze round costs fixed scatter/gather latency
            # whether or not it freezes anything; once a round adds no
            # vertex the fixed point is reached — exit early (the common
            # case after round 1 on a converged mesh). Carries derive from
            # mesh data, not literals, so they stay device-varying under
            # shard_map (same discipline as the collapse selection loop).
            def w_cond(c):
                _, k, changed = c
                return (k < rounds) & changed

            def w_body(c):
                frozen, k, _ = c
                f2 = body(None, frozen)
                return f2, k + 1, jnp.any(f2 & ~frozen)

            frozen, _, _ = jax.lax.while_loop(
                w_cond, w_body,
                (~movable, jnp.sum(mesh.tmask) * 0,
                 jnp.any(mesh.tmask) | True),
            )
        else:
            frozen = jax.lax.fori_loop(0, rounds, body, ~movable)

        pos = jnp.where(frozen[:, None], vert0, target)
        # sub-tolerance snap: displacements under MOVE_TOL of the local
        # metric size are cosmetic — suppress them so relaxation reaches
        # a literal fixed point and the frontier drains (see MOVE_TOL)
        h_loc = jnp.maximum(_local_h(mesh.met), 1e-30)
        small = (
            jnp.linalg.norm(pos - vert0, axis=1) <= MOVE_TOL * h_loc
        )
        pos = jnp.where(small[:, None], vert0, pos)
        bad_t, bad_f = bad_entities(pos)
        still_bad = jnp.any(bad_t) | jnp.any(bad_f)
        pos = jnp.where(still_bad, vert0, pos)

        moved = movable & ~frozen & ~still_bad & ~small & (cnt > 0)
        return pos, jnp.sum(moved.astype(jnp.int32)).astype(
            jnp.int32
        ), jnp.sum((movable & frozen).astype(jnp.int32)).astype(jnp.int32)

    if active is None:
        pos, nmoved, nfrozen = _heavy(mesh)
    else:
        # no active movable vertex: skip centroids, normals, and the
        # validity rounds outright — the converged-sweep common case
        pos, nmoved, nfrozen = jax.lax.cond(
            jnp.any(movable), _heavy,
            lambda m: (m.vert, jnp.int32(0), jnp.int32(0)), mesh,
        )
    # frontier: only vertices that REALLY moved (beyond MOVE_TOL of the
    # local metric size) re-enter the next sweep's active set — this is
    # what lets converging relaxation drain the frontier
    h_loc = jnp.maximum(_local_h(mesh.met), 1e-30)
    chg = jnp.linalg.norm(pos - vert0, axis=1) > MOVE_TOL * h_loc
    return mesh.replace(vert=pos), SmoothStats(
        nmoved=nmoved, nfrozen=nfrozen, changed_v=chg & mesh.vmask,
    )
