"""Batched vertex smoothing (relaxation toward neighbor centroid).

Counterpart of Mmg's vertex-move operators inside `MMG5_mmg3d1_delone`
(reference `src/libparmmg1.c:739`): `movintpt` for free interior vertices,
`movbdyregpt` for regular surface vertices (tangential motion only), and
`movbdyridpt` for feature-line vertices (motion along the feature).
Free interior vertices relax toward the centroid of their edge-neighbors
(Jacobi, under-relaxed); surface vertices relax toward the centroid of
their *surface* neighbors with the normal component of the displacement
removed (first-order geometry preservation); ridge vertices toward the
centroid of their *feature* neighbors. Validity is restored iteratively:
tets that would invert or degrade too much — and surface trias whose
normal would swing past the dihedral threshold (no folds, no new ridges)
— freeze all their vertices back to the original positions; the freeze
loop runs a fixed number of rounds (XLA-friendly) with a global revert as
the final safety net, so the sweep never worsens the worst element below
the bound.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import tags
from ..core.mesh import Mesh
from . import common
from .analysis import surf_tria_mask, vertex_normals

_FEAT_BITS = tags.RIDGE | tags.REF | tags.NOM
_HARD = tags.REQUIRED | tags.CORNER | tags.PARBDY | tags.NOM | tags.OVERLAP
_COS_SURF = 0.70710678


class SmoothStats(NamedTuple):
    nmoved: jax.Array
    nfrozen: jax.Array  # movable vertices frozen by validity rounds


@partial(
    jax.jit,
    static_argnames=("relax", "rounds", "qfactor", "nosurf"),
    donate_argnums=0,
)
def smooth_vertices(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    relax: float = 0.5,
    rounds: int = 4,
    qfactor: float = 0.5,
    nosurf: bool = False,
):
    """One smoothing sweep; returns (mesh, SmoothStats)."""
    pcap = mesh.pcap
    vert0 = mesh.vert
    dtype = vert0.dtype

    vt = mesh.vtag
    hard = (vt & _HARD) != 0
    bdy_v = (vt & tags.BDY) != 0
    feat_v = (vt & _FEAT_BITS) != 0
    free_i = mesh.vmask & ~hard & ~bdy_v
    surf_v = mesh.vmask & ~hard & bdy_v & ~feat_v
    ridge_v = mesh.vmask & ~hard & bdy_v & feat_v
    if nosurf:
        surf_v = jnp.zeros_like(surf_v)
        ridge_v = jnp.zeros_like(ridge_v)
    movable = free_i | surf_v | ridge_v

    # --- edge classes -----------------------------------------------------
    a, b = edges[:, 0], edges[:, 1]
    smask = surf_tria_mask(mesh)
    tri_keys = common.tria_edge_keys(mesh, smask)
    surf_e = common.sorted_membership(
        tri_keys, jnp.where(emask[:, None], edges, -1), bound=mesh.pcap
    )
    feat = common.feature_edge_index(mesh, edges, emask)
    feat_tag = jnp.where(feat >= 0, mesh.edtag[jnp.maximum(feat, 0)], 0)
    feat_e = (feat_tag & _FEAT_BITS) != 0

    def centroid_over(sel):
        w = (emask & sel).astype(dtype)
        acc = jnp.zeros((pcap, 3), dtype)
        acc = common.scatter_rows(acc, a, vert0[b] * w[:, None], op="add")
        acc = common.scatter_rows(acc, b, vert0[a] * w[:, None], op="add")
        cnt = jnp.zeros(pcap, dtype)
        cnt = cnt.at[a].add(w, mode="drop")
        cnt = cnt.at[b].add(w, mode="drop")
        return acc / jnp.maximum(cnt, 1.0)[:, None], cnt

    cent_all, cnt_all = centroid_over(jnp.ones_like(emask))
    cent_surf, cnt_surf = centroid_over(surf_e)
    cent_feat, cnt_feat = centroid_over(feat_e)

    # interior: plain centroid
    d_int = cent_all - vert0
    # surface: tangential part of the surface-neighbor displacement
    # (movbdyregpt role — normal component removed against the vertex
    # normal so the vertex slides on the surface)
    vn = vertex_normals(mesh)
    d_s = cent_surf - vert0
    d_surf = d_s - jnp.sum(d_s * vn, axis=1, keepdims=True) * vn
    # feature line: centroid of the (typically two) feature neighbors —
    # exact for straight ridges, second-order error on curved ones
    d_feat = cent_feat - vert0

    disp = jnp.where(
        free_i[:, None] & (cnt_all > 0)[:, None], d_int, 0.0
    )
    disp = jnp.where(surf_v[:, None] & (cnt_surf > 0)[:, None], d_surf, disp)
    disp = jnp.where(ridge_v[:, None] & (cnt_feat > 0)[:, None], d_feat, disp)
    target = vert0 + relax * disp

    q_old = common.quality_of(vert0, mesh.met, mesh.tet)
    # scale-relative inversion floor (common.POS_VOL_FRAC of the
    # pre-move volume)
    vol_floor = common.POS_VOL_FRAC * jnp.abs(common.vol_of(vert0, mesh.tet))

    # surface-fold guard: original tria normals to compare against
    tri = mesh.tria

    def tria_normals_at(pos):
        p0, p1, p2 = pos[tri[:, 0]], pos[tri[:, 1]], pos[tri[:, 2]]
        return jnp.cross(p1 - p0, p2 - p0)

    r_old = tria_normals_at(vert0)
    nr_old = jnp.linalg.norm(r_old, axis=1)

    def bad_entities(pos):
        q_new = common.quality_of(pos, mesh.met, mesh.tet)
        vol = common.vol_of(pos, mesh.tet)
        bad_t = mesh.tmask & ((vol <= vol_floor) | (q_new < qfactor * q_old))
        r_new = tria_normals_at(pos)
        nr_new = jnp.linalg.norm(r_new, axis=1)
        dotn = jnp.einsum("fi,fi->f", r_old, r_new) / jnp.maximum(
            nr_old * nr_new, 1e-30
        )
        bad_f = smask & (
            (dotn < _COS_SURF) | (nr_new < 1e-12 * jnp.maximum(nr_old, 1e-30))
        )
        return bad_t, bad_f

    def body(_, frozen):
        pos = jnp.where(frozen[:, None], vert0, target)
        bad_t, bad_f = bad_entities(pos)
        freeze_v = jnp.zeros(pcap, bool)
        idx = jnp.where(bad_t[:, None], mesh.tet, pcap)
        freeze_v = freeze_v.at[idx.reshape(-1)].set(True, mode="drop")
        idxf = jnp.where(bad_f[:, None], tri, pcap)
        freeze_v = freeze_v.at[idxf.reshape(-1)].set(True, mode="drop")
        return frozen | freeze_v

    frozen = jax.lax.fori_loop(0, rounds, body, ~movable)

    pos = jnp.where(frozen[:, None], vert0, target)
    bad_t, bad_f = bad_entities(pos)
    still_bad = jnp.any(bad_t) | jnp.any(bad_f)
    pos = jnp.where(still_bad, vert0, pos)

    has_nbrs = (
        (free_i & (cnt_all > 0))
        | (surf_v & (cnt_surf > 0))
        | (ridge_v & (cnt_feat > 0))
    )
    moved = movable & ~frozen & ~still_bad & has_nbrs
    return mesh.replace(vert=pos), SmoothStats(
        nmoved=jnp.sum(moved.astype(jnp.int32)),
        nfrozen=jnp.sum((movable & frozen).astype(jnp.int32)),
    )
