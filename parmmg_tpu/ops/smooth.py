"""Batched vertex smoothing (relaxation toward neighbor centroid).

Counterpart of Mmg's vertex-move operators inside `MMG5_mmg3d1_delone`
(reference `src/libparmmg1.c:739`): `movintpt` for free interior vertices,
`movbdyregpt` for regular surface vertices (tangential motion only), and
`movbdyridpt` for feature-line vertices (motion along the feature).
Free interior vertices relax toward the centroid of their edge-neighbors
(Jacobi, under-relaxed); surface vertices relax toward the centroid of
their *surface* neighbors with the normal component of the displacement
removed (first-order geometry preservation); ridge vertices toward the
centroid of their *feature* neighbors. Validity is restored iteratively:
tets that would invert or degrade too much — and surface trias whose
normal would swing past the dihedral threshold (no folds, no new ridges)
— freeze all their vertices back to the original positions; the freeze
loop runs a fixed number of rounds (XLA-friendly) with a global revert as
the final safety net, so the sweep never worsens the worst element below
the bound.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import tags
from ..core.mesh import Mesh
from . import common
from .analysis import surf_tria_mask, vertex_normals

_FEAT_BITS = tags.RIDGE | tags.REF | tags.NOM
_HARD = tags.REQUIRED | tags.CORNER | tags.PARBDY | tags.NOM | tags.OVERLAP
_COS_SURF = 0.70710678


class SmoothStats(NamedTuple):
    nmoved: jax.Array
    nfrozen: jax.Array  # movable vertices frozen by validity rounds


@partial(
    jax.jit,
    static_argnames=("relax", "rounds", "qfactor", "nosurf"),
    donate_argnums=0,
)
def smooth_vertices(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    relax: float = 0.5,
    rounds: int = 4,
    qfactor: float = 0.5,
    nosurf: bool = False,
):
    """One smoothing sweep; returns (mesh, SmoothStats)."""
    pcap = mesh.pcap
    vert0 = mesh.vert
    dtype = vert0.dtype

    vt = mesh.vtag
    hard = (vt & _HARD) != 0
    bdy_v = (vt & tags.BDY) != 0
    feat_v = (vt & _FEAT_BITS) != 0
    free_i = mesh.vmask & ~hard & ~bdy_v
    surf_v = mesh.vmask & ~hard & bdy_v & ~feat_v
    ridge_v = mesh.vmask & ~hard & bdy_v & feat_v
    if nosurf:
        surf_v = jnp.zeros_like(surf_v)
        ridge_v = jnp.zeros_like(ridge_v)
    movable = free_i | surf_v | ridge_v

    # --- edge classes -----------------------------------------------------
    a, b = edges[:, 0], edges[:, 1]
    smask = surf_tria_mask(mesh)
    tri_keys = common.tria_edge_keys(mesh, smask)
    surf_e = common.sorted_membership(
        tri_keys, jnp.where(emask[:, None], edges, -1), bound=mesh.pcap
    )
    feat = common.feature_edge_index(mesh, edges, emask)
    feat_tag = jnp.where(feat >= 0, mesh.edtag[jnp.maximum(feat, 0)], 0)
    feat_e = (feat_tag & _FEAT_BITS) != 0

    # ONE fused centroid pass: each vertex class wants the centroid over
    # a different edge subset (interior: all edges, surface: surface
    # edges, ridge: feature edges — the movintpt/movbdyregpt/movbdyridpt
    # neighbor disciplines). The classes partition the vertices, so the
    # edge weight can be chosen PER ENDPOINT and all three accumulations
    # share one scatter round — 1/3 the scatter dispatches of the former
    # three-pass version on the latency-bound TPU path (round 5).
    def end_w(vid):
        return (
            emask
            & (
                free_i[vid]
                | (surf_v[vid] & surf_e)
                | (ridge_v[vid] & feat_e)
            )
        ).astype(dtype)

    wa = end_w(a)
    wb = end_w(b)
    acc = jnp.zeros((pcap, 3), dtype)
    acc = common.scatter_rows(acc, a, vert0[b] * wa[:, None], op="add")
    acc = common.scatter_rows(acc, b, vert0[a] * wb[:, None], op="add")
    cnt = jnp.zeros(pcap, dtype)
    cnt = cnt.at[a].add(wa, mode="drop")
    cnt = cnt.at[b].add(wb, mode="drop")
    cent = acc / jnp.maximum(cnt, 1.0)[:, None]

    d = cent - vert0
    # surface: tangential part of the surface-neighbor displacement
    # (movbdyregpt role — normal component removed against the vertex
    # normal so the vertex slides on the surface)
    vn = vertex_normals(mesh)
    d_surf = d - jnp.sum(d * vn, axis=1, keepdims=True) * vn

    has_cnt = (cnt > 0)[:, None]
    disp = jnp.where((free_i | ridge_v)[:, None] & has_cnt, d, 0.0)
    disp = jnp.where(surf_v[:, None] & has_cnt, d_surf, disp)
    target = vert0 + relax * disp

    q_old = common.quality_of(vert0, mesh.met, mesh.tet)
    # scale-relative inversion floor (common.POS_VOL_FRAC of the
    # pre-move volume)
    vol_floor = common.POS_VOL_FRAC * jnp.abs(common.vol_of(vert0, mesh.tet))

    # surface-fold guard: original tria normals to compare against
    tri = mesh.tria

    def tria_normals_at(pos):
        p0, p1, p2 = pos[tri[:, 0]], pos[tri[:, 1]], pos[tri[:, 2]]
        return jnp.cross(p1 - p0, p2 - p0)

    r_old = tria_normals_at(vert0)
    nr_old = jnp.linalg.norm(r_old, axis=1)

    def bad_entities(pos):
        q_new = common.quality_of(pos, mesh.met, mesh.tet)
        vol = common.vol_of(pos, mesh.tet)
        bad_t = mesh.tmask & ((vol <= vol_floor) | (q_new < qfactor * q_old))
        r_new = tria_normals_at(pos)
        nr_new = jnp.linalg.norm(r_new, axis=1)
        dotn = jnp.einsum("fi,fi->f", r_old, r_new) / jnp.maximum(
            nr_old * nr_new, 1e-30
        )
        bad_f = smask & (
            (dotn < _COS_SURF) | (nr_new < 1e-12 * jnp.maximum(nr_old, 1e-30))
        )
        return bad_t, bad_f

    def body(_, frozen):
        pos = jnp.where(frozen[:, None], vert0, target)
        bad_t, bad_f = bad_entities(pos)
        freeze_v = jnp.zeros(pcap, bool)
        idx = jnp.where(bad_t[:, None], mesh.tet, pcap)
        freeze_v = freeze_v.at[idx.reshape(-1)].set(True, mode="drop")
        idxf = jnp.where(bad_f[:, None], tri, pcap)
        freeze_v = freeze_v.at[idxf.reshape(-1)].set(True, mode="drop")
        return frozen | freeze_v

    if common._split_scatter_cols():
        # TPU: each freeze round costs fixed scatter/gather latency
        # whether or not it freezes anything; once a round adds no
        # vertex the fixed point is reached — exit early (the common
        # case after round 1 on a converged mesh). Carries derive from
        # mesh data, not literals, so they stay device-varying under
        # shard_map (same discipline as the collapse selection loop).
        def w_cond(c):
            _, k, changed = c
            return (k < rounds) & changed

        def w_body(c):
            frozen, k, _ = c
            f2 = body(None, frozen)
            return f2, k + 1, jnp.any(f2 & ~frozen)

        frozen, _, _ = jax.lax.while_loop(
            w_cond, w_body,
            (~movable, jnp.sum(mesh.tmask) * 0,
             jnp.any(mesh.tmask) | True),
        )
    else:
        frozen = jax.lax.fori_loop(0, rounds, body, ~movable)

    pos = jnp.where(frozen[:, None], vert0, target)
    bad_t, bad_f = bad_entities(pos)
    still_bad = jnp.any(bad_t) | jnp.any(bad_f)
    pos = jnp.where(still_bad, vert0, pos)

    moved = movable & ~frozen & ~still_bad & (cnt > 0)
    return mesh.replace(vert=pos), SmoothStats(
        nmoved=jnp.sum(moved.astype(jnp.int32)),
        nfrozen=jnp.sum((movable & frozen).astype(jnp.int32)),
    )
