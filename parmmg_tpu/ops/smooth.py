"""Batched vertex smoothing (relaxation toward neighbor centroid).

Counterpart of Mmg's vertex-move operator inside `MMG5_mmg3d1_delone`
(reference `src/libparmmg1.c:739`): free interior vertices relax toward the
centroid of their edge-neighbors (Jacobi, under-relaxed). Validity is
restored iteratively: tets that would invert or degrade too much freeze all
their vertices back to the original positions; the freeze loop runs a fixed
number of rounds (XLA-friendly) with a global revert as the final safety
net, so the sweep never worsens the worst element below the bound.

Round-1 scope: interior vertices only (boundary smoothing joins the
surface-analysis milestone).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import tags
from ..core.mesh import Mesh
from . import common


class SmoothStats(NamedTuple):
    nmoved: jax.Array
    nfrozen: jax.Array  # movable vertices frozen by validity rounds


@partial(jax.jit, static_argnames=("relax", "rounds", "qfactor"), donate_argnums=0)
def smooth_vertices(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    relax: float = 0.5,
    rounds: int = 4,
    qfactor: float = 0.5,
):
    """One smoothing sweep; returns (mesh, SmoothStats)."""
    pcap = mesh.pcap
    vert0 = mesh.vert
    dtype = vert0.dtype

    movable = mesh.vmask & (
        (mesh.vtag & (tags.IMMOVABLE | tags.BDY | tags.OVERLAP)) == 0
    )

    a, b = edges[:, 0], edges[:, 1]
    w = emask.astype(dtype)
    acc = jnp.zeros((pcap, 3), dtype)
    acc = acc.at[a].add(vert0[b] * w[:, None], mode="drop")
    acc = acc.at[b].add(vert0[a] * w[:, None], mode="drop")
    cnt = jnp.zeros(pcap, dtype)
    cnt = cnt.at[a].add(w, mode="drop")
    cnt = cnt.at[b].add(w, mode="drop")
    centroid = acc / jnp.maximum(cnt, 1.0)[:, None]
    target = jnp.where(
        (movable & (cnt > 0))[:, None],
        (1.0 - relax) * vert0 + relax * centroid,
        vert0,
    )

    q_old = common.quality_of(vert0, mesh.met, mesh.tet)
    # scale-relative inversion floor (common.POS_VOL_FRAC of the
    # pre-move volume)
    vol_floor = common.POS_VOL_FRAC * jnp.abs(common.vol_of(vert0, mesh.tet))

    def body(_, frozen):
        pos = jnp.where(frozen[:, None], vert0, target)
        q_new = common.quality_of(pos, mesh.met, mesh.tet)
        vol = common.vol_of(pos, mesh.tet)
        bad = mesh.tmask & ((vol <= vol_floor) | (q_new < qfactor * q_old))
        freeze_v = jnp.zeros(pcap, bool)
        idx = jnp.where(bad[:, None], mesh.tet, pcap)
        freeze_v = freeze_v.at[idx.reshape(-1)].set(True, mode="drop")
        return frozen | freeze_v

    frozen = jax.lax.fori_loop(0, rounds, body, ~movable)

    pos = jnp.where(frozen[:, None], vert0, target)
    vol = common.vol_of(pos, mesh.tet)
    q_new = common.quality_of(pos, mesh.met, mesh.tet)
    still_bad = jnp.any(
        mesh.tmask & ((vol <= vol_floor) | (q_new < qfactor * q_old))
    )
    pos = jnp.where(still_bad, vert0, pos)

    moved = movable & ~frozen & ~still_bad & (cnt > 0)
    return mesh.replace(vert=pos), SmoothStats(
        nmoved=jnp.sum(moved.astype(jnp.int32)),
        nfrozen=jnp.sum((movable & frozen).astype(jnp.int32)),
    )
