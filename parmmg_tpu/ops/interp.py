"""Metric and field interpolation from a background (old) mesh.

TPU-native counterpart of `src/interpmesh_pmmg.c`
(`PMMG_interpMetricsAndFields:663`, per-vertex dispatch
`PMMG_interpMetricsAndFields_mesh:477`): every valid vertex of the new mesh
is located in the old mesh (batched walk, `ops.locate`) and its metric,
level-set, displacement and user fields are interpolated with P1 barycentric
weights — log-Euclidean for anisotropic tensors, harmonic-in-1/h for
isotropic sizes (`PMMG_interp4bar_iso:206` / `_ani:247` semantics).
REQUIRED vertices keep their previous values instead of being re-interpolated
(`PMMG_copyMetrics_point:373` / `PMMG_copySol_point:312` role).

No cross-shard communication happens here: like the reference, each shard
interpolates from *its own* old snapshot because remeshing precedes
migration within an iteration (SURVEY.md §3.4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import metric as metric_mod, tags
from ..core.mesh import Mesh
from . import locate


@jax.jit
def interp_at(
    old: Mesh, tet_idx: jax.Array, bary: jax.Array
):
    """Interpolate old-mesh vertex data at located points.

    tet_idx: [Q] containing tet slots in `old`, bary: [Q,4].
    Returns (met [Q,C], ls [Q,·], disp [Q,·], fields [Q,·]).
    """
    vids = old.tet[tet_idx]  # [Q,4]
    met = metric_mod.interp_metric(old.met[vids], bary)

    def lin(a):
        return jnp.einsum("qk,qkc->qc", bary, a[vids])

    return met, lin(old.ls), lin(old.disp), lin(old.fields)


def interp_metrics_and_fields(
    new: Mesh,
    old: Mesh,
    max_steps: int = 64,
) -> tuple[Mesh, locate.LocateResult]:
    """Locate every valid new vertex in `old` and pull met/ls/disp/fields.

    `old` must carry fresh adjacency (`adjacency.build_adjacency`).
    Vertices tagged REQUIRED keep their current values. Returns the updated
    mesh and the location result (for search statistics / diagnostics).
    """
    for name in ("met", "ls", "disp", "fields"):
        cn, co = getattr(new, name).shape[1], getattr(old, name).shape[1]
        if cn != co:
            raise ValueError(
                f"solution family mismatch: new.{name} has {cn} components, "
                f"old.{name} has {co} — the meshes must carry the same "
                "metric/sol types (the reference errors likewise)"
            )
    res = locate.locate_points(old, new.vert, max_steps=max_steps)
    met_q, ls_q, disp_q, f_q = interp_at(old, res.tet, res.bary)
    keep = (~new.vmask) | ((new.vtag & tags.REQUIRED) != 0)

    def sel(cur, q):
        if cur.shape[1] == 0:
            return cur
        return jnp.where(keep[:, None], cur, q.astype(cur.dtype))

    return (
        new.replace(
            met=sel(new.met, met_q),
            ls=sel(new.ls, ls_q),
            disp=sel(new.disp, disp_q),
            fields=sel(new.fields, f_q),
            met_set=old.met_set,
        ),
        res,
    )
