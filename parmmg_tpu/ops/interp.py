"""Metric and field interpolation from a background (old) mesh.

TPU-native counterpart of `src/interpmesh_pmmg.c`
(`PMMG_interpMetricsAndFields:663`, per-vertex dispatch
`PMMG_interpMetricsAndFields_mesh:477`): every valid vertex of the new mesh
is located in the old mesh (batched walk, `ops.locate`) and its metric,
level-set, displacement and user fields are interpolated with P1 barycentric
weights — log-Euclidean for anisotropic tensors, harmonic-in-1/h for
isotropic sizes (`PMMG_interp4bar_iso:206` / `_ani:247` semantics).
REQUIRED vertices keep their previous values instead of being re-interpolated
(`PMMG_copyMetrics_point:373` / `PMMG_copySol_point:312` role).

No cross-shard communication happens here: like the reference, each shard
interpolates from *its own* old snapshot because remeshing precedes
migration within an iteration (SURVEY.md §3.4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import kernels
from ..core import metric as metric_mod, tags
from ..core.mesh import Mesh
from . import locate


# parmmg-lint: disable=PML005 -- the background mesh is queried repeatedly across calls
@jax.jit
def interp_at(
    old: Mesh, tet_idx: jax.Array, bary: jax.Array
):
    """Interpolate old-mesh vertex data at located points.

    tet_idx: [Q] containing tet slots in `old`, bary: [Q,4].
    Returns (met [Q,C], ls [Q,·], disp [Q,·], fields [Q,·]).
    """
    vids = old.tet[tet_idx]  # [Q,4]
    met = metric_mod.interp_metric(old.met[vids], bary)

    def lin(a):
        return jnp.einsum("qk,qkc->qc", bary, a[vids])

    return met, lin(old.ls), lin(old.disp), lin(old.fields)


# parmmg-lint: disable=PML005 -- the background mesh is queried repeatedly across calls
@jax.jit
def interp_at_points(old: Mesh, tet_idx: jax.Array, pts: jax.Array):
    """Fused pull at walk-located points (`kernels.interp_bary`):
    recompute the clamped barycentric weights from the located tet and
    the query point — the exact expression the walk's own final step
    evaluates, so the weights match `LocateResult.bary` — and
    interpolate the metric in the same pass; ls/disp/fields ride the
    returned weights. The Pallas path keeps the vertex/metric tables
    VMEM-resident; the lax reference is the historical
    locate-then-`interp_at` chain."""
    vids = old.tet[tet_idx]  # [Q,4]
    bary, met = kernels.interp_bary(old.vert, old.met, vids, pts)

    def lin(a):
        return jnp.einsum("qk,qkc->qc", bary, a[vids])

    return met, lin(old.ls), lin(old.disp), lin(old.fields)


def interp_fields_only(new: Mesh, old: Mesh, max_steps: int = 64) -> Mesh:
    """Re-interpolate only the user fields (and ls/disp) of `new` from the
    `old` snapshot — the single-shard post-pass matching the reference's
    per-iteration `PMMG_interpMetricsAndFields` at NP=1 (fields must track
    the geometry through vertex relocation; the adapted metric itself is
    maintained by the operators and left untouched)."""
    if (new.fields.shape[1] + new.ls.shape[1] + new.disp.shape[1]) == 0:
        return new
    # dead slots are zero-padded; locating (0,0,0) outside the domain
    # would drive every one of them into the exhaustive fallback — aim
    # them at a live vertex instead (slot 0 on compacted meshes)
    pts = jnp.where(new.vmask[:, None], new.vert, new.vert[0])
    res = locate.locate_points(old, pts, max_steps=max_steps)
    vids = old.tet[res.tet]

    def lin(a):
        return jnp.einsum("qk,qkc->qc", res.bary, a[vids])

    def sel(cur, q):
        if cur.shape[1] == 0:
            return cur
        return jnp.where(new.vmask[:, None], q.astype(cur.dtype), cur)

    return new.replace(
        ls=sel(new.ls, lin(old.ls)),
        disp=sel(new.disp, lin(old.disp)),
        fields=sel(new.fields, lin(old.fields)),
    )


# parmmg-lint: disable=PML005 -- the background mesh is queried repeatedly across calls
@jax.jit
def interp_at_tria(old: Mesh, tria_idx: jax.Array, bary: jax.Array):
    """Interpolate old-mesh vertex data at points located on boundary
    trias (3-node path: `PMMG_interp3bar_iso/_ani` semantics,
    reference `src/interpmesh_pmmg.c:125`)."""
    vids = old.tria[tria_idx]  # [Q,3]
    met = metric_mod.interp_metric(old.met[vids], bary)

    def lin(a):
        return jnp.einsum("qk,qkc->qc", bary, a[vids])

    return met, lin(old.ls), lin(old.disp), lin(old.fields)


def _check_families(new: Mesh, old: Mesh):
    # shape[-1]: works for both per-shard [PC,C] and stacked [D,PC,C]
    for name in ("met", "ls", "disp", "fields"):
        cn, co = getattr(new, name).shape[-1], getattr(old, name).shape[-1]
        if cn != co:
            raise ValueError(
                f"solution family mismatch: new.{name} has {cn} components, "
                f"old.{name} has {co} — the meshes must carry the same "
                "metric/sol types (the reference errors likewise)"
            )


def _apply_interp(new: Mesh, old: Mesh, res, surface: bool,
                  cos_wedge: float = locate._COS_WEDGE,
                  pts: jax.Array | None = None) -> Mesh:
    """Pure (vmappable) application step: pull values at the located
    tets, overlay the surface path for BDY vertices, respect REQUIRED.

    `pts` (the query points the walk located, when the caller still
    holds them) routes the volume pull through the fused
    locate+interpolate kernel; without them the historical
    `interp_at(res.bary)` path is used."""
    if pts is None:
        met_q, ls_q, disp_q, f_q = interp_at(old, res.tet, res.bary)
    else:
        met_q, ls_q, disp_q, f_q = interp_at_points(old, res.tet, pts)

    if surface:
        from .analysis import surf_tria_mask

        from .analysis import vertex_normals

        smask = surf_tria_mask(old)
        # query normals from the NEW surface arm the cone/wedge
        # discipline: near a ridge the source tria must be on the
        # query's own side of the feature (src/locate_pmmg.c:209-384)
        bres = locate.bdy_locate(
            old, smask, new.vert, normals=vertex_normals(new),
            cos_wedge=cos_wedge,
        )
        # PARBDY interface vertices are BDY-tagged but lie on the
        # synthetic interface (excluded from smask) — their nearest TRUE
        # surface tria can be arbitrarily far, so they stay on the
        # volume path
        on_bdy = (
            ((new.vtag & tags.BDY) != 0)
            & ((new.vtag & tags.PARBDY) == 0)
            & jnp.any(smask)
        )[:, None]
        met_s, ls_s, disp_s, f_s = interp_at_tria(old, bres.tria, bres.bary)

        def pick(qv, sv):
            if qv.shape[1] == 0:
                return qv
            return jnp.where(on_bdy, sv.astype(qv.dtype), qv)

        met_q = pick(met_q, met_s)
        ls_q = pick(ls_q, ls_s)
        disp_q = pick(disp_q, disp_s)
        f_q = pick(f_q, f_s)

    keep = (~new.vmask) | ((new.vtag & tags.REQUIRED) != 0)

    def sel(cur, q):
        if cur.shape[1] == 0:
            return cur
        return jnp.where(keep[:, None], cur, q.astype(cur.dtype))

    return new.replace(
        met=sel(new.met, met_q),
        ls=sel(new.ls, ls_q),
        disp=sel(new.disp, disp_q),
        fields=sel(new.fields, f_q),
        met_set=old.met_set,
    )


def interp_metrics_and_fields(
    new: Mesh,
    old: Mesh,
    max_steps: int = 64,
    surface: bool = True,
    cos_wedge: float = locate._COS_WEDGE,
) -> tuple[Mesh, locate.LocateResult]:
    """Locate every valid new vertex in `old` and pull met/ls/disp/fields.

    `old` must carry fresh adjacency (`adjacency.build_adjacency`).
    Vertices tagged REQUIRED keep their current values. With `surface`,
    vertices tagged BDY are located on the old *boundary triangulation*
    and interpolated from its 3 vertices — the `PMMG_locatePointBdy`
    dispatch of the reference driver (`src/interpmesh_pmmg.c:535-643`,
    `src/locate_pmmg.c:587`), which keeps surface metrics from being
    polluted by interior values on curved boundaries. Returns the updated
    mesh and the volume location result (search statistics/diagnostics).
    """
    _check_families(new, old)
    res = locate.locate_points(old, new.vert, max_steps=max_steps)
    return _apply_interp(new, old, res, surface, cos_wedge,
                         pts=new.vert), res


# parmmg-lint: disable=PML005 -- old/new meshes are both reused by the caller after interpolation
@partial(jax.jit, static_argnames=("max_steps", "surface", "cos_wedge"))
def _interp_all_shards(new: Mesh, old: Mesh, max_steps: int, surface: bool,
                       cos_wedge: float):
    """One device program: walk-locate + interpolate EVERY shard (vmapped
    over the leading shard axis). Returns (stacked mesh, found [D,PC])."""

    def one(n, o):
        # aim dead zero-padded slots at a live vertex so their walks
        # terminate immediately (their values are discarded anyway)
        pts = jnp.where(n.vmask[:, None], n.vert, n.vert[0])
        seeds = locate.morton_seeds(o, pts)
        res = locate.walk_locate(o, pts, seeds, max_steps=max_steps)
        return _apply_interp(n, o, res, surface, cos_wedge,
                             pts=pts), res.found

    return jax.vmap(one)(new, old)


def interp_stacked(
    new: Mesh, old: Mesh, max_steps: int = 64, surface: bool = True,
    cos_wedge: float = locate._COS_WEDGE,
) -> Mesh:
    """Stacked-shard interpolation: one vmapped device call for all
    shards, with a host rescue (exhaustive closest-element search) only
    for the rare vertices the walk could not place. Replaces the
    per-shard host loop the driver used to run (VERDICT r2: no
    O(global-mesh) host work inside `_one_iteration`)."""
    _check_families(new, old)
    out, found = _interp_all_shards(new, old, max_steps, surface, cos_wedge)
    need = ~(found | ~new.vmask)
    if surface:
        # vertices the surface path interpolated already carry the
        # nearest-tria value — the volume rescue must not replace it
        # with a nearest-tet guess (mirrors _apply_interp's on_bdy)
        from .analysis import surf_tria_mask

        smask_any = jax.vmap(lambda o: jnp.any(surf_tria_mask(o)))(old)
        on_bdy = (
            ((new.vtag & tags.BDY) != 0)
            & ((new.vtag & tags.PARBDY) == 0)
            & smask_any[:, None]
        )
        need = need & ~on_bdy
    if bool(jax.device_get(jnp.any(need))):
        import numpy as np

        from .. import parallel  # noqa: F401  (unstack lives there)
        from ..parallel.distribute import unstack_mesh

        need_np = np.asarray(need)
        news = unstack_mesh(out)
        olds = unstack_mesh(old)
        fixed = []
        for s, (n, o) in enumerate(zip(news, olds)):
            fail_idx = np.nonzero(need_np[s])[0]
            if not len(fail_idx):
                fixed.append(n)
                continue
            pad_idx = locate.bucketed_fail_idx(fail_idx)
            fb_tet, fb_bary = locate.exhaustive_locate(
                o, n.vert[jnp.asarray(pad_idx)]
            )
            met_q, ls_q, disp_q, f_q = interp_at(o, fb_tet, fb_bary)
            sel_v = jnp.asarray(pad_idx[: len(fail_idx)])
            keep = (n.vtag[sel_v] & tags.REQUIRED) != 0

            def patch(cur, q):
                if cur.shape[1] == 0:
                    return cur
                return cur.at[sel_v].set(
                    jnp.where(
                        keep[:, None], cur[sel_v],
                        q[: len(fail_idx)].astype(cur.dtype),
                    )
                )

            fixed.append(n.replace(
                met=patch(n.met, met_q),
                ls=patch(n.ls, ls_q),
                disp=patch(n.disp, disp_q),
                fields=patch(n.fields, f_q),
            ))
        out = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *fixed)
    return out
