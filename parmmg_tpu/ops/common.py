"""Shared primitives for the batched remeshing kernels.

The reference applies Mmg's cavity operators serially per group
(`MMG5_mmg3d1_delone` at reference `src/libparmmg1.c:739`); here operators are
applied in parallel Jacobi sweeps over *independent sets*: every candidate
operation claims an arena of tets, and only the best-priority candidate per
arena survives. These helpers implement that selection plus the sort-based
set matching the kernels need — int32/sort/scatter only (TPU-safe without
x64), no hash tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import metric as metric_mod
from ..core.mesh import EDGE_VERTS, Mesh
from .quality import ALPHA


# positivity floor for tentative configurations: a new/retargeted/moved
# tet must keep at least this fraction of its local reference volume —
# scale-relative because absolute thresholds (the old 1e-14) sit below
# f32 resolution at any mesh scale
POS_VOL_FRAC = 1e-4


def vol_tols(dtype):
    """(positivity fraction, conservation tolerance) for volume
    predicates. The positivity fraction is the dtype-independent
    POS_VOL_FRAC (re-exported here so swap's two checks share one call);
    only the conservation tolerance scales with the dtype's epsilon."""
    eps = float(jnp.finfo(dtype).eps)
    return POS_VOL_FRAC, max(1e-9, 256.0 * eps)


def _split_scatter_cols() -> bool:
    """TPU lowers a multi-column scatter-combine ~8x slower than the
    same data as per-column scatters (measured: [1.1M,3] scatter-add
    76ms vs 3x9.3ms single-column on v5e); other backends prefer the
    single call. Trace-time branch — each process compiles for one
    backend."""
    return jax.default_backend() == "tpu"


def scatter_rows(dst, idx, vals, op: str = "set", unique: bool = False):
    """`dst.at[idx].op(vals)` with mode="drop", splitting the columns of
    a 2D `vals` into per-column scatters on TPU. `unique=True` promises
    idx has no duplicates among in-bounds entries — pair with
    `unique_oob` so out-of-bounds sentinels are distinct too."""
    kw = dict(mode="drop", unique_indices=unique)
    if vals.ndim >= 2 and vals.shape[-1] == 0:
        return dst
    if vals.ndim == 1 or not _split_scatter_cols():
        return getattr(dst.at[idx], op)(vals, **kw)
    for k in range(vals.shape[-1]):
        dst = getattr(dst.at[idx, k], op)(vals[..., k], **kw)
    return dst


def seg_broadcast(vals, newgrp, op, neutral):
    """Per-element reduction of `op` over the element's GROUP, where
    groups are contiguous runs in a sorted domain flagged by `newgrp`
    (run starts). Equivalent to `zeros.at[gid].op(vals)[gid]`.

    On TPU: two segmented `associative_scan`s — pure vector work, no
    scatter/gather; measured ~3.8x faster than the scatter+gather pair
    on v5e at 1M rows (random-index HBM access is the bottleneck there;
    scans are lane-parallel). On CPU the scatter pair is faster, so the
    backend picks the lowering (trace-time branch like scatter_rows)."""
    if not _split_scatter_cols():  # non-TPU: scatter+gather is cheaper
        n = vals.shape[0]
        gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
        opname = {jnp.add: "add", jnp.minimum: "min", jnp.maximum: "max"}
        if op in opname:
            acc = getattr(
                jnp.full(n, neutral, vals.dtype).at[gid], opname[op]
            )(vals)
            return acc[gid]
        # generic associative op (e.g. bitwise OR): fall through to scans

    def comb(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, op(v1, v2))

    _, fwd = jax.lax.associative_scan(comb, (newgrp, vals))
    # broadcast the segment total (the value at the run's LAST member)
    # back over the run with a reverse propagate-from-start scan
    lastflag = jnp.concatenate([newgrp[1:], jnp.ones(1, bool)])

    def combr(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, v1)

    seg_end = jnp.where(lastflag, fwd, jnp.asarray(neutral, fwd.dtype))
    _, tot = jax.lax.associative_scan(
        combr, (lastflag, seg_end), reverse=True
    )
    return tot


def seg_broadcast_multi(newgrp, parts):
    """Fused `seg_broadcast` for several reductions sharing one group
    structure: `parts` is a list of (vals, op, neutral); all of them ride
    ONE forward + ONE backward segmented scan with a tuple carry (the
    scans are latency-bound, so k reductions cost ~the same as one).
    Returns the per-element group totals in `parts` order."""
    if not _split_scatter_cols():
        return [seg_broadcast(v, newgrp, op, neu) for v, op, neu in parts]

    def comb(a, b):
        f1 = a[0]
        f2 = b[0]
        out = [f1 | f2]
        for k, (_, op, _) in enumerate(parts, start=1):
            out.append(jnp.where(f2, b[k], op(a[k], b[k])))
        return tuple(out)

    fwd = jax.lax.associative_scan(
        comb, (newgrp, *[v for v, _, _ in parts])
    )
    lastflag = jnp.concatenate([newgrp[1:], jnp.ones(1, bool)])

    def combr(a, b):
        f1 = a[0]
        f2 = b[0]
        return (f1 | f2,) + tuple(
            jnp.where(f2, b[k], a[k]) for k in range(1, len(parts) + 1)
        )

    ends = [
        jnp.where(lastflag, fwd[k + 1], jnp.asarray(neu, fwd[k + 1].dtype))
        for k, (_, _, neu) in enumerate(parts)
    ]
    tot = jax.lax.associative_scan(
        combr, (lastflag, *ends), reverse=True
    )
    return list(tot[1:])


def unique_oob(sel, target, cap):
    """Scatter index vector: `target` where `sel`, else a DISTINCT
    out-of-bounds value (cap + position) — keeps the whole index array
    duplicate-free so scatter_rows(unique=True) is a valid promise even
    for the dropped entries."""
    n = target.shape[0]
    return jnp.where(
        sel, target, cap + jnp.arange(n, dtype=jnp.int32)
    ).astype(jnp.int32)


def two_phase_winners(
    prio: jax.Array,
    cand: jax.Array,
    scatter_arena,
    gather_arena,
):
    """Generic independent-set selection with exact tie-breaking.

    prio: [N] float priorities (higher wins), cand: [N] bool candidates.
    scatter_arena(values) -> arena max-combined values: scatter each
      candidate's value to every arena cell it touches (max combine).
    gather_arena(arena_values) -> [N]: per candidate, max over its cells.

    Phase 1 maxes the float priority per arena cell; the later phase(s)
    break exact float ties by a HASHED candidate index (Luby-MIS style).
    The hash is a bijective odd-multiplier permutation (no collisions),
    and it matters: raw edge indices are spatially sorted, so on a
    uniform mesh (all priorities equal) nearly every candidate would see
    a higher-indexed neighbor in its arena and a sweep would select O(1)
    winners instead of O(n/degree).

    When n <= 2^24 the tie-break is ONE phase: an odd multiplier mod
    2^24 is invertible, so distinct indices get distinct 24-bit hashes,
    each exactly representable in float32. Larger n falls back to
    comparing a 32-bit hash in two 16-bit halves (two phases). Each
    phase costs a scatter+gather round over the arena — the dominant
    cost of the selection loops on TPU.

    Returns [N] bool winners — candidates that are the unique argmax in
    every arena cell they touch.
    """
    n = prio.shape[0]
    p = jnp.where(cand, prio, -jnp.inf)
    best = gather_arena(scatter_arena(p))
    is_top = cand & (p >= best) & jnp.isfinite(p)
    if n <= (1 << 24):
        h24 = (
            jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
        ) & jnp.uint32(0xFFFFFF)
        h = h24.astype(jnp.float32)
        best_h = gather_arena(scatter_arena(jnp.where(is_top, h, -1.0)))
        return is_top & (h >= best_h)
    idx = jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
    hi = (idx >> 16).astype(jnp.float32)
    best_hi = gather_arena(scatter_arena(jnp.where(is_top, hi, -1.0)))
    is_top = is_top & (hi >= best_hi)
    lo = (idx & 0xFFFF).astype(jnp.float32)
    best_lo = gather_arena(scatter_arena(jnp.where(is_top, lo, -1.0)))
    return is_top & (lo >= best_lo)


def rank_winners(
    prio: jax.Array,
    cand: jax.Array,
    scatter_arena,
    gather_arena,
):
    """Independent-set selection in ONE arena propagation.

    Same contract as `two_phase_winners`, but the (priority, hashed-id)
    lexicographic comparison its two scatter+gather rounds implement is
    precomputed as a UNIQUE integer rank (two cheap [N] sorts — sorts
    are ~5x cheaper than an arena round on TPU, PERF_NOTES), so ONE
    max-propagation decides: a candidate wins iff its rank is the max
    in every arena cell it touches. The winner set is the same valid
    independent set, except richer in one benign edge case: a
    candidate that is priority-top in cell A but not in cell B no
    longer leaks its hash into B's tie-break, so B's rightful top
    cannot be spuriously suppressed (two_phase_winners is conservative
    there). The rank is exactly representable in f32 for N < 2^24 —
    the same argument as the collapse rank-MIS (round 4).
    """
    n = prio.shape[0]
    if n > (1 << 24):  # rank exactness in f32 needs N <= 2^24
        return two_phase_winners(prio, cand, scatter_arena, gather_arena)
    h24 = (
        jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
    ) & jnp.uint32(0xFFFFFF)
    p = jnp.where(cand, prio, -jnp.inf)
    order = jnp.lexsort((h24, p))  # ascending (prio, hash)
    rank = (
        jnp.zeros(n, jnp.int32)
        .at[order]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop",
             unique_indices=True)
    )
    r = jnp.where(cand, rank.astype(jnp.float32), -jnp.inf)
    best = gather_arena(scatter_arena(r))
    return cand & (r >= best) & jnp.isfinite(r)


# ---------------------------------------------------------------------------
# frontier (active-set) helpers — round 6
#
# Every sweep records the vertices whose geometry or 1-ring topology it
# changed (`changed_v` in the op stats); the NEXT sweep's candidate
# generation addresses only entities near that frontier. A candidate's
# decision depends on its arena — entities sharing a tet — so the gate
# is the one-ring closure of the changed set: any competitor's change
# lands in a shared tet, whose vertices the closure flags (see
# PERF_NOTES round 6 for the argument). Overflow/first-sweep fallback
# is the all-true mask: gating with it reproduces the full-table sweep
# bit for bit.
# ---------------------------------------------------------------------------


def one_ring_closure(tet, tmask, changed_v):
    """[PC] bool: vertices sharing a valid tet with a changed vertex
    (including the changed vertices themselves). One gather + one
    scatter — the whole frontier bookkeeping stays two cheap
    single-column passes per sweep."""
    pcap = changed_v.shape[0]
    t_hot = jnp.any(changed_v[tet], axis=1) & tmask
    idx = jnp.where(t_hot[:, None], tet, pcap)
    av = jnp.zeros(pcap, bool).at[idx.reshape(-1)].set(True, mode="drop")
    return av | changed_v


def edge_active(active_v, a, b, emask):
    """[E] bool: unique edge has an endpoint inside the active closure."""
    return emask & (active_v[a] | active_v[b])


def topk_candidates(cand, sortkey, K: int):
    """Worst-first candidate compaction shared by the remesh operators:
    the K lowest-`sortkey` rows among `cand` (non-candidates sort to
    +inf). Returns (pick [K] int32 row ids, valid [K] bool). Overflowing
    candidates — only in violent early sweeps — are the BEST-key rows
    and are retried next sweep; the Jacobi schedule already assumes
    multiple passes."""
    key = jnp.where(cand, sortkey, jnp.inf)
    pick = jnp.argsort(key)[:K].astype(jnp.int32)
    return pick, cand[pick]


# uint32 sentinel for packed invalid rows (valid packed keys are
# < (bound+1)^2 - 1 <= 0xFFFE0000 when bound <= PACK_BOUND, so the
# sentinel never collides). A NUMPY scalar, deliberately: a jnp
# constant built at import time leaks as a tracer when this module is
# first imported from inside a jit trace (the lazy `from ..ops import
# common` in core.mesh.compact) — the m0 UnexpectedTracerError
import numpy as _np

SENT_U32 = _np.uint32(0xFFFFFFFF)
# largest entity-id bound for which two int32 keys pack into one uint32
PACK_BOUND = 65534


def pack_ok(bound, ncols: int) -> bool:
    """Static predicate: can `ncols` keys with values in [0, bound) be
    pairwise-packed into uint32 sort keys? Packing halves the comparator
    width of the (bitonic on TPU) sort — the dominant cost of the
    sort-merge kernels — at the price of one multiply-add per row."""
    return bound is not None and ncols >= 2 and bound <= PACK_BOUND


def _pack_pairs(rows: jax.Array, invalid: jax.Array, bound: int):
    """[N,c] int32 rows with values in [0,bound) -> tuple of uint32 key
    columns, adjacent columns packed pairwise; invalid rows map to
    all-sentinel keys (shared — callers mask invalid rows out of every
    result, so a shared group is safe)."""
    s = jnp.uint32(bound + 1)
    c = rows.shape[1]
    cols = []
    i = 0
    while i < c:
        if i + 1 < c:
            kk = rows[:, i].astype(jnp.uint32) * s + rows[:, i + 1].astype(
                jnp.uint32
            )
            i += 2
        else:
            kk = rows[:, i].astype(jnp.uint32)
            i += 1
        cols.append(jnp.where(invalid, SENT_U32, kk))
    return tuple(cols)


def _row_order_groups(rows: jax.Array, invalid: jax.Array, bound):
    """Shared sort core of the row-matching helpers: returns
    (order [N] int32 — sorted row order, newgrp [N] bool — run starts).
    With a static `bound` on the row values the sort runs on packed
    uint32 keys (half the comparator width); otherwise on the raw
    columns with unique negative sentinels for invalid rows."""
    n, c = rows.shape
    if pack_ok(bound, c):
        cols = _pack_pairs(rows.astype(jnp.int32), invalid, bound)
        order = jnp.lexsort(tuple(reversed(cols))).astype(jnp.int32)
        sc = [kk[order] for kk in cols]
        diff = sc[0][1:] != sc[0][:-1]
        for kk in sc[1:]:
            diff = diff | (kk[1:] != kk[:-1])
        newgrp = jnp.concatenate([jnp.ones(1, bool), diff])
        return order, newgrp
    slot = jnp.arange(n, dtype=jnp.int32)
    uniq = jnp.concatenate(
        [(-(slot[:, None] + 2)), jnp.zeros((n, c - 1), jnp.int32)], axis=1
    )
    r = jnp.where(invalid[:, None], uniq, rows.astype(jnp.int32))
    order = jnp.lexsort(tuple(r[:, i] for i in reversed(range(c)))).astype(
        jnp.int32
    )
    sr = r[order]
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), jnp.any(sr[1:] != sr[:-1], axis=1)]
    )
    return order, newgrp


def sorted_pair_groups(lo, hi, dead, bound, dead_slot=None):
    """Sort (lo,hi) pairs and mark group starts — the shared core of
    `unique_edges` and `_detect_feature_edges`. Returns
    (order, newgrp, live_sorted, slo, shi) where slo/shi are the pair
    values in sorted order (garbage on dead rows in the packed path —
    consumers must gate on live_sorted). With `bound` packable the sort
    runs on one uint32 key; dead rows share the max sentinel and form a
    single trailing group that never becomes a representative.
    `dead_slot` (unpacked path only) supplies unique hi-values for dead
    rows; defaults to arange."""
    n = lo.shape[0]
    if pack_ok(bound, 2):
        s = jnp.uint32(bound + 1)
        key = lo.astype(jnp.uint32) * s + hi.astype(jnp.uint32)
        key = jnp.where(dead, SENT_U32, key)
        order = jnp.argsort(key).astype(jnp.int32)
        sk = key[order]
        newgrp = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
        live_sorted = sk != SENT_U32
        return order, newgrp, live_sorted, lo[order], hi[order]
    slot = (
        jnp.arange(n, dtype=jnp.int32) if dead_slot is None else dead_slot
    )
    big = jnp.int32(2**30)
    lo_s = jnp.where(dead, big, lo)
    hi_s = jnp.where(dead, slot, hi)
    order = jnp.lexsort((hi_s, lo_s)).astype(jnp.int32)
    slo, shi = lo_s[order], hi_s[order]
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), (slo[1:] != slo[:-1]) | (shi[1:] != shi[:-1])]
    )
    return order, newgrp, slo < big, slo, shi


def _run_match(keys: jax.Array, query: jax.Array, bound=None):
    """Sort-merge row matching: for each query row, does it appear among
    `keys` rows, and at what first index? Rows containing any negative
    entry are treated as invalid and never match. Returns (hit [Q] bool,
    idx [Q] int32 first-match index into keys or -1). int32-only.
    `bound` (static, optional): exclusive upper bound on row values,
    enables packed uint32 sort keys."""
    k, c = keys.shape
    q = query.shape[0]
    n = k + q
    rows = jnp.concatenate([keys, query], axis=0).astype(jnp.int32)
    invalid = jnp.any(rows < 0, axis=1)
    order, newgrp = _row_order_groups(rows, invalid, bound)
    from_key = order < k
    big = jnp.int32(n)
    # group reductions over the SORTED domain: segmented scans, not
    # scatter+gather (see seg_broadcast); both reductions fused on one
    # scan pair
    cnt_b, min_b = seg_broadcast_multi(newgrp, [
        (from_key.astype(jnp.int32), jnp.add, 0),
        (jnp.where(from_key, order, big), jnp.minimum, big),
    ])
    hit_sorted = cnt_b > 0
    idx_sorted = jnp.where(hit_sorted, min_b, -1)
    hit = jnp.zeros(n, bool).at[order].set(hit_sorted, unique_indices=True)
    idx = jnp.full(n, -1, jnp.int32).at[order].set(idx_sorted,
                                                   unique_indices=True)
    return hit[k:] & ~invalid[k:], jnp.where(invalid[k:], -1, idx[k:])


def _run_match2(keys: jax.Array, query: jax.Array, bound=None):
    """Like `_run_match` but returns, per query row, the FIRST and LAST
    matching key-row indices plus the match count (for entities that can
    legitimately appear twice among the keys, e.g. internal tria faces
    owned by two tets)."""
    k, c = keys.shape
    q = query.shape[0]
    n = k + q
    rows = jnp.concatenate([keys, query], axis=0).astype(jnp.int32)
    invalid = jnp.any(rows < 0, axis=1)
    order, newgrp = _row_order_groups(rows, invalid, bound)
    from_key = order < k
    big = jnp.int32(n)
    cnt_sorted, minidx, maxidx = seg_broadcast_multi(newgrp, [
        (from_key.astype(jnp.int32), jnp.add, 0),
        (jnp.where(from_key, order, big), jnp.minimum, big),
        (jnp.where(from_key, order, -1), jnp.maximum, -1),
    ])
    # per-sorted-position values, scattered back to original row order;
    # the invalid mask lives in the ORIGINAL domain and applies last
    lo = jnp.where(cnt_sorted > 0, minidx, -1)
    hi = jnp.where(cnt_sorted > 0, maxidx, -1)
    out_lo = jnp.full(n, -1, jnp.int32).at[order].set(lo, unique_indices=True)
    out_hi = jnp.full(n, -1, jnp.int32).at[order].set(hi, unique_indices=True)
    out_cnt = jnp.zeros(n, jnp.int32).at[order].set(cnt_sorted,
                                                    unique_indices=True)
    out_lo = jnp.where(invalid, -1, out_lo)
    out_hi = jnp.where(invalid, -1, out_hi)
    out_cnt = jnp.where(invalid, 0, out_cnt)
    return out_lo[k:], out_hi[k:], out_cnt[k:]


def match_rows2(keys: jax.Array, query: jax.Array, bound=None):
    """(first_idx, last_idx, count) of each query row among `keys` rows
    (-1/-1/0 when absent; rows with negative entries never match)."""
    return _run_match2(keys, query, bound)


def sorted_membership(keys: jax.Array, query: jax.Array,
                      bound=None) -> jax.Array:
    """[Q] bool: does each query row appear among `keys` rows? Rows with
    any negative entry never match."""
    hit, _ = _run_match(keys, query, bound)
    return hit


def match_rows(keys: jax.Array, query: jax.Array, bound=None) -> jax.Array:
    """[Q] int32 index of the first row of `keys` equal to each query row,
    -1 if absent."""
    _, idx = _run_match(keys, query, bound)
    return idx


def tria_edge_keys(mesh: Mesh, mask: jax.Array | None = None) -> jax.Array:
    """[3*FC, 2] canonically sorted (lo,hi) vertex pairs of tria edges
    (valid trias by default, or only those selected by `mask`); excluded
    trias give (-1,-1) rows."""
    t = mesh.tria
    pairs = jnp.stack(
        [t[:, [0, 1]], t[:, [1, 2]], t[:, [0, 2]]], axis=1
    )  # [FC,3,2]
    lo = jnp.minimum(pairs[..., 0], pairs[..., 1])
    hi = jnp.maximum(pairs[..., 0], pairs[..., 1])
    dead = ~(mesh.trmask if mask is None else mask)[:, None]
    lo = jnp.where(dead, -1, lo).reshape(-1)
    hi = jnp.where(dead, -1, hi).reshape(-1)
    return jnp.stack([lo, hi], axis=1)


def surface_edge_mask(mesh: Mesh, edges: jax.Array, emask: jax.Array):
    """[E] bool: edge lies on the boundary surface (appears in a valid
    tria). The flat-array analog of the xtetra-tag lookups the reference
    does through `MMG5_HGeom` hashes (`src/hash_pmmg.c`)."""
    keys = tria_edge_keys(mesh)
    q = jnp.where(emask[:, None], edges, -1)
    return sorted_membership(keys, q, bound=mesh.pcap)


def feature_edge_index(mesh: Mesh, edges: jax.Array, emask: jax.Array):
    """[E] int32 index into mesh.edge of the feature edge matching each
    unique tet edge (-1 if none)."""
    lo = jnp.minimum(mesh.edge[:, 0], mesh.edge[:, 1])
    hi = jnp.maximum(mesh.edge[:, 0], mesh.edge[:, 1])
    dead = ~mesh.edmask
    keys = jnp.stack(
        [jnp.where(dead, -1, lo), jnp.where(dead, -1, hi)], axis=1
    )
    q = jnp.where(emask[:, None], edges, -1)
    return match_rows(keys, q, bound=mesh.pcap)


def duplicate_tets(tet: jax.Array, valid: jax.Array, bound=None) -> jax.Array:
    """[T] bool: tet's sorted vertex set appears more than once among valid
    tets (topological damage detector used to reject unsafe collapses —
    the batched stand-in for Mmg's link-condition check). `bound` (static,
    optional) = exclusive vertex-id bound, enables packed uint32 keys."""
    tcap = tet.shape[0]
    slot = jnp.arange(tcap, dtype=jnp.int32)
    keys = jnp.sort(tet, axis=1)
    if pack_ok(bound, 4):
        s = jnp.uint32(bound + 1)
        k0 = keys[:, 0].astype(jnp.uint32) * s + keys[:, 1].astype(jnp.uint32)
        k1 = keys[:, 2].astype(jnp.uint32) * s + keys[:, 3].astype(jnp.uint32)
        # invalid rows: sentinel first key, unique second key (slot) so
        # two invalid rows never read as duplicates of each other
        k0 = jnp.where(valid, k0, SENT_U32)
        k1 = jnp.where(valid, k1, slot.astype(jnp.uint32))
        order = jnp.lexsort((k1, k0)).astype(jnp.int32)
        s0, s1 = k0[order], k1[order]
        same_next = jnp.concatenate(
            [(s0[:-1] == s0[1:]) & (s1[:-1] == s1[1:]), jnp.zeros(1, bool)]
        )
    else:
        keys = jnp.where(valid[:, None], keys, -(slot[:, None] + 2))
        order = jnp.lexsort(
            (keys[:, 3], keys[:, 2], keys[:, 1], keys[:, 0])
        ).astype(jnp.int32)
        sk = keys[order]
        same_next = jnp.concatenate(
            [jnp.all(sk[:-1] == sk[1:], axis=1), jnp.zeros(1, bool)]
        )
    same_prev = jnp.concatenate([jnp.zeros(1, bool), same_next[:-1]])
    dup_sorted = same_next | same_prev
    out = jnp.zeros(tcap, bool).at[order].set(dup_sorted, unique_indices=True)
    return out & valid


def vol_of(vert: jax.Array, tet: jax.Array) -> jax.Array:
    c = vert[tet]
    d1, d2, d3 = c[:, 1] - c[:, 0], c[:, 2] - c[:, 0], c[:, 3] - c[:, 0]
    return jnp.einsum("ti,ti->t", jnp.cross(d1, d2), d3) / 6.0


def quality_of(vert: jax.Array, met: jax.Array, tet: jax.Array) -> jax.Array:
    """Quality of arbitrary tet rows against given vert/met arrays (same
    measure as ops.quality.tet_quality, usable on tentative configs).

    Gathers the 4 corner rows once and derives the 6 edge vectors from
    them — random-index gathers are the dominant kernel cost on TPU
    (row-DMA bound), so 4 wide rows beat 12 endpoint lookups."""
    c = vert[tet]                                     # [T,4,3] one gather
    d1, d2, d3 = c[:, 1] - c[:, 0], c[:, 2] - c[:, 0], c[:, 3] - c[:, 0]
    vol = jnp.einsum("ti,ti->t", jnp.cross(d1, d2), d3) / 6.0
    ev = jnp.asarray(EDGE_VERTS)
    e = c[:, ev[:, 1]] - c[:, ev[:, 0]]               # [T,6,3] from corners
    if met.shape[1] == 6:
        mt = jnp.mean(met[tet], axis=1)
        M = metric_mod.sym6_to_mat(mt)
        l2 = jnp.einsum("tei,tij,tej->te", e, M, e)
        volm = vol * jnp.sqrt(jnp.maximum(metric_mod.metric_det(mt), 0.0))
    else:
        h = jnp.mean(met[tet, 0], axis=1)
        l2 = jnp.sum(e * e, axis=-1) / jnp.maximum(h[:, None] ** 2, 1e-30)
        volm = vol / jnp.maximum(h**3, 1e-30)
    rap = jnp.sum(l2, axis=-1)
    q = ALPHA * volm / jnp.maximum(rap, 1e-30) ** 1.5
    return jnp.where(jnp.isfinite(q), q, 0.0)
