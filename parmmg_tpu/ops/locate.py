"""Point location in a background tetrahedral mesh.

TPU-native re-design of the reference's location machinery
(`src/locate_pmmg.c`: adjacency walk `PMMG_locatePointVol:786`, step
`PMMG_locatePointInTetra:441`, exhaustive fallback `:737`; barycentric
predicates in `src/barycoord_pmmg.c`): instead of one serial walk per vertex,
*all* queries walk simultaneously inside one bounded `lax.while_loop`,
steered by the sign of their barycentric coordinates; seeds come from a
Morton-key spatial sort (replacing the `USE_POINTMAP` warm start); points the
walk cannot resolve (outside the domain, or blocked at a boundary) fall back
to a scanned exhaustive search that returns the closest element
(`PMMG_barycoord*_getClosest` role, reference `src/barycoord_pmmg.c:324,371`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import sfc
from ..core.mesh import Mesh


def tet_barycoords(c: jax.Array, p: jax.Array) -> jax.Array:
    """Barycentric coordinates of points in tets.

    c: [...,4,3] tet vertex coords, p: [...,3] points ->  [...,4] coords
    summing to 1 (for non-degenerate tets). lambda_i is the signed volume of
    the tet with vertex i replaced by p over the tet volume — same
    construction as the reference (`PMMG_barycoord3d_compute`, reference
    `src/barycoord_pmmg.c:238`), vectorized.
    """

    def vol6(a, b, d, e):
        return jnp.einsum("...i,...i->...", jnp.cross(b - a, d - a), e - a)

    v0, v1, v2, v3 = c[..., 0, :], c[..., 1, :], c[..., 2, :], c[..., 3, :]
    v = vol6(v0, v1, v2, v3)
    l0 = vol6(p, v1, v2, v3)
    l1 = vol6(v0, p, v2, v3)
    l2 = vol6(v0, v1, p, v3)
    l3 = vol6(v0, v1, v2, p)
    lam = jnp.stack([l0, l1, l2, l3], axis=-1)
    tiny = jnp.asarray(jnp.finfo(p.dtype).tiny, p.dtype)  # f32-safe floor
    denom = jnp.where(jnp.abs(v) > tiny, v, jnp.where(v >= 0, tiny, -tiny))
    return lam / denom[..., None]


class LocateResult(NamedTuple):
    tet: jax.Array    # [Q] int32 containing (or closest) tet slot
    bary: jax.Array   # [Q,4] barycentric coords, clamped to the simplex
    found: jax.Array  # [Q] bool: strictly located by the walk (not fallback)
    steps: jax.Array  # [Q] int32 walk steps taken (search statistics, the
    #                   `PMMG_locateStats` role, reference src/locate_pmmg.c:996)


def clamp_bary(lam: jax.Array) -> jax.Array:
    """Project barycoords onto the simplex (closest-point behavior for
    slightly-outside points)."""
    lam = jnp.maximum(lam, 0.0)
    s = jnp.sum(lam, axis=-1, keepdims=True)
    return lam / jnp.maximum(s, 1e-30)


def morton_seeds(mesh: Mesh, pts: jax.Array) -> jax.Array:
    """[Q] int32 seed tet per query point from a Morton sort of barycenters.

    Tets are sorted by the Morton key of their barycenter; each query is
    binary-searched into that order, giving a spatially nearby live tet —
    the batched analog of the reference's per-point warm start
    (`PMMG_locate_setStart`, reference `src/locate_pmmg.c:931`).
    """
    bc = jnp.mean(mesh.vert[mesh.tet], axis=1)  # [T,3]
    live = mesh.tmask
    lo = jnp.min(jnp.where(live[:, None], bc, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(live[:, None], bc, -jnp.inf), axis=0)
    keys = sfc.morton_keys(bc, lo, hi)
    keys = jnp.where(live, keys, jnp.int32(2**30))  # dead tets sort last
    order = jnp.argsort(keys).astype(jnp.int32)
    skeys = keys[order]
    nlive = jnp.sum(live.astype(jnp.int32))
    qkeys = sfc.morton_keys(pts, lo, hi)
    pos = jnp.searchsorted(skeys, qkeys).astype(jnp.int32)
    pos = jnp.clip(pos, 0, jnp.maximum(nlive - 1, 0))
    return order[pos]


# parmmg-lint: disable=PML005 -- locate queries the same mesh repeatedly; donation would invalidate it
@partial(jax.jit, static_argnames=("max_steps",))
def walk_locate(
    mesh: Mesh,
    pts: jax.Array,
    seeds: jax.Array,
    max_steps: int = 64,
    eps: float | None = None,
) -> LocateResult:
    """Simultaneous adjacency walk for all query points.

    Requires `mesh.adja` to be fresh (build_adjacency after any topology
    change). Each un-done query moves to the neighbor across the face of its
    most negative barycentric coordinate — the same steering rule as the
    reference's `PMMG_locatePointVol` — until inside, blocked at a boundary
    face, or out of steps.
    """
    if eps is None:
        # dtype-relative inside-tolerance: barycoord noise is ~1e-6 relative
        # in f32, so an absolute 1e-9 would misreport walk failures there
        # (reference PMMG_locatePointInTetra uses a relative epsilon too)
        eps = max(1e-9, 100.0 * float(jnp.finfo(pts.dtype).eps))
    q = pts.shape[0]
    zero = jnp.zeros(q, bool)

    def cond(state):
        cur, done, stuck, steps, it = state
        return (it < max_steps) & jnp.any(~(done | stuck))

    def body(state):
        cur, done, stuck, steps, it = state
        lam = tet_barycoords(mesh.vert[mesh.tet[cur]], pts)
        inside = jnp.min(lam, axis=-1) >= -eps
        face = jnp.argmin(lam, axis=-1)
        code = mesh.adja[cur, face]
        blocked = code < 0
        active = ~(done | stuck)
        new_done = done | (active & inside)
        new_stuck = stuck | (active & ~inside & blocked)
        moving = active & ~inside & ~blocked
        new_cur = jnp.where(moving, code // 4, cur)
        steps = steps + moving.astype(jnp.int32)
        return new_cur, new_done, new_stuck, steps, it + 1

    cur, done, stuck, steps, _ = jax.lax.while_loop(
        cond,
        body,
        (seeds.astype(jnp.int32), zero, zero, jnp.zeros(q, jnp.int32), 0),
    )
    lam = tet_barycoords(mesh.vert[mesh.tet[cur]], pts)
    done = done | (jnp.min(lam, axis=-1) >= -eps)
    return LocateResult(cur, clamp_bary(lam), done, steps)


# parmmg-lint: disable=PML005 -- locate queries the same mesh repeatedly; donation would invalidate it
@partial(jax.jit, static_argnames=("tchunk",))
def exhaustive_locate(mesh: Mesh, pts: jax.Array, tchunk: int = 1024):
    """Best tet per query over ALL valid tets (max of min barycoord),
    scanned in tet chunks to bound memory — the batched analog of the
    reference's exhaustive fallback (`src/locate_pmmg.c:737`). Returns
    (tet [Q], bary [Q,4] clamped)."""
    tcap = mesh.tcap
    nch = -(-tcap // tchunk)
    pad = nch * tchunk - tcap
    tet = jnp.concatenate([mesh.tet, jnp.zeros((pad, 4), jnp.int32)])
    tmask = jnp.concatenate([mesh.tmask, jnp.zeros(pad, bool)])
    tet_c = tet.reshape(nch, tchunk, 4)
    mask_c = tmask.reshape(nch, tchunk)
    base = (jnp.arange(nch, dtype=jnp.int32) * tchunk)[:, None]
    ids_c = base + jnp.arange(tchunk, dtype=jnp.int32)[None, :]

    q = pts.shape[0]

    def step(carry, chunk):
        best_v, best_i = carry
        tets, mask, ids = chunk
        lam = tet_barycoords(mesh.vert[tets][None], pts[:, None])  # [Q,K,4]
        mb = jnp.min(lam, axis=-1)
        mb = jnp.where(mask[None, :], mb, -jnp.inf)
        k = jnp.argmax(mb, axis=-1)
        v = jnp.max(mb, axis=-1)
        upd = v > best_v
        best_v = jnp.where(upd, v, best_v)
        best_i = jnp.where(upd, ids[k], best_i)
        return (best_v, best_i), None

    init = (jnp.full(q, -jnp.inf, pts.dtype), jnp.zeros(q, jnp.int32))
    (best_v, best_i), _ = jax.lax.scan(step, init, (tet_c, mask_c, ids_c))
    lam = tet_barycoords(mesh.vert[mesh.tet[best_i]], pts)
    return best_i, clamp_bary(lam)


def tria_barycoords(c: jax.Array, p: jax.Array) -> jax.Array:
    """Barycentric coords of the projection of p onto the tria plane.

    c: [...,3,3] tria vertex coords, p: [...,3] -> [...,3] coords summing
    to 1 (the 2D projected path of the reference,
    `PMMG_barycoord2d_compute`, `src/barycoord_pmmg.c:135-237`)."""
    a, b, d = c[..., 0, :], c[..., 1, :], c[..., 2, :]
    v0 = b - a
    v1 = d - a
    v2 = p - a
    d00 = jnp.einsum("...i,...i->...", v0, v0)
    d01 = jnp.einsum("...i,...i->...", v0, v1)
    d11 = jnp.einsum("...i,...i->...", v1, v1)
    d20 = jnp.einsum("...i,...i->...", v2, v0)
    d21 = jnp.einsum("...i,...i->...", v2, v1)
    denom = d00 * d11 - d01 * d01
    tiny = jnp.asarray(jnp.finfo(p.dtype).tiny, p.dtype)
    denom = jnp.where(jnp.abs(denom) > tiny, denom, tiny)
    lv = (d11 * d20 - d01 * d21) / denom
    lw = (d00 * d21 - d01 * d20) / denom
    return jnp.stack([1.0 - lv - lw, lv, lw], axis=-1)


class BdyLocateResult(NamedTuple):
    tria: jax.Array   # [Q] int32 best surface-tria slot
    bary: jax.Array   # [Q,3] clamped barycentric coords on that tria
    dist: jax.Array   # [Q] distance to the closest point used


# default wedge threshold: cos 45 deg, the default feature angle.
# Callers with a configured -ar pass cos(angle) so the demotion
# threshold agrees with where the session's ridges actually are.
_COS_WEDGE = 0.70710678


# parmmg-lint: disable=PML005 -- locate queries the same mesh repeatedly; donation would invalidate it
@partial(jax.jit, static_argnames=("window",))
def bdy_locate(
    mesh: Mesh, surf_mask: jax.Array, pts: jax.Array, window: int = 32,
    normals: jax.Array | None = None, cos_wedge: float = _COS_WEDGE,
) -> BdyLocateResult:
    """Locate boundary points on the boundary triangulation — the
    `PMMG_locatePointBdy` role (reference `src/locate_pmmg.c:587`).

    Instead of the reference's serial tria walk, every query scans a
    `window` of surface trias around its position in a Morton order of
    tria barycenters and keeps the one whose (clamped-barycentric)
    closest point is nearest — a batched nearest-tria search with the
    same interpolation-source semantics.

    `normals` ([Q,3] unit query normals, optional) carries the role of
    the reference's cone/wedge vertex/edge classification
    (`PMMG_locatePointInCone/InWedge`, `src/locate_pmmg.c:209-384`):
    within a discretization-error band of a feature line BOTH sides are
    equally near, and raw distance can pick the tria across the ridge —
    interpolating the metric across the feature. A candidate whose plane
    normal deviates from the query normal past the ridge threshold is
    demoted (distance penalty, not exclusion: a query with no compatible
    candidate still gets its geometric nearest). Zero query normals
    (volume/non-surface queries) disable the test for that query."""
    bc3 = jnp.mean(mesh.vert[mesh.tria], axis=1)  # [F,3]
    lo = jnp.min(jnp.where(surf_mask[:, None], bc3, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(surf_mask[:, None], bc3, -jnp.inf), axis=0)
    keys = sfc.morton_keys(bc3, lo, hi)
    keys = jnp.where(surf_mask, keys, jnp.int32(2**30))
    order = jnp.argsort(keys).astype(jnp.int32)
    skeys = keys[order]
    nlive = jnp.sum(surf_mask.astype(jnp.int32))
    qkeys = sfc.morton_keys(pts, lo, hi)
    pos = jnp.searchsorted(skeys, qkeys).astype(jnp.int32)

    offs = jnp.arange(-window // 2, window - window // 2, dtype=jnp.int32)
    cand_pos = jnp.clip(pos[:, None] + offs[None, :], 0,
                        jnp.maximum(nlive - 1, 0))  # [Q,W]
    cand = order[cand_pos]                           # [Q,W] tria slots
    c = mesh.vert[mesh.tria[cand]]                   # [Q,W,3,3]
    lam = clamp_bary(tria_barycoords(c, pts[:, None, :]))
    closest = jnp.einsum("qwk,qwki->qwi", lam, c)
    dist = jnp.linalg.norm(closest - pts[:, None, :], axis=-1)
    dist = jnp.where(surf_mask[cand], dist, jnp.inf)
    score = dist
    if normals is not None:
        raw = jnp.cross(c[..., 1, :] - c[..., 0, :],
                        c[..., 2, :] - c[..., 0, :])
        tn = raw / jnp.maximum(
            jnp.linalg.norm(raw, axis=-1), 1e-30
        )[..., None]
        # |dot|: candidate orientation (winding) must not matter
        dot = jnp.abs(jnp.einsum("qi,qwi->qw", normals, tn))
        has_n = jnp.linalg.norm(normals, axis=-1) > 0.5  # unit or zero
        wrong_side = has_n[:, None] & (dot < cos_wedge)
        pen = jnp.linalg.norm(hi - lo)  # dominates any in-window dist
        score = jnp.where(wrong_side & jnp.isfinite(dist),
                          dist + pen, dist)
    k = jnp.argmin(score, axis=-1)
    qi = jnp.arange(pts.shape[0], dtype=jnp.int32)
    return BdyLocateResult(cand[qi, k], lam[qi, k], dist[qi, k])


def bucketed_fail_idx(fail_idx):
    """Pad a failed-query index list to a power-of-2 bucket so the
    exhaustive kernel compiles for few distinct shapes. Shared by every
    exhaustive-fallback site."""
    import numpy as np

    bucket = max(8, 1 << (len(fail_idx) - 1).bit_length())
    pad_idx = np.zeros(bucket, np.int32)
    pad_idx[: len(fail_idx)] = fail_idx
    return pad_idx


def locate_points(
    mesh: Mesh,
    pts: jax.Array,
    seeds: jax.Array | None = None,
    max_steps: int = 64,
    fallback: bool = True,
) -> LocateResult:
    """Host-orchestrated location: Morton-seeded walk, then exhaustive
    closest-element fallback for the (rare) failures. `mesh` must carry a
    fresh adjacency."""
    if seeds is None:
        seeds = morton_seeds(mesh, pts)
    res = walk_locate(mesh, pts, seeds, max_steps=max_steps)
    found_np = jax.device_get(res.found)
    if fallback and not found_np.all():
        import numpy as np

        # compact the failed subset on host
        fail_idx = np.nonzero(~found_np)[0]
        pad_idx = bucketed_fail_idx(fail_idx)
        fb_tet, fb_bary = exhaustive_locate(mesh, pts[jnp.asarray(pad_idx)])
        tet = res.tet.at[pad_idx[: len(fail_idx)]].set(fb_tet[: len(fail_idx)])
        bary = res.bary.at[pad_idx[: len(fail_idx)]].set(
            fb_bary[: len(fail_idx)]
        )
        res = LocateResult(tet, bary, res.found, res.steps)
    return res
