"""Batched edge split: refine every metric-long edge in parallel.

Functional counterpart of the refinement half of Mmg's adaptation kernel
(`MMG5_mmg3d1_delone`, invoked by the reference at `src/libparmmg1.c:739`):
edges longer than LLONG in the metric are bisected. Instead of serial cavity
splits, a maximal independent set of long edges is selected per sweep (at
most one split edge per tet, priority = metric length), and every incident
tet/tria/feature-edge is split 1→2 in one vectorized update. Repeated
sweeps converge to the same unit-length goal as the serial kernel.

Frozen entities (PARBDY interface, REQUIRED) are never split, matching the
reference's interface-freezing discipline (`src/tag_pmmg.c`).

Frontier mode (round 6): with an `active` vertex mask (one-ring closure
of the previous sweep's changes) candidates are restricted to edges near
the frontier, and the heavy phase — the tria-edge sort-merge, vertex
normals, MIS, and all apply scatters — is skipped entirely via
`lax.cond` when no long active edge exists. `active=None` reproduces the
full-table sweep exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import kernels
from ..core import metric as metric_mod
from ..core import tags
from ..core.mesh import EDGE_VERTS, Mesh
from . import common
from .analysis import surf_tria_mask, vertex_normals


class SplitStats(NamedTuple):
    nsplit: jax.Array       # edges split this sweep
    ncand: jax.Array        # long-edge candidates before selection
    capped: jax.Array       # bool: capacity limited the sweep
    changed_v: jax.Array    # [PC] bool — vertices whose 1-ring changed


# tag bits a new mid-edge vertex inherits from a surface/feature edge
_INHERIT = tags.BDY | tags.RIDGE | tags.REF | tags.REQUIRED


@partial(jax.jit, static_argnames=("llong", "nosurf"), donate_argnums=0)
def split_long_edges(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
    llong: float = float(metric_mod.LLONG),
    nosurf: bool = False,
    active: jax.Array | None = None,
):
    """One split sweep. Mesh must be compacted (valid slots are prefixes).

    Returns (mesh, SplitStats). Adjacency is left stale."""
    ecap = edges.shape[0]
    tcap = mesh.tcap
    pcap = mesh.pcap
    np0 = mesh.npoin
    ne0 = mesh.ntet
    nf0 = mesh.ntria
    ned0 = mesh.nedge

    a, b = edges[:, 0], edges[:, 1]
    l = metric_mod.edge_length(
        mesh.vert[a], mesh.vert[b], mesh.met[a], mesh.met[b]
    )
    pre = emask & (l > llong)
    if active is not None:
        # frontier gate: an inactive long edge was already offered to
        # the MIS last sweep with an identical arena and lost/was
        # rejected — only edges near the change frontier can decide
        # differently this sweep
        pre = pre & (active[a] | active[b])

    def _heavy(mesh):
        # one sort-merge pass maps every tria edge to its unique-edge
        # slot; surface / required-tria masks and the tria-split step
        # below all derive from it (keeps the hot path at a single
        # tria-edge match)
        fcap = mesh.fcap
        edge_keys = jnp.where(emask[:, None], edges, -1)
        tri_keys = common.tria_edge_keys(mesh)  # [3*FC,2], order 01,12,02
        eid3 = common.match_rows(edge_keys, tri_keys,
                                 bound=mesh.pcap).reshape(fcap, 3)

        def mark_edges(tri_mask):
            tgt = jnp.where(tri_mask[:, None] & (eid3 >= 0), eid3, ecap)
            return (
                jnp.zeros(ecap, bool).at[tgt.reshape(-1)].set(True,
                                                              mode="drop")
            )

        surf = mark_edges(mesh.trmask)
        feat = common.feature_edge_index(mesh, edges, emask)
        feat_tag = jnp.where(feat >= 0, mesh.edtag[feat], 0)
        # edges of REQUIRED triangles are frozen too, not just required
        # feature edges (RequiredTriangles discipline, reference
        # src/tag_pmmg.c)
        in_req_tri = mark_edges(
            mesh.trmask & ((mesh.trtag & tags.REQUIRED) != 0)
        )
        frozen = (
            ((mesh.vtag[a] & tags.PARBDY) != 0)
            & ((mesh.vtag[b] & tags.PARBDY) != 0)
        ) | ((feat_tag & tags.REQUIRED) != 0) | in_req_tri
        if nosurf:
            # -nosurf: the boundary surface is exactly preserved — no
            # insertions on surface edges either (Mmg tags the whole
            # boundary MG_REQ under nosurf)
            frozen = frozen | surf
        cand = pre & ~frozen
        ncand = jnp.sum(cand.astype(jnp.int32)).astype(jnp.int32)

        # --- independent-set selection: arena = incident tets --------------
        live_e = (t2e >= 0) & mesh.tmask[:, None]  # [TC,6]
        safe_t2e = jnp.where(live_e, t2e, 0)

        def scatter_arena(vals):  # [E] -> [TC] max over own edges
            v6 = jnp.where(live_e, vals[safe_t2e], -jnp.inf)
            return jnp.max(v6, axis=1)

        def gather_arena(av):  # [TC] -> [E] max over incident tets
            tgt = jnp.where(live_e, t2e, ecap)
            out = jnp.full(ecap, -jnp.inf, av.dtype)
            return out.at[tgt.reshape(-1)].max(
                jnp.broadcast_to(av[:, None], (tcap, 6)).reshape(-1),
                mode="drop",
            )

        win = common.rank_winners(l, cand, scatter_arena, gather_arena)

        # --- capacity capping ----------------------------------------------
        inc_t = jnp.zeros(ecap, jnp.int32).at[safe_t2e.reshape(-1)].add(
            live_e.reshape(-1).astype(jnp.int32), mode="drop"
        )  # tets per edge
        wi = win.astype(jnp.int32)
        rank_v = jnp.cumsum(wi) - 1                      # new-vertex rank
        used_t = jnp.cumsum(wi * inc_t)                  # appended tets
        used_f = jnp.cumsum(wi * surf.astype(jnp.int32) * 2)  # trias (<=2)
        used_e = jnp.cumsum(wi * (feat >= 0).astype(jnp.int32))
        fits = (
            (np0 + rank_v + 1 <= mesh.pcap)
            & (ne0 + used_t <= tcap)
            & (nf0 + used_f <= mesh.fcap)
            & (ned0 + used_e <= mesh.ecap)
        )
        capped = jnp.any(win & ~fits)
        win = win & fits
        wi = win.astype(jnp.int32)
        rank_v = jnp.cumsum(wi) - 1
        nsplit = jnp.sum(wi).astype(jnp.int32)

        # new vertex slot per winner edge
        vnew = jnp.where(win, np0 + rank_v, -1).astype(jnp.int32)

        # per-tet winner mapping (shared by midpoint validation + split)
        w6 = jnp.where(live_e, win[safe_t2e], False)  # [TC,6]
        has = jnp.any(w6, axis=1) & mesh.tmask
        k = jnp.argmax(w6, axis=1)                    # local edge slot
        e_of_t = safe_t2e[jnp.arange(tcap, dtype=jnp.int32), k]
        ev_j = jnp.asarray(EDGE_VERTS)
        li = ev_j[k, 0]
        lj = ev_j[k, 1]
        rows = jnp.arange(tcap, dtype=jnp.int32)

        # --- new vertex position -------------------------------------------
        pa, pb = mesh.vert[a], mesh.vert[b]
        mid = 0.5 * (pa + pb)
        if not nosurf:
            # Curvature-corrected midpoint for plain surface edges — the
            # cubic Bezier tangent rule of Mmg's `MMG5_BezierTgt` patch
            # evaluated at t=1/2: mid + ((e.nb)nb - (e.na)na)/8, which
            # places the point on the circle through the endpoints with
            # the endpoint normals. Feature edges and feature endpoints
            # keep the linear midpoint (their blended vertex normals are
            # meaningless), and any incident tet that the offset would
            # squash below the positivity floor reverts that edge to the
            # linear midpoint.
            # frontier mode: normals are read only at the endpoints of
            # candidate edges — exactly the rows `need` keeps exact
            if active is not None:
                need_v = jnp.zeros(pcap, bool)
                need_v = need_v.at[jnp.where(pre, a, pcap)].set(
                    True, mode="drop"
                )
                need_v = need_v.at[jnp.where(pre, b, pcap)].set(
                    True, mode="drop"
                )
            else:
                need_v = None
            vn = vertex_normals(mesh, need=need_v)
            surf_real = mark_edges(surf_tria_mask(mesh) & mesh.trmask)
            na_, nb_ = vn[a], vn[b]
            has_n = (jnp.sum(na_ * na_, axis=1) > 0.5) & (
                jnp.sum(nb_ * nb_, axis=1) > 0.5
            )
            featv = (
                (mesh.vtag[a] | mesh.vtag[b])
                & (tags.RIDGE | tags.REF | tags.CORNER | tags.NOM
                   | tags.PARBDY)
            ) != 0
            plain = surf_real & has_n & ~featv & (feat < 0)
            e_vec = pb - pa
            corr = (
                jnp.einsum("ei,ei->e", e_vec, nb_)[:, None] * nb_
                - jnp.einsum("ei,ei->e", e_vec, na_)[:, None] * na_
            ) / 8.0
            mid_c = mid + corr
            # per-tet validity of the offset midpoint: both child
            # volumes vs the parent positivity floor, fused
            # (kernels.split_midpoint — one pass over the tet stream)
            newp = mid_c[e_of_t]                      # [TC,3]
            okt = kernels.split_midpoint(mesh.vert, mesh.tet, newp, li, lj)
            bad_off = jnp.zeros(ecap, bool).at[
                jnp.where(has & ~okt, e_of_t, ecap)
            ].max(True, mode="drop")
            mid = jnp.where((plain & ~bad_off)[:, None], mid_c, mid)
        ma = mesh.met[a]
        mets = jnp.stack([ma, mesh.met[b]], axis=-2)  # [E,2,C]
        half = jnp.full(ecap, 0.5, mesh.vert.dtype)
        bary = jnp.stack([half, half], axis=-1)
        mmid = metric_mod.interp_metric(mets, bary)
        new_tag = jnp.where(surf, tags.BDY, 0) | (feat_tag & _INHERIT)
        new_ref = jnp.where(feat >= 0, mesh.edref[jnp.maximum(feat, 0)], 0)

        # winner targets are distinct appended slots; distinct OOB
        # sentinels keep the unique-indices promise (faster scatter
        # lowering on TPU)
        tgt_v = common.unique_oob(win, vnew, mesh.pcap)
        kw = dict(mode="drop", unique_indices=True)
        vert = common.scatter_rows(mesh.vert, tgt_v, mid, unique=True)
        met = common.scatter_rows(mesh.met, tgt_v, mmid, unique=True)
        ls = common.scatter_rows(
            mesh.ls, tgt_v, 0.5 * (mesh.ls[a] + mesh.ls[b]), unique=True
        )
        disp = common.scatter_rows(
            mesh.disp, tgt_v, 0.5 * (mesh.disp[a] + mesh.disp[b]),
            unique=True,
        )
        fields = common.scatter_rows(
            mesh.fields, tgt_v, 0.5 * (mesh.fields[a] + mesh.fields[b]),
            unique=True,
        )
        vtag = mesh.vtag.at[tgt_v].set(new_tag, **kw)
        vref = mesh.vref.at[tgt_v].set(new_ref, **kw)
        vmask = mesh.vmask.at[tgt_v].set(True, **kw)

        # --- split tets ----------------------------------------------------
        nv_of_t = vnew[e_of_t]
        # child A in place: vertex lj -> newv
        tetA = mesh.tet.at[rows, lj].set(
            jnp.where(has, nv_of_t, mesh.tet[rows, lj])
        )
        # child B appended: vertex li -> newv (of the ORIGINAL tet)
        tetB = mesh.tet.at[rows, li].set(nv_of_t)
        app_rank = jnp.cumsum(has.astype(jnp.int32)) - 1
        tgt_t = common.unique_oob(has, ne0 + app_rank, tcap)
        tet = common.scatter_rows(tetA, tgt_t, tetB, unique=True)
        tref = mesh.tref.at[tgt_t].set(mesh.tref, **kw)
        tmask = mesh.tmask.at[tgt_t].set(has, **kw)

        # --- split trias (reuses eid3 from candidate selection) ------------
        w3 = (eid3 >= 0) & win[jnp.maximum(eid3, 0)] & mesh.trmask[:, None]
        fhas = jnp.any(w3, axis=1)
        fk = jnp.argmax(w3, axis=1)
        _TRI_PAIRS = jnp.array([[0, 1], [1, 2], [0, 2]], jnp.int32)
        fu = _TRI_PAIRS[fk, 0]
        fv = _TRI_PAIRS[fk, 1]
        fe = jnp.maximum(eid3[jnp.arange(fcap, dtype=jnp.int32), fk], 0)
        fnv = vnew[fe]
        frows = jnp.arange(fcap, dtype=jnp.int32)
        triA = mesh.tria.at[frows, fv].set(
            jnp.where(fhas, fnv, mesh.tria[frows, fv])
        )
        triB = mesh.tria.at[frows, fu].set(fnv)
        frank = jnp.cumsum(fhas.astype(jnp.int32)) - 1
        tgt_f = common.unique_oob(fhas, nf0 + frank, fcap)
        tria = common.scatter_rows(triA, tgt_f, triB, unique=True)
        trref = mesh.trref.at[tgt_f].set(mesh.trref, **kw)
        trtag = mesh.trtag.at[tgt_f].set(mesh.trtag, **kw)
        trmask = mesh.trmask.at[tgt_f].set(fhas, **kw)

        # --- split feature edges -------------------------------------------
        ehas = win & (feat >= 0)
        fidx = jnp.where(ehas, feat, mesh.ecap).astype(jnp.int32)
        # use the stored row's own endpoint order (rows are not
        # canonically sorted): in place (r0,r1) -> (r0,newv), append
        # (newv,r1)
        r1 = mesh.edge[jnp.maximum(feat, 0), 1]
        edge_arr = mesh.edge.at[fidx, 1].set(vnew, mode="drop")
        erank = jnp.cumsum(ehas.astype(jnp.int32)) - 1
        tgt_e = common.unique_oob(ehas, ned0 + erank, mesh.ecap)
        newrow = jnp.stack([vnew, r1], axis=1)
        edge_arr = common.scatter_rows(edge_arr, tgt_e, newrow, unique=True)
        edref = mesh.edref.at[tgt_e].set(
            jnp.where(feat >= 0, mesh.edref[jnp.maximum(feat, 0)], 0), **kw
        )
        edtag = mesh.edtag.at[tgt_e].set(feat_tag, **kw)
        edmask = mesh.edmask.at[tgt_e].set(ehas, **kw)

        # frontier: the new midpoints plus every vertex of a split tet
        chg = jnp.zeros(pcap, bool).at[tgt_v].set(True, **kw)
        chg = chg.at[
            jnp.where(has[:, None], mesh.tet, pcap).reshape(-1)
        ].set(True, mode="drop")

        out = mesh.replace(
            vert=vert, met=met, ls=ls, disp=disp, fields=fields,
            vtag=vtag, vref=vref, vmask=vmask,
            tet=tet, tref=tref, tmask=tmask,
            tria=tria, trref=trref, trtag=trtag, trmask=trmask,
            edge=edge_arr, edref=edref, edtag=edtag, edmask=edmask,
        )
        return out, nsplit, ncand, capped, chg

    def _skip(mesh):
        return (mesh, jnp.int32(0), jnp.int32(0), jnp.bool_(False),
                jnp.zeros(pcap, bool))

    if active is None:
        out, nsplit, ncand, capped, chg = _heavy(mesh)
    else:
        # converged regions: no long active edge anywhere means no
        # tria-edge sort, no vertex normals, no MIS, no apply scatters
        out, nsplit, ncand, capped, chg = jax.lax.cond(
            jnp.any(pre), _heavy, _skip, mesh
        )
    return out, SplitStats(nsplit=nsplit, ncand=ncand, capped=capped,
                           changed_v=chg)
