"""Batched topology swaps: 3-2 edge swaps and 2-3 face swaps.

Counterpart of Mmg's swap operators inside `MMG5_mmg3d1_delone` (reference
`src/libparmmg1.c:739`), quality-driven: a swap is applied only when the
worst quality of the new configuration beats the worst of the old by a
margin. Independent sets are selected with the affected tets as arena, and
a duplicate-tet post-check rejects the rare interacting pathologies.

The 3-2 swap extracts the ring of a 3-tet interior edge shell without a
walk: each shell tet contributes its two off-edge vertices, every ring
vertex appears exactly twice, so {min, sum/2-min-max, max} are the three
ring vertices — one scatter instead of Mmg's pointer chase.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import tags
from ..core.mesh import FACE_VERTS, Mesh
from . import common

GAIN = 1.02          # required relative quality improvement
QTHRESH = 0.5        # only try to improve tets worse than this


class SwapStats(NamedTuple):
    nswap32: jax.Array
    nswap23: jax.Array


def _oriented(t4: jax.Array, vert) -> jax.Array:
    """Fix orientation of candidate tets [N,4] by swapping first two
    vertices where the volume is negative."""
    vol = common.vol_of(vert, t4)
    sw = vol < 0
    v0 = jnp.where(sw, t4[:, 1], t4[:, 0])
    v1 = jnp.where(sw, t4[:, 0], t4[:, 1])
    return jnp.stack([v0, v1, t4[:, 2], t4[:, 3]], axis=1)


@partial(jax.jit, donate_argnums=0)
def swap_32(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
):
    """3-2 edge swap sweep. Mesh must be compacted; adjacency left stale."""
    ecap = edges.shape[0]
    tcap = mesh.tcap
    tet, tmask = mesh.tet, mesh.tmask
    a, b = edges[:, 0], edges[:, 1]

    live_e = (t2e >= 0) & tmask[:, None]
    safe_t2e = jnp.where(live_e, t2e, 0)
    flat_e = jnp.where(live_e, t2e, ecap).reshape(-1)

    surf = common.surface_edge_mask(mesh, edges, emask)

    # Ring vertices: for edge slot k of a tet, the two OFF-edge local
    # corners are known statically (complement of EDGE_VERTS[k]) — no
    # comparisons, and each per-edge reduction is one single-column
    # scatter (six passes replace the fifteen of the per-corner loop;
    # single-column because TPU lowers multi-column scatter-combines
    # ~8x slower than the same data split per column).
    OFF = jnp.asarray(
        [[2, 3], [1, 3], [1, 2], [0, 3], [0, 2], [0, 1]], jnp.int32
    )
    off1 = tet[:, OFF[:, 0]]                   # [TC,6]
    off2 = tet[:, OFF[:, 1]]
    q_old = common.quality_of(mesh.vert, mesh.met, tet)
    vol_all = common.vol_of(mesh.vert, tet)

    ring_sum = jnp.zeros(ecap, jnp.int32).at[flat_e].add(
        (off1 + off2).reshape(-1), mode="drop"
    )
    inc = jnp.zeros(ecap, jnp.int32).at[flat_e].add(
        jnp.ones(tcap * 6, jnp.int32), mode="drop"
    )
    u = jnp.full(ecap, 2**30, jnp.int32).at[flat_e].min(
        jnp.minimum(off1, off2).reshape(-1), mode="drop"
    )
    w = jnp.full(ecap, -1, jnp.int32).at[flat_e].max(
        jnp.maximum(off1, off2).reshape(-1), mode="drop"
    )
    shell_min_q = jnp.full(ecap, jnp.inf, mesh.vert.dtype).at[flat_e].min(
        jnp.broadcast_to(q_old[:, None], (tcap, 6)).reshape(-1), mode="drop"
    )
    v = ring_sum // 2 - u - w

    ok_ring = (u >= 0) & (v >= 0) & (w >= 0) & (u != v) & (v != w) & (u != w)
    cand = (
        emask
        & (inc == 3)
        & ~surf
        & ok_ring
        & (shell_min_q < QTHRESH)
        # conservative near frozen interfaces
        & ((mesh.vtag[a] & tags.PARBDY) == 0)
        & ((mesh.vtag[b] & tags.PARBDY) == 0)
    )

    # new configuration
    t1 = _oriented(jnp.stack([u, v, w, a], axis=1), mesh.vert)
    t2_ = _oriented(jnp.stack([u, w, v, b], axis=1), mesh.vert)
    q1 = common.quality_of(mesh.vert, mesh.met, t1)
    q2 = common.quality_of(mesh.vert, mesh.met, t2_)
    v1 = common.vol_of(mesh.vert, t1)
    v2 = common.vol_of(mesh.vert, t2_)
    # volume conservation rejects non-convex shells whose new tets are
    # individually positive but overlap outside the old shell (each tet
    # has exactly one slot matching this edge, so the scatter counts each
    # shell tet once)
    shell_vol = jnp.zeros(ecap, vol_all.dtype).at[flat_e].add(
        jnp.broadcast_to(vol_all[:, None], (tcap, 6)).reshape(-1), mode="drop"
    )
    new_min = jnp.minimum(q1, q2)
    pos_frac, cons_tol = common.vol_tols(mesh.dtype)
    vref = jnp.maximum(shell_vol, 1e-30)
    conserve = jnp.abs((v1 + v2) - shell_vol) <= cons_tol * vref
    gain_ok = (
        (new_min > GAIN * shell_min_q)
        & (v1 > pos_frac * vref)
        & (v2 > pos_frac * vref)
        & conserve
    )
    # the new tets must not already exist
    tet_keys = jnp.where(tmask[:, None], jnp.sort(tet, axis=1), -1)
    exists = common.sorted_membership(
        tet_keys,
        jnp.concatenate([jnp.sort(t1, axis=1), jnp.sort(t2_, axis=1)]),
        bound=mesh.pcap,
    )
    cand = cand & gain_ok & ~exists[:ecap] & ~exists[ecap:]

    # --- arena = the 3 shell tets -----------------------------------------
    def scatter_arena(vals):
        v6 = jnp.where(live_e, vals[safe_t2e], -jnp.inf)
        return jnp.max(v6, axis=1)

    def gather_arena(av):
        out = jnp.full(ecap, -jnp.inf, av.dtype)
        return out.at[flat_e].max(
            jnp.broadcast_to(av[:, None], (tcap, 6)).reshape(-1), mode="drop"
        )

    win = common.two_phase_winners(new_min - shell_min_q, cand,
                                   scatter_arena, gather_arena)

    # per-tet winner edge (<=1 by arena property)
    w6 = jnp.where(live_e, win[safe_t2e], False)
    has = jnp.any(w6, axis=1) & tmask
    k = jnp.argmax(w6, axis=1)
    e_t = jnp.where(has, safe_t2e[jnp.arange(tcap), k], -1)

    # rank shell tets of each winner by slot id
    slot = jnp.arange(tcap, dtype=jnp.int32)
    smin = jnp.full(ecap, tcap, jnp.int32).at[
        jnp.where(has, e_t, ecap)
    ].min(slot, mode="drop")
    smax = jnp.full(ecap, -1, jnp.int32).at[
        jnp.where(has, e_t, ecap)
    ].max(slot, mode="drop")
    e_ts = jnp.maximum(e_t, 0)
    rank0 = has & (slot == smin[e_ts])
    rank2 = has & (slot == smax[e_ts])
    rank1 = has & ~rank0 & ~rank2

    tet_new = jnp.where(rank0[:, None], t1[e_ts], tet)
    tet_new = jnp.where(rank1[:, None], t2_[e_ts], tet_new)
    tmask_new = tmask & ~rank2

    # duplicate post-check (cross-swap interactions)
    dup = common.duplicate_tets(tet_new, tmask_new, bound=mesh.pcap)
    bad_e = jnp.zeros(ecap, bool).at[
        jnp.where(dup & has, e_t, ecap)
    ].max(True, mode="drop")
    win = win & ~bad_e
    wk = win[e_ts] & has
    tet_out = jnp.where((rank0 & wk)[:, None], t1[e_ts], tet)
    tet_out = jnp.where((rank1 & wk)[:, None], t2_[e_ts], tet_out)
    tmask_out = tmask & ~(rank2 & wk)

    nswap = jnp.sum(win.astype(jnp.int32))
    out = mesh.replace(tet=tet_out, tmask=tmask_out)
    return out, SwapStats(nswap32=nswap, nswap23=jnp.int32(0))


@partial(jax.jit, donate_argnums=0)
def swap_23(mesh: Mesh, edges: jax.Array, emask: jax.Array):
    """2-3 face swap sweep. Requires FRESH adjacency; leaves it stale.

    The expensive work (three candidate-tet quality/volume evaluations,
    edge/tria membership sorts, winner selection, apply scatters) runs
    on a COMPACTED candidate set: the cheap prefilter (interior face,
    both tets live, pair quality below QTHRESH) admits few faces once
    sweeps settle, so the 4*TC face slots are sorted worst-pair-first
    and only the first tcap//2 evaluated — ~8x fewer rows through the
    heavy path. If more faces prequalify than the bucket holds (only in
    violent early sweeps), the overflow is the BEST-quality pairs,
    which are retried next sweep — the Jacobi schedule already assumes
    multiple passes."""
    tcap = mesh.tcap
    tet, tmask, adja = mesh.tet, mesh.tmask, mesh.adja
    ne0 = mesh.ntet

    # cheap prefilter over all 4*TC face slots
    nb_full = adja.reshape(-1)
    t_id_full = jnp.arange(tcap * 4, dtype=jnp.int32) // 4
    t2_full = jnp.clip(nb_full // 4, 0, tcap - 1)
    q_all = common.quality_of(mesh.vert, mesh.met, tet)
    pre = (
        (nb_full >= 0)
        & tmask[t2_full]
        & tmask[t_id_full]
        & (t_id_full < t2_full)          # each face once
        & (jnp.minimum(q_all[t_id_full], q_all[t2_full]) < QTHRESH)
    )

    # compact, worst pair first
    K = max(256, tcap // 2)
    sortkey = jnp.where(
        pre, jnp.minimum(q_all[t_id_full], q_all[t2_full]), jnp.inf
    )
    pick = jnp.argsort(sortkey)[:K].astype(jnp.int32)
    t_id = pick // 4
    f_id = pick % 4
    nb = nb_full[pick]
    t2c = jnp.clip(nb // 4, 0, tcap - 1)
    valid = pre[pick]

    fvidx = jnp.asarray(FACE_VERTS)[f_id]               # [K,3] local slots
    fv = jnp.take_along_axis(tet[t_id], fvidx, axis=1)  # [K,3] vertex ids
    d1 = tet[t_id, f_id]
    d2 = tet[t2c, nb % 4]

    old_min = jnp.minimum(q_all[t_id], q_all[t2c])

    # edge (d1,d2) must not already exist
    elo = jnp.minimum(d1, d2)
    ehi = jnp.maximum(d1, d2)
    ekeys = jnp.where(emask[:, None], edges, -1)
    equery = jnp.stack(
        [jnp.where(valid, elo, -1), jnp.where(valid, ehi, -1)], axis=1
    )
    edge_exists = common.sorted_membership(ekeys, equery, bound=mesh.pcap)

    # the face must not carry a stored tria: a 2-3 swap deletes the
    # face, which would orphan a material-interface or open-boundary
    # (-opnbdy) surface tria glued between same- or different-ref tets
    fsort = jnp.sort(fv, axis=1)
    trkeys = jnp.sort(
        jnp.where(mesh.trmask[:, None], mesh.tria, -1), axis=1
    )
    face_has_tria = common.sorted_membership(
        trkeys, jnp.where(valid[:, None], fsort, -1), bound=mesh.pcap
    )

    # three new tets around (d1,d2)
    x, y, z = fv[:, 0], fv[:, 1], fv[:, 2]
    cands = [
        jnp.stack([x, y, d1, d2], axis=1),
        jnp.stack([y, z, d1, d2], axis=1),
        jnp.stack([z, x, d1, d2], axis=1),
    ]
    cands = [_oriented(c, mesh.vert) for c in cands]
    qs = [common.quality_of(mesh.vert, mesh.met, c) for c in cands]
    vs = [common.vol_of(mesh.vert, c) for c in cands]
    new_min = jnp.minimum(jnp.minimum(qs[0], qs[1]), qs[2])
    vol_old2 = common.vol_of(mesh.vert, tet)
    pair_vol = vol_old2[t_id] + vol_old2[t2c]
    pos_frac, cons_tol = common.vol_tols(mesh.dtype)
    vref = jnp.maximum(pair_vol, 1e-30)
    conserve = jnp.abs((vs[0] + vs[1] + vs[2]) - pair_vol) <= cons_tol * vref
    vol_ok = (
        (vs[0] > pos_frac * vref)
        & (vs[1] > pos_frac * vref)
        & (vs[2] > pos_frac * vref)
        & conserve
    )

    cand = (
        valid
        & (old_min < QTHRESH)
        & ~edge_exists
        & ~face_has_tria
        & vol_ok
        & (new_min > GAIN * old_min)
    )

    # --- arena = the two tets ---------------------------------------------
    def scatter_arena(vals):
        out = jnp.full(tcap, -jnp.inf, vals.dtype)
        out = out.at[t_id].max(vals, mode="drop")
        out = out.at[t2c].max(vals, mode="drop")
        return out

    def gather_arena(av):
        return jnp.maximum(av[t_id], av[t2c])

    win = common.two_phase_winners(new_min - old_min, cand,
                                   scatter_arena, gather_arena)

    # capacity: one appended tet per winner
    wi = win.astype(jnp.int32)
    rank = jnp.cumsum(wi) - 1
    fits = ne0 + rank + 1 <= tcap
    win = win & fits
    wi = win.astype(jnp.int32)
    rank = jnp.cumsum(wi) - 1

    # tentative apply: children 0/1 overwrite t and t2, child 2 appended
    tet_out = tet
    tgt_a = common.unique_oob(win, t_id, tcap)
    tet_out = common.scatter_rows(tet_out, tgt_a, cands[0], unique=True)
    tgt_b = common.unique_oob(win, t2c, tcap)
    tet_out = common.scatter_rows(tet_out, tgt_b, cands[1], unique=True)
    tgt_c = common.unique_oob(win, ne0 + rank, tcap)
    tet_out = common.scatter_rows(tet_out, tgt_c, cands[2], unique=True)
    tmask_out = tmask.at[tgt_c].set(win, mode="drop", unique_indices=True)

    # duplicate post-check: reject interacting winners and revert
    dup = common.duplicate_tets(tet_out, tmask_out, bound=mesh.pcap)
    bad = (
        dup[jnp.clip(t_id, 0, tcap - 1)]
        | dup[t2c]
        | dup[jnp.clip(ne0 + rank, 0, tcap - 1)]
    ) & win
    win2 = win & ~bad

    def rebuild(_):
        tgt_a2 = common.unique_oob(win2, t_id, tcap)
        tgt_b2 = common.unique_oob(win2, t2c, tcap)
        tgt_c2 = common.unique_oob(win2, ne0 + rank, tcap)
        t_o = tet
        t_o = common.scatter_rows(t_o, tgt_a2, cands[0], unique=True)
        t_o = common.scatter_rows(t_o, tgt_b2, cands[1], unique=True)
        t_o = common.scatter_rows(t_o, tgt_c2, cands[2], unique=True)
        tm_o = tmask.at[tgt_c2].set(win2, mode="drop", unique_indices=True)
        return t_o, tm_o

    def keep(_):
        return tet_out, tmask_out

    if common._split_scatter_cols():
        # interacting winners are rare once sweeps settle: skip the
        # 12-column rebuild scatter round when there are none (each
        # random-index scatter is ~ms on TPU; the cond is free on the
        # common path)
        tet_out, tmask_out = jax.lax.cond(jnp.any(bad), rebuild, keep, None)
    else:
        tet_out, tmask_out = rebuild(None)
    tgt_c = common.unique_oob(win2, ne0 + rank, tcap)
    tref_out = mesh.tref.at[tgt_c].set(mesh.tref[t_id], mode="drop",
                                       unique_indices=True)

    out = mesh.replace(tet=tet_out, tref=tref_out, tmask=tmask_out)
    return out, SwapStats(nswap32=jnp.int32(0),
                          nswap23=jnp.sum(win2.astype(jnp.int32)))
