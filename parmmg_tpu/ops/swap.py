"""Batched topology swaps: 3-2 edge swaps and 2-3 face swaps.

Counterpart of Mmg's swap operators inside `MMG5_mmg3d1_delone` (reference
`src/libparmmg1.c:739`), quality-driven: a swap is applied only when the
worst quality of the new configuration beats the worst of the old by a
margin. Independent sets are selected with the affected tets as arena, and
a duplicate-tet post-check rejects the rare interacting pathologies.

The 3-2 swap extracts the ring of a 3-tet interior edge shell without a
walk: each shell tet contributes its two off-edge vertices, every ring
vertex appears exactly twice, so {min, sum/2-min-max, max} are the three
ring vertices — one scatter instead of Mmg's pointer chase.

Both swaps are frontier-aware (round 6): with an `active` vertex mask
(the one-ring closure of the previous sweep's changes) the candidate set
is restricted to edges/faces near the frontier, and the whole heavy
phase — candidate quality/volume, membership sorts, MIS, duplicate
check, apply — is skipped via `lax.cond` when no candidate survives the
cheap prefilter. `active=None` (the distributed/vmapped paths and all
legacy callers) reproduces the full-table sweep exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import kernels
from ..core import tags
from ..core.mesh import FACE_VERTS, Mesh
from . import common

GAIN = 1.02          # required relative quality improvement
QTHRESH = 0.5        # only try to improve tets worse than this


class SwapStats(NamedTuple):
    nswap32: jax.Array
    nswap23: jax.Array
    changed_v: jax.Array   # [PC] bool — vertices whose 1-ring changed


def _oriented(t4: jax.Array, vert) -> jax.Array:
    """Fix orientation of candidate tets [N,4] by swapping first two
    vertices where the volume is negative."""
    vol = common.vol_of(vert, t4)
    sw = vol < 0
    v0 = jnp.where(sw, t4[:, 1], t4[:, 0])
    v1 = jnp.where(sw, t4[:, 0], t4[:, 1])
    return jnp.stack([v0, v1, t4[:, 2], t4[:, 3]], axis=1)


def _mark_changed(pcap, win, cols):
    """[PC] bool from the vertex columns of winning candidates."""
    chg = jnp.zeros(pcap, bool)
    # static unroll over the 5 ring columns (a python tuple of fixed
    # length, not a traced entity count)
    for c in cols:  # parmmg-lint: disable=PML003
        chg = chg.at[jnp.where(win, c, pcap)].set(True, mode="drop")
    return chg


@partial(jax.jit, donate_argnums=0)
def swap_32(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
    active: jax.Array | None = None,
):
    """3-2 edge swap sweep. Mesh must be compacted; adjacency left stale.

    Like swap_23, the heavy work (candidate-tet quality/volume, tet
    membership sort, winner selection, apply) runs on a COMPACTED
    worst-shell-first candidate set: the full-table phase is only the
    per-edge shell reductions (single-column scatters over the 6*TC
    (tet, edge-slot) pairs), which also record the three shell tet ids
    {min, sum-min-max, max of slot} so the compacted rows address their
    arena directly instead of re-scanning the t2e table. Overflowing
    candidates (only in violent early sweeps) are the best-quality
    shells and are retried next sweep."""
    ecap = edges.shape[0]
    tcap = mesh.tcap
    pcap = mesh.pcap
    tet, tmask = mesh.tet, mesh.tmask

    live_e = (t2e >= 0) & tmask[:, None]
    flat_e = jnp.where(live_e, t2e, ecap).reshape(-1)

    surf = common.surface_edge_mask(mesh, edges, emask)

    # Ring vertices: for edge slot k of a tet, the two OFF-edge local
    # corners are known statically (complement of EDGE_VERTS[k]) — no
    # comparisons, and each per-edge reduction is one single-column
    # scatter (single-column because TPU lowers multi-column
    # scatter-combines ~8x slower than the same data split per column).
    OFF = jnp.asarray(
        [[2, 3], [1, 3], [1, 2], [0, 3], [0, 2], [0, 1]], jnp.int32
    )
    off1 = tet[:, OFF[:, 0]]                   # [TC,6]
    off2 = tet[:, OFF[:, 1]]
    # fused quality+volume over the full tet table (kernels dispatch)
    q_old, vol_all = kernels.quality_vol(mesh.vert, mesh.met, tet)

    ring_sum = jnp.zeros(ecap, jnp.int32).at[flat_e].add(
        (off1 + off2).reshape(-1), mode="drop"
    )
    inc = jnp.zeros(ecap, jnp.int32).at[flat_e].add(
        jnp.ones(tcap * 6, jnp.int32), mode="drop"
    )
    u = jnp.full(ecap, 2**30, jnp.int32).at[flat_e].min(
        jnp.minimum(off1, off2).reshape(-1), mode="drop"
    )
    w = jnp.full(ecap, -1, jnp.int32).at[flat_e].max(
        jnp.maximum(off1, off2).reshape(-1), mode="drop"
    )
    shell_min_q = jnp.full(ecap, jnp.inf, mesh.vert.dtype).at[flat_e].min(
        jnp.broadcast_to(q_old[:, None], (tcap, 6)).reshape(-1), mode="drop"
    )
    # shell tet ids by slot rank: {min, sum-min-max, max} of the (==3)
    # incident tet slots — same one-scatter trick as the ring vertices
    slot6 = jnp.broadcast_to(
        jnp.arange(tcap, dtype=jnp.int32)[:, None], (tcap, 6)
    ).reshape(-1)
    smin = jnp.full(ecap, tcap, jnp.int32).at[flat_e].min(slot6, mode="drop")
    smax = jnp.full(ecap, -1, jnp.int32).at[flat_e].max(slot6, mode="drop")
    ssum = jnp.zeros(ecap, jnp.int32).at[flat_e].add(slot6, mode="drop")
    v = ring_sum // 2 - u - w

    ok_ring = (u >= 0) & (v >= 0) & (w >= 0) & (u != v) & (v != w) & (u != w)
    a, b = edges[:, 0], edges[:, 1]
    cand_pre = (
        emask
        & (inc == 3)
        & ~surf
        & ok_ring
        & (shell_min_q < QTHRESH)
        # conservative near frozen interfaces
        & ((mesh.vtag[a] & tags.PARBDY) == 0)
        & ((mesh.vtag[b] & tags.PARBDY) == 0)
    )
    if active is not None:
        # frontier gate: a shell's verdict can only have changed when a
        # vertex of one of its (endpoint-incident) tets changed — the
        # closure marks both endpoints in that case
        cand_pre = cand_pre & (active[a] | active[b])

    K = min(ecap, max(256, ecap // 8))

    def _heavy(_):
        # compact, worst shell first
        pick, valid = common.topk_candidates(cand_pre, shell_min_q, K)
        ak, bk = a[pick], b[pick]
        uk, vk, wk_ = u[pick], v[pick], w[pick]
        s0 = jnp.clip(smin[pick], 0, tcap - 1)
        s2 = jnp.clip(smax[pick], 0, tcap - 1)
        s1 = jnp.clip(ssum[pick] - smin[pick] - smax[pick], 0, tcap - 1)
        shell_q = shell_min_q[pick]

        # new configuration (compacted rows only) — both candidate tets
        # stacked through ONE fused quality+volume pass
        t1 = _oriented(jnp.stack([uk, vk, wk_, ak], axis=1), mesh.vert)
        t2_ = _oriented(jnp.stack([uk, wk_, vk, bk], axis=1), mesh.vert)
        q12, v12 = kernels.quality_vol(
            mesh.vert, mesh.met, jnp.concatenate([t1, t2_], axis=0)
        )
        q1, q2 = q12[:K], q12[K:]
        v1, v2 = v12[:K], v12[K:]
        # volume conservation rejects non-convex shells whose new tets are
        # individually positive but overlap outside the old shell
        shell_vol = vol_all[s0] + vol_all[s1] + vol_all[s2]
        new_min = jnp.minimum(q1, q2)
        pos_frac, cons_tol = common.vol_tols(mesh.dtype)
        vref = jnp.maximum(shell_vol, 1e-30)
        conserve = jnp.abs((v1 + v2) - shell_vol) <= cons_tol * vref
        gain_ok = (
            (new_min > GAIN * shell_q)
            & (v1 > pos_frac * vref)
            & (v2 > pos_frac * vref)
            & conserve
        )
        # the new tets must not already exist
        tet_keys = jnp.where(tmask[:, None], jnp.sort(tet, axis=1), -1)
        exists = common.sorted_membership(
            tet_keys,
            jnp.concatenate([
                jnp.sort(jnp.where(valid[:, None], t1, -1), axis=1),
                jnp.sort(jnp.where(valid[:, None], t2_, -1), axis=1),
            ]),
            bound=mesh.pcap,
        )
        cand = valid & gain_ok & ~exists[:K] & ~exists[K:]

        # --- arena = the 3 shell tets (addressed directly) ----------------
        def scatter_arena(vals):
            out = jnp.full(tcap, -jnp.inf, vals.dtype)
            out = out.at[s0].max(vals, mode="drop")
            out = out.at[s1].max(vals, mode="drop")
            out = out.at[s2].max(vals, mode="drop")
            return out

        def gather_arena(av):
            return jnp.maximum(jnp.maximum(av[s0], av[s1]), av[s2])

        win = common.rank_winners(new_min - shell_q, cand,
                                  scatter_arena, gather_arena)

        # apply: t1 overwrites the min-slot shell tet, t2 the middle one,
        # the max-slot one dies. Arena exclusivity makes every target tet
        # belong to exactly one winner, so the unique-indices promise holds.
        tgt0 = common.unique_oob(win, s0, tcap)
        tgt1 = common.unique_oob(win, s1, tcap)
        tet_new = common.scatter_rows(tet, tgt0, t1, unique=True)
        tet_new = common.scatter_rows(tet_new, tgt1, t2_, unique=True)
        tgt2 = common.unique_oob(win, s2, tcap)
        tmask_new = tmask.at[tgt2].set(False, mode="drop",
                                       unique_indices=True)

        # duplicate post-check (cross-swap interactions). The killed tet
        # (s2) cannot flag: its tmask was cleared before duplicate_tets
        # ran, so only the two overwritten slots carry signal.
        dup = common.duplicate_tets(tet_new, tmask_new, bound=mesh.pcap)
        bad = (dup[s0] | dup[s1]) & win
        win2 = win & ~bad

        def rebuild(_):
            g0 = common.unique_oob(win2, s0, tcap)
            g1 = common.unique_oob(win2, s1, tcap)
            g2 = common.unique_oob(win2, s2, tcap)
            t_o = common.scatter_rows(tet, g0, t1, unique=True)
            t_o = common.scatter_rows(t_o, g1, t2_, unique=True)
            tm_o = tmask.at[g2].set(False, mode="drop", unique_indices=True)
            return t_o, tm_o

        def keep(_):
            return tet_new, tmask_new

        if common._split_scatter_cols():
            tet_out, tmask_out = jax.lax.cond(jnp.any(bad), rebuild, keep,
                                              None)
        else:
            tet_out, tmask_out = rebuild(None)

        chg = _mark_changed(pcap, win2, (uk, vk, wk_, ak, bk))
        return (tet_out, tmask_out,
                jnp.sum(win2.astype(jnp.int32)).astype(jnp.int32), chg)

    if active is None:
        tet_out, tmask_out, nswap, chg = _heavy(None)
    else:
        # frontier mode: the compacted phase (quality eval, membership
        # sort, MIS, duplicate sort, apply scatters) only runs when the
        # cheap prefilter admits someone — converged sweeps skip it all
        tet_out, tmask_out, nswap, chg = jax.lax.cond(
            jnp.any(cand_pre), _heavy,
            lambda _: (tet, tmask, jnp.int32(0), jnp.zeros(pcap, bool)),
            None,
        )

    out = mesh.replace(tet=tet_out, tmask=tmask_out)
    return out, SwapStats(nswap32=nswap, nswap23=jnp.int32(0),
                          changed_v=chg)


@partial(jax.jit, donate_argnums=0)
def swap_23(
    mesh: Mesh,
    edges: jax.Array,
    emask: jax.Array,
    active: jax.Array | None = None,
):
    """2-3 face swap sweep. Requires FRESH adjacency; leaves it stale.

    The expensive work (three candidate-tet quality/volume evaluations,
    edge/tria membership sorts, winner selection, apply scatters) runs
    on a COMPACTED candidate set: the cheap prefilter (interior face,
    both tets live, pair quality below QTHRESH) admits few faces once
    sweeps settle, so the 4*TC face slots are sorted worst-pair-first
    and only the first tcap//2 evaluated — ~8x fewer rows through the
    heavy path. If more faces prequalify than the bucket holds (only in
    violent early sweeps), the overflow is the BEST-quality pairs,
    which are retried next sweep — the Jacobi schedule already assumes
    multiple passes."""
    tcap = mesh.tcap
    pcap = mesh.pcap
    tet, tmask, adja = mesh.tet, mesh.tmask, mesh.adja
    ne0 = mesh.ntet

    # cheap prefilter over all 4*TC face slots
    nb_full = adja.reshape(-1)
    t_id_full = jnp.arange(tcap * 4, dtype=jnp.int32) // 4
    t2_full = jnp.clip(nb_full // 4, 0, tcap - 1)
    q_all, _ = kernels.quality_vol(mesh.vert, mesh.met, tet)
    pre = (
        (nb_full >= 0)
        & tmask[t2_full]
        & tmask[t_id_full]
        & (t_id_full < t2_full)          # each face once
        & (jnp.minimum(q_all[t_id_full], q_all[t2_full]) < QTHRESH)
    )
    if active is not None:
        # frontier gate at tet granularity: a face pair's verdict can
        # only change when a vertex of either tet's 1-ring changed
        tet_act = jnp.any(active[tet], axis=1)
        pre = pre & (tet_act[t_id_full] | tet_act[t2_full])

    K = max(256, tcap // 2)
    sortkey = jnp.where(
        pre, jnp.minimum(q_all[t_id_full], q_all[t2_full]), jnp.inf
    )

    def _heavy(_):
        # compact, worst pair first
        pick, valid = common.topk_candidates(pre, sortkey, K)
        t_id = pick // 4
        f_id = pick % 4
        nb = nb_full[pick]
        t2c = jnp.clip(nb // 4, 0, tcap - 1)

        fvidx = jnp.asarray(FACE_VERTS)[f_id]               # [K,3] slots
        fv = jnp.take_along_axis(tet[t_id], fvidx, axis=1)  # [K,3] ids
        d1 = tet[t_id, f_id]
        d2 = tet[t2c, nb % 4]

        old_min = jnp.minimum(q_all[t_id], q_all[t2c])

        # edge (d1,d2) must not already exist
        elo = jnp.minimum(d1, d2)
        ehi = jnp.maximum(d1, d2)
        ekeys = jnp.where(emask[:, None], edges, -1)
        equery = jnp.stack(
            [jnp.where(valid, elo, -1), jnp.where(valid, ehi, -1)], axis=1
        )
        edge_exists = common.sorted_membership(ekeys, equery,
                                               bound=mesh.pcap)

        # the face must not carry a stored tria: a 2-3 swap deletes the
        # face, which would orphan a material-interface or open-boundary
        # (-opnbdy) surface tria glued between same- or different-ref tets
        fsort = jnp.sort(fv, axis=1)
        trkeys = jnp.sort(
            jnp.where(mesh.trmask[:, None], mesh.tria, -1), axis=1
        )
        face_has_tria = common.sorted_membership(
            trkeys, jnp.where(valid[:, None], fsort, -1), bound=mesh.pcap
        )

        # three new tets around (d1,d2)
        x, y, z = fv[:, 0], fv[:, 1], fv[:, 2]
        cands = [
            jnp.stack([x, y, d1, d2], axis=1),
            jnp.stack([y, z, d1, d2], axis=1),
            jnp.stack([z, x, d1, d2], axis=1),
        ]
        cands = [_oriented(c, mesh.vert) for c in cands]
        # all three children of every candidate face through ONE fused
        # quality+volume pass over the stacked stream
        qcat, vcat = kernels.quality_vol(
            mesh.vert, mesh.met, jnp.concatenate(cands, axis=0)
        )
        qs = [qcat[:K], qcat[K:2 * K], qcat[2 * K:]]
        vs = [vcat[:K], vcat[K:2 * K], vcat[2 * K:]]
        new_min = jnp.minimum(jnp.minimum(qs[0], qs[1]), qs[2])
        vol_old2 = common.vol_of(mesh.vert, tet)
        pair_vol = vol_old2[t_id] + vol_old2[t2c]
        pos_frac, cons_tol = common.vol_tols(mesh.dtype)
        vref = jnp.maximum(pair_vol, 1e-30)
        conserve = (
            jnp.abs((vs[0] + vs[1] + vs[2]) - pair_vol) <= cons_tol * vref
        )
        vol_ok = (
            (vs[0] > pos_frac * vref)
            & (vs[1] > pos_frac * vref)
            & (vs[2] > pos_frac * vref)
            & conserve
        )

        cand = (
            valid
            & (old_min < QTHRESH)
            & ~edge_exists
            & ~face_has_tria
            & vol_ok
            & (new_min > GAIN * old_min)
        )

        # --- arena = the two tets -----------------------------------------
        def scatter_arena(vals):
            out = jnp.full(tcap, -jnp.inf, vals.dtype)
            out = out.at[t_id].max(vals, mode="drop")
            out = out.at[t2c].max(vals, mode="drop")
            return out

        def gather_arena(av):
            return jnp.maximum(av[t_id], av[t2c])

        win = common.rank_winners(new_min - old_min, cand,
                                  scatter_arena, gather_arena)

        # capacity: one appended tet per winner
        wi = win.astype(jnp.int32)
        rank = jnp.cumsum(wi) - 1
        fits = ne0 + rank + 1 <= tcap
        win = win & fits
        wi = win.astype(jnp.int32)
        rank = jnp.cumsum(wi) - 1

        # tentative apply: children 0/1 overwrite t and t2, child 2
        # appended
        tet_out = tet
        tgt_a = common.unique_oob(win, t_id, tcap)
        tet_out = common.scatter_rows(tet_out, tgt_a, cands[0], unique=True)
        tgt_b = common.unique_oob(win, t2c, tcap)
        tet_out = common.scatter_rows(tet_out, tgt_b, cands[1], unique=True)
        tgt_c = common.unique_oob(win, ne0 + rank, tcap)
        tet_out = common.scatter_rows(tet_out, tgt_c, cands[2], unique=True)
        tmask_out = tmask.at[tgt_c].set(win, mode="drop",
                                        unique_indices=True)

        # duplicate post-check: reject interacting winners and revert
        dup = common.duplicate_tets(tet_out, tmask_out, bound=mesh.pcap)
        bad = (
            dup[jnp.clip(t_id, 0, tcap - 1)]
            | dup[t2c]
            | dup[jnp.clip(ne0 + rank, 0, tcap - 1)]
        ) & win
        win2 = win & ~bad

        def rebuild(_):
            tgt_a2 = common.unique_oob(win2, t_id, tcap)
            tgt_b2 = common.unique_oob(win2, t2c, tcap)
            tgt_c2 = common.unique_oob(win2, ne0 + rank, tcap)
            t_o = tet
            t_o = common.scatter_rows(t_o, tgt_a2, cands[0], unique=True)
            t_o = common.scatter_rows(t_o, tgt_b2, cands[1], unique=True)
            t_o = common.scatter_rows(t_o, tgt_c2, cands[2], unique=True)
            tm_o = tmask.at[tgt_c2].set(win2, mode="drop",
                                        unique_indices=True)
            return t_o, tm_o

        def keep(_):
            return tet_out, tmask_out

        if common._split_scatter_cols():
            # interacting winners are rare once sweeps settle: skip the
            # 12-column rebuild scatter round when there are none (each
            # random-index scatter is ~ms on TPU; the cond is free on the
            # common path)
            tet_out, tmask_out = jax.lax.cond(jnp.any(bad), rebuild, keep,
                                              None)
        else:
            tet_out, tmask_out = rebuild(None)
        tgt_c = common.unique_oob(win2, ne0 + rank, tcap)
        tref_out = mesh.tref.at[tgt_c].set(mesh.tref[t_id], mode="drop",
                                           unique_indices=True)

        chg = _mark_changed(pcap, win2, (x, y, z, d1, d2))
        return (tet_out, tref_out, tmask_out,
                jnp.sum(win2.astype(jnp.int32)).astype(jnp.int32), chg)

    if active is None:
        tet_out, tref_out, tmask_out, nswap, chg = _heavy(None)
    else:
        tet_out, tref_out, tmask_out, nswap, chg = jax.lax.cond(
            jnp.any(pre), _heavy,
            lambda _: (tet, mesh.tref, tmask, jnp.int32(0),
                       jnp.zeros(pcap, bool)),
            None,
        )

    out = mesh.replace(tet=tet_out, tref=tref_out, tmask=tmask_out)
    return out, SwapStats(nswap32=jnp.int32(0), nswap23=nswap,
                          changed_v=chg)
