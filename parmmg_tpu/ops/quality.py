"""Tetrahedron quality measures and distributed-ready histograms.

Counterpart of the reference's `src/quality_pmmg.c` (`PMMG_qualhisto:156`,
`PMMG_prilen:591`, `PMMG_tetraQual:720`) re-expressed as batched device
reductions: per-tet quality is one fused vmap-style computation, and the
distributed histogram is a `psum`/`pmin`-style reduction instead of custom
MPI_Ops (`PMMG_min_iel_compute:82`).

Quality measure: q(K) = alpha * V_M(K) / (sum of squared metric edge
lengths)^(3/2), normalized so the regular tetrahedron scores 1. In a metric
M, V_M = V * sqrt(det M) and edge lengths are metric lengths. Degenerate or
inverted elements score <= 0.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import metric as metric_mod
from ..core.mesh import Mesh

# normalization: regular tet edge a has V = a^3 sqrt(2)/12, sum l^2 = 6 a^2
ALPHA = 6.0**1.5 * 12.0 / math.sqrt(2.0)

# an element under this quality counts as "bad" in reports (same role as
# Mmg's epsilon quality threshold in histograms)
BADQUAL = 0.012


def tet_quality(mesh: Mesh) -> jax.Array:
    """[TC] quality in (0,1] for valid tets (0 where masked/degenerate).

    Routed through the `quality_vol` kernel dispatch (Pallas on TPU,
    the fused lax reference elsewhere) — the same expression DAG this
    function historically inlined, so values are unchanged."""
    from .. import kernels  # deferred: the kernel modules import this module

    q, _ = kernels.quality_vol(mesh.vert, mesh.met, mesh.tet)
    return jnp.where(mesh.tmask, q, 0.0)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QualityHisto:
    """Result of a (possibly cross-shard-reduced) quality histogram."""

    ne: jax.Array        # element count
    qmin: jax.Array
    qmax: jax.Array
    qavg: jax.Array
    worst_elt: jax.Array  # slot id of the worst element (local to its shard)
    nbad: jax.Array       # count with q < BADQUAL
    ninverted: jax.Array  # count with q <= 0
    counts: jax.Array     # [nbins] histogram over (0,1], bin k = [k/n,(k+1)/n)
    worst_shard: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.int32(-1)
    )  # shard owning worst_elt after reduce (-1 = unreduced/single shard)


def quality_histogram(mesh: Mesh, nbins: int = 5) -> QualityHisto:
    """Quality histogram with the reference's binning: 5 uniform bins of
    width 0.2 (`PMMG_QUAL_HISSIZE=5`, reference `src/parmmg.h:93`, filled
    by Mmg's computeInqua `(int)(5*qual)` rule) plus BEST/AVRG/WRST and
    the argmin-with-location the custom MPI_Op reduces
    (`PMMG_min_iel_compute`, `src/quality_pmmg.c:82`)."""
    q = tet_quality(mesh)
    m = mesh.tmask
    ne = jnp.sum(m.astype(jnp.int32))
    qv = jnp.where(m, q, jnp.inf)
    qmin = jnp.min(qv)
    worst = jnp.argmin(qv)
    qmax = jnp.max(jnp.where(m, q, -jnp.inf))
    qavg = jnp.sum(jnp.where(m, q, 0.0)) / jnp.maximum(ne, 1)
    nbad = jnp.sum((m & (q < BADQUAL)).astype(jnp.int32))
    ninv = jnp.sum((m & (q <= 0.0)).astype(jnp.int32))
    bins = jnp.clip((q * nbins).astype(jnp.int32), 0, nbins - 1)
    counts = jnp.zeros(nbins, jnp.int32).at[bins].add(
        m.astype(jnp.int32), mode="drop"
    )
    return QualityHisto(ne, qmin, qmax, qavg, worst, nbad, ninv, counts)


def reduce_histograms(h: QualityHisto, axis_name: str) -> QualityHisto:
    """Cross-shard reduction of per-shard histograms (inside shard_map),
    replacing the reference's custom MPI_Op argmin-with-location reduce
    (`PMMG_min_iel_compute`, reference `src/quality_pmmg.c:82`): after the
    reduce, (worst_shard, worst_elt) identify the globally worst element
    by shard id and that shard's local slot id."""
    ne = jax.lax.psum(h.ne, axis_name)
    qmin = jax.lax.pmin(h.qmin, axis_name)
    qmax = jax.lax.pmax(h.qmax, axis_name)
    qavg = jax.lax.psum(h.qavg * h.ne.astype(h.qavg.dtype), axis_name) / jnp.maximum(
        ne, 1
    ).astype(h.qavg.dtype)
    # argmin-with-location, exact: only shards holding the global min vote
    # for lowest shard id, then that shard's element id wins — no packed
    # integer encoding (which would overflow at TPU-scale element counts)
    shard = jax.lax.axis_index(axis_name)
    imax = jnp.iinfo(jnp.int32).max
    has = h.qmin <= qmin
    worst_shard = jax.lax.pmin(jnp.where(has, shard, imax), axis_name)
    worst = jax.lax.pmin(
        jnp.where(shard == worst_shard, h.worst_elt, imax), axis_name
    )
    nbad = jax.lax.psum(h.nbad, axis_name)
    ninv = jax.lax.psum(h.ninverted, axis_name)
    counts = jax.lax.psum(h.counts, axis_name)
    return QualityHisto(
        ne, qmin, qmax, qavg, worst, nbad, ninv, counts, worst_shard
    )


def merge_stacked_histograms(h: QualityHisto) -> QualityHisto:
    """Reduce a vmapped (leading-axis-stacked) QualityHisto to one global
    histogram — the out-of-shard_map companion of `reduce_histograms`
    (same `PMMG_min_iel_compute` argmin-with-location semantics)."""
    ne = jnp.sum(h.ne)
    qmin = jnp.min(h.qmin)
    worst_shard = jnp.argmin(h.qmin).astype(jnp.int32)
    return QualityHisto(
        ne=ne,
        qmin=qmin,
        qmax=jnp.max(h.qmax),
        qavg=jnp.sum(h.qavg * h.ne.astype(h.qavg.dtype))
        / jnp.maximum(ne, 1).astype(h.qavg.dtype),
        worst_elt=h.worst_elt[worst_shard],
        nbad=jnp.sum(h.nbad),
        ninverted=jnp.sum(h.ninverted),
        counts=jnp.sum(h.counts, axis=0),
        worst_shard=worst_shard,
    )


def _finite_or_dash(v, fmt: str = "8.6f") -> str:
    """Render a summary scalar, or dashes when the reduction ran over an
    empty set (min over nothing is +/-inf, averages can be nan) — an
    empty shard or a fully-drained frontier must still format."""
    v = float(v)
    return format(v, fmt) if math.isfinite(v) else "   --   "


def format_histogram(h: QualityHisto, label: str = "MESH QUALITY") -> str:
    """Human-readable report in the spirit of the reference's stdout
    histogram (verbosity-gated in `PMMG_qualhisto`). Safe on empty
    histograms (ne=0): summary scalars render as dashes, percentages
    as 0."""
    counts = [int(c) for c in jax.device_get(h.counts)]
    n = len(counts)
    lines = [
        f"  -- {label}  {int(h.ne)} elements",
        f"     BEST {_finite_or_dash(h.qmax)}  AVRG {_finite_or_dash(h.qavg)} "
        f" WRST {_finite_or_dash(h.qmin)} (elt {int(h.worst_elt)}"
        + (f" on shard {int(h.worst_shard)})" if int(h.worst_shard) >= 0 else ")"),
    ]
    ne = max(int(h.ne), 1)
    for k in reversed(range(n)):
        lo, hi = k / n, (k + 1) / n
        lines.append(
            f"     {lo:4.2f} < Q < {hi:4.2f}  {counts[k]:10d}  {100.0 * counts[k] / ne:6.2f} %"
        )
    if int(h.nbad):
        lines.append(f"     {int(h.nbad)} elements under quality {BADQUAL}")
    if int(h.ninverted):
        lines.append(f"     {int(h.ninverted)} INVERTED elements")
    return "\n".join(lines)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LengthStats:
    """Edge-length histogram (reference `PMMG_prilen:591` /
    `PMMG_compute_lenStats:106`)."""

    nedge: jax.Array
    lmin: jax.Array
    lmax: jax.Array
    lavg: jax.Array
    n_small: jax.Array  # below collapse threshold
    n_large: jax.Array  # above split threshold
    n_unit: jax.Array   # within [LSHRT, LLONG]
    counts: jax.Array   # [nbins] histogram over log2-length classes


# bin edges for the length histogram — the reference's exact bounds
# (`bd[9]` at `src/quality_pmmg.c:387`: 0, .3, .6, 1/sqrt2, .9, 1.3,
# sqrt2, 2, 5), so "identical histogram" comparisons are well-defined.
# Kept a HOST numpy constant: a module-level jnp.array would capture a
# tracer if this module is first imported while a jit trace is active
# (lazy import from inside a traced caller), leaking it to every later
# use — the UnexpectedTracerError class of failure
_LEN_EDGES = np.array(
    [0.0, 0.3, 0.6, float(metric_mod.LSHRT), 0.9, 1.3,
     float(metric_mod.LLONG), 2.0, 5.0]
)


def length_stats(mesh: Mesh, edges, emask) -> LengthStats:
    p0, p1 = mesh.vert[edges[:, 0]], mesh.vert[edges[:, 1]]
    m0, m1 = mesh.met[edges[:, 0]], mesh.met[edges[:, 1]]
    l = metric_mod.edge_length(p0, p1, m0, m1)
    l = jnp.where(emask, l, jnp.nan)
    ne = jnp.sum(emask.astype(jnp.int32))
    lmin = jnp.nanmin(jnp.where(emask, l, jnp.inf))
    lmax = jnp.nanmax(jnp.where(emask, l, -jnp.inf))
    lavg = jnp.nansum(jnp.where(emask, l, 0.0)) / jnp.maximum(ne, 1)
    small = jnp.sum((emask & (l < metric_mod.LSHRT)).astype(jnp.int32))
    large = jnp.sum((emask & (l > metric_mod.LLONG)).astype(jnp.int32))
    unit = ne - small - large
    k = jnp.searchsorted(_LEN_EDGES, jnp.where(emask, l, 0.0))
    counts = jnp.zeros(_LEN_EDGES.shape[0] + 1, jnp.int32).at[k].add(
        emask.astype(jnp.int32), mode="drop"
    )
    return LengthStats(ne, lmin, lmax, lavg, small, large, unit, counts)


def mesh_length_stats(mesh: Mesh, ecap: int | None = None) -> LengthStats:
    """Whole-mesh edge-length histogram: derive the unique-edge tables
    from the tet connectivity (no prebuilt adjacency needed) and reduce.
    Pure jnp — vmappable over stacked shards and usable inside
    shard_map bodies (pass a static `ecap` there)."""
    from ..core import adjacency  # deferred: adjacency pulls ops.common

    if ecap is None:
        ecap = int(mesh.tcap * 1.7) + 64
    edges, emask, _, _ = adjacency.unique_edges(mesh, ecap)
    return length_stats(mesh, edges, emask)


def in_band_fraction(ls: LengthStats) -> float:
    """Unit-mesh goal as one scalar: the fraction of edges whose metric
    length lies in [LSHRT, LLONG] (0.0 for an empty edge set). This is
    the `len/in_band` value that rides history records, the bench
    envelope and the PERF_DB gate."""
    ne = int(ls.nedge)
    return float(int(ls.n_unit)) / ne if ne > 0 else 0.0


def reduce_length_stats(ls: LengthStats, axis_name: str) -> LengthStats:
    """Cross-shard reduction of per-shard LengthStats inside shard_map —
    the `PMMG_prilen` world totals (reference MPI_Reduce over
    lenStats, `src/quality_pmmg.c:591`). Counts/averages sum exactly;
    interface edges appear once per owning shard, so world counts weigh
    shared edges per replica (documented, exact for fractions up to the
    thin interface band)."""
    ne = jax.lax.psum(ls.nedge, axis_name)
    lavg = jax.lax.psum(
        ls.lavg * ls.nedge.astype(ls.lavg.dtype), axis_name
    ) / jnp.maximum(ne, 1).astype(ls.lavg.dtype)
    return LengthStats(
        nedge=ne,
        lmin=jax.lax.pmin(ls.lmin, axis_name),
        lmax=jax.lax.pmax(ls.lmax, axis_name),
        lavg=lavg,
        n_small=jax.lax.psum(ls.n_small, axis_name),
        n_large=jax.lax.psum(ls.n_large, axis_name),
        n_unit=jax.lax.psum(ls.n_unit, axis_name),
        counts=jax.lax.psum(ls.counts, axis_name),
    )


def merge_stacked_length_stats(ls: LengthStats) -> LengthStats:
    """Reduce a vmapped (leading-axis-stacked) LengthStats to one global
    record — the out-of-shard_map companion of `reduce_length_stats`,
    mirroring `merge_stacked_histograms`."""
    ne = jnp.sum(ls.nedge)
    return LengthStats(
        nedge=ne,
        lmin=jnp.min(ls.lmin),
        lmax=jnp.max(ls.lmax),
        lavg=jnp.sum(ls.lavg * ls.nedge.astype(ls.lavg.dtype))
        / jnp.maximum(ne, 1).astype(ls.lavg.dtype),
        n_small=jnp.sum(ls.n_small),
        n_large=jnp.sum(ls.n_large),
        n_unit=jnp.sum(ls.n_unit),
        counts=jnp.sum(ls.counts, axis=0),
    )


def length_stats_doc(ls: LengthStats) -> dict:
    """JSON-ready dict of a LengthStats (host transfer happens here) —
    the payload the drivers attach to `health:length_histogram` tracer
    events so `obs_report --health` can re-render post-mortem. Non-
    finite summary scalars (empty edge set) become None — the trace
    JSONL stays strict-JSON parseable."""
    fin = lambda v: float(v) if math.isfinite(float(v)) else None
    return dict(
        nedge=int(ls.nedge),
        lmin=fin(ls.lmin), lmax=fin(ls.lmax), lavg=fin(ls.lavg),
        n_small=int(ls.n_small), n_large=int(ls.n_large),
        n_unit=int(ls.n_unit),
        in_band=round(in_band_fraction(ls), 6),
        counts=[int(c) for c in jax.device_get(ls.counts)],
        edges=[float(e) for e in jax.device_get(_LEN_EDGES)],
    )


def format_length_stats(ls: LengthStats) -> str:
    """Edge-length report with the reference's bins (`PMMG_prilen`
    output shape, `src/quality_pmmg.c:591-719`). Safe on empty edge
    sets (nedge=0): min/max/avg render as dashes instead of inf/nan."""
    edges = [float(e) for e in jax.device_get(_LEN_EDGES)]
    counts = [int(c) for c in jax.device_get(ls.counts)]
    ne = max(int(ls.nedge), 1)
    lines = [
        f"  -- RESULTING EDGE LENGTHS  {int(ls.nedge)} edges",
        f"     AVERAGE LENGTH {_finite_or_dash(ls.lavg, '12.4f')}",
        f"     SMALLEST EDGE  {_finite_or_dash(ls.lmin, '12.4f')}",
        f"     LARGEST  EDGE  {_finite_or_dash(ls.lmax, '12.4f')}",
        f"     unit [1/sqrt2, sqrt2]: {int(ls.n_unit)} "
        f"({100.0 * int(ls.n_unit) / ne:.2f} %)",
    ]
    # counts[0] is below edges[0]=0 (empty); interior bins then overflow
    for k in range(len(edges) - 1):
        c = counts[k + 1]
        lines.append(
            f"     {edges[k]:6.2f} < L < {edges[k + 1]:6.2f}  "
            f"{c:10d}  {100.0 * c / ne:6.2f} %"
        )
    c_over = counts[len(edges)]
    lines.append(
        f"     {edges[-1]:6.2f} < L          {c_over:10d}  "
        f"{100.0 * c_over / ne:6.2f} %"
    )
    return "\n".join(lines)
