"""Mesh analysis: boundary detection and (growing) surface classification.

Covers the role of Mmg's `MMG3D_analys` as used by the reference
(`src/libparmmg.c:180`, `src/analys_pmmg.c` for the parallel version):
deriving which entities are boundary, ridges, corners, and required from
the raw connectivity. Round 1 implements boundary-vertex marking and
missing-boundary-triangle synthesis; dihedral-angle ridge/corner detection
lands with the surface milestone.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..core import tags
from ..core.mesh import FACE_VERTS, Mesh
from ..core.adjacency import build_adjacency


@partial(jax.jit, donate_argnums=0)
def mark_boundary(mesh: Mesh) -> Mesh:
    """OR the BDY bit into vtag for every vertex lying on the boundary
    surface: vertices of valid trias, plus vertices of tet faces with no
    neighbor (requires fresh adjacency; pass through build_adjacency
    first when trias may be incomplete)."""
    pcap = mesh.pcap
    bdy = jnp.zeros(pcap, bool)
    idx = jnp.where(mesh.trmask[:, None], mesh.tria, pcap)
    bdy = bdy.at[idx.reshape(-1)].set(True, mode="drop")
    # faces with no neighbor
    open_face = (mesh.adja < 0) & mesh.tmask[:, None]  # [TC,4]
    fverts = mesh.tet[:, jnp.asarray(FACE_VERTS)]      # [TC,4,3]
    idx2 = jnp.where(open_face[..., None], fverts, pcap)
    bdy = bdy.at[idx2.reshape(-1)].set(True, mode="drop")
    vtag = jnp.where(bdy & mesh.vmask, mesh.vtag | tags.BDY, mesh.vtag)
    return mesh.replace(vtag=vtag)


def analyze(mesh: Mesh) -> Mesh:
    """Entry analysis pass: adjacency + boundary marking. Grows toward the
    full `MMG3D_analys` equivalent (ridges, normals, singularities)."""
    mesh = build_adjacency(mesh)
    return mark_boundary(mesh)
