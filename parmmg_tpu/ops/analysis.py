"""Surface analysis: boundary, ridges, corners, normals, non-manifold.

Batched TPU-native counterpart of Mmg's `MMG3D_analys` as used by the
reference (`src/libparmmg.c:180`) and of the parallel analysis subsystem
(`src/analys_pmmg.c:2576`): from raw connectivity, derive which entities
are boundary, sharp (dihedral-angle ridges, `PMMG_setdhd` semantics at
`src/analys_pmmg.c:2001`), singular (corners, `PMMG_singul` at
`src/analys_pmmg.c:1679`), reference-change or non-manifold, and compute
outward-oriented surface normals.

Re-design notes (vs the serial ball traversals in `src/boulep_pmmg.c`):
 - the surface is analyzed with one sort of the 3*FC tria-edge keys:
   group runs give manifold pairing (count==2), open borders (count==1),
   and non-manifold fans (count>2) in a single pass — no hash, no balls.
 - normals are oriented by matching each tria to its owner tet face
   (sort-merge again) and pointing away from the opposite vertex, so
   arbitrary input tria winding never flips a dihedral test.
 - detected feature edges are appended into the explicit `mesh.edge`
   array (deduplicated), which the remesh kernels already consult for
   tag inheritance — detection is additive over file-prescribed features.
Tag semantics follow the MG_* discipline (`src/tag_pmmg.c`).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tags
from ..core.mesh import FACE_VERTS, Mesh
from ..core.adjacency import build_adjacency
# promoted to utils.retry (PR 3) so every host-side jitted entry point
# shares the clear-caches-and-retry discipline; alias kept for the
# in-module call sites
from ..utils.retry import jit_retry as _jit_retry
from . import common

# default feature-detection dihedral angle, degrees (the reference's
# angle-detection default forwarded to Mmg, `-ar` flag)
ANG_DEFAULT = 45.0

_FEATURE = tags.RIDGE | tags.REF | tags.NOM | tags.REQUIRED


@partial(jax.jit, donate_argnums=0)
def mark_boundary(mesh: Mesh) -> Mesh:
    """OR the BDY bit into vtag for every vertex lying on the boundary
    surface: vertices of valid trias, plus vertices of tet faces with no
    neighbor (requires fresh adjacency; pass through build_adjacency
    first when trias may be incomplete)."""
    pcap = mesh.pcap
    bdy = jnp.zeros(pcap, bool)
    idx = jnp.where(mesh.trmask[:, None], mesh.tria, pcap)
    bdy = bdy.at[idx.reshape(-1)].set(True, mode="drop")
    # faces with no neighbor
    open_face = (mesh.adja < 0) & mesh.tmask[:, None]  # [TC,4]
    fverts = mesh.tet[:, jnp.asarray(FACE_VERTS)]      # [TC,4,3]
    idx2 = jnp.where(open_face[..., None], fverts, pcap)
    bdy = bdy.at[idx2.reshape(-1)].set(True, mode="drop")
    vtag = jnp.where(bdy & mesh.vmask, mesh.vtag | tags.BDY, mesh.vtag)
    return mesh.replace(vtag=vtag)


# ---------------------------------------------------------------------------
# boundary-triangle synthesis
# ---------------------------------------------------------------------------

def _sorted3(v):
    lo = jnp.min(v, axis=-1)
    hi = jnp.max(v, axis=-1)
    return jnp.stack([lo, jnp.sum(v, axis=-1) - lo - hi, hi], axis=-1)


# parmmg-lint: disable=PML005 -- pure query; the analysis pipeline keeps the mesh
@jax.jit
def _missing_face_info(mesh: Mesh):
    """Open tet faces (adja<0) with no matching tria: returns
    (need [TC,4] bool, count scalar). Requires fresh adjacency."""
    open_face = (mesh.adja < 0) & mesh.tmask[:, None]
    fverts = mesh.tet[:, jnp.asarray(FACE_VERTS)]           # [TC,4,3]
    fkeys = _sorted3(fverts).reshape(-1, 3)                 # [4TC,3]
    fkeys = jnp.where(open_face.reshape(-1)[:, None], fkeys, -1)
    trkeys = _sorted3(
        jnp.where(mesh.trmask[:, None], mesh.tria, -1)
    )
    have = common.sorted_membership(trkeys, fkeys,
                                    bound=mesh.pcap).reshape(-1, 4)
    need = open_face & ~have
    return need, jnp.sum(need.astype(jnp.int32))




def synthesize_boundary_trias(mesh: Mesh) -> Mesh:
    """Append a boundary tria for every open tet face that has none —
    the role of Mmg's boundary-triangle completion inside `MMG3D_analys`
    (chkBdryTria). FACE_VERTS ordering makes the appended trias outward
    oriented. Host-growth of fcap when needed."""
    need, cnt = _jit_retry(_missing_face_info, mesh)
    n_need = int(cnt)
    if n_need == 0:
        return mesh
    nf0 = int(mesh.ntria)
    if nf0 + n_need > mesh.fcap:
        mesh = mesh.with_capacity(fcap=int((nf0 + n_need) * 1.3) + 8)
        need, _ = _jit_retry(_missing_face_info, mesh)
    return _append_trias(mesh, need)


@partial(jax.jit, donate_argnums=0)
def _append_trias(mesh: Mesh, need: jax.Array) -> Mesh:
    nf0 = mesh.ntria
    fcap = mesh.fcap
    fverts = mesh.tet[:, jnp.asarray(FACE_VERTS)].reshape(-1, 3)
    flat = need.reshape(-1)
    rank = jnp.cumsum(flat.astype(jnp.int32)) - 1
    tgt = jnp.where(flat, nf0 + rank, fcap).astype(jnp.int32)
    # inherit the owner tet's ref so material surfaces keep their label
    trefs = jnp.repeat(mesh.tref, 4)
    tria = mesh.tria.at[tgt].set(fverts, mode="drop")
    trref = mesh.trref.at[tgt].set(trefs, mode="drop")
    trtag = mesh.trtag.at[tgt].set(tags.BDY, mode="drop")
    trmask = mesh.trmask.at[tgt].set(flat, mode="drop")
    return mesh.replace(tria=tria, trref=trref, trtag=trtag, trmask=trmask)


def _tria_owner_match(mesh: Mesh, smask: jax.Array):
    """Owner tet faces of each tria by sorted-triple sort-merge:
    (fid1, fid2, cnt) with fids into the flat 4*TC face slots — shared
    by `tria_normals` and `mark_opnbdy` (one definition of the most
    expensive matching step of surface analysis)."""
    fverts = mesh.tet[:, jnp.asarray(FACE_VERTS)]
    fkeys = _sorted3(fverts).reshape(-1, 3)
    fkeys = jnp.where(jnp.repeat(mesh.tmask, 4)[:, None], fkeys, -1)
    trkeys = _sorted3(jnp.where(smask[:, None], mesh.tria, -1))
    return common.match_rows2(fkeys, trkeys, bound=mesh.pcap)


@partial(jax.jit, donate_argnums=0)
def mark_opnbdy(mesh: Mesh) -> Mesh:
    """Tag internal same-ref trias as open boundaries (-opnbdy mode).

    An input tria whose two owner tets share a ref is an open internal
    surface (baffle/crack sheet); in opnbdy mode it is preserved and
    adapted as real surface (reference `PMMG_IPARAM_opnbdy`,
    `src/libparmmg.h:64`; the tag discipline special case
    `src/tag_pmmg.c:267`). Tags the tria OPNBDY|BDY and its vertices
    BDY; `tria_normals` then includes it in the surface (rim edges fall
    out of `_detect_feature_edges`' open-border rule). Synthetic
    NOSURF interface trias are never open boundaries."""
    smask = surf_tria_mask(mesh)
    fid1, fid2, cnt = _tria_owner_match(mesh, smask)
    ref1 = mesh.tref[jnp.maximum(fid1, 0) // 4]
    ref2 = mesh.tref[jnp.maximum(fid2, 0) // 4]
    opn = smask & (cnt >= 2) & (ref1 == ref2)
    trtag = jnp.where(opn, mesh.trtag | tags.OPNBDY | tags.BDY, mesh.trtag)
    vb = jnp.zeros(mesh.pcap, bool)
    idx = jnp.where(opn[:, None], mesh.tria, mesh.pcap)
    vb = vb.at[idx.reshape(-1)].set(True, mode="drop")
    vtag = jnp.where(vb & mesh.vmask, mesh.vtag | tags.BDY, mesh.vtag)
    return mesh.replace(trtag=trtag, vtag=vtag)


# ---------------------------------------------------------------------------
# oriented normals
# ---------------------------------------------------------------------------

def surf_tria_mask(mesh: Mesh) -> jax.Array:
    """Valid trias that are true surface (excludes NOSURF pure-interface
    parallel trias, which carry no geometry — reference `MG_NOSURF`
    discipline, `src/tag_pmmg.c`)."""
    return mesh.trmask & ((mesh.trtag & tags.NOSURF) == 0)


# parmmg-lint: disable=PML005 -- pure query (normals); callers reuse the mesh
@jax.jit
def tria_normals(mesh: Mesh):
    """Oriented unit normals and areas of boundary trias.

    Returns (normal [FC,3], area [FC], ok [FC] bool). Orientation is
    derived from the owner tets, so input winding does not matter:
     - boundary trias (one owner): outward — away from the opposite
       vertex.
     - internal material-interface trias (two owners with different
       refs): from the lower-ref region into the higher-ref one, which
       is consistent across the whole interface (an arbitrary per-tria
       owner choice would make neighbors antiparallel and turn a flat
       interface into wall-to-wall fake ridges).
     - internal trias with equal refs on both sides carry no surface
       geometry: ok=False, excluded from feature detection and vertex
       normals.
    Trias with no owner tet keep their stored winding.
    """
    smask = surf_tria_mask(mesh)
    p0 = mesh.vert[mesh.tria[:, 0]]
    p1 = mesh.vert[mesh.tria[:, 1]]
    p2 = mesh.vert[mesh.tria[:, 2]]
    raw = jnp.cross(p1 - p0, p2 - p0)               # |raw| = 2*area
    # owner tet faces: match sorted triples (internal faces match twice)
    fid1, fid2, cnt = _tria_owner_match(mesh, smask)  # into 4*TC
    t1 = jnp.maximum(fid1, 0) // 4
    t2 = jnp.maximum(fid2, 0) // 4
    ref1 = mesh.tref[t1]
    ref2 = mesh.tref[t2]
    internal = cnt >= 2
    same_ref = internal & (ref1 == ref2)
    # reference side: the single owner for boundary trias, the lower-ref
    # owner for material interfaces (normal points AWAY from it)
    use2 = internal & (ref2 < ref1)
    t_ref = jnp.where(use2, t2, t1)
    f_ref = jnp.where(use2, jnp.maximum(fid2, 0), jnp.maximum(fid1, 0)) % 4
    opp = mesh.vert[mesh.tet[t_ref, f_ref]]         # opposite vertex
    # open-boundary trias (-opnbdy, tagged by mark_opnbdy) ARE surface
    # despite equal refs; a sheet has no owner-derived orientation, so
    # they keep the stored (file) winding — consistent along the sheet
    opn = (mesh.trtag & tags.OPNBDY) != 0
    flip = (
        (cnt > 0) & ~opn
        & (jnp.einsum("fi,fi->f", raw, p0 - opp) < 0)
    )
    raw = jnp.where(flip[:, None], -raw, raw)
    nrm = jnp.linalg.norm(raw, axis=1)
    ok = smask & (nrm > 0) & (~same_ref | opn)
    unit = raw / jnp.maximum(nrm, 1e-30)[:, None]
    return unit, 0.5 * nrm, ok


# parmmg-lint: disable=PML005 -- pure query (normals); split/smooth reuse the mesh in the same sweep
@jax.jit
def vertex_normals(mesh: Mesh, need: jax.Array | None = None) -> jax.Array:
    """[PC,3] area-weighted unit vertex normals over surface trias
    (zero where the vertex touches no surface tria). Across a ridge the
    blend is geometrically meaningless — ridge vertices are handled by
    tangent-line logic in the smoothing kernel, not by this normal.

    `need` (frontier mode, round 6): [PC] bool mask of the vertices
    whose normals the caller will actually read. Only trias touching a
    needed vertex contribute — every tria of a needed vertex contains
    that vertex, so needed rows come out EXACT while cold rows (whose
    scatter traffic the active-set sweep is shedding) may be zero.
    `need=None` computes every row (legacy full pass)."""
    unit, area, ok = tria_normals(mesh)
    pcap = mesh.pcap
    if need is not None:
        ok = ok & jnp.any(need[mesh.tria], axis=1)
    w = jnp.where(ok, area, 0.0)
    contrib = unit * w[:, None]
    acc = jnp.zeros((pcap, 3), mesh.vert.dtype)
    idx = jnp.where(ok[:, None], mesh.tria, pcap)
    for k in range(3):
        acc = common.scatter_rows(acc, idx[:, k], contrib, op="add")
    n = jnp.linalg.norm(acc, axis=1)
    return acc / jnp.maximum(n, 1e-30)[:, None]


# ---------------------------------------------------------------------------
# feature detection (setdhd + singul semantics)
# ---------------------------------------------------------------------------

# parmmg-lint: disable=PML005 -- pure query (feature-edge detection); analyze() keeps the mesh
@partial(jax.jit, static_argnames=("cos_ang",))
def _detect_feature_edges(mesh: Mesh, cos_ang: float):
    """Classify every unique surface edge by one sort of tria-edge keys.

    Returns, over the 3*FC flat tria-edge slots:
      first  [3FC] bool — slot is the group representative
      pairs  [3FC,2] int32 — (lo,hi) vertex pair of the slot
      etag   [3FC] int32 — feature tag for the group (0 = plain surface)
    Tag rules (reference `PMMG_setdhd`, `src/analys_pmmg.c:2001` /
    Mmg `MMG5_setdhd`): count==2 and normals' dot < cos_ang → RIDGE;
    refs differ → REF; count==1 (open border) → RIDGE|REF;
    count>2 (non-manifold fan) → NOM|REQUIRED.
    """
    fcap = mesh.fcap
    unit, _, ok = tria_normals(mesh)

    t = mesh.tria
    pairs = jnp.stack([t[:, [0, 1]], t[:, [1, 2]], t[:, [0, 2]]], axis=1)
    lo = jnp.minimum(pairs[..., 0], pairs[..., 1]).reshape(-1)
    hi = jnp.maximum(pairs[..., 0], pairs[..., 1]).reshape(-1)
    n3 = 3 * fcap
    dead = ~jnp.repeat(ok, 3)
    order, newgrp, live_sorted, slo, shi = common.sorted_pair_groups(
        lo, hi, dead, mesh.pcap
    )
    cnt = common.seg_broadcast(
        live_sorted.astype(jnp.int32), newgrp, jnp.add, 0
    )
    # (the group-tag OR below shares this group structure but depends on
    # etag_sorted, which itself depends on cnt — two separate scans)
    # manifold partner: runs of exactly 2
    eq_next = jnp.concatenate([newgrp[1:] == False, jnp.zeros(1, bool)])  # noqa: E712
    eq_prev = jnp.concatenate([jnp.zeros(1, bool), eq_next[:-1]])
    not_mid = ~(eq_next & eq_prev)
    pair2 = eq_next & not_mid & jnp.roll(not_mid, -1) & (cnt == 2)
    partner_sorted = jnp.where(
        pair2, jnp.roll(order, -1),
        jnp.where(jnp.roll(pair2, 1) & (cnt == 2), jnp.roll(order, 1), -1),
    )

    tri_of = order // 3
    tri_partner = jnp.maximum(partner_sorted, 0) // 3
    dot = jnp.einsum("si,si->s", unit[tri_of], unit[tri_partner])
    # Open-boundary sheets keep their stored winding, which a file may
    # not orient consistently. Winding consistency across the shared
    # edge is detectable: coherently-oriented neighbors traverse it in
    # OPPOSITE directions. Only an INCONSISTENT OPNBDY pair gets the
    # sign-flipped (negated-dot) test — a mixed-winding flat sheet must
    # not read as wall-to-wall fake ridges, while sharp folds of a
    # consistently-wound sheet keep the full signed dihedral test.
    opn_t = (mesh.trtag & tags.OPNBDY) != 0
    both_opn = opn_t[tri_of] & opn_t[tri_partner]
    # cyclic traversal direction per slot: pairs are stored (01, 12, 02)
    # — the 02 slot is the REVERSE of the tria's cyclic third edge (20),
    # so its stored-order flag must be flipped before comparing
    fwd = (pairs[..., 0] < pairs[..., 1])              # [FC,3]
    is02 = jnp.zeros((1, 3), bool).at[0, 2].set(True)
    cyc = (fwd ^ is02).reshape(-1)
    same_dir = cyc[order] == cyc[jnp.maximum(partner_sorted, 0)]
    dot = jnp.where(both_opn & same_dir, -dot, dot)
    refdiff = mesh.trref[tri_of] != mesh.trref[tri_partner]
    has_partner = partner_sorted >= 0
    # NB: synthetic interface trias (PARBDY|NOSURF) never reach these
    # dihedral/ref tests — surf_tria_mask excludes them from tria_normals'
    # `ok`, so their edge slots are dead here; the checkpoint round trip
    # (io.medit face-comm persistence) guarantees reloaded meshes keep
    # that NOSURF tagging

    etag_sorted = jnp.zeros(n3, jnp.int32)
    etag_sorted = jnp.where(
        live_sorted & has_partner & (dot < cos_ang),
        etag_sorted | tags.RIDGE, etag_sorted,
    )
    etag_sorted = jnp.where(
        live_sorted & has_partner & refdiff,
        etag_sorted | tags.REF, etag_sorted,
    )
    # open borders / fans touching the parallel interface are artifacts
    # of per-shard analysis (the surface continues on the neighbor
    # shard); the reference resolves them with communication rounds
    # (`PMMG_setdhd` exchanges), we suppress them — those entities are
    # PARBDY-frozen anyway
    par_v = (mesh.vtag & tags.PARBDY) != 0
    slo_c = jnp.clip(slo, 0, mesh.pcap - 1)
    shi_c = jnp.clip(shi, 0, mesh.pcap - 1)
    par_edge = par_v[slo_c] & par_v[shi_c]
    etag_sorted = jnp.where(
        live_sorted & (cnt == 1) & ~par_edge,
        etag_sorted | tags.RIDGE | tags.REF, etag_sorted,
    )
    etag_sorted = jnp.where(
        live_sorted & (cnt > 2) & ~par_edge,
        etag_sorted | tags.NOM | tags.REQUIRED, etag_sorted,
    )
    # group tag = OR over members (a fan member's partner-less slots share
    # the group verdict through the segment reduction) — ONE segmented
    # bitwise-OR scan instead of a scatter+gather round per tag bit
    etag_g = common.seg_broadcast(
        etag_sorted, newgrp, jnp.bitwise_or, 0
    )

    first = jnp.zeros(n3, bool).at[order].set(newgrp & live_sorted,
                                              unique_indices=True)
    etag = jnp.zeros(n3, jnp.int32).at[order].set(etag_g, unique_indices=True)
    prs = jnp.stack(
        [jnp.zeros(n3, jnp.int32).at[order].set(slo, unique_indices=True),
         jnp.zeros(n3, jnp.int32).at[order].set(shi, unique_indices=True)],
        axis=1,
    )
    return first, prs, etag


# parmmg-lint: disable=PML005 -- pure query (dedup info); caller merges into the SAME mesh
@jax.jit
def _merge_info(mesh: Mesh, first, prs, etag):
    """Which detected feature edges are new vs already stored; returns
    (new_sel [3FC] bool, n_new, match [3FC] idx into mesh.edge or -1)."""
    elo = jnp.minimum(mesh.edge[:, 0], mesh.edge[:, 1])
    ehi = jnp.maximum(mesh.edge[:, 0], mesh.edge[:, 1])
    ekeys = jnp.stack(
        [jnp.where(mesh.edmask, elo, -1), jnp.where(mesh.edmask, ehi, -1)],
        axis=1,
    )
    feat = first & (etag != 0)
    q = jnp.where(feat[:, None], prs, -1)
    match = common.match_rows(ekeys, q, bound=mesh.pcap)
    new_sel = feat & (match < 0)
    return new_sel, jnp.sum(new_sel.astype(jnp.int32)), match


@partial(jax.jit, donate_argnums=0)
def _apply_features(mesh: Mesh, first, prs, etag, new_sel, match) -> Mesh:
    """OR detected tags into matched stored edges, append the new ones,
    and propagate feature bits to endpoint vertices."""
    ecap = mesh.ecap
    ned0 = mesh.nedge
    # OR into existing edges (per-bit max scatters = bitwise OR)
    midx = jnp.where((match >= 0) & first, match, ecap)
    add = jnp.zeros(ecap, jnp.int32)
    for bit in (tags.RIDGE, tags.REF, tags.NOM, tags.REQUIRED):
        hasbit = jnp.zeros(ecap, bool).at[midx].max(
            (etag & bit) != 0, mode="drop"
        )
        add = add | jnp.where(hasbit, bit, 0)
    edtag = mesh.edtag | add
    # append new ones
    rank = jnp.cumsum(new_sel.astype(jnp.int32)) - 1
    tgt = jnp.where(new_sel, ned0 + rank, ecap).astype(jnp.int32)
    edge = mesh.edge.at[tgt].set(prs, mode="drop")
    edtag = edtag.at[tgt].set(etag, mode="drop")
    edref = mesh.edref.at[tgt].set(0, mode="drop")
    edmask = mesh.edmask.at[tgt].set(new_sel, mode="drop")
    mesh = mesh.replace(edge=edge, edtag=edtag, edref=edref, edmask=edmask)
    return _tag_feature_vertices(mesh)


# parmmg-lint: disable=PML005 -- cold analysis path (once per adapt); host call sites reuse the mesh
@jax.jit
def _tag_feature_vertices(mesh: Mesh) -> Mesh:
    """Endpoints of feature edges inherit the feature bits (the xpoint
    tag propagation of the reference's `PMMG_updateTag`,
    `src/tag_pmmg.c:267`)."""
    pcap = mesh.pcap
    vadd = jnp.zeros(pcap, jnp.int32)
    live = mesh.edmask
    # per-bit max scatters (max is not bitwise OR across differing tags)
    for bit in (tags.RIDGE, tags.REF, tags.NOM, tags.REQUIRED):
        hasbit = jnp.zeros(pcap, bool)
        src = jnp.where(live, (mesh.edtag & bit) != 0, False)
        for k in range(2):
            idx = jnp.where(live, mesh.edge[:, k], pcap)
            hasbit = hasbit.at[idx].max(src, mode="drop")
        vadd = vadd | jnp.where(hasbit, bit, 0)
    # feature vertices are also boundary
    vadd = jnp.where(vadd != 0, vadd | tags.BDY, vadd)
    return mesh.replace(vtag=mesh.vtag | jnp.where(mesh.vmask, vadd, 0))


@partial(jax.jit, static_argnames=("cos_ang",), donate_argnums=0)
def classify_corners(mesh: Mesh, cos_ang: float) -> Mesh:
    """Corner/singularity classification (`PMMG_singul` semantics,
    `src/analys_pmmg.c:1679` / Mmg `MMG5_singul`): a vertex with exactly
    two incident feature edges lies on a feature line (and is CORNER only
    when the line bends sharply: dot of the two outgoing unit directions
    > -cos_ang); any other nonzero feature-edge count is singular. The
    two-edge bend test uses |u1+u2|^2 = 2+2·dot — one scatter-add, no
    per-vertex gather of the pair."""
    pcap = mesh.pcap
    live = mesh.edmask & ((mesh.edtag & (tags.RIDGE | tags.REF | tags.NOM)) != 0)
    a, b = mesh.edge[:, 0], mesh.edge[:, 1]
    deg = jnp.zeros(pcap, jnp.int32)
    deg = deg.at[jnp.where(live, a, pcap)].add(1, mode="drop")
    deg = deg.at[jnp.where(live, b, pcap)].add(1, mode="drop")
    d = mesh.vert[b] - mesh.vert[a]
    u = d / jnp.maximum(jnp.linalg.norm(d, axis=1), 1e-30)[:, None]
    w = live.astype(mesh.vert.dtype)[:, None]
    acc = jnp.zeros((pcap, 3), mesh.vert.dtype)
    acc = acc.at[jnp.where(live, a, pcap)].add(u * w, mode="drop")
    acc = acc.at[jnp.where(live, b, pcap)].add(-u * w, mode="drop")
    bend2 = jnp.sum(acc * acc, axis=1)  # |u1+u2|^2 when deg==2
    sharp = bend2 > (2.0 - 2.0 * cos_ang)
    corner = ((deg == 1) | (deg >= 3) | ((deg == 2) & sharp)) & mesh.vmask
    vtag = jnp.where(corner, mesh.vtag | tags.CORNER, mesh.vtag)
    return mesh.replace(vtag=vtag)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def detect_features(mesh: Mesh, ang: float = ANG_DEFAULT) -> Mesh:
    """Dihedral-angle ridge + ref-change + non-manifold detection, feature
    edge storage, vertex tagging, and corner classification. Additive over
    input-prescribed features (file-loaded edges/tags are kept)."""
    cos_ang = math.cos(math.radians(ang))
    first, prs, etag = _detect_feature_edges(mesh, cos_ang=cos_ang)
    new_sel, n_new, match = _merge_info(mesh, first, prs, etag)
    n_new = int(n_new)
    ned0 = int(mesh.nedge)
    if ned0 + n_new > mesh.ecap:
        mesh = mesh.with_capacity(ecap=int((ned0 + n_new) * 1.3) + 8)
    mesh = _apply_features(mesh, first, prs, etag, new_sel, match)
    return classify_corners(mesh, cos_ang=cos_ang)


def cross_shard_features(
    shards: list, ang: float = ANG_DEFAULT
) -> list:
    """Feature detection for surface edges split by a shard interface —
    the `PMMG_setdhd` role (reference `src/analys_pmmg.c:2001`): each
    side of an interface-crossing surface edge sees only ONE of the two
    adjacent boundary trias, so per-shard dihedral detection must skip it
    (the suppression in `_detect_feature_edges`); here the missing half
    is exchanged across shards, keyed by global vertex ids.

    The reference runs owner-computed triangle-normal exchanges over its
    edge communicators (`MPI_ANALYS_TAG` rounds); on one host the
    exchange is a dict join — on multi-host it becomes one bounded
    `all_gather` of (gid-pair, normal, ref) rows per shard. Singularity
    re-classification then reruns per shard (`PMMG_singul` role).

    Takes/returns a list of per-shard Meshes (already through
    `analyze()`, so vglob + PARBDY tags are set and normals orientable).
    """
    import math as _math

    cos_ang = _math.cos(_math.radians(ang))
    # collect (gid-pair, normal, ref, shard, local slots) rows from every
    # shard — one vectorized block per (shard, tria-edge) combination, no
    # per-entity work
    blk = []
    for s, m in enumerate(shards):
        unit, _, ok = tria_normals(m)
        unit = np.asarray(unit)
        ok = np.asarray(ok)
        tria = np.asarray(m.tria)
        trref = np.asarray(m.trref)
        vt = np.asarray(m.vtag)
        vg = np.asarray(m.vglob)
        par = ((vt & tags.PARBDY) != 0) & (vg >= 0)
        for e0, e1 in ((0, 1), (1, 2), (0, 2)):
            a, b = tria[:, e0], tria[:, e1]
            idx = np.nonzero(ok & par[a] & par[b])[0]
            if not len(idx):
                continue
            la, lb = a[idx].astype(np.int64), b[idx].astype(np.int64)
            ga, gb = vg[la].astype(np.int64), vg[lb].astype(np.int64)
            swap = ga > gb
            blk.append((
                np.where(swap, gb, ga), np.where(swap, ga, gb),
                unit[idx], trref[idx].astype(np.int64),
                np.full(len(idx), s, np.int64),
                np.where(swap, lb, la), np.where(swap, la, lb),
            ))
    if not blk:
        return cross_shard_singul(shards, cos_ang)
    glo, ghi, nrm, ref, shd, llo, lhi = (
        np.concatenate([b[k] for b in blk]) for k in range(7)
    )

    # group rows by gid-pair key (sort-merge join, the device-friendly
    # shape: one all_gather of these arrays + the same sort on multi-host)
    order = np.lexsort((ghi, glo))
    glo, ghi, nrm, ref, shd, llo, lhi = (
        x[order] for x in (glo, ghi, nrm, ref, shd, llo, lhi)
    )
    newgrp = np.concatenate(
        [[True], (glo[1:] != glo[:-1]) | (ghi[1:] != ghi[:-1])]
    )
    starts = np.nonzero(newgrp)[0]
    gid = np.cumsum(newgrp) - 1
    counts = np.diff(np.append(starts, len(glo)))
    # cross-shard groups only (same-shard pairs were already handled by
    # the local detection)
    cross = (
        np.maximum.reduceat(shd, starts) > np.minimum.reduceat(shd, starts)
    )
    etag_g = np.zeros(len(starts), np.int64)
    two = counts == 2
    i0 = starts[two]
    if len(i0):
        dot = np.einsum("ij,ij->i", nrm[i0], nrm[i0 + 1])
        etag_g[two] = (
            np.where(dot < cos_ang, tags.RIDGE, 0)
            | np.where(ref[i0] != ref[i0 + 1], tags.REF, 0)
        )
    etag_g[counts > 2] = tags.NOM | tags.REQUIRED  # cross-shard NOM fan
    etag_g[~cross] = 0

    row_etag = etag_g[gid]
    emit = row_etag != 0
    out = []
    for s, m in enumerate(shards):
        sel = emit & (shd == s)
        if sel.any():
            pairs = np.stack([llo[sel], lhi[sel]], axis=1)
            m = _merge_host_edges(m, pairs, row_etag[sel])
            m = classify_corners(m, cos_ang=cos_ang)
        out.append(m)
    return cross_shard_singul(out, cos_ang)


def cross_shard_singul(shards: list, cos_ang: float) -> list:
    """Singularity classification of parallel points with *global* feature
    counts — the `PMMG_singul` role (reference `src/analys_pmmg.c:1679`).

    Per-shard `classify_corners` counts only the locally-visible feature
    edges: a feature line crossing the interface at a vertex looks like a
    line END (deg 1) on both sides and gets spuriously CORNER-frozen.
    Here the feature-edge degree and direction sum at every interface
    vertex are reduced over all shards — PARBDY-PARBDY edges (replicated
    per side) deduplicated by global-id key — and the corner rule is
    re-evaluated on the global counts. Input-REQUIRED corners are never
    unset. (Cross-shard vertex-NORMAL agreement, the `hashNorver` loop at
    `src/analys_pmmg.c:199-1386`, is obviated: PARBDY endpoints force
    linear midpoints in split and are IMMOVABLE in smoothing — the same
    no-surface-op discipline the reference enforces via MG_NOSURF.)"""
    feature = tags.RIDGE | tags.REF | tags.NOM
    gids_all = []
    dirs_all = []
    seen_pp = np.empty(0, np.int64)
    for m in shards:
        ed = np.asarray(m.edge)
        live = np.asarray(m.edmask) & (
            (np.asarray(m.edtag) & feature) != 0
        )
        if not live.any():
            continue
        e = ed[live]
        vt = np.asarray(m.vtag)
        vg = np.asarray(m.vglob)
        v = np.asarray(m.vert)
        a, b = e[:, 0], e[:, 1]
        d = v[b] - v[a]
        u = d / np.maximum(np.linalg.norm(d, axis=1), 1e-30)[:, None]
        par_a = ((vt[a] & tags.PARBDY) != 0) & (vg[a] >= 0)
        par_b = ((vt[b] & tags.PARBDY) != 0) & (vg[b] >= 0)
        both = par_a & par_b
        # replicated interface edges: count each global key once
        # (vectorized dedup: unique within the shard, isin against the
        # accumulated key array)
        if both.any():
            ga, gb = vg[a[both]], vg[b[both]]
            glo, ghi = np.minimum(ga, gb), np.maximum(ga, gb)
            keys = glo.astype(np.int64) * (2**31) + ghi
            _, first = np.unique(keys, return_index=True)
            fresh = np.zeros(len(keys), bool)
            fresh[first] = True
            fresh &= ~np.isin(keys, seen_pp)
            seen_pp = np.concatenate([seen_pp, keys[fresh]])
            ub = u[both][fresh]
            gids_all.append(vg[a[both]][fresh])
            dirs_all.append(ub)
            gids_all.append(vg[b[both]][fresh])
            dirs_all.append(-ub)
        only_a = par_a & ~both
        only_b = par_b & ~both
        if only_a.any():
            gids_all.append(vg[a[only_a]])
            dirs_all.append(u[only_a])
        if only_b.any():
            gids_all.append(vg[b[only_b]])
            dirs_all.append(-u[only_b])

    if not gids_all:
        return shards
    gids = np.concatenate(gids_all)
    dirs = np.concatenate(dirs_all)
    ug, inv = np.unique(gids, return_inverse=True)
    deg = np.bincount(inv, minlength=len(ug))
    acc = np.zeros((len(ug), 3))
    np.add.at(acc, inv, dirs)
    bend2 = np.sum(acc * acc, axis=1)
    sharp = bend2 > (2.0 - 2.0 * cos_ang)
    corner_g = (deg == 1) | (deg >= 3) | ((deg == 2) & sharp)
    gmax = int(ug.max()) + 1
    is_corner = np.zeros(gmax, bool)
    is_corner[ug] = corner_g
    has_feat = np.zeros(gmax, bool)
    has_feat[ug] = True

    out = []
    for m in shards:
        vt = np.asarray(m.vtag).copy()
        vg = np.asarray(m.vglob)
        sel = (
            ((vt & tags.PARBDY) != 0)
            & (vg >= 0)
            & (vg < gmax)
            & np.asarray(m.vmask)
        )
        gsel = np.clip(vg, 0, gmax - 1)
        want = sel & is_corner[gsel]
        # clear locally-guessed corners on interface feature vertices
        # (never user-required ones), then set the agreed ones
        clear = (
            sel & has_feat[gsel] & ~want & ((vt & tags.REQUIRED) == 0)
        )
        vt[clear] &= ~tags.CORNER
        vt[want] |= tags.CORNER | tags.BDY
        out.append(m.replace(vtag=jnp.asarray(vt)))
    return out


def _merge_host_edges(mesh: Mesh, pairs: np.ndarray, etags: np.ndarray) -> Mesh:
    """OR tags into matching stored feature edges / append the missing
    ones, then re-propagate vertex tags (host-side variant of
    `_apply_features` for the cross-shard pass). Sort-merge join on
    canonical (lo*pcap+hi) keys — vectorized, no per-edge Python."""
    edge = np.asarray(mesh.edge)
    edmask = np.asarray(mesh.edmask).copy()
    edtag = np.asarray(mesh.edtag).copy()
    edref = np.asarray(mesh.edref)

    P = np.int64(mesh.pcap)
    lo = np.minimum(pairs[:, 0], pairs[:, 1]).astype(np.int64)
    hi = np.maximum(pairs[:, 0], pairs[:, 1]).astype(np.int64)
    key = lo * P + hi
    # dedup incoming pairs, OR-combining their tags
    order = np.argsort(key, kind="stable")
    ks, ts = key[order], np.asarray(etags, np.int64)[order]
    first = np.concatenate([[True], ks[1:] != ks[:-1]])
    starts = np.nonzero(first)[0]
    ukey = ks[starts]
    utag = np.bitwise_or.reduceat(ts, starts)

    live = np.nonzero(edmask)[0]
    ekey = (
        np.minimum(edge[live, 0], edge[live, 1]).astype(np.int64) * P
        + np.maximum(edge[live, 0], edge[live, 1])
    )
    eorder = np.argsort(ekey)
    if len(ekey):
        pos = np.clip(np.searchsorted(ekey[eorder], ukey), 0, len(ekey) - 1)
        hit = ekey[eorder[pos]] == ukey
        edtag[live[eorder[pos[hit]]]] |= utag[hit]
    else:
        hit = np.zeros(len(ukey), bool)

    n_add = int((~hit).sum())
    ned = int(edmask.sum())
    if ned + n_add > mesh.ecap:
        mesh = mesh.with_capacity(ecap=int((ned + n_add) * 1.3) + 8)
        m2 = np.asarray(mesh.edmask)
        e2 = np.asarray(mesh.edtag).copy()
        e2[: len(edtag)] = edtag
        edmask, edtag = m2.copy(), e2
        edge = np.asarray(mesh.edge)
        edref = np.asarray(mesh.edref)
    edge = edge.copy()
    edref = edref.copy()
    if n_add:
        slots = np.nonzero(~edmask)[0][:n_add]
        akey = ukey[~hit]
        edge[slots, 0] = akey // P
        edge[slots, 1] = akey % P
        edtag[slots] = utag[~hit]
        edref[slots] = 0
        edmask[slots] = True
    mesh = mesh.replace(
        edge=jnp.asarray(edge), edtag=jnp.asarray(edtag),
        edref=jnp.asarray(edref), edmask=jnp.asarray(edmask),
    )
    return _tag_feature_vertices(mesh)


def analyze(
    mesh: Mesh,
    ang: float | None = ANG_DEFAULT,
    features: bool = True,
    opnbdy: bool = False,
) -> Mesh:
    """Entry analysis pass — the `MMG3D_analys` role: adjacency, boundary
    completion + marking, and (unless `features=False` / `ang is None`,
    the `-nr` no-angle-detection mode) ridge/corner detection. With
    `opnbdy`, internal same-ref trias are preserved as open-boundary
    surface (`-opnbdy`, reference `src/libparmmg.h:64`)."""
    mesh = build_adjacency(mesh)
    mesh = synthesize_boundary_trias(mesh)
    if opnbdy:
        mesh = mark_opnbdy(mesh)
    mesh = mark_boundary(mesh)
    if features and ang is not None:
        mesh = detect_features(mesh, ang)
    return mesh
