"""Fail-safe layer: graded failure, checkpoint/resume, fault injection.

The reference's contract is that adaptation *degrades, never crashes*:
every phase ends in an ``MPI_Allreduce(ier, MIN)`` agreement and the
``failed_handling`` ladder returns the best conformal mesh so far as
``PMMG_LOWFAILURE``/``PMMG_STRONGFAILURE`` (reference
`src/libparmmg1.c:812,831` and `src/libparmmg1.c:970-1011`). Under JAX's
static-shape regime the failure *surface* differs — capacity exhaustion,
non-finite scatter poisoning, retrace-triggered XLA errors, preemption —
but the cure is the same: validate at phase boundaries, roll back to the
last good state, grow-and-retry capacity, and checkpoint so a killed
worker resumes instead of restarting. Four pieces:

- **typed exception taxonomy** (`AdaptError` and friends) that both
  drivers map onto `ReturnStatus.{SUCCESS,LOWFAILURE,STRONGFAILURE}`;
- **PhaseValidator**: the cadence-configurable phase-boundary validator
  (finiteness + positive orientation on device; host conformity via
  `utils.conformity` and communicator symmetry via `parallel.chkcomm`
  at the ``full`` level) replacing the ad-hoc ``_finite_ok``;
- **Checkpointer**: atomic (tmp + ``os.replace``, via
  `io.medit.atomic_replace`) per-iteration checkpoints carrying the
  exact mesh arrays, sweep state, history and an options fingerprint;
  a mismatched fingerprint *refuses* to resume with a clear error;
- **FaultPlan**: deterministic fault injection parsed from
  ``PARMMG_FAULTS="it1:remesh:nan,it2:migrate:overflow,it1:post:kill"``
  with hooks at every phase boundary in both drivers, so every recovery
  path above has a test that actually exercises it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import tags
from .core.mesh import Mesh, tet_volumes

# exit code of an injected ``kill`` fault (simulated preemption) — the
# test harness and tools/check.sh smoke stage assert on it
KILL_EXIT_CODE = 86

CHECKPOINT_FORMAT = 1


# ---------------------------------------------------------------------------
# exception taxonomy
# ---------------------------------------------------------------------------


class AdaptError(RuntimeError):
    """Base of the typed failure taxonomy (always also a RuntimeError so
    pre-existing broad handlers keep catching it)."""


class CapacityError(AdaptError):
    """A static capacity (shard slots, entity tables) was undershot.

    Recoverable: the caller can grow the relevant capacities and retry.
    Carries the per-shard / per-entity overflow scalars the raising site
    already computed:

    - ``overflow``: ``[D, 4]`` int array of per-shard excess
      ``[verts, tets, trias, edges]`` (integrate-side overflow), or None;
    - ``counts`` / ``caps``: pack-side per-destination counts vs the
      static slot caps ``[tets, trias, edges]``, or None.
    """

    def __init__(self, message: str, *, overflow=None, counts=None,
                 caps=None):
        super().__init__(message)
        self.overflow = None if overflow is None else np.asarray(overflow)
        self.counts = None if counts is None else np.asarray(counts)
        self.caps = None if caps is None else np.asarray(caps)


class MemoryBudgetError(AdaptError):
    """The configured device-memory budget blocks a needed growth.

    NOT recoverable by growing (growing is what the budget forbids): the
    distributed loop degrades it to LOWFAILURE with the last conformal
    snapshot; the centralized `adapt` raises it through (the budget is a
    hard caller contract, `test_budget_blocks_growth`)."""


class NumericalError(AdaptError):
    """Phase-boundary validation failed: non-finite coordinates/metric,
    inverted elements, broken conformity or communicator asymmetry.
    Deterministic re-runs reproduce it, so recovery is rollback to the
    last good state + LOWFAILURE, not retry."""


class RetraceError(AdaptError):
    """A transient XLA/executable error (the jax-0.9.0 stale-executable
    class that `utils.retry.jit_retry` papers over, or an injected
    fault). Recoverable once by ``jax.clear_caches()`` + retry."""


class CheckpointMismatchError(AdaptError):
    """A checkpoint exists but was written under incompatible options —
    resuming would silently change the trajectory, so refuse loudly."""


class PreemptionError(BaseException):
    """In-process stand-in for the ``kill`` fault's ``os._exit``
    (``FaultPlan(kill_mode="raise")``): derives from BaseException so no
    driver recovery path can absorb it — exactly like a real
    preemption, the run ends and only the checkpoint survives. Used by
    tests that cannot afford a subprocess per driver."""


def classify(exc: BaseException, have_mesh: bool) -> tags.ReturnStatus:
    """Map an exception escaping a driver onto the graded status ladder
    (the `failed_handling` role): LOWFAILURE iff a conformal result mesh
    survives, STRONGFAILURE otherwise."""
    if have_mesh:
        return tags.ReturnStatus.LOWFAILURE
    return tags.ReturnStatus.STRONGFAILURE


def snapshot(state):
    """Deep copy of a Mesh / stacked-Mesh pytree: the rollback target.
    A real copy, not a reference — the sweep engines donate their input
    buffers, so the kept-good state must own its arrays."""
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state
    )


# ---------------------------------------------------------------------------
# phase-boundary validation
# ---------------------------------------------------------------------------


# parmmg-lint: disable=PML005 -- pure query; the driver keeps the mesh for rollback
@jax.jit
def _sanity_counts(mesh: Mesh) -> jax.Array:
    """[3] int32: (non-finite vertices, non-finite metric rows,
    non-positive tets) over the live entities — the cheap device half of
    the validator (finiteness + positive orientation), one fused reduce
    like the reference's per-phase ``MPI_Allreduce(ier, MIN)``."""
    bad_v = jnp.sum(
        (mesh.vmask & ~jnp.all(jnp.isfinite(mesh.vert), axis=-1))
        .astype(jnp.int32)
    )
    bad_m = jnp.sum(
        (mesh.vmask & ~jnp.all(jnp.isfinite(mesh.met), axis=-1))
        .astype(jnp.int32)
    )
    vol = tet_volumes(mesh)
    n_inv = jnp.sum((mesh.tmask & ~(vol > 0)).astype(jnp.int32))
    return jnp.stack([bad_v, bad_m, n_inv]).astype(jnp.int32)


@dataclasses.dataclass
class PhaseValidator:
    """Cadence-configurable phase-boundary validation.

    ``level``: ``off`` (never), ``basic`` (device finiteness + positive
    orientation — one fused reduce, cheap enough for every iteration),
    ``full`` (basic + host-side conformity via `utils.conformity` and,
    for distributed states with a communicator, geometric/topological
    comm symmetry via `parallel.chkcomm`). ``every`` is the iteration
    cadence: the checks run when ``(it + 1) % every == 0``.
    """

    level: str = "basic"
    every: int = 1

    @property
    def active(self) -> bool:
        return self.level != "off"

    def due(self, it: int) -> bool:
        return self.active and (it + 1) % max(self.every, 1) == 0

    def check(self, state: Mesh, it: int, *, comm=None,
              phase: str = "iteration", force: bool = False) -> None:
        """Raise :class:`NumericalError` when the state is not a valid,
        conformal mesh. ``state`` is a single Mesh or a stacked [D,...]
        Mesh; ``comm`` (a ShardComm) arms the communicator checks at the
        ``full`` level. ``force`` bypasses the level/cadence gate (used
        right after a fault hook poisoned the state: the injection must
        be caught deterministically at ITS boundary, not churned through
        downstream phases first)."""
        if force:
            if not self.due(it):
                # run at least the basic device checks out of cadence
                return PhaseValidator(level="basic", every=1).check(
                    state, it, comm=comm, phase=phase
                )
        elif not self.due(it):
            return
        stacked = state.vert.ndim == 3
        counts = _sanity_counts if not stacked else jax.vmap(_sanity_counts)
        rep = np.asarray(jax.device_get(counts(state)))
        tot = rep.sum(axis=0) if stacked else rep
        if tot.any():
            raise NumericalError(
                f"phase-boundary validation failed after {phase} "
                f"(it {it}): {int(tot[0])} non-finite vertices, "
                f"{int(tot[1])} non-finite metric rows, "
                f"{int(tot[2])} non-positive tets"
            )
        if self.level != "full":
            return
        from .utils.conformity import check_mesh

        if stacked:
            from .parallel.distribute import unstack_mesh

            for s, m in enumerate(unstack_mesh(state)):
                r = check_mesh(m, check_boundary=False)
                if not r.ok:
                    raise NumericalError(
                        f"conformity check failed after {phase} (it {it}) "
                        f"on shard {s}: {r}"
                    )
            if comm is not None:
                from .parallel import chkcomm
                from .parallel.shard import device_mesh

                try:
                    chkcomm.assert_comm_ok(
                        state, comm, device_mesh(state.vert.shape[0]),
                        tol=1e-6,
                    )
                except AssertionError as e:
                    raise NumericalError(
                        f"communicator symmetry check failed after "
                        f"{phase} (it {it}): {e}"
                    ) from e
        else:
            r = check_mesh(state, check_boundary=False)
            if not r.ok:
                raise NumericalError(
                    f"conformity check failed after {phase} (it {it}): {r}"
                )


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------

FAULT_PHASES = ("analysis", "metric", "remesh", "interp", "migrate", "post")
FAULT_KINDS = ("nan", "overflow", "retrace", "kill")


@dataclasses.dataclass
class Fault:
    it: int
    phase: str
    kind: str
    fired: bool = False


class FaultPlan:
    """Deterministic fault schedule, e.g. parsed from
    ``PARMMG_FAULTS="it1:remesh:nan,it2:migrate:overflow,it1:post:kill"``.

    Each entry fires exactly once, at the matching (iteration, phase)
    boundary hook of either driver:

    - ``nan``: poisons the live state (NaN coordinate) — caught by the
      next phase-boundary validation and rolled back;
    - ``overflow``: a forced capacity undershoot — at the ``migrate``
      hook the driver undershoots the real slot capacity (the genuine
      `CapacityError` path fires); elsewhere a synthetic
      :class:`CapacityError` is raised at the hook;
    - ``retrace``: raises :class:`RetraceError` (the transient-XLA
      class) — recovered by clear-caches + retry;
    - ``kill``: simulated preemption — the process exits with
      :data:`KILL_EXIT_CODE` (checkpoint/resume covers it).
    """

    def __init__(self, faults: Optional[List[Fault]] = None,
                 kill_mode: str = "exit"):
        self.faults: List[Fault] = list(faults or [])
        if kill_mode not in ("exit", "raise"):
            raise ValueError(f"kill_mode {kill_mode!r} not in (exit, raise)")
        self.kill_mode = kill_mode

    @classmethod
    def parse(cls, spec: str, kill_mode: str = "exit") -> "FaultPlan":
        faults = []
        for tok in filter(None, (t.strip() for t in spec.split(","))):
            parts = tok.split(":")
            if len(parts) != 3 or not parts[0].startswith("it"):
                raise ValueError(
                    f"bad PARMMG_FAULTS token {tok!r} "
                    "(want it<k>:<phase>:<kind>)"
                )
            it = int(parts[0][2:])
            phase, kind = parts[1], parts[2]
            if phase not in FAULT_PHASES:
                raise ValueError(
                    f"unknown fault phase {phase!r} (one of {FAULT_PHASES})"
                )
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {FAULT_KINDS})"
                )
            faults.append(Fault(it, phase, kind))
        return cls(faults, kill_mode=kill_mode)

    @classmethod
    def resolve(cls, opts) -> "FaultPlan":
        """The plan for one driver run: ``opts.faults`` (a FaultPlan or
        spec string) when set, else the ``PARMMG_FAULTS`` environment
        variable, else an empty plan. A fresh run should get a fresh
        plan — fired state is per-instance."""
        given = getattr(opts, "faults", None)
        if isinstance(given, FaultPlan):
            return given
        if isinstance(given, str):
            return cls.parse(given)
        env = os.environ.get("PARMMG_FAULTS")
        if env:
            return cls.parse(env)
        return cls()

    def take(self, it: int, phase: str, kind: str) -> bool:
        """Consume a pending (phase, kind) fault scheduled at or before
        iteration `it`; True if it fired. Used by the driver for faults
        it must realize itself (the ``migrate`` overflow undershoots the
        real slot capacity) — those need a realizable event, and e.g.
        the first actual migration may come an iteration later than
        scheduled (an idle front moves nothing), so the fault arms the
        first opportunity at or after its iteration."""
        for f in self.faults:
            if not f.fired and f.it <= it and f.phase == phase \
                    and f.kind == kind:
                f.fired = True
                return True
        return False

    def fire(self, it: int, phase: str, state):
        """Apply every pending fault for this (it, phase) boundary.
        Returns the (possibly poisoned) state; may raise or exit."""
        for f in self.faults:
            if f.fired or f.it != it or f.phase != phase:
                continue
            if f.phase == "migrate" and f.kind == "overflow":
                # realized by the driver via take(): it undershoots the
                # REAL slot capacity so the genuine raise + recovery
                # path runs, not a synthetic stand-in
                continue
            f.fired = True
            where = f"it{it}:{phase}"
            if f.kind == "nan":
                idx = (0,) * (state.vert.ndim - 1)
                state = state.replace(
                    vert=state.vert.at[idx].set(jnp.nan)
                )
            elif f.kind == "overflow":
                raise CapacityError(
                    f"injected capacity overflow at {where} (fault plan)",
                    overflow=[[1, 1, 0, 0]],
                )
            elif f.kind == "retrace":
                raise RetraceError(
                    f"injected transient retrace/XLA error at {where} "
                    "(fault plan)"
                )
            elif f.kind == "kill":
                if self.kill_mode == "raise":
                    raise PreemptionError(
                        f"injected preemption at {where} (fault plan, "
                        "kill_mode=raise)"
                    )
                print(
                    f"[failsafe] injected preemption at {where} — "
                    f"exiting with code {KILL_EXIT_CODE}",
                    flush=True,
                )
                os._exit(KILL_EXIT_CODE)
        return state


# ---------------------------------------------------------------------------
# atomic checkpoint / resume
# ---------------------------------------------------------------------------

# resume-safe option fields, excluded from the compatibility fingerprint:
# they steer reporting, scheduling or the failsafe machinery itself, not
# the adaptation trajectory from a given state. `niter` is excluded by
# design: extending/shortening the remaining iterations is a legitimate
# resume (the checkpoint records which iteration it holds).
# `mem_budget_mb` is a per-machine resource knob (auto-derived when
# unset), not a trajectory option.
_FINGERPRINT_EXCLUDE = frozenset({
    "verbose", "niter", "checkpoint_dir", "checkpoint_every", "faults",
    "mem_budget_mb", "validate", "validate_every", "recovery_attempts",
})

_MESH_DATA_FIELDS = tuple(
    f.name for f in dataclasses.fields(Mesh) if not f.metadata.get("static")
)


def options_fingerprint(opts) -> Tuple[str, Dict[str, str]]:
    """(sha256 digest, field->repr dict) over the trajectory-relevant
    option fields — the checkpoint compatibility key."""
    fields = {
        f.name: repr(getattr(opts, f.name))
        for f in dataclasses.fields(opts)
        if f.name not in _FINGERPRINT_EXCLUDE
    }
    blob = json.dumps(fields, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest(), fields


def _histo_to_json(h) -> Optional[dict]:
    if h is None:
        return None
    out = {}
    for f in dataclasses.fields(h):
        v = np.asarray(jax.device_get(getattr(h, f.name)))
        out[f.name] = v.tolist()
    return out


def _histo_from_json(d: Optional[dict]):
    if d is None:
        return None
    from .ops.quality import QualityHisto

    return QualityHisto(**{k: jnp.asarray(np.asarray(v)) for k, v in
                           d.items()})


def _mesh_arrays(mesh: Mesh, prefix: str) -> Dict[str, np.ndarray]:
    return {
        prefix + name: np.asarray(jax.device_get(getattr(mesh, name)))
        for name in _MESH_DATA_FIELDS
    }


def _mesh_static(mesh: Mesh) -> dict:
    return dict(field_ncomp=list(mesh.field_ncomp), met_set=mesh.met_set)


def _mesh_from_arrays(arrs, prefix: str, static: dict) -> Mesh:
    return Mesh(
        **{name: jnp.asarray(arrs[prefix + name])
           for name in _MESH_DATA_FIELDS},
        field_ncomp=tuple(static["field_ncomp"]),
        met_set=bool(static["met_set"]),
    )


@dataclasses.dataclass
class ResumeState:
    """What `Checkpointer.load` hands back to a driver."""

    it: int                      # last completed iteration
    meshes: Dict[str, Mesh]      # "mesh" (+ "old" when fields ride along)
    history: List[dict]
    emult: float
    meta: dict                   # hausd, qual_in, icap, presize_skipped...

    @property
    def mesh(self) -> Mesh:
        return self.meshes["mesh"]


class Checkpointer:
    """Per-iteration atomic checkpoints under one directory.

    Layout: ``ckpt_<it:05d>.npz`` (exact mesh arrays, full capacity —
    restoring reproduces the running state bit for bit, capacities
    included) + ``ckpt_<it:05d>.json`` (iteration, options fingerprint,
    sweep state, history, auxiliary metadata). Both are written to a
    temp file and published with ``os.replace`` (via
    `io.medit.atomic_replace`), json LAST — the json is the commit
    record, so a kill can never leave a readable-but-truncated
    checkpoint. The latest two checkpoints are kept.
    """

    def __init__(self, dirpath: str, opts, driver: str, every: int = 1):
        self.dir = dirpath
        self.driver = driver
        self.every = max(int(every), 1)
        self.fingerprint, self.fields = options_fingerprint(opts)

    # -- naming ----------------------------------------------------------
    def _base(self, it: int) -> str:
        return os.path.join(self.dir, f"ckpt_{it:05d}")

    def _known(self) -> List[int]:
        if not os.path.isdir(self.dir):
            return []
        its = []
        for name in os.listdir(self.dir):
            if name.startswith("ckpt_") and name.endswith(".json"):
                try:
                    its.append(int(name[5:-5]))
                except ValueError:
                    pass
        return sorted(its)

    # -- save ------------------------------------------------------------
    def due(self, it: int) -> bool:
        return (it + 1) % self.every == 0

    def save(self, it: int, meshes: Dict[str, Mesh], *, history, emult,
             meta: Optional[dict] = None,
             aux_arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
        from .io.medit import atomic_replace

        os.makedirs(self.dir, exist_ok=True)
        arrs: Dict[str, np.ndarray] = {}
        statics = {}
        for key, m in meshes.items():
            arrs.update(_mesh_arrays(m, key + "/"))
            statics[key] = _mesh_static(m)
        aux = dict(aux_arrays or {})
        for k, v in aux.items():
            arrs["aux/" + k] = np.asarray(jax.device_get(v))
        base = self._base(it)
        with atomic_replace(base + ".npz", "wb") as f:
            np.savez(f, **arrs)
        doc = dict(
            format=CHECKPOINT_FORMAT,
            driver=self.driver,
            it=int(it),
            fingerprint=self.fingerprint,
            options=self.fields,
            emult=float(emult),
            history=history,
            meshes=statics,
            aux=sorted(aux),
            meta=meta or {},
        )
        with atomic_replace(base + ".json", "w") as f:
            json.dump(doc, f, default=str)
        for old in self._known()[:-2]:
            for ext in (".json", ".npz"):
                try:
                    os.unlink(self._base(old) + ext)
                except OSError:
                    pass

    # -- load ------------------------------------------------------------
    def load(self) -> Optional[ResumeState]:
        """Most recent compatible checkpoint, or None when the directory
        holds none. A checkpoint written under different options RAISES
        :class:`CheckpointMismatchError` (silent restart would discard
        the operator's intent); an unreadable newest checkpoint falls
        back to the previous one."""
        last_err = None
        for it in reversed(self._known()):
            base = self._base(it)
            try:
                with open(base + ".json") as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                last_err = e
                continue
            if doc.get("format") != CHECKPOINT_FORMAT \
                    or doc.get("driver") != self.driver:
                continue
            if doc["fingerprint"] != self.fingerprint:
                diff = sorted(
                    k for k in set(doc.get("options", {})) | set(self.fields)
                    if doc.get("options", {}).get(k) != self.fields.get(k)
                )
                raise CheckpointMismatchError(
                    f"checkpoint {base}.json was written under "
                    f"incompatible options (differing fields: {diff}); "
                    "refusing to resume — delete the checkpoint "
                    "directory or restore the original options"
                )
            try:
                with np.load(base + ".npz") as z:
                    arrs = {k: z[k] for k in z.files}
            except (OSError, ValueError) as e:
                last_err = e
                continue
            meshes = {
                key: _mesh_from_arrays(arrs, key + "/", static)
                for key, static in doc["meshes"].items()
            }
            meta = dict(doc.get("meta", {}))
            meta["aux_arrays"] = {
                k: arrs["aux/" + k] for k in doc.get("aux", ())
            }
            return ResumeState(
                it=int(doc["it"]),
                meshes=meshes,
                history=list(doc["history"]),
                emult=float(doc["emult"]),
                meta=meta,
            )
        if last_err is not None:
            import warnings

            warnings.warn(
                f"no readable checkpoint in {self.dir} "
                f"(last error: {last_err}); starting fresh",
                stacklevel=2,
            )
        return None


# ---------------------------------------------------------------------------
# the harness the drivers hold
# ---------------------------------------------------------------------------


class FailsafeHarness:
    """One driver run's failsafe state: validator + fault plan +
    checkpointer + the bounded-recovery budget. Built by
    :func:`harness`; every hook is a no-op when the corresponding
    feature is off, so the drivers call unconditionally."""

    def __init__(self, opts, driver: str,
                 checkpoint_dir: Optional[str] = None):
        self.validator = PhaseValidator(
            level=getattr(opts, "validate", "basic") or "off",
            every=int(getattr(opts, "validate_every", 1) or 1),
        )
        self.faults = FaultPlan.resolve(opts)
        self.attempts = int(getattr(opts, "recovery_attempts", 0) or 0)
        ckdir = checkpoint_dir or getattr(opts, "checkpoint_dir", None)
        self.ckpt = (
            Checkpointer(
                ckdir, opts, driver,
                every=getattr(opts, "checkpoint_every", 1),
            )
            if ckdir else None
        )

    @property
    def rollback_enabled(self) -> bool:
        return (
            self.validator.active or self.attempts > 0
            or self.ckpt is not None or bool(self.faults.faults)
        )

    def snapshot(self, state):
        return snapshot(state) if self.rollback_enabled else None

    def validate(self, state, it: int, *, comm=None,
                 phase: str = "iteration") -> None:
        self.validator.check(state, it, comm=comm, phase=phase)

    def fire(self, it: int, phase: str, state):
        """Fire pending faults at this boundary; when one poisoned the
        state (``nan``), validate IMMEDIATELY (out of cadence) so the
        injection is caught at its own boundary instead of being
        churned through downstream phases first. No fault pending →
        exactly the no-op path (no extra device work)."""
        before = sum(f.fired for f in self.faults.faults)
        state = self.faults.fire(it, phase, state)
        if sum(f.fired for f in self.faults.faults) != before:
            self.validator.check(state, it, phase=phase, force=True)
        return state

    def resume(self) -> Optional[ResumeState]:
        return self.ckpt.load() if self.ckpt is not None else None

    def save(self, it: int, meshes: Dict[str, Mesh], *, history, emult,
             meta=None, aux_arrays=None) -> None:
        if self.ckpt is None or not self.ckpt.due(it):
            return
        self.ckpt.save(it, meshes, history=history, emult=emult,
                       meta=meta, aux_arrays=aux_arrays)

    def post_iteration(self, it: int, state, history: List[dict]):
        """Fire ``post``-phase faults after the checkpoint commit.
        Raising kinds (retrace/overflow) are absorbed here — the
        iteration's good state is already committed, so recovery is
        record + clear-caches + continue, not a re-run."""
        try:
            return self.faults.fire(it, "post", state)
        except (RetraceError, CapacityError) as e:
            history.append(dict(
                iter=it, phase="post", failure=str(e),
                error=type(e).__name__, recovered=True,
            ))
            if isinstance(e, RetraceError):
                jax.clear_caches()
            return state


def harness(opts, driver: str,
            checkpoint_dir: Optional[str] = None) -> FailsafeHarness:
    """The failsafe harness for one driver run (see
    :class:`FailsafeHarness`)."""
    return FailsafeHarness(opts, driver, checkpoint_dir=checkpoint_dir)
