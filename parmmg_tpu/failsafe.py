"""Fail-safe layer: graded failure, checkpoint/resume, fault injection.

The reference's contract is that adaptation *degrades, never crashes*:
every phase ends in an ``MPI_Allreduce(ier, MIN)`` agreement and the
``failed_handling`` ladder returns the best conformal mesh so far as
``PMMG_LOWFAILURE``/``PMMG_STRONGFAILURE`` (reference
`src/libparmmg1.c:812,831` and `src/libparmmg1.c:970-1011`). Under JAX's
static-shape regime the failure *surface* differs — capacity exhaustion,
non-finite scatter poisoning, retrace-triggered XLA errors, preemption —
but the cure is the same: validate at phase boundaries, roll back to the
last good state, grow-and-retry capacity, and checkpoint so a killed
worker resumes instead of restarting. Four pieces:

- **typed exception taxonomy** (`AdaptError` and friends) that both
  drivers map onto `ReturnStatus.{SUCCESS,LOWFAILURE,STRONGFAILURE}`;
- **PhaseValidator**: the cadence-configurable phase-boundary validator
  (finiteness + positive orientation on device; host conformity via
  `utils.conformity` and communicator symmetry via `parallel.chkcomm`
  at the ``full`` level) replacing the ad-hoc ``_finite_ok``;
- **Checkpointer**: atomic (tmp + ``os.replace``, via
  `io.medit.atomic_replace`) per-iteration checkpoints carrying the
  exact mesh arrays, sweep state, history and an options fingerprint;
  a mismatched fingerprint *refuses* to resume with a clear error;
- **FaultPlan**: deterministic fault injection parsed from
  ``PARMMG_FAULTS="it1:remesh:nan,it2:migrate:overflow,it1:post:kill"``
  with hooks at every phase boundary in both drivers, so every recovery
  path above has a test that actually exercises it.

Multi-host awareness (the reference survives node-scale runs because
every MPI rank owns its sub-mesh and can be restarted from per-rank
state — Cirrottola & Froehly, RR-9307 §restart): under a
`jax.distributed` world the checkpointer shards — each process
atomically writes ``ckpt_<it>.proc<rank>.npz`` for its shard rows and
rank 0 publishes a manifest (world size, per-rank digests) only after a
``multihost.barrier()``, so a kill at ANY point leaves either the old
or the new checkpoint complete; resume refuses loudly on a
world-size/fingerprint mismatch. Validation on the SPMD sweep path is
device-resident (`stacked_status`: psum-reduced
finiteness/orientation/connectivity inside the shard_map, Omega_h-style
— only a [D,4] status table crosses to host, never the mesh).
Preemption is handled by a SIGTERM → checkpoint-then-
:class:`PreemptionError` handler armed by the harness, and silent peer
loss by the collective watchdog (`multihost.run_with_watchdog`) which
raises :class:`PeerLostError` instead of hanging. Faults can be
rank-targeted (``it1:remesh:kill@rank1``) so every multi-host path is
deterministically testable with 2+ CPU processes.

Elasticity + durability (the last three ROADMAP gaps of the fail-safe
story):

- **elastic resume**: a manifest written by an N-process world loads
  under an M-process world — every process digest-verifies all N
  source shard files and re-concatenates the replicated host state
  (the host picture is replicated-deterministic, so world size is a
  resource layout, not a trajectory option). The hard refusal stays
  ONLY for an options-fingerprint mismatch. When the checkpoint's
  shard count no longer matches the device layout, the drivers re-cut
  the merged state through the ordinary `parallel/distribute` +
  `partition` path (owner ranks and comm tables rebuilt from vglob).
- **pluggable durable storage** (`io.ckpt_store`): all checkpoint I/O
  goes through a :class:`~parmmg_tpu.io.ckpt_store.CheckpointStore`
  (`LocalFSStore` — the POSIX tmp+rename layout; `ObjectStore` — GCS
  semantics, single-object atomic put + manifest-last commit), every
  operation under bounded retry with exponential backoff +
  deterministic jitter and a per-op timeout; `ioerror`/`slowio` faults
  at the ``ckpt`` fault phase drive each retry/abort path in tests.
- **async snapshot staging** (`AdaptOptions.checkpoint_async` /
  ``PMMGTPU_ASYNC_CKPT``): the device→host snapshot is taken at the
  iteration boundary (double-buffered — each staged epoch owns its
  host arrays), but serialization + store puts run on a background
  writer thread; the adapt loop blocks only at the commit barrier of
  the PREVIOUS checkpoint, and the SIGTERM/preemption path drains the
  queue before exiting (`FailsafeHarness.finish`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import threading
import time
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import tags
from .core.mesh import Mesh, tet_volumes
from .io.ckpt_store import CheckpointIOError  # noqa: F401  (re-export)
from .obs import metrics as obs_metrics, trace as obs_trace

# exit code of an injected ``kill`` fault (simulated preemption) — the
# test harness and tools/check.sh smoke stage assert on it
KILL_EXIT_CODE = 86
# exit code a multi-host worker uses after converting a PeerLostError
# into a checkpoint-backed exit (tools/fault_smoke.py --multihost and
# the m10 subprocess tests assert on it)
PEER_LOST_EXIT_CODE = 87
# exit code a worker uses when resume REFUSED (an options-fingerprint
# mismatch, CheckpointMismatchError) — distinct so tests can tell a
# loud refusal from a crash
MISMATCH_EXIT_CODE = 88
# exit code a worker uses when checkpoint I/O failed past its bounded
# retries (io.ckpt_store.CheckpointIOError) — the chaos harness and
# smoke stages assert the typed family {0, 86, 87, 88, 89, 92} and
# nothing else
CKPT_IO_EXIT_CODE = 89
# exit code a SURVIVOR uses after a world-agreed elastic reformation
# (`parallel.elastic`): the checkpoint is committed and the fleet
# supervisor (tools/fleet.py) relaunches this rank in the reformed
# world — exit 90 means "relaunch me", not "I failed"
REFORM_EXIT_CODE = 90
# exit code a worker uses after the collective-lockstep ledger
# (`lint.contracts.verify_ledger`, armed under validate="full") proved
# the world's collective schedules diverged — distinct from the generic
# peer-loss 87 so the chaos harness can tell "a rank desynced and every
# rank agreed on that" from "a rank silently vanished"
DIVERGENCE_EXIT_CODE = 92

CHECKPOINT_FORMAT = 1


# ---------------------------------------------------------------------------
# exception taxonomy
# ---------------------------------------------------------------------------


class AdaptError(RuntimeError):
    """Base of the typed failure taxonomy (always also a RuntimeError so
    pre-existing broad handlers keep catching it)."""


class CapacityError(AdaptError):
    """A static capacity (shard slots, entity tables) was undershot.

    Recoverable: the caller can grow the relevant capacities and retry.
    Carries the per-shard / per-entity overflow scalars the raising site
    already computed:

    - ``overflow``: ``[D, 4]`` int array of per-shard excess
      ``[verts, tets, trias, edges]`` (integrate-side overflow), or None;
    - ``counts`` / ``caps``: pack-side per-destination counts vs the
      static slot caps ``[tets, trias, edges]``, or None.
    """

    def __init__(self, message: str, *, overflow=None, counts=None,
                 caps=None):
        super().__init__(message)
        self.overflow = None if overflow is None else np.asarray(overflow)
        self.counts = None if counts is None else np.asarray(counts)
        self.caps = None if caps is None else np.asarray(caps)


class MemoryBudgetError(AdaptError):
    """The configured device-memory budget blocks a needed growth.

    NOT recoverable by growing (growing is what the budget forbids): the
    distributed loop degrades it to LOWFAILURE with the last conformal
    snapshot; the centralized `adapt` raises it through (the budget is a
    hard caller contract, `test_budget_blocks_growth`)."""


class NumericalError(AdaptError):
    """Phase-boundary validation failed: non-finite coordinates/metric,
    inverted elements, broken conformity or communicator asymmetry.
    Deterministic re-runs reproduce it, so recovery is rollback to the
    last good state + LOWFAILURE, not retry."""


class RetraceError(AdaptError):
    """A transient XLA/executable error (the jax-0.9.0 stale-executable
    class that `utils.retry.jit_retry` papers over, or an injected
    fault). Recoverable once by ``jax.clear_caches()`` + retry."""


class CheckpointMismatchError(AdaptError):
    """A checkpoint exists but was written under incompatible options,
    or under a different world size than the resuming run — resuming
    would silently change the trajectory (or deadlock the shard
    exchange), so refuse loudly."""


class PeerLostError(AdaptError):
    """A collective (checkpoint barrier / phase heartbeat) timed out:
    a peer process died or hung, so the SPMD world is broken. NOT
    recoverable in-process — both drivers re-raise it through every
    recovery path (rollback cannot resurrect a peer); the cure is
    checkpoint-backed restart. Raised by
    `parallel.multihost.run_with_watchdog` when
    ``watchdog_timeout`` is configured, instead of hanging forever the
    way a bare collective on a lost TCP peer does."""


class CollectiveDivergenceError(PeerLostError):
    """The collective-lockstep ledger proved the world's collective
    schedules diverged (`lint.contracts.verify_ledger`, armed under
    ``validate="full"``): a subset of ranks skipped or injected a
    collective — the runtime realization of the static PML012 finding.
    Subclasses :class:`PeerLostError` because the consequence is the
    same (the SPMD world is broken, no in-process recovery), but it is
    raised on EVERY rank at the SAME boundary, so workers can exit with
    the distinct :data:`DIVERGENCE_EXIT_CODE` instead of riding a
    one-sided watchdog timeout."""


class PreemptionError(BaseException):
    """In-process stand-in for the ``kill`` fault's ``os._exit``
    (``FaultPlan(kill_mode="raise")``): derives from BaseException so no
    driver recovery path can absorb it — exactly like a real
    preemption, the run ends and only the checkpoint survives. Used by
    tests that cannot afford a subprocess per driver."""


class WorldReformError(BaseException):
    """A world-agreed elastic reformation (`parallel.elastic`): the
    epoch's checkpoint is committed and this SURVIVOR must tear down so
    the fleet can relaunch it in the reformed world. BaseException like
    :class:`PreemptionError` — no recovery path may absorb it (rollback
    cannot un-agree a reformation the other ranks are already exiting
    for). Workers convert it to :data:`REFORM_EXIT_CODE`."""

    def __init__(self, kind: str, epoch: int, old_world: int,
                 new_world: int):
        super().__init__(
            f"world reform ({kind}) agreed at epoch {epoch}: "
            f"{old_world}→{new_world} ranks — checkpoint committed, "
            "exiting for relaunch in the reformed world"
        )
        self.kind = kind
        self.epoch = int(epoch)
        self.old_world = int(old_world)
        self.new_world = int(new_world)


def classify(exc: BaseException, have_mesh: bool) -> tags.ReturnStatus:
    """Map an exception escaping a driver onto the graded status ladder
    (the `failed_handling` role): LOWFAILURE iff a conformal result mesh
    survives, STRONGFAILURE otherwise."""
    if have_mesh:
        return tags.ReturnStatus.LOWFAILURE
    return tags.ReturnStatus.STRONGFAILURE


def snapshot(state):
    """Deep copy of a Mesh / stacked-Mesh pytree: the rollback target.
    A real copy, not a reference — the sweep engines donate their input
    buffers, so the kept-good state must own its arrays."""
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state
    )


def record_rollback(it: int, exc: BaseException,
                    phase: str = "iteration") -> None:
    """Observability hook the drivers call next to every rollback
    `history` entry: the absorbed failure lands in the obs event
    timeline and the `failsafe/rollbacks` counter, so a chaos run's
    recovery sequence is reconstructable from the trace directory
    alone."""
    obs_trace.emit_event("rollback", it=int(it), phase=phase,
                         error=type(exc).__name__)
    obs_metrics.registry().counter("failsafe/rollbacks").inc()


# ---------------------------------------------------------------------------
# phase-boundary validation
# ---------------------------------------------------------------------------


# human-readable labels of the _sanity_counts / stacked_status columns
STATUS_COLS = (
    "nonfinite_verts", "nonfinite_met", "nonpositive_tets", "conn_oob",
)


# parmmg-lint: disable=PML005 -- pure query; the driver keeps the mesh for rollback
@jax.jit
def _sanity_counts(mesh: Mesh) -> jax.Array:
    """[4] int32: (non-finite vertices, non-finite metric rows,
    non-positive tets, tets with out-of-range/dead connectivity) over
    the live entities — the cheap device half of the validator
    (finiteness + positive orientation + capacity/overflow poisoning),
    one fused reduce like the reference's per-phase
    ``MPI_Allreduce(ier, MIN)``."""
    bad_v = jnp.sum(
        (mesh.vmask & ~jnp.all(jnp.isfinite(mesh.vert), axis=-1))
        .astype(jnp.int32)
    )
    bad_m = jnp.sum(
        (mesh.vmask & ~jnp.all(jnp.isfinite(mesh.met), axis=-1))
        .astype(jnp.int32)
    )
    vol = tet_volumes(mesh)
    n_inv = jnp.sum((mesh.tmask & ~(vol > 0)).astype(jnp.int32))
    # connectivity poisoning: a live tet indexing out of the vertex
    # table (per-shard slot overflow truncation) or a dead vertex
    pcap = mesh.vert.shape[0]
    in_rng = (mesh.tet >= 0) & (mesh.tet < pcap)
    live = mesh.vmask[jnp.clip(mesh.tet, 0, pcap - 1)]
    n_oob = jnp.sum(
        (mesh.tmask & ~jnp.all(in_rng & live, axis=1)).astype(jnp.int32)
    )
    return jnp.stack([bad_v, bad_m, n_inv, n_oob]).astype(jnp.int32)


@lru_cache(maxsize=8)
def _stacked_status_fn(dmesh):
    """Memoized jit(shard_map) status reducer per device mesh
    (rebuilding it per call would retrace every validation —
    parmmg-lint PML004). Each shard computes its own [4] counters and
    the replicated [D, 4] table is assembled with one psum
    (`comm.status_allgather`) — the whole check stays on device; only
    the table crosses to host, never a mesh array."""
    from jax.sharding import PartitionSpec as P

    from .parallel.comm import status_allgather
    from .parallel.shard import AXIS, _squeeze

    def body(blk):
        st = _sanity_counts(_squeeze(blk))
        return status_allgather(st, AXIS)

    return jax.jit(jax.shard_map(
        body, mesh=dmesh, in_specs=(P(AXIS),), out_specs=P()
    ))


def stacked_status(stacked: Mesh, dmesh) -> jax.Array:
    """Device-resident per-shard status table of a stacked [D,...] mesh
    laid over `dmesh`: replicated [D, 4] int32 of
    :data:`STATUS_COLS` counters (all-zero iff every shard is sane).
    The Omega_h-style device reduction replacing the
    `multihost.gather_stacked` round trip for ``validate="basic"`` on
    the SPMD path — works identically single-process and across a
    multi-controller world (the psum rides ICI/DCN; the result is
    replicated so every process reads it locally)."""
    return _stacked_status_fn(dmesh)(stacked)


@dataclasses.dataclass
class PhaseValidator:
    """Cadence-configurable phase-boundary validation.

    ``level``: ``off`` (never), ``basic`` (device finiteness + positive
    orientation — one fused reduce, cheap enough for every iteration),
    ``full`` (basic + host-side conformity via `utils.conformity` and,
    for distributed states with a communicator, geometric/topological
    comm symmetry via `parallel.chkcomm`). ``every`` is the iteration
    cadence: the checks run when ``(it + 1) % every == 0``.
    """

    level: str = "basic"
    every: int = 1

    @property
    def active(self) -> bool:
        return self.level != "off"

    def due(self, it: int) -> bool:
        return self.active and (it + 1) % max(self.every, 1) == 0

    def check(self, state: Mesh, it: int, *, comm=None,
              phase: str = "iteration", force: bool = False) -> None:
        """Raise :class:`NumericalError` when the state is not a valid,
        conformal mesh. ``state`` is a single Mesh or a stacked [D,...]
        Mesh; ``comm`` (a ShardComm) arms the communicator checks at the
        ``full`` level. ``force`` bypasses the level/cadence gate (used
        right after a fault hook poisoned the state: the injection must
        be caught deterministically at ITS boundary, not churned through
        downstream phases first)."""
        if force:
            if not self.due(it):
                # run at least the basic device checks out of cadence
                return PhaseValidator(level="basic", every=1).check(
                    state, it, comm=comm, phase=phase
                )
        elif not self.due(it):
            return
        stacked = state.vert.ndim == 3
        counts = _sanity_counts if not stacked else jax.vmap(_sanity_counts)
        rep = np.asarray(jax.device_get(counts(state)))
        tot = rep.sum(axis=0) if stacked else rep
        if tot.any():
            raise NumericalError(
                f"phase-boundary validation failed after {phase} "
                f"(it {it}): {int(tot[0])} non-finite vertices, "
                f"{int(tot[1])} non-finite metric rows, "
                f"{int(tot[2])} non-positive tets, "
                f"{int(tot[3])} tets with out-of-range connectivity"
            )
        if self.level != "full":
            return
        from .utils.conformity import check_mesh

        if stacked:
            from .parallel.distribute import unstack_mesh

            for s, m in enumerate(unstack_mesh(state)):
                r = check_mesh(m, check_boundary=False)
                if not r.ok:
                    raise NumericalError(
                        f"conformity check failed after {phase} (it {it}) "
                        f"on shard {s}: {r}"
                    )
            if comm is not None:
                from .parallel import chkcomm
                from .parallel.shard import device_mesh

                try:
                    chkcomm.assert_comm_ok(
                        state, comm, device_mesh(state.vert.shape[0]),
                        tol=1e-6,
                    )
                except AssertionError as e:
                    raise NumericalError(
                        f"communicator symmetry check failed after "
                        f"{phase} (it {it}): {e}"
                    ) from e
        else:
            r = check_mesh(state, check_boundary=False)
            if not r.ok:
                raise NumericalError(
                    f"conformity check failed after {phase} (it {it}): {r}"
                )

    def check_sharded(self, state: Mesh, dmesh, it: int, *,
                      phase: str = "sweep", force: bool = False) -> None:
        """Device-resident basic validation for the SPMD sweep path.

        The per-shard finiteness/orientation/connectivity counters are
        reduced INSIDE the shard_map (`stacked_status`) and only the
        replicated [D, 4] table is fetched — zero host gathers of mesh
        arrays, so validation adds one tiny device reduce per sweep
        instead of a cross-process allgather of the whole stacked
        state. Raises :class:`NumericalError` with per-shard
        attribution. The ``full``-level host work (conformity,
        chkcomm) intentionally stays on the gathered iteration-boundary
        path — this method only ever runs the basic device half."""
        if not self.active or not (force or self.due(it)):
            return
        rep = np.asarray(jax.device_get(stacked_status(state, dmesh)))
        if rep.any():
            bad = {
                s: dict(zip(STATUS_COLS, (int(x) for x in row)))
                for s, row in enumerate(rep) if row.any()
            }
            raise NumericalError(
                f"device-resident validation failed after {phase} "
                f"(it {it}); per-shard counters: {bad}"
            )


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------

FAULT_PHASES = (
    "analysis", "metric", "remesh", "interp", "migrate", "post", "ckpt",
    "comm",
)
FAULT_KINDS = (
    "nan", "overflow", "retrace", "kill", "sigterm", "ioerror", "slowio",
    "preempt-notice", "peer-lost", "desync",
)
# kinds that live at the ``ckpt`` phase: they fire inside the
# checkpoint STORE (consumed per store operation via
# `FaultPlan.io_fault`, not at a driver phase boundary)
_IO_FAULT_KINDS = ("ioerror", "slowio")
# the ``comm`` phase hosts exactly one kind: ``desync`` poisons the
# targeted rank's collective-lockstep ledger (as if it had dispatched
# a collective its peers never will), exercised by the chaos harness's
# --desync rung — detected by `verify_collectives`, not a watchdog
_COMM_FAULT_KINDS = ("desync",)
# everything the ckpt phase accepts: the store-op pair above plus
# ``kill``, which at this phase means "die at the next manifest
# PUBLISH at/after store op k" — i.e. INSIDE the two-barrier commit
# window of the sharded checkpoint protocol, the nastiest spot a
# preemption can land
_CKPT_FAULT_KINDS = _IO_FAULT_KINDS + ("kill",)


@dataclasses.dataclass
class Fault:
    it: int
    phase: str
    kind: str
    rank: Optional[int] = None   # None = every process; else that rank only
    fired: bool = False

    @property
    def mine(self) -> bool:
        """Does this fault target the current process? Rank-targeted
        faults (``kill@rank1``) fire only on the named
        `jax.process_index()` — how a 2-process CPU test kills exactly
        one worker mid-iteration."""
        return self.rank is None or self.rank == jax.process_index()


class FaultPlan:
    """Deterministic fault schedule, e.g. parsed from
    ``PARMMG_FAULTS="it1:remesh:nan,it2:migrate:overflow,it1:post:kill"``.

    Each entry fires exactly once, at the matching (iteration, phase)
    boundary hook of either driver:

    - ``nan``: poisons the live state (NaN coordinate) — caught by the
      next phase-boundary validation and rolled back;
    - ``overflow``: a forced capacity undershoot — at the ``migrate``
      hook the driver undershoots the real slot capacity (the genuine
      `CapacityError` path fires); elsewhere a synthetic
      :class:`CapacityError` is raised at the hook;
    - ``retrace``: raises :class:`RetraceError` (the transient-XLA
      class) — recovered by clear-caches + retry;
    - ``kill``: simulated preemption — the process exits with
      :data:`KILL_EXIT_CODE` (checkpoint/resume covers it);
    - ``preempt-notice``: a maintenance-event notice
      (`parallel.multihost.request_preemption_notice`) — the drivers
      force an out-of-cadence checkpoint at the next iteration boundary
      and keep running (the proactive half of preemption handling);
    - ``peer-lost``: a simulated coordination-service peer-death
      report on the targeted rank
      (`parallel.multihost.simulate_peer_loss`) — its next
      barrier/heartbeat raises the typed :class:`PeerLostError`
      instead of hanging, exercising the survivor-side detection path
      without actually killing a peer;
    - ``desync`` (``comm`` phase only): poisons the targeted rank's
      collective-lockstep ledger — as if it had dispatched a
      collective its peers never will — so the next
      ``verify_collectives`` boundary (``validate="full"``) raises
      :class:`CollectiveDivergenceError` on EVERY rank simultaneously
      instead of a one-sided watchdog timeout;
    - ``ioerror`` / ``slowio`` (``ckpt`` phase only): checkpoint-store
      I/O faults, consumed per STORE OPERATION via :meth:`io_fault` —
      for these the ``it<k>`` field indexes store ops (0-based, per
      process), not iterations, so "fail the 3rd put" is expressible;
      ``kill`` at the ``ckpt`` phase arms at store op ``it<k>`` but
      fires at the next manifest PUBLISH — a death inside the
      two-barrier commit window of the sharded protocol.
    """

    def __init__(self, faults: Optional[List[Fault]] = None,
                 kill_mode: str = "exit"):
        self.faults: List[Fault] = list(faults or [])
        if kill_mode not in ("exit", "raise"):
            raise ValueError(f"kill_mode {kill_mode!r} not in (exit, raise)")
        self.kill_mode = kill_mode
        self._ckpt_ops = 0   # store-operation ordinal (io_fault clock)

    @classmethod
    def parse(cls, spec: str, kill_mode: str = "exit") -> "FaultPlan":
        faults = []
        for tok in filter(None, (t.strip() for t in spec.split(","))):
            parts = tok.split(":")
            if len(parts) != 3 or not parts[0].startswith("it"):
                raise ValueError(
                    f"bad PARMMG_FAULTS token {tok!r} "
                    "(want it<k>:<phase>:<kind>[@rank<r>])"
                )
            it = int(parts[0][2:])
            phase, kind = parts[1], parts[2]
            rank = None
            if "@" in kind:
                kind, _, rk = kind.partition("@")
                if not rk.startswith("rank") or not rk[4:].isdigit():
                    raise ValueError(
                        f"bad fault rank suffix {rk!r} in {tok!r} "
                        "(want @rank<r>, r a 0-based process index)"
                    )
                rank = int(rk[4:])
            if phase not in FAULT_PHASES:
                raise ValueError(
                    f"unknown fault phase {phase!r} (one of {FAULT_PHASES})"
                )
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {FAULT_KINDS})"
                )
            if kind in _IO_FAULT_KINDS and phase != "ckpt":
                raise ValueError(
                    f"fault token {tok!r}: kinds {_IO_FAULT_KINDS} pair "
                    "exclusively with the 'ckpt' phase (store-operation "
                    "faults)"
                )
            if phase == "ckpt" and kind not in _CKPT_FAULT_KINDS:
                raise ValueError(
                    f"fault token {tok!r}: the 'ckpt' phase accepts "
                    f"kinds {_CKPT_FAULT_KINDS} (store-operation "
                    "faults; 'kill' = die at the next manifest "
                    "publish), other kinds fire at driver phases"
                )
            if (kind in _COMM_FAULT_KINDS) != (phase == "comm"):
                raise ValueError(
                    f"fault token {tok!r}: kind 'desync' pairs "
                    "exclusively with the 'comm' phase (it poisons the "
                    "collective-lockstep ledger at an iteration "
                    "boundary)"
                )
            faults.append(Fault(it, phase, kind, rank=rank))
        return cls(faults, kill_mode=kill_mode)

    @classmethod
    def resolve(cls, opts) -> "FaultPlan":
        """The plan for one driver run: ``opts.faults`` (a FaultPlan or
        spec string) when set, else the ``PARMMG_FAULTS`` environment
        variable, else an empty plan. A fresh run should get a fresh
        plan — fired state is per-instance."""
        given = getattr(opts, "faults", None)
        if isinstance(given, FaultPlan):
            return given
        if isinstance(given, str):
            return cls.parse(given)
        env = os.environ.get("PARMMG_FAULTS")
        if env:
            return cls.parse(env)
        return cls()

    def take(self, it: int, phase: str, kind: str) -> bool:
        """Consume a pending (phase, kind) fault scheduled at or before
        iteration `it`; True if it fired. Used by the driver for faults
        it must realize itself (the ``migrate`` overflow undershoots the
        real slot capacity) — those need a realizable event, and e.g.
        the first actual migration may come an iteration later than
        scheduled (an idle front moves nothing), so the fault arms the
        first opportunity at or after its iteration."""
        for f in self.faults:
            if not f.fired and f.it <= it and f.phase == phase \
                    and f.kind == kind and f.mine:
                f.fired = True
                obs_trace.emit_event(
                    "fault_injected", kind=kind, phase=phase, it=int(it),
                    realized="driver",
                )
                obs_metrics.registry().counter(
                    "failsafe/faults_injected"
                ).inc()
                return True
        return False

    def io_fault(self, op: str, name: str,
                 timeout: Optional[float] = None) -> None:
        """Checkpoint-store fault hook (`CheckpointStore.fault_cb`),
        invoked before every raw store attempt. Consumes pending
        ``ckpt``-phase faults: the ``it<k>`` field is the 0-based STORE
        OPERATION ordinal (per process) at/after which the fault arms;
        each fault fires exactly once, in schedule order. ``ioerror``
        raises OSError — the store's bounded retry absorbs isolated
        ones; schedule at least `attempts` of them to force the typed
        :class:`~parmmg_tpu.io.ckpt_store.CheckpointIOError` abort.
        ``slowio`` outsleeps the store's per-op timeout (a no-op when
        no timeout is configured), driving the timeout→retry path.
        ``kill`` arms at op k but fires only at the next manifest
        PUBLISH — between the data barrier and the commit barrier of
        the sharded protocol, so the chaos matrix can aim a preemption
        INSIDE the commit window (the commit token never lands,
        survivors get a typed PeerLostError, resume falls back to the
        previous committed epoch)."""
        k = self._ckpt_ops
        self._ckpt_ops += 1
        for f in self.faults:
            if f.fired or f.phase != "ckpt" or not f.mine or f.it > k:
                continue
            if f.kind == "kill" and op != "publish":
                continue  # armed, but only the commit token triggers it
            f.fired = True
            obs_trace.emit_event(
                "fault_injected", kind=f.kind, phase="ckpt", op=op,
                store_op=k,
            )
            obs_metrics.registry().counter(
                "failsafe/faults_injected"
            ).inc()
            if f.kind == "kill":
                if self.kill_mode == "raise":
                    raise PreemptionError(
                        f"injected commit-window preemption at store "
                        f"op {k} ({op} {name!r}) (fault plan, "
                        "kill_mode=raise)"
                    )
                print(
                    f"[failsafe] injected commit-window preemption at "
                    f"store op {k} ({op} {name!r}) — exiting with code "
                    f"{KILL_EXIT_CODE}",
                    flush=True,
                )
                os._exit(KILL_EXIT_CODE)
            if f.kind == "ioerror":
                raise OSError(
                    f"injected checkpoint ioerror at store op {k} "
                    f"({op} {name!r}) (fault plan)"
                )
            if f.kind == "slowio" and timeout is not None:
                time.sleep(timeout + 0.25)
            return

    def fire(self, it: int, phase: str, state):
        """Apply every pending fault for this (it, phase) boundary.
        Returns the (possibly poisoned) state; may raise or exit."""
        for f in self.faults:
            if f.fired or f.it != it or f.phase != phase or not f.mine:
                continue
            if f.phase == "migrate" and f.kind == "overflow":
                # realized by the driver via take(): it undershoots the
                # REAL slot capacity so the genuine raise + recovery
                # path runs, not a synthetic stand-in
                continue
            f.fired = True
            where = f"it{it}:{phase}" + (
                f"@rank{f.rank}" if f.rank is not None else ""
            )
            # timeline first, action second: the JSONL line is flushed
            # before a `kill` can os._exit, so even a hard death leaves
            # the injected fault in the durable event log
            obs_trace.emit_event(
                "fault_injected", kind=f.kind, phase=phase, it=int(it),
                where=where,
            )
            obs_metrics.registry().counter(
                "failsafe/faults_injected"
            ).inc()
            if f.kind == "nan":
                idx = (0,) * (state.vert.ndim - 1)
                state = state.replace(
                    vert=state.vert.at[idx].set(jnp.nan)
                )
            elif f.kind == "overflow":
                raise CapacityError(
                    f"injected capacity overflow at {where} (fault plan)",
                    overflow=[[1, 1, 0, 0]],
                )
            elif f.kind == "retrace":
                raise RetraceError(
                    f"injected transient retrace/XLA error at {where} "
                    "(fault plan)"
                )
            elif f.kind == "preempt-notice":
                # proactive maintenance-event notice: the harness polls
                # it between iterations and checkpoints out of cadence
                # BEFORE any SIGTERM arrives — the run itself continues
                from .parallel import multihost

                print(
                    f"[failsafe] injected preemption notice at {where} "
                    "(fault plan)", flush=True,
                )
                multihost.request_preemption_notice(
                    f"injected at {where} (fault plan)"
                )
            elif f.kind == "peer-lost":
                # simulated coordination-service peer-death report on
                # THIS rank: the next barrier/heartbeat refuses with a
                # typed PeerLostError — the detection path a real dead
                # peer drives, minus the dead peer
                from .parallel import multihost

                print(
                    f"[failsafe] injected peer-loss report at {where} "
                    "(fault plan)", flush=True,
                )
                multihost.simulate_peer_loss(
                    f"injected at {where} (fault plan)"
                )
            elif f.kind == "desync":
                # poison THIS rank's collective-lockstep ledger: one
                # phantom record is indistinguishable from having
                # dispatched a collective the peers never will, without
                # actually wedging a real collective (which could only
                # end in a watchdog timeout — the exact failure mode
                # the ledger exists to replace with a typed error)
                from .lint import contracts as lint_contracts

                led = lint_contracts.ledger()
                armed = ("armed" if led is not None else
                         "NOT armed — undetectable without validate=full")
                print(
                    f"[failsafe] injected collective desync at {where} "
                    f"(fault plan; ledger {armed})",
                    flush=True,
                )
                if led is not None:
                    led.record("desync-fault", -1, where)
            elif f.kind == "sigterm":
                # real preemption notice: the platform's SIGTERM, aimed
                # at ourselves — exercises the harness's checkpoint-
                # then-exit handler end to end (handler sets the flag;
                # the driver commits a checkpoint at the iteration
                # boundary and raises PreemptionError)
                print(
                    f"[failsafe] injected SIGTERM at {where} (fault "
                    "plan)", flush=True,
                )
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "kill":
                if self.kill_mode == "raise":
                    raise PreemptionError(
                        f"injected preemption at {where} (fault plan, "
                        "kill_mode=raise)"
                    )
                print(
                    f"[failsafe] injected preemption at {where} — "
                    f"exiting with code {KILL_EXIT_CODE}",
                    flush=True,
                )
                os._exit(KILL_EXIT_CODE)
        return state


# ---------------------------------------------------------------------------
# atomic checkpoint / resume
# ---------------------------------------------------------------------------

# resume-safe option fields, excluded from the compatibility fingerprint:
# they steer reporting, scheduling or the failsafe machinery itself, not
# the adaptation trajectory from a given state. `niter` is excluded by
# design: extending/shortening the remaining iterations is a legitimate
# resume (the checkpoint records which iteration it holds).
# `mem_budget_mb` is a per-machine resource knob (auto-derived when
# unset), not a trajectory option.
_FINGERPRINT_EXCLUDE = frozenset({
    "verbose", "niter", "checkpoint_dir", "checkpoint_every", "faults",
    "mem_budget_mb", "validate", "validate_every", "recovery_attempts",
    "checkpoint_keep", "watchdog_timeout", "checkpoint_store",
    "checkpoint_async",
    # kernels is a backend-selection knob (Pallas vs lax reference, the
    # same computation to documented tolerance), like the platform the
    # run executes on — which was never fingerprinted either
    "kernels",
    # nparts is a RESOURCE layout, not a trajectory option, under
    # elastic resume: a checkpoint taken at one shard count may be
    # re-cut onto another (the drivers merge + re-partition through
    # parallel/distribute when the counts differ), exactly like the
    # world size it used to travel with
    "nparts",
    # balance_band tunes WHERE work lives (the closed-loop rebalance
    # trigger), a resource-layout knob like nparts: a resume may widen
    # or narrow the band without invalidating the checkpointed mesh
    "balance_band",
    # govern arms the closed-loop run governor (parmmg_tpu.control) —
    # a budget/termination controller like niter, which was never
    # fingerprinted: arming or disarming control on a resume is a
    # legitimate operator decision, not a different trajectory from
    # the checkpointed state
    "govern",
})

_MESH_DATA_FIELDS = tuple(
    f.name for f in dataclasses.fields(Mesh) if not f.metadata.get("static")
)


def options_fingerprint(opts) -> Tuple[str, Dict[str, str]]:
    """(sha256 digest, field->repr dict) over the trajectory-relevant
    option fields — the checkpoint compatibility key."""
    fields = {
        f.name: repr(getattr(opts, f.name))
        for f in dataclasses.fields(opts)
        if f.name not in _FINGERPRINT_EXCLUDE
    }
    blob = json.dumps(fields, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest(), fields


def _histo_to_json(h) -> Optional[dict]:
    if h is None:
        return None
    out = {}
    for f in dataclasses.fields(h):
        v = np.asarray(jax.device_get(getattr(h, f.name)))
        out[f.name] = v.tolist()
    return out


def _histo_from_json(d: Optional[dict]):
    if d is None:
        return None
    from .ops.quality import QualityHisto

    return QualityHisto(**{k: jnp.asarray(np.asarray(v)) for k, v in
                           d.items()})


def _mesh_arrays(mesh: Mesh, prefix: str) -> Dict[str, np.ndarray]:
    return {
        prefix + name: np.asarray(jax.device_get(getattr(mesh, name)))
        for name in _MESH_DATA_FIELDS
    }


def _mesh_static(mesh: Mesh) -> dict:
    return dict(field_ncomp=list(mesh.field_ncomp), met_set=mesh.met_set)


def _mesh_from_arrays(arrs, prefix: str, static: dict) -> Mesh:
    return Mesh(
        **{name: jnp.asarray(arrs[prefix + name])
           for name in _MESH_DATA_FIELDS},
        field_ncomp=tuple(static["field_ncomp"]),
        met_set=bool(static["met_set"]),
    )


@dataclasses.dataclass
class ResumeState:
    """What `Checkpointer.load` hands back to a driver."""

    it: int                      # last completed iteration
    meshes: Dict[str, Mesh]      # "mesh" (+ "old" when fields ride along)
    history: List[dict]
    emult: float
    meta: dict                   # hausd, qual_in, icap, presize_skipped...
    # how many processes wrote the loaded checkpoint — != the current
    # world size marks an ELASTIC resume (the state was re-concatenated
    # from the source world's shard files)
    source_world: int = 1

    @property
    def mesh(self) -> Mesh:
        return self.meshes["mesh"]


def _digest_arrays(arrs: Dict[str, np.ndarray]) -> str:
    """Deterministic content digest of a checkpoint array dict (name +
    dtype + shape + bytes, sorted keys) — what the rank-0 manifest
    records per rank and what resume re-verifies."""
    h = hashlib.sha256()
    for k in sorted(arrs):
        a = np.ascontiguousarray(np.asarray(arrs[k]))
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _rank_rows(nrows: int, world: int, rank: int) -> Tuple[int, int]:
    """Contiguous shard-row range process `rank` checkpoints (shards
    are laid over `jax.devices()` in process order, so contiguous
    chunks follow device ownership)."""
    return rank * nrows // world, (rank + 1) * nrows // world


def _proc_of(name: str) -> Optional[int]:
    """Rank of a per-rank shard file name (``ckpt_*.proc<r>.npz``), or
    None for the manifest / single-file npz."""
    if not name.endswith(".npz"):
        return None
    stem = name[:-4]
    i = stem.rfind(".proc")
    if i < 0 or not stem[i + 5:].isdigit():
        return None
    return int(stem[i + 5:])


class Checkpointer:
    """Per-iteration atomic checkpoints through a pluggable store.

    All I/O goes through an `io.ckpt_store.CheckpointStore` (default:
    `LocalFSStore` over ``checkpoint_dir`` — the original POSIX
    tmp+rename layout; ``AdaptOptions.checkpoint_store`` selects an
    object store with GCS put semantics instead). Every store op runs
    under bounded retry + backoff + per-op timeout; what follows
    describes the PROTOCOL, which is backend-independent because it
    relies only on atomic whole-object puts and manifest-last ordering.

    Single-process layout: ``ckpt_<it:05d>.npz`` (exact mesh arrays,
    full capacity — restoring reproduces the running state bit for bit,
    capacities included) then ``ckpt_<it:05d>.json`` (iteration,
    options fingerprint, sweep state, history, auxiliary metadata) as
    the LAST object — the json is the commit token, so a kill can
    never leave a readable-but-truncated checkpoint.

    Multi-process (``world > 1``, the per-rank restart state of the
    reference's node-scale runs): each process writes only its shard
    rows as ``ckpt_<it:05d>.proc<rank>.npz``; after a coordination
    ``barrier`` confirms every rank's data object is published, rank 0
    writes the json manifest (world size, per-rank content digests,
    which mesh keys are sharded) and a second barrier releases the
    world — a kill at ANY point therefore leaves either the old or the
    new checkpoint complete, never a torn one.

    **Elastic resume**: `load` accepts a manifest written by ANY world
    size — every process digest-verifies all source shard files and
    re-concatenates the replicated host state (world size is a resource
    layout, not a trajectory option; the drivers re-cut when the shard
    count itself changed). The hard :class:`CheckpointMismatchError`
    refusal remains ONLY for an options-fingerprint mismatch; an
    unreadable or digest-failing newest checkpoint falls back to the
    previous one.

    **Async staging** (`stage` / `commit_pending` / `drain`, driven by
    the harness under ``checkpoint_async``): the device→host snapshot
    happens in `stage` on the caller's thread (each epoch owns its host
    arrays — the double buffer), serialization + data-object puts run
    on a background writer thread, and the caller blocks only in
    `commit_pending` — i.e. at the NEXT checkpoint, on the previous
    epoch's commit. `overlap_s` accumulates writer time hidden behind
    compute (the ``ckpt_overlap_s`` BENCH series).

    GC: the newest `keep` committed checkpoints are retained. Pruning
    is RANK-SCOPED so concurrent GC on a shared FS cannot race another
    rank's in-flight write: rank r removes only its own
    ``ckpt_*.proc<r>.npz`` objects; rank 0 additionally removes
    manifests, single-file npzs and stale proc files of ranks outside
    the current world (elastic leftovers). Concurrent deletes are
    tolerated (a missing object is success).
    """

    def __init__(self, dirpath: Optional[str], opts, driver: str,
                 every: int = 1, keep: int = 2,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 barrier=None, store=None, fault_cb=None):
        from .io import ckpt_store

        self.dir = dirpath
        self.driver = driver
        self.every = max(int(every), 1)
        self.keep = max(int(keep), 1)
        self.rank = jax.process_index() if rank is None else int(rank)
        self.world = jax.process_count() if world is None else int(world)
        self._barrier = barrier if barrier is not None else (
            lambda tag: None
        )
        self.fingerprint, self.fields = options_fingerprint(opts)
        if store is None:
            store = getattr(opts, "checkpoint_store", None)
        self.store = ckpt_store.make_store(store, dirpath,
                                           fault_cb=fault_cb)
        # async staging state: at most ONE epoch in flight
        self._staged = None          # (it, thread, box, commit_main)
        self.overlap_s = 0.0

    # -- naming ----------------------------------------------------------
    def _name(self, it: int) -> str:
        return f"ckpt_{it:05d}"

    def _known(self) -> List[int]:
        its = []
        for name in self.store.list():
            if name.startswith("ckpt_") and name.endswith(".json"):
                try:
                    its.append(int(name[5:-5]))
                except ValueError:
                    pass
        return sorted(its)

    # -- GC ---------------------------------------------------------------
    def _prunable(self, name: str) -> bool:
        if name.endswith(f".proc{self.rank}.npz"):
            return True
        if self.rank != 0:
            return False
        r = _proc_of(name)
        if r is None:
            return True          # manifest or single-file npz: rank 0's
        return r >= self.world   # stale rank of a previous (larger) world

    def _prune(self) -> None:
        """Retain only the newest `keep` committed checkpoints. Runs
        after the commit barrier — a kill mid-prune can only lose
        already-superseded state, which `load` skips. Rank-scoped (see
        class docstring) so no rank ever unlinks an object another live
        rank may be re-publishing. Epochs are judged against the oldest
        RETAINED committed epoch rather than by enumerating manifests:
        once rank 0 deletes an old manifest, the other ranks must still
        recognize that epoch's data files as superseded (epoch ids are
        monotone, so anything older than the retained window is dead —
        committed or orphaned — while anything newer is in flight and
        protected)."""
        known = self._known()
        if len(known) < self.keep:
            return
        threshold = known[-self.keep]
        for name in self.store.list():
            if not (name.startswith("ckpt_") and self._prunable(name)):
                continue
            digits = name[5:].split(".", 1)[0]
            if digits.isdigit() and int(digits) < threshold:
                self.store.delete(name)

    # -- save ------------------------------------------------------------
    def due(self, it: int) -> bool:
        return (it + 1) % self.every == 0

    def _prepare(self, it: int, meshes: Dict[str, Mesh], *, history,
                 emult, meta, aux_arrays):
        """Snapshot + plan one checkpoint epoch. Device→host transfer
        happens HERE, on the caller's thread (the staged epoch owns its
        host arrays); what returns is pure host work:
        ``(objs, tail, commit)`` where `objs` is this rank's data
        objects ([(name, array-dict)]), `tail` runs on the WRITER
        thread after the puts (collective-free commit work: the
        world-1 manifest + prune), and `commit` runs on the CALLER
        thread (the multi-process barrier/manifest/barrier/prune
        sequence — collectives must never run on a worker thread)."""
        base = self._name(it)
        aux = {
            k: np.asarray(jax.device_get(v))
            for k, v in (aux_arrays or {}).items()
        }
        doc = dict(
            format=CHECKPOINT_FORMAT,
            driver=self.driver,
            it=int(it),
            fingerprint=self.fingerprint,
            options=self.fields,
            emult=float(emult),
            history=list(history),
            meshes={key: _mesh_static(m) for key, m in meshes.items()},
            aux=sorted(aux),
            meta=dict(meta or {}),
            world=self.world,
        )
        full = {
            key: _mesh_arrays(m, key + "/") for key, m in meshes.items()
        }

        def manifest_bytes() -> bytes:
            return json.dumps(doc, default=str).encode()

        if self.world == 1:
            arrs: Dict[str, np.ndarray] = {}
            for fa in full.values():
                arrs.update(fa)
            for k, v in aux.items():
                arrs["aux/" + k] = v

            def tail():
                # no collectives in a 1-process world: the writer can
                # publish the commit token and GC itself, so an async
                # epoch is durable as soon as the writer finishes
                self.store.publish(base + ".json", manifest_bytes())
                self._prune()

            return [(base + ".npz", arrs)], tail, (lambda: None)

        sharded = sorted(
            key for key, m in meshes.items() if m.vert.ndim == 3
        )
        doc["sharded"] = sharded

        def rank_arrays(r: int) -> Dict[str, np.ndarray]:
            arrs: Dict[str, np.ndarray] = {}
            for key, fa in full.items():
                if key in sharded:
                    nrows = fa[key + "/vert"].shape[0]
                    lo, hi = _rank_rows(nrows, self.world, r)
                    arrs.update({k: v[lo:hi] for k, v in fa.items()})
                elif r == 0:
                    # replicated (non-stacked) state rides with rank 0
                    arrs.update(fa)
            if r == 0:
                for k, v in aux.items():
                    arrs["aux/" + k] = v
            return arrs

        own = rank_arrays(self.rank)

        def commit():
            # every rank's data object is durable before the commit
            # record exists — the manifest can never name a missing
            # shard file. The host state is replicated-deterministic
            # (`models/distributed` contract), so rank 0 computes every
            # rank's slice digest locally.
            self._barrier(f"ckpt-data-{it}")
            if self.rank == 0:
                doc["digests"] = {
                    str(r): _digest_arrays(
                        own if r == self.rank else rank_arrays(r)
                    )
                    for r in range(self.world)
                }
                # the publish runs inside the store's own _op
                # retry/timeout envelope (PMMGTPU_CKPT_TIMEOUT), and
                # peers' ckpt-commit barrier is watchdog-bounded: a
                # wedge ends typed, not hung
                # parmmg-lint: disable=PML015 -- bounded by the store's _op timeout envelope; peers' barrier has the watchdog
                self.store.publish(base + ".json", manifest_bytes())
            # no rank proceeds (and possibly dies mid-next-iteration)
            # until the manifest is published: old and new are both
            # complete here
            self._barrier(f"ckpt-commit-{it}")
            self._prune()

        return (
            [(f"{base}.proc{self.rank}.npz", own)], (lambda: None), commit
        )

    def save(self, it: int, meshes: Dict[str, Mesh], *, history, emult,
             meta: Optional[dict] = None,
             aux_arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Synchronous save: snapshot, serialize, put, commit — the
        caller returns only when the epoch is durable."""
        from .io import ckpt_store

        objs, tail, commit = self._prepare(
            it, meshes, history=history, emult=emult, meta=meta,
            aux_arrays=aux_arrays,
        )
        t0 = time.perf_counter()
        for name, arrs in objs:
            self.store.put(name, ckpt_store.npz_bytes(arrs))
        tail()
        commit()
        self._note_commit(it, mode="sync",
                          seconds=time.perf_counter() - t0)

    def _note_commit(self, it: int, mode: str, seconds: float) -> None:
        """Timeline + counter record of a durable checkpoint commit —
        what a post-mortem needs to know survived."""
        obs_trace.emit_event("checkpoint_commit", it=int(it), mode=mode,
                             seconds=round(seconds, 4))
        obs_metrics.registry().counter("ckpt/commits").inc()

    # -- async staging ----------------------------------------------------
    def stage(self, it: int, meshes: Dict[str, Mesh], *, history, emult,
              meta: Optional[dict] = None,
              aux_arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Asynchronous save: the device→host snapshot happens now (so
        the adapt loop may mutate the live state immediately), but
        serialization + data puts run on a background writer thread.
        At most one epoch is in flight — staging a new epoch first
        commits the previous one (the ONLY point the caller blocks)."""
        from .io import ckpt_store

        if self._staged is not None:
            self.commit_pending()
        objs, tail, commit = self._prepare(
            it, meshes, history=history, emult=emult, meta=meta,
            aux_arrays=aux_arrays,
        )
        box: dict = {}

        def _write():
            t0 = time.perf_counter()
            try:
                for name, arrs in objs:
                    self.store.put(name, ckpt_store.npz_bytes(arrs))
                tail()
            except BaseException as e:
                box["error"] = e
            finally:
                box["busy"] = time.perf_counter() - t0

        t = threading.Thread(
            target=_write, name=f"parmmg-ckpt-writer:{it}", daemon=True
        )
        t.start()
        self._staged = (it, t, box, commit)

    def commit_pending(self) -> None:
        """Block until the staged epoch (if any) is fully committed.
        Writer failures surface here as the typed store error
        (`io.ckpt_store.CheckpointIOError`); the multi-process commit
        (barriers + manifest) runs on THIS thread. Accumulates the
        writer time hidden behind compute into `overlap_s`."""
        st = self._staged
        if st is None:
            return
        it, t, box, commit = st
        t0 = time.perf_counter()
        t.join()
        waited = time.perf_counter() - t0
        self._staged = None
        self.overlap_s += max(0.0, box.get("busy", 0.0) - waited)
        if "error" in box:
            raise box["error"]
        commit()
        self._note_commit(it, mode="async",
                          seconds=box.get("busy", 0.0))

    def drain(self) -> None:
        """Flush the staging queue: after this, no checkpoint state is
        in flight — the SIGTERM/preemption exit path and normal run
        teardown both end through here."""
        self.commit_pending()

    # -- load ------------------------------------------------------------
    def load(self) -> Optional[ResumeState]:
        """Most recent compatible checkpoint, or None when the store
        holds none. A checkpoint written under different TRAJECTORY
        options RAISES :class:`CheckpointMismatchError` (silent restart
        would discard the operator's intent); a world-size difference
        is an ELASTIC resume — all source shard files are read and
        digest-verified and the replicated host state re-concatenated
        (`ResumeState.source_world` records the origin). An unreadable
        or digest-failing newest checkpoint falls back to the previous
        one."""
        last_err = None
        for it in reversed(self._known()):
            base = self._name(it)
            try:
                doc = json.loads(self.store.get(base + ".json").decode())
            except (OSError, ValueError) as e:
                last_err = e
                continue
            if doc.get("format") != CHECKPOINT_FORMAT \
                    or doc.get("driver") != self.driver:
                continue
            if doc["fingerprint"] != self.fingerprint:
                diff = sorted(
                    k for k in set(doc.get("options", {})) | set(self.fields)
                    if doc.get("options", {}).get(k) != self.fields.get(k)
                )
                raise CheckpointMismatchError(
                    f"checkpoint {base}.json was written under "
                    f"incompatible options (differing fields: {diff}); "
                    "refusing to resume — delete the checkpoint "
                    "directory or restore the original options"
                )
            ck_world = int(doc.get("world", 1))
            try:
                arrs = self._load_arrays(base, doc)
            except (OSError, ValueError, KeyError) as e:
                last_err = e
                continue
            meshes = {
                key: _mesh_from_arrays(arrs, key + "/", static)
                for key, static in doc["meshes"].items()
            }
            meta = dict(doc.get("meta", {}))
            meta["aux_arrays"] = {
                k: arrs["aux/" + k] for k in doc.get("aux", ())
            }
            # timeline record of the recovery: a chaos post-mortem
            # chain ends fault → detection → RESUME, and this is the
            # only place that knows which epoch the run came back from
            obs_trace.emit_event(
                "resume", it=int(doc["it"]), source_world=ck_world,
                world=self.world,
            )
            obs_metrics.registry().counter("ckpt/resumes").inc()
            return ResumeState(
                it=int(doc["it"]),
                meshes=meshes,
                history=list(doc["history"]),
                emult=float(doc["emult"]),
                meta=meta,
                source_world=ck_world,
            )
        if last_err is not None:
            import warnings

            warnings.warn(
                f"no readable checkpoint in {self.dir or self.store} "
                f"(last error: {last_err}); starting fresh",
                stacklevel=2,
            )
        return None

    def _load_arrays(self, base: str, doc: dict) -> Dict[str, np.ndarray]:
        """The full array dict of one committed checkpoint: the single
        npz (source world 1) or every SOURCE rank's shard file
        digest-verified and re-concatenated in rank order (== the
        original replicated host state). Every process reads every
        file — which is also exactly what elastic resume needs: the
        re-concatenation is indifferent to how many processes are
        reading now vs. how many wrote."""
        from .io import ckpt_store

        ck_world = int(doc.get("world", 1))
        if ck_world == 1:
            return ckpt_store.npz_arrays(self.store.get(base + ".npz"))
        per_rank: List[Dict[str, np.ndarray]] = []
        digests = doc.get("digests", {})
        for r in range(ck_world):
            arrs = ckpt_store.npz_arrays(
                self.store.get(f"{base}.proc{r}.npz")
            )
            want = digests.get(str(r))
            if want is not None and _digest_arrays(arrs) != want:
                raise ValueError(
                    f"checkpoint shard {base}.proc{r}.npz fails its "
                    "manifest digest (corrupt or torn write)"
                )
            per_rank.append(arrs)
        sharded = set(doc.get("sharded", ()))
        out: Dict[str, np.ndarray] = {}
        for key in doc["meshes"]:
            prefix = key + "/"
            if key in sharded:
                for name in _MESH_DATA_FIELDS:
                    out[prefix + name] = np.concatenate(
                        [per_rank[r][prefix + name]
                         for r in range(ck_world)], axis=0,
                    )
            else:
                out.update({
                    k: v for k, v in per_rank[0].items()
                    if k.startswith(prefix)
                })
        for k in doc.get("aux", ()):
            out["aux/" + k] = per_rank[0]["aux/" + k]
        return out


# ---------------------------------------------------------------------------
# the harness the drivers hold
# ---------------------------------------------------------------------------


class FailsafeHarness:
    """One driver run's failsafe state: validator + fault plan +
    checkpointer + the bounded-recovery budget + the multi-host
    liveness machinery (heartbeat watchdog, SIGTERM checkpoint-then-
    exit). Built by :func:`harness`; every hook is a no-op when the
    corresponding feature is off, so the drivers call
    unconditionally."""

    def __init__(self, opts, driver: str,
                 checkpoint_dir: Optional[str] = None):
        self.validator = PhaseValidator(
            level=getattr(opts, "validate", "basic") or "off",
            every=int(getattr(opts, "validate_every", 1) or 1),
        )
        # collective-lockstep ledger: validate="full" arms schedule
        # recording in `parallel.multihost._coll_span`; any other level
        # leaves the hook a single None-check (zero steady overhead)
        self._ledger_armed = False
        if self.validator.level == "full":
            from .lint import contracts as lint_contracts

            lint_contracts.install_ledger()
            self._ledger_armed = True
        self.faults = FaultPlan.resolve(opts)
        self.attempts = int(getattr(opts, "recovery_attempts", 0) or 0)
        self.watchdog = getattr(opts, "watchdog_timeout", None)
        self.preempt_requested = False
        self._armed = False
        self._prev_sigterm = None
        ckdir = checkpoint_dir or getattr(opts, "checkpoint_dir", None)
        store = getattr(opts, "checkpoint_store", None)
        # async snapshot staging: opt-in per options or environment —
        # the env knob lets the smoke/chaos harnesses flip it without
        # re-plumbing every entry point
        self.async_staging = bool(
            getattr(opts, "checkpoint_async", False)
            or os.environ.get("PMMGTPU_ASYNC_CKPT")
        )
        self.ckpt = (
            Checkpointer(
                ckdir, opts, driver,
                every=getattr(opts, "checkpoint_every", 1),
                keep=getattr(opts, "checkpoint_keep", 2) or 2,
                barrier=self._barrier,
                store=store,
                fault_cb=self.faults.io_fault,
            )
            if (ckdir or store is not None) else None
        )
        # elastic world supervisor (PMMGTPU_ELASTIC env contract):
        # armed only with a checkpoint store to coordinate through —
        # a reformation without a durable epoch to resume from would
        # just be a crash with extra steps
        self.elastic = None
        if self.ckpt is not None:
            from .parallel import elastic

            self.elastic = elastic.coordinator_from_env(self.ckpt.store)

    # -- multi-host liveness --------------------------------------------
    def _barrier(self, tag: str) -> None:
        from .parallel import multihost

        multihost.barrier(tag, timeout=self.watchdog)

    def heartbeat(self, it: int, phase: str = "iteration") -> None:
        """Collective liveness check at a phase boundary: all processes
        must arrive within ``opts.watchdog_timeout`` seconds or the
        wait raises :class:`PeerLostError` — a killed peer becomes a
        typed failure instead of an indefinite hang in the next
        collective. No-op single-process or with no timeout configured
        (an unbounded barrier would reintroduce the hang)."""
        if self.watchdog is None:
            return
        from .parallel import multihost

        multihost.barrier(f"hb:{phase}:{it}", timeout=self.watchdog)

    # -- preemption (SIGTERM -> checkpoint-then-exit) -------------------
    def arm_preemption(self) -> None:
        """Install the SIGTERM handler (main thread only, and only when
        checkpointing is configured — without a checkpoint there is
        nothing to commit, so the platform default stays). The handler
        only sets a flag; the driver loop commits a checkpoint at the
        next iteration boundary and raises :class:`PreemptionError`,
        mirroring the injected ``kill`` fault's semantics but with the
        grace window real preemption notices give."""
        if self.ckpt is None or self._armed:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        self._prev_sigterm = signal.signal(
            signal.SIGTERM, self._on_sigterm
        )
        self._armed = True

    def _on_sigterm(self, signum, frame) -> None:
        self.preempt_requested = True
        # a flag write plus one appended timeline line — both safe in
        # signal-handler context, and the only record of WHEN the
        # platform's SIGTERM landed relative to the iteration spans
        obs_trace.emit_event("sigterm_received")

    def disarm_preemption(self) -> None:
        if self._armed:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._armed = False

    @property
    def rollback_enabled(self) -> bool:
        return (
            self.validator.active or self.attempts > 0
            or self.ckpt is not None or bool(self.faults.faults)
        )

    def snapshot(self, state):
        return snapshot(state) if self.rollback_enabled else None

    def validate(self, state, it: int, *, comm=None,
                 phase: str = "iteration") -> None:
        self.validator.check(state, it, comm=comm, phase=phase)

    def validate_sharded(self, state, dmesh, it: int, *,
                         phase: str = "sweep") -> None:
        """Device-resident basic validation of a sharded stacked state
        (the SPMD sweep path) — see `PhaseValidator.check_sharded`."""
        self.validator.check_sharded(state, dmesh, it, phase=phase)

    def verify_collectives(self, it: int,
                           phase: str = "iteration") -> None:
        """Collective-lockstep check at a phase boundary (the runtime
        half of the static PML012 rule): under ``validate="full"`` and
        at the validator's cadence, world-compare the per-rank ledger
        digests and raise :class:`CollectiveDivergenceError` on every
        rank when the schedules diverged. Contains a collective when it
        runs, so the drivers call it only at boundaries every rank
        reaches unconditionally (right next to `elastic_poll`). No-op
        at any other validate level, single-process, or off-cadence."""
        if not self._ledger_armed or not self.validator.due(it):
            return
        from .lint import contracts as lint_contracts

        lint_contracts.verify_ledger(
            it, phase=phase, timeout=self.watchdog
        )

    def fire(self, it: int, phase: str, state):
        """Fire pending faults at this boundary; when one poisoned the
        state (``nan``), validate IMMEDIATELY (out of cadence) so the
        injection is caught at its own boundary instead of being
        churned through downstream phases first. No fault pending →
        exactly the no-op path (no extra device work)."""
        before = sum(f.fired for f in self.faults.faults)
        state = self.faults.fire(it, phase, state)
        if sum(f.fired for f in self.faults.faults) != before:
            self.validator.check(state, it, phase=phase, force=True)
        return state

    def resume(self) -> Optional[ResumeState]:
        return self.ckpt.load() if self.ckpt is not None else None

    def preempt_notice(self) -> bool:
        """A maintenance-event preemption NOTICE is pending (the
        `parallel.multihost` file/callback hook, or the injected
        ``preempt-notice`` fault): the drivers force an out-of-cadence
        checkpoint at the next iteration boundary so the state is
        durable BEFORE the SIGTERM lands. Unlike `preempt_requested`
        this does not end the run — it makes the eventual kill cheap.
        Polled only when checkpointing is configured (without a
        checkpoint there is nothing to commit proactively)."""
        if self.ckpt is None:
            return False
        from .parallel import multihost

        return multihost.preemption_notice()

    # -- elastic world reformation --------------------------------------
    def elastic_poll(self, it: int):
        """World-agreed reform vote at an iteration boundary (see
        `parallel.elastic.ElasticCoordinator.poll`). Contains a
        collective when armed in a multi-process world, so EVERY rank
        must reach this call at the same boundary — the distributed
        loop calls it unconditionally right before its checkpoint
        decision. Returns None (keep adapting) or the agreed
        :class:`~parmmg_tpu.parallel.elastic.ReformDecision`; no-op
        (None) when elasticity is not armed."""
        if self.elastic is None:
            return None
        return self.elastic.poll(it, timeout=self.watchdog)

    def elastic_exit(self, decision) -> BaseException:
        """Seal one agreed reformation AFTER the reform checkpoint is
        fully committed (callers drain async staging first): writes
        this rank's exit ack (the downtime clock) and returns the typed
        error to leave the driver with — PreemptionError for the
        departing rank, WorldReformError for survivors."""
        self.elastic.ack_exit(decision)
        return self.elastic.error_for(decision)

    def save(self, it: int, meshes: Dict[str, Mesh], *, history, emult,
             meta=None, aux_arrays=None, force: bool = False) -> None:
        """Checkpoint when due — or unconditionally with ``force``
        (the preemption path commits out of cadence: the SIGTERM grace
        window must not be spent waiting for the next due iteration).
        Under async staging the snapshot is taken now but committed at
        the NEXT save (or at `finish`) — except on the preemption path,
        which drains immediately: an exit must leave a committed
        checkpoint, not a staged one."""
        if self.ckpt is None or not (force or self.ckpt.due(it)):
            return
        if self.async_staging:
            self.ckpt.stage(it, meshes, history=history, emult=emult,
                            meta=meta, aux_arrays=aux_arrays)
            if self.preempt_requested:
                self.ckpt.drain()
            return
        self.ckpt.save(it, meshes, history=history, emult=emult,
                       meta=meta, aux_arrays=aux_arrays)

    def finish(self) -> None:
        """Drain the async staging queue: serialize, store and COMMIT
        any staged epoch before control returns. The drivers call this
        on every exit path (normal completion, typed failure,
        preemption) — the SIGTERM contract is that the process never
        exits with checkpoint state still in flight."""
        if self.ckpt is not None:
            self.ckpt.drain()
        if self._ledger_armed:
            from .lint import contracts as lint_contracts

            lint_contracts.uninstall_ledger()
            self._ledger_armed = False

    @property
    def ckpt_overlap_s(self) -> float:
        """Checkpoint wall time overlapped with compute so far (async
        staging only; 0.0 otherwise) — recorded into BENCH JSON."""
        return self.ckpt.overlap_s if self.ckpt is not None else 0.0

    def post_iteration(self, it: int, state, history: List[dict]):
        """Fire ``post``-phase faults after the checkpoint commit.
        Raising kinds (retrace/overflow) are absorbed here — the
        iteration's good state is already committed, so recovery is
        record + clear-caches + continue, not a re-run."""
        try:
            return self.faults.fire(it, "post", state)
        except (RetraceError, CapacityError) as e:
            history.append(dict(
                iter=it, phase="post", failure=str(e),
                error=type(e).__name__, recovered=True,
            ))
            if isinstance(e, RetraceError):
                jax.clear_caches()
            return state


def harness(opts, driver: str,
            checkpoint_dir: Optional[str] = None) -> FailsafeHarness:
    """The failsafe harness for one driver run (see
    :class:`FailsafeHarness`)."""
    return FailsafeHarness(opts, driver, checkpoint_dir=checkpoint_dir)
