"""Admission control: size classes, header peeks, the bounded queue.

Why size classes at all: the whole port lives in the static-shape
regime — `remesh_sweeps` and every other device program is compile-
cached on the mesh's CAPACITIES (`models.adapt`, PR-1's memoized jit
factories). A server that loaded each tenant mesh at its natural
``counts × headroom`` capacities would recompile per job and serve
nothing but XLA. Bucketing jobs into a small table of padded size
classes makes every job in a class share one set of compiled
executables: the batch IS the shared compile cache, and the per-class
warm-boot (`JobServer.warmup`) makes even the first request
compile-free.

Admission is where the two typed refusals of the backpressure contract
live:

- :class:`~parmmg_tpu.service.jobs.QueueFullError` — the bounded queue
  is at capacity (transient; the client retries);
- :class:`~parmmg_tpu.service.jobs.JobTooLargeError` — no class can
  hold ``counts × margin`` (permanent for this input; the job is
  journaled ``rejected``).

The classifier reads entity COUNTS, not the mesh: `peek_counts` scans
the medit/VTU header (``Vertices``/``Tetrahedra`` sections,
``NumberOfPoints``/``NumberOfCells`` attributes) so an oversized
submission is refused for the cost of a text scan, never a device
allocation. The ``margin`` (default 2.0) is the growth headroom a job
keeps INSIDE its class before `adapt`'s capacity ladder would have to
grow past the class caps and break compile sharing; it deliberately
exceeds `Mesh.from_numpy`'s 1.5 load headroom, so a class-admitted
mesh always loads strictly below its class capacities.
"""

from __future__ import annotations

import dataclasses
import os
import re
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from .jobs import (
    BadJobError,
    JobSpec,
    JobTooLargeError,
    QueueFullError,
    SloInfeasibleError,
)

# --- size classes ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SizeClass:
    """One padded capacity bucket: every job admitted here runs at
    EXACTLY these capacities, so every job here shares one compile."""

    name: str
    pcap: int
    tcap: int
    fcap: int
    ecap: int

    def holds(self, npoin: int, ntet: int, margin: float) -> bool:
        return (npoin * margin <= self.pcap
                and ntet * margin <= self.tcap)

    def caps(self) -> dict:
        return dict(pcap=self.pcap, tcap=self.tcap, fcap=self.fcap,
                    ecap=self.ecap)


#: default table, smallest first (the classifier picks the first fit).
#: Sized for the CPU test fixtures up through "a real small mesh";
#: production tables are a `JobServer(classes=...)` argument.
DEFAULT_CLASSES = (
    SizeClass("tiny", pcap=512, tcap=2048, fcap=512, ecap=512),
    SizeClass("small", pcap=2048, tcap=8192, fcap=2048, ecap=2048),
    SizeClass("medium", pcap=8192, tcap=32768, fcap=8192, ecap=8192),
)


def classify(npoin: int, ntet: int,
             classes: Iterable[SizeClass] = DEFAULT_CLASSES,
             margin: float = 2.0) -> SizeClass:
    """Smallest class holding ``counts × margin``, or the typed
    too-large refusal naming the largest class's capacities."""
    table = list(classes)
    for cls in table:
        if cls.holds(npoin, ntet, margin):
            return cls
    largest = table[-1]
    raise JobTooLargeError(
        f"mesh with {npoin} vertices / {ntet} tets exceeds every size "
        f"class (largest '{largest.name}': pcap {largest.pcap}, tcap "
        f"{largest.tcap}, margin {margin})",
        npoin=npoin, ntet=ntet, margin=margin,
        largest_class=largest.name,
        largest_pcap=largest.pcap, largest_tcap=largest.tcap,
    )


# --- header peeks ----------------------------------------------------------

_VTU_RE = re.compile(
    rb'NumberOfPoints\s*=\s*"(\d+)".*?NumberOfCells\s*=\s*"(\d+)"',
    re.DOTALL,
)


def _peek_medit(path: str) -> Tuple[int, int]:
    counts = {}
    want = {"vertices": "np", "tetrahedra": "nt"}
    with open(path, errors="replace") as f:
        pending = None
        for line in f:
            tok = line.strip()
            if pending is not None and tok:
                if tok.split()[0].lstrip("-").isdigit():
                    counts[pending] = int(tok.split()[0])
                pending = None
                if len(counts) == 2:
                    break
                continue
            if tok.lower() in want:
                pending = want[tok.lower()]
    if "np" not in counts or "nt" not in counts:
        raise ValueError(
            f"{path}: no Vertices/Tetrahedra sections in header scan"
        )
    return counts["np"], counts["nt"]


def _peek_vtu(path: str) -> Tuple[int, int]:
    with open(path, "rb") as f:
        head = f.read(65536)
    m = _VTU_RE.search(head)
    if not m:
        raise ValueError(f"{path}: no NumberOfPoints/NumberOfCells "
                         "attributes in header scan")
    return int(m.group(1)), int(m.group(2))


def peek_counts(path: str) -> Tuple[int, int]:
    """(npoin, ntet) from the file HEADER — the admission-time size
    check must not pay a full parse (let alone a device transfer) for
    a mesh it is about to refuse. Raises the typed
    :class:`BadJobError` when the input is missing or unscannable."""
    if not os.path.exists(path):
        raise BadJobError(f"input mesh not found: {path}", path=path)
    ext = os.path.splitext(path)[1].lower()
    try:
        if ext == ".vtu":
            return _peek_vtu(path)
        if ext in (".mesh", ".meshb"):
            if ext == ".meshb":
                # binary medit: the cheap text scan does not apply;
                # fall back to the real reader's header discipline
                from ..io import medit

                raw = medit.read_mesh(path)
                return len(raw.verts), len(raw.tets)
            return _peek_medit(path)
    except BadJobError:
        raise
    except Exception as e:
        raise BadJobError(
            f"unreadable input mesh {path}: {e}", path=path
        ) from e
    raise BadJobError(
        f"unknown mesh format {ext!r} for {path} (expected .mesh/"
        ".meshb/.vtu)", path=path, ext=ext,
    )


# --- SLO admission from PERF_DB history ------------------------------------

#: deadline = quote × margin when the client did not set one — derived
#: from DATA, not config (PMMGTPU_SLO_MARGIN overrides; 4x leaves room
#: for queueing plus the usual container wall-clock swing the serve
#: bench gates with --rel-floor 8)
SLO_MARGIN_ENV = "PMMGTPU_SLO_MARGIN"
SLO_MARGIN_DEFAULT = 4.0


def resolve_slo_margin(margin: Optional[float] = None) -> float:
    """Explicit margin, else PMMGTPU_SLO_MARGIN, else the default."""
    if margin is not None:
        return float(margin)
    raw = os.environ.get(SLO_MARGIN_ENV, "").strip()
    return float(raw) if raw else SLO_MARGIN_DEFAULT


def _default_platform() -> str:
    """The platform key quotes are looked up under — the same stamp
    the serve bench writes into its PERF_DB records
    (PMMGTPU_SLO_PLATFORM overrides for cross-platform quoting)."""
    env = os.environ.get("PMMGTPU_SLO_PLATFORM", "").strip()
    if env:
        return env
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "cpu"


class SloPolicy:
    """Per-size-class latency quotes from PERF_DB rung history, and the
    admission decision they drive.

    The serve bench commits ``jobs_per_min`` records under rung
    ``serve-<class>``; :func:`obs.history.quote` folds them with the
    SAME rolling-median/partial-skip baseline selection the perf gate
    uses, so the latency a client is promised at submit is exactly the
    history the gate holds the server to. Two decisions per job:

    - an explicit ``deadline_s`` below the quoted latency is refused
      typed (:class:`SloInfeasibleError`) at submit — better a refusal
      in milliseconds than a mid-run deadline after burning
      batch-mates' machine time;
    - a job WITHOUT a deadline gets ``quote × margin`` (plus the
      rung's recorded warmup as a cold-start allowance) as its
      data-derived default, so every admitted job runs under a
      deadline the measured history says is feasible.

    A class with no usable history quotes ``None`` and admission
    passes through unchanged — the policy arms itself as records
    accumulate, exactly like the perf gate."""

    def __init__(self, db, platform: Optional[str] = None,
                 margin: Optional[float] = None, window: int = 8):
        from ..obs import history as history_mod

        self._history = history_mod
        if isinstance(db, (str, os.PathLike)):
            self.records: List[dict] = history_mod.load_db(str(db))
        else:
            self.records = list(db or [])
        self.platform = platform or _default_platform()
        self.margin = resolve_slo_margin(margin)
        self.window = int(window)

    def quote(self, class_name: str) -> Optional[dict]:
        """Rolling-median latency quote for one size class, or None
        when the rung has no non-partial throughput history."""
        q = self._history.quote(
            self.records, self.platform, f"serve-{class_name}",
            window=self.window,
        )
        jm = q.get("jobs_per_min")
        if not jm or not jm.get("value"):
            return None
        latency_s = 60.0 / float(jm["value"])
        doc = dict(
            latency_s=round(latency_s, 3),
            jobs_per_min=round(float(jm["value"]), 3),
            baseline_n=int(jm["n"]),
            rung=f"serve-{class_name}", platform=self.platform,
        )
        if jm.get("wall_s") is not None:
            doc["wall_s"] = round(float(jm["wall_s"]), 3)
        if jm.get("warmup_s") is not None:
            doc["warmup_s"] = round(float(jm["warmup_s"]), 3)
        return doc

    def admit(self, spec: JobSpec, class_name: str) -> JobSpec:
        """Apply the SLO decision to an about-to-be-queued job:
        returns the spec (deadline defaulted from data when unset) or
        raises the typed refusal."""
        q = self.quote(class_name)
        if q is None:
            return spec
        if spec.deadline_s is not None:
            if float(spec.deadline_s) < q["latency_s"]:
                raise SloInfeasibleError(
                    f"job {spec.job_id}: deadline {spec.deadline_s}s is "
                    f"below the quoted '{class_name}' latency "
                    f"{q['latency_s']}s (rolling median of "
                    f"{q['baseline_n']} PERF_DB record(s)) — the run "
                    "would deadline mid-flight",
                    deadline_s=float(spec.deadline_s),
                    quoted_s=q["latency_s"],
                    baseline_n=q["baseline_n"],
                    size_class=class_name, platform=self.platform,
                )
            return spec
        # the quote is WARMED-executable throughput; a job that lands on
        # a cold class (solo runs, a restarted server replaying its
        # journal before warmup) pays the full compile first, so the
        # derived default adds the recorded warmup as a cold-start
        # allowance — explicit deadlines are still judged against the
        # raw latency, which is infeasible even warm
        derived = round(q["latency_s"] * self.margin
                        + q.get("warmup_s", 0.0), 3)
        return dataclasses.replace(spec, deadline_s=derived)


# --- the bounded queue -----------------------------------------------------


class AdmissionQueue:
    """Bounded FIFO of admitted ``(spec, size_class)`` pairs.

    ``take_batch`` pops the head job plus up to ``batch_max - 1``
    later jobs of the SAME class (a bucket shares one compile, so a
    batch must be class-homogeneous); jobs of other classes keep their
    relative order — head-of-line classes cannot starve the rest
    because the next ``take_batch`` starts from the new head."""

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    def offer(self, spec: JobSpec, cls: SizeClass) -> None:
        if len(self._q) >= self.cap:
            raise QueueFullError(
                f"admission queue at capacity ({self.cap}); resubmit "
                "after the backlog drains",
                queue_depth=len(self._q), queue_cap=self.cap,
            )
        self._q.append((spec, cls))

    def occupancy(self) -> Dict[str, int]:
        """Queued jobs per size-class name (the ``--status``
        endpoint's occupancy gauge; classes with no queued jobs are
        simply absent — the renderer zero-fills from the table)."""
        out: Dict[str, int] = {}
        for _spec, cls in self._q:
            out[cls.name] = out.get(cls.name, 0) + 1
        return out

    def push_front(self, items: List[Tuple[JobSpec, SizeClass]]) -> None:
        """Restore popped-but-unrun batch members to the queue head
        (drain interrupt) — their admission already paid the cap."""
        for item in reversed(items):
            self._q.appendleft(item)

    def remove(self, job_id: str) -> Optional[JobSpec]:
        """Remove a queued job (cancellation); None when not queued."""
        for i, (spec, _cls) in enumerate(self._q):
            if spec.job_id == job_id:
                del self._q[i]
                return spec
        return None

    def take_batch(self, batch_max: int) -> List[Tuple[JobSpec, SizeClass]]:
        if not self._q:
            return []
        head_spec, head_cls = self._q.popleft()
        batch = [(head_spec, head_cls)]
        rest: deque = deque()
        while self._q and len(batch) < batch_max:
            spec, cls = self._q.popleft()
            if cls.name == head_cls.name:
                batch.append((spec, cls))
            else:
                rest.append((spec, cls))
        rest.extend(self._q)
        self._q = rest
        return batch
