"""Prometheus status endpoint for the job server (``--status PORT``).

The serving loop already counts everything that matters into the
always-on metrics registry (``serve/*`` counters: submitted, done,
failed, refused_*, requeued, batches, ...). This module is the thin
scrape surface over it: :func:`status_text` renders those counters
plus the live queue picture (depth, per-size-class occupancy from
:meth:`~parmmg_tpu.service.admission.AdmissionQueue.occupancy`, the
draining flag) in Prometheus text exposition format 0.0.4, and
:class:`StatusServer` is a daemon-threaded stdlib ``http.server``
exposing it at ``/metrics`` (plus a trivial ``/healthz``) so
``tools/serve.py --status <port>`` can be scraped without touching
the serving loop. Pure stdlib — no client library, no new deps.
"""

from __future__ import annotations

import http.server
import re
import threading

from ..obs import metrics as obs_metrics

__all__ = ["status_text", "StatusServer"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """Registry key -> legal Prometheus metric name (``serve/done``
    -> ``parmmg_serve_done``)."""
    return "parmmg_" + _NAME_RE.sub("_", name)


def status_text(server) -> str:
    """Prometheus text-format snapshot of one
    :class:`~parmmg_tpu.service.server.JobServer`."""
    doc = obs_metrics.registry().to_doc()
    lines = []
    for key in sorted(doc.get("counters", {})):
        if not key.startswith("serve/"):
            continue
        name = _prom_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {doc['counters'][key]}")
    depth = _prom_name("serve/queue_depth")
    lines.append(f"# TYPE {depth} gauge")
    lines.append(f"{depth} {len(server.queue)}")
    occ = server.queue.occupancy()
    occ_name = _prom_name("serve/queue_occupancy")
    lines.append(f"# TYPE {occ_name} gauge")
    for cls in server.classes:
        lines.append(
            f'{occ_name}{{size_class="{cls.name}"}} '
            f"{occ.get(cls.name, 0)}"
        )
    drain = _prom_name("serve/draining")
    lines.append(f"# TYPE {drain} gauge")
    lines.append(f"{drain} {1 if server.draining else 0}")
    return "\n".join(lines) + "\n"


class StatusServer:
    """Daemon-threaded HTTP scrape endpoint for one job server.

    Binds immediately (``port=0`` picks an ephemeral port — read
    ``.port`` after construction), serves on a daemon thread after
    :meth:`start`, and never blocks the serving loop: every request
    renders a fresh :func:`status_text` snapshot."""

    def __init__(self, server, port: int = 0,
                 host: str = "127.0.0.1"):
        job_server = server

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.startswith("/healthz"):
                    body = b"ok\n"
                    ctype = "text/plain"
                else:
                    body = status_text(job_server).encode()
                    ctype = "text/plain; version=0.0.4"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrapes are not server events

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _Handler
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "StatusServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serve-status",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
