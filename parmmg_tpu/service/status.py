"""Prometheus status endpoints: job server AND live adaptation runs.

The serving loop already counts everything that matters into the
always-on metrics registry (``serve/*`` counters: submitted, done,
failed, refused_*, requeued, batches, ...). This module is the thin
scrape surface over it: :func:`status_text` renders those counters
plus the live queue picture (depth, per-size-class occupancy from
:meth:`~parmmg_tpu.service.admission.AdmissionQueue.occupancy`, the
draining flag) in Prometheus text exposition format 0.0.4, and
:class:`StatusServer` is a daemon-threaded stdlib ``http.server``
exposing it at ``/metrics`` (plus a trivial ``/healthz``). Pure
stdlib — no client library, no new deps.

Round 12 generalized the server over a *render callable*, so the same
endpoint also serves a bare ``adapt`` / ``adapt_distributed`` run:
:func:`run_status_text` renders the run-health picture (current
iteration/phase, per-operator acceptance counters, in-band fraction,
per-rank heartbeat age, drain-curve ETA) from the metrics registry +
`obs.health.run_state`, and :func:`serve_run_from_env` is the
``PMMGTPU_STATUS_PORT`` contract the drivers honor: set the env var
and any traced-or-not run serves ``/healthz`` + ``/metrics`` on that
port for its duration (multi-process runs bind ``port + rank`` so
every rank is scrapable; ``0`` picks an ephemeral port and prints it).
"""

from __future__ import annotations

import http.server
import os
import re
import threading
from typing import Callable, Optional

from ..obs import health as obs_health
from ..obs import metrics as obs_metrics

__all__ = [
    "status_text", "run_status_text", "StatusServer",
    "serve_run_from_env",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """Registry key -> legal Prometheus metric name (``serve/done``
    -> ``parmmg_serve_done``)."""
    return "parmmg_" + _NAME_RE.sub("_", name)


def status_text(server) -> str:
    """Prometheus text-format snapshot of one
    :class:`~parmmg_tpu.service.server.JobServer`."""
    doc = obs_metrics.registry().to_doc()
    lines = []
    for key in sorted(doc.get("counters", {})):
        if not key.startswith("serve/"):
            continue
        name = _prom_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {doc['counters'][key]}")
    depth = _prom_name("serve/queue_depth")
    lines.append(f"# TYPE {depth} gauge")
    lines.append(f"{depth} {len(server.queue)}")
    occ = server.queue.occupancy()
    occ_name = _prom_name("serve/queue_occupancy")
    lines.append(f"# TYPE {occ_name} gauge")
    for cls in server.classes:
        lines.append(
            f'{occ_name}{{size_class="{cls.name}"}} '
            f"{occ.get(cls.name, 0)}"
        )
    drain = _prom_name("serve/draining")
    lines.append(f"# TYPE {drain} gauge")
    lines.append(f"{drain} {1 if server.draining else 0}")
    return "\n".join(lines) + "\n"


# run-state scalars exported as gauges, with their endpoint names
_RUN_GAUGES = (
    ("iteration", "run/iteration"),
    ("sweep", "run/sweep"),
    ("in_band", "len/in_band"),
    ("active_fraction", "run/active_fraction"),
    ("drain_eta_sweeps", "run/drain_eta_sweeps"),
    ("heartbeat_age_s", "run/heartbeat_age_s"),
)

# registry counters a run scrape exports (operator acceptance + sweep
# progress — the live half of what obs_report renders post-mortem)
_RUN_COUNTER_PREFIXES = ("ops/", "sweeps", "recompiles/", "failsafe/")


def run_status_text() -> str:
    """Prometheus text-format snapshot of the CURRENT adaptation run in
    this process: operator-acceptance counters from the always-on
    metrics registry, plus the `obs.health.run_state` live picture
    (phase, iteration, in-band fraction, heartbeat age, drain ETA).
    The phase is a labeled info-style gauge; the rank label rides every
    line implicitly via the per-rank port (PMMGTPU_STATUS_PORT + rank)."""
    doc = obs_metrics.registry().to_doc()
    st = obs_health.run_state().snapshot()
    lines = []
    for key in sorted(doc.get("counters", {})):
        if not any(key == p or key.startswith(p)
                   for p in _RUN_COUNTER_PREFIXES):
            continue
        name = _prom_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {doc['counters'][key]}")
    for key in ("sweep_active_fraction", "len/in_band",
                "work/imbalance"):
        if key not in doc.get("gauges", {}):
            continue
        if key == "len/in_band" and st.get("in_band") is not None:
            # the run state carries the fresher value (final length
            # stats at "done") — emitting both would duplicate the
            # metric name in one exposition
            continue
        name = _prom_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {doc['gauges'][key]}")
    phase = st.get("phase")
    pname = _prom_name("run/phase")
    lines.append(f"# TYPE {pname} gauge")
    lines.append(f'{pname}{{phase="{phase or "idle"}"}} 1')
    for key, gname in _RUN_GAUGES:
        v = st.get(key)
        if v is None:
            continue
        name = _prom_name(gname)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


class StatusServer:
    """Daemon-threaded HTTP scrape endpoint over a render callable.

    ``StatusServer(job_server)`` keeps the original job-server scrape
    (renders :func:`status_text`); ``StatusServer(render=fn)`` serves
    whatever ``fn() -> str`` returns — the run endpoint passes
    :func:`run_status_text`. Binds immediately (``port=0`` picks an
    ephemeral port — read ``.port`` after construction), serves on a
    daemon thread after :meth:`start`, and never blocks the instrumented
    loop: every request renders a fresh snapshot."""

    def __init__(self, server=None, port: int = 0,
                 host: str = "127.0.0.1",
                 render: Optional[Callable[[], str]] = None):
        if render is None:
            if server is None:
                render = run_status_text
            else:
                job_server = server
                render = lambda: status_text(job_server)

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.startswith("/healthz"):
                    body = b"ok\n"
                    ctype = "text/plain"
                else:
                    body = render().encode()
                    ctype = "text/plain; version=0.0.4"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrapes are not server events

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _Handler
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "StatusServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serve-status",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def serve_run_from_env() -> Optional[StatusServer]:
    """The ``PMMGTPU_STATUS_PORT`` contract: when the env var is set,
    return a STARTED run-status server for this process (else None).
    Multi-process runs offset the port by the jax process index so all
    ranks are scrapable side by side; a nonzero base port that is
    already taken (two concurrent runs on one host) degrades to an
    ephemeral port rather than failing the run. The bound port is
    printed once — with ``PMMGTPU_STATUS_PORT=0`` that line is the only
    way to find the endpoint."""
    raw = os.environ.get("PMMGTPU_STATUS_PORT", "").strip()
    if not raw:
        return None
    try:
        base = int(raw)
    except ValueError:
        return None
    rank = 0
    try:
        import jax

        rank = int(jax.process_index())
    except Exception:
        pass
    port = base + rank if base else 0
    try:
        srv = StatusServer(render=run_status_text, port=port)
    except OSError:
        srv = StatusServer(render=run_status_text, port=0)
    srv.start()
    obs_health.run_state().update(rank=rank, status_port=srv.port)
    print(f"  ## run status endpoint: http://{srv.host}:{srv.port}"
          "/metrics", flush=True)
    return srv
