"""Crash-safe job journal on the checkpoint-store contract.

One JSON record per job (``job_<id>.json``) in any
:class:`~parmmg_tpu.io.ckpt_store.CheckpointStore` (LocalFS, ``mem://``,
``gs://``) — the journal rides the exact same durable substrate, retry
envelope and commit-token discipline as the mesh checkpoints, so a
deployment that trusts its checkpoints already trusts its job ledger.

Every transition is written with ``publish_json`` (atomic commit-token
put): a reader sees either the previous whole record or the next whole
record, never a torn one. A SIGKILLed server therefore leaves each job
in exactly the last state it durably reached — ``submitted`` (queued,
never started) or ``running`` (in flight) — and :meth:`JobJournal.replay`
re-enqueues every non-terminal job on restart, which is the zero-job-
loss contract: admission is acknowledged only after the ``submitted``
record is published, so an acknowledged job can never vanish.

The record::

    {format: 1, job_id, tenant, state, size_class, attempts,
     spec: {...JobSpec...},
     history: [{state, ts, detail}, ...],
     result: {digest, ne, np, wall_s} | error: {type, code, message}}

Transitions are validated against
:data:`~parmmg_tpu.service.jobs.TRANSITIONS`; an illegal edge raises
:class:`JournalStateError` — a state machine that cannot be driven
backwards is what makes the replay's "non-terminal ⇒ requeue" rule
sound.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..io.ckpt_store import CheckpointIOError, CheckpointStore
from .jobs import (
    JobSpec,
    RUNNING,
    SUBMITTED,
    TERMINAL_STATES,
    TRANSITIONS,
)

JOURNAL_FORMAT = 1
_NAME_FMT = "job_{}.json"
_PREFIX = "job_"


class JournalStateError(ValueError):
    """An illegal job-state transition was attempted (programming
    error or a corrupt record) — refused before anything is written."""


class JobJournal:
    """The durable job ledger. One writer (the serving process);
    readers (replay, reports, smoke harnesses) see committed whole
    records only."""

    def __init__(self, store: CheckpointStore):
        self.store = store

    # -- reads ------------------------------------------------------------
    def load(self, job_id: str) -> Optional[dict]:
        try:
            return self.store.get_json(_NAME_FMT.format(job_id))
        except (FileNotFoundError, CheckpointIOError):
            return None

    def jobs(self) -> List[dict]:
        """Every committed job record (torn/corrupt names skipped —
        a broken record must not wedge a replay)."""
        out = []
        for name in self.store.list():
            if not (name.startswith(_PREFIX) and name.endswith(".json")):
                continue
            try:
                out.append(self.store.get_json(name))
            except (FileNotFoundError, CheckpointIOError, ValueError):
                continue
        return sorted(out, key=lambda d: (d.get("history") or
                                          [{}])[0].get("ts", 0.0))

    # -- the one write path ----------------------------------------------
    def transition(self, job_id: str, state: str, *,
                   spec: Optional[JobSpec] = None,
                   size_class: str = "",
                   detail: str = "",
                   result: Optional[dict] = None,
                   error: Optional[dict] = None) -> dict:
        doc = self.load(job_id)
        old = doc.get("state") if doc else None
        if state not in TRANSITIONS.get(old, frozenset()):
            raise JournalStateError(
                f"job {job_id}: illegal transition {old!r} -> {state!r}"
            )
        if doc is None:
            if spec is None:
                raise JournalStateError(
                    f"job {job_id}: first transition needs the spec"
                )
            doc = dict(format=JOURNAL_FORMAT, job_id=job_id,
                       tenant=spec.tenant, state=None,
                       size_class=size_class, attempts=0,
                       spec=spec.to_doc(), history=[])
        doc["state"] = state
        if size_class:
            doc["size_class"] = size_class
        if state == RUNNING:
            doc["attempts"] = int(doc.get("attempts", 0)) + 1
        if result is not None:
            doc["result"] = result
        if error is not None:
            doc["error"] = error
        doc.setdefault("history", []).append(
            dict(state=state, ts=time.time(), detail=detail)
        )
        self.store.publish_json(_NAME_FMT.format(job_id), doc)
        return doc

    # -- lifecycle sugar ---------------------------------------------------
    def submit(self, spec: JobSpec, size_class: str) -> dict:
        return self.transition(spec.job_id, SUBMITTED, spec=spec,
                               size_class=size_class, detail="admitted")

    def reject(self, spec: JobSpec, error: dict, detail: str = "") -> dict:
        from .jobs import REJECTED

        return self.transition(spec.job_id, REJECTED, spec=spec,
                               error=error,
                               detail=detail or error.get("code", ""))

    def running(self, job_id: str, detail: str = "") -> dict:
        return self.transition(job_id, RUNNING, detail=detail)

    def terminal(self, job_id: str, state: str, *,
                 result: Optional[dict] = None,
                 error: Optional[dict] = None,
                 detail: str = "") -> dict:
        if state not in TERMINAL_STATES:
            raise JournalStateError(f"{state!r} is not terminal")
        return self.transition(job_id, state, result=result,
                               error=error, detail=detail)

    def requeue(self, job_id: str, reason: str) -> dict:
        """running -> submitted: the drain/crash edge. The attempt
        count survives (it only grows on ``running``), so a job's
        record tells its whole multi-attempt story."""
        return self.transition(job_id, SUBMITTED,
                               detail=f"requeued: {reason}")

    # -- restart ----------------------------------------------------------
    def replay(self) -> Dict[str, List[dict]]:
        """Partition the ledger for a restarting server: non-terminal
        records (to re-enqueue — ``running`` ones are first moved back
        to ``submitted`` with a crash-replay note) vs terminal ones."""
        requeue, terminal = [], []
        for doc in self.jobs():
            state = doc.get("state")
            if state in TERMINAL_STATES:
                terminal.append(doc)
                continue
            if state == RUNNING:
                doc = self.requeue(doc["job_id"],
                                   "crash replay: found running")
            requeue.append(doc)
        return dict(requeue=requeue, terminal=terminal)
