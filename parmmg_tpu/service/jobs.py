"""Job vocabulary of the adaptation service: specs, states, typed errors.

The reference is a one-shot CLI — one process, one mesh, one exit code
(`src/parmmg.c` returns `PMMG_STRONGFAILURE` and dies). A multi-tenant
server needs the same taxonomy discipline at PER-JOB granularity: every
way a job can end must be a machine-readable, typed outcome, so one
tenant's bad mesh produces an error RESPONSE instead of a dead server.

Two error families, mirroring `parmmg_tpu.failsafe`:

- **refusals** (:class:`ServiceRefusal`, an :class:`AdaptError`): the
  job was never admitted — bounded queue full, no size class large
  enough, input unreadable, or the server draining on a preemption
  notice. Each carries a stable ``code`` string (the per-request error
  response) plus a payload with the numbers the client needs to react
  (queue depth, the largest class's capacities, ...).
- **in-flight interrupts** (:class:`JobDeadlineError`,
  :class:`JobCancelledError`): raised from the driver's iteration/phase
  boundary hook INSIDE ``adapt``. They subclass ``BaseException`` the
  way :class:`~parmmg_tpu.failsafe.PreemptionError` does and for the
  same reason: the in-driver recovery ladder (rollback, grow-and-retry)
  must never absorb them — a job past its deadline must stop burning
  its batch-mates' machine time, not retry harder.

Job lifecycle (the journal's state machine, enforced by
`service.journal`)::

    submitted -> running -> done | failed | deadline
    submitted -> cancelled | rejected
    running   -> cancelled
    running   -> submitted        (requeue: drain or crash replay)

Terminal states carry either a ``result`` (digest, entity counts,
wall seconds) or an ``error`` (type + code + message).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from ..failsafe import AdaptError

# --- job states ------------------------------------------------------------

SUBMITTED = "submitted"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
DEADLINE = "deadline"
REJECTED = "rejected"
CANCELLED = "cancelled"

TERMINAL_STATES = frozenset({DONE, FAILED, DEADLINE, REJECTED, CANCELLED})

# legal transitions: FROM state -> allowed TO states. `None` is the
# unjournaled initial state; RUNNING -> SUBMITTED is the requeue edge
# (graceful drain, crash replay) that makes zero-job-loss possible.
TRANSITIONS = {
    None: frozenset({SUBMITTED, REJECTED}),
    SUBMITTED: frozenset({RUNNING, CANCELLED, REJECTED}),
    RUNNING: frozenset({DONE, FAILED, DEADLINE, CANCELLED, SUBMITTED}),
}


# --- refusals (admission-time, typed, machine-readable) --------------------


class ServiceRefusal(AdaptError):
    """A job the server declined to admit. ``code`` is the stable
    per-request error response string; ``payload`` the structured
    context. Subclasses are DISTINCT refusals — a client retries a
    ``queue-full`` but must re-mesh a ``too-large``."""

    code = "refused"
    #: transient refusals (client may retry unchanged) are never
    #: journaled; permanent ones terminate the job as ``rejected``
    transient = True

    def __init__(self, message: str, **payload):
        super().__init__(message)
        self.payload = dict(payload)

    def doc(self) -> dict:
        """The machine-readable refusal response."""
        return dict(error=type(self).__name__, code=self.code,
                    transient=self.transient, message=str(self),
                    **self.payload)


class QueueFullError(ServiceRefusal):
    """Backpressure: the bounded admission queue is at capacity.
    Transient — resubmit when the queue drains."""

    code = "queue-full"
    transient = True


class JobTooLargeError(ServiceRefusal):
    """No configured size class can hold this mesh (with the growth
    margin remeshing needs). Permanent for this input — the job
    terminates ``rejected``."""

    code = "too-large"
    transient = False


class SloInfeasibleError(ServiceRefusal):
    """The job's deadline is below what PERF_DB history says this size
    class costs (the admission quote, `service.admission.SloPolicy`):
    the run would deadline mid-flight after burning its batch-mates'
    machine time, so it is refused AT SUBMIT instead. Permanent for
    this (deadline, size-class) pair — resubmit with a feasible
    deadline or a coarser target. Payload carries the quoted latency,
    the deadline asked for, and the baseline depth the quote came
    from."""

    code = "slo-infeasible"
    transient = False


class BadJobError(ServiceRefusal):
    """The job's input could not be read/parsed (missing file, unknown
    format, corrupt header). Permanent — ``rejected``."""

    code = "bad-input"
    transient = False


class ServerDrainingError(ServiceRefusal):
    """The server holds a preemption notice (or operator drain) and has
    stopped admitting. Transient — resubmit to the restarted server."""

    code = "draining"
    transient = True


# --- in-flight interrupts (BaseException: unabsorbable by recovery) --------


class JobDeadlineError(BaseException):
    """The per-attempt deadline expired; raised at the next iteration/
    phase boundary of the running job. The job terminates in the typed
    ``deadline`` state; batch-mates are untouched."""

    code = "deadline"

    def __init__(self, job_id: str, deadline_s: float, phase: str):
        super().__init__(
            f"job {job_id}: deadline of {deadline_s}s exceeded at "
            f"phase boundary '{phase}'"
        )
        self.job_id = job_id
        self.deadline_s = deadline_s
        self.phase = phase


class JobCancelledError(BaseException):
    """The job was cancelled while running; honored at the next
    iteration/phase boundary. Terminal state ``cancelled``."""

    code = "cancelled"

    def __init__(self, job_id: str, phase: str):
        super().__init__(
            f"job {job_id}: cancelled at phase boundary '{phase}'"
        )
        self.job_id = job_id
        self.phase = phase


# --- the job spec ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One tenant's adaptation request: medit/VTK in → adapted mesh out.

    ``deadline_s`` is a PER-ATTEMPT execution budget (measured from the
    attempt's start, not from submission): a server crash + journal
    replay must not spuriously deadline every requeued job. ``faults``
    is a job-scoped `PARMMG_FAULTS` schedule (the chaos grammar) — the
    blast-radius tests' way of poisoning exactly one batch member."""

    job_id: str
    inmesh: str
    tenant: str = "default"
    insol: Optional[str] = None
    outmesh: Optional[str] = None
    hsiz: Optional[float] = 0.45
    niter: int = 2
    deadline_s: Optional[float] = None
    faults: Optional[str] = None
    submitted_ts: float = 0.0

    def __post_init__(self):
        if not self.job_id or "/" in self.job_id:
            raise ValueError(f"bad job_id {self.job_id!r}")
        if self.submitted_ts == 0.0:
            object.__setattr__(self, "submitted_ts", time.time())

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_doc(doc: dict) -> "JobSpec":
        names = {f.name for f in dataclasses.fields(JobSpec)}
        return JobSpec(**{k: v for k, v in doc.items() if k in names})
