"""The job server: bucketed batches, blast-radius isolation, drain.

`JobServer` composes every robustness layer this repo has grown into
one serving loop:

- **admission** (`service.admission`): typed refusals, bounded queue,
  size-class bucketing — every admitted job is journaled ``submitted``
  BEFORE the submit call returns, so acknowledgement implies
  durability;
- **execution**: a batch is a class-homogeneous group of jobs run
  back-to-back through the existing `models.adapt` driver at the
  class's pinned capacities — identical shapes mean every batch member
  (and every later batch of the class) reuses the same compiled
  executables (the PR-1 memoized jit factories; `warmup` pre-pays the
  compile per class so the first request is compile-free);
- **blast-radius isolation**: each member runs under its own typed
  fence. A `NumericalError`/`CapacityError`/... downgrades THAT job to
  ``failed`` with a machine-readable error doc; a deadline or
  cancellation (BaseException-family, raised from the phase-boundary
  hook) downgrades it to ``deadline``/``cancelled``; the loop then
  simply continues with the next member — the poisoned job is masked
  out of the batch and the survivors' results stand. Because the
  service runs jobs fail-fast (``recovery_attempts=0``: retry policy
  is a JOB-layer concern, visible in the journal's attempt count, not
  an invisible in-driver rollback), a batch-mate's output is the SAME
  device program on the SAME input as a solo run — asserted
  bit-identical (`mesh_digest`) by tests/test_m21_service.py and
  tools/serve_smoke.py;
- **deadlines + cancellation**: wired through ``adapt``'s
  ``phase_hook`` — the same iteration/phase boundary the failsafe
  harness uses for checkpoints and preemption, so a job is interrupted
  only at a consistent boundary, never mid-device-program;
- **graceful drain**: `request_drain` (SIGTERM / preemption notice in
  `tools/serve.py`) stops admission with the typed ``draining``
  refusal, interrupts the in-flight job at its next boundary, and
  journals it back to ``submitted`` (requeue) — combined with the
  journal's replay, a drain or a SIGKILL loses zero jobs;
- **per-tenant observability**: every transition emits a job-id/
  tenant-labelled event + counter through `obs/`, rendered by
  ``tools/obs_report.py --serve`` as the per-job timeline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Iterable, List, Optional, Tuple

from ..failsafe import AdaptError, PreemptionError, WorldReformError
from ..obs import (
    health as obs_health,
    metrics as obs_metrics,
    trace as obs_trace,
)
from . import jobs as J
from .admission import (
    AdmissionQueue,
    DEFAULT_CLASSES,
    SizeClass,
    classify,
    peek_counts,
)
from .jobs import (
    JobCancelledError,
    JobDeadlineError,
    JobSpec,
    ServerDrainingError,
    ServiceRefusal,
)
from .journal import JobJournal


class _DrainInterrupt(BaseException):
    """Internal: the in-flight job is being requeued for a graceful
    drain (never absorbed by the in-driver recovery ladder)."""


def mesh_digest(mesh) -> str:
    """Bit-level digest of a result mesh at its FULL capacities —
    the strictest form of the isolation assertion: a batch-mate's
    output must match a solo run of the same class byte for byte,
    padding included."""
    import numpy as np

    h = hashlib.sha256()
    for name in ("vert", "vref", "vtag", "vmask", "tet", "tref",
                 "tmask", "tria", "trref", "trmask", "met"):
        a = getattr(mesh, name, None)
        if a is None:
            continue
        arr = np.asarray(a)
        h.update(name.encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def default_options():
    """The service's shared driver options: fail-fast (typed per-job
    errors surface instead of invisible in-driver retries) and every
    compile-keyed static fixed, so one class = one compile."""
    from ..models.adapt import AdaptOptions

    return AdaptOptions(
        niter=2, max_sweeps=2, hgrad=None, polish_sweeps=0,
        recovery_attempts=0,
    )


class JobServer:
    """One serving process. Construction is cheap (no device touch);
    the first executed or warmed job pays its class's compile."""

    def __init__(self, store, *,
                 classes: Iterable[SizeClass] = DEFAULT_CLASSES,
                 queue_cap: int = 16,
                 batch_max: int = 4,
                 margin: float = 2.0,
                 base_options=None,
                 slo=None):
        self.journal = JobJournal(store)
        self.classes = tuple(classes)
        self.queue = AdmissionQueue(queue_cap)
        self.batch_max = int(batch_max)
        self.margin = float(margin)
        self._base_options = base_options
        # SLO admission from history: an admission.SloPolicy, or a
        # PERF_DB path to build one from (None = no SLO enforcement —
        # the pre-quote behavior)
        if slo is not None and not hasattr(slo, "admit"):
            from .admission import SloPolicy

            slo = SloPolicy(slo)
        self.slo = slo
        self._draining = False
        self._cancel_requested: set = set()
        self._running_id: Optional[str] = None
        self.warmup_s: float = 0.0
        # test-only: a pause right after a job is journaled `running`
        # gives the smoke harness (tools/serve_smoke.py) a deterministic
        # SIGKILL window — journal shows terminal batch-mates PLUS one
        # in-flight job, the exact crash the replay contract covers.
        self._test_sleep_s = float(
            os.environ.get("PMMGTPU_SERVE_TEST_SLEEP_S", "0") or 0.0
        )

    # -- options -----------------------------------------------------------
    @property
    def base_options(self):
        if self._base_options is None:
            self._base_options = default_options()
        return self._base_options

    def _class_named(self, name: str) -> Optional[SizeClass]:
        for cls in self.classes:
            if cls.name == name:
                return cls
        return None

    # -- admission ---------------------------------------------------------
    def submit(self, spec: JobSpec) -> dict:
        """Admit one job: classify, journal ``submitted``, enqueue.
        Raises a typed :class:`ServiceRefusal`; permanent refusals
        (too-large, bad-input) additionally journal the job as
        ``rejected`` so it still reaches a typed TERMINAL state."""
        reg = obs_metrics.registry()
        if self._draining:
            reg.counter("serve/refused_draining").inc()
            err = ServerDrainingError(
                "server is draining on a preemption notice/operator "
                "stop; resubmit to the restarted server",
            )
            obs_trace.emit_event("job_refused", job_id=spec.job_id,
                                 tenant=spec.tenant, code=err.code,
                                 transient=True)
            raise err
        existing = self.journal.load(spec.job_id)
        if existing is not None:
            # idempotent resubmission (spool re-ingest after a crash
            # between journal publish and spool unlink)
            return existing
        try:
            npoin, ntet = peek_counts(spec.inmesh)
            cls = classify(npoin, ntet, self.classes, self.margin)
            if self.slo is not None:
                # quote-infeasible deadlines are refused HERE (typed,
                # permanent → journaled rejected below); deadline-less
                # jobs leave with the data-derived default attached
                spec = self.slo.admit(spec, cls.name)
        except ServiceRefusal as err:
            code = f"serve/refused_{err.code.replace('-', '_')}"
            reg.counter(code).inc()
            if not err.transient:
                self.journal.reject(spec, err.doc())
                obs_trace.emit_event(
                    "job_terminal", job_id=spec.job_id,
                    tenant=spec.tenant, state=J.REJECTED, code=err.code,
                )
            else:
                obs_trace.emit_event("job_refused", job_id=spec.job_id,
                                     tenant=spec.tenant, code=err.code,
                                     transient=True)
            raise
        try:
            self.queue.offer(spec, cls)
        except ServiceRefusal as err:
            reg.counter("serve/refused_queue_full").inc()
            obs_trace.emit_event("job_refused", job_id=spec.job_id,
                                 tenant=spec.tenant, code=err.code,
                                 transient=True)
            raise
        rec = self.journal.submit(spec, cls.name)
        reg.counter("serve/submitted").inc()
        obs_trace.emit_event(
            "job_submitted", job_id=spec.job_id, tenant=spec.tenant,
            size_class=cls.name, npoin=npoin, ntet=ntet,
            deadline_s=spec.deadline_s,
        )
        return rec

    def replay(self) -> int:
        """Restart path: re-enqueue every non-terminal journaled job
        (``running`` records are first requeued — the crash edge).
        Returns the number of jobs restored to the queue."""
        restored = 0
        for doc in self.journal.replay()["requeue"]:
            spec = JobSpec.from_doc(doc.get("spec", {}))
            cls = self._class_named(doc.get("size_class", ""))
            if cls is None:
                npoin, ntet = peek_counts(spec.inmesh)
                cls = classify(npoin, ntet, self.classes, self.margin)
            self.queue.offer(spec, cls)
            restored += 1
            obs_metrics.registry().counter("serve/replayed").inc()
        return restored

    # -- cancellation / drain ---------------------------------------------
    def cancel(self, job_id: str) -> Optional[str]:
        """Cancel a queued (immediate) or running (next-boundary) job.
        Returns the resulting state, or None for unknown/terminal."""
        if self.queue.remove(job_id) is not None:
            self.journal.terminal(job_id, J.CANCELLED,
                                  error=dict(code="cancelled",
                                             message="cancelled while "
                                                     "queued"))
            obs_metrics.registry().counter("serve/cancelled").inc()
            obs_trace.emit_event("job_terminal", job_id=job_id,
                                 state=J.CANCELLED, code="cancelled")
            return J.CANCELLED
        if job_id == self._running_id:
            self._cancel_requested.add(job_id)
            return J.RUNNING
        return None

    def request_drain(self) -> None:
        """Stop admitting (typed ``draining`` refusals) and interrupt
        the in-flight job at its next phase boundary (requeued)."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def idle(self) -> bool:
        return len(self.queue) == 0 and self._running_id is None

    # -- warm boot ---------------------------------------------------------
    def warmup(self, classes: Optional[Iterable[SizeClass]] = None) -> float:
        """Pre-pay each class's compiles with a synthetic job at the
        class's exact capacities: the same memoized jit factories real
        jobs hit, driven to `lower().compile()` by one tiny end-to-end
        pass (an AOT-only lower would not seed the dispatch cache the
        executing path reads). First real request per class is then
        compile-free."""
        from ..models.adapt import adapt
        from ..utils.gen import unit_cube_mesh

        t0 = time.monotonic()
        warmed = []
        for cls in (tuple(classes) if classes is not None
                    else self.classes):
            mesh = unit_cube_mesh(2, **cls.caps())
            opts = dataclasses.replace(self.base_options, niter=1,
                                       hsiz=0.45, faults=None)
            adapt(mesh, opts)
            warmed.append(cls.name)
        self.warmup_s = round(time.monotonic() - t0, 3)
        obs_trace.emit_event("serve_warmup", classes=warmed,
                             seconds=self.warmup_s)
        return self.warmup_s

    # -- execution ---------------------------------------------------------
    def _load_mesh(self, spec: JobSpec, cls: SizeClass):
        ext = os.path.splitext(spec.inmesh)[1].lower()
        if ext == ".vtu":
            from ..io import vtk

            mesh = vtk.load_vtu(spec.inmesh)
            return mesh.with_capacity(**cls.caps())
        from ..io import medit

        return medit.load_mesh(spec.inmesh, spec.insol, **cls.caps())

    def _save_mesh(self, mesh, path: str) -> None:
        if os.path.splitext(path)[1].lower() == ".vtu":
            from ..io import vtk

            vtk.save_vtu(mesh, path)
            return
        from ..io import medit

        medit.save_mesh(mesh, path)

    def _boundary_hook(self, spec: JobSpec, deadline_ts: Optional[float]):
        def hook(phase: str) -> None:
            if self._draining:
                raise _DrainInterrupt()
            if spec.job_id in self._cancel_requested:
                raise JobCancelledError(spec.job_id, phase)
            if deadline_ts is not None and time.monotonic() > deadline_ts:
                raise JobDeadlineError(spec.job_id, spec.deadline_s,
                                       phase)
        return hook

    def _execute(self, spec: JobSpec, cls: SizeClass):
        from ..models.adapt import adapt

        mesh = self._load_mesh(spec, cls)
        opts = dataclasses.replace(
            self.base_options, hsiz=spec.hsiz, niter=spec.niter,
            faults=spec.faults,
        )
        deadline_ts = (time.monotonic() + spec.deadline_s
                       if spec.deadline_s is not None else None)
        hook = self._boundary_hook(spec, deadline_ts)
        # the hook also covers admission->start queueing time zero:
        # deadline_s is a per-ATTEMPT budget (see JobSpec docstring)
        return adapt(mesh, opts, phase_hook=hook)

    def _run_job(self, spec: JobSpec, cls: SizeClass) -> str:
        """One fenced batch member: returns the terminal state (or
        re-raises the drain interrupt after requeueing)."""
        reg = obs_metrics.registry()
        rec = self.journal.running(spec.job_id)
        attempt = int(rec.get("attempts", 1))
        obs_trace.emit_event(
            "job_running", job_id=spec.job_id, tenant=spec.tenant,
            size_class=cls.name, attempt=attempt,
        )
        self._running_id = spec.job_id
        if self._test_sleep_s:
            time.sleep(self._test_sleep_s)
        tr = obs_trace.get_tracer()
        t0 = time.monotonic()
        try:
            with tr.span("serve/job", job_id=spec.job_id,
                         tenant=spec.tenant, size_class=cls.name):
                out, info = self._execute(spec, cls)
            wall = round(time.monotonic() - t0, 3)
            if int(info.get("status", 0)) != 0:
                # the driver absorbed a typed failure by rolling back
                # to the last conformal mesh (graded LOWFAILURE — the
                # reference's failed_handling ladder). At the SERVICE
                # layer that is this job's typed failure, not a result:
                # surface the absorbed error from the run history.
                absorbed = [h for h in info.get("history", [])
                            if h.get("error")]
                err = (absorbed[-1] if absorbed
                       else dict(error="AdaptError",
                                 failure="degraded (LOWFAILURE)"))
                self.journal.terminal(
                    spec.job_id, J.FAILED,
                    error=dict(type=err["error"], code=err["error"],
                               message=str(err.get("failure", "")),
                               status=int(info["status"])),
                )
                reg.counter("serve/failed").inc()
                obs_trace.emit_event(
                    "job_terminal", job_id=spec.job_id,
                    tenant=spec.tenant, state=J.FAILED,
                    code=err["error"], wall_s=wall, attempt=attempt,
                )
                return J.FAILED
            digest = mesh_digest(out)
            if spec.outmesh:
                self._save_mesh(out, spec.outmesh)
            # run-health quality stamp (round 12): the final unit-band
            # edge fraction and the obs.health verdict ride the result
            # + terminal event, so `obs_report --serve` gets its
            # per-job quality column without re-running anything
            in_band = obs_health.history_in_band(
                info.get("history", [])
            )
            verdict = (info.get("health") or {}).get("verdict")
            result = dict(
                digest=digest, ne=int(out.ntet), npoin=int(out.npoin),
                status=int(info.get("status", 0)), wall_s=wall,
            )
            if in_band is not None:
                result["in_band"] = in_band
            if verdict is not None:
                result["verdict"] = verdict
            self.journal.terminal(spec.job_id, J.DONE, result=result)
            reg.counter("serve/done").inc()
            obs_trace.emit_event(
                "job_terminal", job_id=spec.job_id, tenant=spec.tenant,
                state=J.DONE, code="ok", wall_s=wall, digest=digest,
                attempt=attempt, in_band=in_band, verdict=verdict,
            )
            return J.DONE
        except JobDeadlineError as e:
            return self._typed_terminal(spec, J.DEADLINE, e.code, e,
                                        t0, attempt)
        except JobCancelledError as e:
            return self._typed_terminal(spec, J.CANCELLED, e.code, e,
                                        t0, attempt)
        except _DrainInterrupt:
            self.journal.requeue(spec.job_id, "graceful drain")
            reg.counter("serve/requeued").inc()
            obs_trace.emit_event("job_requeued", job_id=spec.job_id,
                                 tenant=spec.tenant,
                                 reason="graceful drain")
            raise
        except (PreemptionError, WorldReformError):
            # infrastructure (not job) failure mid-attempt: requeue the
            # job and let the caller's typed exit drive the restart
            self.journal.requeue(spec.job_id, "preemption during run")
            reg.counter("serve/requeued").inc()
            obs_trace.emit_event("job_requeued", job_id=spec.job_id,
                                 tenant=spec.tenant,
                                 reason="preemption during run")
            raise
        except AdaptError as e:
            code = type(e).__name__
            return self._typed_terminal(spec, J.FAILED, code, e, t0,
                                        attempt)
        finally:
            self._running_id = None
            self._cancel_requested.discard(spec.job_id)

    def _typed_terminal(self, spec: JobSpec, state: str, code: str,
                        err: BaseException, t0: float,
                        attempt: int) -> str:
        wall = round(time.monotonic() - t0, 3)
        self.journal.terminal(
            spec.job_id, state,
            error=dict(type=type(err).__name__, code=code,
                       message=str(err)),
        )
        reg = obs_metrics.registry()
        reg.counter(f"serve/{state}").inc()
        obs_trace.emit_event(
            "job_terminal", job_id=spec.job_id, tenant=spec.tenant,
            state=state, code=code, wall_s=wall, attempt=attempt,
        )
        return state

    def run_once(self) -> int:
        """Run ONE class-homogeneous batch off the queue head; returns
        the number of jobs that reached a terminal state. A drain
        interrupt requeues the in-flight member (journal + queue) and
        pushes un-started members back untouched."""
        batch = self.queue.take_batch(self.batch_max)
        if not batch:
            return 0
        reg = obs_metrics.registry()
        reg.counter("serve/batches").inc()
        tr = obs_trace.get_tracer()
        finished = 0
        with tr.span("serve/batch", size_class=batch[0][1].name,
                     jobs=len(batch)):
            for i, (spec, cls) in enumerate(batch):
                if self._draining:
                    self._push_back(batch[i:])
                    break
                try:
                    self._run_job(spec, cls)
                    finished += 1
                except _DrainInterrupt:
                    # _run_job already journaled the requeue; restore
                    # the in-memory queue (this member + the rest)
                    self._push_back(batch[i:])
                    break
        return finished

    def _push_back(self, members: List[Tuple[JobSpec, SizeClass]]) -> None:
        self.queue.push_front(members)
