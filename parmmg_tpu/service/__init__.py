"""Adaptation-as-a-service: a fault-contained multi-tenant job server.

The service arc's foundation (ROADMAP "Adaptation-as-a-service"):
independent adaptation jobs (medit/VTK in → adapted mesh out) are
admitted through a bounded queue with typed refusals, bucketed into
padded size classes that share compiled executables, executed with
per-job blast-radius isolation + deadlines, and tracked in a
crash-safe journal on the checkpoint-store contract. `tools/serve.py`
is the process wrapper (spool ingestion, drain-on-notice, bench);
`tools/serve_smoke.py` the end-to-end acceptance harness.

Modules: `jobs` (specs, states, typed errors), `admission` (size
classes + bounded queue), `journal` (durable state machine),
`server` (the serving loop), `status` (the Prometheus scrape
endpoint behind ``tools/serve.py --status``).
"""

from .admission import (  # noqa: F401
    AdmissionQueue,
    DEFAULT_CLASSES,
    SizeClass,
    classify,
    peek_counts,
)
from .jobs import (  # noqa: F401
    BadJobError,
    CANCELLED,
    DEADLINE,
    DONE,
    FAILED,
    JobCancelledError,
    JobDeadlineError,
    JobSpec,
    JobTooLargeError,
    QueueFullError,
    REJECTED,
    RUNNING,
    SUBMITTED,
    ServerDrainingError,
    ServiceRefusal,
    TERMINAL_STATES,
)
from .journal import JobJournal, JournalStateError  # noqa: F401
from .server import JobServer, default_options, mesh_digest  # noqa: F401
from .status import (  # noqa: F401
    StatusServer,
    run_status_text,
    serve_run_from_env,
    status_text,
)
