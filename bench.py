"""Benchmark: adapt a refined unit cube to a uniform size map and report
remeshing throughput as ONE JSON line.

Workload: cube n=10 (6,000 input tets) -> hsiz=0.05 (~110k output tets),
the shape of the reference CI adaptation runs
(`cmake/testing/pmmg_tests.cmake:30-50`, `-mesh-size`-class workloads).

Baseline note (BASELINE.md): the reference ParMmg binary cannot be built
in this environment (its Mmg/Metis dependencies are CMake
ExternalProjects requiring network download, and no MPI toolchain is
installed), so the recorded anchor is this framework's own steady-state
throughput on the host CPU backend for the identical workload —
an honest same-algorithm hardware comparison. vs_baseline therefore
reads as "accelerator speedup over the CPU execution".

Robustness: XLA compilation over the shared TPU tunnel has a highly
variable latency (observed 1-10x swings), so the measurement runs in a
subprocess with its own timeout and falls back to a smaller workload —
the driver always gets a parseable line.
"""

import json
import os
import signal
import subprocess
import sys
import time

# steady-state tets/sec of the default workload on the host CPU backend
# (measured with a warm jit cache; see BASELINE.md "CPU anchor" row).
# History: round-2 M5/M6 kernels 1367.3; round-3 passes 2128.2 /
# 2003.5; round-4 2122.7; re-measured 2026-08-03 with the round-5
# kernels (one-round rank MIS, fused smoothing centroids): 91,100
# output tets in 38.7 s. Host wall-clock drifts a few percent with
# machine load — anchors are refreshed the same day as the TPU
# measurement so vs_baseline stays an honest same-code same-day
# hardware ratio.
CPU_ANCHOR_TPS = 2351.3
# CPU anchor for the large workload (n=12, hsiz=0.04 -> ~200k tets):
# 201,001 tets in 163.5 s, measured 2026-08-03 on the round-5 tree
# (round 4: 1,141.4; round 3: 1,060.3). The CPU halves its rate at
# this size (working set leaves cache) while the TPU holds steady —
# the large configs are the representative points for the 10M-tet
# north star.
CPU_ANCHOR_TPS_LARGE = 1229.1
# CPU anchor for the xl ladder (n=14, hsiz=0.03: 325,232 tets in
# 353.9 s, measured 2026-08-03 round-5 tree; round 3 measured 1,031 at
# the same class — the rate keeps sagging as the working set grows)
CPU_ANCHOR_TPS_XL = 919.0

# Total wall-clock the bench allows itself. The round-4 driver run was
# killed by the harness outer timeout (rc=124) AFTER its record lines
# printed — the lines survived but the clean exit did not. Every attempt
# below is now bounded by the remaining budget and the process exits 0
# with whatever record landed. Overridable for local experiments.
BUDGET_S = float(os.environ.get("PARMMG_BENCH_BUDGET_S", "1380"))


class StageDeadline(BaseException):
    """Per-stage time budget expired (the worker's SIGALRM). Derives
    from BaseException so no driver recovery path can absorb it —
    whatever state the run is in, the worker must commit a PARTIAL
    record NOW, because the next authority is the parent's hard kill
    and after that the harness's rc=124."""


# the stage phase most recently entered by the measured run — what a
# partial record names as `died_in` (BENCH_r01/r03 gave us rc=124 with
# no hint of WHERE the budget went; this closes that gap)
_PHASE_NOW = ["startup"]


def _note_phase(name: str) -> None:
    _PHASE_NOW[0] = name
    # a liveness marker the PARENT can parse out of a killed worker's
    # captured stdout — the worker may never get to print its record
    print(f"BENCH_PHASE {name}", flush=True)


def _rung_for_cfg(cfg) -> str:
    """The PERF_DB rung label of one bench config — shared by the full
    and partial record paths so both land in the same baseline group.
    A kernels-on config gets a distinct `-pk` rung: Pallas-kernel and
    lax measurements must never share a gate baseline (tools/
    perf_gate.py keys on the rung, and its coarse fallback honors the
    marker too)."""
    pk = "-pk" if cfg.get("kernels") == "on" else ""
    if cfg.get("dist"):
        return f"dist-p{cfg.get('nparts', '?')}{pk}"
    try:
        return f"n{cfg.get('n', '?')}-hsiz{float(cfg['hsiz']):g}{pk}"
    except (KeyError, TypeError, ValueError):
        return f"n{cfg.get('n', '?')}-hsiz{cfg.get('hsiz', '?')}{pk}"


def _envelope(rec, cfg):
    """Stamp the PERF_DB envelope (schema/run_id/git_sha/timestamp/
    platform/rung) via the ONE record constructor — worker-committed
    and parent-synthesized records must be indistinguishable in shape
    (obs.history.make_record; the r0x two-dict drift is gone)."""
    from parmmg_tpu.obs import history as obs_history

    return obs_history.make_record(rec, rung=_rung_for_cfg(cfg))


def _total_compile_s() -> float:
    """Run-level AOT compile seconds from the obs cost collector (the
    per-entry-point `compile_s` gauges summed at the source) — 0.0 when
    the run was untraced and no capture happened."""
    from parmmg_tpu.obs import costs as obs_costs

    return obs_costs.collector().total_compile_s()


def partial_record(cfg, died_in=None, reason="stage deadline"):
    """The committed-partial BENCH line: parseable by every consumer of
    the full record, explicitly marked, enveloped like the full record,
    and naming the stage/phase the budget died in — the never-blind
    contract of the bench ladder."""
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    return _envelope({
        "metric": ("tets_per_sec_distributed" if cfg.get("dist")
                   else "tets_per_sec"),
        "value": 0.0,
        "unit": "tet/s",
        "vs_baseline": 0.0,
        "partial": True,
        "stage": f"n{cfg.get('n', '?')}-hsiz{cfg.get('hsiz', '?')}",
        "died_in": died_in or _PHASE_NOW[0],
        "error": reason,
        "platform": platform,
    }, cfg)


def _arm_stage_deadline() -> None:
    """Arm the worker-side SIGALRM per the PARMMG_STAGE_BUDGET_S env
    contract (set by `_attempt` just under the subprocess timeout, and
    by tools/xl_stage.sh under each stage watchdog). The handler raises
    :class:`StageDeadline` at the next Python-level checkpoint; a
    budget expiring inside one long C-level XLA compile is instead
    caught by the parent's subprocess timeout — two layers, so a
    partial record is committed either way."""
    budget = os.environ.get("PARMMG_STAGE_BUDGET_S")
    if not budget:
        return

    def _on_alarm(signum, frame):
        raise StageDeadline(
            f"stage budget {budget}s expired in phase {_PHASE_NOW[0]}"
        )

    signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(max(int(float(budget)), 1))


def est_out_tets(hsiz):
    """Predicted output-tet count of a unit cube adapted to uniform
    `hsiz` (~12 tets per hsiz^3 cell at Mmg-unit quality) — the single
    sizing formula shared by the bench and the scaling tools."""
    return int(12.0 / hsiz**3)


def _workload(n, hsiz, tight=False):
    """Mesh pre-sized so the whole adaptation stays in ONE capacity
    bucket: every kernel compiles exactly once (compile over the TPU
    tunnel costs minutes; execution costs seconds). The feature-edge
    capacity is presized too: analysis detects the cube's 12 ridge
    lines and splits grow them to ~(est/12)^(1/3) segments each — an
    un-presized ecap reshapes the edge table mid-run and invalidates
    every warmed kernel (the round-4/5 'unfused run never completes'
    failure).

    `tight` trims the headroom for the million-tet-class rungs, where
    XLA compile time scales with the array sizes and the generous
    default sizing is the difference between a 90-minute and a
    ~60-minute analysis compile: the measured PEAK element count is
    1.05-1.18x est (growth tapers at the metric target), so 1.45x
    covers it with margin; vertices peak near 0.19x est and surface
    trias far below 0.12x est."""
    from parmmg_tpu.utils.gen import unit_cube_mesh

    est = est_out_tets(hsiz)
    if tight:
        caps = dict(
            tcap=int(est * 1.45),
            pcap=max(int(est * 0.28), 4096),
            fcap=max(int(est * 0.12), 4096),
        )
    else:
        caps = dict(
            tcap=int(est * 1.9),
            pcap=max(int(est * 0.45), 4096),
            fcap=max(int(est * 0.30), 4096),
        )
    return unit_cube_mesh(
        n,
        ecap=max(int(24 * (est / 12.0) ** (1.0 / 3.0)) + 256, 1024),
        **caps,
    )


def _enable_compile_cache():
    """Persistent XLA compile cache. Compilation over the shared TPU
    tunnel costs 10-45 min cold; a disk cache hit costs <1 s. The env
    var JAX_COMPILATION_CACHE_DIR is not honored by this jax build, so
    the config flag is set programmatically. The CPU cache is OPT-IN
    (PARMMG_CPU_CACHE=1): the round-2-era (de)serialization crash DOES
    reproduce on this jaxlib when loading cached CPU executables
    (re-measured PR 1, tests/conftest.py note) — a crashed CPU anchor
    loses the whole bench line, so cold-but-stable is the default."""
    # loader-spam silencing must land before the XLA plugin loads
    # (jax.devices() below latches the C++ log level) — keyed off the
    # requested platform since the backend is not known yet. TPU runs
    # keep full error logging: tunnel diagnostics matter there.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    if jax.devices()[0].platform == "tpu":
        cache = os.path.join(here, ".jax_cache")
    elif not os.environ.get("PARMMG_CPU_CACHE"):
        return  # CPU cache loads crash this jaxlib — opt-in only
    else:
        # NOT the test suite's committed tests/.jax_cache_cpu: bench
        # shapes would dirty the tracked artifact with large blobs the
        # suite never loads
        cache = os.path.join(here, ".jax_cache_cpu")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 1)


def measure_converged_sweep(out, reps=3):
    """Converged-sweep cost probe (rounds 6/8): on an adapted
    (converged) mesh, time one full-table sweep against one
    empty-frontier sweep over clean tables — the cost of a no-op
    verification sweep under active-set scheduling vs the legacy
    full-capacity cost. This is the number the adapt-vs-distributed
    parity check compares (same probe as tools/phase_times.py, shared
    here so every BENCH JSON carries it). Returns
    {"full_s", "frontier_s", "ratio"} in seconds."""
    import functools
    import time as _time

    import jax
    import jax.numpy as jnp

    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import (
        UNFUSED_TCAP, Frontier, _sweep_body, remesh_sweep,
    )

    mesh = compact(out)
    ecap = int(mesh.tcap * 1.6) + 64
    edges, emask, t2e, nu = adj.unique_edges(mesh, ecap)
    mesh = adj.build_adjacency(mesh)
    fr = Frontier(
        changed=jnp.zeros(mesh.pcap, bool),
        dirty=jnp.int32(0),
        tables=(edges, emask, t2e, jnp.asarray(nu, jnp.int32)),
        adja_ok=jnp.bool_(True),
    )
    # above the compile-budget threshold the fused whole-sweep program
    # must not be built for a probe — dispatch per-op, and copy the
    # input per call because the unfused op kernels donate their
    # buffers (the copy is linear and small against sweeps this size)
    unfused = mesh.tcap > UNFUSED_TCAP
    if unfused:
        body = functools.partial(_sweep_body, fused=False)

        def call(**kw):
            m = jax.tree_util.tree_map(jnp.copy, mesh)
            return body(m, ecap, phase_skip=False, **kw)
    else:
        def call(**kw):
            return remesh_sweep(mesh, ecap, phase_skip=False, **kw)

    def timed(fn):
        fn()  # compile + warm
        t0 = _time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(jax.tree_util.tree_leaves(fn())[0])
        return (_time.perf_counter() - t0) / reps

    t_full = timed(lambda: call())
    t_fr = timed(lambda: call(frontier=fr))
    return {
        "full_s": round(t_full, 6),
        "frontier_s": round(t_fr, 6),
        "ratio": round(t_full / max(t_fr, 1e-9), 2),
    }


def run(n=10, hsiz=0.05, niter=1, max_sweeps=12, anchor=CPU_ANCHOR_TPS,
        tight=False, kernels=None):
    import jax

    from parmmg_tpu.kernels import registry as kernels_registry
    from parmmg_tpu.lint.contracts import RetraceCounter
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import quality

    _enable_compile_cache()

    opts = AdaptOptions(niter=niter, hsiz=hsiz, max_sweeps=max_sweeps, hgrad=None,
                        kernels=kernels)
    if kernels is not None:
        kernels_registry.set_mode(kernels)
    # the EFFECTIVE backend this run measured (auto resolves per
    # platform): recorded in the line and in the rung via the cfg
    kernels_on = any(
        kernels_registry.enabled(nm) for nm in kernels_registry.names()
    )
    # PARMMG_BENCH_CKPT=1: checkpoint the TIMED run (fresh dir — the
    # warmup must not leave a checkpoint the timed run would resume
    # from) through the async staging path, so the record carries a
    # real ckpt_overlap_s — how much checkpoint wall time hid behind
    # compute. Off by default: the headline throughput row stays
    # I/O-free (the key then records 0.0). PARMMG_BENCH_CKPT_STORE
    # points the bench at a store SPEC instead of a temp dir — a real
    # ``gs://`` bucket (PMMGTPU_GCS_* env) or a fake-GCS endpoint, the
    # real-bucket checkpoint-overlap measurement of the ROADMAP's
    # preemptible-fleet thread (tools/ckpt_bench.py drives it per
    # epoch size).
    steady_opts = opts
    _ckpt_tmp = None
    if os.environ.get("PARMMG_BENCH_CKPT"):
        import dataclasses
        import tempfile

        _ckpt_store = os.environ.get("PARMMG_BENCH_CKPT_STORE")
        if _ckpt_store:
            steady_opts = dataclasses.replace(
                opts, checkpoint_store=_ckpt_store,
                checkpoint_async=True,
            )
        else:
            _ckpt_tmp = tempfile.mkdtemp(prefix="parmmg_bench_ckpt_")
            steady_opts = dataclasses.replace(
                opts, checkpoint_dir=_ckpt_tmp, checkpoint_async=True,
            )

    # retrace accounting (lint.contracts): the warmup run is EXPECTED
    # to compile; the timed run must hit the in-process executable
    # cache (same static shapes by construction), so a nonzero
    # steady:* count in the record is a regression signal — exactly the
    # warm-cache failures ADVICE.md documents
    def _hook(tag):
        def h(p):
            counter.enter_phase(f"{tag}:{p}")
            _note_phase(f"{tag}:{p}")
        return h

    counter = RetraceCounter()
    with counter:
        counter.enter_phase("warmup")
        _note_phase("warmup")
        adapt(_workload(n, hsiz, tight), opts, phase_hook=_hook("warmup"))

        mesh = _workload(n, hsiz, tight)
        counter.enter_phase("steady")
        _note_phase("steady")
        t0 = time.perf_counter()
        out, info = adapt(mesh, steady_opts, phase_hook=_hook("steady"))
        wall = time.perf_counter() - t0
    if _ckpt_tmp is not None:
        import shutil

        shutil.rmtree(_ckpt_tmp, ignore_errors=True)

    ne = int(out.ntet)
    h = quality.quality_histogram(out)
    tps = ne / wall
    steady_misses = sum(
        v for k, v in counter.counts.items() if k.startswith("steady")
    )
    # per-sweep fraction of unique edges the active-set sweep offered to
    # its operators (round 6): 1.0 on a full/first sweep, decaying as
    # the frontier drains — the byte-level-reduction telemetry the
    # PERF_NOTES round-5 analysis called for
    saf = [
        round(r["n_active"] / max(r["n_unique"], 1), 4)
        for r in info["history"] if "n_active" in r
    ]
    # unit-band edge fraction trajectory (round 12 obs.health
    # telemetry): the final value is the `len/in_band` gate key —
    # quality in the reference's own -prilen terms
    band = [r["in_band"] for r in info["history"] if "in_band" in r]
    _note_phase("converged-probe")
    return _envelope({
        "metric": "tets_per_sec",
        "value": round(tps, 1),
        "unit": "tet/s",
        "vs_baseline": round(tps / anchor, 3),
        "ne": ne,
        "wall_s": round(wall, 2),
        "platform": jax.devices()[0].platform,
        "qmin": round(float(h.qmin), 5),
        "qavg": round(float(h.qavg), 5),
        "recompiles": dict(counter.counts),
        "steady_recompiles": steady_misses,
        "sweep_active_fraction": saf,
        "len/in_band": band[-1] if band else 0.0,
        "in_band_series": band,
        # cost of one converged (no-op) sweep, full-table vs drained
        # frontier — the centralized half of the adapt-vs-distributed
        # parity check (run_dist records the distributed half)
        "converged_sweep_cost": measure_converged_sweep(out),
        # checkpoint wall time hidden behind compute by the async
        # staging writer (0.0 when the run checkpoints synchronously or
        # not at all — see PARMMG_BENCH_CKPT above)
        "ckpt_overlap_s": float(info.get("ckpt_overlap_s", 0.0)),
        # AOT lower+compile seconds this process paid (0.0 on untraced
        # runs — the cost capture is trace-gated): the wall/roofline
        # comparisons can exclude compile instead of warning about it
        "compile_s": _total_compile_s(),
        # Pallas kernel subsystem state of THIS measurement — on/off
        # also keys the rung (…-pk) so the perf gate never mixes
        # kernel-on and kernel-off baselines
        "kernels": "on" if kernels_on else "off",
    }, dict(n=n, hsiz=hsiz, kernels="on" if kernels_on else "off"))


def run_dist(n=8, hsiz=0.08, nparts=2, niter=2, max_sweeps=12,
             anchor=CPU_ANCHOR_TPS, frontier=True, kernels=None):
    """Distributed-driver bench: warmup + timed `adapt_distributed`
    with active-set sweeps, recording the per-sweep
    `sweep_active_fraction` series and the converged-sweep cost parity
    triple — distributed full-table vs distributed drained-frontier vs
    the CENTRALIZED frontier probe on the merged mesh at the same tet
    count. `frontier=False` is the A/B baseline (CLI -nofrontier)."""
    import dataclasses
    import time as _time

    import jax
    import jax.numpy as jnp

    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, merge_adapted, remesh_phase,
    )
    from parmmg_tpu.ops import quality

    from parmmg_tpu.kernels import registry as kernels_registry

    _enable_compile_cache()
    opts = DistOptions(
        niter=niter, hsiz=hsiz, max_sweeps=max_sweeps, hgrad=None,
        nparts=nparts, min_shard_elts=16, frontier=frontier,
        kernels=kernels,
    )
    if kernels is not None:
        kernels_registry.set_mode(kernels)
    kernels_on = any(
        kernels_registry.enabled(nm) for nm in kernels_registry.names()
    )
    _note_phase("dist-warmup")
    adapt_distributed(_workload(n, hsiz), opts)
    _note_phase("dist-steady")
    # migration / balance cost, first-class: cells + payload crossing
    # shards and the balancing-block wall during the TIMED run only
    # (the registry is process-global, so diff across the warmup)
    from parmmg_tpu.obs import metrics as obs_metrics

    _reg = obs_metrics.registry()
    _mig0 = (
        _reg.counter("migrate/cells_moved").value,
        _reg.counter("migrate/payload_bytes").value,
        _reg.counter("migrate/rebalances").value,
        _reg.histogram("migrate/wall_s").sum,
    )
    t0 = time.perf_counter()
    st, comm, info = adapt_distributed(_workload(n, hsiz), opts)
    wall = time.perf_counter() - t0
    migrate_cost = {
        "cells": _reg.counter("migrate/cells_moved").value - _mig0[0],
        "payload_bytes":
            _reg.counter("migrate/payload_bytes").value - _mig0[1],
        "rebalances":
            _reg.counter("migrate/rebalances").value - _mig0[2],
        "wall_s": round(
            _reg.histogram("migrate/wall_s").sum - _mig0[3], 4
        ),
    }
    merged = merge_adapted(st, comm)
    ne = int(merged.ntet)
    h = quality.quality_histogram(merged)
    saf = [
        r.get("active_fraction",
              r.get("n_active", 0) / max(r.get("n_unique", 1), 1))
        for r in info["history"] if "n_unique" in r
    ]
    # per-iteration load-imbalance factor (live-tets max/mean across
    # shards, from the driver history): the BENCH record carries the
    # WORST iteration so the perf gate can ratchet balance, and the
    # whole series for the report
    imb = [r["imbalance"] for r in info["history"] if "imbalance" in r]
    band = [r["in_band"] for r in info["history"] if "in_band" in r]

    _note_phase("dist-converged-probe")
    dist_cfg = dict(dist=True, n=n, hsiz=hsiz, nparts=nparts,
                    kernels="on" if kernels_on else "off")
    # distributed converged-iteration cost: one full-table sweep on the
    # converged stacked mesh (the legacy per-iteration floor) vs the
    # drained-frontier skip path
    hist: list = []
    probe_opts = dataclasses.replace(opts, frontier=False, verbose=0)
    hausd = 0.01

    def timed(fn, reps=2):
        fn()
        t0 = _time.perf_counter()
        for _ in range(reps):
            fn()
        return (_time.perf_counter() - t0) / reps

    t_full = timed(lambda: remesh_phase(
        st, probe_opts, [1.6], hist, 0, hausd
    ))
    fr_opts = dataclasses.replace(opts, frontier=True, verbose=0)
    drained = jnp.zeros((st.vert.shape[0], st.vert.shape[1]), bool)
    t_fr = timed(lambda: remesh_phase(
        st, fr_opts, [1.6], hist, 0, hausd, fr0=drained
    ))
    central = measure_converged_sweep(merged)
    return _envelope({
        "metric": "tets_per_sec_distributed",
        "value": round(ne / wall, 1),
        "unit": "tet/s",
        "vs_baseline": round(ne / wall / anchor, 3),
        "ne": ne,
        "nparts": nparts,
        "frontier": bool(frontier),
        "wall_s": round(wall, 2),
        "platform": jax.devices()[0].platform,
        "qmin": round(float(h.qmin), 5),
        "qavg": round(float(h.qavg), 5),
        "sweep_active_fraction": [round(x, 4) for x in saf],
        "imbalance": round(max(imb), 4) if imb else 0.0,
        "imbalance_series": [round(x, 4) for x in imb],
        "migrate_cost": migrate_cost,
        "len/in_band": band[-1] if band else 0.0,
        "in_band_series": band,
        # AOT lower+compile seconds this process paid (0.0 on untraced
        # runs — the cost capture is trace-gated), so wall comparisons
        # can exclude compile instead of warning about it
        "compile_s": _total_compile_s(),
        # the acceptance triple: dist frontier must be within 1.5x of
        # the centralized frontier sweep at equal tet count (was ~10x
        # full-table)
        "converged_sweep_cost": {
            "dist_full_s": round(t_full, 6),
            "dist_frontier_s": round(t_fr, 6),
            "central_frontier_s": central["frontier_s"],
            "central_full_s": central["full_s"],
            "dist_vs_central_frontier": round(
                t_fr / max(central["frontier_s"], 1e-9), 3
            ),
            "dist_full_vs_frontier": round(
                t_full / max(t_fr, 1e-9), 2
            ),
        },
        "kernels": "on" if kernels_on else "off",
    }, dist_cfg)


def _last_phase(text) -> str:
    for line in reversed((text or "").strip().splitlines()):
        if line.startswith("BENCH_PHASE "):
            return line[len("BENCH_PHASE "):].strip()
    return "startup"


def _attempt(cfg, tmo, env_extra=None):
    """Run one measurement in a subprocess; ALWAYS returns a record —
    the worker's full JSON line, the worker's own partial line (its
    SIGALRM stage deadline fired), or a parent-synthesized partial
    carrying the last BENCH_PHASE marker (the worker died inside one
    un-interruptible compile and the subprocess timeout killed it).
    The rc=124-with-nothing-committed failure mode is gone."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, **(env_extra or {}))
    if env.get("JAX_PLATFORMS") == "cpu":
        env.pop("PALLAS_AXON_POOL_IPS", None)
    # worker-side deadline just under the parent's hard kill: the
    # worker gets first shot at committing its partial record with the
    # in-process context (phase, platform) only it knows
    env["PARMMG_STAGE_BUDGET_S"] = str(max(int(tmo) - 45, 30))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker",
             json.dumps(cfg)],
            capture_output=True, text=True, timeout=tmo, cwd=here, env=env,
        )
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue  # truncated write (e.g. worker OOM-killed)
        return partial_record(
            cfg, died_in=_last_phase(out.stdout),
            reason=f"worker exited rc={out.returncode} with no record",
        )
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", errors="replace")
        return partial_record(
            cfg, died_in=_last_phase(stdout),
            reason=f"subprocess timeout after {int(tmo)}s",
        )


def main():
    """Print a parseable line EARLY, then improve on it — and exit 0
    inside the harness budget.

    The round-3 record was lost because the bench led with a 3300 s
    large-workload attempt and the harness outer timeout fired before
    any line was printed; round 4 printed its lines early (two TPU
    records landed) but the opportunistic ladder then overran the outer
    budget and the process died rc=124. Lessons applied: the default
    workload runs first under a tight cap and prints IMMEDIATELY; every
    subsequent attempt is admitted only if the REMAINING wall-clock
    budget covers its expected warm-cache duration, and its subprocess
    timeout is clipped to the remaining budget — so the bench always
    exits 0 with its record printed, whatever the cache state.
    """
    if "--worker" in sys.argv:
        cfg = json.loads(sys.argv[-1])
        _arm_stage_deadline()
        kw = {k: v for k, v in cfg.items() if k != "dist"}
        try:
            rec = run_dist(**kw) if cfg.get("dist") else run(**kw)
        except StageDeadline as e:
            # cfg keeps its dist marker: the partial record's envelope
            # (rung/metric) must match the full record this attempt
            # would have committed
            rec = partial_record(cfg, reason=str(e))
        signal.alarm(0)
        print(json.dumps(rec), flush=True)
        return

    t_start = time.monotonic()

    def remaining(reserve=45.0):
        return BUDGET_S - (time.monotonic() - t_start) - reserve

    def _score(r):
        """Record goodness: a full measurement beats a partial, TPU
        beats CPU, then raw throughput."""
        if r is None:
            return (-1, 0, 0.0)
        return (
            0 if r.get("partial") else 1,
            1 if r.get("platform") == "tpu" else 0,
            float(r.get("value", 0.0)),
        )

    def _full_tpu(r):
        return (r is not None and not r.get("partial")
                and r.get("platform") == "tpu")

    # 1. default workload on TPU, tight cap: the must-land line
    rec = _attempt(dict(n=10, hsiz=0.05, anchor=CPU_ANCHOR_TPS),
                   min(900, max(remaining(), 60)))
    if not _full_tpu(rec):
        # Cold compile cache: the fused-sweep program alone can exceed
        # the cap. The per-op (unfused) path compiles in small pieces —
        # each lands in the persistent cache, so even a timed-out
        # attempt makes the next one cheaper. Slightly slower execution
        # (per-sweep dispatch), far cheaper compile: the cold-cache
        # TPU line of last resort.
        tmo = remaining()
        if tmo > 120:
            rec2 = _attempt(dict(n=10, hsiz=0.05, anchor=CPU_ANCHOR_TPS),
                            min(1200, tmo), {"PARMMG_UNFUSED_TCAP": "0"})
            if _score(rec2) > _score(rec):
                rec = rec2
    if _full_tpu(rec):
        print(json.dumps(rec), flush=True)
    else:
        # tunnel unusable. If an attempt silently fell back to the CPU
        # backend its measurement is still honest (labeled via
        # "platform") — keep it rather than re-running; re-run on CPU
        # only when the TPU attempts produced no full record at all.
        cpu = rec if (rec is not None and not rec.get("partial")) else None
        if cpu is None and remaining() > 120:
            c2 = _attempt(dict(n=10, hsiz=0.05, anchor=CPU_ANCHOR_TPS),
                          min(600, remaining()), {"JAX_PLATFORMS": "cpu"})
            cpu = c2 if not c2.get("partial") else None
            if cpu is None and _score(c2) > _score(rec):
                rec = c2
        # the never-blind contract: a line is ALWAYS committed — the
        # best full record, else the best partial (which names the
        # stage/phase the budget died in), never rc=124 silence
        best = cpu if cpu is not None else rec
        if best is None:
            best = _envelope({
                "metric": "tets_per_sec", "value": 0.0, "unit": "tet/s",
                "vs_baseline": 0.0, "partial": True,
                "error": "all attempts timed out",
            }, dict(n=10, hsiz=0.05))
        print(json.dumps(best), flush=True)
        return

    # 2. opportunistic ladder toward the 10M-tet north star: n=12
    # (proven), n=14 (~440k), n=16 (~1.2M — the scale rung, cache
    # pre-warmed in-round by tools/scale_pipeline.py). est = expected
    # warm-cache wall for warmup+timed runs + interpreter/cache-load
    # slack; a rung is attempted only if the remaining budget covers
    # it, so a cold cache burns bounded time and the process still
    # exits 0. A line is printed only when it improves the record:
    # parsed, on-TPU, larger workload than the previous line.
    fails = 0
    for cfg, est in (
        (dict(n=12, hsiz=0.04, anchor=CPU_ANCHOR_TPS_LARGE), 240),
        (dict(n=14, hsiz=0.03, anchor=CPU_ANCHOR_TPS_XL), 500),
        # warm-cache estimate; only reachable when the earlier rungs
        # finish fast (or with a raised PARMMG_BENCH_BUDGET_S) — the
        # canonical 1M-tet record lives in SCALE_RUNS.jsonl either way
        (dict(n=16, hsiz=0.02, anchor=CPU_ANCHOR_TPS_XL,
              max_sweeps=20, tight=True), 900),
    ):
        tmo = remaining()
        if tmo < est:
            break
        big = _attempt(cfg, tmo)
        if big is not None:
            # full OR partial: every attempted rung commits its line
            # (a partial one records which phase ate the budget)
            print(json.dumps(big), flush=True)
        if _full_tpu(big):
            continue
        if fails:
            break  # two cold/failed rungs: the tunnel won't yield more
        # one failed rung doesn't preclude a LARGER warm one (cache
        # warming targets the scale rungs first); budget still gates
        fails = 1

    # distributed-frontier rung (round 8): the adapt-vs-distributed
    # converged-sweep parity record — small workload (compile cost
    # dominates the distributed driver), admitted only with budget
    # to spare; its line is additional, never replaces the headline
    tmo = remaining()
    if tmo > 240:
        drec = _attempt(
            dict(dist=True, n=8, hsiz=0.08, nparts=2), min(900, tmo)
        )
        if drec is not None:
            print(json.dumps(drec), flush=True)


if __name__ == "__main__":
    main()
