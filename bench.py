"""Benchmark: adapt a refined unit cube to a uniform size map and report
remeshing throughput as ONE JSON line.

Workload: cube n=10 (6,000 input tets) -> hsiz=0.05 (~110k output tets),
the shape of the reference CI adaptation runs
(`cmake/testing/pmmg_tests.cmake:30-50`, `-mesh-size`-class workloads).

Baseline note (BASELINE.md): the reference ParMmg binary cannot be built
in this environment (its Mmg/Metis dependencies are CMake
ExternalProjects requiring network download, and no MPI toolchain is
installed), so the recorded anchor is this framework's own steady-state
throughput on the host CPU backend for the identical workload —
an honest same-algorithm hardware comparison. vs_baseline therefore
reads as "accelerator speedup over the CPU execution".

Robustness: XLA compilation over the shared TPU tunnel has a highly
variable latency (observed 1-10x swings), so the measurement runs in a
subprocess with its own timeout and falls back to a smaller workload —
the driver always gets a parseable line.
"""

import json
import os
import subprocess
import sys
import time

# steady-state tets/sec of the default workload on the host CPU backend
# (measured with a warm jit cache; see BASELINE.md "CPU anchor" row).
# Round-2 M5/M6 kernels measured 1367.3; early round-3 kernel work
# (packed sorts, fused sweep loop, scatter layer) measured 2128.2;
# re-measured 2026-07-31 with the second round-3 pass (seg_broadcast,
# early-exit MIS, platform-aware lowering): 93,828 output tets in
# 46.8 s. Host wall-clock drifts a few percent with machine load —
# anchors are refreshed the same day as the TPU measurement so
# vs_baseline stays an honest same-code same-day hardware ratio.
CPU_ANCHOR_TPS = 2003.5
# CPU anchor for the small fallback workload (n=8, hsiz=0.08),
# same-day measurement (24,604 output tets in 4.09 s)
CPU_ANCHOR_TPS_SMALL = 6015.7
# CPU anchor for the large workload (n=12, hsiz=0.04 -> ~201k tets,
# same-day: 201,166 tets in 189.7 s). The CPU halves its rate at this
# size (working set leaves cache) while the TPU holds steady — the
# large config is the representative point for the 10M-tet north star.
CPU_ANCHOR_TPS_LARGE = 1060.3


def _workload(n, hsiz):
    """Mesh pre-sized so the whole adaptation stays in ONE capacity
    bucket: every kernel compiles exactly once (compile over the TPU
    tunnel costs minutes; execution costs seconds)."""
    from parmmg_tpu.utils.gen import unit_cube_mesh

    est = int(12.0 / hsiz**3)
    return unit_cube_mesh(
        n,
        tcap=int(est * 1.9),
        pcap=max(int(est * 0.45), 4096),
        fcap=max(int(est * 0.30), 4096),
    )


def run(n=10, hsiz=0.05, niter=1, max_sweeps=12, anchor=CPU_ANCHOR_TPS):
    import jax

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import quality

    opts = AdaptOptions(niter=niter, hsiz=hsiz, max_sweeps=max_sweeps, hgrad=None)

    # warmup run: pays every jit compile; the timed run below hits the
    # in-process executable cache (same static shapes by construction)
    adapt(_workload(n, hsiz), opts)

    mesh = _workload(n, hsiz)
    t0 = time.perf_counter()
    out, info = adapt(mesh, opts)
    wall = time.perf_counter() - t0

    ne = int(out.ntet)
    h = quality.quality_histogram(out)
    tps = ne / wall
    return {
        "metric": "tets_per_sec",
        "value": round(tps, 1),
        "unit": "tet/s",
        "vs_baseline": round(tps / anchor, 3),
        "ne": ne,
        "wall_s": round(wall, 2),
        "platform": jax.devices()[0].platform,
        "qmin": round(float(h.qmin), 5),
        "qavg": round(float(h.qavg), 5),
    }


_CONFIGS = [
    # (args, per-attempt timeout seconds, extra env). The TPU attempts
    # get long budgets: remote compilation of the fused sweep
    # while_loop over the tunnel takes 10-45 minutes cold (execution is
    # seconds) — a short timeout records a CPU fallback even though the
    # TPU run would succeed (that is exactly what happened in round 2).
    # The large config goes first: it is where the TPU advantage shows
    # (2.39x same-day CPU at ~204k tets vs 1.37x at ~94k; measured
    # 2026-07-31) and the closest in-reach point to the 10M-tet target.
    (dict(n=12, hsiz=0.04, anchor=CPU_ANCHOR_TPS_LARGE), 3300, {}),
    (dict(n=10, hsiz=0.05, anchor=CPU_ANCHOR_TPS), 1800, {}),
    (dict(n=8, hsiz=0.08, anchor=CPU_ANCHOR_TPS_SMALL), 600, {}),
    # last resort when the TPU tunnel is unusable: the same measurement
    # on the host CPU backend, honestly labeled via the "platform" field
    (dict(n=10, hsiz=0.05, anchor=CPU_ANCHOR_TPS), 480,
     {"JAX_PLATFORMS": "cpu"}),
]


def main():
    if "--worker" in sys.argv:
        cfg = json.loads(sys.argv[-1])
        print(json.dumps(run(**cfg)), flush=True)
        return

    here = os.path.dirname(os.path.abspath(__file__))
    for cfg, tmo, env_extra in _CONFIGS:
        try:
            env = dict(os.environ, **env_extra)
            if env_extra.get("JAX_PLATFORMS") == "cpu":
                env.pop("PALLAS_AXON_POOL_IPS", None)
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 json.dumps(cfg)],
                capture_output=True, text=True, timeout=tmo, cwd=here,
                env=env,
            )
            for line in reversed(out.stdout.strip().splitlines()):
                if line.startswith("{"):
                    print(line)
                    return
        except subprocess.TimeoutExpired:
            continue
    # every attempt timed out (tunnel unusable): still emit a line
    print(json.dumps({
        "metric": "tets_per_sec", "value": 0.0, "unit": "tet/s",
        "vs_baseline": 0.0, "error": "all attempts timed out",
    }))


if __name__ == "__main__":
    main()
