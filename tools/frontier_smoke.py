"""Distributed-frontier smoke gate (round 8, tools/check.sh stage).

Asserts the active-set carry through the distributed SPMD/vmapped path
actually behaves — on a 2-shard tiny fixture, CPU, minutes not hours:

  1. DRAIN: with frozen interfaces (-nobalance) a converged run's
     `sweep_active_fraction` must drain to 0 and the converged
     iterations must take the drained-skip path (zero ops, identity).
  2. EQUIVALENCE: frontier on/off on the balanced driver must produce
     conformal merged meshes of the same element count and quality
     class (the test_m12 discipline, driver-level).
  3. COST: the drained-frontier converged phase must not cost more
     than the full-table converged phase (the 10x lever this PR moves
     to the distributed drivers; the committed BENCH JSON records the
     real ratio at bench scale).
  4. TELEMETRY: the metrics registry must carry the world
     `sweep_active_fraction` gauge and the per-shard gauges the obs
     report renders.

Exit 0 on success; any assertion prints FAIL and exits 1.
"""

import dataclasses
import sys
import time

from _cli import REPO, parse_argv  # noqa: F401


def main() -> int:
    import numpy as np

    import jax.numpy as jnp

    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, merge_adapted, remesh_phase,
    )
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.ops import quality
    from parmmg_tpu.utils.conformity import check_mesh
    from parmmg_tpu.utils.gen import unit_cube_mesh

    obs_metrics.registry().reset()
    t0 = time.time()
    base = dict(nparts=2, niter=4, hsiz=0.25, max_sweeps=8,
                min_shard_elts=16, hgrad=None)

    # --- 1. drain + skip (frozen interfaces keep the carry honest) ----
    opts = DistOptions(frontier=True, nobalancing=True, **base)
    st, comm, info = adapt_distributed(unit_cube_mesh(4), opts)
    hist = [r for r in info["history"] if "n_unique" in r]
    assert hist, "no sweep records"
    last = hist[-1]
    assert last.get("active_fraction", 1.0) == 0.0, (
        f"FAIL: active fraction did not drain: {last}"
    )
    assert last.get("skipped"), (
        f"FAIL: converged iteration did not take the drained-skip "
        f"path: {last}"
    )
    skips = sum(1 for r in hist if r.get("skipped"))
    print(f"## drain: {skips} drained-skip iteration(s), final "
          f"active_fraction={last['active_fraction']}", flush=True)

    # --- 2. frontier on/off equivalence on the balanced driver --------
    outs = {}
    for frontier in (True, False):
        opts = DistOptions(frontier=frontier, **base)
        st, comm, info = adapt_distributed(unit_cube_mesh(4), opts)
        merged = merge_adapted(st, comm)
        rep = check_mesh(merged)
        assert rep.ok, f"FAIL: frontier={frontier} not conformal: {rep}"
        h = quality.quality_histogram(merged)
        outs[frontier] = (int(merged.ntet), float(h.qmin), float(h.qavg))
    ne_f, qmin_f, qavg_f = outs[True]
    ne_t, qmin_t, qavg_t = outs[False]
    assert abs(ne_f - ne_t) <= max(0.02 * ne_t, 16), (ne_f, ne_t)
    assert abs(qmin_f - qmin_t) < 0.05, (qmin_f, qmin_t)
    assert abs(qavg_f - qavg_t) < 0.02, (qavg_f, qavg_t)
    print(f"## equivalence: frontier ne={ne_f} qmin={qmin_f:.4f} vs "
          f"full ne={ne_t} qmin={qmin_t:.4f}", flush=True)

    # --- 3. converged-phase cost: drained skip <= full table ----------
    hist2: list = []
    full_opts = dataclasses.replace(opts, frontier=False, verbose=0)
    fr_opts = dataclasses.replace(opts, frontier=True, verbose=0)
    drained = jnp.zeros((st.vert.shape[0], st.vert.shape[1]), bool)

    def timed(fn, reps=2):
        fn()
        t = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t) / reps

    t_full = timed(lambda: remesh_phase(
        st, full_opts, [1.6], hist2, 0, 0.01
    ))
    t_fr = timed(lambda: remesh_phase(
        st, fr_opts, [1.6], hist2, 0, 0.01, fr0=drained
    ))
    assert t_fr <= t_full * 1.05, (
        f"FAIL: drained frontier phase ({t_fr * 1e3:.1f} ms) costs more "
        f"than full table ({t_full * 1e3:.1f} ms)"
    )
    print(f"## converged phase: full {t_full * 1e3:.1f} ms vs drained "
          f"{t_fr * 1e3:.1f} ms ({t_full / max(t_fr, 1e-9):.1f}x)",
          flush=True)

    # --- 4. telemetry: world + per-shard gauges -----------------------
    doc = obs_metrics.registry().to_doc()
    gauges = doc["gauges"]
    assert "sweep_active_fraction" in gauges, gauges.keys()
    shard_gauges = [k for k in gauges
                    if k.startswith("sweep_active_fraction/shard")]
    assert len(shard_gauges) >= 2, (
        f"FAIL: per-shard active gauges missing: {sorted(gauges)}"
    )
    print(f"## telemetry: {len(shard_gauges)} per-shard gauges, world "
          f"gauge={gauges['sweep_active_fraction']}", flush=True)

    print(f"## frontier-smoke OK in {time.time() - t0:.1f}s", flush=True)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"FAIL: {e}", flush=True)
        sys.exit(1)
