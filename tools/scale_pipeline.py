"""Chained scale ladder: warm + run each rung, append records to
SCALE_RUNS.jsonl. Designed to run unattended for hours in the
background while other work proceeds: each rung is independent, a
failed warm still runs the measurement (the watchdogged scale_run pays
the remaining compiles itself), and every completed record is flushed
to disk immediately.

Rungs climb toward the 10M-tet north star (BASELINE.json): n=14/0.03
(~440k tets — the regime that has never completed on the TPU) then
n=16/0.0229 (>=1M tets — the round-5 headline).

Usage: python tools/scale_pipeline.py [--only RUNG]
"""

import json
import os
import subprocess
import sys
import time

from _cli import REPO, parse_argv  # noqa: F401

RUNGS = [
    # (name, n, hsiz, warm_stall, run_stall, run_retries, tight)
    ("m", 14, 0.03, 2100, 2100, 4, False),
    # hsiz 0.02 -> est 1.5M predicted output tets: the n=14 record
    # shows the CONVERGED count lands near 0.72-0.75x the est formula
    # (coarsening continues past the growth phase), so this sizing puts
    # the final mesh at ~1.05-1.1M — safely over the 1M bar. Tight
    # capacity sizing: at these shapes XLA compile time tracks array
    # size, and the default 1.9x headroom put the cold analysis
    # compile past the 90-min stall limit.
    ("xl", 16, 0.02, 5400, 5400, 3, True),
]

OUT = os.path.join(REPO, "SCALE_RUNS.jsonl")


def run_rung(name, n, hsiz, warm_stall, run_stall, retries, tight=False):
    t0 = time.time()
    tflag = ["--tight", "1"] if tight else []
    print(f"#### rung {name}: warm n={n} hsiz={hsiz} tight={tight}",
          flush=True)
    warm = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_ops.py"),
         str(n), str(hsiz), "--stall", str(warm_stall)] + tflag,
        cwd=REPO)
    print(f"#### rung {name}: warm rc={warm.returncode} "
          f"({round(time.time() - t0)}s); measuring", flush=True)
    t1 = time.time()
    rec = None
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "scale_run.py"),
         str(n), str(hsiz), "--stall", str(run_stall),
         "--retries", str(retries)] + tflag,
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    for line in p.stdout:  # stream: progress is visible in the log live
        sys.stdout.write(line)
        sys.stdout.flush()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                pass
    p.wait()
    if rec is not None:
        rec["rung"] = name
        rec["warm_rc"] = warm.returncode
        rec["warm_s"] = round(t1 - t0, 1)
        rec["measure_s"] = round(time.time() - t1, 1)
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"#### rung {name}: RECORDED {rec}", flush=True)
    else:
        print(f"#### rung {name}: NO RECORD", flush=True)
    return rec


def main():
    _, flags = parse_argv(sys.argv[1:])
    only = flags.get("only")
    for rung in RUNGS:
        if only and rung[0] != only:
            continue
        run_rung(*rung)


if __name__ == "__main__":
    main()
