"""Print the per-sweep history of a bench workload (CPU or TPU) — which
sweeps are split-dominant vs quality-dominant, to guide phase-aware
scheduling of the sweep body."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    hsiz = float(sys.argv[2]) if len(sys.argv) > 2 else 0.05

    bench._enable_compile_cache()
    from parmmg_tpu.models.adapt import AdaptOptions, adapt

    mesh = bench._workload(n, hsiz)
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=12, hgrad=None)
    t0 = time.perf_counter()
    out, info = adapt(mesh, opts)
    wall = time.perf_counter() - t0
    print(f"wall={wall:.1f}s ne={int(out.ntet)}")
    for r in info["history"]:
        print(
            f"it{r['iter']} sw{r['sweep']:2d}: split={r['nsplit']:6d} "
            f"coll={r['ncollapse']:6d} swap={r['nswap']:6d} "
            f"moved={r['nmoved']:6d} ne={r['ne']:7d} capped={r['capped']}"
        )


if __name__ == "__main__":
    main()
