"""Print the per-sweep history of a bench workload (CPU or TPU) — which
sweeps are split-dominant vs quality-dominant, to guide phase-aware
scheduling of the sweep body. Rendering is `obs.health`'s single
sweep-history formatter (round 12), so this tool, `obs_report
--health` and the health smoke all print the same rows."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    hsiz = float(sys.argv[2]) if len(sys.argv) > 2 else 0.05

    bench._enable_compile_cache()
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.obs import health

    mesh = bench._workload(n, hsiz)
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=12, hgrad=None)
    t0 = time.perf_counter()
    out, info = adapt(mesh, opts)
    wall = time.perf_counter() - t0
    print(f"wall={wall:.1f}s ne={int(out.ntet)}")
    print(health.format_history_rows(info["history"]))


if __name__ == "__main__":
    main()
