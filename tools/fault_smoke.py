"""Fault-injection smoke for the CI gate (tools/check.sh).

Exercises one scenario per recovery family on the small synthetic
fixture, end to end through the public drivers:

1. NaN poisoning (``it1:remesh:nan``) — the phase-boundary validator
   must catch it and the run must degrade to LOWFAILURE with a
   conformal, saveable mesh and a ``failure`` history entry;
2. capacity overflow (``it0:remesh:overflow``) — the bounded
   grow-and-retry loop must absorb it and still return SUCCESS;
3. kill/resume — a subprocess (this script with ``--worker``) is killed
   by an injected preemption (os._exit) at an iteration boundary; the
   parent resumes from the atomic checkpoint and must reproduce the
   uninterrupted run's mesh counts and quality histogram.

``--multihost`` runs the 2-process stage instead (its own check.sh
gate, between this smoke and tier-1): four phases of
``tests/multihost_worker.py --failsafe`` under the PMMGTPU_* env —
(A) an uninterrupted 2-process run for the reference digest; (B) the
same run with a rank-targeted ``it0:post:kill@rank1`` fault and a
sharded checkpoint directory: rank 1 must exit with KILL_EXIT_CODE
after the barrier-committed checkpoint and rank 0's collective
watchdog must convert the silent peer loss into PeerLostError
(PEER_LOST_EXIT_CODE) instead of hanging; (C) a 2-process resume from
the sharded checkpoint, which must reproduce phase A's merged-mesh
digest bit for bit; (D) an ELASTIC resume of the same 2-rank
checkpoint at world size 1 (one controller owning all 8 devices,
PMMGTPU_SPMD_SWEEPS=1 so the identical SPMD sweep programs run) —
the re-concatenated state must continue to the same digest bit for
bit.

Run hermetically on CPU: ``python tools/fault_smoke.py``. Exit 0 =
every scenario behaved; any unhandled exception or mismatch fails the
gate.
"""

import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from parmmg_tpu import failsafe  # noqa: E402
from parmmg_tpu.core.tags import ReturnStatus  # noqa: E402
from parmmg_tpu.io import medit  # noqa: E402
from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.obs import trace as obs_trace  # noqa: E402
from parmmg_tpu.obs.report import load_timeline  # noqa: E402
from parmmg_tpu.utils.conformity import check_mesh  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402

OPTS = dict(hsiz=0.35, niter=2, max_sweeps=4, hgrad=None,
            polish_sweeps=0)


def _fault_kinds(obs_dir):
    """Injected-fault kinds present in a trace directory's JSONL
    timeline (what every chaos seed must leave next to its log)."""
    return [
        r.get("args", {}).get("kind")
        for r in load_timeline(obs_dir)
        if r.get("type") == "event" and r.get("name") == "fault_injected"
    ]


def _key(mesh, info):
    h = info["qual_out"]
    return (
        int(mesh.npoin), int(mesh.ntet),
        tuple(int(x) for x in np.asarray(jax.device_get(h.counts))),
    )


def worker(ckdir: str) -> None:
    """Child mode: run with checkpointing; PARMMG_FAULTS (set by the
    parent) kills this process at the scheduled boundary."""
    adapt(unit_cube_mesh(3), AdaptOptions(**OPTS), checkpoint_dir=ckdir)
    print("worker finished without being killed", flush=True)
    sys.exit(3)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_fault_smoke_")
    try:
        # --- scenario 1: NaN -> LOWFAILURE + conformal + saveable -----
        # run under an explicit tracer: the injected fault and the
        # rollback that absorbed it must land in the JSONL timeline
        obs_nan = os.path.join(tmp, "obs_nan")
        out, info = adapt(
            unit_cube_mesh(3),
            AdaptOptions(faults="it1:remesh:nan", **OPTS),
            tracer=obs_trace.Tracer(obs_nan),
        )
        assert info["status"] == ReturnStatus.LOWFAILURE, info["status"]
        assert any("failure" in r for r in info["history"])
        assert check_mesh(out, check_boundary=False).ok
        medit.save_mesh(out, os.path.join(tmp, "nan.mesh"))
        assert "nan" in _fault_kinds(obs_nan), _fault_kinds(obs_nan)
        assert any(
            r.get("name") == "rollback" for r in load_timeline(obs_nan)
        ), "rollback missing from the event timeline"
        print("[fault-smoke] nan: LOWFAILURE + conformal + saved OK "
              "(+ fault/rollback events in the obs timeline)")

        # --- scenario 2: overflow -> grow-and-retry SUCCESS -----------
        out, info = adapt(
            unit_cube_mesh(3),
            AdaptOptions(faults="it0:remesh:overflow", **OPTS),
        )
        assert info["status"] == ReturnStatus.SUCCESS, info["status"]
        assert any("failure" in r for r in info["history"])
        print("[fault-smoke] overflow: recovered to SUCCESS")

        # --- scenario 3: kill + resume --------------------------------
        ref, ref_info = adapt(unit_cube_mesh(3), AdaptOptions(**OPTS))
        ckdir = os.path.join(tmp, "ckpt")
        obs_kill = os.path.join(tmp, "obs_kill")
        env = dict(os.environ, PARMMG_FAULTS="it0:post:kill",
                   PMMGTPU_TRACE=obs_kill)
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", ckdir],
            env=env, capture_output=True, text=True, timeout=1500,
        )
        assert p.returncode == failsafe.KILL_EXIT_CODE, (
            p.returncode, p.stdout[-2000:], p.stderr[-2000:],
        )
        assert not [f for f in sorted(os.listdir(ckdir)) if ".tmp." in f], (
            "atomic write left temp files behind"
        )
        # the per-line JSONL flush must survive the worker's os._exit:
        # the kill is IN the timeline even though flush() never ran
        assert "kill" in _fault_kinds(obs_kill), _fault_kinds(obs_kill)
        assert any(
            r.get("name") == "checkpoint_commit"
            for r in load_timeline(obs_kill)
        ), "checkpoint commit missing from the killed worker's timeline"
        res, res_info = adapt(
            unit_cube_mesh(3), AdaptOptions(**OPTS), checkpoint_dir=ckdir
        )
        assert _key(res, res_info) == _key(ref, ref_info), (
            _key(res, res_info), _key(ref, ref_info),
        )
        print("[fault-smoke] kill/resume: resumed run matches "
              "uninterrupted run (kill + ckpt commit in the obs "
              "timeline)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run_pair(worker, tmp, tag, extra_env, timeout=900):
    """Launch 2 coordinated worker processes (4 CPU devices each) and
    wait; returns (exit codes, log texts)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=root,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
            # a wedged worker can be SIGABRT'ed for a Python stack
            PYTHONFAULTHANDLER="1",
        )
        env.update(extra_env)
        lp = os.path.join(tmp, f"{tag}{pid}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, worker, "--failsafe"], env=env,
            stdout=open(lp, "w"), stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        rcs = [p.wait(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            p.kill()
    return rcs, [open(lp).read() for lp in logs]


def _run_single(worker, tmp, tag, extra_env, timeout=900):
    """One UN-coordinated worker process owning all 8 CPU devices (the
    world-size-1 elastic-resume leg); returns (exit code, log text)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for k in ("PMMGTPU_COORDINATOR", "PMMGTPU_NUM_PROCS",
              "PMMGTPU_PROC_ID"):
        env.pop(k, None)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=root,
        # run the IDENTICAL SPMD sweep programs single-process so the
        # continued trajectory is bit-comparable to the 2-process runs
        PMMGTPU_SPMD_SWEEPS="1",
        PYTHONFAULTHANDLER="1",
    )
    env.update(extra_env)
    lp = os.path.join(tmp, f"{tag}.log")
    p = subprocess.run(
        [sys.executable, worker, "--failsafe"], env=env,
        stdout=open(lp, "w"), stderr=subprocess.STDOUT, cwd=root,
        timeout=timeout,
    )
    return p.returncode, open(lp).read()


def _digest_lines(text):
    return [ln for ln in text.splitlines()
            if ln.startswith("ADAPT_DIGEST")]


def main_multihost() -> int:
    """The 2-process kill/peer-lost/resume stage (see module
    docstring). Uses the same worker as tests/test_m10_multihost.py so
    the gate and the slow tests exercise one code path."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")
    tmp = tempfile.mkdtemp(prefix="parmmg_mh_smoke_")
    ck = os.path.join(tmp, "ck")
    try:
        rcs, logs = _run_pair(
            worker, tmp, "ref", {"PMMGTPU_WATCHDOG": "300"}
        )
        assert rcs == [0, 0], (rcs, logs[0][-2000:], logs[1][-2000:])
        ref = _digest_lines(logs[0])
        assert ref and _digest_lines(logs[1]) == ref, logs[0][-2000:]
        print(f"[mh-smoke] reference run: {ref[0]}")

        rcs, logs = _run_pair(worker, tmp, "kill", {
            "PMMGTPU_CKPT_DIR": ck,
            "PMMGTPU_WATCHDOG": "60",
            "PARMMG_FAULTS": "it0:post:kill@rank1",
        })
        assert rcs[1] == failsafe.KILL_EXIT_CODE, (
            rcs, logs[1][-2000:],
        )
        assert rcs[0] == failsafe.PEER_LOST_EXIT_CODE, (
            rcs, logs[0][-2000:],
        )
        names = sorted(os.listdir(ck))
        assert names == ["ckpt_00000.json", "ckpt_00000.proc0.npz",
                         "ckpt_00000.proc1.npz"], names
        assert not [f for f in names if ".tmp." in f]
        print("[mh-smoke] kill@rank1: rank1 exited "
              f"{failsafe.KILL_EXIT_CODE} after the barrier-committed "
              f"checkpoint; rank0 converted the silent peer loss into "
              f"PeerLostError (exit {failsafe.PEER_LOST_EXIT_CODE})")

        # snapshot the kill checkpoint BEFORE any resume consumes it:
        # each resume leg gets its own copy (a resumed run writes new
        # checkpoints into the directory and GCs the old ones)
        ck1 = os.path.join(tmp, "ck_elastic")
        shutil.copytree(ck, ck1)

        rcs, logs = _run_pair(worker, tmp, "resume", {
            "PMMGTPU_CKPT_DIR": ck, "PMMGTPU_WATCHDOG": "300",
        })
        assert rcs == [0, 0], (rcs, logs[0][-2000:], logs[1][-2000:])
        got = _digest_lines(logs[0])
        assert got == ref and _digest_lines(logs[1]) == ref, (got, ref)
        print("[mh-smoke] 2-process resume from the sharded checkpoint "
              "matches the uninterrupted run bit for bit")

        # elastic resume: the SAME 2-rank manifest restarts at world
        # size 1 — all shard files digest-verified, re-concatenated,
        # and the continued run must land on the reference digest
        rc, log = _run_single(worker, tmp, "elastic", {
            "PMMGTPU_CKPT_DIR": ck1,
        })
        assert rc == 0, (rc, log[-2000:])
        got = _digest_lines(log)
        assert got == ref, (got, ref)
        print("[mh-smoke] ELASTIC resume (2-rank checkpoint -> world "
              "size 1) matches the uninterrupted run bit for bit")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    if len(sys.argv) > 1 and sys.argv[1] == "--multihost":
        sys.exit(main_multihost())
    sys.exit(main())
