"""Fault-injection smoke for the CI gate (tools/check.sh).

Exercises one scenario per recovery family on the small synthetic
fixture, end to end through the public drivers:

1. NaN poisoning (``it1:remesh:nan``) — the phase-boundary validator
   must catch it and the run must degrade to LOWFAILURE with a
   conformal, saveable mesh and a ``failure`` history entry;
2. capacity overflow (``it0:remesh:overflow``) — the bounded
   grow-and-retry loop must absorb it and still return SUCCESS;
3. kill/resume — a subprocess (this script with ``--worker``) is killed
   by an injected preemption (os._exit) at an iteration boundary; the
   parent resumes from the atomic checkpoint and must reproduce the
   uninterrupted run's mesh counts and quality histogram.

Run hermetically on CPU: ``python tools/fault_smoke.py``. Exit 0 =
every scenario behaved; any unhandled exception or mismatch fails the
gate.
"""

import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from parmmg_tpu import failsafe  # noqa: E402
from parmmg_tpu.core.tags import ReturnStatus  # noqa: E402
from parmmg_tpu.io import medit  # noqa: E402
from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.utils.conformity import check_mesh  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402

OPTS = dict(hsiz=0.35, niter=2, max_sweeps=4, hgrad=None,
            polish_sweeps=0)


def _key(mesh, info):
    h = info["qual_out"]
    return (
        int(mesh.npoin), int(mesh.ntet),
        tuple(int(x) for x in np.asarray(jax.device_get(h.counts))),
    )


def worker(ckdir: str) -> None:
    """Child mode: run with checkpointing; PARMMG_FAULTS (set by the
    parent) kills this process at the scheduled boundary."""
    adapt(unit_cube_mesh(3), AdaptOptions(**OPTS), checkpoint_dir=ckdir)
    print("worker finished without being killed", flush=True)
    sys.exit(3)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_fault_smoke_")
    try:
        # --- scenario 1: NaN -> LOWFAILURE + conformal + saveable -----
        out, info = adapt(
            unit_cube_mesh(3),
            AdaptOptions(faults="it1:remesh:nan", **OPTS),
        )
        assert info["status"] == ReturnStatus.LOWFAILURE, info["status"]
        assert any("failure" in r for r in info["history"])
        assert check_mesh(out, check_boundary=False).ok
        medit.save_mesh(out, os.path.join(tmp, "nan.mesh"))
        print("[fault-smoke] nan: LOWFAILURE + conformal + saved OK")

        # --- scenario 2: overflow -> grow-and-retry SUCCESS -----------
        out, info = adapt(
            unit_cube_mesh(3),
            AdaptOptions(faults="it0:remesh:overflow", **OPTS),
        )
        assert info["status"] == ReturnStatus.SUCCESS, info["status"]
        assert any("failure" in r for r in info["history"])
        print("[fault-smoke] overflow: recovered to SUCCESS")

        # --- scenario 3: kill + resume --------------------------------
        ref, ref_info = adapt(unit_cube_mesh(3), AdaptOptions(**OPTS))
        ckdir = os.path.join(tmp, "ckpt")
        env = dict(os.environ, PARMMG_FAULTS="it0:post:kill")
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", ckdir],
            env=env, capture_output=True, text=True, timeout=1500,
        )
        assert p.returncode == failsafe.KILL_EXIT_CODE, (
            p.returncode, p.stdout[-2000:], p.stderr[-2000:],
        )
        assert not [f for f in os.listdir(ckdir) if ".tmp." in f], (
            "atomic write left temp files behind"
        )
        res, res_info = adapt(
            unit_cube_mesh(3), AdaptOptions(**OPTS), checkpoint_dir=ckdir
        )
        assert _key(res, res_info) == _key(ref, ref_info), (
            _key(res, res_info), _key(ref, ref_info),
        )
        print("[fault-smoke] kill/resume: resumed run matches "
              "uninterrupted run")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    sys.exit(main())
