"""Distributed-observability smoke for the CI gate (check.sh dist-obs).

The round-11 acceptance, end to end on the 2-process CPU fixture: a
traced 2-rank `adapt_stacked_input` run (each rank owning 4 of the 8
CPU devices, collectives crossing the process boundary) must leave a
trace directory from which the cross-rank observatory reconstructs:

1. **aligned timelines** — both ranks' clock segments carry a
   synced offset (``sync_tracer_clock``'s median-of-K estimate, rank 0
   exactly 0) and the aligned per-rank timelines are monotone;
2. **collective decomposition** — the ``coll:*`` spans match across
   ranks and split into nonzero straggler-lag + transfer, with a
   worst-straggler rank named per phase and per-rank ``comm/wait_s``
   both in the report and in the always-on metrics gauges;
3. **imbalance in the bench record** — the per-iteration live-tets
   max/mean factor rides the history records and lands in the PERF_DB
   envelope (gate key ``imbalance``) exactly as `bench.run_dist`
   publishes it;
4. **critical path** — per-iteration rows naming the gating rank and
   phase render, and the merged Perfetto trace is written.

Run hermetically on CPU: ``python tools/dist_obs_smoke.py``; exit 0 =
the whole pipeline behaved. ``--worker`` is the child mode (do not run
directly). Budget knob: PARMMG_STAGE_BUDGET_S bounds the worker wait.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def worker() -> int:
    """Child mode: one rank of the traced 2-process adapt run. The
    PMMGTPU_* env (coordinator, trace dir, watchdog) comes from the
    parent; prints DIST_IMB with the per-iteration imbalance series so
    the parent can build the bench record without a second run."""
    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input,
    )
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    assert multi and jax.process_count() == 2, "2-process env required"
    watchdog = float(os.environ.get("PMMGTPU_WATCHDOG", "120"))

    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    opts = DistOptions(
        hsiz=0.32, niter=2, max_sweeps=4, nparts=8, min_shard_elts=8,
        hgrad=None, polish_sweeps=0, watchdog_timeout=watchdog,
    )
    try:
        _out, _comm2, info = adapt_stacked_input(st, comm, opts)
    except failsafe.PeerLostError as e:
        print(f"PEER_LOST rank={jax.process_index()}: {e}", flush=True)
        os._exit(failsafe.PEER_LOST_EXIT_CODE)
    imb = [r["imbalance"] for r in info["history"]
           if "imbalance" in r]
    print(f"DIST_IMB {json.dumps(imb)}", flush=True)
    print(f"DIST_OK rank={jax.process_index()} "
          f"status={int(info['status'])}", flush=True)
    return 0


def _spawn_pair(tmp: str, obs: str, timeout: float):
    """fault_smoke's 2-process launch idiom, plus PMMGTPU_TRACE."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=ROOT,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
            PMMGTPU_TRACE=obs,
            PMMGTPU_WATCHDOG="120",
            PYTHONFAULTHANDLER="1",
        )
        lp = os.path.join(tmp, f"rank{pid}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=open(lp, "w"),
            stderr=subprocess.STDOUT, cwd=ROOT,
        ))
    try:
        rcs = [p.wait(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            p.kill()
    return rcs, [open(lp).read() for lp in logs]


def main() -> int:
    budget = float(os.environ.get("PARMMG_STAGE_BUDGET_S", "600"))
    tmp = tempfile.mkdtemp(prefix="parmmg_dist_obs_")
    obs = os.path.join(tmp, "obs")
    try:
        rcs, logs = _spawn_pair(tmp, obs, timeout=budget)
        if rcs != [0, 0]:
            for i, log in enumerate(logs):
                print(f"---- rank{i} log ----\n{log[-4000:]}",
                      file=sys.stderr)
            print(f"[dist-obs] worker exits {rcs}", file=sys.stderr)
            return 1
        assert all("DIST_OK" in log for log in logs), "no DIST_OK"

        from parmmg_tpu.obs import dist as obs_dist
        from parmmg_tpu.obs import history as obs_history
        from parmmg_tpu.obs import metrics as obs_metrics
        from parmmg_tpu.obs import report as obs_report

        # 1. both ranks traced, clocks synced, timelines monotone ----
        segs = obs_dist.rank_segments(obs)
        assert sorted(segs) == [0, 1], f"ranks traced: {sorted(segs)}"
        for rank in (0, 1):
            last = segs[rank][-1]
            assert last["aligned"], f"rank {rank} clock never synced"
            assert last["rounds"] > 0, last
        assert segs[0][-1]["offset_us"] == 0.0, "rank 0 must anchor"
        off1 = segs[1][-1]["offset_us"]
        tls = obs_dist.aligned_timelines(obs)
        for rank, recs in tls.items():
            ats = [r["ats_us"] for r in recs]
            assert ats == sorted(ats), f"rank {rank} not monotone"

        # 2. collective decomposition: nonzero wait, worst rank -----
        comm = obs_dist.decompose_collectives(tls)
        assert comm["instances"] > 0, "no matched collectives"
        world2 = [n for n, ph in comm["phases"].items()
                  if any(i["world"] == 2 for i in
                         obs_dist.collective_instances(tls)
                         if i["name"] == n)]
        assert world2, "no collective matched across both ranks"
        total_wait = {r: d["wait_s"] for r, d in
                      comm["per_rank"].items()}
        assert all(w > 0 for w in total_wait.values()), total_wait
        named = [ph for ph in comm["phases"].values()
                 if "worst_rank" in ph]
        assert named, "no worst-straggler rank named"
        merged = obs_metrics.merge_dir(obs)
        assert merged and "comm/wait_s" in merged["gauges"], \
            "comm/wait_s gauge missing"
        gw = merged["gauges"]["comm/wait_s"]["per_rank"]
        assert len(gw) == 2 and all(v > 0 for v in gw.values()), gw
        assert "work/imbalance" in merged["gauges"], \
            "work/imbalance gauge missing"

        # 3. imbalance factor rides the bench/PERF_DB record --------
        imb_line = next(ln for ln in logs[0].splitlines()
                        if ln.startswith("DIST_IMB "))
        imb = json.loads(imb_line[len("DIST_IMB "):])
        assert imb and all(x >= 1.0 for x in imb), imb
        import bench

        payload = dict(metric="wall_s", value=0.0,
                       imbalance=round(max(imb), 4),
                       imbalance_series=imb)
        rec = bench._envelope(payload, dict(dist=True, nparts=8))
        assert rec["imbalance"] == round(max(imb), 4)
        assert rec["rung"] == "dist-p8", rec["rung"]
        assert "imbalance" in obs_history.GATE_KEYS, \
            "perf gate cannot ratchet balance"

        # 4. critical path renders; merged Perfetto trace written ---
        cp = obs_dist.critical_path(tls)
        assert cp, "no critical-path rows"
        text = obs_report.render_dist(obs)
        for want in ("clock alignment", "collective decomposition",
                     "critical path", "trace_merged.json"):
            assert want in text, f"report missing {want!r}"
        assert os.path.exists(os.path.join(obs, "trace_merged.json"))

        gated = {}
        for row in cp:
            gated[row["rank"]] = gated.get(row["rank"], 0.0) \
                + row["dur_us"] / 1e6
        print(f"[dist-obs] rank1 offset {off1:.1f}us "
              f"(+/-{segs[1][-1]['err_us']:.1f}); "
              f"wait {', '.join(f'r{r}={w:.3f}s' for r, w in sorted(total_wait.items()))}; "
              f"imbalance max {max(imb):.4f}; "
              f"critical path {len(cp)} row(s), gated "
              f"{', '.join(f'r{r}={s:.3f}s' for r, s in sorted(gated.items()))}")
        print("[dist-obs] aligned timelines, skew decomposition, "
              "bench imbalance and critical path all verified")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(worker() if "--worker" in sys.argv else main())
