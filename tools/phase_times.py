"""Wall-clock phase breakdown of one bench-shaped adapt() on the
current backend: timestamps every verbose phase marker and sweep line,
plus the surrounding warmup/timed split — locates where non-sweep wall
time goes (dispatch round trips, polish, analysis, interp).

Usage: python tools/phase_times.py [n] [hsiz] [max_sweeps]
"""

import sys
import time

from _cli import REPO, parse_argv  # noqa: F401

import builtins

_t0 = time.perf_counter()
_orig = builtins.print


def _tprint(*a, **k):
    _orig(f"[{time.perf_counter() - _t0:8.2f}s]", *a, **k)


def main():
    pos, _ = parse_argv(sys.argv[1:])
    n = int(pos[0]) if pos else 10
    hsiz = float(pos[1]) if len(pos) > 1 else 0.05
    ms = int(pos[2]) if len(pos) > 2 else 12

    import bench

    bench._enable_compile_cache()
    import jax

    from parmmg_tpu.models.adapt import AdaptOptions, adapt

    _tprint(f"platform={jax.devices()[0].platform}")
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=ms, hgrad=None,
                        verbose=2)
    builtins.print = _tprint
    try:
        mesh = bench._workload(n, hsiz)
        _tprint("== warmup adapt ==")
        adapt(mesh, opts)
        _tprint("== timed adapt ==")
        t0 = time.perf_counter()
        mesh = bench._workload(n, hsiz)
        _tprint("   (workload rebuilt)")
        out, info = adapt(mesh, opts)
        wall = time.perf_counter() - t0
        _tprint(f"== done: ne={int(out.ntet)} wall={wall:.2f}s "
                f"tps={int(out.ntet) / wall:.1f}")
        saf = [
            round(r["n_active"] / max(r["n_unique"], 1), 3)
            for r in info["history"] if "n_active" in r
        ]
        _tprint(f"   sweep_active_fraction={saf}")
        # converged-sweep cost probe: the ONE shared definition
        # (bench.measure_converged_sweep — the same numbers every BENCH
        # record carries), not a local re-implementation
        probe = bench.measure_converged_sweep(out)
        _tprint(
            f"== no-op sweep probe: full-table "
            f"{probe['full_s'] * 1e3:.1f} ms vs empty-frontier "
            f"{probe['frontier_s'] * 1e3:.1f} ms "
            f"({probe['ratio']:.1f}x cheaper)"
        )
    finally:
        builtins.print = _orig


if __name__ == "__main__":
    main()
