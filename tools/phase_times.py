"""Wall-clock phase breakdown of one bench-shaped adapt() on the
current backend: timestamps every verbose phase marker and sweep line,
plus the surrounding warmup/timed split — locates where non-sweep wall
time goes (dispatch round trips, polish, analysis, interp).

Usage: python tools/phase_times.py [n] [hsiz] [max_sweeps]
"""

import sys
import time

from _cli import REPO, parse_argv  # noqa: F401

import builtins

_t0 = time.perf_counter()
_orig = builtins.print


def _tprint(*a, **k):
    _orig(f"[{time.perf_counter() - _t0:8.2f}s]", *a, **k)


def main():
    pos, _ = parse_argv(sys.argv[1:])
    n = int(pos[0]) if pos else 10
    hsiz = float(pos[1]) if len(pos) > 1 else 0.05
    ms = int(pos[2]) if len(pos) > 2 else 12

    import bench

    bench._enable_compile_cache()
    import jax

    from parmmg_tpu.models.adapt import AdaptOptions, adapt

    _tprint(f"platform={jax.devices()[0].platform}")
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=ms, hgrad=None,
                        verbose=2)
    builtins.print = _tprint
    try:
        mesh = bench._workload(n, hsiz)
        _tprint("== warmup adapt ==")
        adapt(mesh, opts)
        _tprint("== timed adapt ==")
        t0 = time.perf_counter()
        mesh = bench._workload(n, hsiz)
        _tprint("   (workload rebuilt)")
        out, info = adapt(mesh, opts)
        wall = time.perf_counter() - t0
        _tprint(f"== done: ne={int(out.ntet)} wall={wall:.2f}s "
                f"tps={int(out.ntet) / wall:.1f}")
        saf = [
            round(r["n_active"] / max(r["n_unique"], 1), 3)
            for r in info["history"] if "n_active" in r
        ]
        _tprint(f"   sweep_active_fraction={saf}")
        _noop_probe(out)
    finally:
        builtins.print = _orig


def _noop_probe(out, reps=3):
    """Converged-sweep cost probe (round 6): on the adapted mesh, time a
    full-table sweep against a frontier sweep whose active set is EMPTY
    and whose tables are clean — the cost of a no-op verification sweep
    under active-set scheduling vs the legacy full-capacity cost."""
    import jax
    import jax.numpy as jnp

    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import Frontier, remesh_sweep

    mesh = compact(out)
    ecap = int(mesh.tcap * 1.6) + 64
    edges, emask, t2e, nu = adj.unique_edges(mesh, ecap)
    mesh = adj.build_adjacency(mesh)
    fr = Frontier(
        changed=jnp.zeros(mesh.pcap, bool),
        dirty=jnp.int32(0),
        tables=(edges, emask, t2e, jnp.asarray(nu, jnp.int32)),
        adja_ok=jnp.bool_(True),
    )

    def timed(fn):
        fn()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn())
        return (time.perf_counter() - t0) / reps

    t_full = timed(lambda: remesh_sweep(mesh, ecap, phase_skip=False))
    t_noop = timed(
        lambda: remesh_sweep(mesh, ecap, phase_skip=False, frontier=fr)
    )
    _tprint(
        f"== no-op sweep probe: full-table {t_full * 1e3:.1f} ms vs "
        f"empty-frontier {t_noop * 1e3:.1f} ms "
        f"({t_full / max(t_noop, 1e-9):.1f}x cheaper)"
    )


if __name__ == "__main__":
    main()
