"""Seeded chaos harness for the fail-safe layer (tools/check.sh gate).

Two matrices, one contract:

**Single-rank matrix** (default): N randomized-but-SEEDED fault
schedules — kill / sigterm / ioerror / slowio / nan / overflow /
retrace / preempt-notice at random iterations, phases and store-op
ordinals, with async snapshot staging flipped at random — each run
against the public `adapt` driver in a subprocess. Killed runs are
resumed fault-free; some resumes randomly FLIP the Pallas-kernel
backend (``PMMGTPU_KERNELS`` off↔on) to assert end to end that
backend knobs are excluded from the checkpoint fingerprint and never
refuse a resume (digest equivalence is only asserted for un-flipped
resumes — the interpret-mode kernels are equivalent, not
bit-identical).

**Multi-rank matrix** (``--world N``): seeded schedules that target
RANDOM RANKS of a real ``jax.distributed`` world (N coordinated
processes, the `tests/multihost_worker.py --failsafe` workload) with
trajectory-NEUTRAL faults only — kill@rank r, broadcast sigterm,
peer-lost@rank r (an injected coordination-service report), ckpt-store
ioerror/slowio bursts @rank r, preempt-notice, and the commit-window
kill (``it<k>:ckpt:kill@rank0``: rank 0 dies at the manifest publish,
BETWEEN the two barrier rounds of the sharded commit). Every rank of
every seed must end typed; killed/broken worlds are resumed fault-free
(alternating same-world and ELASTIC world-1 resumes) and must
reproduce the uninterrupted reference digest bit for bit; and every
seed must leave a complete per-rank post-mortem — the JSONL timelines
+ ``metrics_rank*.json`` rendered by ``tools/obs_report.py --chaos``
as a fault → detection → recovery chain per rank (asserted per seed).

The contract under chaos (both matrices):

- every run terminates inside the stage watchdog (subprocess timeout)
  — **zero hangs**;
- every run ends in a TYPED outcome: exit 0 with a
  ``CHAOS_RESULT``/``ADAPT_DIGEST`` line, or a documented exit code of
  the 86/87/88/89 family (kill/preemption, peer lost, resume refusal,
  checkpoint I/O abort) — **zero untyped tracebacks** in any log;
- killed runs RESUME from their checkpoint **bit-identically**
  (single-rank schedules containing trajectory-altering faults —
  nan/overflow/retrace — and backend-flipped resumes assert the typed
  outcome only).

Scheduling rules keeping every assertion well-defined: a terminal
fault is always the LAST fault of its schedule, so everything before
it is committed into the checkpoint the resume reads, and the resumed
run (fault-free) replays the identical deterministic trajectory.

Run: ``python tools/chaos_smoke.py --seeds 3 [--seed-base 0]
[--world N]``. Exit 0 = every seeded schedule behaved. The optional
``PARMMG_STAGE_BUDGET_S`` env bounds the stage: once the elapsed time
plus a (measured) per-seed estimate would exceed it, remaining seeds
are skipped with a notice instead of tripping the stage timeout.
"""

import argparse
import hashlib
import os
import random
import subprocess
import sys
import tempfile
import time
import shutil

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# exit codes of the typed family (mirrors parmmg_tpu.failsafe without
# importing jax in the parent before the workers fork their own envs)
KILL = 86
PEER_LOST = 87
MISMATCH = 88
CKPT_IO = 89
DIVERGENCE = 92
TYPED_RCS = {0, KILL, PEER_LOST, MISMATCH, CKPT_IO, DIVERGENCE}

OPTS = dict(hsiz=0.45, niter=3, max_sweeps=3, hgrad=None,
            polish_sweeps=0)
# per-run stage watchdog: a wedged worker is a FAILURE of the
# zero-hang contract, not something to wait out
RUN_TIMEOUT = 600
# the multi-rank workload runs more machinery (coordination handshake,
# SPMD compiles on every rank) — give each WORLD run a wider bound
WORLD_RUN_TIMEOUT = 900
# multi-rank workload geometry (tests/multihost_worker.py --failsafe):
# niter=2, so schedules may reference it0/it1 only
WORLD_NITER = 2

# faults whose recovery changes the trajectory (rollback, grown
# capacities): runs containing them assert typed outcomes, not digests
TRAJECTORY_FAULTS = ("nan", "overflow", "retrace")
NEUTRAL_FAULTS = ("preempt-notice",)
DRIVER_PHASES = ("remesh", "post")


class StageBudget:
    """PARMMG_STAGE_BUDGET_S accountant: refuses to start a unit of
    work whose (measured) duration estimate would overrun the stage
    budget — the harness then reports a capped-but-green stage instead
    of being SIGKILLed mid-seed by the stage timeout."""

    def __init__(self):
        b = os.environ.get("PARMMG_STAGE_BUDGET_S")
        self.budget = float(b) if b else None
        self.t0 = time.monotonic()
        self.worst = 0.0

    def note(self, seconds: float) -> None:
        self.worst = max(self.worst, seconds)

    def allows_another(self, fallback_estimate: float = 120.0) -> bool:
        if self.budget is None:
            return True
        est = self.worst or fallback_estimate
        return time.monotonic() - self.t0 + est * 1.15 < self.budget


def worker(ckdir: str) -> None:
    """Child mode: one checkpointing adapt run under the PARMMG_FAULTS
    env schedule; every outcome is typed — a result line + exit 0, or a
    CHAOS_TYPED line + an 86/88/89-family exit code."""
    import jax
    from jax._src import xla_bridge as _xb

    # Pallas registers Mosaic lowerings for platform "tpu" at import
    # time and refuses once "tpu" is deregistered — import it first
    # (same ordering as tests/conftest.py / tools/kernel_smoke.py);
    # the kernel-flip resume leg runs with PMMGTPU_KERNELS=on
    import jax.experimental.pallas  # noqa: F401
    from jax.experimental.pallas import tpu as _pltpu  # noqa: F401

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.io.ckpt_store import CheckpointIOError
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    try:
        out, info = adapt(
            unit_cube_mesh(2), AdaptOptions(**OPTS), checkpoint_dir=ckdir
        )
    except failsafe.PreemptionError as e:
        # the sigterm fault's graceful path: checkpoint committed, exit
        # through the same code the hard kill uses
        print(f"CHAOS_TYPED PreemptionError: {e}", flush=True)
        os._exit(failsafe.KILL_EXIT_CODE)
    except failsafe.CheckpointMismatchError as e:
        print(f"CHAOS_TYPED CheckpointMismatchError: {e}", flush=True)
        sys.exit(failsafe.MISMATCH_EXIT_CODE)
    except CheckpointIOError as e:
        print(f"CHAOS_TYPED CheckpointIOError: {e}", flush=True)
        sys.exit(failsafe.CKPT_IO_EXIT_CODE)
    h = hashlib.sha256()
    d = jax.device_get(out)
    for name in ("vert", "vmask", "tet", "tmask", "tria", "trmask",
                 "vtag", "trtag"):
        h.update(np.ascontiguousarray(np.asarray(getattr(d, name)))
                 .tobytes())
    print(
        f"CHAOS_RESULT status={int(info['status'])} "
        f"digest={h.hexdigest()}",
        flush=True,
    )
    sys.exit(0)


def govern_worker() -> None:
    """Child mode for ``--govern``: one governed forced-oscillation
    run. A discontinuous metric (0.5 -> 0.13 edge targets split at
    x=0.5) keeps split and collapse fighting over the same band of
    elements, so an ungoverned run burns its whole
    ``niter x max_sweeps`` budget churning. With the governor armed
    the run must instead end EARLY with the typed verdict and a sweep
    refund — reported as a ``GOVERN_RESULT`` line the parent
    asserts."""
    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp
    import numpy as np

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(3, perturb=0.1, seed=3)
    x = np.asarray(mesh.vert[:, 0])
    h = np.where(x < 0.5, 0.5, 0.13)
    # met_set=True or prepare_metric overwrites the discontinuity
    mesh = mesh.replace(met=jnp.asarray(h, mesh.vert.dtype)[:, None],
                        met_set=True)
    budget, niter = 30, 3
    _out, info = adapt(
        mesh,
        AdaptOptions(niter=niter, max_sweeps=budget, converge_frac=0.0,
                     hgrad=None, polish_sweeps=0, govern=True),
    )
    hlt = info["health"]
    ctl = hlt.get("control", {})
    sweeps = len([r for r in info["history"] if "nsplit" in r])
    print(
        f"GOVERN_RESULT verdict={hlt['verdict']} "
        f"early_stop={int(bool(hlt.get('early_stop')))} "
        f"refunded={ctl.get('refunded_sweeps', 0)} "
        f"decisions={ctl.get('decisions', 0)} "
        f"sweeps={sweeps} budget={budget * niter}",
        flush=True,
    )
    sys.exit(0)


def main_govern(args) -> int:
    """The run-governor acceptance scenario: a seeded forced-churn run
    with ``PMMGTPU_GOVERN`` control points must (a) terminate early —
    inside the stage watchdog, well under its sweep budget — with the
    typed ``oscillating``/``stalled`` verdict, (b) refund the unused
    budget (counter + ``info["health"]["control"]``), and (c) leave
    ``control_decision`` events the real ``obs_report --control`` CLI
    renders as the decision post-mortem."""
    import glob
    import json as _json

    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_gov_")
    failures = []
    try:
        obs = os.path.join(tmp, "obs")
        log = os.path.join(tmp, "govern.log")
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(JAX_PLATFORMS="cpu", PMMGTPU_TRACE=obs)
        try:
            with open(log, "w") as lf:
                p = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--govern-worker"],
                    env=env, stdout=lf, stderr=subprocess.STDOUT,
                    timeout=RUN_TIMEOUT,
                )
        except subprocess.TimeoutExpired:
            failures.append(
                "govern: HANG — the governor must terminate a forced "
                "oscillation inside the watchdog")
            raise SystemExit
        text = open(log).read()
        if p.returncode != 0:
            failures.append(
                f"govern: worker exited {p.returncode}: "
                f"…{text[-1500:]}")
            raise SystemExit
        res = {}
        for ln in reversed(text.splitlines()):
            if ln.startswith("GOVERN_RESULT"):
                res = dict(tok.split("=", 1) for tok in ln.split()[1:])
                break
        if not res:
            failures.append(f"govern: no GOVERN_RESULT line: "
                            f"…{text[-1500:]}")
            raise SystemExit
        label = (f"govern: verdict={res.get('verdict')} "
                 f"refunded={res.get('refunded')}")
        if res.get("early_stop") != "1":
            failures.append(f"{label}: run was NOT early-stopped")
            raise SystemExit
        if res.get("verdict") not in ("oscillating", "stalled"):
            failures.append(f"{label}: verdict is not the typed "
                            "churn family")
            raise SystemExit
        if int(res.get("refunded", 0)) <= 0:
            failures.append(f"{label}: no sweep budget was refunded")
            raise SystemExit
        if int(res.get("sweeps", 0)) >= int(res.get("budget", 0)):
            failures.append(f"{label}: the full sweep budget was "
                            "spent — that is not an early stop")
            raise SystemExit
        print(f"[chaos-govern] forced churn stopped typed "
              f"'{res['verdict']}' after {res['sweeps']} of "
              f"{res['budget']} budgeted sweep(s), "
              f"{res['refunded']} refunded")

        # the durable timeline must carry the decision events (they
        # survive even a killed run — same stdlib-parse rule as the
        # chaos post-mortem, the parent stays jax-free)
        actions = []
        for path in sorted(glob.glob(
                os.path.join(obs, "events_rank*.jsonl"))):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = _json.loads(line)
                    except _json.JSONDecodeError:
                        continue
                    if rec.get("type") == "event" and \
                            rec.get("name") == "control_decision":
                        actions.append(
                            rec.get("args", {}).get("action"))
        if "early_stop" not in actions:
            failures.append(
                f"govern: timeline carries no early_stop "
                f"control_decision event (saw {actions})")
            raise SystemExit

        # post-mortem through the REAL CLI: the refund must render
        p2 = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "obs_report.py"),
             obs, "--control", "1"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        if p2.returncode != 0:
            failures.append(
                f"govern: --control post-mortem failed: "
                f"{p2.stdout[-1000:]}{p2.stderr[-1000:]}")
            raise SystemExit
        for want in ("control decisions", "early_stop",
                     "refunded sweeps", "final verdict"):
            if want not in p2.stdout:
                failures.append(
                    f"govern: --control post-mortem misses "
                    f"{want!r}:\n{p2.stdout}")
                raise SystemExit
        print(f"[chaos-govern] --control post-mortem renders "
              f"{len(actions)} decision(s) incl. the early stop + "
              "refund")
        print("[chaos-govern] the governor converted runaway churn "
              "into a typed early stop with its budget refunded")
        return 0
    except SystemExit:
        pass
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("\n[chaos-govern] FAILURES:")
    for f in failures:
        print(" -", f)
    return 1


def gen_schedule(rng: random.Random):
    """One seeded single-rank schedule: (spec string, terminal kind or
    None, trajectory-altering?, async staging?, flip kernel backend on
    resume?)."""
    faults = []
    trajectory = False
    # 0-2 background faults
    for _ in range(rng.randint(0, 2)):
        roll = rng.random()
        if roll < 0.4:
            # checkpoint-store I/O faults: it<k> = store-op ordinal;
            # a burst >= the retry budget forces the typed 89 abort
            burst = rng.choice((1, 1, 2, 5))
            start = rng.randint(0, 3)
            kind = rng.choice(("ioerror", "slowio"))
            faults += [f"it{start + j}:ckpt:{kind}" for j in range(burst)]
        elif roll < 0.7:
            kind = rng.choice(TRAJECTORY_FAULTS)
            trajectory = True
            faults.append(
                f"it{rng.randint(0, OPTS['niter'] - 1)}:"
                f"{rng.choice(DRIVER_PHASES)}:{kind}"
            )
        else:
            faults.append(
                f"it{rng.randint(0, OPTS['niter'] - 1)}:"
                f"{rng.choice(DRIVER_PHASES)}:preempt-notice"
            )
    terminal = None
    if rng.random() < 0.6:
        terminal = rng.choice(("kill", "sigterm"))
        # appended LAST so it fires after any same-boundary background
        # fault (the resume-equivalence rule of the module docstring).
        # kill exits inside the post hook itself, so the final
        # iteration works; sigterm only sets a flag the NEXT loop-top
        # check converts into the checkpoint-backed exit, so it must
        # land one iteration earlier to fire at all.
        term_it = OPTS["niter"] - (1 if terminal == "kill" else 2)
        faults.append(f"it{term_it}:post:{terminal}")
    # resume-across-backends leg: some killed runs resume with the
    # kernel backend flipped (PMMGTPU_KERNELS=on — interpret mode off
    # TPU). The fingerprint excludes backend knobs, so the resume must
    # be ACCEPTED; bit-digests are only asserted for un-flipped resumes
    flip = terminal is not None and rng.random() < 0.4
    return ",".join(faults), terminal, trajectory, rng.random() < 0.5, \
        flip


def _timeline_kinds(obs_dir: str):
    """(exists, injected-fault kinds) of a seed's JSONL timeline. The
    parent must stay jax-free, so the lines are parsed with stdlib
    json rather than through parmmg_tpu.obs.report."""
    import glob
    import json as _json

    paths = sorted(glob.glob(os.path.join(obs_dir, "events_rank*.jsonl")))
    kinds = []
    n_lines = 0
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except _json.JSONDecodeError:
                    continue
                n_lines += 1
                if rec.get("type") == "event" \
                        and rec.get("name") == "fault_injected":
                    kinds.append(rec.get("args", {}).get("kind"))
    return bool(paths) and n_lines > 0, kinds


def _assert_postmortem(obs_dir: str, label: str, kinds=()):
    """Render the per-rank chaos post-mortem for a seed's trace dir
    through the REAL CLI (a subprocess — the parent stays jax-free)
    and require it to name every expected fault kind. Returns the
    rendered text; raises AssertionError on a broken report."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         obs_dir, "--chaos", "1"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert p.returncode == 0, (
        f"{label}: chaos post-mortem failed to render: "
        f"{p.stdout[-1000:]}{p.stderr[-1000:]}"
    )
    text = p.stdout
    assert "chaos post-mortem" in text, text[-500:]
    for kind in kinds:
        assert f"injected: {kind}" in text, (
            f"{label}: post-mortem does not name injected fault "
            f"{kind!r}:\n{text}"
        )
    return text


def _run(ckdir: str, log: str, env_extra: dict) -> int:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        # small per-op timeout so slowio faults genuinely trip it, and
        # fast backoff so ioerror retries don't stretch the stage
        PMMGTPU_CKPT_TIMEOUT="2",
        PMMGTPU_CKPT_BACKOFF="0.01",
        # every chaos run leaves a JSONL event timeline next to its
        # log (the tracer is armed via the env contract) — the
        # failure sequence is reconstructable post-mortem even for a
        # hard-killed worker
        PMMGTPU_TRACE=ckdir + "_obs",
    )
    env.update(env_extra)
    with open(log, "w") as lf:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", ckdir],
            env=env, stdout=lf, stderr=subprocess.STDOUT,
            timeout=RUN_TIMEOUT,
        )
    return p.returncode


def _field(text: str, key: str):
    for ln in reversed(text.splitlines()):
        if ln.startswith("CHAOS_RESULT"):
            for tok in ln.split():
                if tok.startswith(key + "="):
                    return tok.split("=", 1)[1]
    return None


def main(args) -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_")
    failures = []
    budget = StageBudget()
    done = 0
    try:
        # shared fault-free reference digest (all terminal/neutral
        # schedules must converge to it)
        ref_log = os.path.join(tmp, "ref.log")
        rc = _run(os.path.join(tmp, "ck_ref"), ref_log,
                  {"PARMMG_FAULTS": ""})
        ref_text = open(ref_log).read()
        assert rc == 0 and _field(ref_text, "digest"), (
            rc, ref_text[-2000:],
        )
        ref_digest = _field(ref_text, "digest")
        print(f"[chaos] reference digest {ref_digest[:16]}…")

        for seed in range(args.seed_base, args.seed_base + args.seeds):
            if not budget.allows_another():
                print(f"[chaos] stage budget reached after {done} "
                      f"seed(s) — skipping seeds {seed}.."
                      f"{args.seed_base + args.seeds - 1}")
                break
            t_start = time.monotonic()
            rng = random.Random(seed)
            spec, terminal, trajectory, use_async, flip = \
                gen_schedule(rng)
            ck = os.path.join(tmp, f"ck_{seed}")
            log = os.path.join(tmp, f"seed_{seed}.log")
            env = {"PARMMG_FAULTS": spec}
            if use_async:
                env["PMMGTPU_ASYNC_CKPT"] = "1"
            label = (f"seed {seed}: faults={spec or '<none>'} "
                     f"async={int(use_async)}")
            try:
                rc = _run(ck, log, env)
            except subprocess.TimeoutExpired:
                failures.append(f"{label}: HANG (watchdog)")
                continue
            finally:
                done += 1
                budget.note(time.monotonic() - t_start)
            text = open(log).read()
            if rc not in TYPED_RCS:
                failures.append(
                    f"{label}: untyped exit {rc}: …{text[-1500:]}"
                )
                continue
            if "Traceback (most recent call last)" in text:
                failures.append(
                    f"{label}: untyped traceback: …{text[-1500:]}"
                )
                continue
            # every seed leaves a JSONL event timeline next to its log,
            # and a terminal fault must be IN it — the per-line flush
            # guarantee holds even through the worker's os._exit
            has_tl, kinds = _timeline_kinds(ck + "_obs")
            if not has_tl:
                failures.append(f"{label}: no obs timeline written")
                continue
            if rc == KILL and terminal and terminal not in kinds:
                failures.append(
                    f"{label}: terminal fault {terminal!r} missing "
                    f"from the obs timeline (saw {kinds})"
                )
                continue
            if rc == 0:
                status = _field(text, "status")
                if status not in ("0", "1"):
                    failures.append(f"{label}: bad status {status}")
                    continue
                if not trajectory \
                        and _field(text, "digest") != ref_digest:
                    failures.append(
                        f"{label}: neutral-schedule digest diverged"
                    )
                    continue
                print(f"[chaos] {label} -> typed status {status}")
            elif rc == KILL:
                # resume the killed run fault-free: bit-identical —
                # with the kernel backend randomly FLIPPED on some
                # seeds (the fingerprint-exclusion leg: a backend knob
                # must never refuse a resume)
                renv = {"PARMMG_FAULTS": ""}
                if flip:
                    renv["PMMGTPU_KERNELS"] = "on"
                try:
                    rc2 = _run(ck, log + ".resume", renv)
                except subprocess.TimeoutExpired:
                    failures.append(f"{label}: resume HANG")
                    continue
                rtext = open(log + ".resume").read()
                if rc2 == MISMATCH:
                    failures.append(
                        f"{label}: resume REFUSED"
                        + (" with kernels flipped — the backend knob "
                           "leaked into the fingerprint" if flip
                           else "") + f": …{rtext[-1500:]}"
                    )
                    continue
                if rc2 != 0 or "Traceback (most recent call last)" \
                        in rtext:
                    failures.append(
                        f"{label}: resume exit {rc2}: …{rtext[-1500:]}"
                    )
                    continue
                ok = _field(rtext, "digest") == ref_digest
                if flip:
                    print(f"[chaos] {label} -> {terminal} + resume "
                          "ACCEPTED with kernels flipped off->on "
                          "(fingerprint excludes backend knobs)")
                elif trajectory:
                    # a pre-kill trajectory fault is baked into the
                    # checkpoint: the resume must still END typed, but
                    # the digest legitimately differs
                    print(f"[chaos] {label} -> {terminal} + resume "
                          "(typed, trajectory fault absorbed)")
                elif not ok:
                    failures.append(f"{label}: resume digest diverged")
                    continue
                else:
                    print(f"[chaos] {label} -> {terminal} + "
                          "bit-identical resume")
            else:
                print(f"[chaos] {label} -> typed exit {rc}")
        if failures:
            print("\n[chaos] FAILURES:")
            for f in failures:
                print(" -", f)
            return 1
        print(f"[chaos] all {done} seeded schedules terminated "
              "typed — zero hangs, zero untyped tracebacks")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# multi-rank matrix (--world N)
# ---------------------------------------------------------------------------


def _world_env(extra: dict) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=ROOT,
        PYTHONFAULTHANDLER="1",
        PMMGTPU_CKPT_TIMEOUT="5",
        PMMGTPU_CKPT_BACKOFF="0.01",
    )
    env.update(extra)
    return env


def _run_world(tmp: str, tag: str, world: int, extra: dict):
    """N coordinated `multihost_worker.py --failsafe` processes (8/N
    CPU devices each). Returns (rcs, log texts); raises
    subprocess.TimeoutExpired on a hang (after killing the world)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker_py = os.path.join(ROOT, "tests", "multihost_worker.py")
    ndev = 8 // world
    procs, logs = [], []
    for pid in range(world):
        env = _world_env(dict(
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS=str(world),
            PMMGTPU_PROC_ID=str(pid),
            **extra,
        ))
        lp = os.path.join(tmp, f"{tag}{pid}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, worker_py, "--failsafe"], env=env,
            stdout=open(lp, "w"), stderr=subprocess.STDOUT, cwd=ROOT,
        ))
    deadline = time.monotonic() + WORLD_RUN_TIMEOUT
    rcs = []
    try:
        for p in procs:
            rcs.append(p.wait(timeout=max(deadline - time.monotonic(),
                                          1.0)))
    finally:
        for p in procs:
            p.kill()
    return rcs, [open(lp).read() for lp in logs]


def _run_world_single(tmp: str, tag: str, extra: dict):
    """One UN-coordinated worker owning all 8 devices with the same
    SPMD sweep programs (PMMGTPU_SPMD_SWEEPS=1) — the elastic N→1
    resume leg. Returns (rc, log text)."""
    env = _world_env(dict(
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PMMGTPU_SPMD_SWEEPS="1",
        **extra,
    ))
    for k in ("PMMGTPU_COORDINATOR", "PMMGTPU_NUM_PROCS",
              "PMMGTPU_PROC_ID"):
        env.pop(k, None)
    lp = os.path.join(tmp, f"{tag}.log")
    p = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tests", "multihost_worker.py"),
         "--failsafe"],
        env=env, stdout=open(lp, "w"), stderr=subprocess.STDOUT,
        cwd=ROOT, timeout=WORLD_RUN_TIMEOUT,
    )
    return p.returncode, open(lp).read()


def _digest_lines(text: str):
    return [ln for ln in text.splitlines()
            if ln.startswith("ADAPT_DIGEST")]


def gen_world_schedule(rng: random.Random, world: int):
    """One seeded multi-rank schedule over trajectory-NEUTRAL faults
    (every killed/broken world must resume to the reference digest).

    Returns (spec, terminal, expected) where terminal is None or
    (kind, rank) and expected maps rank -> set of allowed exit codes.
    """
    all_ok = {r: {0} for r in range(world)}
    faults = []
    # 0-2 background faults: absorbed ckpt-store noise + notices
    for _ in range(rng.randint(0, 2)):
        rank = rng.randrange(world)
        if rng.random() < 0.5:
            burst = rng.choice((1, 2))        # < retry budget: absorbed
            start = rng.randint(0, 4)
            kind = rng.choice(("ioerror", "slowio"))
            faults += [f"it{start + j}:ckpt:{kind}@rank{rank}"
                       for j in range(burst)]
        else:
            faults.append(f"it{rng.randint(0, WORLD_NITER - 1)}:post:"
                          f"preempt-notice@rank{rank}")
    terminal = None
    expected = all_ok
    roll = rng.random()
    rank = rng.randrange(world)
    # ~5/6 of seeds end in a terminal fault; survivors of a killed
    # rank exit 87 via the collective watchdog (or 0 if they finished
    # their last collective first — a legitimate race at the tail)
    survivors = {r: {0, PEER_LOST} for r in range(world)}
    if roll < 0.20:
        # rank-targeted hard kill after the it0 checkpoint commit
        terminal = ("kill", rank)
        faults.append(f"it0:post:kill@rank{rank}")
        expected = {**survivors, rank: {KILL}}
    elif roll < 0.40:
        # broadcast SIGTERM (a platform preemption hits the whole
        # world): every rank commits, then exits through the graceful
        # preemption path
        terminal = ("sigterm", None)
        faults.append("it0:post:sigterm")
        expected = {r: {KILL} for r in range(world)}
    elif roll < 0.60:
        # injected coordination-service peer-loss report on one rank:
        # ITS next barrier refuses typed; the real peers then lose it
        terminal = ("peer-lost", rank)
        faults.append(f"it0:post:peer-lost@rank{rank}")
        expected = {**survivors, rank: {PEER_LOST}}
    elif roll < 0.80:
        # commit-window kill: rank 0 dies AT THE MANIFEST PUBLISH,
        # between the data barrier and the commit barrier — the epoch
        # stays uncommitted, survivors watchdog out typed
        terminal = ("kill", 0)
        faults.append(f"it{rng.randint(0, 2)}:ckpt:kill@rank0")
        expected = {**survivors, 0: {KILL}}
    elif roll < 0.90:
        # unabsorbable ckpt-store outage on one rank: typed 89 abort
        # mid-protocol, peers watchdog out
        terminal = ("ioerror", rank)
        start = rng.randint(1, 4)
        faults += [f"it{start + j}:ckpt:ioerror@rank{rank}"
                   for j in range(8)]
        expected = {**survivors, rank: {CKPT_IO, PEER_LOST}}
    return ",".join(faults), terminal, expected


def main_world(args) -> int:
    world = args.world
    assert 8 % world == 0, f"--world {world} must divide 8 devices"
    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_w_")
    failures = []
    budget = StageBudget()
    done = 0
    try:
        # fault-free reference digest at the target world size (the
        # single-controller SPMD run reproduces it bit for bit — the
        # elastic legs lean on that, asserted by fault_smoke/m10)
        t0 = time.monotonic()
        rcs, logs = _run_world(tmp, "ref", world,
                               {"PMMGTPU_WATCHDOG": "300"})
        budget.note(time.monotonic() - t0)
        assert rcs == [0] * world, (rcs, logs[0][-2000:],
                                    logs[-1][-2000:])
        ref = _digest_lines(logs[0])
        assert ref and all(_digest_lines(t) == ref for t in logs), logs
        print(f"[chaos-w{world}] reference {ref[0][:60]}…")

        for seed in range(args.seed_base, args.seed_base + args.seeds):
            # a terminal seed costs run + resume: require 2 units
            if not budget.allows_another(fallback_estimate=240.0):
                print(f"[chaos-w{world}] stage budget reached after "
                      f"{done} seed(s) — skipping seeds {seed}.."
                      f"{args.seed_base + args.seeds - 1}")
                break
            t_start = time.monotonic()
            rng = random.Random(10_000 + seed)
            spec, terminal, expected = gen_world_schedule(rng, world)
            ck = os.path.join(tmp, f"ck_{seed}")
            obs = ck + "_obs"
            label = (f"w{world} seed {seed}: "
                     f"faults={spec or '<none>'}")
            extra = {
                "PARMMG_FAULTS": spec,
                "PMMGTPU_CKPT_DIR": ck,
                "PMMGTPU_WATCHDOG": "60",
                "PMMGTPU_TRACE": obs,
            }
            try:
                rcs, logs = _run_world(tmp, f"seed{seed}_", world,
                                       extra)
            except subprocess.TimeoutExpired:
                failures.append(f"{label}: HANG (watchdog)")
                done += 1
                continue
            finally:
                budget.note(time.monotonic() - t_start)
            done += 1
            bad = [
                (r, rc) for r, rc in enumerate(rcs)
                if rc not in TYPED_RCS
            ]
            if bad:
                failures.append(
                    f"{label}: untyped exits {bad}: "
                    f"…{logs[bad[0][0]][-1500:]}"
                )
                continue
            wrong = [
                (r, rc) for r, rc in enumerate(rcs)
                if rc not in expected[r]
            ]
            if wrong:
                failures.append(
                    f"{label}: exits {rcs} outside the expected "
                    f"per-rank sets {expected}: "
                    f"…{logs[wrong[0][0]][-1500:]}"
                )
                continue
            tb = [r for r, t in enumerate(logs)
                  if "Traceback (most recent call last)" in t]
            if tb:
                failures.append(
                    f"{label}: untyped traceback on rank {tb[0]}: "
                    f"…{logs[tb[0]][-1500:]}"
                )
                continue

            if terminal is None:
                if any(_digest_lines(t) != ref for t in logs):
                    failures.append(
                        f"{label}: neutral-schedule digest diverged"
                    )
                    continue
                try:
                    _assert_postmortem(obs, label)
                except AssertionError as e:
                    failures.append(str(e))
                    continue
                print(f"[chaos-w{world}] {label} -> all ranks typed, "
                      "reference digest")
                continue

            # terminal seed: resume fault-free, alternating the resume
            # world — even seeds same-world, odd seeds ELASTIC N->1
            elastic = seed % 2 == 1
            try:
                if elastic:
                    rc1, text = _run_world_single(
                        tmp, f"seed{seed}_resume",
                        {"PMMGTPU_CKPT_DIR": ck, "PMMGTPU_TRACE": obs},
                    )
                    rcs2, rlogs = [rc1], [text]
                else:
                    rcs2, rlogs = _run_world(
                        tmp, f"seed{seed}_resume_", world,
                        {"PMMGTPU_CKPT_DIR": ck,
                         "PMMGTPU_WATCHDOG": "300",
                         "PMMGTPU_TRACE": obs},
                    )
            except subprocess.TimeoutExpired:
                failures.append(f"{label}: resume HANG")
                continue
            if any(rc != 0 for rc in rcs2):
                failures.append(
                    f"{label}: resume exits {rcs2}: "
                    f"…{rlogs[0][-1500:]}"
                )
                continue
            if any(_digest_lines(t) != ref for t in rlogs):
                failures.append(
                    f"{label}: "
                    f"{'elastic ' if elastic else ''}resume digest "
                    f"diverged (want {ref})"
                )
                continue
            # the per-rank post-mortem must render AND name the
            # injected terminal fault + the recovery chain
            kind = terminal[0]
            try:
                text = _assert_postmortem(obs, label, kinds=[kind])
                assert ("recover  resume" in text
                        or "recover  checkpoint_commit" in text), (
                    f"{label}: post-mortem shows no recovery events:"
                    f"\n{text}"
                )
            except AssertionError as e:
                failures.append(str(e))
                continue
            print(f"[chaos-w{world}] {label} -> typed "
                  f"{dict(enumerate(rcs))}, "
                  f"{'elastic 1-rank' if elastic else f'{world}-rank'}"
                  " resume bit-identical, post-mortem complete")
        if failures:
            print(f"\n[chaos-w{world}] FAILURES:")
            for f in failures:
                print(" -", f)
            return 1
        print(f"[chaos-w{world}] all {done} seeded rank-targeted "
              "schedules terminated typed — zero hangs, bit-identical "
              "resumes, per-rank post-mortems complete")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# elastic autoscaling rung (--elastic)
# ---------------------------------------------------------------------------


def _parse_digest(text: str):
    """(ne, qmin, status) of the last ADAPT_DIGEST line, or None."""
    for ln in reversed(text.splitlines()):
        if not ln.startswith("ADAPT_DIGEST"):
            continue
        fields = dict(
            tok.split("=", 1) for tok in ln.split()[2:] if "=" in tok
        )
        return (int(fields["ne"]), float(fields["qmin"]),
                int(fields["status"]))
    return None


def _world_events(obs_dir: str):
    """{event name: [args]} of the world_shrink/world_grow records in
    a trace dir's JSONL timelines (stdlib parse — jax-free parent)."""
    import glob
    import json as _json

    out = {"world_shrink": [], "world_grow": []}
    for p in sorted(glob.glob(os.path.join(obs_dir,
                                           "events_rank*.jsonl"))):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except _json.JSONDecodeError:
                    continue
                if rec.get("type") == "event" \
                        and rec.get("name") in out:
                    out[rec["name"]].append(rec.get("args", {}))
    return out


def main_elastic(args) -> int:
    """The acceptance scenario of the elastic supervisor, end to end
    and operator-free: a 2-rank fleet absorbs a preemption NOTICE at
    rank 1 (checkpoint → world-agreed shrink to 1 → fault-free
    continuation), then grows back to 2 on the standing
    capacity-restored signal, and finishes with reference-class
    quality. Asserts the full observability contract on the way:
    ``world_shrink`` AND ``world_grow`` events with downtime seconds,
    and the ``obs_report --chaos`` post-mortem rendering the
    world-size timeline. A budget-permitting third run launches BELOW
    target (``--initial-world 1 --world 3``) and asserts the grow is
    BATCHED: one reformation straight to the target."""
    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_el_")
    budget = StageBudget()
    failures = []
    fleet_py = os.path.join(ROOT, "tools", "fleet.py")

    def run_fleet(tag, extra_args):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(JAX_PLATFORMS="cpu",
                   PMMGTPU_CKPT_BACKOFF="0.01")
        lp = os.path.join(tmp, f"{tag}.log")
        p = subprocess.run(
            [sys.executable, fleet_py, "--world", "2",
             "--devices-per-rank", "4", "--niter", "4",
             "--epoch-timeout", "800", "--watchdog", "120",
             "--ckpt", os.path.join(tmp, f"ck_{tag}")] + extra_args,
            env=env, stdout=open(lp, "w"), stderr=subprocess.STDOUT,
            timeout=WORLD_RUN_TIMEOUT * 3, cwd=ROOT,
        )
        return p.returncode, open(lp).read()

    try:
        # --- the elastic seed: notice at rank 1, capacity standing ----
        t0 = time.monotonic()
        cap = os.path.join(tmp, "capacity_restored")
        open(cap, "w").close()   # capacity available the moment the
        # world runs below target: the grow follows the shrink with no
        # operator in the loop
        obs = os.path.join(tmp, "obs")
        rc, text = run_fleet("elastic", [
            "--trace", obs, "--capacity-file", cap,
            "--faults", "it0:post:preempt-notice@rank1",
        ])
        budget.note(time.monotonic() - t0)
        label = "elastic seed (notice@rank1 -> shrink -> grow)"
        if rc != 0:
            print(text[-4000:])
            failures.append(f"{label}: fleet exit {rc}")
            raise SystemExit(1)
        if "Traceback (most recent call last)" in text:
            failures.append(f"{label}: untyped traceback in fleet log")
            raise SystemExit(1)
        # world trajectory 2 -> 1 -> 2, three epochs, completed
        assert "FLEET_OK epochs=3 final_world=2" in text, text[-2000:]
        assert "launching world=2" in text \
            and "launching world=1" in text, text[-2000:]
        dig = _parse_digest(text)
        assert dig is not None, "no ADAPT_DIGEST relayed by the fleet"
        ne, qmin, status = dig
        assert status == 0, f"{label}: final status {status}"
        assert 150 <= ne <= 5000, f"{label}: implausible ne {ne}"
        assert qmin >= 0.15, f"{label}: quality floor broken ({qmin})"
        # both transitions in the durable timelines, with downtime
        ev = _world_events(obs)
        for name in ("world_shrink", "world_grow"):
            assert ev[name], f"{label}: no {name} event in {obs}"
            a = ev[name][0]
            assert float(a.get("downtime_s", -1)) >= 0.0, (name, a)
        sh, gr = ev["world_shrink"][0], ev["world_grow"][0]
        assert (int(sh["old"]), int(sh["new"])) == (2, 1), sh
        assert (int(gr["old"]), int(gr["new"])) == (1, 2), gr
        # the post-mortem renders the injected notice AND the
        # world-size timeline with downtime seconds
        pm = _assert_postmortem(obs, label, kinds=["preempt-notice"])
        assert "world-size timeline" in pm, pm[-1500:]
        assert "world_shrink" in pm and "world_grow" in pm, pm[-1500:]
        assert "downtime" in pm, pm[-1500:]
        print(f"[chaos-elastic] {label} -> 2->1->2, ne={ne} "
              f"qmin={qmin:.4f}, shrink downtime "
              f"{sh['downtime_s']}s, grow downtime "
              f"{gr['downtime_s']}s")

        # --- fixed-world reference (budget-permitting): the elastic
        # finish must land in the same quality class as a world that
        # never reformed
        if budget.allows_another(fallback_estimate=240.0):
            rc, rtext = run_fleet("ref", [])
            assert rc == 0, (rc, rtext[-2000:])
            rdig = _parse_digest(rtext)
            assert rdig is not None and rdig[2] == 0, rdig
            rne, rqmin, _ = rdig
            assert abs(ne - rne) / max(rne, 1) <= 0.5, (
                f"{label}: elastic ne {ne} vs reference {rne}"
            )
            # same quality CLASS, not the same trajectory: the two
            # re-cuts (8->4->8 shards) re-partition mid-run, so the
            # worst element legitimately differs — gate at half the
            # fixed-world qmin on top of the absolute floor above
            assert qmin >= 0.5 * rqmin, (
                f"{label}: elastic qmin {qmin} vs reference {rqmin}"
            )
            print(f"[chaos-elastic] reference world-2 finish ne={rne} "
                  f"qmin={rqmin:.4f} — elastic finish is "
                  "quality-equivalent")
        else:
            print("[chaos-elastic] stage budget reached — reference "
                  "comparison skipped (absolute gates held)")

        # --- batch grow (budget-permitting): a world launched BELOW
        # target reaches it in ONE reformation — 1 -> 3 is one grow
        # vote + one relaunch, not two single-step reforms
        if budget.allows_another(fallback_estimate=240.0):
            bobs = os.path.join(tmp, "obs_batch")
            rc, btext = run_fleet("batchgrow", [
                "--world", "3", "--devices-per-rank", "2",
                "--initial-world", "1",
                "--trace", bobs, "--capacity-file", cap,
            ])
            blabel = "batch grow (initial 1, target 3)"
            assert rc == 0, (blabel, rc, btext[-2000:])
            assert "FLEET_OK epochs=2 final_world=3" in btext, \
                btext[-2000:]
            assert "launching world=1" in btext \
                and "launching world=3" in btext, btext[-2000:]
            bev = _world_events(bobs)
            assert bev["world_grow"], f"{blabel}: no world_grow event"
            bg = bev["world_grow"][0]
            assert (int(bg["old"]), int(bg["new"])) == (1, 3), bg
            print(f"[chaos-elastic] {blabel} -> one reformation, "
                  f"grow downtime {bg['downtime_s']}s")
        else:
            print("[chaos-elastic] stage budget reached — batch-grow "
                  "scenario skipped")
        print("[chaos-elastic] notice -> commit -> shrink -> continue "
              "-> grow -> quality finish: complete, zero operator "
              "input")
        return 0
    except SystemExit:
        pass
    except AssertionError as e:
        failures.append(str(e))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("\n[chaos-elastic] FAILURES:")
    for f in failures:
        print(" -", f)
    return 1


# ---------------------------------------------------------------------------
# collective-desync rung (--desync)
# ---------------------------------------------------------------------------


def main_desync(args) -> int:
    """The collective-lockstep acceptance scenario: a 2-rank world with
    the ledger armed (``PMMGTPU_VALIDATE=full``) absorbs an injected
    ``it1:comm:desync@rank1`` — one rank's collective schedule is
    poisoned as if it had dispatched a collective its peers never will.
    The contract under test: EVERY rank exits with the typed
    :data:`DIVERGENCE` code at the same boundary (zero hangs — the
    watchdog never has to fire), and the post-mortem renders the
    ``collective_divergence`` detection in the fault → detection chain.
    A fault-free control run under the same validate level proves the
    ledger itself never false-positives on a lockstep schedule."""
    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_ds_")
    failures = []
    budget = StageBudget()
    try:
        # --- control: ledger armed, no fault → clean lockstep finish --
        t0 = time.monotonic()
        try:
            rcs, logs = _run_world(tmp, "ctl_", 2, {
                "PMMGTPU_WATCHDOG": "120",
                "PMMGTPU_VALIDATE": "full",
            })
        except subprocess.TimeoutExpired:
            failures.append("desync control: HANG (watchdog)")
            raise SystemExit
        budget.note(time.monotonic() - t0)
        if rcs != [0, 0]:
            failures.append(
                f"desync control: ledger-armed fault-free world "
                f"exited {rcs}: …{logs[0][-1500:]}"
            )
            raise SystemExit
        ref = _digest_lines(logs[0])
        if not ref or any(_digest_lines(t) != ref for t in logs):
            failures.append(
                "desync control: ranks disagree on the clean digest"
            )
            raise SystemExit
        print("[chaos-desync] control: ledger armed, 2 ranks, "
              "fault-free — clean lockstep finish")

        # --- the desync seed: rank 1's schedule poisoned at it1 -------
        spec = "it1:comm:desync@rank1"
        ck = os.path.join(tmp, "ck_desync")
        obs = ck + "_obs"
        label = f"desync seed: faults={spec}"
        t0 = time.monotonic()
        try:
            rcs, logs = _run_world(tmp, "desync_", 2, {
                "PARMMG_FAULTS": spec,
                "PMMGTPU_CKPT_DIR": ck,
                "PMMGTPU_WATCHDOG": "120",
                "PMMGTPU_TRACE": obs,
                "PMMGTPU_VALIDATE": "full",
            })
        except subprocess.TimeoutExpired:
            failures.append(f"{label}: HANG (watchdog) — the ledger "
                            "must convert a desync into a typed exit")
            raise SystemExit
        budget.note(time.monotonic() - t0)
        # the whole point of the ledger: BOTH ranks take the typed
        # divergence exit at the same boundary — not one rank typed
        # and the other riding a watchdog timeout
        if rcs != [DIVERGENCE, DIVERGENCE]:
            failures.append(
                f"{label}: exits {rcs}, want "
                f"[{DIVERGENCE}, {DIVERGENCE}] on every rank: "
                f"…{logs[0][-1500:]}\n…{logs[1][-1500:]}"
            )
            raise SystemExit
        missing = [r for r, t in enumerate(logs)
                   if "COLL_DIVERGENCE" not in t]
        if missing:
            failures.append(
                f"{label}: rank {missing[0]} exited {DIVERGENCE} "
                f"without the typed COLL_DIVERGENCE line: "
                f"…{logs[missing[0]][-1500:]}"
            )
            raise SystemExit
        try:
            text = _assert_postmortem(obs, label, kinds=["desync"])
            assert "collective_divergence" in text, (
                f"{label}: post-mortem does not render the "
                f"collective_divergence detection:\n{text}"
            )
        except AssertionError as e:
            failures.append(str(e))
            raise SystemExit
        print(f"[chaos-desync] {label} -> both ranks exited typed "
              f"{DIVERGENCE} at the same boundary, post-mortem "
              "renders fault -> collective_divergence")
        print("[chaos-desync] desynced collective schedule became a "
              "simultaneous typed error — zero hangs, zero watchdog "
              "timeouts")
        return 0
    except SystemExit:
        pass
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("\n[chaos-desync] FAILURES:")
    for f in failures:
        print(" -", f)
    return 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    if len(sys.argv) > 1 and sys.argv[1] == "--govern-worker":
        govern_worker()
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--world", type=int, default=1,
                    help="multi-rank matrix: N coordinated processes "
                         "(default 1 = the single-rank matrix)")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic autoscaling rung: notice-driven "
                         "shrink + capacity-restored grow through "
                         "tools/fleet.py")
    ap.add_argument("--desync", action="store_true",
                    help="collective-desync rung: an injected "
                         "it1:comm:desync@rank1 must end in the typed "
                         "divergence exit on EVERY rank (the "
                         "collective-lockstep ledger), never a hang")
    ap.add_argument("--govern", action="store_true",
                    help="run-governor rung: a forced split<->collapse "
                         "oscillation must terminate EARLY with the "
                         "typed verdict, a refunded sweep budget and "
                         "a rendered control_decision post-mortem")
    args = ap.parse_args()
    if args.elastic:
        sys.exit(main_elastic(args))
    if args.desync:
        sys.exit(main_desync(args))
    if args.govern:
        sys.exit(main_govern(args))
    sys.exit(main(args) if args.world == 1 else main_world(args))
