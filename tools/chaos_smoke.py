"""Seeded chaos harness for the fail-safe layer (tools/check.sh gate).

Generates N randomized-but-SEEDED fault schedules — kill / sigterm /
ioerror / slowio / nan / overflow / retrace / preempt-notice at random
iterations, phases and store-op ordinals, with async snapshot staging
flipped at random — and runs each against the public `adapt` driver in
a subprocess. The contract under chaos:

- every run terminates inside the stage watchdog (subprocess timeout)
  — **zero hangs**;
- every run ends in a TYPED outcome: exit 0 with a
  ``CHAOS_RESULT status=<ReturnStatus>`` line, or a documented exit
  code of the 86/87/88/89 family (kill/preemption, peer lost, resume
  refusal, checkpoint I/O abort) announced by a ``CHAOS_TYPED`` line —
  **zero untyped tracebacks** anywhere in any log;
- a killed run RESUMES from its checkpoint **bit-identically**: the
  resumed final-mesh digest equals the uninterrupted reference run's
  (schedules containing trajectory-altering faults — nan / overflow /
  retrace, whose recovery legitimately changes the iteration history —
  assert the typed outcome only; schedules made purely of
  trajectory-neutral faults must also reproduce the reference digest).

Scheduling rules keeping every assertion well-defined: a terminal fault
(kill/sigterm) is always the LAST driver-phase fault of its schedule,
so everything before it is committed into the checkpoint the resume
reads, and the resumed run (fault-free) replays the identical
deterministic trajectory.

Run: ``python tools/chaos_smoke.py --seeds 3 [--seed-base 0]``.
Exit 0 = every seeded schedule behaved.
"""

import argparse
import hashlib
import os
import random
import subprocess
import sys
import tempfile
import shutil

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# exit codes of the typed family (mirrors parmmg_tpu.failsafe without
# importing jax in the parent before the workers fork their own envs)
KILL = 86
PEER_LOST = 87
MISMATCH = 88
CKPT_IO = 89
TYPED_RCS = {0, KILL, PEER_LOST, MISMATCH, CKPT_IO}

OPTS = dict(hsiz=0.45, niter=3, max_sweeps=3, hgrad=None,
            polish_sweeps=0)
# per-run stage watchdog: a wedged worker is a FAILURE of the
# zero-hang contract, not something to wait out
RUN_TIMEOUT = 600

# faults whose recovery changes the trajectory (rollback, grown
# capacities): runs containing them assert typed outcomes, not digests
TRAJECTORY_FAULTS = ("nan", "overflow", "retrace")
NEUTRAL_FAULTS = ("preempt-notice",)
DRIVER_PHASES = ("remesh", "post")


def worker(ckdir: str) -> None:
    """Child mode: one checkpointing adapt run under the PARMMG_FAULTS
    env schedule; every outcome is typed — a result line + exit 0, or a
    CHAOS_TYPED line + an 86/88/89-family exit code."""
    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.io.ckpt_store import CheckpointIOError
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    try:
        out, info = adapt(
            unit_cube_mesh(2), AdaptOptions(**OPTS), checkpoint_dir=ckdir
        )
    except failsafe.PreemptionError as e:
        # the sigterm fault's graceful path: checkpoint committed, exit
        # through the same code the hard kill uses
        print(f"CHAOS_TYPED PreemptionError: {e}", flush=True)
        os._exit(failsafe.KILL_EXIT_CODE)
    except failsafe.CheckpointMismatchError as e:
        print(f"CHAOS_TYPED CheckpointMismatchError: {e}", flush=True)
        sys.exit(failsafe.MISMATCH_EXIT_CODE)
    except CheckpointIOError as e:
        print(f"CHAOS_TYPED CheckpointIOError: {e}", flush=True)
        sys.exit(failsafe.CKPT_IO_EXIT_CODE)
    h = hashlib.sha256()
    d = jax.device_get(out)
    for name in ("vert", "vmask", "tet", "tmask", "tria", "trmask",
                 "vtag", "trtag"):
        h.update(np.ascontiguousarray(np.asarray(getattr(d, name)))
                 .tobytes())
    print(
        f"CHAOS_RESULT status={int(info['status'])} "
        f"digest={h.hexdigest()}",
        flush=True,
    )
    sys.exit(0)


def gen_schedule(rng: random.Random):
    """One seeded schedule: (spec string, terminal kind or None,
    trajectory-altering?, async staging?)."""
    faults = []
    trajectory = False
    # 0-2 background faults
    for _ in range(rng.randint(0, 2)):
        roll = rng.random()
        if roll < 0.4:
            # checkpoint-store I/O faults: it<k> = store-op ordinal;
            # a burst >= the retry budget forces the typed 89 abort
            burst = rng.choice((1, 1, 2, 5))
            start = rng.randint(0, 3)
            kind = rng.choice(("ioerror", "slowio"))
            faults += [f"it{start + j}:ckpt:{kind}" for j in range(burst)]
        elif roll < 0.7:
            kind = rng.choice(TRAJECTORY_FAULTS)
            trajectory = True
            faults.append(
                f"it{rng.randint(0, OPTS['niter'] - 1)}:"
                f"{rng.choice(DRIVER_PHASES)}:{kind}"
            )
        else:
            faults.append(
                f"it{rng.randint(0, OPTS['niter'] - 1)}:"
                f"{rng.choice(DRIVER_PHASES)}:preempt-notice"
            )
    terminal = None
    if rng.random() < 0.6:
        terminal = rng.choice(("kill", "sigterm"))
        # appended LAST so it fires after any same-boundary background
        # fault (the resume-equivalence rule of the module docstring).
        # kill exits inside the post hook itself, so the final
        # iteration works; sigterm only sets a flag the NEXT loop-top
        # check converts into the checkpoint-backed exit, so it must
        # land one iteration earlier to fire at all.
        term_it = OPTS["niter"] - (1 if terminal == "kill" else 2)
        faults.append(f"it{term_it}:post:{terminal}")
    return ",".join(faults), terminal, trajectory, rng.random() < 0.5


def _timeline_kinds(obs_dir: str):
    """(exists, injected-fault kinds) of a seed's JSONL timeline. The
    parent must stay jax-free, so the lines are parsed with stdlib
    json rather than through parmmg_tpu.obs.report."""
    import glob
    import json as _json

    paths = glob.glob(os.path.join(obs_dir, "events_rank*.jsonl"))
    kinds = []
    n_lines = 0
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except _json.JSONDecodeError:
                    continue
                n_lines += 1
                if rec.get("type") == "event" \
                        and rec.get("name") == "fault_injected":
                    kinds.append(rec.get("args", {}).get("kind"))
    return bool(paths) and n_lines > 0, kinds


def _run(ckdir: str, log: str, env_extra: dict) -> int:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        # small per-op timeout so slowio faults genuinely trip it, and
        # fast backoff so ioerror retries don't stretch the stage
        PMMGTPU_CKPT_TIMEOUT="2",
        PMMGTPU_CKPT_BACKOFF="0.01",
        # every chaos run leaves a JSONL event timeline next to its
        # log (the tracer is armed via the env contract) — the
        # failure sequence is reconstructable post-mortem even for a
        # hard-killed worker
        PMMGTPU_TRACE=ckdir + "_obs",
    )
    env.update(env_extra)
    with open(log, "w") as lf:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", ckdir],
            env=env, stdout=lf, stderr=subprocess.STDOUT,
            timeout=RUN_TIMEOUT,
        )
    return p.returncode


def _field(text: str, key: str):
    for ln in reversed(text.splitlines()):
        if ln.startswith("CHAOS_RESULT"):
            for tok in ln.split():
                if tok.startswith(key + "="):
                    return tok.split("=", 1)[1]
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--seed-base", type=int, default=0)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="parmmg_chaos_")
    failures = []
    try:
        # shared fault-free reference digest (all terminal/neutral
        # schedules must converge to it)
        ref_log = os.path.join(tmp, "ref.log")
        rc = _run(os.path.join(tmp, "ck_ref"), ref_log,
                  {"PARMMG_FAULTS": ""})
        ref_text = open(ref_log).read()
        assert rc == 0 and _field(ref_text, "digest"), (
            rc, ref_text[-2000:],
        )
        ref_digest = _field(ref_text, "digest")
        print(f"[chaos] reference digest {ref_digest[:16]}…")

        for seed in range(args.seed_base, args.seed_base + args.seeds):
            rng = random.Random(seed)
            spec, terminal, trajectory, use_async = gen_schedule(rng)
            ck = os.path.join(tmp, f"ck_{seed}")
            log = os.path.join(tmp, f"seed_{seed}.log")
            env = {"PARMMG_FAULTS": spec}
            if use_async:
                env["PMMGTPU_ASYNC_CKPT"] = "1"
            label = (f"seed {seed}: faults={spec or '<none>'} "
                     f"async={int(use_async)}")
            try:
                rc = _run(ck, log, env)
            except subprocess.TimeoutExpired:
                failures.append(f"{label}: HANG (watchdog)")
                continue
            text = open(log).read()
            if rc not in TYPED_RCS:
                failures.append(
                    f"{label}: untyped exit {rc}: …{text[-1500:]}"
                )
                continue
            if "Traceback (most recent call last)" in text:
                failures.append(
                    f"{label}: untyped traceback: …{text[-1500:]}"
                )
                continue
            # every seed leaves a JSONL event timeline next to its log,
            # and a terminal fault must be IN it — the per-line flush
            # guarantee holds even through the worker's os._exit
            has_tl, kinds = _timeline_kinds(ck + "_obs")
            if not has_tl:
                failures.append(f"{label}: no obs timeline written")
                continue
            if rc == KILL and terminal and terminal not in kinds:
                failures.append(
                    f"{label}: terminal fault {terminal!r} missing "
                    f"from the obs timeline (saw {kinds})"
                )
                continue
            if rc == 0:
                status = _field(text, "status")
                if status not in ("0", "1"):
                    failures.append(f"{label}: bad status {status}")
                    continue
                if not trajectory \
                        and _field(text, "digest") != ref_digest:
                    failures.append(
                        f"{label}: neutral-schedule digest diverged"
                    )
                    continue
                print(f"[chaos] {label} -> typed status {status}")
            elif rc == KILL:
                # resume the killed run fault-free: bit-identical
                try:
                    rc2 = _run(ck, log + ".resume",
                               {"PARMMG_FAULTS": ""})
                except subprocess.TimeoutExpired:
                    failures.append(f"{label}: resume HANG")
                    continue
                rtext = open(log + ".resume").read()
                if rc2 != 0 or "Traceback (most recent call last)" \
                        in rtext:
                    failures.append(
                        f"{label}: resume exit {rc2}: …{rtext[-1500:]}"
                    )
                    continue
                ok = _field(rtext, "digest") == ref_digest
                if trajectory:
                    # a pre-kill trajectory fault is baked into the
                    # checkpoint: the resume must still END typed, but
                    # the digest legitimately differs
                    print(f"[chaos] {label} -> {terminal} + resume "
                          "(typed, trajectory fault absorbed)")
                elif not ok:
                    failures.append(f"{label}: resume digest diverged")
                    continue
                else:
                    print(f"[chaos] {label} -> {terminal} + "
                          "bit-identical resume")
            else:
                print(f"[chaos] {label} -> typed exit {rc}")
        if failures:
            print("\n[chaos] FAILURES:")
            for f in failures:
                print(" -", f)
            return 1
        print(f"[chaos] all {args.seeds} seeded schedules terminated "
              "typed — zero hangs, zero untyped tracebacks")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    sys.exit(main())
