"""Chained per-op profiler: real numbers on backends whose
block_until_ready does not synchronize (the remote TPU tunnel).

Each op runs R times inside one jitted lax.fori_loop with the mesh as
loop carry (true data dependency) — `parmmg_tpu.obs.costs.
chained_seconds`, the shared chained-timing definition — so the
measured wall time is actual device compute. Usage:

    python tools/profile_chain.py [n] [hsiz] [R]
"""
# parmmg-lint: disable-file=PML005 -- profiling harness reuses the same mesh across timed repeats

import sys
import time

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)

import jax

from parmmg_tpu.obs import costs as obs_costs


def main():
    pos, _ = parse_argv(sys.argv[1:])
    n = int(pos[0]) if pos else 8
    hsiz = float(pos[1]) if len(pos) > 1 else 0.08
    R = int(pos[2]) if len(pos) > 2 else 20

    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import analysis, collapse, smooth, split, swap

    print(f"platform: {jax.devices()[0].platform}", flush=True)
    if jax.devices()[0].platform == "tpu":
        # share bench.py's persistent compile cache (tunnel compiles
        # cost minutes; disk hits cost <1s). CPU-unsafe, TPU only.
        from bench import _enable_compile_cache

        _enable_compile_cache()
    import bench

    # the bench's own workload recipe (shared sizing formula + capacity
    # multipliers) so profiled shapes match benchmarked ones exactly
    mesh = bench._workload(n, hsiz)
    t0 = time.perf_counter()
    mesh, _ = adapt(mesh, AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=8,
                                       hgrad=None))
    print(f"prep: {time.perf_counter() - t0:.1f}s ne={int(mesh.ntet)}",
          flush=True)
    mesh = adjacency.build_adjacency(mesh)
    ecap = int(mesh.tcap * 1.6) + 64
    edges, emask, t2e, _ = adjacency.unique_edges(mesh, ecap)
    jax.block_until_ready(mesh)

    def timeit(name, step):
        dt = obs_costs.chained_seconds(step, mesh, reps=R) * 1000
        print(f"  {name:18s} {dt:8.1f} ms", flush=True)
        return dt

    dep = lambda m, x: m.replace(
        vert=m.vert.at[0, 0].add(0.0 * x.reshape(-1)[0].astype(m.dtype))
    )

    rows = []
    rows.append(("compact", timeit("compact", compact)))
    rows.append(("unique_edges", timeit(
        "unique_edges",
        lambda m: dep(m, adjacency.unique_edges(m, ecap)[0]),
    )))
    rows.append(("build_adjacency", timeit(
        "build_adjacency",
        lambda m: dep(m, adjacency.build_adjacency(m).adja),
    )))
    rows.append(("tria_normals", timeit(
        "tria_normals", lambda m: dep(m, analysis.tria_normals(m)[0]),
    )))
    rows.append(("vertex_normals", timeit(
        "vertex_normals", lambda m: dep(m, analysis.vertex_normals(m)),
    )))
    rows.append(("split", timeit(
        "split",
        lambda m: split.split_long_edges(m, edges, emask, t2e)[0],
    )))
    rows.append(("collapse", timeit(
        "collapse",
        lambda m: collapse.collapse_short_edges(m, edges, emask, t2e)[0],
    )))
    rows.append(("swap32", timeit(
        "swap32", lambda m: swap.swap_32(m, edges, emask, t2e)[0],
    )))
    rows.append(("swap23", timeit(
        "swap23", lambda m: swap.swap_23(m, edges, emask)[0],
    )))
    rows.append(("smooth", timeit(
        "smooth", lambda m: smooth.smooth_vertices(m, edges, emask)[0],
    )))
    print(f"TOTAL {sum(ms for _, ms in rows):.1f} ms  "
          f"(ne={int(mesh.ntet)} tcap={mesh.tcap})")


if __name__ == "__main__":
    main()
