"""Perf-gate smoke for the CI gate (tools/check.sh, between the obs
smoke and tier-1): deterministic end-to-end exercise of the PERF_DB
envelope + regression gate on the hermetic CPU harness.

1. Measure one tiny CPU adapt (the obs-smoke workload) and commit it as
   a fresh PERF_DB-envelope record — asserting every envelope field is
   populated (schema / run_id / git_sha / timestamp / platform / rung).
2. Gate it through the REAL CLI (`tools/perf_gate.py`) against the
   committed fixture baseline `tests/fixtures/perf_db_smoke.jsonl`
   with wide tolerance (--rel-floor 8: a machine 8x slower than the
   fixture median still passes — the pass path must be deterministic
   across containers) — must exit 0.
3. Force a regression (wall_s x1000 on the same record) — must exit
   with the TYPED code (obs.history.REGRESSION_EXIT = 91), and the
   verdict must name wall_s.

Exit 0 = both gate paths behave; anything else fails the CI stage.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from parmmg_tpu.obs import history as obs_history  # noqa: E402
from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures",
                       "perf_db_smoke.jsonl")


def _gate(db, rec_path, extra=()):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "--db", db, rec_path, "--rel-floor", "8"] + list(extra),
        capture_output=True, text=True, cwd=REPO,
    )
    sys.stdout.write(out.stdout)
    sys.stderr.write(out.stderr)
    return out


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_perf_gate_smoke_")
    try:
        # 1. a freshly-generated tiny CPU bench record
        t0 = time.perf_counter()
        out, info = adapt(
            unit_cube_mesh(2),
            AdaptOptions(hsiz=0.5, niter=1, max_sweeps=3, hgrad=None,
                         polish_sweeps=0),
        )
        wall = time.perf_counter() - t0
        ne = int(out.ntet)
        rec = obs_history.make_record(dict(
            metric="smoke_tets_per_sec", value=round(ne / wall, 2),
            unit="tet/s", ne=ne, wall_s=round(wall, 3), platform="cpu",
        ), rung="smoke-n2")
        for key in ("schema", "run_id", "git_sha", "timestamp",
                    "platform", "rung"):
            assert rec.get(key), f"envelope field {key} not populated"
        rec_path = os.path.join(tmp, "rec.json")
        with open(rec_path, "w") as f:
            json.dump(rec, f)
        print(f"[perf-gate-smoke] record: ne={ne} wall={wall:.2f}s "
              f"run_id={rec['run_id']} git_sha={rec['git_sha'][:12]}")

        # 2. pass path against the committed fixture baseline
        db = os.path.join(tmp, "db.jsonl")
        shutil.copy(FIXTURE, db)
        res = _gate(db, rec_path)
        assert res.returncode == 0, (
            f"pass path exited {res.returncode}: {res.stdout}"
        )
        print("[perf-gate-smoke] pass path OK (rc=0)")

        # 3. forced regression: typed failure naming the key
        bad = dict(rec, wall_s=rec["wall_s"] * 1000.0)
        bad_path = os.path.join(tmp, "bad.json")
        with open(bad_path, "w") as f:
            json.dump(bad, f)
        res = _gate(db, bad_path)
        assert res.returncode == obs_history.REGRESSION_EXIT, (
            f"forced regression exited {res.returncode}, wanted "
            f"{obs_history.REGRESSION_EXIT}"
        )
        assert "wall_s" in res.stdout and "REGRESS" in res.stdout, (
            res.stdout
        )
        print(f"[perf-gate-smoke] forced regression OK "
              f"(rc={obs_history.REGRESSION_EXIT}, names wall_s)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
