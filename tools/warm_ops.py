"""Warm the persistent compile cache for the unfused sweep ops at a
given workload shape, ONE op per subprocess.

The tunnel's remote-compile RPC can hang (no client timeout); compiling
each op in its own watchdogged subprocess means a hang loses one op's
attempt, not the whole chain, and every completed compile lands in
.jax_cache for the real run.

Exits nonzero (with a summary) if any op never warmed — a scripted
`warm_ops && scale_run` must not proceed into the cold-compile
livelock on a half-warm cache.

Usage: python tools/warm_ops.py [n] [hsiz] [--stall S] [--attempts K]
"""

import os
import subprocess
import sys
import time

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)

OPS = [
    "prep", "compact", "unique_edges", "split", "collapse", "swap32",
    "build_adjacency", "swap23", "smooth", "histogram", "polish",
]


def worker(n, hsiz, op, tight=False):
    import bench

    bench._enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import AdaptOptions
    from parmmg_tpu.ops import collapse, quality, smooth, split, swap

    mesh = bench._workload(n, hsiz, tight)
    ecap = int(mesh.tcap * 1.6) + 64
    # the real run enters the sweeps AFTER analysis + metric prep, so
    # every program below must be warmed at the ANALYZED shapes: with
    # an un-presized workload, analyze() grows the feature-edge
    # capacity and the warms would compile the wrong bucket
    from parmmg_tpu.models.adapt import prepare_metric
    from parmmg_tpu.ops import analysis

    mesh = analysis.analyze(mesh)
    mesh = prepare_metric(mesh, AdaptOptions(hsiz=hsiz, hgrad=None), ecap)
    if op == "prep":
        # the remaining pre-sweep phases (hausd resolve / target
        # estimate / histogram) compile their own programs — at
        # 844k-tet shapes they cost long enough to trip the scale_run
        # stall watchdog when cold
        from parmmg_tpu.models.adapt import (
            estimate_target_ntet, resolve_hausd,
        )

        resolve_hausd(mesh, AdaptOptions(hgrad=None))
        estimate_target_ntet(mesh)
        out = quality.quality_histogram(mesh)
        jax.block_until_ready(out.counts)
        return
    mesh = compact(mesh)
    if op == "compact":
        jax.block_until_ready(mesh.tet)
        return
    edges, emask, t2e, nu = adjacency.unique_edges(mesh, ecap)
    if op == "unique_edges":
        jax.block_until_ready(edges)
        return
    if op == "split":
        out, _ = split.split_long_edges(mesh, edges, emask, t2e)
    elif op == "collapse":
        out, _ = collapse.collapse_short_edges(mesh, edges, emask, t2e)
    elif op == "swap32":
        out, _ = swap.swap_32(mesh, edges, emask, t2e)
    elif op == "build_adjacency":
        out = adjacency.build_adjacency(mesh)
    elif op == "swap23":
        out = adjacency.build_adjacency(mesh)
        out, _ = swap.swap_23(out, edges, emask)
    elif op == "smooth":
        out, _ = smooth.smooth_vertices(mesh, edges, emask)
    elif op == "histogram":
        out = quality.quality_histogram(mesh)
    elif op == "polish":
        # the post-convergence polish dispatches a sweep variant
        # (noinsert=True, phase_skip=False) that no other path compiles;
        # below UNFUSED_TCAP it is a distinct fused program (ADVICE r4)
        from parmmg_tpu.models import adapt as adapt_mod

        unfused = mesh.tcap > adapt_mod.UNFUSED_TCAP
        out, _ = (adapt_mod._sweep_body if unfused
                  else adapt_mod.remesh_sweep)(
            mesh, ecap, noinsert=True, phase_skip=False,
            fused=not unfused)
        out = out.tet
    else:
        raise SystemExit(f"unknown op {op}")
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])


def main():
    argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        worker(int(argv[1]), float(argv[2]), argv[3],
               tight=len(argv) > 4 and argv[4] == "tight")
        return
    pos, flags = parse_argv(argv)
    n = int(pos[0]) if pos else 14
    hsiz = float(pos[1]) if len(pos) > 1 else 0.03
    tight = flags.get("tight", "") not in ("", "0")
    # above the measured worst single-op compile (~1250 s for split at
    # ~850k-tet capacities): a timeout below it livelocks — a killed
    # compile caches nothing
    stall = int(flags.get("stall", 1800))
    # --attempts K: per-op retry cap. Scripted prep stages pass 1 so a
    # compile that exceeds its (already long) stall cap fails fast
    # instead of burning stall*3 of the stage budget (ADVICE r5)
    attempts = int(flags.get("attempts", 3))
    if attempts < 1:
        raise SystemExit(f"--attempts must be >= 1, got {attempts}")
    # --ops a,b,c: warm a subset (lets two warmers split the list and
    # overlap server-side compiles — watch the compile-helper OOM risk)
    ops = flags.get("ops")
    ops = ops.split(",") if ops else OPS
    unknown = set(ops) - set(OPS)
    if unknown:  # fail in milliseconds, not after a cold-compile chain
        raise SystemExit(f"unknown ops {sorted(unknown)}; valid: {OPS}")
    failed = []
    for op in ops:
        ok = False
        for attempt in range(1, attempts + 1):
            t0 = time.time()
            try:
                rc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--worker", str(n), str(hsiz), op]
                    + (["tight"] if tight else []),
                    timeout=stall, cwd=REPO,
                ).returncode
            except subprocess.TimeoutExpired:
                print(f"{op}: attempt {attempt} TIMED OUT at {stall}s",
                      flush=True)
                continue
            print(f"{op}: rc={rc} in {round(time.time() - t0, 1)}s",
                  flush=True)
            if rc == 0:
                ok = True
                break
        if not ok:
            failed.append(op)
    if failed:
        print(f"## NOT WARMED: {failed}", flush=True)
        sys.exit(1)
    print("## all ops warmed", flush=True)


if __name__ == "__main__":
    main()
