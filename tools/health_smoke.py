"""Run-health smoke for the CI gate (tools/check.sh health stage).

The round-12 acceptance, end to end on the hermetic CPU harness:

1. **centralized leg** — a traced tiny adapt run under
   ``PMMGTPU_STATUS_PORT=0`` must (a) carry the unit-band edge
   fraction (`in_band`) on every sweep record, (b) serve a live
   ``/healthz`` + Prometheus ``/metrics`` scrape MID-RUN (scraped from
   the driver's own phase hook — the run is provably still going), and
   (c) emit the `health:*` trace events from which
   ``obs_report --health`` renders the edge-length histogram, the
   termination verdict and the drain curve;
2. **gate leg** — the final in-band fraction rides a BENCH/PERF_DB
   envelope under the gate key ``len/in_band`` and the noise-aware
   gate actually regresses a quality drop (higher-is-better honored);
3. **forced-stall leg** — a ``max_sweeps=1`` run must be judged
   ``stalled``, never ``converged``;
4. **2-process leg** — a traced 2-rank ``adapt_stacked_input`` run
   leaves a trace directory from which ``--health`` renders the world
   histogram + verdict (``--worker`` is the child mode).

Exit 0 = the run-health observatory is live. Budget knob:
PARMMG_STAGE_BUDGET_S bounds the 2-process wait.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import urllib.request

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def worker() -> int:
    """Child mode: one rank of the traced 2-process adapt run."""
    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input,
    )
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    assert multi and jax.process_count() == 2, "2-process env required"
    watchdog = float(os.environ.get("PMMGTPU_WATCHDOG", "120"))

    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    opts = DistOptions(
        hsiz=0.32, niter=1, max_sweeps=3, nparts=8, min_shard_elts=8,
        hgrad=None, polish_sweeps=0, watchdog_timeout=watchdog,
    )
    try:
        _out, _comm2, info = adapt_stacked_input(st, comm, opts)
    except failsafe.PeerLostError as e:
        print(f"PEER_LOST rank={jax.process_index()}: {e}", flush=True)
        os._exit(failsafe.PEER_LOST_EXIT_CODE)
    bands = [r["in_band"] for r in info["history"] if "in_band" in r]
    print(f"HEALTH_BANDS {json.dumps(bands)}", flush=True)
    print(f"HEALTH_OK rank={jax.process_index()} "
          f"verdict={info['health']['verdict']} "
          f"status={int(info['status'])}", flush=True)
    return 0


def _spawn_pair(tmp: str, obs: str, timeout: float):
    """dist_obs_smoke's 2-process launch idiom."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PMMGTPU_STATUS_PORT", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=ROOT,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
            PMMGTPU_TRACE=obs,
            PMMGTPU_WATCHDOG="120",
            PYTHONFAULTHANDLER="1",
        )
        lp = os.path.join(tmp, f"rank{pid}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=open(lp, "w"),
            stderr=subprocess.STDOUT, cwd=ROOT,
        ))
    try:
        rcs = [p.wait(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            p.kill()
    return rcs, [open(lp).read() for lp in logs]


def main() -> int:
    budget = float(os.environ.get("PARMMG_STAGE_BUDGET_S", "600"))
    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.obs import health as obs_health
    from parmmg_tpu.obs import history as obs_history
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.obs import report as obs_report
    from parmmg_tpu.obs import trace as obs_trace
    from parmmg_tpu.utils.gen import unit_cube_mesh

    tmp = tempfile.mkdtemp(prefix="parmmg_health_smoke_")
    obs_dir = os.path.join(tmp, "obs")
    try:
        # 1. centralized leg: traced run, live scrape mid-run --------
        obs_metrics.registry().reset()
        obs_health.run_state().reset()
        os.environ["PMMGTPU_STATUS_PORT"] = "0"
        tr = obs_trace.Tracer(obs_dir)
        healthz = []

        def hook(phase):
            # the run is between driver phases here — a successful
            # probe is BY CONSTRUCTION a mid-run probe
            port = obs_health.run_state().snapshot().get("status_port")
            if port and phase == "sweeps":
                hz = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=5).read()
                assert hz == b"ok\n", hz
                healthz.append(hz)

        # a Prometheus-style poller on its own thread: latches the
        # first /metrics body that carries sweep counters — scraped
        # while the driver loop is still executing (the endpoint only
        # listens for the run's duration)
        import threading
        import time as _time

        latched = []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                port = obs_health.run_state().snapshot()\
                    .get("status_port")
                if port:
                    try:
                        body = urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=5).read().decode()
                    except OSError:
                        body = ""
                    if ("parmmg_sweeps" in body
                            and "parmmg_run_phase" in body):
                        latched.append(body)
                        return
                _time.sleep(0.05)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        out, info = adapt(
            unit_cube_mesh(2),
            AdaptOptions(hsiz=0.5, niter=1, max_sweeps=3, hgrad=None,
                         polish_sweeps=0),
            tracer=tr, phase_hook=hook,
        )
        stop.set()
        poller.join(timeout=10)
        os.environ.pop("PMMGTPU_STATUS_PORT", None)
        tr.flush()
        hist = [r for r in info["history"] if "nsplit" in r]
        assert hist and all("in_band" in r for r in hist), \
            "sweep records missing in_band"
        assert healthz, "no mid-run /healthz probe succeeded"
        assert latched, "no mid-run /metrics scrape saw sweep counters"
        body = latched[-1]
        for want in ("parmmg_run_phase", "parmmg_sweeps",
                     "parmmg_ops_split_accepted",
                     "parmmg_run_heartbeat_age_s"):
            assert want in body, (want, body)
        print(f"[health-smoke] mid-run scrape OK "
              f"({len(body.splitlines())} metric lines)")

        assert info["health"]["verdict"] in obs_health.VERDICTS
        text = obs_report.render_health(obs_dir)
        for want in ("verdict:", "UNIT EDGE LENGTHS",
                     "drain curve", "sweep history"):
            assert want in text, (want, text)
        in_band = obs_health.history_in_band(info["history"])
        assert in_band is not None and 0.0 <= in_band <= 1.0
        print(f"[health-smoke] --health renders verdict="
              f"{info['health']['verdict']} in_band={in_band:.3f}")

        # 2. gate leg: len/in_band rides the envelope + regresses ----
        import bench

        bands = [r["in_band"] for r in hist]
        payload = {"metric": "tets_per_sec", "value": 1000.0,
                   "len/in_band": bands[-1], "in_band_series": bands}
        rec = bench._envelope(payload, dict(n=2, hsiz=0.5,
                                            kernels="off"))
        assert rec["len/in_band"] == bands[-1]
        assert "len/in_band" in obs_history.GATE_KEYS, \
            "perf gate cannot ratchet mesh quality"
        assert obs_history.GATE_KEYS["len/in_band"] == "higher"
        base = [dict(rec, **{"len/in_band": 0.95, "run_id": f"b{i}"})
                for i in range(4)]
        bad = dict(rec, **{"len/in_band": 0.05})
        res = obs_history.gate(base, bad)
        assert "len/in_band" in res.regressions, \
            [r for r in res.rows]
        good = dict(rec, **{"len/in_band": 0.96})
        assert "len/in_band" not in obs_history.gate(base, good)\
            .regressions
        print("[health-smoke] len/in_band enveloped + gate honors "
              "higher-is-better")

        # 3. forced-stall leg: max_sweeps=1 must NOT read converged ---
        obs_metrics.registry().reset()
        obs_health.run_state().reset()
        out2, info2 = adapt(
            unit_cube_mesh(2),
            AdaptOptions(hsiz=0.35, niter=1, max_sweeps=1, hgrad=None,
                         polish_sweeps=0),
        )
        v2 = info2["health"]
        assert v2["verdict"] == "stalled", v2
        print(f"[health-smoke] forced stall judged {v2['verdict']!r} "
              f"({v2['reason']})")

        # 4. 2-process leg: world histogram + verdict post-mortem ----
        obs2 = os.path.join(tmp, "obs2")
        rcs, logs = _spawn_pair(tmp, obs2, timeout=budget)
        if rcs != [0, 0]:
            for i, log in enumerate(logs):
                print(f"---- rank{i} log ----\n{log[-4000:]}",
                      file=sys.stderr)
            print(f"[health-smoke] worker exits {rcs}",
                  file=sys.stderr)
            return 1
        assert all("HEALTH_OK" in log for log in logs), "no HEALTH_OK"
        s = obs_report.health_summary(obs2)
        assert sorted(s["ranks"]) == [0, 1], s["ranks"]
        assert s["verdict"] and \
            s["verdict"]["verdict"] in obs_health.VERDICTS
        assert s["length"] and s["length"]["nedge"] > 0, s["length"]
        assert s["in_band"] is not None and 0.0 < s["in_band"] <= 1.0
        text2 = obs_report.render_health(obs2)
        for want in ("verdict:", "UNIT EDGE LENGTHS", "sweep history"):
            assert want in text2, (want, text2)
        band_line = next(ln for ln in logs[0].splitlines()
                         if ln.startswith("HEALTH_BANDS "))
        bands2 = json.loads(band_line[len("HEALTH_BANDS "):])
        assert bands2, "2-process run carried no in_band series"
        print(f"[health-smoke] 2-process --health: verdict="
              f"{s['verdict']['verdict']} "
              f"in_band={s['in_band']:.3f} over "
              f"{s['length']['nedge']} world edges")
        print("[health-smoke] live endpoint, verdicts, histogram and "
              "gate key all verified")
        return 0
    finally:
        os.environ.pop("PMMGTPU_STATUS_PORT", None)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(worker() if "--worker" in sys.argv else main())
