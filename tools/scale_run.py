"""Single-chip scale experiment: adapt a cube to a target hsiz and report
throughput — the ladder toward the 10M-tet north star (BASELINE.json).

Above UNFUSED_TCAP the sweep runs per-op (see UNFUSED_TCAP /
run_batched_sweep_loop in models/adapt.py), so each XLA program stays
small enough for the tunnel's compile helper; the persistent compile
cache (.jax_cache/) makes reruns disk-hits.

The tunnel's remote-compile RPC can silently die mid-request (observed:
"response body closed before all bytes were read", and hangs with no
client-side timeout — a 21 s compile once sat for 100+ min on a dead
connection). The driver mode therefore runs the measurement in a worker
subprocess under a STALL WATCHDOG: no stdout progress for --stall
seconds → kill and relaunch. Retries are monotonic ONLY if --stall
exceeds the longest single compile (a kill mid-compile caches nothing);
the measured worst case is split_long_edges at ~1250 s for ~850k-tet
capacities (PERF_NOTES.md), hence the default. Pre-warm with
tools/warm_ops.py to make attempts cheap.

Usage: python tools/scale_run.py [n] [hsiz] [--stall S] [--retries R]
"""

import json
import os
import signal
import subprocess
import sys
import time

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)


def _envelope(rec, n, hsiz):
    """PERF_DB envelope via the one shared constructor
    (obs.history.make_record) — full and partial records of a rung are
    indistinguishable in shape and land in the same baseline group."""
    from parmmg_tpu.obs import history as obs_history

    return obs_history.make_record(rec, rung=f"xl-n{n}-hsiz{hsiz:g}")


def partial_record(n, hsiz, died_in="startup", reason="stage deadline"):
    """Committed-partial record for a stage that hit its time budget —
    same shape as the full record, explicitly marked, naming the phase
    the budget died in (the never-blind bench-ladder contract; closes
    the BENCH_r03/r04 rc=124-with-nothing gap)."""
    return _envelope({
        "metric": "tets_per_sec_cold", "value": 0.0, "unit": "tet/s",
        "includes_compile": True, "partial": True,
        "stage": f"n{n}-hsiz{hsiz}", "died_in": died_in, "error": reason,
    }, n, hsiz)


def _arm_stage_deadline(on_expire):
    """SIGALRM per the PARMMG_STAGE_BUDGET_S env contract (set by
    tools/xl_stage.sh under each stage watchdog): fires `on_expire` at
    the next Python-level checkpoint, well before the outer timeout's
    SIGKILL — the worker commits its own partial record with the phase
    context only it has."""
    budget = os.environ.get("PARMMG_STAGE_BUDGET_S")
    if not budget:
        return

    def _on_alarm(signum, frame):
        on_expire()

    signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(max(int(float(budget)), 1))


def _parse_budgets(spec):
    """'sweeps=0,finalize=2' -> {'sweeps': 0, 'finalize': 2}; '' -> {}.
    Phase names are adapt()'s own markers (analysis / metric /
    input histogram / sweeps / finalize)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            name, _, val = part.partition("=")
            out[name.strip()] = int(val)
    return out


def worker(n, hsiz, tight=False):
    import bench

    bench._enable_compile_cache()
    import jax

    from parmmg_tpu.lint.contracts import run_adapt_with_budget
    from parmmg_tpu.models.adapt import AdaptOptions
    from parmmg_tpu.ops import quality

    est = bench.est_out_tets(hsiz)
    print(f"n={n} hsiz={hsiz} est_out={est} tight={tight} platform="
          f"{jax.devices()[0].platform}", flush=True)
    mesh = bench._workload(n, hsiz, tight)
    print(f"input ne={int(mesh.ntet)} tcap={mesh.tcap} pcap={mesh.pcap}",
          flush=True)
    # budget: refinement needs ~log2(est/input_ne) doubling sweeps (the
    # MIS splits at most one edge per tet per sweep) BEFORE quality
    # work starts; 60x-class refinements (n=16 -> hsiz 0.02) burn 6
    # sweeps on growth alone, so 14 would exhaust mid-growth and leave
    # an unconverged uniform bisection (observed: ne exactly 64x input,
    # qmin == qavg)
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=20, hgrad=None,
                        verbose=2)
    # per-phase retrace budgets (lint.contracts): the xl ladder sets
    # PARMMG_RETRACE_BUDGETS="sweeps=64" after tools/warm_ops.py prep —
    # an explosion guard against per-sweep retracing (each program still
    # traces once even on disk-cache hits; the strict warm-cache
    # steady_recompiles==0 contract lives in bench.py's in-process
    # steady phase). Unset = counts recorded in the JSON, not enforced.
    budgets = _parse_budgets(os.environ.get("PARMMG_RETRACE_BUDGETS"))
    from parmmg_tpu.lint.contracts import RetraceCounter

    counter = RetraceCounter()

    def _expire():
        # the partial record is printed FROM the signal handler: a
        # deadline mid-sweep must still commit a parseable line before
        # the stage watchdog's kill (value 0.0, explicitly partial)
        print(json.dumps(partial_record(
            n, hsiz, died_in=counter._phase,
            reason="PARMMG_STAGE_BUDGET_S expired",
        )), flush=True)
        os._exit(3)

    # warm the envelope machinery (module import + git-sha subprocess
    # cache) OUTSIDE the signal handler: _expire must only format and
    # print
    from parmmg_tpu.obs import history as obs_history

    obs_history.git_sha()
    _arm_stage_deadline(_expire)
    t0 = time.perf_counter()
    out, info = run_adapt_with_budget(mesh, opts, budgets=budgets,
                                      counter=counter)
    signal.alarm(0)
    wall = time.perf_counter() - t0
    ne = int(out.ntet)
    h = quality.quality_histogram(out)
    saf = [
        round(r["n_active"] / max(r["n_unique"], 1), 4)
        for r in info["history"] if "n_active" in r
    ]
    # converged-sweep parity probe (round 8): full-table vs
    # drained-frontier no-op sweep on the adapted mesh — the same
    # numbers bench.py records, so the ladder's trajectory carries the
    # frontier win at every rung (probe compiles respect UNFUSED_TCAP)
    converged = bench.measure_converged_sweep(out, reps=2)
    # COLD timing: one adapt() with no warmup — compile time (or cache
    # hits) is folded in, so this number is NOT comparable to bench.py's
    # steady-state tets_per_sec; the metric name says so
    rec = _envelope({
        "metric": "tets_per_sec_cold", "value": round(ne / wall, 1),
        "unit": "tet/s", "includes_compile": True,
        "ne": ne, "wall_s": round(wall, 2),
        "platform": jax.devices()[0].platform,
        "qmin": round(float(h.qmin), 5), "qavg": round(float(h.qavg), 5),
        "recompiles": info["recompiles"],
        "sweep_active_fraction": saf,
        "converged_sweep_cost": converged,
    }, n, hsiz)
    print(json.dumps(rec), flush=True)


def drive(n, hsiz, stall, retries, tight=False):
    """Run the worker under the stall watchdog. Returns the final JSON
    record line, or None."""
    for attempt in range(retries):
        print(f"## attempt {attempt + 1}/{retries}", flush=True)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(n), str(hsiz)] + (["tight"] if tight else []),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO,
            # unbuffered worker stdio: the watchdog below keys off
            # output cadence, and a block-buffered pipe would hide
            # minutes of per-sweep progress (observed: healthy n=14
            # runs killed at the stall limit with sweeps mid-flight)
            env=dict(os.environ, PYTHONUNBUFFERED="1"),
        )
        os.set_blocking(p.stdout.fileno(), False)
        last_out = time.time()
        buf = ""
        rec = None

        def consume(chunk):
            nonlocal buf, rec
            buf += chunk.decode("utf-8", errors="replace")
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                print(line, flush=True)
                if line.startswith("{"):
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        pass

        while True:
            chunk = p.stdout.read()  # None when no data (non-blocking)
            if chunk:
                last_out = time.time()
                consume(chunk)
            if p.poll() is not None:
                # final drain: output written between the last read and
                # exit (typically the JSON record itself) must not drop
                os.set_blocking(p.stdout.fileno(), True)
                consume(p.stdout.read() or b"")
                break
            if time.time() - last_out > stall:
                print(f"## stall: no output for {stall}s, killing "
                      "(compile cache keeps completed work)", flush=True)
                p.kill()
                p.wait()
                break
            time.sleep(5)
        if rec is not None:
            return rec
    return None


def main():
    argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        worker(int(argv[1]), float(argv[2]),
               tight=len(argv) > 3 and argv[3] == "tight")
        return
    pos, flags = parse_argv(argv)
    n = int(pos[0]) if pos else 14
    hsiz = float(pos[1]) if len(pos) > 1 else 0.03
    stall = int(flags.get("stall", 1500))
    retries = int(flags.get("retries", 6))
    tight = flags.get("tight", "") not in ("", "0")
    bench_json = flags.get("bench-json")
    rec = drive(n, hsiz, stall, retries, tight=tight)
    if rec is None:
        # all retries stalled without even a worker-side partial: the
        # driver commits the partial record itself — the ladder's
        # trajectory is never blind, whatever killed the workers
        rec = partial_record(
            n, hsiz, died_in="worker",
            reason=f"all {retries} attempts stalled (no output for "
                   f"{stall}s each)",
        )
        print(json.dumps(rec), flush=True)
    if bench_json:
        tmp = bench_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, bench_json)
        print(f"## bench_json={bench_json}", flush=True)
    if rec.get("partial"):
        sys.exit(1)


if __name__ == "__main__":
    main()
