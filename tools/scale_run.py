"""Single-chip scale experiment: adapt a cube to a target hsiz and report
throughput — the ladder toward the 10M-tet north star (BASELINE.json).

Above UNFUSED_TCAP the sweep runs per-op (see UNFUSED_TCAP /
run_batched_sweep_loop in models/adapt.py), so each
XLA program stays small enough for the tunnel's compile helper; the
persistent compile cache (.jax_cache/) makes reruns disk-hits.

Usage: python tools/scale_run.py [n] [hsiz]
"""

import json
import os
import sys
import time


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 14
    hsiz = float(sys.argv[2]) if len(sys.argv) > 2 else 0.03

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench._enable_compile_cache()
    import jax

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import quality

    est = bench.est_out_tets(hsiz)
    print(f"n={n} hsiz={hsiz} est_out={est} platform="
          f"{jax.devices()[0].platform}", flush=True)
    mesh = bench._workload(n, hsiz)
    print(f"input ne={int(mesh.ntet)} tcap={mesh.tcap} pcap={mesh.pcap}",
          flush=True)
    opts = AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=14, hgrad=None,
                        verbose=2)
    t0 = time.perf_counter()
    out, info = adapt(mesh, opts)
    wall = time.perf_counter() - t0
    ne = int(out.ntet)
    h = quality.quality_histogram(out)
    # COLD timing: one adapt() with no warmup — compile time (or cache
    # hits) is folded in, so this number is NOT comparable to bench.py's
    # steady-state tets_per_sec; the metric name says so
    rec = {
        "metric": "tets_per_sec_cold", "value": round(ne / wall, 1),
        "unit": "tet/s", "includes_compile": True,
        "ne": ne, "wall_s": round(wall, 2),
        "platform": jax.devices()[0].platform,
        "qmin": round(float(h.qmin), 5), "qavg": round(float(h.qavg), 5),
    }
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
