"""Adaptation-service acceptance smoke: the mixed poisoned batch.

End-to-end proof of the four serving contracts, through the real
`tools/serve.py` process (no in-process shortcuts):

1. **typed admission**: an oversized submission (header says 50k
   vertices) is refused ``too-large`` for the cost of a text scan and
   journaled ``rejected`` — a typed terminal, not an exception;
2. **blast-radius isolation**: one batch carries a healthy job, a
   nan-poisoned job (`JobSpec.faults`, the chaos grammar) and a
   deadline-exceeded job. The poisoned members end ``failed`` /
   ``deadline`` with machine-readable error docs; the healthy members
   end ``done`` with digests BIT-IDENTICAL to a solo run of the same
   input (the strictest no-cross-contamination statement);
3. **crash-safe journal**: the server is SIGKILLed mid-batch (the
   ``PMMGTPU_SERVE_TEST_SLEEP_S`` window guarantees ≥1 terminal and
   exactly one ``running`` record at kill time), restarted on the same
   journal, and must replay to completion — every admitted job reaches
   a typed terminal state, zero lost, the killed attempt visible as
   ``attempts >= 2`` on the in-flight job;
4. **observability**: the shared trace dir spans both server processes
   (JSONL appends), and ``obs_report --serve`` renders every job's
   submitted → running → terminal timeline across the kill.

Exit 0 = all gates green; 1 = any violated (with a FAILURES list).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

SERVE = os.path.join(ROOT, "tools", "serve.py")
POLL_S = 0.1
KILL_WINDOW_SLEEP_S = "2.0"
STAGE_TIMEOUT = 600

TERMINAL = {"done", "failed", "deadline", "rejected", "cancelled"}


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=ROOT,
               TF_CPP_MIN_LOG_LEVEL="3")
    return env


def write_inputs(tmp):
    """The healthy cube mesh (a real adaptable input) and the
    oversized IMPOSTOR: a text header declaring 50k vertices — the
    admission peek must refuse it without ever loading it."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from parmmg_tpu.io import medit
    from parmmg_tpu.utils.gen import unit_cube_mesh

    cube = os.path.join(tmp, "cube.mesh")
    medit.save_mesh(unit_cube_mesh(2), cube)
    big = os.path.join(tmp, "big.mesh")
    with open(big, "w") as f:
        f.write("MeshVersionFormatted 2\nDimension\n3\n"
                "Vertices\n50000\nTetrahedra\n200000\nEnd\n")
    return cube, big


def journal_docs(journal_dir):
    docs = {}
    if not os.path.isdir(journal_dir):
        return docs
    for name in sorted(os.listdir(journal_dir)):
        if not (name.startswith("job_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(journal_dir, name)) as f:
                doc = json.load(f)
            docs[doc["job_id"]] = doc
        except (OSError, ValueError, KeyError):
            continue
    return docs


def spool_spec(spool, doc):
    path = os.path.join(spool, f"{doc['job_id']}.json.tmp")
    with open(path, "w") as f:
        json.dump(doc, f)
    os.replace(path, path[:-len(".tmp")])


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_serve_smoke_")
    failures = []
    try:
        t_start = time.monotonic()
        cube, big = write_inputs(tmp)
        journal = os.path.join(tmp, "journal")
        spool = os.path.join(tmp, "spool")
        obs = os.path.join(tmp, "obs")
        os.makedirs(spool, exist_ok=True)

        # --- solo baseline: the digest every batched healthy job
        # must reproduce bit for bit
        solo_spec = os.path.join(tmp, "solo.json")
        with open(solo_spec, "w") as f:
            json.dump(dict(job_id="solo", inmesh=cube, hsiz=0.45,
                           niter=1), f)
        p = subprocess.run(
            [sys.executable, SERVE, "--solo", solo_spec,
             "--journal", os.path.join(tmp, "journal_solo")],
            env=_env(), capture_output=True, text=True,
            timeout=STAGE_TIMEOUT, cwd=ROOT,
        )
        line = next((ln for ln in p.stdout.splitlines()
                     if ln.startswith("JOB_RESULT")), "")
        fields = dict(tok.split("=", 1) for tok in line.split()[1:])
        if p.returncode != 0 or fields.get("state") != "done":
            failures.append(f"solo baseline: rc={p.returncode} "
                            f"line={line!r}")
            raise SystemExit(1)
        solo_digest = fields["digest"]
        print(f"[serve-smoke] solo baseline done "
              f"(digest {solo_digest}, "
              f"{time.monotonic() - t_start:.1f}s)")

        # --- the mixed batch: 2 healthy, 1 nan-poisoned, 1 deadline,
        # 1 oversized — spooled before the server starts so they land
        # in ONE class-homogeneous batch (batch_max=4; the oversized
        # one is refused at admission and never queued)
        jobs = [
            dict(job_id="h1", inmesh=cube, tenant="acme", niter=1),
            dict(job_id="e", inmesh=cube, tenant="evil", niter=1,
                 faults="it0:remesh:nan"),
            dict(job_id="d", inmesh=cube, tenant="slow", niter=1,
                 deadline_s=1e-4),
            dict(job_id="h2", inmesh=cube, tenant="acme", niter=1),
            dict(job_id="o", inmesh=big, tenant="big"),
        ]
        for doc in jobs:
            spool_spec(spool, doc)

        env = _env()
        env["PMMGTPU_SERVE_TEST_SLEEP_S"] = KILL_WINDOW_SLEEP_S
        log1 = open(os.path.join(tmp, "server1.log"), "w")
        srv = subprocess.Popen(
            [sys.executable, SERVE, "--spool", spool,
             "--journal", journal, "--trace", obs,
             "--batch-max", "4", "--idle-exit", "300"],
            env=env, stdout=log1, stderr=subprocess.STDOUT, cwd=ROOT,
        )

        # --- SIGKILL mid-batch: wait for >=1 terminal AND one
        # `running` record, then kill with no warning whatsoever
        deadline = time.monotonic() + STAGE_TIMEOUT
        killed = False
        while time.monotonic() < deadline and srv.poll() is None:
            docs = journal_docs(journal)
            states = {j: d.get("state") for j, d in docs.items()}
            n_term = sum(1 for s in states.values() if s in TERMINAL)
            running = [j for j, s in states.items() if s == "running"]
            if n_term >= 1 and running:
                os.kill(srv.pid, signal.SIGKILL)
                srv.wait()
                killed = True
                print(f"[serve-smoke] SIGKILL mid-batch: "
                      f"{n_term} terminal, {running[0]} running "
                      f"(states {states})")
                break
            time.sleep(POLL_S)
        if not killed:
            failures.append(
                f"never reached the kill window (server rc "
                f"{srv.poll()}, journal "
                f"{ {j: d.get('state') for j, d in journal_docs(journal).items()} })"
            )
            if srv.poll() is None:
                srv.kill()
                srv.wait()
            raise SystemExit(1)
        kill_states = {j: d.get("state")
                       for j, d in journal_docs(journal).items()}
        in_flight = [j for j, s in kill_states.items()
                     if s == "running"]

        # --- restart on the same journal + trace dir: the replay
        # must finish EVERY job typed, no operator input
        log2 = open(os.path.join(tmp, "server2.log"), "w")
        srv2 = subprocess.run(
            [sys.executable, SERVE, "--spool", spool,
             "--journal", journal, "--trace", obs,
             "--batch-max", "4", "--idle-exit", "5"],
            env=_env(), stdout=log2, stderr=subprocess.STDOUT,
            timeout=STAGE_TIMEOUT, cwd=ROOT,
        )
        if srv2.returncode != 0:
            failures.append(f"restarted server exit "
                            f"{srv2.returncode} (wanted 0 via "
                            "idle-exit)")

        docs = journal_docs(journal)
        expect = dict(h1="done", h2="done", e="failed", d="deadline",
                      o="rejected")
        for jid, want in expect.items():
            got = docs.get(jid, {}).get("state")
            if got != want:
                failures.append(f"job {jid}: state {got!r}, wanted "
                                f"{want!r}")
        # zero lost: every journaled job terminal
        for jid, doc in docs.items():
            if doc.get("state") not in TERMINAL:
                failures.append(f"job {jid}: non-terminal "
                                f"{doc.get('state')!r} after replay")
        # healthy batch-mates bit-identical to the solo run
        for jid in ("h1", "h2"):
            dig = (docs.get(jid, {}).get("result") or {}).get("digest")
            if dig != solo_digest:
                failures.append(
                    f"job {jid}: digest {dig} != solo {solo_digest} "
                    "(batch-mate output contaminated)"
                )
        # typed error docs on the poisoned members
        e_err = docs.get("e", {}).get("error") or {}
        if "Numerical" not in str(e_err.get("type", "")):
            failures.append(f"job e: error doc {e_err} lacks the "
                            "typed NumericalError")
        d_err = docs.get("d", {}).get("error") or {}
        if d_err.get("code") != "deadline":
            failures.append(f"job d: error doc {d_err} lacks "
                            "code=deadline")
        o_err = docs.get("o", {}).get("error") or {}
        if o_err.get("code") != "too-large":
            failures.append(f"job o: error doc {o_err} lacks "
                            "code=too-large")
        # the killed in-flight job re-ran: its attempt count says so
        for jid in in_flight:
            att = int(docs.get(jid, {}).get("attempts", 0))
            if att < 2:
                failures.append(f"job {jid}: killed while running but "
                                f"attempts={att} (no replay attempt)")
        if not failures:
            print(f"[serve-smoke] mixed batch: "
                  + "  ".join(f"{j}->{docs[j]['state']}"
                              for j in sorted(expect)))

        # --- the per-job report must render the cross-restart story
        p = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "obs_report.py"),
             obs, "--serve", "1"],
            env=_env(), capture_output=True, text=True, timeout=120,
            cwd=ROOT,
        )
        rep = p.stdout
        if p.returncode != 0:
            failures.append(f"obs_report --serve exit {p.returncode}")
        for needle in ("serve post-mortem", "job h1", "job e",
                       "job d", "tenant acme"):
            if needle not in rep:
                failures.append(f"--serve report lacks {needle!r}")
        for jid in in_flight:
            if f"job {jid}" in rep and "job_requeued" not in rep \
                    and "attempt=2" not in rep:
                failures.append(
                    f"--serve report: no replay evidence for the "
                    f"killed job {jid}"
                )
        if not failures:
            print("[serve-smoke] --serve post-mortem renders the "
                  "kill-spanning timelines")
            print(f"[serve-smoke] OK: admission refusals, poisoned-"
                  f"batch containment, SIGKILL+replay, bit-identical "
                  f"survivors ({time.monotonic() - t_start:.1f}s)")
            return 0
    except SystemExit:
        pass
    except subprocess.TimeoutExpired as e:
        failures.append(f"stage timeout: {e}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("\n[serve-smoke] FAILURES:")
    for f in failures:
        print(" -", f)
    return 1


if __name__ == "__main__":
    sys.exit(main())
