#!/bin/bash
# CI gate: static analysis first (fails fast, pure stdlib — no
# accelerator touch), then the fault-injection smoke (one NaN + one
# overflow + one kill/resume scenario on the small fixture, through the
# public drivers), then the tier-1 test command from ROADMAP.md.
#
#   tools/check.sh            # lint + fault smoke + tier-1 tests
#   tools/check.sh --lint-only
#
# The linter must exit 0 on the committed tree: every finding is either
# fixed or carries an explicit `# parmmg-lint: disable=RULE -- why`
# suppression. New findings therefore fail this gate.
set -u
cd "$(dirname "$0")/.." || exit 1

# the machine-readable findings artifact rides along: the JSON document
# must parse and carry count=0 — a gate on the artifact contract itself
# (tooling downstream consumes it), not just on the human rendering
LINT_JSON="${LINT_JSON:-/tmp/parmmg_lint.json}"
python -m parmmg_tpu.lint --json "$LINT_JSON" parmmg_tpu tools >/dev/null
rc=$?
echo "## lint rc=$rc"
[ $rc -ne 0 ] && exit $rc
python - "$LINT_JSON" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["count"] == 0 and doc["findings"] == [], doc
assert any(r.startswith("PML016") or r == "PML016" for r in doc["rules"]), \
    sorted(doc["rules"])
EOF
rc=$?
echo "## lint-json rc=$rc"
[ $rc -ne 0 ] && exit $rc
[ "${1:-}" = "--lint-only" ] && exit 0

timeout -k 10 1800 env JAX_PLATFORMS=cpu python tools/fault_smoke.py
rc=$?
echo "## fault-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# 2-process multi-host stage: rank-targeted kill after a sharded,
# barrier-committed checkpoint; the survivor's watchdog must raise a
# typed PeerLostError, a 2-process resume must be bit-identical, and
# the same 2-rank checkpoint must ELASTICALLY resume at world size 1
timeout -k 10 1800 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --multihost
rc=$?
echo "## fault-smoke-multihost rc=$rc"
[ $rc -ne 0 ] && exit $rc

# seeded chaos stage: randomized-but-seeded fault schedules (kill /
# sigterm / ioerror / slowio / nan / overflow / preempt-notice, async
# staging flipped at random) — every run must end in a typed status or
# a bit-identical resume; zero hangs, zero untyped tracebacks. Some
# killed runs resume with the Pallas-kernel backend FLIPPED
# (PMMGTPU_KERNELS off->on): backend knobs must never refuse a resume
timeout -k 10 1800 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=1500 \
    python tools/chaos_smoke.py --seeds 3
rc=$?
echo "## chaos-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# multi-rank chaos matrix: seeded schedules target RANDOM RANKS of a
# real 2-process jax.distributed world — kill@rank, broadcast sigterm,
# injected peer-loss reports, ckpt-store ioerror/slowio bursts, and
# commit-window kills BETWEEN the two manifest barriers. Every rank
# must exit typed, killed worlds must resume bit-identically (elastic
# 2->1 on odd seeds), and every seed must render a per-rank chaos
# post-mortem (obs_report --chaos). PARMMG_STAGE_BUDGET_S-bounded:
# the harness stops scheduling seeds rather than tripping the timeout
timeout -k 10 2700 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=2400 \
    python tools/chaos_smoke.py --world 2 --seeds 3
rc=$?
echo "## chaos-world2 rc=$rc"
[ $rc -ne 0 ] && exit $rc

# collective-desync rung: with the lockstep ledger armed
# (PMMGTPU_VALIDATE=full), an injected it1:comm:desync@rank1 must end
# in the typed divergence exit (92) on EVERY rank at the SAME boundary
# — never a hang, never a one-sided watchdog timeout — and the chaos
# post-mortem must render the collective_divergence detection
timeout -k 10 2700 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=2400 \
    python tools/chaos_smoke.py --desync
rc=$?
echo "## chaos-desync rc=$rc"
[ $rc -ne 0 ] && exit $rc

# elastic autoscaling rung: the operator-free acceptance scenario —
# a 2-rank fleet (tools/fleet.py) absorbs a preemption NOTICE at
# rank 1 (checkpoint -> world-agreed shrink to 1 -> fault-free
# continuation), grows back to 2 on the standing capacity-restored
# signal, and finishes quality-equivalent to a fixed world; both
# world_shrink and world_grow events (with downtime seconds) must
# land in the obs timelines and the --chaos post-mortem must render
# the world-size timeline. Budget-bounded like chaos-world2.
timeout -k 10 2700 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=2400 \
    python tools/chaos_smoke.py --elastic
rc=$?
echo "## chaos-elastic rc=$rc"
[ $rc -ne 0 ] && exit $rc

# distributed-frontier smoke: 2-shard tiny run — sweep_active_fraction
# must drain to ~0 at convergence with the drained-skip path taken,
# frontier on/off must stay result-equivalent, and the drained
# converged phase must not cost more than the full-table one
timeout -k 10 1200 env JAX_PLATFORMS=cpu python tools/frontier_smoke.py
rc=$?
echo "## frontier-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# distributed-observability smoke: a traced 2-process run must leave
# clock-ALIGNED per-rank timelines (synced offsets persisted in the
# JSONL clock segments, rank 0 anchoring), a nonzero straggler-lag vs
# transfer decomposition of the matched coll:* spans with per-rank
# comm/wait_s gauges, the live-tets imbalance factor riding the
# PERF_DB bench envelope (gate key `imbalance`), a rendered
# critical-path table and the merged Perfetto trace
timeout -k 10 900 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=750 \
    python tools/dist_obs_smoke.py
rc=$?
echo "## dist-obs rc=$rc"
[ $rc -ne 0 ] && exit $rc

# load-balancing smoke: a 2-process run seeded with a deliberately
# SKEWED cut must conserve live tets through the closed-loop
# balancer's migrations, end with the measured imbalance back inside
# the band, and leave `rebalance` decision events that render as the
# "balance decisions" line in obs_report --dist
timeout -k 10 900 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=750 \
    python tools/balance_smoke.py
rc=$?
echo "## balance rc=$rc"
[ $rc -ne 0 ] && exit $rc

# Pallas-kernel smoke: interpret-mode run of every registered kernel
# on the tiny fixture with equivalence vs its lax reference, vmap +
# shard_map dispatch parity, and the PMMGTPU_KERNELS=off driver A/B
# (off twice bit-identical; off-vs-on equivalent) on the cube mesh
timeout -k 10 1200 env JAX_PLATFORMS=cpu python tools/kernel_smoke.py
rc=$?
echo "## kernel-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# observability smoke: one tiny traced run must yield a structurally
# valid Chrome trace + JSONL timeline, exact op counters, captured XLA
# cost docs (cost table + HBM watermark line in the report), and a
# parseable obs_report — the never-go-blind gate for the perf arc
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/obs_smoke.py
rc=$?
echo "## obs-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# run-health smoke: a traced tiny run must carry the unit-band edge
# fraction on every sweep record, serve a live /healthz + /metrics
# scrape MID-RUN (PMMGTPU_STATUS_PORT contract), render the
# edge-length histogram + termination verdict + drain curve via
# obs_report --health, envelope len/in_band for the perf gate
# (higher-is-better honored), judge a forced max_sweeps=1 run
# `stalled`, and reconstruct the world histogram from a 2-process run
timeout -k 10 900 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=750 \
    python tools/health_smoke.py
rc=$?
echo "## health-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# run-governor smoke: a governed forced-oscillation run must stop
# EARLY with the typed verdict and its unused sweep budget refunded
# (counter control/refunded_sweeps + a rendered obs_report --control
# decision log), a healthy improving run must NOT be stopped, and SLO
# admission must refuse an infeasible deadline typed at submit while
# stamping deadline-less jobs with the PERF_DB-derived default
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/control_smoke.py
rc=$?
echo "## control-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# adaptation-service smoke: the mixed poisoned batch through the real
# tools/serve.py process — typed too-large refusal, nan + deadline
# members contained to their own typed terminals, SIGKILL mid-batch +
# journal replay on restart with ZERO lost jobs, healthy batch-mates
# bit-identical to a solo run, obs_report --serve rendering the
# kill-spanning per-job timelines
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/serve_smoke.py
rc=$?
echo "## serve-smoke rc=$rc"
[ $rc -ne 0 ] && exit $rc

# serve-throughput bench: N warmed synthetic jobs of one size class on
# a fake-GCS journal; the jobs_per_min record gates (higher-better)
# against the committed PERF_DB baseline with the usual wide rel-floor
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/serve.py \
    --bench 1 --jobs 4 --warmup 1 --classes tiny \
    --db PERF_DB.jsonl --rel-floor 8
rc=$?
echo "## serve-bench rc=$rc"
[ $rc -ne 0 ] && exit $rc

# checkpoint-overlap bench vs a gs:// store (fake-GCS server in CI;
# a real bucket when PMMGTPU_GCS_BUCKET + auth are present): records
# ckpt_overlap_s per epoch size through the PARMMG_BENCH_CKPT_STORE
# wiring and gates them against the committed PERF_DB baselines (wide
# rel-floor — wall clocks differ per container)
timeout -k 10 900 env JAX_PLATFORMS=cpu PARMMG_STAGE_BUDGET_S=750 \
    python tools/ckpt_bench.py --every 1,2,4 --niter 6 \
    --db PERF_DB.jsonl --rel-floor 8
rc=$?
echo "## ckpt-bench rc=$rc"
[ $rc -ne 0 ] && exit $rc

# perf gate: a freshly-generated tiny CPU bench record must carry the
# full PERF_DB envelope and gate CLEAN against the committed fixture
# baseline (wide tolerance — deterministic across containers), and a
# forced 1000x wall_s regression must exit the TYPED code (91)
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/perf_gate_smoke.py
rc=$?
echo "## perf-gate rc=$rc"
[ $rc -ne 0 ] && exit $rc

set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
echo "## tier1 rc=$rc"
exit $rc
