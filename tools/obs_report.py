"""Render a run report from a trace directory (parmmg_tpu.obs).

Usage:
  python tools/obs_report.py <trace-dir>            # text report
  python tools/obs_report.py <trace-dir> --json 1   # structured JSON
  python tools/obs_report.py <trace-dir> --chaos 1  # per-rank chaos
                                  # post-mortem: injected fault ->
                                  # detection -> recovery chain per
                                  # rank (file-ordered JSONL, spans a
                                  # kill and its resume), merged with
                                  # the surviving metrics_rank*.json
  python tools/obs_report.py <trace-dir> --serve 1  # per-job serving
                                  # post-mortem: submitted -> running
                                  # -> typed terminal timeline per job
                                  # (file-ordered, spans server
                                  # restarts), tenant/refusal rollups
  python tools/obs_report.py <trace-dir> --health 1 # run-health view:
                                  # unit-length edge histogram (the
                                  # reference's -prilen picture),
                                  # termination verdict (converged /
                                  # stalled / oscillating /
                                  # budget_exhausted) with reasons,
                                  # drain curve + ETA, sweep history
  python tools/obs_report.py <trace-dir> --control 1 # run-governor
                                  # decision log: every hold /
                                  # early_stop / tune_budget /
                                  # shorten_niter control_decision
                                  # event with its reason, the sweep
                                  # refund total, and the final
                                  # (possibly governor-overridden)
                                  # health verdict
  python tools/obs_report.py <trace-dir> --dist 1   # cross-rank view:
                                  # clock-aligned per-rank timelines,
                                  # per-phase collective decomposition
                                  # (straggler lag vs transfer, worst
                                  # rank named), load-imbalance factor
                                  # and the per-iteration critical
                                  # path; also writes the merged
                                  # Perfetto trace trace_merged.json
  python tools/obs_report.py <trace-dir> --merge-metrics out.json
                                  # one world metrics doc from the
                                  # per-rank metrics_rank*.json files

The trace directory is what a run under ``PMMGTPU_TRACE=<dir>`` (or an
explicit ``tracer=Tracer(dir)``) leaves behind: ``trace_rank<r>.json``
(Chrome trace events — load in Perfetto / chrome://tracing for the
timeline view, alongside any ``profile/`` device capture),
``events_rank<r>.jsonl`` (the durable line log, complete even after an
``os._exit`` death) and ``metrics_rank<r>.json``. Pure stdlib + host
code: never touches the accelerator.

The operators section includes the active-set telemetry (round 8):
the world ``sweep_active_fraction`` gauge plus a per-shard column from
the ``sweep_active_fraction/shard<i>`` gauges the distributed drivers
record — a drained shard reads 0.000 while its neighbors still churn.

Round 9: the *cost attribution* section joins captured XLA cost docs
(``costs_rank*.json``) with the measured span means into roofline
verdicts per jitted phase, and the *memory* section renders the
``hbm/*`` watermark gauges — see README "Cost attribution & perf
gating" for the capture recipe.
"""

import json
import sys

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)

from parmmg_tpu.obs import metrics as obs_metrics
from parmmg_tpu.obs import report as obs_report


def main():
    pos, flags = parse_argv(sys.argv[1:])
    if not pos:
        print(__doc__)
        return 2
    trace_dir = pos[0]
    if "merge-metrics" in flags:
        merged = obs_metrics.merge_dir(trace_dir)
        if merged is None:
            print(f"no metrics_rank*.json under {trace_dir}",
                  file=sys.stderr)
            return 1
        with open(flags["merge-metrics"], "w") as f:
            json.dump(merged, f, indent=1)
        print(f"merged {merged['world']} rank doc(s) -> "
              f"{flags['merge-metrics']}")
        return 0
    if flags.get("dist", "") not in ("", "0"):
        if flags.get("json", "") not in ("", "0"):
            print(json.dumps(obs_report.dist_summary(trace_dir),
                             indent=1, default=str))
            return 0
        print(obs_report.render_dist(trace_dir))
        return 0
    if flags.get("health", "") not in ("", "0"):
        if flags.get("json", "") not in ("", "0"):
            print(json.dumps(obs_report.health_summary(trace_dir),
                             indent=1, default=str))
            return 0
        print(obs_report.render_health(trace_dir))
        return 0
    if flags.get("control", "") not in ("", "0"):
        if flags.get("json", "") not in ("", "0"):
            print(json.dumps(obs_report.control_summary(trace_dir),
                             indent=1, default=str))
            return 0
        print(obs_report.render_control(trace_dir))
        return 0
    if flags.get("serve", "") not in ("", "0"):
        if flags.get("json", "") not in ("", "0"):
            print(json.dumps(obs_report.serve_summary(trace_dir),
                             indent=1, default=str))
            return 0
        print(obs_report.render_serve(trace_dir))
        return 0
    if flags.get("chaos", "") not in ("", "0"):
        if flags.get("json", "") not in ("", "0"):
            print(json.dumps(obs_report.chaos_summary(trace_dir),
                             indent=1, default=str))
            return 0
        print(obs_report.render_chaos(trace_dir))
        return 0
    if flags.get("json", "") not in ("", "0"):
        print(json.dumps(obs_report.summarize(trace_dir), indent=1,
                         default=str))
        return 0
    print(obs_report.render(trace_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
