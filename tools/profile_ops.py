"""Per-op steady-state profiler for the remeshing kernels, on the
shared `parmmg_tpu.obs.costs` timing/attribution helpers.

Times each kernel of the sweep (warm jit, `obs.costs.timed_mean`) on
whatever backend jax resolves — run as-is for the TPU tunnel, or with
`env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu` for the host anchor —
and attributes each kernel's XLA cost (flops, bytes accessed,
arithmetic intensity, roofline bound vs the platform peak table): the
selection table for the Pallas arc, and the regenerable source of the
PERF_NOTES roofline tables.

Usage:

    python tools/profile_ops.py [n] [hsiz] [reps] [--json <path>]
        [--kernels auto|off|on|<csv>]

`--json <path>` additionally commits the whole table as ONE
PERF_DB-envelope record (metric ``profile_ops``, per-op rows under
``ops``) — append it with `tools/perf_gate.py --update-baseline`, or
regenerate a PERF_NOTES table from the file instead of copy-pasting
stdout.

`--kernels` sets the Pallas kernel dispatch mode for the op rows
(parmmg_tpu.kernels.registry). Independent of the mode, a per-kernel
section profiles every REGISTERED kernel on the fixture's packed
streams: the lax reference with its XLA-counted cost, and the Pallas
implementation with its analytic I/O contract (`est_cost`) — the
bytes-moved comparison that is the kernel's fusion claim. On non-TPU
backends the Pallas timing is the interpret harness (correctness
path), so only the bytes/intensity columns are meaningful there; run
the same tool on TPU for achieved %-of-roof.
"""
# parmmg-lint: disable-file=PML004,PML005 -- one-shot profiling harness: wrappers are built once per process and meshes are deliberately reused across repeats

import json
import os
import sys
import time

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)

import jax

from parmmg_tpu.obs import costs as obs_costs
from parmmg_tpu.obs import history as obs_history


def profile_op(name, jitfn, args, reps=5):
    """One per-op row: measured steady-state mean (shared timed_mean
    definition) + the kernel's XLA cost doc + its roofline verdict."""
    sec = obs_costs.timed_mean(lambda: jitfn(*args), reps=reps)
    try:
        doc = obs_costs.cost_doc(jitfn, args)
    except Exception as exc:  # analysis never sinks the measurement
        doc = dict(flops=0.0, bytes_accessed=0.0,
                   error=f"{type(exc).__name__}: {exc}")
    row = dict(
        op=name, ms=round(sec * 1e3, 3),
        flops=doc.get("flops", 0.0),
        bytes_accessed=doc.get("bytes_accessed", 0.0),
    )
    if "error" in doc:
        row["cost_error"] = doc["error"]
    row.update({
        k: v for k, v in obs_costs.roofline(
            row["flops"], row["bytes_accessed"], sec,
            doc.get("platform", jax.devices()[0].platform),
        ).items()
        if k in ("intensity", "bound", "pct_of_roof")
    })
    return row


def profile_kernels(mesh, reps):
    """Per-registered-kernel rows: the lax reference (XLA-counted cost)
    vs the Pallas implementation (analytic I/O contract) on the
    fixture's packed streams — the after-picture of the fusion."""
    import jax.numpy as jnp

    from parmmg_tpu.kernels import registry as kreg
    from parmmg_tpu.ops import common as ops_common

    bc = jnp.mean(mesh.vert[mesh.tet], axis=1)
    ntc = mesh.tet.shape[0]
    zi = jnp.zeros(ntc, jnp.int32)
    vol = ops_common.vol_of(mesh.vert, mesh.tet)
    args_for = {
        "quality_vol": (mesh.vert, mesh.met, mesh.tet),
        "collapse_cavity": (mesh.vert, mesh.met, mesh.tet,
                            ops_common.POS_VOL_FRAC * jnp.abs(vol)),
        "split_midpoint": (mesh.vert, mesh.tet, bc, zi, zi + 1),
        "interp_bary": (mesh.vert, mesh.met, mesh.tet, bc),
    }
    rows = []
    for name in kreg.names():
        k = kreg.get(name)
        args = args_for.get(name)
        if args is None:
            continue
        rows.append(profile_op(f"k:{name}/lax",
                               jax.jit(k.lax_reference), args, reps))
        est = k.est_cost(*args) if k.est_cost else dict(
            flops=0.0, bytes_accessed=0.0)
        pal = jax.jit(k.pallas_impl)
        sec = obs_costs.timed_mean(lambda: pal(*args), reps=reps)
        row = dict(op=f"k:{name}/pallas", ms=round(sec * 1e3, 3),
                   flops=est["flops"],
                   bytes_accessed=est["bytes_accessed"],
                   cost_source="est_io")
        row.update({
            kk: v for kk, v in obs_costs.roofline(
                row["flops"], row["bytes_accessed"], sec,
                jax.devices()[0].platform,
            ).items() if kk in ("intensity", "bound", "pct_of_roof")
        })
        rows.append(row)
    return rows


def main():
    pos, flags = parse_argv(sys.argv[1:])
    n = int(pos[0]) if pos else 8
    hsiz = float(pos[1]) if len(pos) > 1 else 0.08
    reps = int(pos[2]) if len(pos) > 2 else 5

    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.kernels import registry as kreg
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import analysis, collapse, smooth, split, swap

    if "kernels" in flags:
        kreg.set_mode(flags["kernels"])
    kmode = kreg.resolve_mode()
    kernels_on = any(kreg.enabled(nm) for nm in kreg.names())
    print(f"platform: {jax.devices()[0].platform}  "
          f"kernels: {kmode} ({'pallas' if kernels_on else 'lax'})",
          flush=True)
    import bench

    # the bench's own workload recipe (shared sizing formula + capacity
    # multipliers) so profiled shapes match benchmarked ones exactly
    mesh = bench._workload(n, hsiz)
    # reach steady state: one adaptation pass
    t0 = time.perf_counter()
    mesh, _ = adapt(mesh, AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=8,
                                       hgrad=None))
    print(f"steady-state prep: {time.perf_counter() - t0:.1f}s "
          f"ne={int(mesh.ntet)}", flush=True)
    ecap = int(mesh.tcap * 1.6) + 64

    rows = []

    run_compact = jax.jit(lambda m: compact(m))
    rows.append(profile_op("compact", run_compact, (mesh,), reps))
    mesh = run_compact(mesh)

    ue = jax.jit(adjacency.unique_edges, static_argnames=("ecap",))
    run_ue = jax.jit(lambda m: ue(m, ecap))
    rows.append(profile_op("unique_edges", run_ue, (mesh,), reps))
    edges, emask, t2e, nu = run_ue(mesh)

    rows.append(profile_op("build_adjacency", adjacency.build_adjacency,
                           (mesh,), reps))
    mesh = adjacency.build_adjacency(mesh)

    rows.append(profile_op("tria_normals", analysis.tria_normals,
                           (mesh,), reps))
    rows.append(profile_op("vertex_normals", analysis.vertex_normals,
                           (mesh,), reps))

    @jax.jit
    def run_split(m):
        # outer non-donating jit: the ops' donate_argnums would otherwise
        # invalidate the reused input buffer on TPU between reps
        return split.split_long_edges(m, edges, emask, t2e)[0]

    rows.append(profile_op("split", run_split, (mesh,), reps))

    @jax.jit
    def run_col(m):
        return collapse.collapse_short_edges(m, edges, emask, t2e)[0]

    rows.append(profile_op("collapse", run_col, (mesh,), reps))

    @jax.jit
    def run_s32(m):
        return swap.swap_32(m, edges, emask, t2e)[0]

    rows.append(profile_op("swap32", run_s32, (mesh,), reps))

    @jax.jit
    def run_s23(m):
        return swap.swap_23(m, edges, emask)[0]

    rows.append(profile_op("swap23", run_s23, (mesh,), reps))

    @jax.jit
    def run_sm(m):
        return smooth.smooth_vertices(m, edges, emask)[0]

    rows.append(profile_op("smooth", run_sm, (mesh,), reps))

    print(f"\nper-op steady state (ms, mean of {reps}) + roofline, "
          f"ne={int(mesh.ntet)} tcap={mesh.tcap}:")
    print(f"  {'op':<16s} {'ms':>8s} {'flops':>10s} {'bytes':>10s} "
          f"{'F/B':>6s} {'%roof':>7s}  bound")
    for r in rows:
        pct = f"{r['pct_of_roof']:.2%}" if "pct_of_roof" in r else "-"
        print(f"  {r['op']:<16s} {r['ms']:8.1f} {r['flops']:>10.3g} "
              f"{r['bytes_accessed']:>10.3g} {r['intensity']:>6.2f} "
              f"{pct:>7s}  {r['bound']}")
    print(f"  TOTAL            {sum(r['ms'] for r in rows):8.1f}")

    krows = profile_kernels(mesh, reps)
    if krows:
        print("\nregistered kernels: lax reference (XLA-counted) vs "
              "Pallas (I/O contract):")
        print(f"  {'kernel':<26s} {'ms':>8s} {'flops':>10s} "
              f"{'bytes':>10s} {'F/B':>6s}  bound")
        for r in krows:
            print(f"  {r['op']:<26s} {r['ms']:8.1f} "
                  f"{r['flops']:>10.3g} {r['bytes_accessed']:>10.3g} "
                  f"{r['intensity']:>6.2f}  {r['bound']}")
        if jax.devices()[0].platform != "tpu":
            print("  (pallas ms on this backend = interpret harness — "
                  "compare bytes/F/B here, time on TPU)")

    if "json" in flags:
        rung = f"ops-n{n}-hsiz{hsiz:g}" + ("-pk" if kernels_on else "")
        rec = obs_history.make_record(dict(
            metric="profile_ops",
            value=round(sum(r["ms"] for r in rows), 3),
            unit="ms_total",
            ne=int(mesh.ntet), tcap=int(mesh.tcap), reps=reps,
            platform=jax.devices()[0].platform,
            kernels=("on" if kernels_on else "off"),
            kernels_mode=kmode,
            ops=rows,
            kernels_profile=krows,
        ), rung=rung)
        tmp = flags["json"] + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(tmp, flags["json"])
        print(f"## profile_ops record -> {flags['json']}")


if __name__ == "__main__":
    main()
