"""Per-op steady-state profiler for the remeshing kernels.

Times each kernel of the sweep (warm jit, block_until_ready) on whatever
backend jax resolves — run as-is for the TPU tunnel, or with
`env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu` for the host anchor.
Produces the PERF_NOTES.md table. Usage:

    python tools/profile_ops.py [n] [hsiz] [reps]
"""
# parmmg-lint: disable-file=PML004,PML005 -- one-shot profiling harness: wrappers are built once per process and meshes are deliberately reused across repeats

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1000.0, out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    hsiz = float(sys.argv[2]) if len(sys.argv) > 2 else 0.08
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 5

    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import analysis, collapse, smooth, split, swap

    print(f"platform: {jax.devices()[0].platform}", flush=True)
    import bench

    # the bench's own workload recipe (shared sizing formula + capacity
    # multipliers) so profiled shapes match benchmarked ones exactly
    mesh = bench._workload(n, hsiz)
    # reach steady state: one adaptation pass
    t0 = time.perf_counter()
    mesh, _ = adapt(mesh, AdaptOptions(niter=1, hsiz=hsiz, max_sweeps=8,
                                       hgrad=None))
    print(f"steady-state prep: {time.perf_counter() - t0:.1f}s "
          f"ne={int(mesh.ntet)}", flush=True)
    ecap = int(mesh.tcap * 1.6) + 64

    rows = []

    ms, mesh2 = timeit(jax.jit(lambda m: compact(m)), mesh, reps=reps)
    rows.append(("compact", ms))
    mesh = mesh2

    ue = jax.jit(adjacency.unique_edges, static_argnames=("ecap",))
    ms, (edges, emask, t2e, nu) = timeit(lambda m: ue(m, ecap), mesh,
                                         reps=reps)
    rows.append(("unique_edges", ms))

    ms, mesh_adj = timeit(adjacency.build_adjacency, mesh, reps=reps)
    rows.append(("build_adjacency", ms))
    mesh = mesh_adj

    ms, _ = timeit(analysis.tria_normals, mesh, reps=reps)
    rows.append(("tria_normals", ms))

    ms, _ = timeit(analysis.vertex_normals, mesh, reps=reps)
    rows.append(("vertex_normals", ms))

    @jax.jit
    def run_split(m):
        # outer non-donating jit: the ops' donate_argnums would otherwise
        # invalidate the reused input buffer on TPU between reps
        return split.split_long_edges(m, edges, emask, t2e)[0]

    ms, _ = timeit(run_split, mesh, reps=reps)
    rows.append(("split", ms))

    @jax.jit
    def run_col(m):
        return collapse.collapse_short_edges(m, edges, emask, t2e)[0]

    ms, _ = timeit(run_col, mesh, reps=reps)
    rows.append(("collapse", ms))

    @jax.jit
    def run_s32(m):
        return swap.swap_32(m, edges, emask, t2e)[0]

    ms, _ = timeit(run_s32, mesh, reps=reps)
    rows.append(("swap32", ms))

    @jax.jit
    def run_s23(m):
        return swap.swap_23(m, edges, emask)[0]

    ms, _ = timeit(run_s23, mesh, reps=reps)
    rows.append(("swap23", ms))

    @jax.jit
    def run_sm(m):
        return smooth.smooth_vertices(m, edges, emask)[0]

    ms, _ = timeit(run_sm, mesh, reps=reps)
    rows.append(("smooth", ms))

    print(f"\nper-op steady state (ms, mean of {reps}), "
          f"ne={int(mesh.ntet)} tcap={mesh.tcap}:")
    for name, ms in rows:
        print(f"  {name:16s} {ms:8.1f}")
    print(f"  TOTAL            {sum(ms for _, ms in rows):8.1f}")


if __name__ == "__main__":
    main()
