"""Run-governor smoke for the CI gate (tools/check.sh control stage).

The closed-loop control acceptance, end to end on the hermetic CPU
harness (`parmmg_tpu.control` + `service.admission.SloPolicy`):

1. **forced-oscillation leg** — a governed run over a discontinuous
   metric (a 0.5 -> 0.13 target-size jump at x=0.5, the classic
   split<->collapse churn driver) must terminate EARLY with the typed
   ``oscillating``/``stalled`` verdict, refund its unused sweep budget
   (counter ``control/refunded_sweeps``, the refund folded into
   ``info["health"]["control"]``), and leave ``control_decision``
   trace events that ``obs_report --control`` renders;
2. **improving-run leg** — the SAME governor over a healthy converging
   run must never early-stop: control refuses to trade quality it can
   see accruing (the in_band slope guard + the decaying-ops verdict);
3. **admission leg** — a `JobServer` armed with a PERF_DB fixture
   (``serve-<class>`` throughput history) refuses an infeasible
   deadline TYPED at submit (``slo-infeasible``, journaled
   ``rejected``, counter ``serve/refused_slo_infeasible``) and stamps
   a deadline-less job with the data-derived ``quote x margin``
   default.

Exit 0 = the governor stops what telemetry condemns, spares what it
clears, and admission quotes what history proves.
"""

import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def oscillation_mesh():
    """The validated forced-churn scenario: a perturbed cube whose
    metric demands 0.5-edges on one half and 0.13-edges on the other —
    the discontinuity keeps split and collapse fighting over the same
    band of elements sweep after sweep."""
    import jax.numpy as jnp
    import numpy as np

    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(3, perturb=0.1, seed=3)
    x = np.asarray(mesh.vert[:, 0])
    h = np.where(x < 0.5, 0.5, 0.13)
    # met_set=True or prepare_metric overwrites the discontinuity with
    # implied sizes
    return mesh.replace(met=jnp.asarray(h, mesh.vert.dtype)[:, None],
                        met_set=True)


def main() -> int:
    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.obs import health as obs_health
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.obs import report as obs_report
    from parmmg_tpu.obs import trace as obs_trace
    from parmmg_tpu.utils.gen import unit_cube_mesh

    tmp = tempfile.mkdtemp(prefix="parmmg_control_smoke_")
    obs_dir = os.path.join(tmp, "obs")
    try:
        # 1. forced oscillation: the governor must stop it early ------
        obs_metrics.registry().reset()
        obs_health.run_state().reset()
        tr = obs_trace.Tracer(obs_dir)
        budget = 30
        _out, info = adapt(
            oscillation_mesh(),
            AdaptOptions(niter=3, max_sweeps=budget, converge_frac=0.0,
                         hgrad=None, polish_sweeps=0, govern=True),
            tracer=tr,
        )
        tr.flush()
        health = info["health"]
        assert health.get("early_stop"), (
            "governed forced-oscillation run did not early-stop: "
            f"{health}"
        )
        assert health["verdict"] in ("oscillating", "stalled"), health
        assert health["reason"].startswith("governor early stop"), \
            health["reason"]
        ctl = health["control"]
        assert ctl["refunded_sweeps"] > 0, ctl
        assert ctl["decisions"] >= 1, ctl
        refunded = obs_metrics.registry().counter(
            "control/refunded_sweeps").value
        assert refunded == ctl["refunded_sweeps"], \
            (refunded, ctl["refunded_sweeps"])
        sweeps_run = len([r for r in info["history"] if "nsplit" in r])
        assert sweeps_run < budget * 3, (
            "early stop claimed but the full budget was spent"
        )
        print(f"[control-smoke] forced oscillation -> "
              f"verdict={health['verdict']} early_stop after "
              f"{sweeps_run} sweep(s), {ctl['refunded_sweeps']} "
              "refunded")

        # the decision log is a rendered artifact, not just counters
        s = obs_report.control_summary(obs_dir)
        acts = s["by_action"]
        assert acts.get("early_stop", 0) >= 1, acts
        assert s["refunded_sweeps"] > 0, s
        text = obs_report.render_control(obs_dir)
        for want in ("control decisions", "early_stop", "refunded",
                     "final verdict"):
            assert want in text, (want, text)
        print(f"[control-smoke] --control renders "
              f"{len(s['decisions'])} decision(s): "
              + "  ".join(f"{k} {v}" for k, v in sorted(acts.items())))

        # 2. healthy improving run: the governor must NOT stop it -----
        obs_metrics.registry().reset()
        obs_health.run_state().reset()
        _out2, info2 = adapt(
            unit_cube_mesh(2),
            AdaptOptions(hsiz=0.5, niter=1, max_sweeps=8, hgrad=None,
                         polish_sweeps=0, govern=True),
        )
        h2 = info2["health"]
        assert not h2.get("early_stop"), (
            "governor early-stopped a healthy improving run: "
            f"{h2}"
        )
        assert h2["verdict"] not in ("oscillating", "stalled"), h2
        assert "control" in h2, h2
        print(f"[control-smoke] healthy run spared -> "
              f"verdict={h2['verdict']} "
              f"(decisions={h2['control']['decisions']})")

        # 3. SLO admission vs a PERF_DB fixture -----------------------
        from parmmg_tpu.io import ckpt_store, medit
        from parmmg_tpu.service import JobServer, JobSpec, SizeClass
        from parmmg_tpu.service.jobs import SloInfeasibleError

        tiny = SizeClass("t", pcap=256, tcap=1024, fcap=256, ecap=256)
        db_path = os.path.join(tmp, "perf_db.jsonl")
        with open(db_path, "w") as f:
            for i, jpm in enumerate((140.0, 150.0, 145.0)):
                f.write(json.dumps(dict(
                    rung="serve-t", platform="cpu",
                    metric="jobs_per_min", value=jpm,
                    unit="jobs/min", run_id=f"fix{i}",
                    warmup_s=30.0,
                )) + "\n")
        os.environ["PMMGTPU_SLO_PLATFORM"] = "cpu"
        ckpt_store.memory_bucket("control-smoke").clear()
        server = JobServer(
            ckpt_store.make_store("mem://control-smoke", None),
            classes=(tiny,), slo=db_path,
        )
        quote = server.slo.quote("t")
        assert quote and quote["baseline_n"] == 3, quote
        inmesh = os.path.join(tmp, "cube.mesh")
        medit.save_mesh(unit_cube_mesh(2), inmesh)

        # infeasible deadline: refused typed, journaled rejected
        try:
            server.submit(JobSpec(job_id="slo-bad", inmesh=inmesh,
                                  deadline_s=quote["latency_s"] / 10))
            raise AssertionError(
                "infeasible deadline was admitted (quote "
                f"{quote['latency_s']}s)"
            )
        except SloInfeasibleError as err:
            doc = err.doc()
            assert doc["code"] == "slo-infeasible", doc
            assert doc["transient"] is False, doc
            assert doc["quoted_s"] == quote["latency_s"], doc
        jdoc = server.journal.load("slo-bad")
        assert jdoc and jdoc["state"] == "rejected", jdoc
        refused = obs_metrics.registry().counter(
            "serve/refused_slo_infeasible").value
        assert refused == 1, refused
        print(f"[control-smoke] deadline {quote['latency_s'] / 10:.4f}s"
              f" < quote {quote['latency_s']}s -> typed slo-infeasible"
              " at submit, journaled rejected")

        # deadline-less job: data-derived default = quote x margin
        # plus the rung's recorded warmup as the cold-start allowance
        rec = server.submit(JobSpec(job_id="slo-ok", inmesh=inmesh))
        got = rec["spec"]["deadline_s"]
        want = round(quote["latency_s"] * server.slo.margin
                     + quote["warmup_s"], 3)
        assert got == want, (got, want)
        print(f"[control-smoke] deadline-less job stamped "
              f"{got}s (= quote x {server.slo.margin} margin "
              f"+ {quote['warmup_s']}s warmup allowance)")

        print("[control-smoke] OK: governor stops churn, spares "
              "progress; admission quotes history")
        return 0
    finally:
        os.environ.pop("PMMGTPU_SLO_PLATFORM", None)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
