"""Checkpoint-overlap bench against a real object store.

Closes the ROADMAP "real-bucket bench" thread of the preemptible-fleet
arc: measure how much checkpoint wall time the async staging writer
(`AdaptOptions.checkpoint_async`, PR 5) hides behind compute when the
store is a REAL ``gs://`` endpoint rather than a local directory —
``ckpt_overlap_s`` vs epoch size (``checkpoint_every``), recorded as
PERF_DB-enveloped records the perf gate watches.

Store resolution:

- ``PMMGTPU_GCS_BUCKET`` set → a real bucket:
  ``gs://$PMMGTPU_GCS_BUCKET/<prefix>`` with auth per the
  ``PMMGTPU_GCS_*`` contract (`parmmg_tpu/io/gcs.py`); backend tag
  ``gcs``;
- otherwise → a hermetic in-process fake-GCS server
  (`tests/fake_gcs.py`) speaking the same stdlib-HTTP adapter over
  real sockets; backend tag ``gcs-fake`` (CI mode — the adapter,
  retry taxonomy and manifest-last publish discipline are all
  exercised; only the WAN latency is synthetic).

Each epoch size runs one checkpointing adapt through the SAME
machinery the bench ladder arms with ``PARMMG_BENCH_CKPT=1`` (which
now takes ``PARMMG_BENCH_CKPT_STORE`` for the store spec); the record
carries ``wall_s`` (gated one-sided ↓), ``value`` =
``ckpt_overlap_s`` (gated ↑ — a staging regression that stops hiding
I/O behind compute shows up as a value drop), commits, and bytes put.

Usage::

  python tools/ckpt_bench.py [--every 1,2,4] [--niter 6]
      [--json BENCH_ckpt.json] [--db PERF_DB.jsonl --update 1]

Exit 0 on success (and on a budget-capped partial sweep — every
completed epoch size still prints/commits its record).
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

WORKLOAD = dict(hsiz=0.45, max_sweeps=2, hgrad=None, polish_sweeps=0)


def resolve_store():
    """(spec, backend tag, cleanup fn). Real bucket when
    PMMGTPU_GCS_BUCKET names one, else a fresh fake-GCS server."""
    bucket = os.environ.get("PMMGTPU_GCS_BUCKET")
    if bucket:
        prefix = f"parmmg-ckpt-bench/{os.getpid()}-{int(time.time())}"
        return f"gs://{bucket}/{prefix}", "gcs", (lambda: None)
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    from fake_gcs import FakeGCS

    srv = FakeGCS()
    base = srv.start()
    os.environ["PMMGTPU_GCS_ENDPOINT"] = base
    os.environ["PMMGTPU_GCS_AUTH"] = "anon"
    return "gs://parmmg-bench/ckpt", "gcs-fake", srv.stop


def run_one(every: int, niter: int, spec: str):
    """One checkpointing adapt at epoch size `every` through the
    bench's PARMMG_BENCH_CKPT_STORE wiring; returns the payload."""
    import dataclasses

    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.utils.gen import unit_cube_mesh

    reg = obs_metrics.registry()
    commits0 = reg.counter("ckpt/commits").value
    bytes0 = reg.counter("ckpt/put_bytes").value
    # per-epoch-size prefix: each sweep point owns its object namespace
    # (a resumable leftover would skew the next point's trajectory)
    opts = AdaptOptions(
        niter=niter, checkpoint_every=every, checkpoint_async=True,
        checkpoint_store=f"{spec}-e{every}", **WORKLOAD,
    )
    mesh = unit_cube_mesh(2)
    t0 = time.perf_counter()
    out, info = adapt(mesh, opts)
    wall = time.perf_counter() - t0
    overlap = float(info.get("ckpt_overlap_s", 0.0))
    return dict(
        metric="ckpt_bench",
        ckpt_every=every,
        niter=niter,
        wall_s=round(wall, 4),
        # the gated headline: checkpoint wall time HIDDEN behind
        # compute by the async writer (one-sided ↑ in the gate)
        value=round(overlap, 4),
        ckpt_overlap_s=round(overlap, 4),
        ckpt_commits=int(reg.counter("ckpt/commits").value - commits0),
        ckpt_put_bytes=int(
            reg.counter("ckpt/put_bytes").value - bytes0
        ),
        ne=int(out.ntet),
        platform=jax.devices()[0].platform,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--every", default="1,2,4",
                    help="comma list of checkpoint_every epoch sizes")
    ap.add_argument("--niter", type=int, default=6)
    ap.add_argument("--json", default=None,
                    help="write the enveloped records here")
    ap.add_argument("--db", default=None,
                    help="PERF_DB.jsonl to gate against")
    ap.add_argument("--update", default="0",
                    help="append records to --db (baseline ratchet)")
    ap.add_argument("--rel-floor", type=float, default=0.5,
                    help="gate tolerance floor (CI uses a wide one — "
                         "wall clocks differ per container)")
    args = ap.parse_args()

    from parmmg_tpu.obs import history as obs_history

    spec, backend, cleanup = resolve_store()
    print(f"[ckpt-bench] store {spec} (backend {backend})")
    budget = os.environ.get("PARMMG_STAGE_BUDGET_S")
    budget_s = float(budget) if budget else None
    t_start = time.monotonic()
    # one untimed, checkpoint-free warmup: every sweep point then runs
    # against warm jit caches, so wall_s compares epoch sizes instead
    # of measuring which point paid the compile
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    adapt(unit_cube_mesh(2), AdaptOptions(niter=args.niter, **WORKLOAD))
    print(f"[ckpt-bench] warmup done "
          f"({time.monotonic() - t_start:.1f}s)")
    records = []
    worst = 0.0
    try:
        for every in [int(e) for e in args.every.split(",") if e]:
            if budget_s is not None and (
                time.monotonic() - t_start + worst * 1.15 > budget_s
            ):
                print(f"[ckpt-bench] stage budget reached — epoch "
                      f"sizes from {every} skipped")
                break
            t0 = time.monotonic()
            payload = run_one(every, args.niter, spec)
            worst = max(worst, time.monotonic() - t0)
            payload["backend"] = backend
            rec = obs_history.make_record(
                payload, rung=f"ckpt-{backend}-e{every}"
            )
            records.append(rec)
            print(
                f"[ckpt-bench] every={every}: wall {payload['wall_s']}s"
                f"  overlap {payload['ckpt_overlap_s']}s  commits "
                f"{payload['ckpt_commits']}  put "
                f"{payload['ckpt_put_bytes']} B"
            )
    finally:
        cleanup()
    if not records:
        print("[ckpt-bench] no epoch size completed", file=sys.stderr)
        return 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(dict(records=records), f, indent=1)
        print(f"[ckpt-bench] records -> {args.json}")
    if args.db:
        db = obs_history.load_db(args.db)
        rc = 0
        for rec in records:
            res = obs_history.gate(db, rec, rel_floor=args.rel_floor)
            for line in res.lines():
                print(line)
            if not res.ok:
                rc = obs_history.REGRESSION_EXIT
            if args.update not in ("", "0"):
                obs_history.append_db(args.db, rec)
        if args.update not in ("", "0"):
            print(f"[ckpt-bench] {len(records)} record(s) appended "
                  f"to {args.db}")
        return rc
    return 0


if __name__ == "__main__":
    main_rc = main()
    sys.exit(main_rc)
