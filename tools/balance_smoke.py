"""Closed-loop load-balancing smoke for the CI gate (check.sh balance).

The PR-17 acceptance, end to end on the 2-process CPU fixture: a
deliberately SKEWED initial cut (one shard owning most of the mesh)
driven through a traced 2-rank `adapt_stacked_input` run with the
closed-loop balancer on must:

1. finish typed-clean on both ranks (no watchdog, no peer loss);
2. CONSERVE live tets — the final per-shard totals sum to the merged
   mesh's tet count (migration moved work, it didn't mint or lose it);
3. end back INSIDE the balance band — the final live-tets max/mean is
   at or under the band the policy ran with;
4. leave at least one `rebalance` trace event carrying the decision
   telemetry (trigger, pre/post imbalance, cells, wall), and the
   "balance decisions" line must render in `obs_report --dist`.

Run hermetically on CPU: ``python tools/balance_smoke.py``; exit 0 =
the loop closed. ``--worker`` is the child mode (do not run directly).
Budget knob: PARMMG_STAGE_BUDGET_S bounds the worker wait.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BAND = 1.5
NPARTS = 4


def skewed_partition(mesh, nparts: int):
    """A deliberately imbalanced cut: chunk the SFC order 2x finer than
    the shard count, then give shard 0 every chunk the others don't
    take — most of the mesh lands on one shard while every shard stays
    nonempty (uniform capacities need live cells everywhere)."""
    import numpy as np
    import jax

    from parmmg_tpu.parallel.partition import sfc_partition

    chunks = np.asarray(jax.device_get(sfc_partition(mesh, 2 * nparts)))
    part = np.where(chunks < nparts + 1, 0, chunks - nparts)
    return part


def worker() -> int:
    """Child mode: one rank of the traced skewed 2-process run. Prints
    BAL_TOT (final per-shard live tets + merged tet count) and BAL_IMB
    (per-iteration imbalance series + final) for the parent asserts."""
    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.utils.gen import unit_cube_mesh

    assert multi and jax.process_count() == 2, "2-process env required"
    watchdog = float(os.environ.get("PMMGTPU_WATCHDOG", "120"))

    mesh = unit_cube_mesh(3)
    part = skewed_partition(mesh, NPARTS)
    st, comm = split_mesh(mesh, part, NPARTS)
    ne0 = np.asarray(jax.device_get(st.tmask.sum(axis=1)))
    imb0 = float(ne0.max()) / max(float(ne0.mean()), 1.0)
    assert imb0 > BAND, f"fixture not skewed: {imb0:.3f} <= {BAND}"
    # niter=2, max_sweeps=3: the re-cut the skew forces changes the
    # stacked shapes, so every extra iteration pays a fresh SPMD
    # compile wave — this is the smallest config that still drives the
    # full loop (skew -> decision -> migration/re-cut -> in-band)
    opts = DistOptions(
        hsiz=0.32, niter=2, max_sweeps=3, nparts=NPARTS,
        min_shard_elts=8, hgrad=None, polish_sweeps=0,
        watchdog_timeout=watchdog, balance_band=BAND,
    )
    try:
        out, comm2, info = adapt_stacked_input(st, comm, opts)
    except failsafe.PeerLostError as e:
        print(f"PEER_LOST rank={jax.process_index()}: {e}", flush=True)
        os._exit(failsafe.PEER_LOST_EXIT_CODE)
    ne = np.asarray(jax.device_get(out.tmask.sum(axis=1)))
    imb_final = float(ne.max()) / max(float(ne.mean()), 1.0)
    merged = merge_adapted(out, comm2)
    imb = [r["imbalance"] for r in info["history"] if "imbalance" in r]
    print(f"BAL_TOT {json.dumps(dict(shard_ne=ne.tolist(), merged=int(merged.ntet)))}",
          flush=True)
    print(f"BAL_IMB {json.dumps(dict(series=imb, initial=round(imb0, 4), final=round(imb_final, 4)))}",
          flush=True)
    print(f"BAL_OK rank={jax.process_index()} "
          f"status={int(info['status'])}", flush=True)
    return 0


def _spawn_pair(tmp: str, obs: str, timeout: float):
    """dist_obs_smoke's 2-process launch idiom (2 CPU devices each)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            PYTHONPATH=ROOT,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
            PMMGTPU_TRACE=obs,
            PMMGTPU_WATCHDOG="120",
            PYTHONFAULTHANDLER="1",
        )
        lp = os.path.join(tmp, f"rank{pid}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=open(lp, "w"),
            stderr=subprocess.STDOUT, cwd=ROOT,
        ))
    try:
        rcs = [p.wait(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            p.kill()
    return rcs, [open(lp).read() for lp in logs]


def main() -> int:
    budget = float(os.environ.get("PARMMG_STAGE_BUDGET_S", "600"))
    tmp = tempfile.mkdtemp(prefix="parmmg_balance_")
    obs = os.path.join(tmp, "obs")
    try:
        rcs, logs = _spawn_pair(tmp, obs, timeout=budget)
        if rcs != [0, 0]:
            for i, log in enumerate(logs):
                print(f"---- rank{i} log ----\n{log[-4000:]}",
                      file=sys.stderr)
            print(f"[balance] worker exits {rcs}", file=sys.stderr)
            return 1
        assert all("BAL_OK" in log for log in logs), "no BAL_OK"

        def tagged(tag):
            line = next(ln for ln in logs[0].splitlines()
                        if ln.startswith(tag + " "))
            return json.loads(line[len(tag) + 1:])

        # 2. conservation: migration moved work, it didn't mint any --
        tot = tagged("BAL_TOT")
        assert sum(tot["shard_ne"]) == tot["merged"], tot

        # 3. the skewed run ends back inside the band ----------------
        imb = tagged("BAL_IMB")
        assert imb["initial"] > BAND, imb
        assert imb["final"] <= BAND, \
            f"final imbalance {imb['final']} outside band {BAND}"

        # 4. the decision telemetry landed ---------------------------
        from parmmg_tpu.obs import dist as obs_dist
        from parmmg_tpu.obs import report as obs_report

        summary = obs_dist.dist_summary(obs)
        decisions = summary["work"].get("balance_decisions", [])
        assert decisions, "no rebalance event in the trace"
        moved = sum(int(d.get("cells", 0)) for d in decisions)
        recuts = [d for d in decisions
                  if d.get("trigger") in ("balance-policy", "grps_ratio",
                                          "capacity-recut", "graph")]
        assert moved > 0 or recuts, decisions
        text = obs_report.render_dist(obs)
        assert "balance decisions:" in text, "report line missing"

        print(f"[balance] imbalance {imb['initial']:.3f} -> "
              f"{imb['final']:.3f} (band {BAND}); "
              f"{len(decisions)} decision(s), {moved} cell(s) moved; "
              f"tets conserved at {tot['merged']}")
        print("[balance] skewed-demand loop closed: conservation, "
              "band re-entry and decision telemetry all verified")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(worker() if "--worker" in sys.argv else main())
